"""grafttsan — runtime happens-before race detection for the threaded
overlap stack (pass 3 of the analysis suite).

PRs 6-9 made the fast path genuinely multi-threaded: grad-ready hooks
fire mid-backward, the bucket/pull schedulers issue collectives off-band,
dist_async RPCs ride a background executor, and DataLoader / watchdog /
parameter-server threads all touch engine-managed state.  Correctness
hangs on read/write-set discipline (the paper's async dependency engine,
reborn as ``NDArray._version`` stamps + view groups + handle
issue/wait transitions) — and until now nothing checked that discipline
mechanically.

The checker assigns each thread a **vector clock**: a map
``thread-ident -> epoch`` advanced on every synchronization release and
joined on every acquire, so "A happens-before B" is decidable as a
component-wise clock comparison (the classic FastTrack/TSan relation).
Synchronization edges come from the primitives the stack actually uses:

* ``_AsyncHandle`` issue -> wait (``kvstore.ReduceHandle``/``PullHandle``):
  issue releases the issuer's clock onto the handle, ``wait()`` joins it
  into the waiter — the ONLY sanctioned way to consume in-flight values;
* scheduler critical regions (``overlap.BucketScheduler`` /
  ``PullScheduler`` entry points) — single-owner regions whose violation
  is itself a diagnostic;
* explicit ``sync_release(key)`` / ``sync_acquire(key)`` pairs for
  user-level channels the checker cannot see (queues, events).

Tracked state and the EH2xx diagnostics it yields:

=======  ==============================================================
EH201    unsynchronized cross-thread write to an NDArray while an async
         handle (reduce/pull) holding it is in flight — the wire is
         reading/writing those bytes; only the issuing thread (or a
         thread that waited the handle) may touch them
EH202    scheduler critical region entered concurrently from two
         threads — a grad-ready/first-touch hook mutating
         BucketScheduler/PullScheduler state while another thread is
         inside ``arm``/``disarm``/``take``/``issue``/``finish``
EH203    bulk segment joined from a foreign thread: a deferred value
         recorded under one thread's ``engine.bulk`` scope was resolved
         (flushing the owner's open segment mid-recording) by another
         thread — off-thread work must dispatch under
         ``engine.offband()`` on concrete values instead
EH204    read/write race on an explicitly ``track()``-ed shared array:
         two accesses from different threads, at least one a write,
         with no happens-before edge between them
=======  ==============================================================

Every report carries BOTH racing stacks (the remembered stack of the
prior access/issue/entry and the live stack of the racing thread), is
appended to a bounded in-process list (:func:`reports`), mirrored into
the graftwatch flight-recorder ring (``tsan_report`` events — so a
report survives into crash dumps), counted in
``graft_tsan_reports_total{code=...}``, and logged.  With
``GRAFT_TSAN_ABORT=1`` the racing thread additionally raises
:class:`TsanError`.

Master switch ``GRAFT_TSAN`` (default OFF — the instrumented hot paths
check one cached flag when disabled; ``bench_eager.py`` tracks
``tsan_overhead_pct`` for the enabled mode, informational <10% bar).
``set_enabled(True/False/None)`` overrides programmatically (None
re-reads the env).  ``python -m incubator_mxnet_tpu.analysis.tsan
--selftest`` forces one race per rule and a clean workload (the lint
smoke tier).
"""
from __future__ import annotations

import os
import threading
import traceback
from collections import deque
from contextlib import nullcontext as _nullcontext

__all__ = ["enabled", "set_enabled", "abort_enabled", "TsanError",
           "Report", "reports", "clear", "track", "untrack",
           "sync_release", "sync_acquire", "region",
           "on_write", "on_read", "handle_issue", "handle_settle",
           "segment_open", "check_segment", "selftest", "RULES"]

RULES = {
    "EH201": "unsynchronized cross-thread write to an array with an "
             "in-flight async handle",
    "EH202": "scheduler critical region entered concurrently from two "
             "threads",
    "EH203": "bulk segment joined (resolved/flushed) from a foreign "
             "thread without offband",
    "EH204": "read/write race on a tracked shared array without a "
             "happens-before edge",
}

_MAX_REPORTS = 256
_STACK_LIMIT = 24               # frames kept per remembered stack


def _env_on(name, default=""):
    return os.environ.get(name, default).strip().lower() \
        in ("1", "true", "yes", "on")


# the cached master switch: instrumented hot paths (NDArray._write,
# engine.resolve, handle issue) pay ONE list-index when disabled.
# set_enabled(None) re-reads the env; toggling mid-run is a test/debug
# affordance, not a lockstep-sensitive knob.
_ACTIVE = [_env_on("GRAFT_TSAN")]


def enabled():
    return _ACTIVE[0]


def set_enabled(flag):
    """Force the detector on/off (None = re-read GRAFT_TSAN)."""
    _ACTIVE[0] = _env_on("GRAFT_TSAN") if flag is None else bool(flag)


def abort_enabled():
    return _env_on("GRAFT_TSAN_ABORT")


class TsanError(RuntimeError):
    """Raised at the racing access under GRAFT_TSAN_ABORT=1."""

    def __init__(self, report):
        super().__init__("%s: %s" % (report.code, report.message))
        self.report = report
        self.code = report.code


class Report(object):
    """One detected race: the diagnostic, the live (racing) stack and
    the remembered stack of the other side."""

    __slots__ = ("code", "message", "thread", "other_thread",
                 "stack", "other_stack")

    def __init__(self, code, message, thread, other_thread,
                 stack, other_stack):
        self.code = code
        self.message = message
        self.thread = thread            # racing (current) thread name
        self.other_thread = other_thread
        self.stack = stack              # list[str], current thread
        self.other_stack = other_stack  # list[str], remembered side

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return "%s [%s vs %s]: %s" % (self.code, self.thread,
                                      self.other_thread, self.message)


# ---------------------------------------------------------------------------
# detector state — all guarded by one lock (the detector itself must be
# race-free; contention is negligible at the instrumented sites' rates)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_clocks = {}                    # tid -> {tid: epoch}
_sync_vcs = {}                  # user sync key -> released clock
_handles = {}                   # id(value NDArray) -> handle info dict
_handle_meta = {}               # id(handle) -> release clock
_tracked = {}                   # id(NDArray) -> tracked-cell dict
_regions = {}                   # id(obj) -> occupancy dict
_reports = deque(maxlen=_MAX_REPORTS)


def _tid():
    return threading.get_ident()


def _clock_of(tid):
    vc = _clocks.get(tid)
    if vc is None:
        vc = _clocks[tid] = {tid: 1}
    return vc


def _release_snapshot():
    """Advance the calling thread's epoch and return a released copy of
    its clock (call under _lock)."""
    tid = _tid()
    vc = _clock_of(tid)
    vc[tid] = vc.get(tid, 0) + 1
    return dict(vc)


def _join(released):
    """Join a released clock into the calling thread's (under _lock)."""
    if not released:
        return
    vc = _clock_of(_tid())
    for t, e in released.items():
        if vc.get(t, 0) < e:
            vc[t] = e


def _ordered_after(released, owner_tid):
    """Does the CALLING thread's clock already contain ``owner_tid``'s
    epoch at ``released``?  True means the remembered access
    happens-before the current one (call under _lock)."""
    vc = _clocks.get(_tid())
    if vc is None:
        return False
    return vc.get(owner_tid, 0) >= released.get(owner_tid, 0)


def _grab_stack():
    return traceback.format_stack()[-_STACK_LIMIT:-2] or ["<no stack>"]


def _capture(skip=2):
    """Cheap remembered-side stack: frame summaries WITHOUT source-line
    lookup (the expensive half of format_stack) — lines resolve lazily
    at report time.  This runs on every handle issue / tracked access,
    so it must cost microseconds, not the linecache walk."""
    import sys as _sys
    try:
        f = _sys._getframe(skip)
    except ValueError:
        f = None
    return traceback.StackSummary.extract(
        traceback.walk_stack(f), limit=_STACK_LIMIT, lookup_lines=False)


def _fmt_stack(stack):
    """Remembered stacks as text: captured summaries format lazily
    (walk_stack order is innermost-first — reverse to the conventional
    outermost-first reading); pre-formatted lists pass through."""
    if not stack:
        return []
    if isinstance(stack, traceback.StackSummary) or (
            isinstance(stack, list) and stack
            and isinstance(stack[0], traceback.FrameSummary)):
        return traceback.format_list(list(reversed(list(stack))))
    return list(stack)


def _live_stack_of(tid):
    """The CURRENT stack of another live thread (EH202's remembered
    side: the owner is still inside the region at conflict time, so its
    live frames ARE the evidence)."""
    import sys as _sys
    frame = _sys._current_frames().get(tid)
    if frame is None:
        return []
    return traceback.format_stack(frame)[-_STACK_LIMIT:]


def _thread_name():
    return threading.current_thread().name


def _report(code, message, other_thread=None, other_stack=None):
    rep = Report(code, message, _thread_name(), other_thread or "?",
                 _grab_stack(), _fmt_stack(other_stack))
    _reports.append(rep)
    try:
        from ..telemetry import blackbox as _blackbox
        _blackbox.record(
            "tsan_report", code=code, message=message,
            thread=rep.thread, other_thread=rep.other_thread,
            stack_tail=rep.stack[-4:], other_stack_tail=rep.other_stack[-4:])
    except Exception:
        pass                    # a dying recorder must not mask the race
    try:
        from ..telemetry import metrics as _metrics
        _metrics.tsan_report(code)
    except Exception:
        pass
    import logging
    logging.getLogger("grafttsan").warning(
        "%s: %s\n-- racing thread %s:\n%s-- other thread %s:\n%s",
        code, message, rep.thread, "".join(rep.stack[-6:]),
        rep.other_thread, "".join(rep.other_stack[-6:]))
    if abort_enabled():
        raise TsanError(rep)
    return rep


def reports():
    """Reports recorded so far (oldest first)."""
    return list(_reports)


def clear():
    """Drop reports AND detector state (tests)."""
    with _lock:
        _reports.clear()
        _clocks.clear()
        _sync_vcs.clear()
        _handles.clear()
        _handle_meta.clear()
        _tracked.clear()
        _regions.clear()


# ---------------------------------------------------------------------------
# explicit sync edges (user channels the checker cannot see)
# ---------------------------------------------------------------------------

def sync_release(key):
    """Publish a happens-before release point under ``key`` (pair with
    :func:`sync_acquire` on the consuming thread — e.g. around a queue
    handoff the checker does not instrument)."""
    if not _ACTIVE[0]:
        return
    with _lock:
        released = _release_snapshot()
        prev = _sync_vcs.get(key)
        if prev:                # releases accumulate (channel semantics)
            for t, e in prev.items():
                if released.get(t, 0) < e:
                    released[t] = e
        _sync_vcs[key] = released


def sync_acquire(key):
    """Acquire the edge released under ``key`` (no-op if none yet)."""
    if not _ACTIVE[0]:
        return
    with _lock:
        _join(_sync_vcs.get(key))


# ---------------------------------------------------------------------------
# async handles (EH201): issue = release, wait = acquire
# ---------------------------------------------------------------------------

import weakref as _weakref


def handle_issue(handle):
    """Register an ``_AsyncHandle``'s values as in flight (called from
    kvstore at issue time)."""
    if not _ACTIVE[0] or not handle.values:
        return
    tid = _tid()
    with _lock:
        released = _release_snapshot()
        _handle_meta[id(handle)] = released
    stack = _capture()
    href = _weakref.ref(handle)
    tname = _thread_name()
    with _lock:
        for v in handle.values:
            _handles[id(v)] = {
                "arr": _weakref.ref(v), "handle": href, "tid": tid,
                "thread": tname, "vc": released, "stack": stack,
                "label": getattr(handle, "label", None),
                "reported": False,
            }


def handle_acquire(handle):
    """Wait STARTED: the waiting thread joins the issuer's clock, so its
    own writes from here on (e.g. the PS handle's ``_materialize``
    applying deferred values) are ordered after the issue.  The registry
    stays live — a THIRD thread writing a value while this thread is
    still blocked inside the wait is exactly the EH201 window."""
    with _lock:
        _join(_handle_meta.get(id(handle)))


def handle_settle(handle):
    """Wait COMPLETED (or the handle was abandoned): deregister the
    values.  Called unconditionally from kvstore so a detector toggled
    off mid-flight cannot leak registry entries into false reports on
    later writes — but with nothing ever registered (the default-off
    steady state) the cost stays at two dict-truthiness checks, no
    lock."""
    if not _handles and not _handle_meta:
        return
    if not handle.values and id(handle) not in _handle_meta:
        return
    with _lock:
        _handle_meta.pop(id(handle), None)
        for v in handle.values:
            info = _handles.get(id(v))
            if info is not None and info["handle"]() is handle:
                del _handles[id(v)]


def _check_handle_write(arr):
    aid = id(arr)
    info = _handles.get(aid)
    if info is None or info["arr"]() is not arr:
        return
    h = info["handle"]()
    if h is None:
        # dead weakref (a handle leaked without settling): GC the entry
        with _lock:
            if _handles.get(aid) is info:
                del _handles[aid]
        return
    # NOTE: no early-out on h.done — wait() flips done BEFORE the
    # blocking section, and the wire owns the bytes until the block
    # returns; the registry entry (removed by handle_settle in wait's
    # finally) is what delimits the in-flight window
    if _tid() == info["tid"]:
        return                  # program order on the issuing thread —
        #                         the version-stamp rails own this case
    with _lock:
        ordered = _ordered_after(info["vc"], info["tid"])
        if not ordered and not info["reported"]:
            info["reported"] = True
        elif not ordered:
            return              # one report per in-flight window
        else:
            return
    _report(
        "EH201",
        "unsynchronized write to an array (shape %s) while async handle "
        "%r is in flight — issued on thread %r; wait() the handle (or "
        "synchronize with the issuing thread) before mutating its "
        "values" % (getattr(arr, "_shape", None), info["label"],
                    info["thread"]),
        other_thread=info["thread"], other_stack=info["stack"])


# ---------------------------------------------------------------------------
# tracked shared arrays (EH204)
# ---------------------------------------------------------------------------

def track(arr, label=None):
    """Opt an array into full cross-thread read/write race checking.
    Handle-held arrays are tracked automatically (EH201); this is for
    state shared through channels the checker cannot infer."""
    if not _ACTIVE[0]:
        return arr
    with _lock:
        _tracked[id(arr)] = {"ref": _weakref.ref(arr),
                             "label": label or ("array%s"
                                                % (getattr(arr, "_shape",
                                                           None),)),
                             "last": None}
    return arr


def untrack(arr):
    with _lock:
        _tracked.pop(id(arr), None)


def _check_tracked(arr, kind):
    cell = _tracked.get(id(arr))
    if cell is None or cell["ref"]() is not arr:
        return
    tid = _tid()
    with _lock:
        last = cell["last"]
        racy = (last is not None and last["tid"] != tid
                and (kind == "write" or last["kind"] == "write")
                and not _ordered_after(last["vc"], last["tid"]))
        snap = dict(_clock_of(tid))
        prev = last
        mine = {"tid": tid, "thread": _thread_name(),
                "kind": kind, "vc": snap, "stack": None}
        cell["last"] = mine
    # stack captured OUTSIDE the lock (no source-line lookup), assigned
    # through the LOCAL record: by now another racing thread may already
    # have replaced cell["last"], and writing through the cell would put
    # this thread's frames into the other thread's record
    mine["stack"] = _capture()
    if racy:
        _report(
            "EH204",
            "%s of tracked shared array %s races with a prior %s on "
            "thread %r (no happens-before edge)"
            % (kind, cell["label"], prev["kind"], prev["thread"]),
            other_thread=prev["thread"],
            other_stack=prev["stack"] or ())


# ---------------------------------------------------------------------------
# the NDArray instrumentation points
# ---------------------------------------------------------------------------

def on_write(arr):
    """Called from ``NDArray._write`` when the detector is active."""
    _check_handle_write(arr)
    if _tracked:
        _check_tracked(arr, "write")


def on_read(arr):
    """Called for reads of tracked arrays (EH204 only — reads of
    in-flight handle values are sanctioned via the first-touch hooks)."""
    if _tracked:
        _check_tracked(arr, "read")


# ---------------------------------------------------------------------------
# scheduler critical regions (EH202)
# ---------------------------------------------------------------------------

_NULL = _nullcontext()


class _Region(object):
    __slots__ = ("obj_id", "name", "owned")

    def __init__(self, obj, name):
        self.obj_id = id(obj)
        self.name = name
        self.owned = False

    def __enter__(self):
        tid = _tid()
        conflict = None
        with _lock:
            cur = _regions.get(self.obj_id)
            if cur is None:
                _regions[self.obj_id] = {"tid": tid,
                                         "thread": _thread_name(),
                                         "name": self.name, "depth": 1}
                self.owned = True
            elif cur["tid"] == tid:
                cur["depth"] += 1
                self.owned = True
            else:
                conflict = dict(cur)
        if conflict is not None:
            # the owner is STILL inside the region: its live frames are
            # the remembered side — entry itself stays capture-free
            _report(
                "EH202",
                "scheduler region %r entered while thread %r is inside "
                "%r on the same scheduler — hook/consumer mutation "
                "without the single-owner discipline"
                % (self.name, conflict["thread"], conflict["name"]),
                other_thread=conflict["thread"],
                other_stack=_live_stack_of(conflict["tid"]))
        return self

    def __exit__(self, et, ev, tb):
        if self.owned:
            with _lock:
                cur = _regions.get(self.obj_id)
                if cur is not None and cur["tid"] == _tid():
                    cur["depth"] -= 1
                    if cur["depth"] <= 0:
                        del _regions[self.obj_id]
        return False


def region(obj, name):
    """Bracket one scheduler entry point: a second thread entering ANY
    region of the same object while one is open is an EH202 race (the
    schedulers are single-owner by design — the GIL serializes
    bytecodes, not compound state transitions)."""
    if not _ACTIVE[0]:
        return _NULL
    return _Region(obj, name)


# ---------------------------------------------------------------------------
# bulk segments (EH203)
# ---------------------------------------------------------------------------

def segment_open(state):
    """Stamp a fresh ``_BulkState`` with its opening stack (engine calls
    this only when the detector is active; ``owner_tid`` itself is
    stamped unconditionally by the engine — one int per scope)."""
    state.tsan_stack = _capture()


def check_segment(state):
    """A deferred value of ``state`` is being resolved: flushing from a
    thread other than the scope's owner races the owner's ongoing
    recording (instructions/ext/pendings mutate under it)."""
    if not _ACTIVE[0]:
        return
    owner = getattr(state, "owner_tid", None)
    if owner is None or owner == _tid():
        return
    if getattr(state, "tsan_reported", False):
        return
    state.tsan_reported = True
    _report(
        "EH203",
        "bulk segment (%d recorded instruction(s)) owned by thread id %d "
        "resolved from a foreign thread — the flush mutates the owner's "
        "open recording state; hand concrete values across threads, or "
        "dispatch the off-thread work under engine.offband()"
        % (len(getattr(state, "instructions", ())), owner),
        other_thread="owner-tid-%d" % owner,
        other_stack=getattr(state, "tsan_stack", None) or ())


# ---------------------------------------------------------------------------
# selftest (the lint smoke tier): one forced race per rule + a clean run
# ---------------------------------------------------------------------------

def _expect(problems, code, fn):
    clear()
    fn()
    got = [r.code for r in reports()]
    if got != [code]:
        problems.append("%s fixture produced %r (expected exactly [%r])"
                        % (code, got, code))
        return
    rep = reports()[0]
    if not rep.stack or not rep.other_stack:
        problems.append("%s report lost a stack (stack=%d frames, "
                        "other=%d)" % (code, len(rep.stack),
                                       len(rep.other_stack)))


def selftest():
    """Force one race per EH2xx rule through the real instrumented
    paths, then verify a clean mini-workload reports nothing.  Returns a
    list of problems — empty means pass (wired into tools/run_lint.sh).
    """
    import numpy as np
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import engine

    prev = _ACTIVE[0]
    set_enabled(True)
    problems = []
    import logging
    logger = logging.getLogger("grafttsan")
    prev_disabled = logger.disabled
    logger.disabled = True      # the forced races are the point; their
    #                             warnings would read as CI failures
    try:
        # EH201 — write to an in-flight handle value from another thread
        def eh201():
            kv = mx.kv.create("local")
            arr = mx.nd.array(np.ones((4,), np.float32))
            handle = kv.reduce_many_async([arr], label="selftest")
            t = threading.Thread(
                target=lambda: arr._write(jnp.zeros((4,), jnp.float32)),
                name="tsan-self-writer")
            t.start()
            t.join()
            handle.abandon()
        _expect(problems, "EH201", eh201)

        # EH202 — two threads inside one scheduler's regions
        def eh202():
            obj = object()
            inside = threading.Event()
            release = threading.Event()

            def holder():
                with region(obj, "take"):
                    inside.set()
                    release.wait(5)
            t = threading.Thread(target=holder, name="tsan-self-holder")
            t.start()
            inside.wait(5)
            with region(obj, "_on_ready"):
                pass
            release.set()
            t.join()
        _expect(problems, "EH202", eh202)

        # EH203 — resolve a deferred value from a foreign thread
        def eh203():
            a = mx.nd.array(np.ones((4, 4), np.float32))
            with engine.bulk(8):
                b = a * a
                t = threading.Thread(target=b.asnumpy,
                                     name="tsan-self-reader")
                t.start()
                t.join()
        _expect(problems, "EH203", eh203)

        # EH204 — unsynchronized write/write on a tracked array
        def eh204():
            arr = track(mx.nd.array(np.zeros((2,), np.float32)),
                        label="selftest-cell")
            arr._write(jnp.ones((2,), jnp.float32))
            t = threading.Thread(
                target=lambda: arr._write(jnp.zeros((2,), jnp.float32)),
                name="tsan-self-racer")
            t.start()
            t.join()
            untrack(arr)
        _expect(problems, "EH204", eh204)

        # clean run — bulked train-ish loop + handles used correctly
        clear()
        kv = mx.kv.create("local")
        w = mx.nd.array(np.ones((8,), np.float32))
        for _ in range(3):
            with engine.bulk(16):
                y = (w * w) + w
            h = kv.reduce_many_async([y], label="clean")
            h.wait()
            w._write(y._read())        # post-wait write: synchronized
        if reports():
            problems.append("clean run produced %d report(s): %r"
                            % (len(reports()), reports()))
        return problems
    finally:
        logger.disabled = prev_disabled
        set_enabled(prev)
        clear()


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m incubator_mxnet_tpu.analysis.tsan",
        description="grafttsan happens-before race detector")
    ap.add_argument("--selftest", action="store_true",
                    help="force one race per EH2xx rule + a clean run "
                         "(CI smoke tier)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the diagnostic codes and exit")
    args = ap.parse_args(argv)
    if args.list_rules:
        for code in sorted(RULES):
            print("%s  %s" % (code, RULES[code]))
        return 0
    if args.selftest:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        problems = selftest()
        if problems:
            for p in problems:
                print("grafttsan selftest FAIL: %s" % p)
            return 1
        print("grafttsan selftest OK (4 forced races caught with both "
              "stacks; clean run silent)")
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    # run the CANONICAL module instance: executed as __main__ this file
    # is a second module object whose _ACTIVE flag the instrumented
    # call sites (ndarray/kvstore/engine import the package path) never
    # see — set_enabled would silently toggle the wrong copy
    import sys
    from incubator_mxnet_tpu.analysis.tsan import main as _main
    sys.exit(_main())
