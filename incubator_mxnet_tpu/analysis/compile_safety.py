"""graftguard — compile-safety lint (GL3xx, pass 5) + runtime
retrace/donation auditor (EH301-EH304) for the whole-step compiled path.

PR 16 (graftstep) made ONE donated XLA program the steady-state unit of
training.  That buys the dispatch win the TPU-compilation papers promise
— and introduces a hazard class none of the existing passes can see:

* host round-trips hiding inside traced regions (a ``.asnumpy()`` in a
  loss function turns "one program" into "one program per step plus a
  device sync"),
* Python control flow on traced values (works eagerly, explodes or
  silently specializes under ``jax.jit``),
* values baked as compile-time constants that were supposed to vary
  (the lr/wd/rescale bug class PR 16 fixed by hand),
* reads of donated buffers after dispatch (XLA aliased the memory; the
  value is gone on real hardware, and only *sometimes* gone on CPU —
  the worst kind of latent bug),
* guard-key churn re-tracing every step with nothing naming WHICH of
  the eight key components moved.

Static pass (AST, no execution) — run by ``graftlint --all``:

GL301    host materialization inside trace-eligible code: ``.asnumpy()``
         / ``.item()`` / ``.tolist()`` / ``float()/int()/bool()`` /
         ``np.*`` applied to a traced value
GL302    Python ``if``/``while``/ternary/``assert`` branching on a
         traced array value (shape/dtype/ndim reads stay static and are
         exempt)
GL303    nondeterminism inside a traced closure: ``os.environ`` /
         ``os.getenv`` / ``time.*`` / ``random.*`` / ``np.random.*`` /
         ``datetime``/``uuid``/``secrets`` reads get frozen at trace
         time (or fork per retrace) — hoist them out of the trace
GL304    mutation of captured Python state under trace (append/store to
         a closed-over list/dict, ``global``/``nonlocal`` writes): runs
         once at trace time, never again on the compiled path
GL305    hyperparameter-looking scalar (lr/wd/rescale/momentum/beta/
         eps/clip) closed over as a trace-time CONSTANT instead of
         riding as a traced operand — changing it later silently
         doesn't take effect (or forces a retrace)
GL306    a donated buffer referenced AFTER the donating dispatch in the
         same block: XLA aliased that memory for an output
GL307    ``compile_step`` called under an open ``autograd.record()``
         scope (the compiled step IS the whole record/backward/step
         triple; nesting deadlocks the tape)
GL308    a traced function parameter used ONLY for its shape/dtype —
         shape-polymorphic input with no value use: make it a static
         argument or add a guard-key component, or every new shape
         retraces a program that didn't need the data at all

Runtime auditor (``GRAFT_COMPILE_CHECK=1``) — instruments
``gluon.step_compile.CompiledStep``:

EH301    retrace-storm detection with guard-key DIFFING: every miss is
         diffed component-by-component against the last key and the
         exact churned element (input-sig / input-fmt / param-set /
         param-meta / optimizer-sig / n-ctx / kvstore-sig /
         bucket-bytes) is journaled to the blackbox and counted in
         ``graft_step_retraces_total{reason}``; >= 3 misses inside an
         8-call window raises the storm (warn by default,
         ``GRAFT_COMPILE_CHECK_ABORT=1`` to raise)
EH302    donated-buffer use-after-dispatch: the NDArrays whose jax
         buffers a dispatch donates are poisoned at dispatch; any
         ``_read`` before the replacement ``_write`` lands raises with
         BOTH stacks (dispatch + read), tsan-style.  Poisoning follows
         the donation CONTRACT (argument positions 0/1), not
         ``_donation_supported()`` — so CPU CI catches what only real
         TPUs would corrupt
EH303    constant-bake drift: the fused-formula config scalars
         (momentum/beta/eps/clip) are hashed into the entry at trace
         time and re-hashed per dispatch; a changed hash under an
         unchanged guard key means a live value is silently frozen
         inside the compiled program
EH304    compiled-vs-eager divergence sentinel: every
         ``GRAFT_COMPILE_CHECK_EVERY=N`` compiled steps, the entry's
         UN-jitted twin programs replay the same operands (same rng
         key) and outputs/params/states must agree within
         ``GRAFT_COMPILE_CHECK_ULPS`` (default 64 — the un-jitted twin
         is an independent computation path, so fusion/reassociation
         legitimately moves reduction chains a few tens of ULP)

The hot-path cost when disabled is one list-index check per NDArray
read/write (the grafttsan convention) plus one memoized env parse per
compiled call; ``bench_eager --smoke`` gates the enabled cost < 2%.

CLI: ``python -m incubator_mxnet_tpu.analysis.compile_safety --selftest``
forces every GL301-GL308 and EH301-EH304 diagnostic through the real
lint / compile_step paths (lint tier 11).
"""

from __future__ import annotations

import ast
import builtins
import os
import re
import sys
import warnings

from .contracts import Diagnostic, _fcompute_tree, suppressions_for
from .concurrency import _line_suppressions, package_root

__all__ = [
    "RULES", "EH_RULES", "GUARD_COMPONENTS", "CompileSafetyError",
    "StepAuditor", "diff_guard_key", "enabled", "set_enabled", "refresh",
    "lint_source", "lint_file", "lint_package", "lint_registry",
    "lint_callable", "on_read", "on_write", "selftest", "main",
]

RULES = {
    "GL301": "host materialization (.asnumpy/.item/float()/np.*) on a "
             "traced value inside trace-eligible code",
    "GL302": "Python if/while branching on a traced array value",
    "GL303": "env/config/clock/RNG nondeterminism inside a traced "
             "closure (frozen at trace time)",
    "GL304": "mutation of captured Python state under trace (runs once, "
             "at trace time)",
    "GL305": "hyperparameter scalar closed over as a trace-time "
             "constant instead of riding as a traced operand",
    "GL306": "donated buffer referenced after the donating dispatch",
    "GL307": "compile_step under an open autograd.record() scope",
    "GL308": "traced parameter used only for shape/dtype (shape-"
             "polymorphic input without a guard-key component)",
}

EH_RULES = {
    "EH301": "retrace storm (guard-key churn; diff names the component)",
    "EH302": "donated-buffer read after dispatch, before the "
             "replacement landed",
    "EH303": "constant-bake drift under an unchanged guard key",
    "EH304": "compiled-vs-eager ULP divergence on a sentinel step",
}

# the nine components of CompiledStep._guard_key, in tuple order
GUARD_COMPONENTS = ("input-sig", "input-fmt", "param-set", "param-meta",
                    "optimizer-sig", "n-ctx", "kvstore-sig",
                    "bucket-bytes", "quant-cfg")


# ---------------------------------------------------------------------------
# switches (lens/pulse convention: memoized on the RAW env string so tests
# and live sessions flipping the var mid-process still take effect)
# ---------------------------------------------------------------------------

_OFF_VALUES = ("", "0", "false", "no", "off")
_enabled_override = None
_check_env_memo = ["\x00", False]

# raw flag for the NDArray read/write hot path: one list-index load when
# the auditor is off (grafttsan convention); refreshed per compiled call
_ACTIVE = [False]


def enabled():
    if _enabled_override is not None:
        return bool(_enabled_override)
    raw = os.environ.get("GRAFT_COMPILE_CHECK", "0")
    if raw != _check_env_memo[0]:
        _check_env_memo[1] = raw.strip().lower() not in _OFF_VALUES
        _check_env_memo[0] = raw
    return _check_env_memo[1]


def set_enabled(flag):
    """Force the auditor on/off (None restores the env var)."""
    global _enabled_override
    _enabled_override = flag
    refresh()


def refresh():
    """Re-read the switch into the hot-path flag; returns the state."""
    _ACTIVE[0] = enabled()
    if not _ACTIVE[0] and _POISON:
        _POISON.clear()
    return _ACTIVE[0]


_every_memo = ["\x00", 0]


def check_every():
    """EH304 sentinel period (0 = sentinel off, the default).  Memoized
    on the raw env string — this is read once per compiled call."""
    raw = os.environ.get("GRAFT_COMPILE_CHECK_EVERY", "0")
    if raw != _every_memo[0]:
        try:
            _every_memo[1] = max(0, int(raw))
        except ValueError:
            _every_memo[1] = 0
        _every_memo[0] = raw
    return _every_memo[1]


def ulp_tol():
    """EH304 tolerance.  The twin is UN-jitted on purpose (independent
    computation path), so XLA fusion/reassociation legitimately moves
    reduction chains a few tens of ULP — 64 absorbs that while still
    catching any real bake/donation bug (those diverge by thousands)."""
    try:
        return max(0, int(os.environ.get("GRAFT_COMPILE_CHECK_ULPS",
                                         "64")))
    except ValueError:
        return 64


def abort_on_storm():
    return os.environ.get("GRAFT_COMPILE_CHECK_ABORT",
                          "0").strip().lower() not in _OFF_VALUES


class CompileSafetyError(RuntimeError):
    """A runtime EH3xx violation (code in ``.code``)."""

    def __init__(self, code, message):
        super().__init__(message)
        self.code = code


# ---------------------------------------------------------------------------
# static pass: shared AST helpers
# ---------------------------------------------------------------------------

# attribute reads that stay STATIC under jit (reading them off a tracer
# yields concrete Python values, so taint does not flow through them)
_STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "context",
                           "ctx", "name", "grad_req", "_version"})
# calls whose results are static regardless of argument taint
_STATIC_CALLS = frozenset({"len", "isinstance", "type", "getattr",
                           "hasattr", "id", "callable"})
_MATERIALIZE_ATTRS = frozenset({"asnumpy", "item", "tolist", "asscalar"})
_CAST_BUILTINS = frozenset({"float", "int", "bool", "complex"})
_MUTATOR_METHODS = frozenset({"append", "extend", "insert", "add",
                              "update", "setdefault", "pop", "popitem",
                              "remove", "discard", "clear", "write"})
_NONDET_PREFIXES = (("os", "environ"), ("os", "getenv"), ("time",),
                    ("random",), ("numpy", "random"), ("datetime",),
                    ("uuid",), ("secrets",))
_HYPER_RE = re.compile(
    r"(?:^|_)(lr|learning_rate|wd|weight_decay|rescale(?:_grad)?|"
    r"momentum|beta1|beta2|eps|epsilon|clip(?:_gradient)?)(?:_|$)")
_BUILTIN_NAMES = frozenset(dir(builtins))

# calls whose function-typed arguments get traced by jax / graftstep
_TRACE_ENTRYPOINTS = frozenset({
    "jit", "pjit", "pmap", "vjp", "jvp", "grad", "value_and_grad",
    "eval_shape", "make_jaxpr", "linearize", "checkpoint_policy",
    "compile_step", "functionalize", "serving_fn", "CompiledStep"})
_TRACE_KWARGS = frozenset({"loss", "fun", "f", "fn"})


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _call_name(call):
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _fn_params(args_node, skip_self=True):
    names = []
    for a in (getattr(args_node, "posonlyargs", []) + args_node.args):
        names.append(a.arg)
    if args_node.vararg is not None:
        names.append(args_node.vararg.arg)
    for a in args_node.kwonlyargs:
        names.append(a.arg)
    if args_node.kwarg is not None:
        names.append(args_node.kwarg.arg)
    if skip_self and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _body_list(fn_node):
    body = fn_node.body
    return body if isinstance(body, list) else [body]


def _walk_skip_defs(root_nodes, skip_lambdas=False):
    """Walk statements/expressions, NOT descending into nested
    FunctionDefs (they are traced — and checked — separately if
    reachable); Lambdas share the enclosing namespace and ARE entered
    unless ``skip_lambdas``."""
    stack = list(root_nodes)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if skip_lambdas and isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _TaintEnv(object):
    """Per-function taint: which local names carry traced array values.

    Coarse by design (nested lambdas share the namespace; tuple targets
    taint every element) — the rules it feeds are advisory lint, and
    over-taint is bounded by the _STATIC_ATTRS / _STATIC_CALLS breaks."""

    def __init__(self, fn_node, seeds, import_names):
        self.fn = fn_node
        self.imports = import_names
        self.locals = set(_fn_params(fn_node.args, skip_self=False))
        self.tainted = set(seeds)
        for node in _walk_skip_defs(_body_list(fn_node)):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                self.locals.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.locals.add(node.name)
            elif isinstance(node, ast.Lambda):
                self.locals.update(_fn_params(node.args, skip_self=False))
        self._fixpoint()

    def is_free(self, name):
        return (name not in self.locals and name not in self.imports
                and name not in _BUILTIN_NAMES)

    def expr_tainted(self, node):
        """True if evaluating ``node`` can yield a traced value."""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Load) and n.id in self.tainted:
                    return True
                continue
            if isinstance(n, ast.Attribute):
                if n.attr in _STATIC_ATTRS:
                    continue            # x.shape is static under jit
                stack.append(n.value)
                continue
            if isinstance(n, ast.Call):
                cn = _call_name(n)
                if isinstance(n.func, ast.Name) and cn in _STATIC_CALLS:
                    continue            # len(x)/isinstance(x, T) static
                stack.extend(ast.iter_child_nodes(n))
                continue
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))
        return False

    def _targets(self, t, out):
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, ast.Starred):
            self._targets(t.value, out)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._targets(e, out)
        elif isinstance(t, ast.Subscript):
            # storing a traced value INTO a container taints the
            # container name (shadows[n] = NDArray(v))
            root = t.value
            while isinstance(root, (ast.Subscript, ast.Attribute)):
                root = root.value
            if isinstance(root, ast.Name):
                out.add(root.id)

    def _fixpoint(self):
        for _ in range(4):
            grew = False
            for node in _walk_skip_defs(_body_list(self.fn)):
                tgt, val = None, None
                if isinstance(node, ast.Assign):
                    tgt, val = node.targets, node.value
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    tgt, val = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    tgt, val = [node.target], node.value
                elif isinstance(node, ast.For):
                    tgt, val = [node.target], node.iter
                elif isinstance(node, ast.comprehension):
                    tgt, val = [node.target], node.iter
                if val is None or tgt is None:
                    continue
                if not self.expr_tainted(val):
                    continue
                # `for k, v in D.items()` — dict keys are host values
                # (param-name strings), only the VALUES carry taint;
                # `.keys()` carries none
                if (isinstance(node, (ast.For, ast.comprehension))
                        and isinstance(val, ast.Call)
                        and isinstance(val.func, ast.Attribute)
                        and val.func.attr in ("items", "keys")):
                    if val.func.attr == "keys":
                        continue
                    t0 = tgt[0]
                    if isinstance(t0, (ast.Tuple, ast.List)) \
                            and len(t0.elts) == 2:
                        names = set()
                        self._targets(t0.elts[1], names)
                        new = names - self.tainted
                        if new:
                            self.tainted |= new
                            grew = True
                        continue
                names = set()
                for t in tgt:
                    self._targets(t, names)
                new = names - self.tainted
                if new:
                    self.tainted |= new
                    grew = True
            if not grew:
                return


# ---------------------------------------------------------------------------
# static pass: per-module scan
# ---------------------------------------------------------------------------

class _ModuleScan(object):
    def __init__(self, source, filename, module):
        self.source = source
        self.filename = filename
        self.module = module
        self.tree = ast.parse(source)
        self.suppress = _line_suppressions(source)
        self._scope_sup = {}
        self.diags = []
        self.parents = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[id(child)] = parent
        self.defs = []            # {"node","scope","cls","qual"}
        self.by_name = {}
        self.methods = {}         # (cls, name) -> def info
        self._collect_defs(self.tree, (), None)
        self.imports = self._import_aliases()
        self.assigned_funcs = {}  # name -> factory Call node
        self.cstep_names = set()  # names bound from *.compile_step(...)
        self._collect_assignments()
        self.donated_names = {}   # callable name -> donated positions
        self.donated_keys = {}    # entry["..."] key -> donated positions
        self._collect_donations()
        self.traced = {}          # id(def node) -> (info, seed set)

    # -- structure ---------------------------------------------------------
    def _collect_defs(self, node, scope, cls, direct=False):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = {"node": child, "scope": scope, "cls": cls,
                        "qual": ".".join(scope + (child.name,))}
                self.defs.append(info)
                self.by_name.setdefault(child.name, []).append(info)
                if cls is not None and direct:
                    self.methods[(cls, child.name)] = info
                # nested closures keep the enclosing class: their
                # ``self.X(...)`` calls must still resolve to methods
                self._collect_defs(child, scope + (child.name,), cls)
            elif isinstance(child, ast.ClassDef):
                self._collect_defs(child, scope, child.name, direct=True)
            else:
                self._collect_defs(child, scope, cls, direct)

    def _import_aliases(self):
        out = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = \
                        tuple(a.name.split("."))
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = tuple(node.module.split("."))
                for a in node.names:
                    out[a.asname or a.name] = base + (a.name,)
        # common scientific alias even when imported indirectly
        out.setdefault("np", ("numpy",))
        out.setdefault("jnp", ("jax", "numpy"))
        return out

    def canonical(self, dotted):
        if not dotted:
            return dotted
        head = self.imports.get(dotted[0])
        if head:
            return head + dotted[1:]
        return dotted

    def _collect_assignments(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t, v = node.targets[0], node.value
            if not isinstance(t, ast.Name) or not isinstance(v, ast.Call):
                continue
            self.assigned_funcs.setdefault(t.id, v)
            if _call_name(v) == "compile_step":
                self.cstep_names.add(t.id)

    # -- donation map ------------------------------------------------------
    def _donate_positions(self, kw_value, jit_call):
        node = kw_value
        if isinstance(node, ast.Name):
            # resolve `donate = (0, 1) if cond else ()` in the enclosing
            # function
            fn = self.parents.get(id(jit_call))
            while fn is not None and not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self.parents.get(id(fn))
            if fn is not None:
                for n in ast.walk(fn):
                    if (isinstance(n, ast.Assign)
                            and len(n.targets) == 1
                            and isinstance(n.targets[0], ast.Name)
                            and n.targets[0].id == node.id):
                        node = n.value
                        break
        cands = [node]
        if isinstance(node, ast.IfExp):
            cands = [node.body, node.orelse]
        out = set()
        for c in cands:
            if isinstance(c, (ast.Tuple, ast.List)):
                for e in c.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                            e.value, int):
                        out.add(e.value)
            elif isinstance(c, ast.Constant) and isinstance(c.value, int):
                out.add(c.value)
        return out or None

    def _collect_donations(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call) or _call_name(node) not in (
                    "jit", "pjit"):
                continue
            pos = None
            for kw in node.keywords:
                if kw.arg == "donate_argnums":
                    pos = self._donate_positions(kw.value, node)
            if not pos:
                continue
            parent = self.parents.get(id(node))
            if not isinstance(parent, ast.Assign) or len(
                    parent.targets) != 1:
                continue
            t = parent.targets[0]
            if isinstance(t, ast.Name):
                self.donated_names[t.id] = pos
            elif (isinstance(t, ast.Subscript)
                  and isinstance(t.slice, ast.Constant)
                  and isinstance(t.slice.value, str)):
                self.donated_keys[t.slice.value] = pos

    def donated_positions_of_call(self, call):
        f = call.func
        if isinstance(f, ast.Name):
            return self.donated_names.get(f.id)
        if (isinstance(f, ast.Subscript)
                and isinstance(f.slice, ast.Constant)
                and isinstance(f.slice.value, str)):
            return self.donated_keys.get(f.slice.value)
        if isinstance(f, ast.Call) and _call_name(f) in ("jit", "pjit"):
            for kw in f.keywords:
                if kw.arg == "donate_argnums":
                    return self._donate_positions(kw.value, f)
        return None

    # -- traced-set discovery ----------------------------------------------
    def _lookup_def(self, name, scope):
        best = None
        for info in self.by_name.get(name, ()):
            s = info["scope"]
            if scope[:len(s)] == s and (
                    best is None or len(s) > len(best["scope"])):
                best = info
        return best

    def _returned_defs(self, factory_info):
        """Nested FunctionDefs (or lambdas) a factory returns."""
        out = []
        fscope = factory_info["scope"] + (factory_info["node"].name,)
        for node in _walk_skip_defs(_body_list(factory_info["node"])):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            if isinstance(v, ast.Lambda):
                out.append({"node": v, "scope": fscope, "cls": None,
                            "qual": factory_info["qual"] + ".<lambda>"})
            elif isinstance(v, ast.Name):
                info = self._lookup_def(v.id, fscope)
                if info is not None:
                    out.append(info)
        return out

    def _resolve_callable_arg(self, arg, scope, cls):
        """Defs a function-typed argument resolves to."""
        if isinstance(arg, ast.Lambda):
            return [{"node": arg, "scope": scope, "cls": None,
                     "qual": ".".join(scope) + ".<lambda>"}]
        if isinstance(arg, ast.Name):
            info = self._lookup_def(arg.id, scope)
            fac = self.assigned_funcs.get(arg.id)
            # a local `step = self._make_step(...)` assignment SHADOWS a
            # same-named method/outer def: prefer the factory result
            # unless the def is at least as deeply nested as the call
            if info is not None and (fac is None
                                     or len(info["scope"]) >= len(scope)):
                return [info]
            if fac is not None:
                facs = self._resolve_callee(fac, scope, cls)
                out = [d for f in facs for d in self._returned_defs(f)]
                if out:
                    return out
            return [info] if info is not None else []
        if isinstance(arg, ast.Call):
            facs = self._resolve_callee(arg, scope, cls)
            return [d for f in facs for d in self._returned_defs(f)]
        return []

    def _resolve_callee(self, call, scope, cls):
        f = call.func
        if isinstance(f, ast.Name):
            info = self._lookup_def(f.id, scope)
            return [info] if info is not None else []
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and cls is not None):
            info = self.methods.get((cls, f.attr))
            return [info] if info is not None else []
        return []

    def _enclosing(self, node):
        """(scope, cls) of the def/class region containing ``node``."""
        scope, cls, cur = [], None, self.parents.get(id(node))
        chain = []
        while cur is not None:
            chain.append(cur)
            cur = self.parents.get(id(cur))
        for n in reversed(chain):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.append(n.name)
                cls = None
            elif isinstance(n, ast.ClassDef):
                cls = n.name
        # method bodies: cls is the class of the nearest enclosing def
        cur, mcls = self.parents.get(id(node)), None
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                p = self.parents.get(id(cur))
                if isinstance(p, ast.ClassDef):
                    mcls = p.name
                break
            cur = self.parents.get(id(cur))
        return tuple(scope), (mcls or cls)

    def _mark_traced(self, info, seeds):
        key = id(info["node"])
        entry = self.traced.get(key)
        if entry is None:
            self.traced[key] = (info, set(seeds))
            return True
        before = len(entry[1])
        entry[1].update(seeds)
        return len(entry[1]) != before

    def discover(self):
        work = []
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) not in _TRACE_ENTRYPOINTS:
                continue
            scope, cls = self._enclosing(node)
            cands = list(node.args)
            cands += [kw.value for kw in node.keywords
                      if kw.arg in _TRACE_KWARGS]
            for arg in cands:
                for info in self._resolve_callable_arg(arg, scope, cls):
                    seeds = _fn_params(info["node"].args)
                    if self._mark_traced(info, seeds):
                        work.append(info)
        # propagate through direct calls, mapping argument taint onto
        # callee parameters (a literal flag like flat_mode=True must NOT
        # taint — branching on it is static specialization, not a bug)
        guard = 0
        while work and guard < 400:
            guard += 1
            info = work.pop()
            env = self._env_for(info)
            fscope = info["scope"] + (
                getattr(info["node"], "name", "<lambda>"),)
            for node in ast.walk(info["node"]):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name):
                    # same shadowing rules as argument resolution: a
                    # local `step = factory(...)` beats an outer def
                    callees = self._resolve_callable_arg(
                        f, fscope, info["cls"])
                else:
                    callees = self._resolve_callee(node, fscope,
                                                   info["cls"])
                if not callees:
                    continue
                for callee in callees:
                    params = _fn_params(callee["node"].args)
                    seeds = set()
                    for pos, a in enumerate(node.args):
                        if pos < len(params) and env.expr_tainted(a):
                            seeds.add(params[pos])
                    for kw in node.keywords:
                        if kw.arg in params and env.expr_tainted(kw.value):
                            seeds.add(kw.arg)
                    if self._mark_traced(callee, seeds):
                        work.append(callee)

    def _env_for(self, info):
        seeds = set(self.traced.get(id(info["node"]), (None, set()))[1])
        # params of nested traced lambdas share the namespace
        for node in _walk_skip_defs(_body_list(info["node"])):
            if isinstance(node, ast.Lambda) and id(node) in self.traced:
                seeds.update(self.traced[id(node)][1])
        return _TaintEnv(info["node"], seeds, self.imports)

    # -- emission ----------------------------------------------------------
    def emit(self, code, site, line, message):
        sup, why = False, None
        for ln in (line, line - 1):
            codes = self.suppress.get(ln) or {}
            if code in codes:
                sup, why = True, codes[code]
                break
        if not sup and code in self._scope_sup:
            # a directive on (or right above) the enclosing ``def`` line
            # suppresses for the whole closure — the deliberate-bake
            # idiom (optimizer formula appliers) without a comment per
            # flagged line
            sup, why = True, self._scope_sup[code]
        self.diags.append(Diagnostic(
            code, site, message, file=self.filename, line=line,
            suppressed=sup, justification=why))

    # -- rule checks -------------------------------------------------------
    def check_traced(self, info, seeds, rules=None):
        fn = info["node"]
        site = "%s.%s" % (self.module, info["qual"] or "<lambda>")
        self._scope_sup = {}
        for ln in (fn.lineno, fn.lineno - 1):
            self._scope_sup.update(self.suppress.get(ln) or {})
        env = _TaintEnv(fn, seeds, self.imports)
        on = (lambda c: rules is None or c in rules)
        body = _body_list(fn)
        if on("GL301"):
            self._gl301(env, body, site)
        if on("GL302"):
            self._gl302(env, body, site)
        if on("GL303"):
            self._gl303(body, site)
        if on("GL304"):
            self._gl304(env, body, site)
        if on("GL305"):
            self._gl305(env, body, site)
        if on("GL308") and isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._gl308(fn, seeds, site)
        self._scope_sup = {}

    def _gl301(self, env, body, site):
        for node in _walk_skip_defs(body):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in _MATERIALIZE_ATTRS
                    and env.expr_tainted(f.value)):
                self.emit("GL301", site, node.lineno,
                          ".%s() on a traced value forces a host "
                          "round-trip inside the trace — keep it a jax "
                          "value (or hoist the read out of the compiled "
                          "region)" % f.attr)
                continue
            if (isinstance(f, ast.Name) and f.id in _CAST_BUILTINS
                    and not env.is_free(f.id) is False and node.args
                    and f.id not in env.locals
                    and any(env.expr_tainted(a) for a in node.args)):
                self.emit("GL301", site, node.lineno,
                          "%s() on a traced value materializes it on "
                          "the host at trace time" % f.id)
                continue
            dotted = env_canonical = _dotted(f)
            if dotted:
                env_canonical = self.canonical(dotted)
            if (env_canonical and env_canonical[0] == "numpy"
                    and len(env_canonical) > 1
                    and any(env.expr_tainted(a) for a in node.args)):
                self.emit("GL301", site, node.lineno,
                          "%s on a traced value runs on the host (use "
                          "the jnp twin so it stays in the program)"
                          % ".".join(dotted))
            elif (env_canonical == ("jax", "device_get")
                    and any(env.expr_tainted(a) for a in node.args)):
                self.emit("GL301", site, node.lineno,
                          "jax.device_get inside a traced region "
                          "synchronizes the device mid-trace")

    def _static_test(self, env, test):
        """True when every tainted leaf of ``test`` is consumed by a
        host-static predicate: identity (`x is None`), or key/element
        membership with an untainted probe (`name in params`).  Such
        tests branch on Python-level structure, not traced VALUES, and
        are safe under trace."""
        if not env.expr_tainted(test):
            return True
        if isinstance(test, ast.BoolOp):
            return all(self._static_test(env, v) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._static_test(env, test.operand)
        if isinstance(test, ast.Compare):
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in test.ops):
                return True
            if (all(isinstance(o, (ast.In, ast.NotIn)) for o in test.ops)
                    and not env.expr_tainted(test.left)):
                return True
        return False

    def _gl302(self, env, body, site):
        for node in _walk_skip_defs(body):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            else:
                continue
            if self._static_test(env, test):
                continue
            if env.expr_tainted(test):
                self.emit("GL302", site, node.lineno,
                          "Python control flow on a traced array value: "
                          "under jit this either fails or silently "
                          "specializes on the trace-time value (use "
                          "jnp.where / lax.cond)")

    def _gl303(self, body, site):
        for node in _walk_skip_defs(body):
            target = None
            if isinstance(node, ast.Call):
                target = _dotted(node.func)
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)):
                target = _dotted(node.value)
            if not target:
                continue
            canon = self.canonical(target)
            for pre in _NONDET_PREFIXES:
                if canon[:len(pre)] == pre:
                    self.emit("GL303", site, node.lineno,
                              "%s inside a traced closure is read ONCE "
                              "at trace time (and re-read only on "
                              "retrace) — hoist it out of the compiled "
                              "region" % ".".join(target))
                    break

    def _gl304(self, env, body, site):
        for node in _walk_skip_defs(body):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                self.emit("GL304", site, node.lineno,
                          "%s write under trace runs at trace time "
                          "only — the compiled program never repeats "
                          "it" % type(node).__name__.lower())
                continue
            root = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        r = t
                        while isinstance(r, (ast.Subscript,
                                             ast.Attribute)):
                            r = r.value
                        if isinstance(r, ast.Name) and env.is_free(r.id):
                            root = r.id
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATOR_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and env.is_free(node.func.value.id)):
                root = node.func.value.id
            if root is not None:
                self.emit("GL304", site, node.lineno,
                          "mutation of captured %r under trace happens "
                          "at trace time, not per step — the compiled "
                          "program will not repeat it" % root)

    def _gl305(self, env, body, site):
        for node in _walk_skip_defs(body):
            name, line = None, None
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and env.is_free(node.id)
                    and _HYPER_RE.search(node.id)):
                name, line = node.id, node.lineno
            elif (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and _HYPER_RE.search(node.attr)
                    and not isinstance(self.parents.get(id(node)),
                                       ast.Call)
                    and not (isinstance(node.value, ast.Name)
                             and node.value.id in env.imports)):
                parent = self.parents.get(id(node))
                if not (isinstance(parent, ast.Call)
                        and parent.func is node):
                    name, line = node.attr, node.lineno
            if name is not None:
                self.emit("GL305", site, line,
                          "hyperparameter %r is closed over as a trace-"
                          "time CONSTANT — changing it later silently "
                          "has no effect on the compiled program (pass "
                          "it as a traced operand, the lr/wd/rescale "
                          "convention)" % name)

    def _gl308(self, fn, seeds, site):
        params = [p for p in _fn_params(fn.args)
                  if p in seeds and not p.startswith("_")]
        loads = {p: [] for p in params}
        for node in _walk_skip_defs(_body_list(fn)):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in loads):
                loads[node.id].append(node)
        for p, uses in loads.items():
            if not uses:
                continue
            shape_only = True
            for u in uses:
                parent = self.parents.get(id(u))
                if (isinstance(parent, ast.Attribute)
                        and parent.attr in ("shape", "dtype", "ndim",
                                            "size")):
                    continue
                if (isinstance(parent, ast.Call)
                        and isinstance(parent.func, ast.Name)
                        and parent.func.id == "len"):
                    continue
                shape_only = False
                break
            if shape_only:
                self.emit("GL308", site, fn.lineno,
                          "traced parameter %r is used only for its "
                          "shape/dtype — a shape-polymorphic input with "
                          "no value use retraces per shape for data it "
                          "never reads (make it static or add a guard-"
                          "key component)" % p)

    # -- module-wide rules (GL306 / GL307) ---------------------------------
    def check_module_rules(self):
        self._gl306()
        self._gl307()

    def _stmt_blocks(self, fn):
        """Every statement list in ``fn`` + stmt -> (block, idx) map."""
        blocks, pos = [], {}
        stack = [fn]
        while stack:
            node = stack.pop()
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(node, field, None)
                if (isinstance(sub, list) and sub
                        and isinstance(sub[0], ast.stmt)):
                    blocks.append((sub, node))
                    for i, s in enumerate(sub):
                        pos[id(s)] = (sub, i, node)
                    stack.extend(
                        s for s in sub
                        if not isinstance(s, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)))
            for h in getattr(node, "handlers", ()) or ():
                stack.append(h)
        return blocks, pos

    def _gl306(self):
        if not (self.donated_names or self.donated_keys):
            return
        for info in self.defs:
            fn = info["node"]
            site = "%s.%s" % (self.module, info["qual"])
            _blocks, pos = self._stmt_blocks(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                dpos = self.donated_positions_of_call(node)
                if not dpos:
                    continue
                dnames = {a.id for p, a in enumerate(node.args)
                          if p in dpos and isinstance(a, ast.Name)}
                if not dnames:
                    continue
                # the statement holding the call, then every LATER
                # statement of its block and of each ancestor block
                stmt = node
                while id(stmt) not in pos and id(stmt) in self.parents:
                    stmt = self.parents[id(stmt)]
                while id(stmt) in pos:
                    block, idx, owner = pos[id(stmt)]
                    for later in block[idx + 1:]:
                        for n in ast.walk(later):
                            if (isinstance(n, ast.Name)
                                    and isinstance(n.ctx, ast.Load)
                                    and n.id in dnames):
                                self.emit(
                                    "GL306", site, n.lineno,
                                    "%r was DONATED at line %d — XLA "
                                    "aliased its buffer for an output; "
                                    "this read sees freed memory on "
                                    "real hardware" % (n.id,
                                                       node.lineno))
                    stmt = owner
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        break

    def _gl307(self):
        def scan(node, recording):
            for child in ast.iter_child_nodes(node):
                rec = recording
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    if any(isinstance(item.context_expr, ast.Call)
                           and _call_name(item.context_expr) == "record"
                           for item in child.items):
                        rec = True
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    scan(child, False)
                    continue
                if recording and isinstance(child, ast.Call):
                    cn = _call_name(child)
                    if cn == "compile_step" or (
                            isinstance(child.func, ast.Name)
                            and child.func.id in self.cstep_names):
                        scope, _cls = self._enclosing(child)
                        self.emit(
                            "GL307",
                            "%s.%s" % (self.module,
                                       ".".join(scope) or "<module>"),
                            child.lineno,
                            "compile_step under an open "
                            "autograd.record() scope: the compiled step "
                            "IS the whole record/backward/step triple — "
                            "call it outside any recording scope")
                scan(child, rec)

        scan(self.tree, False)

    # -- driver ------------------------------------------------------------
    def run(self, skip_registered=True):
        self.discover()
        for _key, (info, seeds) in sorted(
                self.traced.items(),
                key=lambda kv: kv[1][0]["node"].lineno):
            if skip_registered and self._is_registered(info["node"]):
                continue          # fcomputes are linted by lint_registry
            self.check_traced(info, seeds)
        self.check_module_rules()
        return self._dedup(self.diags)

    def _is_registered(self, fn_node):
        for dec in getattr(fn_node, "decorator_list", ()) or ():
            d = dec.func if isinstance(dec, ast.Call) else dec
            name = _call_name(d) if isinstance(d, ast.Call) else (
                d.attr if isinstance(d, ast.Attribute)
                else getattr(d, "id", None))
            if name and "register" in name:
                return True
        return False

    @staticmethod
    def _dedup(diags):
        seen, out = set(), []
        for d in diags:
            key = (d.code, d.file, d.line, d.op_name)
            if key in seen:
                continue
            seen.add(key)
            out.append(d)
        return out


# ---------------------------------------------------------------------------
# static pass: public entry points
# ---------------------------------------------------------------------------

def lint_source(source, filename="<memory>", module=None):
    """Lint one source string (fixture tests, editor integration)."""
    module = module or os.path.splitext(os.path.basename(filename))[0]
    try:
        scan = _ModuleScan(source, filename, module)
    except SyntaxError:
        return []
    return scan.run()


def lint_file(path):
    with open(path) as f:
        return lint_source(f.read(), filename=path)


def lint_package(root=None):
    """GL3xx over every .py file in the package (serving/, armor/,
    gluon/step_compile.py and everything else os.walk finds — the same
    walk the GL2xx pass uses, nothing opts out)."""
    root = root or package_root()
    diags = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path) as f:
                    source = f.read()
            except OSError:
                continue
            rel = os.path.relpath(path, os.path.dirname(root))
            diags.extend(lint_source(source, filename=path,
                                     module=rel[:-3].replace(os.sep,
                                                             ".")))
    return diags


# fcomputes already answer to GL108 for np.random/time/os.environ
# impurity, so registry mode runs only the rules GL1xx cannot express:
# materialization and control flow on the op's TRACED inputs
_REGISTRY_RULES = frozenset({"GL301", "GL302"})


def _array_param_seeds(args_node):
    """Taint seeds for an unnamed variadic fcompute: required params and
    None-default optionals are the arrays (``bias=None``); params with a
    bool/number/tuple default are host-side config (``axis=0``,
    ``no_bias=False``) and must NOT be seeded."""
    pos = list(getattr(args_node, "posonlyargs", ())) + list(args_node.args)
    defaults = list(args_node.defaults)
    first_def = len(pos) - len(defaults)
    seeds = set()
    for i, a in enumerate(pos):
        if i < first_def:
            seeds.add(a.arg)
        else:
            d = defaults[i - first_def]
            if isinstance(d, ast.Constant) and d.value is None:
                seeds.add(a.arg)
    for a, d in zip(args_node.kwonlyargs, args_node.kw_defaults):
        if d is None or (isinstance(d, ast.Constant) and d.value is None):
            seeds.add(a.arg)
    if args_node.vararg is not None:
        seeds.add(args_node.vararg.arg)
    return seeds


def lint_registry(names=None):
    """GL3xx over the live op registry: taint is seeded from the first
    ``num_inputs`` positional parameters (the traced arrays), so host
    kwargs like ``axis``/``is_train`` never false-positive."""
    from ..ops.registry import _REGISTRY
    diags, seen = [], set()
    for name in sorted(_REGISTRY):
        if names is not None and name not in names:
            continue
        op = _REGISTRY[name]
        if id(op) in seen:
            continue
        seen.add(id(op))
        fcompute = getattr(op, "fcompute", None)
        if fcompute is None:
            continue
        fn_node = _fcompute_tree(fcompute)
        if fn_node is None:
            continue
        params = _fn_params(fn_node.args)
        n = op.num_inputs if isinstance(op.num_inputs, int) else None
        if n is not None:
            seeds = set(params[:n])
        else:
            inames = getattr(op, "input_names", None)
            if inames:
                seeds = set(inames) & set(params)
            else:
                seeds = _array_param_seeds(fn_node.args)
        code = getattr(fcompute, "__code__", None)
        fname = code.co_filename if code else None
        line = code.co_firstlineno if code else None
        sup = suppressions_for(fcompute)
        scan = _ModuleScan("", fname or "<builtin>", "ops")
        scan.parents = {id(c): p for p in ast.walk(fn_node)
                        for c in ast.iter_child_nodes(p)}
        info = {"node": fn_node, "scope": (), "cls": None,
                "qual": fn_node.name}
        scan.check_traced(info, seeds, rules=_REGISTRY_RULES)
        for d in scan.diags:
            why = sup.get(d.code)
            diags.append(Diagnostic(
                d.code, name,
                "%s (line +%d)" % (d.message, d.line - fn_node.lineno),
                file=fname, line=line,
                suppressed=d.code in sup, justification=why))
    return _ModuleScan._dedup(diags)


def lint_callable(fn, taint_params=None, rules=None):
    """Lint one live function the way the package pass would lint a
    traced closure (used on user functions handed to compile_step)."""
    import inspect
    import textwrap
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return []
    try:
        tree = ast.parse(src)
    except (SyntaxError, IndentationError):
        return []
    fn_node = None
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_node = node
            break
    if fn_node is None:
        return []
    code = getattr(fn, "__code__", None)
    scan = _ModuleScan(src, code.co_filename if code else "<callable>",
                       getattr(fn, "__module__", None) or "<callable>")
    seeds = set(taint_params if taint_params is not None
                else _fn_params(fn_node.args))
    info = {"node": fn_node, "scope": (), "cls": None,
            "qual": fn_node.name}
    scan.check_traced(info, seeds, rules=rules)
    return scan._dedup(scan.diags)


# ---------------------------------------------------------------------------
# guard-key diffing (EH301 feed; also the always-on retrace metric label)
# ---------------------------------------------------------------------------

def _r(v, n=48):
    s = repr(v)
    return s if len(s) <= n else s[:n - 3] + "..."

_PARAM_META_FIELDS = ("name", "shape", "dtype", "grad_req")
_OPT_SIG_FIELDS = ("type", "multi_precision", "momentum",
                   "clip_gradient", "beta1", "beta2", "epsilon")


def diff_guard_key(old, new):
    """(component, detail) naming the FIRST differing element of two
    CompiledStep guard keys; ('cold', ...) when there is no prior key."""
    if old is None:
        return "cold", "no prior guard key (first trace)"
    if old == new:
        return "identical", None
    for i, comp in enumerate(GUARD_COMPONENTS):
        if i >= len(old) or i >= len(new) or old[i] == new[i]:
            continue
        o, n = old[i], new[i]
        if comp == "input-sig":
            detail = _diff_seq(o, n, "arg")
        elif comp == "param-set":
            detail = ("%d -> %d params" % (len(o), len(n))
                      if len(o) != len(n) else
                      "same count, different Parameter identities")
        elif comp == "param-meta":
            detail = _diff_meta(o, n)
        elif comp == "optimizer-sig":
            detail = _diff_fields(o, n, _OPT_SIG_FIELDS, "optimizer")
        else:
            detail = "%s -> %s" % (_r(o), _r(n))
        return comp, detail
    return "guard-key", "%s -> %s" % (_r(old), _r(new))


def _diff_seq(o, n, what):
    if len(o) != len(n):
        return "%d -> %d %ss" % (len(o), len(n), what)
    for i, (a, b) in enumerate(zip(o, n)):
        if a != b:
            return "%s %d: %s -> %s" % (what, i, _r(a), _r(b))
    return "%s -> %s" % (_r(o), _r(n))


def _diff_meta(o, n):
    if len(o) != len(n):
        return "%d -> %d params" % (len(o), len(n))
    for a, b in zip(o, n):
        if a == b:
            continue
        for f, (x, y) in zip(_PARAM_META_FIELDS[1:], zip(a[1:], b[1:])):
            if x != y:
                return "param %s: %s %s -> %s" % (a[0], f, _r(x), _r(y))
        return "param %s -> %s" % (_r(a), _r(b))
    return _r((o, n))


def _diff_fields(o, n, fields, what):
    for f, (x, y) in zip(fields, zip(o, n)):
        if x != y:
            return "%s %s: %s -> %s" % (what, f, _r(x), _r(y))
    return "%s -> %s" % (_r(o), _r(n))


# ---------------------------------------------------------------------------
# runtime auditor
# ---------------------------------------------------------------------------

def _journal(code, msg, **fields):
    try:
        from ..telemetry import blackbox
        blackbox.record("compile_check", code=code, msg=msg, **fields)
    except Exception:
        pass


def _stack_summary(skip=2, limit=10):
    import traceback
    frames = traceback.extract_stack()[:-skip]
    frames = [f for f in frames
              if "/analysis/compile_safety" not in (f.filename or "")]
    return "".join(traceback.format_list(frames[-limit:]))


# id(nd) -> (nd, tag, dispatch_stack).  Holding the NDArray strongly for
# the poison window (one dispatch) both keeps ids stable and lets sweep
# name survivors; the window is closed by _write (replacement landing)
# or StepAuditor.sweep() in the dispatch finally.
_POISON = {}


def on_read(nd):
    """NDArray._read hook (armed only while _ACTIVE[0] is True)."""
    rec = _POISON.get(id(nd))
    if rec is None:
        return
    _nd, tag, dispatch_stack = rec
    msg = ("EH302 donated-buffer read after dispatch: this NDArray's "
           "jax buffer was donated to the compiled %r program — XLA "
           "aliased that memory for an output, and the replacement "
           "value has not landed yet.  On real hardware this read "
           "returns freed memory.\n"
           "--- dispatch (donation) stack ---\n%s"
           "--- offending read stack ---\n%s"
           % (tag, dispatch_stack, _stack_summary()))
    _journal("EH302", "donated-buffer read after dispatch", tag=tag)
    raise CompileSafetyError("EH302", msg)


def on_write(nd):
    """NDArray._write hook: the replacement landing re-arms the buffer."""
    _POISON.pop(id(nd), None)


class StepAuditor(object):
    """Per-CompiledStep runtime auditor (EH301-EH304).

    Created lazily by CompiledStep when GRAFT_COMPILE_CHECK is on; all
    hooks are no-ops when the flag is off (raw-flag gated at the call
    sites, so the disabled cost never exceeds one list-index check)."""

    STORM_WINDOW = 8          # calls
    STORM_MISSES = 3          # misses within the window -> storm
    DEEP_EVERY = 4            # EH302/EH303 deep-check sampling (calls)

    def __init__(self, label="trainer"):
        self.label = label
        self.calls = 0
        self.storms = 0
        self.sentinel_checks = 0
        self.worst_sentinel_ulp = 0
        self._miss_log = []               # (call_idx, component, detail)
        self._since_sentinel = 0
        self._since_deep = 0
        self._poisoned = []
        self._stack_memo = {}             # tag -> dispatch stack (stable)

    # -- EH301 -------------------------------------------------------------
    def note_call(self):
        self.calls += 1

    def note_miss(self, component, detail):
        self._miss_log.append((self.calls, component, detail))
        del self._miss_log[:-64]
        recent = [m for m in self._miss_log
                  if self.calls - m[0] < self.STORM_WINDOW]
        if len(recent) < self.STORM_MISSES:
            return
        counts = {}
        for _c, comp, _d in recent:
            counts[comp] = counts.get(comp, 0) + 1
        top = max(counts, key=lambda k: counts[k])
        msg = ("EH301 retrace storm on %r: %d guard misses within the "
               "last %d calls; churned component: %s (%s) — last diff: "
               "%s" % (self.label, len(recent), self.STORM_WINDOW, top,
                       ", ".join("%s x%d" % (k, counts[k])
                                 for k in sorted(counts)),
                       detail or "<no detail>"))
        # graftxray: the retraces re-ran HLO cost analysis — name what
        # actually got more expensive, not just which guard churned
        try:
            from ..telemetry import xray as _xray_mod
            cost_growth = _xray_mod.cost_regressions()
        except Exception:
            cost_growth = ""
        if cost_growth:
            msg += " — cost growth since previous trace: " + cost_growth
        self.storms += 1
        self._miss_log = []     # re-arm: one report per storm burst
        _journal("EH301", msg, component=top, detail=detail,
                 cost_growth=cost_growth or None)
        try:
            from ..telemetry import metrics as _m
            _m.step_retrace_storm()
        except Exception:
            pass
        if abort_on_storm():
            raise CompileSafetyError("EH301", msg)
        warnings.warn("graftguard %s" % msg, RuntimeWarning,
                      stacklevel=3)

    # -- EH303 -------------------------------------------------------------
    def check_bake(self, kinds, baked, live):
        if baked == live:
            return
        where = "fused config"
        for k, (b, l) in enumerate(zip(baked, live)):
            if b == l:
                continue
            kind = kinds[k] if k < len(kinds) else "?"
            fields = (("beta1", "beta2", "epsilon", "clip_gradient")
                      if kind == "adam" else ("momentum",
                                              "clip_gradient"))
            where = "bucket %d (%s)" % (k, kind)
            for f, (x, y) in zip(fields, zip(b, l)):
                if x != y:
                    where += ": %s baked=%s live=%s" % (f, _r(x), _r(y))
                    break
            break
        msg = ("EH303 constant-bake drift under an UNCHANGED guard key: "
               "%s — the compiled program is still using the trace-time "
               "value; this scalar is baked as a constant (it must "
               "either join the guard key or ride as a traced operand)"
               % where)
        _journal("EH303", msg)
        raise CompileSafetyError("EH303", msg)

    # -- EH302/EH303 sampling ----------------------------------------------
    def deep_due(self):
        """Deep-check sampling (EH302 poison window + EH303 bake
        re-hash): arming every donated buffer on every call costs a
        dict store per array at dispatch plus a pop per array at
        write-back — it scales with param count and alone breaches the
        < 2% budget on many-param models.  Both defects are structural
        (a read-after-dispatch consumer runs every step; a drifted bake
        stays drifted), so checking every DEEP_EVERY-th armed call
        keeps the detection while capping the steady-state cost; tests
        force a window with ``aud._since_deep = aud.DEEP_EVERY``."""
        self._since_deep += 1
        if self._since_deep < self.DEEP_EVERY:
            return False
        self._since_deep = 0
        return True

    # -- EH302 -------------------------------------------------------------
    def poison(self, nds, tag):
        # the dispatch site for a given tag is the same frames every
        # step — capture once (extract_stack per dispatch would blow
        # the < 2% budget on its own)
        stack = self._stack_memo.get(tag)
        if stack is None:
            stack = self._stack_memo[tag] = _stack_summary()
        ids = []
        for nd in nds:
            _POISON[id(nd)] = (nd, tag, stack)
            ids.append(id(nd))
        self._poisoned = ids

    def sweep(self):
        """Close the poison window (dispatch finally): anything the
        write-back did not replace is unpoisoned here rather than left
        armed across steps."""
        for i in self._poisoned:
            _POISON.pop(i, None)
        self._poisoned = []

    # -- EH304 -------------------------------------------------------------
    def sentinel_due(self):
        n = check_every()
        if n <= 0:
            return False
        self._since_sentinel += 1
        if self._since_sentinel < n:
            return False
        self._since_sentinel = 0
        return True

    def check_parity(self, tag, compiled, reference, tol=None):
        from ..gluon.step_compile import max_ulp_diff
        tol = ulp_tol() if tol is None else tol
        worst, where = 0, tag
        for path, a, b in _zip_leaves(tag, compiled, reference):
            u = max_ulp_diff(a, b)
            if u > worst:
                worst, where = u, path
        self.sentinel_checks += 1
        if worst > self.worst_sentinel_ulp:
            self.worst_sentinel_ulp = worst
        if worst <= tol:
            return worst
        msg = ("EH304 compiled-vs-eager divergence on a sentinel step: "
               "%s diverged by %s ULP (tolerance %d) — the compiled "
               "program and its un-jitted twin no longer agree on the "
               "same operands and rng key" % (where, worst, tol))
        _journal("EH304", msg, ulp=int(worst), where=where)
        raise CompileSafetyError("EH304", msg)


def _zip_leaves(path, a, b):
    if isinstance(a, (tuple, list)) and isinstance(b, (tuple, list)):
        if len(a) != len(b):
            raise CompileSafetyError(
                "EH304", "EH304 structure mismatch at %s: %d vs %d "
                "leaves" % (path, len(a), len(b)))
        for i, (x, y) in enumerate(zip(a, b)):
            yield from _zip_leaves("%s[%d]" % (path, i), x, y)
        return
    if isinstance(a, dict) and isinstance(b, dict):
        if set(a) != set(b):
            raise CompileSafetyError(
                "EH304", "EH304 structure mismatch at %s: keys %s vs %s"
                % (path, sorted(a), sorted(b)))
        for k in sorted(a):
            yield from _zip_leaves("%s[%r]" % (path, k), a[k], b[k])
        return
    if a is None and b is None:
        return
    yield path, a, b


# ---------------------------------------------------------------------------
# selftest: every GL301-GL308 + EH301-EH304 through the real paths
# ---------------------------------------------------------------------------

_GL_FIXTURES = {
    # code -> (bad source, clean source)
    "GL301": (
        "import jax\n"
        "def step(f):\n"
        "    def loss(x):\n"
        "        return float(x.sum()) + x.asnumpy().mean()\n"
        "    return jax.jit(loss)\n",
        "import jax\n"
        "def step(f):\n"
        "    def loss(x):\n"
        "        return x.sum() * 2\n"
        "    return jax.jit(loss)\n"),
    "GL302": (
        "import jax\n"
        "def build():\n"
        "    def f(x):\n"
        "        if x.sum() > 0:\n"
        "            return x\n"
        "        return -x\n"
        "    return jax.jit(f)\n",
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def build():\n"
        "    def f(x):\n"
        "        if x.ndim > 1:\n"
        "            return x\n"
        "        return jnp.where(x > 0, x, -x)\n"
        "    return jax.jit(f)\n"),
    "GL303": (
        "import jax\n"
        "import os\n"
        "def build():\n"
        "    def f(x):\n"
        "        scale = 2.0 if os.environ.get('FAST') else 1.0\n"
        "        return x * scale\n"
        "    return jax.jit(f)\n",
        "import jax\n"
        "import os\n"
        "def build():\n"
        "    scale = 2.0 if os.environ.get('FAST') else 1.0\n"
        "    def f(x):\n"
        "        return x * scale\n"
        "    return jax.jit(f)\n"),
    "GL304": (
        "import jax\n"
        "def build():\n"
        "    seen = []\n"
        "    def f(x):\n"
        "        seen.append(1)\n"
        "        return x * 2\n"
        "    return jax.jit(f)\n",
        "import jax\n"
        "def build():\n"
        "    def f(x):\n"
        "        seen = []\n"
        "        seen.append(1)\n"
        "        return x * 2\n"
        "    return jax.jit(f)\n"),
    "GL305": (
        "import jax\n"
        "def build(lr):\n"
        "    def update(w, g):\n"
        "        return w - lr * g\n"
        "    return jax.jit(update)\n",
        "import jax\n"
        "def build():\n"
        "    def update(w, g, lr):\n"
        "        return w - lr * g\n"
        "    return jax.jit(update)\n"),
    "GL306": (
        "import jax\n"
        "def run(f, w, s, x):\n"
        "    prog = jax.jit(f, donate_argnums=(0, 1))\n"
        "    out = prog(w, s, x)\n"
        "    stale = w.sum()\n"
        "    return out, stale\n",
        "import jax\n"
        "def run(f, w, s, x):\n"
        "    prog = jax.jit(f, donate_argnums=(0, 1))\n"
        "    out = prog(w, s, x)\n"
        "    return out, x.sum()\n"),
    "GL307": (
        "from incubator_mxnet_tpu import autograd\n"
        "def train(trainer, net, loss, x):\n"
        "    with autograd.record():\n"
        "        step = trainer.compile_step(net, loss=loss)\n"
        "    return step(x)\n",
        "from incubator_mxnet_tpu import autograd\n"
        "def train(trainer, net, loss, x):\n"
        "    step = trainer.compile_step(net, loss=loss)\n"
        "    return step(x)\n"),
    "GL308": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def build():\n"
        "    def f(x, template):\n"
        "        return x.reshape(template.shape[0], -1)\n"
        "    return jax.jit(f)\n",
        "import jax\n"
        "def build():\n"
        "    def f(x, template):\n"
        "        return x.reshape(template.shape[0], -1) + template\n"
        "    return jax.jit(f)\n"),
}


def _codes(diags, active_only=True):
    return sorted({d.code for d in diags
                   if not (active_only and d.suppressed)})


def selftest(verbose=False):
    """Returns a list of problems — empty means pass."""
    problems = []

    # ---- static: every rule's bad fixture fires, its clean twin doesn't
    for code, (bad, good) in sorted(_GL_FIXTURES.items()):
        got = _codes(lint_source(bad, filename="fixture_%s.py" % code))
        if code not in got:
            problems.append("%s: bad fixture produced %s (expected %s)"
                            % (code, got or "nothing", code))
        got_clean = _codes(lint_source(good,
                                       filename="fixture_%s_ok.py"
                                       % code))
        if code in got_clean:
            problems.append("%s: clean fixture still fires (%s)"
                            % (code, got_clean))
        if verbose:
            print("static %s: bad=%s clean=%s" % (code, got, got_clean))

    # ---- static: suppression honored
    sup_src = _GL_FIXTURES["GL304"][0].replace(
        "seen.append(1)",
        "seen.append(1)  # graftlint: disable=GL304 -- trace-time memo")
    sup = lint_source(sup_src, filename="fixture_sup.py")
    if any(d.code == "GL304" and not d.suppressed for d in sup):
        problems.append("suppression comment was not honored")
    if not any(d.code == "GL304" and d.suppressed
               and d.justification for d in sup):
        problems.append("suppressed finding lost its justification")

    # ---- static: the repo itself is clean (package walk + registry)
    import incubator_mxnet_tpu  # noqa: F401  (registers the op registry)
    pkg = [d for d in lint_package() if not d.suppressed]
    if pkg:
        problems.append("package pass not clean: %s"
                        % "; ".join(repr(d) for d in pkg[:8]))
    reg = [d for d in lint_registry() if not d.suppressed]
    if reg:
        problems.append("registry pass not clean: %s"
                        % "; ".join(repr(d) for d in reg[:8]))

    # ---- guard-key diffing names exact components
    old = ((((6, 5), "float32"),), "fmt", (1, 2), (("w0", (1, 5),
            "float32", "write"),), ("SGD", False, 0.9, None, None, None,
            None), 1, None, 1 << 20)
    new_shape = ((((3, 5), "float32"),),) + old[1:]
    comp, detail = diff_guard_key(old, new_shape)
    if comp != "input-sig" or "arg 0" not in (detail or ""):
        problems.append("guard diff misnamed a shape flip: %s / %s"
                        % (comp, detail))
    new_gr = (old[0], old[1], old[2],
              (("w0", (1, 5), "float32", "null"),)) + old[4:]
    comp, detail = diff_guard_key(old, new_gr)
    if comp != "param-meta" or "grad_req" not in (detail or ""):
        problems.append("guard diff misnamed a grad_req flip: %s / %s"
                        % (comp, detail))

    # ---- runtime: EH301-EH304 through the REAL compile_step path
    problems.extend(_selftest_runtime(verbose))
    return problems


def _selftest_runtime(verbose=False):
    import numpy as np
    import incubator_mxnet_tpu as mx
    from ..gluon import Trainer
    from ..gluon import step_compile as sc
    from ..telemetry import blackbox

    problems = []
    prev_override = _enabled_override
    prev_every = os.environ.get("GRAFT_COMPILE_CHECK_EVERY")
    set_enabled(True)
    try:
        # EH301: forced shape-flip loop -> storm naming input-sig
        net = sc._make_net("graftguard_eh301_")
        sc._seed_params(net)
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.05, "momentum": 0.9},
                     kvstore=None)
        cstep = sc.CompiledStep(tr, net, enabled=True)
        rng = np.random.RandomState(11)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for i in range(5):     # every step a NEW shape: pure churn
                x = mx.nd.array(rng.uniform(
                    0.5, 1.5, (2 + i, 5)).astype(np.float32))
                cstep(x)
        aud = cstep._auditor
        if aud is None or aud.storms < 1:
            problems.append("EH301: shape-flip loop raised no storm "
                            "(auditor=%r)" % aud)
        else:
            storm = [str(w.message) for w in caught
                     if "EH301" in str(w.message)]
            if not storm or "input-sig" not in storm[-1]:
                problems.append("EH301 storm did not name the churned "
                                "component: %s" % (storm or "<no warn>"))
            elif verbose:
                print("EH301:", storm[-1][:120])
        evs = [e for e in blackbox.events()
               if e.get("kind") == "compile_check"
               and e["data"].get("code") == "EH301"]
        if not evs:
            problems.append("EH301 storm was not journaled to blackbox")

        # steady harness for EH302/303/304
        net2 = sc._make_net("graftguard_eh_")
        sc._seed_params(net2)
        tr2 = Trainer(net2.collect_params(), "sgd",
                      {"learning_rate": 0.05, "momentum": 0.9},
                      kvstore=None)
        cs2 = sc.CompiledStep(tr2, net2, enabled=True)
        x = mx.nd.array(rng.uniform(0.5, 1.5, (4, 5)).astype(np.float32))
        for _ in range(3):
            cs2(x)
        if cs2.compiled_steps < 2:
            problems.append("runtime harness never reached the compiled "
                            "path (compiled=%d)" % cs2.compiled_steps)

        # EH302: a consumer reading a donated param before the
        # replacement lands (interposed inside the real write-back)
        real_wb = cs2._write_back
        victim = {}

        def bad_write_back(entry, new_w, new_s, state_nds, frozen_nds,
                           aux):
            nd = tr2._params[entry["trainable"][0]].list_data()[0]
            victim["val"] = nd._read()        # donated, not yet replaced
            return real_wb(entry, new_w, new_s, state_nds, frozen_nds,
                           aux)

        cs2._write_back = bad_write_back
        cs2._auditor._since_deep = cs2._auditor.DEEP_EVERY
        try:
            cs2(x)
            problems.append("EH302: donated read before write-back did "
                            "not raise")
        except CompileSafetyError as e:
            if e.code != "EH302" or "dispatch" not in str(e) \
                    or "read stack" not in str(e):
                problems.append("EH302 raised without both stacks: %s"
                                % str(e)[:160])
            elif verbose:
                print("EH302: raised with both stacks")
        finally:
            cs2._write_back = real_wb
        cs2(x)                                 # clean step passes again

        # EH303: drift a fused-config scalar UNDER the guard key (the
        # guard reads optimizer attrs; _fused_config is monkeypatched so
        # only the bake hash sees the drift — exactly the future-guard-
        # regression this rule defends against)
        from .. import optimizer as opt_mod
        real_cfg = opt_mod._fused_config

        def drifted_cfg(optimizer, kind):
            cfg = real_cfg(optimizer, kind)
            return (cfg[0] + 0.05,) + tuple(cfg[1:])

        opt_mod._fused_config = drifted_cfg
        cs2._auditor._since_deep = cs2._auditor.DEEP_EVERY
        try:
            import incubator_mxnet_tpu.gluon.step_compile as _sc
            _sc.opt._fused_config = drifted_cfg
            try:
                cs2(x)
                problems.append("EH303: baked-config drift did not "
                                "raise")
            except CompileSafetyError as e:
                if e.code != "EH303" or "momentum" not in str(e):
                    problems.append("EH303 did not name the drifted "
                                    "field: %s" % str(e)[:160])
                elif verbose:
                    print("EH303:", str(e)[:120])
        finally:
            opt_mod._fused_config = real_cfg
            _sc.opt._fused_config = real_cfg
        cs2(x)

        # EH304: sentinel replay clean, then a poisoned twin must raise
        os.environ["GRAFT_COMPILE_CHECK_EVERY"] = "1"
        try:
            cs2(x)
            aud2 = cs2._auditor
            if aud2 is None or aud2.sentinel_checks < 1:
                problems.append("EH304 sentinel never ran under "
                                "GRAFT_COMPILE_CHECK_EVERY=1")
            key = next(k for k in cs2._entries
                       if isinstance(cs2._entries.get(k), dict))
            entry = cs2._entries[key]
            real_raw = entry["one_raw"]
            entry["one_raw"] = (
                lambda *a: _perturb(real_raw(*a)))
            try:
                cs2(x)
                problems.append("EH304: perturbed twin did not raise")
            except CompileSafetyError as e:
                if e.code != "EH304" or "ULP" not in str(e):
                    problems.append("EH304 raised oddly: %s"
                                    % str(e)[:160])
                elif verbose:
                    print("EH304:", str(e)[:120])
            finally:
                entry["one_raw"] = real_raw
            cs2(x)                             # clean sentinel again
        finally:
            if prev_every is None:
                os.environ.pop("GRAFT_COMPILE_CHECK_EVERY", None)
            else:
                os.environ["GRAFT_COMPILE_CHECK_EVERY"] = prev_every

        # disabled inertness: flag off -> hooks dormant, no poison left
        set_enabled(False)
        if _POISON:
            problems.append("poison map not empty after disable")
        cs2(x)
    finally:
        set_enabled(prev_override)
    return problems


def _perturb(res):
    import jax.numpy as jnp
    outs, aux, new_w, new_s = res
    new_w = tuple(w + jnp.float32(1e-3) for w in new_w)
    return outs, aux, new_w, new_s


def main(argv=None):
    import argparse
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m incubator_mxnet_tpu.analysis.compile_safety",
        description="graftguard compile-safety lint + auditor selftest")
    ap.add_argument("--selftest", action="store_true",
                    help="force every GL3xx/EH3xx diagnostic through "
                         "the real lint / compile_step paths (CI tier)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if not args.selftest:
        ap.print_help()
        return 2
    problems = selftest(verbose=args.verbose)
    if problems:
        for p in problems:
            print("graftguard selftest FAIL: %s" % p, file=sys.stderr)
        return 1
    print("graftguard selftest OK (GL301-GL308 fixtures + clean twins, "
          "suppression flow, guard-key diffing, EH301 storm named the "
          "churned component, EH302 both-stack raise, EH303 bake drift, "
          "EH304 sentinel parity, repo package+registry clean)")
    return 0


if __name__ == "__main__":
    # `python -m ...compile_safety` executes this file a SECOND time as
    # __main__ while step_compile/ndarray hold the canonical sys.modules
    # copy — set_enabled() on the __main__ twin would be invisible to
    # them, so delegate to the canonical module's main().
    from incubator_mxnet_tpu.analysis import compile_safety as _canon
    sys.exit(_canon.main())
