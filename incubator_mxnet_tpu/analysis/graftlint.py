"""graftlint — CLI for the op-contract + concurrency + compile-safety
linters.

Usage::

    python -m incubator_mxnet_tpu.analysis.graftlint [--all] [--json]
           [--ops NAME[,NAME...]] [--list-rules] [--baseline PATH]

Imports the full ops package (registration side effects populate the
registry and the registration log), runs every contract rule (GL1xx),
then the static concurrency rules (GL2xx — lock-order inversions,
unguarded thread-shared globals, ``_sched_*`` protocol completeness,
daemon threads without shutdown paths; analysis/concurrency.py) and the
compile-safety rules (GL3xx — host round-trips / traced branching /
constant-baked hyperparameters / donation hazards in trace-eligible
closures; analysis/compile_safety.py) over the package sources, and
exits non-zero on unsuppressed findings.  ``--ops`` restricts to the
op-contract + registry compile-safety passes.  ``--json`` emits the
machine-readable report to stdout, ``--report PATH`` writes it to a file
alongside the human summary (one linter pass serves both),
``--contracts`` dumps every registered op's machine-readable contract
(Operator.contract()).

Baselines: ``--write-baseline PATH`` snapshots the current unsuppressed
findings; a later run with ``--baseline PATH`` fails ONLY on findings
not in the snapshot (new code held strict, legacy debt non-blocking) —
masked findings are still printed and counted.

Linting is platform-independent, so the CLI pins jax to CPU before the
ops import — the axon sitecustomize otherwise force-selects the TPU
platform and a lint run would die at backend init (or crawl through the
tunnel) on a box without an attached TPU.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu_platform():
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass   # backend already initialized (in-process callers): lint
        #        works on whatever platform the host chose


def _report_json(diags):
    active = [d for d in diags if not d.suppressed]
    counts = {}
    for d in active:
        counts[d.code] = counts.get(d.code, 0) + 1
    return {
        "version": 1,
        "total": len(active),
        "suppressed": sum(1 for d in diags if d.suppressed),
        "counts": counts,
        "diagnostics": [d.as_dict() for d in diags],
    }


def _baseline_key(d):
    """Identity of a finding across unrelated edits: code + site + the
    file's basename (absolute paths differ per checkout; line numbers
    drift with every edit above them, so they are deliberately NOT part
    of the key — the baseline masks by count per key instead)."""
    return "%s|%s|%s" % (d.code, d.op_name,
                         os.path.basename(d.file) if d.file else "-")


def _baseline_counts(diags):
    counts = {}
    for d in diags:
        if d.suppressed:
            continue
        k = _baseline_key(d)
        counts[k] = counts.get(k, 0) + 1
    return counts


def write_baseline(path, diags):
    with open(path, "w") as f:
        json.dump({"version": 1, "counts": _baseline_counts(diags)},
                  f, indent=2, sort_keys=True)


def apply_baseline(path, diags):
    """Split active findings into (new, masked) against a snapshot.
    Per key, up to the snapshot's count is masked; anything beyond it
    (or any unseen key) is new and fails the run."""
    with open(path) as f:
        doc = json.load(f)
    budget = dict(doc.get("counts") or {})
    new, masked = [], []
    for d in diags:
        if d.suppressed:
            continue
        k = _baseline_key(d)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            masked.append(d)
        else:
            new.append(d)
    return new, masked


def main(argv=None):
    from . import compile_safety, concurrency, contracts

    ap = argparse.ArgumentParser(
        prog="graftlint", description="op-contract static analyzer")
    ap.add_argument("--all", action="store_true",
                    help="lint every registered op (default when no --ops)")
    ap.add_argument("--ops", default=None,
                    help="comma-separated op names to lint")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable JSON report on stdout")
    ap.add_argument("--report", default=None, metavar="PATH",
                    help="also write the JSON report to PATH (single pass)")
    ap.add_argument("--contracts", action="store_true",
                    help="dump every op's machine-readable contract as "
                         "JSON and exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the diagnostic codes and exit")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="fail only on findings NOT in this snapshot "
                         "(legacy debt stays non-blocking)")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="snapshot the current unsuppressed findings "
                         "and exit 0")
    args = ap.parse_args(argv)

    if args.list_rules:
        rules = dict(contracts.RULES)
        rules.update(concurrency.RULES)
        rules.update(compile_safety.RULES)
        for code in sorted(rules):
            print("%s  %s" % (code, rules[code]))
        for code in sorted(compile_safety.EH_RULES):
            print("%s  %s (runtime, GRAFT_COMPILE_CHECK=1)"
                  % (code, compile_safety.EH_RULES[code]))
        return 0

    _force_cpu_platform()
    # registration side effects; engine hazards (pass 2) live at runtime
    # behind GRAFT_ENGINE_CHECK=1, not here
    import incubator_mxnet_tpu.ops  # noqa: F401
    import incubator_mxnet_tpu.operator  # noqa: F401  custom-op registry

    names = None
    if args.ops:
        names = {n for n in args.ops.split(",") if n}

    if args.contracts:
        from ..ops.registry import _REGISTRY
        out = {n: op.contract() for n, op in sorted(_REGISTRY.items())
               if names is None or n in names}
        print(json.dumps(out, indent=2, default=str))
        return 0

    diags = contracts.lint_all(names=names)
    diags += compile_safety.lint_registry(names=names)
    if names is None:
        # the concurrency + compile-safety tiers lint the package
        # sources, not ops — an --ops-restricted run (fixture tests)
        # skips them
        diags += concurrency.lint_package()
        diags += compile_safety.lint_package()
    active = [d for d in diags if not d.suppressed]

    if args.write_baseline:
        write_baseline(args.write_baseline, diags)
        print("graftlint: baseline of %d finding(s) written to %s"
              % (len(active), args.write_baseline))
        return 0

    masked = []
    if args.baseline:
        active, masked = apply_baseline(args.baseline, diags)

    report = _report_json(diags)
    if args.baseline:
        report["baseline"] = {"path": args.baseline,
                              "masked": len(masked),
                              "new": len(active)}

    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2, default=str)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        for d in diags:
            print(repr(d))
        print("graftlint: %d finding(s), %d suppressed, %d op name(s) "
              "checked" % (len(active),
                           sum(1 for d in diags if d.suppressed),
                           len(names) if names is not None else
                           _registry_size()))
        if masked:
            print("graftlint: %d baseline-masked finding(s) (%s)"
                  % (len(masked), args.baseline))
        if args.report:
            print("graftlint: JSON report at %s" % args.report)
    return 1 if active else 0


def _registry_size():
    from ..ops.registry import _REGISTRY
    return len(_REGISTRY)


if __name__ == "__main__":
    sys.exit(main())
