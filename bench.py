"""Benchmark: ResNet-50 training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}

Baseline: the reference's published single-GPU ResNet-50 train number,
batch 32 — 90.74 img/s on M40 (docs/faq/perf.md:174; the K80 row is 45.52).
Same workload (ResNet-50, synthetic ImageNet shapes), run the TPU-native
way: ONE fused XLA train step (forward+loss+backward+SGD update) via
parallel.DataParallelTrainer, bf16 compute with f32 master weights
(mixed precision, reference mp_sgd semantics), batch 256.

The final sync is a host fetch of the last step's loss — the donated
parameter chain makes it depend on every step, so the measured time is
true end-to-end wall clock (block_until_ready alone does not reliably
synchronize through the axon device tunnel).
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_IMG_S = 90.74  # M40, ResNet-50 train batch 32 (docs/faq/perf.md:174)


def _probe_backend():
    """Run backend discovery in a side process under a hard timeout (it
    inherits JAX_PLATFORMS, so a pinned platform is probed as pinned).
    Returns the reported default backend, or "" on crash/hang."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True,
            timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT", "90")))
        out = r.stdout.strip()
        return out.splitlines()[-1] if r.returncode == 0 and out else ""
    except Exception:
        return ""


def _resolve_backend():
    """Pick the jax platform BEFORE jax initializes in this process.

    On machines without a healthy TPU, backend discovery either raises
    (BENCH_r05: rc=1, "Unable to initialize backend" from
    ``jax.default_backend()`` via the axon plugin) or hangs for minutes —
    and an operator-pinned ``JAX_PLATFORMS=tpu`` hits the same wall
    in-process.  So: probe discovery in a side process under a hard
    timeout (it inherits any pinned platform).  Unpinned, cpu is forced
    unless the probe reports a live TPU (as before); pinned, the pin
    wins whenever the probe SUCCEEDS (a healthy ``cuda`` pin stays
    ``cuda``) and only a crashed/hung probe falls back to cpu.  A pinned
    ``cpu`` skips the probe.  Belt-and-braces, the in-process query
    still falls back to cpu on a backend-init error instead of crashing
    the bench."""
    global _RESOLVED_BACKEND
    pinned = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if pinned != "cpu":
        probed = _probe_backend()
        if (not probed) if pinned else (probed != "tpu"):
            os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        _RESOLVED_BACKEND = jax.default_backend()
    except RuntimeError:
        # the probe lied or raced: documented CPU fallback (discovery
        # caches only successes, so the retry re-runs against cpu)
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        _RESOLVED_BACKEND = jax.default_backend()
    return _RESOLVED_BACKEND


_RESOLVED_BACKEND = None


def _tpu_kernel_smoke(backend):
    """Exercise the Pallas flash-attention kernel on the real chip and
    check it against the jnp reference path (the TPU-marked smoke subset
    of the op test strategy — the CPU suite can never reach this code)."""
    import jax
    import jax.numpy as jnp
    if backend != "tpu":
        return
    from incubator_mxnet_tpu.ops.attention import (
        _attention_reference, _flash_forward_pallas)
    rs = np.random.RandomState(1)
    for causal in (False, True):
        q = jnp.asarray(rs.randn(2, 4, 256, 64).astype(np.float32))
        k = jnp.asarray(rs.randn(2, 4, 256, 64).astype(np.float32))
        v = jnp.asarray(rs.randn(2, 4, 256, 64).astype(np.float32))
        got = _flash_forward_pallas(q, k, v, causal, 0.125)
        # the kernel computes in full f32; hold the jnp reference to the
        # same precision (TPU default would run its matmuls in bf16)
        with jax.default_matmul_precision("highest"):
            ref = _attention_reference(q, k, v, causal, 0.125)
        err = float(jnp.max(jnp.abs(got - ref)))
        assert err < 2e-3, "flash kernel mismatch on TPU (causal=%s): %g" \
            % (causal, err)


def _compiled_step_probe(n_params=8, shape=(16, 16), iters=6):
    """graftstep rider: a token-sized whole-step-compilation probe so the
    chip bench's JSON carries the compiled-vs-eager step ratio on the
    REAL backend (the full 64-param gate lives in bench_eager --smoke).
    Returns {} when the probe cannot run — the headline img/s must not
    die on a telemetry rider."""
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    try:
        class Net(gluon.HybridBlock):
            def __init__(self, **kw):
                super().__init__(**kw)
                with self.name_scope():
                    for k in range(n_params):
                        setattr(self, "w%d" % k,
                                self.params.get("w%d" % k, shape=shape))

            def hybrid_forward(self, F, x, **ps):
                acc = None
                for k in range(n_params):
                    y = (ps["w%d" % k] * ps["w%d" % k] * x).sum()
                    acc = y if acc is None else acc + y
                return acc

        def build(prefix):
            net = Net(prefix=prefix)
            net.initialize(ctx=mx.cpu())
            rs = np.random.RandomState(0)
            for name in sorted(net.collect_params()):
                p = net.collect_params()[name]
                p.set_data(mx.nd.array(
                    rs.randn(*p.shape).astype(np.float32)))
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.01, "momentum": 0.9},
                               kvstore=None)
            return net, tr

        x = mx.nd.array(
            np.random.RandomState(1).rand(*shape).astype(np.float32))
        net_e, tr_e = build("bpe")
        net_c, tr_c = build("bpc")
        cstep = tr_c.compile_step(net_c, enabled=True)

        def eager_iter():
            with autograd.record():
                out = net_e(x)
            out.backward()
            tr_e.step(1)

        for _ in range(2):          # warm: compiles + lazy trace
            eager_iter()
            cstep(x)
        t0 = time.perf_counter()
        for _ in range(iters):
            eager_iter()
        net_e.collect_params()[sorted(net_e.collect_params())[-1]] \
            .data().asnumpy()
        dt_e = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            cstep(x)
        net_c.collect_params()[sorted(net_c.collect_params())[-1]] \
            .data().asnumpy()
        dt_c = (time.perf_counter() - t0) / iters
        return {
            "compiled_step_latency_ratio": round(dt_c / dt_e, 3),
            "compiled_step_eager_ms": round(dt_e * 1e3, 3),
            "compiled_step_compiled_ms": round(dt_c * 1e3, 3),
            "compiled_step_backend": jax.default_backend(),
            "compiled_step_retraces": cstep.retraces,
        }
    except Exception as exc:
        return {"compiled_step_error": "%s: %s" % (type(exc).__name__,
                                                   exc)}


def main():
    backend = _resolve_backend()
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel import make_mesh, DataParallelTrainer

    _tpu_kernel_smoke(backend)

    on_tpu = backend == "tpu"
    # CPU fallback exists to keep the bench trajectory alive on TPU-less
    # machines (same workload, token-sized): batch 4 x 2 steps finishes
    # in ~1 min where the TPU shape would run for hours.
    batch = int(os.environ.get("BENCH_BATCH", "256" if on_tpu else "4"))
    dtype = os.environ.get("BENCH_DTYPE",
                           "bfloat16" if on_tpu else "float32")
    mx.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())

    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer="sgd", optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9},
        mesh=mesh, dtype=None if dtype in ("float32", "none") else dtype)

    n_steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "2"))
    rs = np.random.RandomState(0)

    if os.environ.get("BENCH_DATA", "0") not in ("0", ""):
        # Feed training from a RecordIO file through the full data plane
        # (indexed reader → threaded raw decode → batch assembly →
        # PrefetchingIter): the reference's train_imagenet.py shape.
        #
        # Two measured quantities: (a) the host pipeline's standalone
        # rate, (b) training over DISTINCT device-resident batches that
        # the pipeline produced.  The batches are staged to HBM before
        # the first jit runs because the axon device tunnel collapses
        # host->device transfer bandwidth ~100x once any XLA execution
        # has happened (measured 66 ms -> 6.4 s for the same 38 MB
        # device_put; docs/perf_analysis_r03.md) — a transport artifact
        # a real TPU host's DMA path does not share; overlap belongs to
        # PrefetchingIter, which this mode exercises on the host side.
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        n_batches = 4
        it = _make_rec_iter(mx, rs, batch, n_batches=n_batches)
        pipe0 = time.perf_counter()
        host_batches = []
        for _ in range(2 * n_batches):  # two epochs through the pipeline
            b = _next_cycled(it)
            host_batches.append((np.asarray(b.data[0]._read()),
                                 np.asarray(b.label[0]._read())))
        pipe_dt = time.perf_counter() - pipe0
        pipe_img_s = len(host_batches) * batch / pipe_dt
        batch_sh = NamedSharding(mesh, P("dp"))
        dev_batches = [(_jax.device_put(x, batch_sh),
                        _jax.device_put(y, batch_sh))
                       for x, y in host_batches[:n_batches]]
        for i in range(3):
            x, y = dev_batches[i % n_batches]
            loss = trainer.step(mx.nd.NDArray(x), mx.nd.NDArray(y))
        float(np.asarray(loss))
        t0 = time.perf_counter()
        for i in range(n_steps):
            x, y = dev_batches[i % n_batches]
            loss = trainer.step(mx.nd.NDArray(x), mx.nd.NDArray(y))
        final = float(np.asarray(loss))
        dt = time.perf_counter() - t0
        assert np.isfinite(final), "bench loss went non-finite"
        img_s = n_steps * batch / dt
        print(json.dumps({
            "metric": "resnet50_train_imgs_per_sec_per_chip_recordio",
            "value": round(img_s, 2),
            "unit": "img/s",
            "backend": backend,
            "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
            "host_pipeline_img_per_sec": round(pipe_img_s, 2),
            **_compiled_step_probe(),
            "metrics": mx.telemetry.compact_snapshot(),
            "blackbox": mx.telemetry.blackbox.stats(),
        }))
        return
    else:
        x = mx.nd.array(rs.rand(batch, 3, 224, 224).astype(np.float32))
        y = mx.nd.array((rs.rand(batch) * 1000).astype(np.float32))

        # warmup (compile); sync before the timed region starts
        for _ in range(3 if on_tpu else 1):
            loss = trainer.step(x, y)
        float(np.asarray(loss))

        t0 = time.perf_counter()
        for _ in range(n_steps):
            loss = trainer.step(x, y)
        final = float(np.asarray(loss))  # host fetch = true sync point
        dt = time.perf_counter() - t0
        metric = "resnet50_train_imgs_per_sec_per_chip"
    assert np.isfinite(final), "bench loss went non-finite"

    img_s = n_steps * batch / dt
    print(json.dumps({
        "metric": metric,
        "value": round(img_s, 2),
        "unit": "img/s",
        "backend": backend,
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        **_compiled_step_probe(),
        "metrics": mx.telemetry.compact_snapshot(),
        "blackbox": mx.telemetry.blackbox.stats(),
    }))


def _make_rec_iter(mx, rs, batch, n_batches):
    """Write a raw-tensor .rec (if absent) and open the full pipeline over
    it: uint8 end-to-end on the host, cast to compute dtype on device."""
    from incubator_mxnet_tpu import recordio, io as mio
    n = batch * n_batches
    path = os.environ.get("BENCH_REC_PATH",
                          "/tmp/bench_imagenet_raw_%d" % n)
    if not os.path.exists(path + ".rec"):
        rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
        for i in range(n):
            img = (rs.rand(224, 224, 3) * 255).astype(np.uint8)
            header = recordio.IRHeader(0, float(i % 1000), i, 0)
            rec.write_idx(i, recordio.pack(header, img.tobytes()))
        rec.close()
    it = mio.ImageRecordIter(
        path_imgrec=path + ".rec", path_imgidx=path + ".idx",
        data_shape=(3, 224, 224), batch_size=batch, dtype="uint8",
        aug_list=[], preprocess_threads=2, prefetch_buffer=3,
        ctx=mx.cpu(0))
    return it


def _next_cycled(it):
    try:
        return it.next()
    except StopIteration:
        it.reset()
        return it.next()


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:
        # the bench trajectory parses ONE JSON line per round: even on an
        # unexpected failure, emit it (rc stays non-zero so the failure
        # itself is still visible)
        print(json.dumps({
            "metric": "resnet50_train_imgs_per_sec_per_chip"
                      + ("_recordio" if os.environ.get("BENCH_DATA", "0")
                         not in ("0", "") else ""),
            "value": None,
            "unit": "img/s",
            "backend": (_RESOLVED_BACKEND
                        or os.environ.get("JAX_PLATFORMS") or "unknown"),
            "error": "%s: %s" % (type(exc).__name__, exc),
        }))
        raise
