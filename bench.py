"""Benchmark: ResNet-50 training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}

Baseline: the reference's published single-GPU ResNet-50 train number,
batch 32 — 90.74 img/s on M40 (docs/faq/perf.md:174; the K80 row is 45.52).
Same workload (ResNet-50, synthetic ImageNet shapes), run the TPU-native
way: ONE fused XLA train step (forward+loss+backward+SGD update) via
parallel.DataParallelTrainer, bf16 compute with f32 master weights
(mixed precision, reference mp_sgd semantics), batch 256.

The final sync is a host fetch of the last step's loss — the donated
parameter chain makes it depend on every step, so the measured time is
true end-to-end wall clock (block_until_ready alone does not reliably
synchronize through the axon device tunnel).
"""
import json
import os
import time

import numpy as np

BASELINE_IMG_S = 90.74  # M40, ResNet-50 train batch 32 (docs/faq/perf.md:174)


def _tpu_kernel_smoke():
    """Exercise the Pallas flash-attention kernel on the real chip and
    check it against the jnp reference path (the TPU-marked smoke subset
    of the op test strategy — the CPU suite can never reach this code)."""
    import jax
    import jax.numpy as jnp
    if jax.default_backend() != "tpu":
        return
    from incubator_mxnet_tpu.ops.attention import (
        _attention_reference, _flash_forward_pallas)
    rs = np.random.RandomState(1)
    for causal in (False, True):
        q = jnp.asarray(rs.randn(2, 4, 256, 64).astype(np.float32))
        k = jnp.asarray(rs.randn(2, 4, 256, 64).astype(np.float32))
        v = jnp.asarray(rs.randn(2, 4, 256, 64).astype(np.float32))
        got = _flash_forward_pallas(q, k, v, causal, 0.125)
        # the kernel computes in full f32; hold the jnp reference to the
        # same precision (TPU default would run its matmuls in bf16)
        with jax.default_matmul_precision("highest"):
            ref = _attention_reference(q, k, v, causal, 0.125)
        err = float(jnp.max(jnp.abs(got - ref)))
        assert err < 2e-3, "flash kernel mismatch on TPU (causal=%s): %g" \
            % (causal, err)


def main():
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    from incubator_mxnet_tpu.parallel import make_mesh, DataParallelTrainer

    _tpu_kernel_smoke()

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    mx.random.seed(0)
    net = vision.resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())

    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer="sgd", optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9},
        mesh=mesh, dtype=None if dtype in ("float32", "none") else dtype)

    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(batch, 3, 224, 224).astype(np.float32))
    y = mx.nd.array((rs.rand(batch) * 1000).astype(np.float32))

    # warmup (compile); sync before the timed region starts
    for _ in range(3):
        loss = trainer.step(x, y)
    float(np.asarray(loss))

    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = trainer.step(x, y)
    final = float(np.asarray(loss))  # host fetch = true sync point
    dt = time.perf_counter() - t0
    assert np.isfinite(final), "bench loss went non-finite"

    img_s = n_steps * batch / dt
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
