// C++ training surface over the MXTrainer* C ABI — the TPU rebuild's
// cpp-package (ref: cpp-package/include/mxnet-cpp/, which wraps the
// reference's C API the same way for C++ training without Python).
//
// Header-only RAII wrapper; link against src/build/libmxtpu_train.so.
//
//   mxtpu::Trainer t(symbol_json, {{"data", {64, 6}},
//                                  {"softmax_label", {64}}},
//                    "sgd", R"({"learning_rate": 0.5})");
//   t.SetInput("data", x.data(), x.size());
//   t.SetInput("softmax_label", y.data(), y.size());
//   float loss = t.Step();            // forward + backward + update
//   std::string params = t.SaveParams();
#pragma once

#include <cstdint>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

extern "C" {
int MXTrainerCreate(const char*, const char*, const char*, const void*, int,
                    uint32_t, const char**, const uint32_t*, const uint32_t*,
                    void**);
int MXTrainerSetInput(void*, const char*, const float*, uint32_t);
int MXTrainerStep(void*, float*);
int MXTrainerForward(void*);
int MXTrainerGetOutputShape(void*, uint32_t, uint32_t**, uint32_t*);
int MXTrainerGetOutput(void*, uint32_t, float*, uint32_t);
int MXTrainerSaveParams(void*, const char**, uint64_t*);
int MXTrainerFree(void*);
int MXDataIterCreate(const char*, const char*, void**);
int MXDataIterNext(void*, int*);
int MXDataIterReset(void*);
int MXDataIterGetData(void*, const float**, const uint32_t**, uint32_t*);
int MXDataIterGetLabel(void*, const float**, const uint32_t**, uint32_t*);
int MXDataIterFree(void*);
int MXMetricCreate(const char*, void**);
int MXMetricUpdate(void*, const float*, const uint32_t*, uint32_t,
                   const float*, const uint32_t*, uint32_t);
int MXMetricGet(void*, float*);
int MXMetricReset(void*);
int MXMetricFree(void*);
const char* MXTrainGetLastError();
}

namespace mxtpu {

class Trainer {
 public:
  Trainer(const std::string& symbol_json,
          const std::map<std::string, std::vector<uint32_t>>& input_shapes,
          const std::string& optimizer = "sgd",
          const std::string& optimizer_params_json = "",
          const std::string& param_bytes = "") {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0};
    std::vector<uint32_t> dims;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      for (uint32_t d : kv.second) dims.push_back(d);
      indptr.push_back(static_cast<uint32_t>(dims.size()));
    }
    if (MXTrainerCreate(symbol_json.c_str(), optimizer.c_str(),
                        optimizer_params_json.empty()
                            ? nullptr
                            : optimizer_params_json.c_str(),
                        param_bytes.empty() ? nullptr : param_bytes.data(),
                        static_cast<int>(param_bytes.size()),
                        static_cast<uint32_t>(keys.size()), keys.data(),
                        indptr.data(), dims.data(), &handle_) != 0) {
      throw std::runtime_error(MXTrainGetLastError());
    }
  }

  ~Trainer() {
    if (handle_) MXTrainerFree(handle_);
  }
  Trainer(const Trainer&) = delete;
  Trainer& operator=(const Trainer&) = delete;

  void SetInput(const std::string& key, const float* data, size_t size) {
    Check(MXTrainerSetInput(handle_, key.c_str(), data,
                            static_cast<uint32_t>(size)));
  }

  // One fused train step on the staged inputs; returns the batch loss.
  float Step() {
    float loss = 0.f;
    Check(MXTrainerStep(handle_, &loss));
    return loss;
  }

  void Forward() { Check(MXTrainerForward(handle_)); }

  std::vector<uint32_t> OutputShape(uint32_t index = 0) {
    uint32_t* data = nullptr;
    uint32_t ndim = 0;
    Check(MXTrainerGetOutputShape(handle_, index, &data, &ndim));
    return std::vector<uint32_t>(data, data + ndim);
  }

  std::vector<float> GetOutput(uint32_t index = 0) {
    auto shape = OutputShape(index);
    size_t n = 1;
    for (uint32_t d : shape) n *= d;
    std::vector<float> out(n);
    Check(MXTrainerGetOutput(handle_, index, out.data(),
                             static_cast<uint32_t>(n)));
    return out;
  }

  // MXNet-binary .params bytes of the current parameters (loadable by
  // Python nd.load / Module and by MXPredCreate).
  std::string SaveParams() {
    const char* bytes = nullptr;
    uint64_t size = 0;
    Check(MXTrainerSaveParams(handle_, &bytes, &size));
    return std::string(bytes, static_cast<size_t>(size));
  }

 private:
  static void Check(int rc) {
    if (rc != 0) throw std::runtime_error(MXTrainGetLastError());
  }
  void* handle_ = nullptr;
};

// One batch as returned by DataIter::GetData/GetLabel — values are a
// COPY (the ABI's shared buffer is only valid until the next fetch).
struct Batch {
  std::vector<float> values;
  std::vector<uint32_t> shape;
  size_t size() const { return values.size(); }
};

// Data iterator by registered name + JSON kwargs (the reference's
// MXDataIterCreateIter family): ImageRecordIter / CSVIter / MNISTIter /
// LibSVMIter.
class DataIter {
 public:
  DataIter(const std::string& name, const std::string& params_json) {
    if (MXDataIterCreate(name.c_str(), params_json.c_str(), &handle_) != 0) {
      throw std::runtime_error(MXTrainGetLastError());
    }
  }
  ~DataIter() {
    if (handle_) MXDataIterFree(handle_);
  }
  DataIter(const DataIter&) = delete;
  DataIter& operator=(const DataIter&) = delete;

  bool Next() {
    int has = 0;
    Check(MXDataIterNext(handle_, &has));
    return has != 0;
  }
  void Reset() { Check(MXDataIterReset(handle_)); }

  Batch GetData() { return Fetch(&MXDataIterGetData); }
  Batch GetLabel() { return Fetch(&MXDataIterGetLabel); }

 private:
  using FetchFn = int (*)(void*, const float**, const uint32_t**, uint32_t*);
  Batch Fetch(FetchFn fn) {
    const float* data = nullptr;
    const uint32_t* shape = nullptr;
    uint32_t ndim = 0;
    Check(fn(handle_, &data, &shape, &ndim));
    Batch b;
    b.shape.assign(shape, shape + ndim);
    size_t n = 1;
    for (uint32_t d : b.shape) n *= d;
    b.values.assign(data, data + n);
    return b;
  }
  static void Check(int rc) {
    if (rc != 0) throw std::runtime_error(MXTrainGetLastError());
  }
  void* handle_ = nullptr;
};

// Eval metric by registry name ("accuracy", "top_k_accuracy", "mse", ...).
class Metric {
 public:
  explicit Metric(const std::string& name) {
    if (MXMetricCreate(name.c_str(), &handle_) != 0) {
      throw std::runtime_error(MXTrainGetLastError());
    }
  }
  ~Metric() {
    if (handle_) MXMetricFree(handle_);
  }
  Metric(const Metric&) = delete;
  Metric& operator=(const Metric&) = delete;

  void Update(const Batch& label, const Batch& pred) {
    Check(MXMetricUpdate(handle_, label.values.data(), label.shape.data(),
                         static_cast<uint32_t>(label.shape.size()),
                         pred.values.data(), pred.shape.data(),
                         static_cast<uint32_t>(pred.shape.size())));
  }
  float Get() {
    float v = 0.f;
    Check(MXMetricGet(handle_, &v));
    return v;
  }
  void Reset() { Check(MXMetricReset(handle_)); }

 private:
  static void Check(int rc) {
    if (rc != 0) throw std::runtime_error(MXTrainGetLastError());
  }
  void* handle_ = nullptr;
};

}  // namespace mxtpu
