// Train an MLP from C++ — the reference's cpp-package/example/mlp.cpp
// role on the TPU rebuild.  Builds against the header-only wrapper and
// libmxtpu_train.so; the symbol JSON can come from any saved
// model ( Symbol.tojson() ) — here it is inlined for a self-contained
// example.
//
//   make -C src && g++ -std=c++17 -Icpp-package/include \
//       cpp-package/example/train_mlp.cc -Lsrc/build -lmxtpu_train \
//       -o /tmp/train_mlp && LD_LIBRARY_PATH=src/build /tmp/train_mlp
#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

#include "mxnet_tpu/trainer.hpp"

namespace {

// fc(16) -> relu -> fc(2) -> softmax, the canonical two-layer classifier
const char* kSymbolJson = R"json({
  "nodes": [
    {"op": "null", "name": "data", "inputs": []},
    {"op": "null", "name": "fc1_weight", "inputs": []},
    {"op": "null", "name": "fc1_bias", "inputs": []},
    {"op": "FullyConnected", "name": "fc1",
     "attrs": {"num_hidden": "16"}, "inputs": [[0,0,0],[1,0,0],[2,0,0]]},
    {"op": "Activation", "name": "relu1",
     "attrs": {"act_type": "relu"}, "inputs": [[3,0,0]]},
    {"op": "null", "name": "fc2_weight", "inputs": []},
    {"op": "null", "name": "fc2_bias", "inputs": []},
    {"op": "FullyConnected", "name": "fc2",
     "attrs": {"num_hidden": "2"}, "inputs": [[4,0,0],[5,0,0],[6,0,0]]},
    {"op": "null", "name": "softmax_label", "inputs": []},
    {"op": "SoftmaxOutput", "name": "softmax",
     "attrs": {"normalization": "batch"}, "inputs": [[7,0,0],[8,0,0]]}
  ],
  "arg_nodes": [0, 1, 2, 5, 6, 8],
  "heads": [[9, 0, 0]]
})json";

}  // namespace

int main() {
  const uint32_t batch = 64, dim = 6;
  std::mt19937 gen(0);
  std::normal_distribution<float> dist(0.f, 1.f);
  std::vector<float> x(batch * dim), w_true(dim), y(batch);
  for (auto& v : w_true) v = dist(gen);
  for (auto& v : x) v = dist(gen);
  for (uint32_t i = 0; i < batch; ++i) {
    float s = 0.f;
    for (uint32_t j = 0; j < dim; ++j) s += x[i * dim + j] * w_true[j];
    y[i] = s > 0.f ? 1.f : 0.f;
  }

  mxtpu::Trainer trainer(kSymbolJson,
                         {{"data", {batch, dim}}, {"softmax_label", {batch}}},
                         "sgd", R"({"learning_rate": 1.0})");
  trainer.SetInput("data", x.data(), x.size());
  trainer.SetInput("softmax_label", y.data(), y.size());

  float first = 0.f, last = 0.f;
  for (int step = 0; step < 400; ++step) {
    last = trainer.Step();
    if (step == 0) first = last;
    if (step % 100 == 0) std::printf("step %3d  loss %.4f\n", step, last);
  }
  std::printf("loss %.4f -> %.4f\n", first, last);

  trainer.Forward();
  auto probs = trainer.GetOutput();
  uint32_t correct = 0;
  for (uint32_t i = 0; i < batch; ++i) {
    correct += (probs[i * 2 + 1] > probs[i * 2]) == (y[i] > 0.5f);
  }
  std::printf("train accuracy %.3f\n", double(correct) / batch);
  std::string params = trainer.SaveParams();
  std::printf("params blob: %zu bytes\n", params.size());
  return (last < first && correct > batch * 9 / 10) ? 0 : 1;
}
