// Train an MLP from C++ — the reference's cpp-package/example/mlp.cpp
// role on the TPU rebuild, now with the FULL loop: write a RecordIO
// dataset, feed it back through a registered data iterator
// (ImageRecordIter, raw-decode), train with the fused Step, and score
// with a registry eval metric — all through the C ABI, no Python at the
// call site.
//
//   make -C src && g++ -std=c++17 -Icpp-package/include \
//       cpp-package/example/train_mlp.cc -Lsrc/build -lmxtpu_train \
//       -lmxtpu_io -o /tmp/train_mlp && \
//       LD_LIBRARY_PATH=src/build /tmp/train_mlp
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#include "mxnet_tpu/trainer.hpp"

extern "C" {
void* MXTPURecordIOWriterCreate(const char* path);
int MXTPURecordIOWriterWrite(void* handle, const char* data, uint64_t size);
void MXTPURecordIOWriterFree(void* handle);
}

namespace {

// fc(16) -> relu -> fc(2) -> softmax, the canonical two-layer classifier
const char* kSymbolJson = R"json({
  "nodes": [
    {"op": "null", "name": "data", "inputs": []},
    {"op": "null", "name": "fc1_weight", "inputs": []},
    {"op": "null", "name": "fc1_bias", "inputs": []},
    {"op": "FullyConnected", "name": "fc1",
     "attrs": {"num_hidden": "16"}, "inputs": [[0,0,0],[1,0,0],[2,0,0]]},
    {"op": "Activation", "name": "relu1",
     "attrs": {"act_type": "relu"}, "inputs": [[3,0,0]]},
    {"op": "null", "name": "fc2_weight", "inputs": []},
    {"op": "null", "name": "fc2_bias", "inputs": []},
    {"op": "FullyConnected", "name": "fc2",
     "attrs": {"num_hidden": "2"}, "inputs": [[4,0,0],[5,0,0],[6,0,0]]},
    {"op": "null", "name": "softmax_label", "inputs": []},
    {"op": "SoftmaxOutput", "name": "softmax",
     "attrs": {"normalization": "batch"}, "inputs": [[7,0,0],[8,0,0]]}
  ],
  "arg_nodes": [0, 1, 2, 5, 6, 8],
  "heads": [[9, 0, 0]]
})json";

// recordio.py IRHeader: struct {u32 flag; f32 label; u64 id; u64 id2}
// followed by the payload — flag 0 means the label rides in the header.
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};
static_assert(sizeof(IRHeader) == 24, "IRHeader must pack to 24 bytes");

// Write n raw-uint8 1x8x8 "images"; label = 1 when the mean pixel is
// bright.  Returns the per-record labels for the final exit check.
std::vector<float> write_dataset(const std::string& path, uint32_t n) {
  std::mt19937 gen(0);
  std::uniform_int_distribution<int> pix(0, 3);
  void* w = MXTPURecordIOWriterCreate(path.c_str());
  if (!w) throw std::runtime_error("cannot open " + path);
  std::vector<float> labels;
  std::string rec;
  for (uint32_t i = 0; i < n; ++i) {
    uint8_t img[64];
    int sum = 0;
    for (auto& p : img) {
      p = static_cast<uint8_t>(pix(gen));
      sum += p;
    }
    IRHeader h{0, sum > 96 ? 1.f : 0.f, i, 0};
    labels.push_back(h.label);
    rec.assign(reinterpret_cast<const char*>(&h), sizeof(h));
    rec.append(reinterpret_cast<const char*>(img), sizeof(img));
    if (MXTPURecordIOWriterWrite(w, rec.data(), rec.size()) != 0) {
      throw std::runtime_error("record write failed");
    }
  }
  MXTPURecordIOWriterFree(w);
  return labels;
}

}  // namespace

int main() {
  const uint32_t n = 256, batch = 32;
  const std::string rec_path =
      "/tmp/mxtpu_train_mlp." + std::to_string(getpid()) + ".rec";
  write_dataset(rec_path, n);

  // the registered raw-decode RecordIO pipeline (reader -> parser pool
  // -> prefetcher), driven through MXDataIterCreate by name
  mxtpu::DataIter iter(
      "ImageRecordIter",
      R"({"path_imgrec": ")" + rec_path + R"(", "data_shape": [1, 8, 8],
          "batch_size": 32, "label_width": 1, "decode": "raw",
          "preprocess_threads": 2, "prefetch_buffer": 2})");

  mxtpu::Trainer trainer(kSymbolJson,
                         {{"data", {batch, 1, 8, 8}},
                          {"softmax_label", {batch}}},
                         "sgd", R"({"learning_rate": 0.5, "momentum": 0.9})");

  float loss = 0.f;
  for (int epoch = 0; epoch < 60; ++epoch) {
    iter.Reset();
    while (iter.Next()) {
      auto data = iter.GetData();
      auto label = iter.GetLabel();
      trainer.SetInput("data", data.values.data(), data.size());
      trainer.SetInput("softmax_label", label.values.data(), label.size());
      loss = trainer.Step();
    }
    if (epoch % 20 == 0) std::printf("epoch %2d  loss %.4f\n", epoch, loss);
  }

  // evaluation epoch: forward only, scored by the registry metric
  mxtpu::Metric acc("accuracy");
  iter.Reset();
  while (iter.Next()) {
    auto data = iter.GetData();
    auto label = iter.GetLabel();
    trainer.SetInput("data", data.values.data(), data.size());
    trainer.Forward();
    auto probs = trainer.GetOutput();
    mxtpu::Batch pred{std::move(probs), trainer.OutputShape()};
    acc.Update(label, pred);
  }
  float accuracy = acc.Get();
  std::printf("eval accuracy %.3f\n", accuracy);

  std::string params = trainer.SaveParams();
  std::printf("params blob: %zu bytes\n", params.size());
  return accuracy > 0.9f ? 0 : 1;
}
