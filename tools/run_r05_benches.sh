#!/bin/bash
# Round-5 TPU measurement set.  Run from the repo root with the axon
# tunnel live; each stage writes JSON lines under docs/bench_results_r05/.
# Stages are independent — a tunnel drop only loses the stage in flight.
# Ordered by evidence value: artifacts that have never been measured
# with the honest (DUS-chain) harness come first.
set -x
OUT=docs/bench_results_r05
mkdir -p "$OUT"

# 1. INT8 op ratios at reference shapes (serial DUS chain) — round-4
#    verdict task 7, no prior honest measurement exists
python benchmark/python/quantization/benchmark_op.py --serial-sweep \
    --chain 256 > "$OUT/int8_serial_shapes.jsonl" 2> /tmp/r05_serial.err

# 2. sparse updater with and without bulk — verdict task 4's Done bar
python benchmark/python/sparse/updater.py \
    > "$OUT/updater_eager.jsonl" 2> /tmp/r05_upd1.err
python benchmark/python/sparse/updater.py --bulk 16 \
    > "$OUT/updater_bulk.jsonl" 2> /tmp/r05_upd2.err

# 3. quantized resnet-50 end-to-end (DUS harness refresh)
python example/quantization/imagenet_inference.py --chain 50 \
    --calib-mode naive > "$OUT/quantized_resnet50.jsonl" 2> /tmp/r05_quant.err

# 4. chip-true inference sweep refresh (two-point DUS harness)
python example/image-classification/benchmark_score.py --mode steady \
    --chain 100 > "$OUT/inference_steady_dus.jsonl" 2> /tmp/r05_sweep.err

# 5. transformer MFU with the corrected (non-embedding) accounting
python bench_transformer.py > "$OUT/transformer_mfu.jsonl" \
    2> /tmp/r05_tf.err

# 6. eager micro-bench (bulk now also defers the optimizer updates)
python bench_eager.py > "$OUT/eager_bulk.jsonl" 2> /tmp/r05_eager.err
