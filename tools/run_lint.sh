#!/usr/bin/env bash
# lint tier of the verify recipe: the op-contract static analyzer must be
# clean (suppressed findings are allowed; unsuppressed ones fail the
# build).  Thin wrapper over the canonical entry point — graftlint itself
# pins jax to CPU and one pass produces both the human summary and the
# machine-readable JSON report (for bench/verdict diagnostic tracking).
#
# Usage: tools/run_lint.sh [report.json]
set -uo pipefail
cd "$(dirname "$0")/.."

REPORT="${1:-/tmp/graftlint_report.json}"
exec python -m incubator_mxnet_tpu.analysis.graftlint --all --report "$REPORT"
