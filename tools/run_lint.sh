#!/usr/bin/env bash
# lint tier of the verify recipe, two sub-tiers:
#
# 1. graftlint — the op-contract static analyzer must be clean
#    (suppressed findings are allowed; unsuppressed ones fail the build).
#    Thin wrapper over the canonical entry point — graftlint itself pins
#    jax to CPU and one pass produces both the human summary and the
#    machine-readable JSON report (for bench/verdict diagnostic tracking).
# 2. telemetry smoke — dump a chrome trace from a 3-op bulked program and
#    validate the schema + record→flush flow links (graftscope); a trace
#    regression exits non-zero just like a lint finding.
# 3. graftfuse + graftlap + graftduplex smoke — bench_eager.py --smoke
#    steps a many-small-param Trainer through the bucketed fused path
#    (asserting bit-parity with the per-param path), through the
#    overlapped reduce path (grad-ready hooks issuing bucket allreduces
#    mid-backward, asserting bit-parity with the serial bucketed path),
#    AND through the full-duplex update_on_kvstore step (reduces
#    overlapped + per-bucket async weight pulls waited at first touch,
#    duplex_step_* parity asserted), so a fused-step, overlap or duplex
#    regression fails this tier.
# 4. graftwatch smoke — telemetry --blackbox --selftest exercises the
#    flight recorder end-to-end (engine flushes, kvstore collectives, a
#    step journal, an in-flight bracket) and validates the dump schema.
# 5. graftlens smoke — telemetry --analyze --selftest merges two
#    synthetic rank dumps (rank 1 deliberately delayed) and requires a
#    schema-valid merged trace with cross-rank flow links per reduced
#    bucket plus a straggler table blaming rank 1; bench_eager --smoke
#    (tier 3) additionally reports lens_overhead_pct against its < 2%
#    budget (tracked in BENCH JSON, like blackbox_overhead_pct).
# 6. grafttsan smoke — analysis.tsan --selftest forces one race per
#    EH2xx rule through the real instrumented paths (handles, scheduler
#    regions, bulk segments, tracked arrays), requires the exact
#    diagnostic with both stacks, and requires a clean workload to stay
#    silent.  graftlint --all (tier 1) now also runs the GL2xx static
#    concurrency rules over the package sources; bench_eager --smoke
#    reports tsan_overhead_pct (detector default-off; informational).
# 7. graftserve smoke — serving --selftest drives threaded traffic
#    through the dynamic batcher (bit-parity vs the eager forward, SLO
#    conservation, atomic hot-swap, LRU residency), and
#    bench_serving.py --smoke emits the serving BENCH JSON (p50/p99 vs
#    offered QPS) asserting batched dispatch >= 3x the serial
#    Module.predict loop with bit-equal outputs.
# 8. graftarmor smoke — armor --selftest exercises the robustness layer
#    end-to-end: deterministic fault-grammar replay, PS wire self-healing
#    against a real ParameterServer (retry + idempotent server-side dedup
#    + typed give-up), atomic checkpoint round-trip with last-valid
#    resume after corruption, and watchdog hang escalation delivering a
#    typed error naming the dead rank; bench_eager --smoke (tier 3)
#    additionally reports armor_overhead_pct (retry plumbing with zero
#    faults armed) against its < 2% budget in BENCH JSON.
# 9. graftpulse smoke — telemetry.autotune --selftest runs the synthetic
#    starved-DataLoader scenario end-to-end: the lens-driven controller
#    must grow the loader's workers until the data_wait fraction drops
#    below the bound within a bounded number of steps, with every
#    decision journaled to the flight recorder; bench_eager --smoke
#    (tier 3) additionally reports pulse_overhead_pct (the async device
#    ledger's cost) against its < 2% budget in BENCH JSON.
# 10. graftstep smoke — gluon.step_compile --selftest drives the
#    whole-step compiled training path: one lazy trace on a static-shape
#    loop (zero retraces after step 2), a set_learning_rate that must
#    NOT retrace (lr rides as a traced operand), at most one guarded
#    retrace per shape change, and ULP-tolerance parity of params +
#    optimizer states against the bucketed-eager triple at every stage;
#    bench_eager --smoke (tier 3) additionally gates the
#    compiled_step_latency_ratio (compiled steady-state <= 0.8x the
#    bucketed-eager step on the 64-param dist_sync bench) in BENCH JSON.
# 11. graftguard smoke — analysis.compile_safety --selftest forces every
#    GL30x fixture (plus its clean twin) through the compile-safety
#    linter and every EH30x diagnostic through the real CompiledStep
#    paths: an EH301 retrace storm that must name the churned guard-key
#    component, an EH302 donated-buffer read-after-dispatch raising with
#    both stacks, an EH303 constant-bake drift under an unchanged guard
#    key, and an EH304 compiled-vs-eager ULP sentinel; graftlint --all
#    (tier 1) also runs the GL3xx pass over the package sources and the
#    op registry; bench_eager --smoke (tier 3) additionally reports
#    compile_check_overhead_pct (auditor armed, zero findings) against
#    its < 2% budget in BENCH JSON.
# 12. graftxray smoke — telemetry.xray --selftest captures a triggered
#    3-dispatch profiler session around the REAL compiled step and
#    asserts in-program phase attribution (forward/backward/update[k]
#    scopes resolved from the executable's optimized HLO against the
#    trace's hlo_op stream) with EXACT-sum conservation (phase device
#    ns + unattributed == program device span, integer equality), cost
#    summaries registered at trace time, and armed-but-idle dispatches
#    opening no session; bench_eager --smoke (tier 3) additionally
#    gates xray_overhead_pct (harness armed, no capture) against its
#    < 2% budget in BENCH JSON.
# 13. graftzero smoke — parallel.quant --selftest proves the block-scaled
#    quantization kernels (int8/2bit encode/decode round-trips, the
#    documented per-element error bounds, packed-field summability,
#    wire-byte accounting, shard ownership maps, error-feedback
#    convergence in exact arithmetic); bench_eager --smoke (tier 3)
#    additionally gates the int8 wire-bytes ratio (>= 3.5x below f32),
#    the GRAFT_QUANT_REDUCE=0 escape hatch (bit-identical, < 2%
#    overhead) and the ZeRO-1 shard parity + ~1/N state-bytes claim via
#    an 8-device child run.
# 14. graftelastic smoke — elastic --selftest runs kill → re-partition →
#    rejoin → byte-parity in one subprocess: the membership algebra and
#    re-partition plans are pure/deterministic, a simulated 3-rank
#    cluster that loses and regains a rank reproduces the unfaulted loss
#    trajectory byte-for-byte with lockstep digests agreeing across two
#    membership epochs, a chunked armor snapshot round-trips through a
#    REAL ParameterServer wire (torn stream -> typed corruption error),
#    seeded membership.join/repartition chaos replays deterministically
#    (drop -> the rank keeps the old epoch; stuck quiesce -> typed
#    QuiesceTimeoutError), ZeRO shard state re-partitions across changed
#    world sizes both directions (refusing with ShardOwnershipError when
#    GRAFT_ELASTIC is off), and GRAFT_ELASTIC=0 leaves the step fence
#    untaken; bench_eager --smoke (tier 3) additionally gates
#    elastic_overhead_pct (enabled-idle fence) against its < 2% budget
#    in BENCH JSON.
#
# Usage: tools/run_lint.sh [report.json]
set -uo pipefail
cd "$(dirname "$0")/.."

REPORT="${1:-/tmp/graftlint_report.json}"
python -m incubator_mxnet_tpu.analysis.graftlint --all --report "$REPORT" \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.analysis.tsan --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench_eager.py --smoke \
    || exit $?
python -m incubator_mxnet_tpu.telemetry --blackbox --selftest \
    || exit $?
python -m incubator_mxnet_tpu.telemetry --analyze --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.serving --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench_serving.py --smoke \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.armor --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.telemetry.autotune --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.gluon.step_compile --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.analysis.compile_safety --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.telemetry.xray --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.parallel.quant --selftest \
    || exit $?
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m incubator_mxnet_tpu.elastic --selftest \
    || exit $?
exec python -m incubator_mxnet_tpu.telemetry --selftest
