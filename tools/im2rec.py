#!/usr/bin/env python
"""im2rec: pack an image directory into a RecordIO file.

TPU-native rebirth of the reference's tools/im2rec.py (and the C++
tools/im2rec.cc): makes .lst index files from a directory tree and packs
the listed images (optionally resized/re-encoded) into .rec/.idx pairs
that ImageRecordIter / ImageRecordDataset consume.

Usage (same two-phase flow as the reference):
    python tools/im2rec.py prefix image_root --list --recursive
    python tools/im2rec.py prefix image_root --resize 256 --quality 95
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from incubator_mxnet_tpu import recordio  # noqa: E402

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive):
    """Yield (relpath, label) with labels assigned per sorted subdirectory
    (ref: im2rec.py list_image)."""
    if recursive:
        cat = {}
        for path, dirs, files in sorted(os.walk(root, followlinks=True)):
            dirs.sort()
            for fname in sorted(files):
                if os.path.splitext(fname)[1].lower() not in _EXTS:
                    continue
                label_dir = os.path.relpath(path, root).split(os.sep)[0]
                if label_dir not in cat:
                    cat[label_dir] = len(cat)
                yield (os.path.relpath(os.path.join(path, fname), root),
                       cat[label_dir])
    else:
        k = 0
        for fname in sorted(os.listdir(root)):
            if os.path.splitext(fname)[1].lower() in _EXTS:
                yield fname, 0
                k += 1


def write_list(path_out, image_list):
    """.lst format: index \\t label \\t relpath (ref: im2rec.py write_list)."""
    with open(path_out, "w") as f:
        for i, (path, label) in enumerate(image_list):
            f.write("%d\t%f\t%s\n" % (i, float(label), path))


def read_list(path_in):
    with open(path_in) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def make_record(prefix, root, args):
    """Pack every .lst entry into prefix.rec/prefix.idx
    (ref: im2rec.py image_encode + write_worker)."""
    try:
        import cv2
    except ImportError:
        raise SystemExit("im2rec packing requires opencv-python (cv2)")
    lst = prefix + ".lst"
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    for idx, labels, relpath in read_list(lst):
        fullpath = os.path.join(root, relpath)
        header = recordio.IRHeader(0, labels[0] if len(labels) == 1
                                   else labels, idx, 0)
        if args.pass_through:
            with open(fullpath, "rb") as f:
                s = recordio.pack(header, f.read())
        else:
            img = cv2.imread(fullpath, cv2.IMREAD_COLOR)
            if img is None:
                print("imread failed, skipping %s" % fullpath)
                continue
            if args.resize:
                h, w = img.shape[:2]
                scale = args.resize / min(h, w)
                img = cv2.resize(img, (int(w * scale + 0.5),
                                       int(h * scale + 0.5)))
            if args.center_crop:
                h, w = img.shape[:2]
                m = min(h, w)
                y0, x0 = (h - m) // 2, (w - m) // 2
                img = img[y0:y0 + m, x0:x0 + m]
            if args.pack_raw:
                # raw uint8 HWC RGB tensor — ImageIter decode='raw' skips
                # JPEG entirely (the host-decode-free TPU feeding path)
                s = recordio.pack(header, cv2.cvtColor(
                    img, cv2.COLOR_BGR2RGB).tobytes())
            else:
                s = recordio.pack_img(header, img, quality=args.quality,
                                      img_fmt=args.encoding)
        rec.write_idx(idx, s)
        n += 1
        if n % 1000 == 0:
            print("packed %d images" % n)
    rec.close()
    print("wrote %d records to %s.rec" % (n, prefix))


def main():
    ap = argparse.ArgumentParser(
        description="Create image lists and RecordIO packs "
                    "(ref: tools/im2rec.py)")
    ap.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="create the .lst index instead of packing")
    ap.add_argument("--recursive", action="store_true",
                    help="label images by first-level subdirectory")
    ap.add_argument("--shuffle", type=int, default=1)
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--test-ratio", type=float, default=0.0)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter edge to this many pixels")
    ap.add_argument("--center-crop", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    ap.add_argument("--pass-through", action="store_true",
                    help="pack raw files without re-encoding")
    ap.add_argument("--pack-raw", action="store_true",
                    help="pack decoded uint8 HWC tensors (no image "
                         "encoding) for ImageIter's decode='raw' fast path")
    args = ap.parse_args()

    if args.list:
        images = list(list_images(args.root, args.recursive))
        if args.shuffle:
            random.seed(100)    # fixed seed like the reference
            random.shuffle(images)
        n_train = int(len(images) * args.train_ratio)
        n_test = int(len(images) * args.test_ratio)
        if args.train_ratio < 1.0 or args.test_ratio > 0.0:
            write_list(args.prefix + "_train.lst", images[:n_train])
            if n_test:
                write_list(args.prefix + "_test.lst",
                           images[n_train:n_train + n_test])
            rest = images[n_train + n_test:]
            if rest:
                write_list(args.prefix + "_val.lst", rest)
        else:
            write_list(args.prefix + ".lst", images)
        print("listed %d images" % len(images))
    else:
        make_record(args.prefix, args.root, args)


if __name__ == "__main__":
    main()
