"""Create a .idx index for an existing .rec file, enabling random access
(ref: tools/rec2idx.py — the reference walks the RecordIO stream with
tell() before each read and writes ``key\\toffset`` lines).

Usage:
    python tools/rec2idx.py data/test.rec data/test.idx
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..")))

from incubator_mxnet_tpu import recordio  # noqa: E402


def create_index(rec_path, idx_path, key_type=int):
    """Walk the stream; record each record's byte offset under running
    integer keys (the im2rec convention)."""
    reader = recordio.MXRecordIO(rec_path, "r")
    n = 0
    with open(idx_path, "w") as fidx:
        while True:
            pos = reader.tell()
            item = reader.read()
            if item is None:
                break
            fidx.write("%s\t%d\n" % (key_type(n), pos))
            n += 1
    reader.close()
    return n


def main():
    p = argparse.ArgumentParser(
        description="Create an index file for a RecordIO file")
    p.add_argument("record", help="path to the .rec file")
    p.add_argument("index", help="path for the .idx file to create")
    args = p.parse_args()
    n = create_index(args.record, args.index)
    print("wrote %d entries to %s" % (n, args.index))


if __name__ == "__main__":
    main()
