#!/usr/bin/env python
"""launch: start a multi-process distributed training job on one machine
(or print the per-host commands for a cluster).

TPU-native rebirth of the reference's tools/launch.py (dmlc-core tracker:
local/ssh/mpi launchers setting DMLC_ROLE/DMLC_PS_ROOT_URI for ps-lite).
Here there are no parameter-server roles: every process is a worker and
they rendezvous through the jax coordination service, so launching means
spawning N copies of the command with MX_COORDINATOR / MX_NUM_PROCESSES /
MX_PROCESS_ID set (consumed by parallel/dist.py init_process).

    python tools/launch.py -n 4 python train.py --kv-store dist_sync
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def main():
    ap = argparse.ArgumentParser(
        description="Launch a distributed job (ref: tools/launch.py)")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-H", "--host", default="127.0.0.1",
                    help="coordinator host (process 0's address)")
    ap.add_argument("-p", "--port", type=int, default=9355,
                    help="coordinator port")
    ap.add_argument("--launcher", choices=["local", "print"], default="local",
                    help="'local': fork N processes here; 'print': emit the "
                         "command to run on each host of a cluster")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="training command to launch")
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")

    coordinator = "%s:%d" % (args.host, args.port)
    if args.launcher == "print":
        for r in range(args.num_workers):
            env = ("MX_COORDINATOR=%s MX_NUM_PROCESSES=%d MX_PROCESS_ID=%d"
                   % (coordinator, args.num_workers, r))
            print("[host %d] %s %s" % (r, env, " ".join(args.command)))
        return 0

    procs = []
    try:
        for r in range(args.num_workers):
            env = dict(os.environ)
            env.update({"MX_COORDINATOR": coordinator,
                        "MX_NUM_PROCESSES": str(args.num_workers),
                        "MX_PROCESS_ID": str(r),
                        # each local process simulates one host: restrict it
                        # to the CPU platform unless the caller overrides
                        "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu")})
            procs.append(subprocess.Popen(args.command, env=env))
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        return 1


if __name__ == "__main__":
    sys.exit(main())
