"""KVStore push/pull bandwidth measurement — TPU counterpart of the
reference's tool (ref: tools/bandwidth/measure.py:1-40).

Pushes ResNet-152-sized gradient buffers (or a custom size list) through
a kvstore and reports effective GB/s per push+pull round, for
local / device / dist_sync (dense and 2-bit compressed) / dist_async.

Single process measures the local store; run under ``tools/launch.py -n
N`` for the dist types — every worker pushes, rank 0 prints.  The timed
region ends on a host fetch of the pulled value (through the axon tunnel
``wait_to_read`` alone does not synchronize).

Usage:
    python tools/bandwidth/measure.py --kv-store local
    python tools/launch.py -n 2 python tools/bandwidth/measure.py \
        --kv-store dist_sync [--gc-type 2bit]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))


def default_sizes():
    """ResNet-152-ish parameter sizing: a few big conv/fc buffers plus a
    tail of small ones (the shape mix that stresses batching)."""
    sizes = [2048 * 1000, 2048 * 512 * 9, 1024 * 256 * 9, 512 * 128 * 9]
    sizes += [256 * 64 * 9] * 8 + [65536] * 16 + [4096] * 32
    return sizes


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--kv-store", default="local")
    p.add_argument("--num-batches", type=int, default=10)
    p.add_argument("--gc-type", default="none",
                   help="'2bit' enables the compressed wire")
    p.add_argument("--optimizer", default="none",
                   help="server-side optimizer name or 'none'")
    p.add_argument("--platform", default=None,
                   help="'cpu' forces the CPU backend (multi-process CPU "
                        "runs: every worker must pick it BEFORE jax init)")
    p.add_argument("--report", default="time", choices=["time", "bytes"],
                   help="'bytes': report wire bytes shipped per round "
                        "instead of loopback time.  On loopback transports "
                        "encode/decode compute swamps free local bytes, so "
                        "time CANNOT see the compression win "
                        "(docs/bench_results_r04/README.md:97); bytes mode "
                        "measures what the wire actually ships — the "
                        "quantity the compressed wire optimizes.  The "
                        "per-value byte model is wire_bytes_per_worker, "
                        "whose lowering (u32 all-to-all + s8 all-gather) "
                        "is pinned by an HLO assertion in "
                        "tests/test_compression.py")
    p.add_argument("--num-workers", type=int, default=0,
                   help="bytes mode: model W workers without launching "
                        "them (default: the live kv.num_workers)")
    args = p.parse_args()

    if args.platform == "cpu":
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=2")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd

    if args.report == "bytes":
        from incubator_mxnet_tpu.parallel.compression import (
            wire_bytes_per_worker)
        sizes = default_sizes()
        W = args.num_workers
        if W <= 0:
            kv = mx.kv.create(args.kv_store)
            W = kv.num_workers
        W = max(W, 2)      # a 1-worker "wire" ships nothing; model the
        #                    smallest real topology and report that W
        comp = dense = 0
        for n in sizes:
            c, d = wire_bytes_per_worker(n, W)
            comp += c
            dense += d
        shipped = comp if args.gc_type != "none" else dense
        print(json.dumps({
            "metric": "kvstore_wire_bytes_per_round",
            "kv_store": args.kv_store, "gc_type": args.gc_type,
            "num_workers": W,
            "payload_mb": round(4 * sum(sizes) / 1e6, 1),
            "value": shipped, "unit": "bytes/worker/round",
            "dense_bytes": dense, "compressed_bytes": comp,
            "compression_ratio": round(dense / comp, 2),
        }, ), flush=True)
        return

    kv = mx.kv.create(args.kv_store)
    if args.gc_type != "none":
        kv.set_gradient_compression({"type": args.gc_type, "threshold": 0.5})
    if args.optimizer != "none":
        import incubator_mxnet_tpu.optimizer as opt
        kv.set_optimizer(opt.create(args.optimizer, learning_rate=0.01))

    rs = np.random.RandomState(0)
    sizes = default_sizes()
    keys = list(range(len(sizes)))
    vals = [nd.array(rs.uniform(-1, 1, (s,)).astype(np.float32))
            for s in sizes]
    outs = [nd.zeros((s,)) for s in sizes]
    kv.init(keys, [nd.zeros((s,)) for s in sizes])

    # warm-up round (compiles the reduce programs)
    kv.push(keys, vals)
    kv.pull(keys, out=outs)
    float(outs[0].asnumpy()[0])

    total_bytes = 4 * sum(sizes)
    t0 = time.perf_counter()
    for _ in range(args.num_batches):
        kv.push(keys, vals)
        kv.pull(keys, out=outs)
    float(outs[0].asnumpy()[0])        # host fetch = true sync
    dt = time.perf_counter() - t0

    gbs = args.num_batches * total_bytes / dt / 1e9
    if kv.rank == 0:
        print(json.dumps({
            "metric": "kvstore_push_pull_bandwidth",
            "kv_store": args.kv_store, "gc_type": args.gc_type,
            "num_workers": kv.num_workers,
            "payload_mb": round(total_bytes / 1e6, 1),
            "rounds": args.num_batches,
            "value": round(gbs, 3), "unit": "GB/s",
            "ms_per_round": round(dt / args.num_batches * 1e3, 2),
        }), flush=True)


if __name__ == "__main__":
    main()
