"""Parse a training log into a markdown table
(ref: tools/parse_log.py — same Epoch[N] Train-/Validation-/Time regex
family over the Speedometer/fit log format this framework emits).

Usage:
    python tools/parse_log.py train.log
    python tools/parse_log.py train.log --metric-names accuracy top_k_accuracy
"""
import argparse
import re


def parse(lines, metric_names=("accuracy",)):
    """{epoch: {column: value}} from fit/Speedometer log lines."""
    num = r"=([-+]?[.\d]+(?:[eE][-+]?\d+)?)"
    pats = []
    for s in metric_names:
        pats.append(("train-" + s,
                     re.compile(r".*Epoch\[(\d+)\] Train-" + re.escape(s)
                                + r".*" + num)))
        pats.append(("val-" + s,
                     re.compile(r".*Epoch\[(\d+)\] Validation-" + re.escape(s)
                                + r".*" + num)))
    pats.append(("time", re.compile(r".*Epoch\[(\d+)\] Time.*" + num)))
    data = {}
    for line in lines:
        for col, pat in pats:
            m = pat.match(line)
            if m:
                epoch, val = int(m.group(1)), float(m.group(2))
                data.setdefault(epoch, {})[col] = val
                break
    return data, [c for c, _ in pats]


def to_markdown(data, cols):
    out = ["| epoch | " + " | ".join(cols) + " |",
           "| --- |" + " --- |" * len(cols)]
    for epoch in sorted(data):
        row = data[epoch]
        out.append("| %d | " % epoch
                   + " | ".join("%.6g" % row[c] if c in row else ""
                                for c in cols) + " |")
    return "\n".join(out)


def main():
    p = argparse.ArgumentParser(description="Parse training output log")
    p.add_argument("logfile", type=str)
    p.add_argument("--format", default="markdown",
                   choices=["markdown", "none"])
    p.add_argument("--metric-names", nargs="+", default=["accuracy"])
    args = p.parse_args()
    with open(args.logfile) as f:
        data, cols = parse(f.readlines(), args.metric_names)
    if args.format == "markdown":
        print(to_markdown(data, cols))


if __name__ == "__main__":
    main()
