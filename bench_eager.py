"""Eager-dispatch micro-benchmark (SURVEY hard-part #2).

The reference engineered engine op-bulking because per-op push overhead
dominated small-op imperative workloads (threaded_engine.h:472-509
BulkAppend / MXNET_EXEC_BULK_EXEC_*).  This framework's answer is layered:

1. per-op micro-jit cache (ops/registry.py bind) — steady-state eager
   dispatch is a dict hit + one XLA async dispatch,
2. CachedOp / hybridize — a whole Block traces into ONE XLA program
   (the segment-level bulking the reference built by hand),
3. DataParallelTrainer.step_multi — K whole train steps scanned into one
   launch.

plus the transparent ``mx.engine.bulk`` scope (engine.py) — the direct
BulkAppend analogue: unmodified eager code inside the scope is deferred
and replayed as one cached XLA program.

This script quantifies all three on the current backend: a chain of
small elementwise ops (the reference's worst case) run eagerly op-by-op,
the same loop inside ``engine.bulk``, and the chain as one hybridized
CachedOp.  Prints ONE JSON line with ops/sec for each.

Round 4 adds the graftfuse step-latency section: a 64-small-param model
stepped through ``gluon.Trainer`` on the per-param path (one optimizer
kernel per parameter) vs the bucketed fused path (one multi-tensor
dispatch per bucket) — the ratio lands in the BENCH JSON as
``fused_step_speedup`` and the two paths are asserted bit-identical.
``--smoke`` runs ONLY a fast version of that section plus the graftlap
overlap section (small iteration counts) so the lint tier exercises the
bucketed and overlapped paths end-to-end.

Round 7 (graftlap) adds ``overlap_step_*``: the same 64-param model
trained with a REAL backward pass through a dist_sync store, stepping
with bucket reduces issued serially inside ``step()`` (the PR 4 path)
vs issued mid-backward by the grad-ready hooks — only the ``step()``
call is timed (the backward is identical either way), the two runs are
asserted bit-identical, and the measured overlap ratio
(``graft_trainer_overlap_ratio``) is reported.

Round 8 (graftlens) adds ``lens_overhead_pct``: a real train loop
(record scope, backward, kvstore collectives, step journal) timed with
the per-step attribution engine on vs off — same < 2% bar as the flight
recorder.

Round 10 (grafttsan) adds ``tsan_overhead_pct``: the same real train
loop (handles issued/waited, scheduler regions, NDArray writes — every
instrumented site firing) with the happens-before race detector on vs
off.  The detector is DEFAULT-OFF, so the number is informational; the
enabled-mode design bar is < 10%.

Round 12 (graftpulse) adds ``pulse_overhead_pct``: a bulked ASYNC train
loop (no sync mode — flush-boundary reaper enqueues and mem-timeline
probes firing) with the async device-time ledger on vs off, each round
draining the reaper inside its own window.  Same < 2% bar as the lens.

Round 19 (graftzero) adds ``quant_step_*`` / ``zero_step_*``: the same
64-param dist_sync loop with the block-scaled quantized bucket wire
(``GRAFT_QUANT_REDUCE=int8`` — wire bytes off the kvstore counters,
gated >= 3.5x below f32; the ``=0`` escape hatch asserted bit-identical
at < 2% overhead) and, via an 8-device child process, the ZeRO-1
sharded update (``GRAFT_SHARD_OPTIMIZER=1`` — byte-parity with the
unsharded ctx-0 replica, per-shard optimizer-state bytes ~1/N).

Round 17 (graftguard) adds ``compile_check_overhead_pct``: the compiled
whole-step path (graftstep) timed with the EH3xx runtime auditor armed
(guard-key bookkeeping, bake-hash recheck, donated-buffer poisoning and
sweep — but NO sentinel replay) vs off.  Same < 2% bar; the off mode
additionally asserts the hot-path flag is a cached list-index load.

Round 18 (graftxray) adds ``xray_overhead_pct``: the same compiled step
with the capture harness ARMED (GRAFT_XRAY=1 — dispatch_begin/end
bracketing every dispatch) but no trigger firing, vs unarmed.  Same
< 2% bar: armed-idle must cost one memoized env read per bracket.  The
smoke run then forces ONE capture and reports the per-phase device
split (``xray_phase_device_us``) as the attribution regression
sentinel — phases must be present and the partition conservation-exact.

Round 20 (graftelastic) adds ``elastic_overhead_pct``: the enabled-idle
membership fence (GRAFT_ELASTIC=1, Membership attached, no change ever
queued).  The fence's gate — one memoized env read + an empty-deque
check — is timed directly at nanosecond resolution and reported as a
fraction of the median real fused-step time (a paired-step estimator
cannot resolve a sub-microsecond check under this box's drift).  Same
< 2% bar.
"""
import json
import sys
import time

import numpy as np


CHAIN = 64          # ops per iteration (a*b+c, relu, sum-free chain)
ITERS = 30
SHAPE = (64, 64)

FUSED_N_PARAMS = 64
FUSED_SHAPE = (16, 16)


def _fused_step_bench(iters=30, n_params=FUSED_N_PARAMS, shape=FUSED_SHAPE):
    """Per-param vs bucketed Trainer.step over a many-small-param model.
    Returns the metrics dict; asserts the two paths stay bit-identical
    (the graftfuse contract) before reporting any speedup."""
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon

    def build(prefix):
        rs = np.random.RandomState(0)
        ps = []
        for k in range(n_params):
            p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
            p.initialize(ctx=mx.cpu())
            p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
            p.grad()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
            ps.append(p)
        return ps

    opt_kw = {"learning_rate": 0.01, "momentum": 0.9}
    pa, pb = build("pp"), build("bk")
    per_param = gluon.Trainer(pa, "sgd", dict(opt_kw), kvstore=None)
    per_param._bucket_bytes_override = 0        # force the per-param path
    bucketed = gluon.Trainer(pb, "sgd", dict(opt_kw), kvstore=None)

    def timed(trainer, params):
        trainer.step(1)
        params[-1].data().asnumpy()             # warm + sync
        t0 = time.perf_counter()
        for _ in range(iters):
            trainer.step(1)
        params[-1].data().asnumpy()
        return (time.perf_counter() - t0) / iters

    dt_pp = timed(per_param, pa)
    dt_bk = timed(bucketed, pb)
    parity = all(a.data().asnumpy().tobytes() == b.data().asnumpy().tobytes()
                 for a, b in zip(pa, pb))
    assert parity, "bucketed Trainer.step diverged from the per-param path"
    return {
        "fused_step_params": n_params,
        "fused_step_per_param_ms": round(dt_pp * 1e3, 3),
        "fused_step_bucketed_ms": round(dt_bk * 1e3, 3),
        "fused_step_speedup": round(dt_pp / dt_bk, 2),
        "fused_step_parity": parity,
    }


def _overlap_step_bench(iters=12, repeats=4, n_params=FUSED_N_PARAMS,
                        shape=FUSED_SHAPE, bucket_bytes=1 << 20):
    """Serial-bucketed vs overlapped Trainer.step over a many-small-param
    model behind a (single-worker) dist_sync store — the reduce_many
    wire the fused path rides.  Each iteration runs a real
    record()/backward() so the grad-ready hooks fire; only the step()
    call is timed (mean per round, min over interleaved rounds), because
    graftlap's claim is that step() stops doing cold communication work,
    not that backward gets faster.  Asserts bit-parity before reporting
    and carries the measured overlap ratio from telemetry."""
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, telemetry

    def build(prefix, overlap):
        rs = np.random.RandomState(0)
        ps = []
        for k in range(n_params):
            p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
            p.initialize(ctx=mx.cpu())
            p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
            ps.append(p)
        t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                          kvstore=mx.kv.create("dist_sync"))
        t._bucket_bytes_override = bucket_bytes
        t._overlap_override = overlap
        return ps, t

    rs = np.random.RandomState(1)
    consts = [mx.nd.array(rs.randn(*shape).astype(np.float32))
              for _ in range(n_params)]

    def train_round(params, trainer, n, timed):
        step_s = 0.0
        for _ in range(n):
            with autograd.record():
                loss = None
                for p, c in zip(params, consts):
                    y = (p.data() * p.data() * c).sum()
                    loss = y if loss is None else loss + y
            loss.backward()
            t0 = time.perf_counter()
            trainer.step(1)
            if timed:
                step_s += time.perf_counter() - t0
        params[-1].data().asnumpy()              # sync
        return step_s / max(n, 1)

    pa, ta = build("ovs", False)
    pb, tb = build("ovo", True)
    # warmup: compiles + plan build + (for B) the first serial step that
    # arms the hooks — from here on B's backward issues every bucket
    train_round(pa, ta, 2, timed=False)
    train_round(pb, tb, 2, timed=False)
    best = {False: float("inf"), True: float("inf")}
    for _ in range(repeats):
        best[False] = min(best[False], train_round(pa, ta, iters, True))
        best[True] = min(best[True], train_round(pb, tb, iters, True))
    parity = all(a.data().asnumpy().tobytes() == b.data().asnumpy().tobytes()
                 for a, b in zip(pa, pb))
    assert parity, "overlapped Trainer.step diverged from the serial " \
        "bucketed path"
    snap = telemetry.compact_snapshot()
    return {
        "overlap_step_params": n_params,
        "overlap_step_buckets": int(snap.get(
            "graft_trainer_bucket_count", 0)),
        "overlap_step_serial_ms": round(best[False] * 1e3, 3),
        "overlap_step_overlapped_ms": round(best[True] * 1e3, 3),
        "overlap_step_latency_ratio": round(best[True] / best[False], 3),
        "overlap_step_speedup": round(best[False] / best[True], 2),
        "overlap_step_parity": parity,
        "overlap_measured_ratio": round(float(snap.get(
            "graft_trainer_overlap_ratio", 0.0)), 4),
        "overlap_buckets_overlapped_total": snap.get(
            'graft_trainer_overlap_buckets_total{mode="overlapped"}', 0),
        "overlap_buckets_serial_total": snap.get(
            'graft_trainer_overlap_buckets_total{mode="serial"}', 0),
    }


def _duplex_step_bench(iters=12, repeats=3, n_params=FUSED_N_PARAMS,
                       shape=FUSED_SHAPE, bucket_bytes=1 << 20):
    """graftduplex (round 9): the 64-param dist_sync bench with the
    store-side update (``update_on_kvstore=True`` — push applies the
    server-semantics optimizer, pull broadcasts weights back), stepped
    three ways on the same wire:

    * ``serial``   — the whole handshake cold inside step(),
    * ``overlap``  — PR 7 semantics: bucket reduces issued mid-backward
      (grad-ready hooks), pulls still synchronous,
    * ``duplex``   — reduces overlapped AND each bucket's weight pull an
      async ``PullHandle`` waited at first touch in the NEXT forward.

    Two views are reported: step-only latency (what step() still pays)
    and whole-loop latency (the honest end-to-end number — the pull win
    is a wait MOVED under the next forward, not merely relocated cost;
    the loop ratio proves it was actually hidden).  Bit-parity across
    all three is asserted before any number is reported, and the
    pull-side exposed-wait delta (graft_trainer_pull_exposed_seconds)
    shows the async pulls strictly below the synchronous-pull baseline."""
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, telemetry

    def build(prefix, overlap, pull):
        rs = np.random.RandomState(0)
        ps = []
        for k in range(n_params):
            p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
            p.initialize(ctx=mx.cpu())
            p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
            ps.append(p)
        t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                          kvstore=mx.kv.create("dist_sync"),
                          update_on_kvstore=True)
        t._bucket_bytes_override = bucket_bytes
        t._overlap_override = overlap
        t._overlap_pull_override = pull
        return ps, t

    rs = np.random.RandomState(1)
    consts = [mx.nd.array(rs.randn(*shape).astype(np.float32))
              for _ in range(n_params)]

    def train_round(params, trainer, n, timed):
        step_s = 0.0
        t_loop = time.perf_counter()
        for _ in range(n):
            with autograd.record():
                loss = None
                for p, c in zip(params, consts):
                    y = (p.data() * p.data() * c).sum()
                    loss = y if loss is None else loss + y
            loss.backward()
            t0 = time.perf_counter()
            trainer.step(1)
            if timed:
                step_s += time.perf_counter() - t0
        params[-1].data().asnumpy()              # sync (first-touch too)
        return step_s / max(n, 1), (time.perf_counter() - t_loop) / max(n, 1)

    cfgs = {"serial": (False, False), "overlap": (True, False),
            "duplex": (True, True)}
    runs, best_step, best_loop, pull_exposed = {}, {}, {}, {}
    for name, (ov, pl) in cfgs.items():
        runs[name] = build(name[:2], ov, pl)
        train_round(*runs[name], n=2, timed=False)     # warm + arm
        best_step[name] = best_loop[name] = float("inf")
    for _ in range(repeats):
        for name in cfgs:
            snap0 = telemetry.compact_snapshot().get(
                "graft_trainer_pull_exposed_seconds_sum", 0.0)
            step_ms, loop_ms = train_round(*runs[name], n=iters, timed=True)
            best_step[name] = min(best_step[name], step_ms)
            best_loop[name] = min(best_loop[name], loop_ms)
            pull_exposed[name] = telemetry.compact_snapshot().get(
                "graft_trainer_pull_exposed_seconds_sum", 0.0) - snap0
    ref = runs["serial"][0]
    parity = all(
        a.data().asnumpy().tobytes() == b.data().asnumpy().tobytes()
        for name in ("overlap", "duplex")
        for a, b in zip(ref, runs[name][0]))
    assert parity, "full-duplex step diverged from the serial path"
    snap = telemetry.compact_snapshot()
    return {
        "duplex_step_params": n_params,
        "duplex_step_serial_ms": round(best_step["serial"] * 1e3, 3),
        "duplex_step_overlap_ms": round(best_step["overlap"] * 1e3, 3),
        "duplex_step_full_ms": round(best_step["duplex"] * 1e3, 3),
        "duplex_loop_serial_ms": round(best_loop["serial"] * 1e3, 3),
        "duplex_loop_overlap_ms": round(best_loop["overlap"] * 1e3, 3),
        "duplex_loop_full_ms": round(best_loop["duplex"] * 1e3, 3),
        "duplex_step_overlap_ratio": round(
            best_step["overlap"] / best_step["serial"], 3),
        "duplex_step_full_ratio": round(
            best_step["duplex"] / best_step["serial"], 3),
        "duplex_loop_full_ratio": round(
            best_loop["duplex"] / best_loop["serial"], 3),
        "duplex_step_parity": parity,
        "duplex_pull_exposed_serial_s": round(
            pull_exposed.get("serial", 0.0), 6),
        "duplex_pull_exposed_full_s": round(
            pull_exposed.get("duplex", 0.0), 6),
        "duplex_pull_overlap_ratio": round(float(snap.get(
            "graft_trainer_pull_overlap_ratio", 0.0)), 4),
    }


def _compiled_step_bench(iters=12, repeats=3, n_params=FUSED_N_PARAMS,
                         shape=FUSED_SHAPE, ulp_tol=16):
    """graftstep: the whole bucketed-eager training iteration
    (record → forward → backward → Trainer.step, dispatched as many
    programs plus the host tape walk) vs the SAME iteration as the
    compiled whole-step program pair (fwd+bwd → ``reduce_many`` →
    donated fused update) over the 64-param dist_sync model the other
    trainer benches use.  The whole iteration is timed — the compiled
    step's claim is that the HOST work between programs (eager op
    dispatch, tape bookkeeping, 64 per-param python hops) disappears,
    not that any one program gets faster.  Params+states parity is
    asserted under the documented ULP tolerance (lr rides as a traced
    operand in the compiled program — ~1 ULP fma drift per step), and
    the static-shape loop must show exactly ONE trace (zero retraces
    after step 2)."""
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.gluon.step_compile import max_ulp_diff

    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                for k in range(n_params):
                    setattr(self, "w%d" % k,
                            self.params.get("w%d" % k, shape=shape))

        def hybrid_forward(self, F, x, **ps):
            acc = None
            for k in range(n_params):
                y = (ps["w%d" % k] * ps["w%d" % k] * x).sum()
                acc = y if acc is None else acc + y
            return acc

    def build(prefix):
        net = Net(prefix=prefix)
        net.initialize(ctx=mx.cpu())
        rs = np.random.RandomState(0)
        for name in sorted(net.collect_params()):
            p = net.collect_params()[name]
            p.set_data(mx.nd.array(
                rs.randn(*p.shape).astype(np.float32)))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.01, "momentum": 0.9},
                           kvstore=mx.kv.create("dist_sync"))
        return net, tr

    x = mx.nd.array(
        np.random.RandomState(1).rand(*shape).astype(np.float32))
    net_e, tr_e = build("cse")
    net_c, tr_c = build("csc")
    cstep = tr_c.compile_step(net_c, enabled=True)

    def eager_iter():
        with autograd.record():
            out = net_e(x)
        out.backward()
        tr_e.step(1)

    def compiled_iter():
        cstep(x, batch_size=1)

    # warmup: the eager arm compiles its per-op/per-bucket programs and
    # builds its plan; the compiled arm's first call falls back eager
    # and traces lazily, the second dispatches the compiled pair
    for _ in range(2):
        eager_iter()
        compiled_iter()
    net_e.collect_params()[sorted(net_e.collect_params())[0]] \
        .data().asnumpy()
    best = {"eager": float("inf"), "compiled": float("inf")}
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            eager_iter()
        net_e.collect_params()[sorted(net_e.collect_params())[-1]] \
            .data().asnumpy()                    # sync
        best["eager"] = min(best["eager"],
                            (time.perf_counter() - t0) / iters)
        t0 = time.perf_counter()
        for _ in range(iters):
            compiled_iter()
        net_c.collect_params()[sorted(net_c.collect_params())[-1]] \
            .data().asnumpy()
        best["compiled"] = min(best["compiled"],
                               (time.perf_counter() - t0) / iters)
    worst_ulp = 0
    for ne, nc in zip(sorted(net_e.collect_params()),
                      sorted(net_c.collect_params())):
        ulp = max_ulp_diff(net_e.collect_params()[ne].data()._read(),
                           net_c.collect_params()[nc].data()._read())
        worst_ulp = max(worst_ulp, ulp)
    assert worst_ulp <= ulp_tol, \
        "compiled step diverged from bucketed-eager by %s ULP" % worst_ulp
    assert cstep.retraces == 1, \
        "static-shape loop retraced the compiled step (%d traces)" \
        % cstep.retraces
    return {
        "compiled_step_params": n_params,
        "compiled_step_eager_ms": round(best["eager"] * 1e3, 3),
        "compiled_step_compiled_ms": round(best["compiled"] * 1e3, 3),
        "compiled_step_latency_ratio": round(
            best["compiled"] / best["eager"], 3),
        "compiled_step_speedup": round(
            best["eager"] / best["compiled"], 2),
        "compiled_step_backend": jax.default_backend(),
        "compiled_step_parity_ulp": int(worst_ulp),
        "compiled_step_retraces": cstep.retraces,
        "compiled_step_compiled_total": cstep.compiled_steps,
        "compiled_step_fallback_total": cstep.fallback_steps,
    }


def _quant_step_bench(iters=8, repeats=3, n_params=FUSED_N_PARAMS,
                      shape=FUSED_SHAPE, bucket_bytes=1 << 14):
    """graftzero quantized wire: the 64-param dist_sync train loop run
    three ways — baseline (no quant env), explicit off
    (``GRAFT_QUANT_REDUCE=0``, must stay BIT-identical with < 2%
    overhead: the escape-hatch contract) and ``int8`` (wire bytes
    measured off the kvstore counters, gated >= 3.5x below f32; params
    asserted within the documented block-scale tolerance).  Arms run
    sequentially, each under its own env value, because the quantizer
    resolves the mode at every step."""
    import os
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, telemetry

    def build(prefix):
        rs = np.random.RandomState(0)
        ps = []
        for k in range(n_params):
            p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
            p.initialize(ctx=mx.cpu())
            p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
            ps.append(p)
        t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                          kvstore=mx.kv.create("dist_sync"))
        t._bucket_bytes_override = bucket_bytes
        return ps, t

    rs = np.random.RandomState(1)
    consts = [mx.nd.array(rs.randn(*shape).astype(np.float32))
              for _ in range(n_params)]

    def train_round(params, trainer, n):
        step_s = 0.0
        for _ in range(n):
            with autograd.record():
                loss = None
                for p, c in zip(params, consts):
                    y = (p.data() * p.data() * c).sum()
                    loss = y if loss is None else loss + y
            loss.backward()
            t0 = time.perf_counter()
            trainer.step(1)
            step_s += time.perf_counter() - t0
        params[-1].data().asnumpy()              # sync
        return step_s / max(n, 1)

    def wire_counter():
        return float(telemetry.compact_snapshot().get(
            "graft_kvstore_wire_bytes_total", 0.0))

    arms, times, wire = {}, {}, {}
    saved = os.environ.get("GRAFT_QUANT_REDUCE")
    try:
        for arm, env in (("base", None), ("off", "0"), ("int8", "int8")):
            os.environ.pop("GRAFT_QUANT_REDUCE", None)
            if env is not None:
                os.environ["GRAFT_QUANT_REDUCE"] = env
            ps, t = build("q" + arm)
            train_round(ps, t, 2)                # warm: plan + compiles
            w0 = wire_counter()
            best = float("inf")
            for _ in range(repeats):
                best = min(best, train_round(ps, t, iters))
            arms[arm] = ps
            times[arm] = best
            wire[arm] = wire_counter() - w0
    finally:
        os.environ.pop("GRAFT_QUANT_REDUCE", None)
        if saved is not None:
            os.environ["GRAFT_QUANT_REDUCE"] = saved

    off_parity = all(
        a.data().asnumpy().tobytes() == b.data().asnumpy().tobytes()
        for a, b in zip(arms["base"], arms["off"]))
    assert off_parity, \
        "GRAFT_QUANT_REDUCE=0 escape hatch is not bit-identical"
    maxdiff = max(
        float(np.abs(a.data().asnumpy() - b.data().asnumpy()).max())
        for a, b in zip(arms["base"], arms["int8"]))
    # loose end-to-end ceiling: the per-step per-element bound is
    # lr * max|block|/254 (observability.md quantization contract);
    # this workload's gradients keep it orders of magnitude below 1e-2
    assert maxdiff < 1e-2, \
        "int8 quantized params drifted %.4g from the float oracle" % maxdiff
    ratio = wire["base"] / max(wire["int8"], 1.0)
    return {
        "quant_step_params": n_params,
        "quant_step_base_ms": round(times["base"] * 1e3, 3),
        "quant_step_off_ms": round(times["off"] * 1e3, 3),
        "quant_step_int8_ms": round(times["int8"] * 1e3, 3),
        "quant_step_latency_ratio": round(
            times["int8"] / times["base"], 3),
        "quant_off_overhead_pct": round(
            (times["off"] / times["base"] - 1.0) * 100.0, 2),
        "quant_off_parity": off_parity,
        "quant_wire_bytes_f32": int(wire["base"]),
        "quant_wire_bytes_int8": int(wire["int8"]),
        "quant_wire_ratio": round(ratio, 2),
        "quant_int8_maxdiff": maxdiff,
    }


def _zero_step_bench(steps=4):
    """graftzero ZeRO-1: the sharded update needs a multi-device mesh,
    and the host platform's device count is fixed at jax import — so
    this bench re-execs itself (``--zero-child``) with an 8-device CPU
    mesh and parses the child's JSON line.  The child asserts the
    sharded params byte-identical to the unsharded step's ctx-0 replica
    and reports the per-shard optimizer-state bytes (the ~1/N claim)
    straight off ``Updater.states_nbytes`` + the shard-bytes gauge."""
    import os
    import subprocess
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("GRAFT_SHARD_OPTIMIZER", None)
    env.pop("GRAFT_QUANT_REDUCE", None)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--zero-child",
         str(int(steps))],
        env=env, capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        raise RuntimeError("zero_step child failed:\n%s"
                           % (out.stderr or out.stdout)[-4000:])
    return json.loads(out.stdout.strip().splitlines()[-1])


def _zero_step_child(steps=4, n_params=24, shape=(16, 16),
                     bucket_bytes=1 << 12):
    """The in-mesh body of :func:`_zero_step_bench` (run with 8 host
    devices): unsharded vs ``GRAFT_SHARD_OPTIMIZER=1`` momentum-SGD
    steps over 8 context replicas, byte-parity + state-shard report."""
    import os
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, engine, gluon, telemetry

    n_ctx = 8
    ctxs = [mx.cpu(i) for i in range(n_ctx)]
    rs = np.random.RandomState(0)
    weights = [rs.randn(*shape).astype(np.float32) for _ in range(n_params)]
    base = [rs.randn(*shape).astype(np.float32) for _ in range(n_params)]

    def build(prefix, zero):
        os.environ.pop("GRAFT_SHARD_OPTIMIZER", None)
        if zero:
            os.environ["GRAFT_SHARD_OPTIMIZER"] = "1"
        ps = []
        for k in range(n_params):
            p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
            p.initialize(ctx=ctxs)
            ps.append(p)
        for p, w in zip(ps, weights):
            for d in p.list_data():
                d._write(engine.colocate(jnp.asarray(w), d._read()))
        t = gluon.Trainer(ps, "sgd",
                          {"learning_rate": 0.01, "momentum": 0.9},
                          kvstore=mx.kv.create("dist_sync"))
        t._bucket_bytes_override = bucket_bytes
        consts = [[mx.nd.array(c * (j + 1), ctx=ctx)
                   for j, ctx in enumerate(ctxs)] for c in base]
        return ps, t, consts

    def run(ps, t, consts, n, warm=2):
        step_s = 0.0
        for it in range(warm + n):
            with autograd.record():
                losses = []
                for j, ctx in enumerate(ctxs):
                    loss = None
                    for p, cs in zip(ps, consts):
                        d = p.data(ctx)
                        y = (d * d * cs[j]).sum()
                        loss = y if loss is None else loss + y
                    losses.append(loss)
            autograd.backward(losses)
            t0 = time.perf_counter()
            t.step(n_ctx)
            if it >= warm:
                step_s += time.perf_counter() - t0
        ps[-1].data(ctxs[0]).asnumpy()           # sync
        return step_s / max(n, 1)

    pa, ta, ca = build("u", False)
    dt_u = run(pa, ta, ca, steps)
    unsharded_bytes = ta._updaters[0].states_nbytes()
    pb, tb, cb = build("z", True)
    dt_z = run(pb, tb, cb, steps)
    os.environ.pop("GRAFT_SHARD_OPTIMIZER", None)
    parity = all(
        pa[k].list_data()[0].asnumpy().tobytes()
        == pb[k].list_data()[0].asnumpy().tobytes()
        for k in range(n_params))
    assert parity, \
        "ZeRO-1 sharded step diverged from the unsharded ctx-0 replica"
    shard_bytes = max(u.states_nbytes() for u in tb._updaters)
    gauge = float(telemetry.compact_snapshot().get(
        "graft_trainer_state_shard_bytes", 0.0))
    assert gauge == float(shard_bytes), \
        "shard-bytes gauge %.0f != measured %d" % (gauge, shard_bytes)
    print(json.dumps({
        "zero_step_params": n_params,
        "zero_step_ctxs": n_ctx,
        "zero_step_unsharded_ms": round(dt_u * 1e3, 3),
        "zero_step_sharded_ms": round(dt_z * 1e3, 3),
        "zero_step_latency_ratio": round(dt_z / dt_u, 3),
        "zero_step_parity": parity,
        "zero_state_unsharded_bytes": int(unsharded_bytes),
        "zero_state_shard_bytes": int(shard_bytes),
        "zero_state_shard_fraction": round(
            shard_bytes / max(unsharded_bytes, 1), 4),
    }))


def _lens_overhead_bench(iters=20, repeats=4, n_params=8, shape=(16, 16)):
    """graftlens steady-state cost on a real train loop (record scope,
    backward, kvstore collectives, step journal — every lens source
    firing): the same loop timed with the lens ON (the default) vs
    forced OFF, interleaved min-of-rounds with the mode order ALTERNATED
    per round (the loop keeps warming for dozens of iterations on CPU,
    so a fixed order books the drift to whichever mode runs first).
    The acceptance bar is < 2% (ISSUE 8), same contract as
    blackbox_overhead_pct."""
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.telemetry import lens

    rs = np.random.RandomState(0)
    ps = []
    for k in range(n_params):
        p = gluon.Parameter("lob%d" % k, shape=shape)
        p.initialize(ctx=mx.cpu())
        p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
        ps.append(p)
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=mx.kv.create("local"))

    def loop():
        t0 = time.perf_counter()
        for _ in range(iters):
            with autograd.record():
                loss = None
                for p in ps:
                    y = (p.data() * p.data()).sum()
                    loss = y if loss is None else loss + y
            loss.backward()
            trainer.step(1)
        ps[-1].data().asnumpy()
        return time.perf_counter() - t0

    for _ in range(3):
        loop()                                   # warm compiles + plan
    best = {True: float("inf"), False: float("inf")}
    prev = lens._enabled_override
    try:
        for r in range(repeats):
            order = (False, True) if r % 2 == 0 else (True, False)
            for state in order:
                lens.set_enabled(state)
                best[state] = min(best[state], loop())
    finally:
        lens.set_enabled(prev)
    pct = (best[True] - best[False]) / best[False] * 100.0
    return {
        "lens_on_step_ms": round(best[True] / iters * 1e3, 3),
        "lens_off_step_ms": round(best[False] / iters * 1e3, 3),
        "lens_overhead_pct": round(pct, 2),
    }


def _pulse_overhead_bench(iters=50, repeats=6, n_params=8, shape=(16, 16)):
    """graftpulse async-ledger cost on a real bulked ASYNC train loop
    (flush-boundary reaper enqueues + mem-timeline probes firing — the
    graftpulse dispatch-site surface): the same loop timed with the
    pulse ledger ON (the default) vs forced OFF, lens on throughout,
    interleaved min-of-rounds with alternating mode order like the lens
    bench.  Each timed round drains the reaper INSIDE its window so the
    on-mode pays its full cost (a pending queue crossing into the off
    round would book the on-mode's work to the off-mode's clock).  The
    acceptance bar is < 2% (ISSUE 12)."""
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.telemetry import lens

    rs = np.random.RandomState(0)
    ps = []
    for k in range(n_params):
        p = gluon.Parameter("pob%d" % k, shape=shape)
        p.initialize(ctx=mx.cpu())
        p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
        ps.append(p)
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=mx.kv.create("local"))

    def loop():
        t0 = time.perf_counter()
        for _ in range(iters):
            with mx.engine.bulk(64):
                with autograd.record():
                    loss = None
                    for p in ps:
                        y = (p.data() * p.data()).sum()
                        loss = y if loss is None else loss + y
                loss.backward()
            trainer.step(1)
        ps[-1].data().asnumpy()
        lens.pulse_drain(10.0)
        return time.perf_counter() - t0

    prev_lens = lens._enabled_override
    prev_pulse = lens._pulse_override
    lens.set_enabled(True)
    try:
        for _ in range(3):
            loop()                               # warm compiles + plan
        best = {True: float("inf"), False: float("inf")}
        for r in range(repeats):
            order = (False, True) if r % 2 == 0 else (True, False)
            for state in order:
                lens.set_pulse(state)
                best[state] = min(best[state], loop())
    finally:
        lens.set_pulse(prev_pulse)
        lens.set_enabled(prev_lens)
        lens.reset()
    pct = (best[True] - best[False]) / best[False] * 100.0
    return {
        "pulse_on_step_ms": round(best[True] / iters * 1e3, 3),
        "pulse_off_step_ms": round(best[False] / iters * 1e3, 3),
        "pulse_overhead_pct": round(pct, 2),
    }


def _tsan_overhead_bench(iters=20, repeats=4, n_params=8, shape=(16, 16)):
    """grafttsan enabled-mode cost on a real overlapped train loop —
    async reduce handles (issue/settle + value registry), scheduler
    regions, and the NDArray._write hook all firing.  Interleaved
    min-of-rounds with alternating mode order, like the lens bench.
    Default-off means the bar is informational (<10% when enabled)."""
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.analysis import tsan

    rs = np.random.RandomState(0)
    ps = []
    for k in range(n_params):
        p = gluon.Parameter("tob%d" % k, shape=shape)
        p.initialize(ctx=mx.cpu())
        p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
        ps.append(p)
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=mx.kv.create("dist_sync"))
    trainer._bucket_bytes_override = 512
    trainer._overlap_override = True

    def loop():
        t0 = time.perf_counter()
        for _ in range(iters):
            with autograd.record():
                loss = None
                for p in ps:
                    y = (p.data() * p.data()).sum()
                    loss = y if loss is None else loss + y
            loss.backward()
            trainer.step(1)
        ps[-1].data().asnumpy()
        return time.perf_counter() - t0

    for _ in range(3):
        loop()                                   # warm compiles + plan
    best = {True: float("inf"), False: float("inf")}
    prev = tsan._ACTIVE[0]
    try:
        for r in range(repeats):
            order = (False, True) if r % 2 == 0 else (True, False)
            for state in order:
                tsan.set_enabled(state)
                best[state] = min(best[state], loop())
    finally:
        tsan.set_enabled(prev)
        tsan.clear()
    pct = (best[True] - best[False]) / best[False] * 100.0
    return {
        "tsan_on_step_ms": round(best[True] / iters * 1e3, 3),
        "tsan_off_step_ms": round(best[False] / iters * 1e3, 3),
        "tsan_overhead_pct": round(pct, 2),
    }


def _blackbox_overhead_bench(iters=ITERS, repeats=5):
    """Flight-recorder steady-state cost on the 64-op bulked dispatch
    chain: the same loop timed with the recorder ON (the default) vs
    forced OFF, interleaved across ``repeats`` rounds (min-of-rounds on
    both sides cancels machine drift).  The acceptance bar is < 2%
    (ISSUE 6): the recorder's per-flush cost is one ring append + one
    in-flight bracket, amortized over a whole segment dispatch."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.telemetry import blackbox

    rs = np.random.RandomState(1)
    a = mx.nd.array(rs.rand(*SHAPE).astype(np.float32))
    b = mx.nd.array(rs.rand(*SHAPE).astype(np.float32) + 0.5)
    c = mx.nd.array(rs.rand(*SHAPE).astype(np.float32))
    with mx.engine.bulk(CHAIN + 1):
        _chain_eager(a, b, c, CHAIN).asnumpy()      # compile the replay

    def timed():
        t0 = time.perf_counter()
        for _ in range(iters):
            with mx.engine.bulk(CHAIN + 1):
                out = _chain_eager(a, b, c, CHAIN)
        out.asnumpy()
        return time.perf_counter() - t0

    best = {True: float("inf"), False: float("inf")}
    prev = blackbox._enabled_override
    try:
        for _ in range(repeats):
            for state in (False, True):
                blackbox.set_enabled(state)
                timed()                              # warm this mode
                best[state] = min(best[state], timed())
    finally:
        blackbox.set_enabled(prev)
    pct = (best[True] - best[False]) / best[False] * 100.0
    return {
        "blackbox_on_ops_per_sec": round(CHAIN * iters / best[True], 1),
        "blackbox_off_ops_per_sec": round(CHAIN * iters / best[False], 1),
        "blackbox_overhead_pct": round(pct, 2),
    }


def _armor_overhead_bench(iters=25, repeats=2):
    """graftarmor inertness: with no faults armed, the PS wire's retry
    plumbing (request ids, fault_point probes, reconnect bookkeeping)
    must be ~free.  Times a push/pull loop against a real localhost
    ParameterServer with GRAFT_FAULTS unset vs armed with a clause that
    never matches; the delta is reported against the < 2% budget and
    the armed runs must inject ZERO faults (chaos round, satellite of
    the robustness PR)."""
    from incubator_mxnet_tpu.parallel import ps
    from incubator_mxnet_tpu.armor import faults

    srv = ps.ParameterServer(host="127.0.0.1")
    client = ps.PSClient(srv.address)
    grad = {"w": np.ones(1024, np.float32)}
    fired = 0
    try:
        client.init({"w": np.zeros(1024, np.float32)})

        def timed():
            t0 = time.perf_counter()
            for _ in range(iters):
                client.push(grad)
                client.pull(["w"])
            return time.perf_counter() - t0

        best = {True: float("inf"), False: float("inf")}
        for _ in range(repeats):
            for armed in (False, True):
                if armed:
                    faults.configure("bench.never:error:cmd=never")
                else:
                    faults.reset()
                timed()                          # warm this mode
                best[armed] = min(best[armed], timed())
                if armed:
                    fired += sum(r.fires for r in faults.active_rules())
    finally:
        faults.reset()
        client.close()
        srv.shutdown()
    pct = (best[True] - best[False]) / best[False] * 100.0
    if fired:
        raise AssertionError(
            "armor chaos round: %d faults fired with a never-matching "
            "clause armed" % fired)
    return {
        "armor_rpc_calls_per_sec": round(2 * iters / best[False], 1),
        "armor_overhead_pct": round(pct, 2),
        "armor_faults_fired": fired,
    }


def _compile_check_overhead_bench(iters=50, repeats=9):
    """graftguard inertness: the EH3xx auditor armed on the compiled
    whole-step path (note_call/guard bookkeeping, per-dispatch bake-hash
    recheck, donated-buffer poison + sweep; the EH304 sentinel stays off
    — it deliberately doubles the dispatch) vs the default-off path,
    against the same CompiledStep.  The estimator is PAIRED: every
    iteration times one off call and one armed call back-to-back
    (alternating which mode goes first so warm-cache ordering bias
    cancels), and the reported figure is the median of the per-pair
    deltas over the pooled median off time.  The auditor's cost is a
    few us on a ~ms step while this single-core box drifts by tens of
    percent between separately-sampled windows (scheduler stalls, GC,
    frequency scaling) — only samples taken microseconds apart share
    enough machine state for the difference to mean anything, and a
    GC hit on one side of a single pair lands in that pair's delta
    alone, where the median discards it.  The off mode must be a
    cached flag load (memoized env read, poison map empty) and the
    armed rounds must report ZERO findings."""
    import os

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.analysis import compile_safety as csafety
    from incubator_mxnet_tpu.gluon import step_compile as sc

    # (16, 16) params like the other overhead benches — the auditor's
    # cost is a fixed few us per step, so a microscopic step would
    # report an overhead % no real workload sees
    net = sc._make_net("bench_guard_", n_params=8, shape=(16, 16))
    sc._seed_params(net)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01, "momentum": 0.9},
                       kvstore=None)
    cstep = sc.CompiledStep(tr, net, enabled=True)
    x = mx.nd.array(
        np.random.RandomState(3).rand(16, 16).astype(np.float32))
    for _ in range(3):              # kv init + lazy trace + steady state
        cstep(x)
    assert cstep.compiled_steps >= 1, "bench never reached compiled path"

    import statistics

    all_offs, deltas = [], []

    def paired_round(flip):
        """One round of `iters` off/armed pairs appended to the pools;
        `flip` swaps which mode runs first within each pair."""
        for i in range(iters):
            pair = {}
            order = (False, True) if (i + flip) % 2 == 0 else (True, False)
            for armed in order:
                csafety.set_enabled(True if armed else None)
                t0 = time.perf_counter()
                cstep(x)
                pair[armed] = time.perf_counter() - t0
            all_offs.append(pair[False])
            deltas.append(pair[True] - pair[False])
        # off = one cached flag load on the hot path
        csafety.set_enabled(None)
        assert not csafety._ACTIVE[0] and not csafety._POISON, \
            "auditor left armed state behind when off"

    prev_every = os.environ.pop("GRAFT_COMPILE_CHECK_EVERY", None)
    try:
        for armed in (True, False):              # warm both modes once
            csafety.set_enabled(True if armed else None)
            for _ in range(4):
                cstep(x)
        for r in range(repeats):
            paired_round(r)
        aud = cstep._auditor
        if aud is not None and aud.storms:
            raise AssertionError(
                "graftguard bench: %d storm report(s) on a static-shape "
                "loop" % aud.storms)
    finally:
        csafety.set_enabled(None)
        if prev_every is not None:
            os.environ["GRAFT_COMPILE_CHECK_EVERY"] = prev_every
    off_med = statistics.median(all_offs)
    pct = statistics.median(deltas) / off_med * 100.0
    return {
        "compile_check_steps_per_sec": round(1.0 / off_med, 1),
        "compile_check_overhead_pct": round(pct, 2),
    }


def _xray_overhead_bench(iters=50, repeats=9):
    """graftxray inertness: the capture harness ARMED (GRAFT_XRAY=1 —
    ``dispatch_begin``/``dispatch_end`` bracketing every compiled
    dispatch) but with no trigger firing, vs unarmed, on the same
    CompiledStep.  Same PAIRED estimator as the graftguard bench: each
    iteration times one unarmed and one armed call back-to-back
    (alternating order so warm-cache bias cancels) and the figure is
    the median per-pair delta over the pooled median unarmed time —
    the armed-idle cost is a memoized env read + one lock check per
    bracket, far below this box's window-to-window drift.  The
    slow-step trigger is disabled for the timed rounds (a GC hiccup
    tripping a capture would poison the deltas).  Afterwards ONE
    capture is forced across 2 dispatches and the per-phase device
    split is returned as the attribution regression sentinel: phases
    must be present and the partition conservation-EXACT."""
    import os
    import statistics

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import step_compile as sc
    from incubator_mxnet_tpu.telemetry import xray

    net = sc._make_net("bench_xray_", n_params=8, shape=(16, 16))
    sc._seed_params(net)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01, "momentum": 0.9},
                       kvstore=None)
    cstep = sc.CompiledStep(tr, net, enabled=True)
    x = mx.nd.array(
        np.random.RandomState(5).rand(16, 16).astype(np.float32))

    saved = {k: os.environ.pop(k, None)
             for k in ("GRAFT_XRAY", "GRAFT_XRAY_EVERY",
                       "GRAFT_XRAY_STEPS", "GRAFT_XRAY_SLOW_X")}
    xray.reset()
    # a scheduler stall on one armed call must request a capture-stat,
    # not a capture: park the slow-step trigger out of reach
    os.environ["GRAFT_XRAY_SLOW_X"] = "1e9"
    try:
        for _ in range(3):          # kv init + lazy trace + steady state
            cstep(x)
        assert cstep.compiled_steps >= 1, \
            "bench never reached compiled path"
        for armed in (True, False):             # warm both modes once
            if armed:
                os.environ["GRAFT_XRAY"] = "1"
            else:
                os.environ.pop("GRAFT_XRAY", None)
            for _ in range(4):
                cstep(x)
        all_offs, deltas = [], []
        for r in range(repeats):
            for i in range(iters):
                pair = {}
                order = (False, True) if (i + r) % 2 == 0 \
                    else (True, False)
                for armed in order:
                    if armed:
                        os.environ["GRAFT_XRAY"] = "1"
                    else:
                        os.environ.pop("GRAFT_XRAY", None)
                    t0 = time.perf_counter()
                    cstep(x)
                    pair[armed] = time.perf_counter() - t0
                all_offs.append(pair[False])
                deltas.append(pair[True] - pair[False])
        assert not xray.sessions() and not xray.capture_active(), \
            "armed-idle bench opened a capture session"
        # the per-phase sentinel: one forced capture across 2 dispatches
        os.environ["GRAFT_XRAY"] = "1"
        os.environ["GRAFT_XRAY_STEPS"] = "2"
        assert xray.request_capture("bench")
        for _ in range(3):
            cstep(x)
        sess = xray.sessions()
        assert sess and sess[-1]["ok"], "bench capture failed: %r" % (
            sess[-1].get("error") if sess else "<no session>")
        rep = sess[-1]["report"]
        assert rep["conservation_ok"], \
            "phase attribution not conservation-exact in bench capture"
        assert rep["phases"], "no phases attributed in bench capture"
        phases = {p: round(d["device_s"] * 1e6, 3)
                  for p, d in rep["phases"].items()}
        unattr_us = round(rep["unattributed_s"] * 1e6, 3)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        xray.reset()
    off_med = statistics.median(all_offs)
    pct = statistics.median(deltas) / off_med * 100.0
    return {
        "xray_overhead_pct": round(pct, 2),
        "xray_phase_device_us": phases,
        "xray_unattributed_us": unattr_us,
    }


def _elastic_overhead_bench(iters=30, reps=200000, n_params=8,
                            shape=(16, 16)):
    """graftelastic enabled-idle cost: a Membership is attached and
    GRAFT_ELASTIC=1, but no change is ever queued — the ONLY per-step
    work the fence adds in ``Trainer.step`` is its gate (one memoized
    env read + an empty-deque check).  That gate is sub-microsecond on
    a ~1 ms step, far below what a paired-step estimator can resolve
    on this box (window-to-window drift alone is a few percent — a
    paired gate would flake), so the figure is measured directly: the
    gate expression is timed over ``reps`` evaluations at nanosecond
    resolution (loop baseline subtracted) and reported as a fraction
    of the median REAL fused-step time.  Gate < 2%."""
    import statistics

    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, elastic
    from incubator_mxnet_tpu.elastic import Membership

    rs = np.random.RandomState(0)
    ps = []
    for k in range(n_params):
        p = gluon.Parameter("elb%d" % k, shape=shape)
        p.initialize(ctx=mx.cpu())
        p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
        ps.append(p)
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=None)
    trainer.attach_membership(Membership(0, world_size=1))

    def one_step():
        with autograd.record():
            loss = None
            for p in ps:
                y = (p.data() * p.data()).sum()
                loss = y if loss is None else loss + y
        loss.backward()
        t0 = time.perf_counter()
        trainer.step(1)
        ps[-1].data().asnumpy()
        return time.perf_counter() - t0

    try:
        elastic.set_enabled(True)           # the fence runs during warmup
        for _ in range(3):
            one_step()
        elastic.set_enabled(False)
        step_times = [one_step() for _ in range(iters)]
        off_med = statistics.median(step_times)

        # the gate, timed directly — the EXACT expression step() runs
        elastic.set_enabled(True)
        enabled, membership = elastic.enabled, trainer._membership
        fired = 0
        t0 = time.perf_counter()
        for _ in range(reps):
            if enabled() and membership is not None \
                    and membership.pending():
                fired += 1
        gate_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(reps):
            pass
        loop_s = time.perf_counter() - t0
        assert fired == 0, "idle fence fired with an empty queue"
        fence_s = max(0.0, (gate_s - loop_s) / reps)
    finally:
        elastic.set_enabled(None)
    pct = fence_s / off_med * 100.0
    return {
        "elastic_off_step_ms": round(off_med * 1e3, 3),
        "elastic_fence_ns": round(fence_s * 1e9, 1),
        "elastic_overhead_pct": round(pct, 4),
    }


def smoke():
    """Fast path for the lint tier: exercise the bucketed step +
    bit-parity assert in a few seconds, print one JSON line."""
    import jax
    res = _fused_step_bench(iters=3)
    res.update(_overlap_step_bench(iters=4, repeats=2))
    res.update(_duplex_step_bench(iters=4, repeats=2))
    res.update(_compiled_step_bench(iters=4, repeats=2))
    # graftstep acceptance gate: the compiled steady-state step must
    # beat bucketed-eager by >= 1.25x (ratio <= 0.8) on this model
    assert res["compiled_step_latency_ratio"] <= 0.8, \
        "compiled step is not fast enough: ratio %.3f > 0.8" \
        % res["compiled_step_latency_ratio"]
    res.update(_quant_step_bench(iters=5, repeats=2))
    # graftzero acceptance gates: int8 wire >= 3.5x below f32, the off
    # escape hatch bit-identical at < 2% overhead
    assert res["quant_wire_ratio"] >= 3.5, \
        "int8 wire ratio %.2f < 3.5" % res["quant_wire_ratio"]
    assert res["quant_off_overhead_pct"] < 2.0, \
        "quant-off escape hatch overhead %.2f%% >= 2%%" \
        % res["quant_off_overhead_pct"]
    res.update(_zero_step_bench(steps=3))
    assert res["zero_step_parity"], "ZeRO-1 parity failed"
    assert res["zero_state_shard_fraction"] <= 0.5, \
        "ZeRO-1 shard fraction %.3f not ~1/N" \
        % res["zero_state_shard_fraction"]
    res.update(_blackbox_overhead_bench(iters=10, repeats=3))
    res.update(_lens_overhead_bench(iters=10, repeats=3))
    res.update(_pulse_overhead_bench(iters=10, repeats=3))
    res.update(_tsan_overhead_bench(iters=8, repeats=2))
    res.update(_armor_overhead_bench(iters=25, repeats=2))
    res.update(_compile_check_overhead_bench(iters=50, repeats=9))
    # graftguard acceptance gate: auditor armed (no sentinel) must cost
    # < 2% on the compiled step
    assert res["compile_check_overhead_pct"] < 2.0, \
        "compile-check auditor overhead %.2f%% >= 2%%" \
        % res["compile_check_overhead_pct"]
    res.update(_xray_overhead_bench(iters=50, repeats=9))
    # graftxray acceptance gate: armed-but-idle capture harness must
    # cost < 2% on the compiled step
    assert res["xray_overhead_pct"] < 2.0, \
        "xray armed-idle overhead %.2f%% >= 2%%" % res["xray_overhead_pct"]
    res.update(_elastic_overhead_bench(iters=20, reps=100000))
    # graftelastic acceptance gate: enabled-idle step fence must cost
    # < 2% on the fused step
    assert res["elastic_overhead_pct"] < 2.0, \
        "elastic enabled-idle overhead %.2f%% >= 2%%" \
        % res["elastic_overhead_pct"]
    res["metric"] = "fused_step_smoke"
    res["backend"] = jax.default_backend()
    print(json.dumps(res))


def _chain_eager(a, b, c, n):
    for _ in range(n // 4):
        a = a * b
        a = a + c
        a = a.abs()
        a = a - c
    return a


def _chain_views(a, b, c, n):
    """Same budget of compute ops, but with the reshape/transpose glue of
    a real model body interleaved (round 6: views defer, so this must
    still flush as one program per scope)."""
    x = a
    h, w = SHAPE
    for _ in range(n // 4):
        x = x * b
        x = x.reshape((h * w,))        # view
        x = x + 1.0
        x = x.reshape(SHAPE)           # view
        x = x.transpose((1, 0))        # shape op
        x = x.abs()
        x = x[0:h]                     # basic-slice view (full range)
        x = x - c
    return x


def main():
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon

    backend = jax.default_backend()
    rs = np.random.RandomState(0)
    a = mx.nd.array(rs.rand(*SHAPE).astype(np.float32))
    b = mx.nd.array(rs.rand(*SHAPE).astype(np.float32) + 0.5)
    c = mx.nd.array(rs.rand(*SHAPE).astype(np.float32))

    # warmup (fills the per-op jit caches)
    _chain_eager(a, b, c, CHAIN).asnumpy()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = _chain_eager(a, b, c, CHAIN)
    out.asnumpy()                       # sync
    dt_eager = time.perf_counter() - t0
    eager_ops = CHAIN * ITERS / dt_eager

    # engine bulking: same eager code, deferred + replayed as ONE program
    # (sync once at the end, like the eager loop above)
    with mx.engine.bulk(CHAIN + 1):
        _chain_eager(a, b, c, CHAIN).asnumpy()      # compile the replay
    t0 = time.perf_counter()
    for _ in range(ITERS):
        with mx.engine.bulk(CHAIN + 1):
            out = _chain_eager(a, b, c, CHAIN)
    out.asnumpy()
    dt_bulkscope = time.perf_counter() - t0
    bulkscope_ops = CHAIN * ITERS / dt_bulkscope

    # -- VIEW-GLUE variant (round 6): reshape/transpose/slice interleaved
    # with the compute ops.  Views defer, so the whole chain must still
    # be ONE program per scope; flush-cause counters + segment-length
    # histogram make the claim auditable (and regressions visible).
    _chain_views(a, b, c, CHAIN).asnumpy()          # warm per-op caches
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = _chain_views(a, b, c, CHAIN)
    out.asnumpy()
    dt_views_eager = time.perf_counter() - t0
    with mx.engine.bulk(4 * CHAIN):
        _chain_views(a, b, c, CHAIN).asnumpy()      # compile the replay
    mx.engine.reset_flush_stats()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        with mx.engine.bulk(4 * CHAIN):
            out = _chain_views(a, b, c, CHAIN)
    out.asnumpy()
    dt_views_bulk = time.perf_counter() - t0
    view_stats = mx.engine.flush_stats()
    view_flushes = sum(view_stats["causes"].values())
    views_eager_ops = CHAIN * ITERS / dt_views_eager
    views_bulk_ops = CHAIN * ITERS / dt_views_bulk

    class Chain(gluon.HybridBlock):
        def hybrid_forward(self, F, a, b, c):
            for _ in range(CHAIN // 4):
                a = a * b
                a = a + c
                a = F.abs(a)
                a = a - c
            return a

    blk = Chain()
    blk.hybridize()
    blk(a, b, c).asnumpy()              # trace + compile
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = blk(a, b, c)
    out.asnumpy()
    dt_bulk = time.perf_counter() - t0
    bulk_ops = CHAIN * ITERS / dt_bulk

    # -- TRAINING variant: record() + backward() inside the scope --------
    # (the reference's primary bulking target, MXNET_EXEC_BULK_EXEC_TRAIN:
    # the recorded chain becomes one replay + ONE segment-vjp dispatch)
    from incubator_mxnet_tpu import autograd

    def _train_step(bulked):
        import contextlib
        scope = mx.engine.bulk(CHAIN + 8) if bulked \
            else contextlib.nullcontext()
        with scope:
            with autograd.record():
                out = _chain_eager(a, b, c, CHAIN)
                loss = (out * out).sum()
            loss.backward()
        return loss

    a.attach_grad()
    _train_step(False).asnumpy()        # warm per-op caches
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = _train_step(False)
    loss.asnumpy()
    dt_train_eager = time.perf_counter() - t0

    _train_step(True).asnumpy()         # compile replay + segment vjp
    mx.engine.reset_flush_stats()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = _train_step(True)
    loss.asnumpy()
    dt_train_bulk = time.perf_counter() - t0
    train_stats = mx.engine.flush_stats()
    train_eager_ops = CHAIN * ITERS / dt_train_eager
    train_bulk_ops = CHAIN * ITERS / dt_train_bulk

    # -- graftfuse: bucketed Trainer.step vs per-param (round 4) ---------
    fused = _fused_step_bench(iters=ITERS)

    # -- graftlap: overlapped vs serial bucketed step (round 7) ----------
    overlap = _overlap_step_bench(iters=ITERS // 2)

    # -- graftduplex: full-duplex update_on_kvstore step (round 9) -------
    duplex = _duplex_step_bench(iters=ITERS // 2)

    # -- graftstep: whole-step compiled training (round 16) --------------
    compiled = _compiled_step_bench(iters=ITERS // 2)

    # -- graftzero: quantized wire + ZeRO-1 sharded update (round 19) ----
    quant = _quant_step_bench(iters=ITERS // 4)
    zero = _zero_step_bench(steps=ITERS // 6)

    # -- graftwatch: flight-recorder overhead on the same 64-op chain ----
    blackbox_overhead = _blackbox_overhead_bench()

    # -- graftlens: attribution overhead on a real train loop (round 8) --
    lens_overhead = _lens_overhead_bench()

    # -- graftpulse: async device-ledger overhead (round 12) -------------
    pulse_overhead = _pulse_overhead_bench()

    # -- grafttsan: race-detector overhead, enabled mode (round 10) ------
    tsan_overhead = _tsan_overhead_bench()

    # -- graftelastic: enabled-idle step-fence overhead (round 20) -------
    elastic_overhead = _elastic_overhead_bench()

    print(json.dumps({
        **fused,
        **overlap,
        **duplex,
        **compiled,
        **quant,
        **zero,
        **blackbox_overhead,
        **lens_overhead,
        **pulse_overhead,
        **tsan_overhead,
        **elastic_overhead,
        "metric": "eager_small_op_dispatch",
        "backend": backend,
        "chain_len": CHAIN,
        "eager_ops_per_sec": round(eager_ops, 1),
        "engine_bulk_ops_per_sec": round(bulkscope_ops, 1),
        "hybridized_ops_per_sec": round(bulk_ops, 1),
        "engine_bulk_speedup": round(bulkscope_ops / eager_ops, 2),
        "hybridize_speedup": round(bulk_ops / eager_ops, 2),
        "view_chain_eager_ops_per_sec": round(views_eager_ops, 1),
        "view_chain_bulk_ops_per_sec": round(views_bulk_ops, 1),
        "view_chain_bulk_speedup": round(views_bulk_ops / views_eager_ops,
                                         2),
        # ops-per-dispatch over the view-glue chain: ITERS scopes should
        # cost exactly ITERS replay dispatches (views no longer fragment)
        "view_chain_flushes": view_flushes,
        "view_chain_ops_per_dispatch": round(CHAIN * ITERS
                                             / max(view_flushes, 1), 1),
        "view_chain_flush_causes": view_stats["causes"],
        "view_chain_segment_len_hist": {str(k): v for k, v in sorted(
            view_stats["segment_lengths"].items())},
        "train_eager_ops_per_sec": round(train_eager_ops, 1),
        "train_bulk_ops_per_sec": round(train_bulk_ops, 1),
        "train_bulk_speedup": round(train_bulk_ops / train_eager_ops, 2),
        "train_flush_causes": train_stats["causes"],
        "train_segment_len_hist": {str(k): v for k, v in sorted(
            train_stats["segment_lengths"].items())},
        # graftscope: the registry snapshot rides along so the perf
        # trajectory carries flush/segment/phase counters per round
        "metrics": mx.telemetry.compact_snapshot(),
        # graftwatch: recorder status (ring occupancy + event mix)
        "blackbox": mx.telemetry.blackbox.stats(),
    }))


if __name__ == "__main__":
    if "--zero-child" in sys.argv[1:]:
        _zero_step_child(steps=int(sys.argv[sys.argv.index("--zero-child")
                                            + 1]))
    elif "--smoke" in sys.argv[1:]:
        smoke()
    else:
        main()
