"""Sparse dot throughput — TPU counterpart of the reference's sparse dot
benchmark (ref: benchmark/python/sparse/dot.py:1).

Measures ``mx.nd.sparse.dot`` for csr·dense at the reference's density
sweep.  On TPU sparse compute lowers to gather/segment-sum XLA programs
(ndarray/sparse.py) — there is no hand-written SpMV kernel to race, so
the interesting numbers are effective GFLOP/s (counting nnz MACs) and
the crossover vs a plain dense matmul of the same logical shape.

Prints JSON lines.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402

CONFIGS = [
    # (m, k, n, density) — reference sweep shapes (dot.py:226-239 style)
    (512, 3200, 512, 0.01),
    (512, 3200, 512, 0.05),
    (2048, 10000, 256, 0.01),
    (2048, 10000, 256, 0.001),
    (8192, 100000, 64, 0.001),
]


def _rand_csr(rs, m, k, density):
    dense = np.zeros((m, k), np.float32)
    nnz = int(m * k * density)
    rows = rs.randint(0, m, nnz)
    cols = rs.randint(0, k, nnz)
    dense[rows, cols] = rs.randn(nnz).astype(np.float32)
    return mx.nd.sparse.csr_matrix(dense), dense


def measure(f, repeat=10):
    out = f()
    out.wait_to_read()
    t0 = time.perf_counter()
    for _ in range(repeat):
        out = f()
        out.wait_to_read()
    return (time.perf_counter() - t0) / repeat


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--repeat", type=int, default=10)
    args = p.parse_args()
    rs = np.random.RandomState(0)
    for m, k, n, density in CONFIGS:
        csr, dense_np = _rand_csr(rs, m, k, density)
        rhs = mx.nd.array(rs.randn(k, n).astype(np.float32))
        dense_lhs = mx.nd.array(dense_np)

        t_sp = measure(lambda: mx.nd.sparse.dot(csr, rhs), args.repeat)
        t_dn = measure(lambda: mx.nd.dot(dense_lhs, rhs), args.repeat)
        nnz = csr.data.shape[0]
        print(json.dumps({
            "op": "csr_dot_dense", "shape": [m, k, n], "density": density,
            "sparse_ms": round(t_sp * 1e3, 3),
            "dense_ms": round(t_dn * 1e3, 3),
            "effective_gflops": round(2 * nnz * n / t_sp / 1e9, 2),
            "dense_gflops": round(2 * m * k * n / t_dn / 1e9, 2),
            "sparse_vs_dense": round(t_dn / t_sp, 2),
        }), flush=True)


if __name__ == "__main__":
    main()
