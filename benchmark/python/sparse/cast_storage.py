"""cast_storage throughput — TPU counterpart of the reference's
cast-storage benchmark (ref: benchmark/python/sparse/cast_storage.py:1).

dense->csr / dense->row_sparse and back, timed per call on the eager
surface (these are host+device hybrid conversions in the TPU build:
nonzero scans run as XLA programs, index bookkeeping on host —
ndarray/sparse.py cast_storage).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402

CONFIGS = [
    # (rows, cols, density)
    (512, 8192, 0.01),
    (2048, 8192, 0.01),
    (8192, 8192, 0.001),
    (8192, 512, 0.05),
]


def measure(f, repeat=10):
    f()
    t0 = time.perf_counter()
    for _ in range(repeat):
        f()
    return (time.perf_counter() - t0) / repeat


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--repeat", type=int, default=10)
    args = p.parse_args()
    rs = np.random.RandomState(0)
    for rows, cols, density in CONFIGS:
        dense_np = np.zeros((rows, cols), np.float32)
        nnz = int(rows * cols * density)
        dense_np[rs.randint(0, rows, nnz), rs.randint(0, cols, nnz)] = 1.0
        dense = mx.nd.array(dense_np)
        csr = mx.nd.sparse.cast_storage(dense, "csr")
        rsp = mx.nd.sparse.cast_storage(dense, "row_sparse")

        out = {
            "op": "cast_storage", "shape": [rows, cols], "density": density,
            "dense_to_csr_ms": round(measure(
                lambda: mx.nd.sparse.cast_storage(dense, "csr"),
                args.repeat) * 1e3, 3),
            "dense_to_rsp_ms": round(measure(
                lambda: mx.nd.sparse.cast_storage(dense, "row_sparse"),
                args.repeat) * 1e3, 3),
            "csr_to_dense_ms": round(measure(
                lambda: csr.todense(), args.repeat) * 1e3, 3),
            "rsp_to_dense_ms": round(measure(
                lambda: rsp.todense(), args.repeat) * 1e3, 3),
        }
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
