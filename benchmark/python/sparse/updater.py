"""Row-sparse optimizer-update throughput — TPU counterpart of the
reference's updater benchmark (ref: benchmark/python/sparse/updater.py:1).

Times SGD updates on a large embedding-style weight when the gradient is
row-sparse (the lazy path touches only occupied rows — optimizer.py
_sparse_sgd, the analogue of SGDUpdateRspRspImpl) vs the same gradient
densified.  Prints JSON lines.

``--bulk N``: run each update stream inside ``mx.engine.bulk`` so N
consecutive updates flush as ONE XLA dispatch — the configuration that
matters for training loops (the reference bulks optimizer updates inside
train segments, threaded_engine.h:472-509).  Without it the lazy path
pays per-op dispatch floors that dwarf its bandwidth win on this
transport (docs/bench_results_r04/README.md:89).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray  # noqa: E402

CONFIGS = [
    # (rows, cols, occupied-row fraction)
    (100000, 128, 0.01),
    (100000, 128, 0.1),
    (1000000, 64, 0.001),
]


def measure(update, sync, repeat=10, bulk=0):
    """ms per update.  bulk mode: N updates recorded per segment, one
    flush per scope exit, sync OUTSIDE the scope (a sync inside would
    materialize and break the segment)."""
    if bulk:
        def run():
            with mx.engine.bulk(bulk + 1):
                for _ in range(bulk):
                    update()
            sync()
        run()                       # warm-up (compile the replay)
        t0 = time.perf_counter()
        run()
        return (time.perf_counter() - t0) / bulk
    # non-bulk: sync EVERY update (the round-4 methodology — per-dispatch
    # latency is part of what this mode measures)
    update(); sync()
    t0 = time.perf_counter()
    for _ in range(repeat):
        update()
        sync()
    return (time.perf_counter() - t0) / repeat


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--repeat", type=int, default=10)
    p.add_argument("--bulk", type=int, default=0,
                   help="defer N updates per XLA dispatch via engine.bulk")
    args = p.parse_args()
    rs = np.random.RandomState(0)
    for rows, cols, frac in CONFIGS:
        k = max(1, int(rows * frac))
        idx = np.sort(rs.choice(rows, size=k, replace=False))
        vals = rs.randn(k, cols).astype(np.float32)
        grad_rsp = RowSparseNDArray(mx.nd.array(vals),
                                    mx.nd.array(idx.astype(np.int64)),
                                    (rows, cols))
        grad_dense = mx.nd.array(grad_rsp.todense().asnumpy())

        opt = mx.optimizer.SGD(learning_rate=0.1, lazy_update=True)
        w_lazy = mx.nd.array(rs.randn(rows, cols).astype(np.float32))
        w_dense = mx.nd.array(w_lazy.asnumpy())

        t_lazy = measure(lambda: opt.update(0, w_lazy, grad_rsp, None),
                         w_lazy.wait_to_read, args.repeat, args.bulk)
        t_dense = measure(lambda: opt.update(1, w_dense, grad_dense, None),
                          w_dense.wait_to_read, args.repeat, args.bulk)
        print(json.dumps({
            "op": "sgd_update", "weight_shape": [rows, cols],
            "occupied_frac": frac, "bulk": args.bulk,
            "lazy_rsp_ms": round(t_lazy * 1e3, 3),
            "dense_ms": round(t_dense * 1e3, 3),
            "lazy_speedup": round(t_dense / t_lazy, 2),
        }), flush=True)


if __name__ == "__main__":
    main()
