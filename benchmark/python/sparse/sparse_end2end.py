"""End-to-end sparse-linear-model training throughput — TPU counterpart of
the reference's sparse end-to-end benchmark (ref: benchmark/python/sparse/
sparse_end2end.py:1, the linear-classification workload with CSR batches,
row-sparse gradients, and lazy updates).

Workload: logistic regression over a dim-D sparse feature space.  Each
step: CSR batch -> sparse.dot forward -> row-sparse gradient (only the
features the batch touches) -> lazy SGD update.  Reports samples/sec.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..")))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import nd  # noqa: E402
from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray  # noqa: E402


def make_batches(rs, n_batches, batch, dim, nnz):
    batches = []
    for _ in range(n_batches):
        dense = np.zeros((batch, dim), np.float32)
        for i in range(batch):
            cols = rs.choice(dim, size=nnz, replace=False)
            dense[i, cols] = rs.randn(nnz).astype(np.float32)
        y = (rs.rand(batch) > 0.5).astype(np.float32) * 2 - 1
        batches.append((mx.nd.sparse.csr_matrix(dense), dense,
                        mx.nd.array(y)))
    return batches


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--dim", type=int, default=100000)
    p.add_argument("--nnz", type=int, default=64)
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args()
    rs = np.random.RandomState(0)
    batches = make_batches(rs, 4, args.batch_size, args.dim, args.nnz)
    w = mx.nd.zeros((args.dim, 1))
    opt = mx.optimizer.SGD(learning_rate=0.1, lazy_update=True)

    def step(i):
        csr, dense_np, y = batches[i % len(batches)]
        scores = mx.nd.sparse.dot(csr, w).reshape((-1,))
        margin = scores * y
        # logistic grad d/ds -log(sigmoid(margin)) = -y*sigmoid(-margin)
        coef = -(y / (1 + mx.nd.exp(margin)))
        # row-sparse grad: only the feature rows this batch touches
        touched = np.unique(csr.indices.asnumpy().astype(np.int64))
        gw_dense = mx.nd.dot(mx.nd.array(dense_np).T,
                             coef.reshape((-1, 1))) / args.batch_size
        gvals = mx.nd.array(gw_dense.asnumpy()[touched])
        grad = RowSparseNDArray(gvals, mx.nd.array(touched),
                                (args.dim, 1))
        opt.update(0, w, grad, None)
        w.wait_to_read()

    step(0)  # warm-up
    t0 = time.perf_counter()
    for i in range(args.steps):
        step(i)
    dt = time.perf_counter() - t0
    sps = args.steps * args.batch_size / dt
    print(json.dumps({
        "metric": "sparse_linear_train_samples_per_sec",
        "value": round(sps, 1), "unit": "samples/s",
        "batch": args.batch_size, "dim": args.dim, "nnz": args.nnz,
    }), flush=True)


if __name__ == "__main__":
    main()
