"""INT8 vs bf16/f32 op speed on the real chip — the TPU counterpart of the
reference's quantized-op benchmark (ref: benchmark/python/quantization/
benchmark_op.py:1-90).

Times the framework's own op kernels (the fcomputes the nd/symbol front
ends dispatch): ``Convolution``/``FullyConnected`` in bf16 and f32 vs
``_contrib_quantized_conv``/``_contrib_quantized_fully_connected`` whose
int8 operands lower to the MXU's s8×s8→s32 pipeline
(ops/quantization.py:189, preferred_element_type=int32).

Timing discipline (axon tunnel): ``block_until_ready`` does not reliably
sync, so each measurement jits ONE program that scans the op N times with
a data dependency between iterations and fetches a scalar — wall clock
around the host fetch is true device time (same recipe as
docs/perf_analysis_r03.md).

Prints JSON lines; run with --fc for the FullyConnected sweep too.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..")))

import jax                                       # noqa: E402
import jax.numpy as jnp                          # noqa: E402

from incubator_mxnet_tpu.ops.registry import get_op  # noqa: E402

REPEATS = int(os.environ.get("BENCH_REPEATS", "20"))

# reference sweep (benchmark_op.py:73-89): resnet-style conv shapes
CONV_CONFIGS = [
    # (data_shape, kernel, num_filter, pad, stride)
    ((32, 64, 56, 56), (1, 1), 256, (0, 0), (1, 1)),
    ((32, 256, 56, 56), (1, 1), 64, (0, 0), (1, 1)),
    ((32, 256, 56, 56), (1, 1), 128, (0, 0), (2, 2)),
    ((32, 128, 28, 28), (3, 3), 128, (1, 1), (1, 1)),
    ((32, 1024, 14, 14), (1, 1), 256, (0, 0), (1, 1)),
    ((32, 2048, 7, 7), (1, 1), 512, (0, 0), (1, 1)),
]

FC_CONFIGS = [
    # (batch, in_features, num_hidden)
    (32, 2048, 1000),
    (256, 2048, 1000),
    (256, 4096, 4096),
    # large enough to clear the per-iteration latency floor and expose
    # the MXU's double-rate int8 pipeline (the reference shapes above
    # all finish under it on this chip)
    (8192, 8192, 8192),
]


def _timed_scan(fn, *args, repeats=None):
    """Jit a scan of ``fn``; return ms/call.

    Each iteration rebinds the first operand through a SELECT on a
    runtime predicate of the previous output (always false, but not
    provably so) — a data dependency XLA can neither hoist nor
    distribute through the op.  Scalar add/mul perturbations are NOT
    enough: XLA rewrites ``(a+eps)@b`` as ``a@b + eps@b`` and hoists
    ``a@b`` (measured: 8192^3 matmuls "ran" at 2x the chip's dense
    ceiling); an optimization_barrier alone fared even worse.  The
    select costs one elementwise pass per iteration — small vs any op
    worth benchmarking here.  Final scalar fetch = true sync on the
    axon tunnel.
    """
    if repeats is None:
        repeats = REPEATS   # read at call time so tests can shrink it

    @jax.jit
    def many(*a):
        def body(carry, _):
            out = fn(*carry)
            lead = out[0] if isinstance(out, tuple) else out
            probe = lead.reshape(-1)[0].astype(jnp.float32)
            first = jnp.where(probe > 1e30, carry[0] + carry[0].dtype.type(1),
                              carry[0])
            carry = (first,) + carry[1:]
            return carry, probe
        _, probes = jax.lax.scan(body, a, None, length=repeats)
        return probes.sum()

    try:
        float(many(*args))      # compile + warm
    except jax.errors.JaxRuntimeError:
        # XLA's CPU backend mis-lowers some s8 ops inside scan (LLVM
        # verifier failure); fall back to a per-call loop — fine off the
        # axon tunnel where per-dispatch cost is microseconds.
        one = jax.jit(lambda *a: (
            (fn(*a)[0] if isinstance(fn(*a), tuple) else fn(*a))
            .reshape(-1)[0].astype(jnp.float32)))
        float(one(*args))
        t0 = time.perf_counter()
        for _ in range(repeats):
            r = one(*args)
        float(r)
        return (time.perf_counter() - t0) / repeats * 1e3
    t0 = time.perf_counter()
    float(many(*args))          # host fetch = true sync
    return (time.perf_counter() - t0) / repeats * 1e3


def bench_conv(data_shape, kernel, num_filter, pad, stride):
    rs = np.random.RandomState(0)
    conv = get_op("Convolution").fcompute
    qconv = get_op("_contrib_quantized_conv").fcompute
    w_shape = (num_filter, data_shape[1]) + kernel
    x32 = jnp.asarray(rs.normal(0, 0.2, data_shape), jnp.float32)
    w32 = jnp.asarray(rs.normal(0, 1, w_shape), jnp.float32)

    results = {}
    for name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        x, w = x32.astype(dt), w32.astype(dt)
        results[name] = _timed_scan(
            lambda a, b: conv(a, b, None, kernel=kernel, stride=stride,
                              pad=pad, num_filter=num_filter, no_bias=True),
            x, w)

    x8 = jnp.clip(jnp.rint(x32 / jnp.abs(x32).max() * 127), -127,
                  127).astype(jnp.int8)
    w8 = jnp.clip(jnp.rint(w32 / jnp.abs(w32).max() * 127), -127,
                  127).astype(jnp.int8)
    mn = jnp.float32(-1)
    mx_ = jnp.float32(1)
    results["int8"] = _timed_scan(
        lambda a, b: qconv(a, b, mn, mx_, mn, mx_, kernel=kernel,
                           stride=stride, pad=pad, num_filter=num_filter,
                           no_bias=True),
        x8, w8)
    return results


def bench_fc(batch, in_features, num_hidden):
    rs = np.random.RandomState(0)
    fc = get_op("FullyConnected").fcompute
    qfc = get_op("_contrib_quantized_fully_connected").fcompute
    x32 = jnp.asarray(rs.normal(0, 0.2, (batch, in_features)), jnp.float32)
    w32 = jnp.asarray(rs.normal(0, 1, (num_hidden, in_features)), jnp.float32)

    results = {}
    for name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        x, w = x32.astype(dt), w32.astype(dt)
        results[name] = _timed_scan(
            lambda a, b: fc(a, b, num_hidden=num_hidden, no_bias=True),
            x, w)

    x8 = jnp.clip(jnp.rint(x32 * 127), -127, 127).astype(jnp.int8)
    w8 = jnp.clip(jnp.rint(w32 / jnp.abs(w32).max() * 127), -127,
                  127).astype(jnp.int8)
    mn, mx_ = jnp.float32(-1), jnp.float32(1)
    results["int8"] = _timed_scan(
        lambda a, b: qfc(a, b, mn, mx_, mn, mx_, num_hidden=num_hidden,
                         no_bias=True),
        x8, w8)
    return results


def bench_serial_shape(fn, x0, ops, L1=128, L2=512, repeats=3):
    """ms/op at ONE shape by the floor-cancelling serial chain.

    A ``fori_loop`` chains L applications of ``fn`` inside one program;
    each iteration writes a scalar probe of its output INTO the carried
    input via ``dynamic_update_slice`` (element [0..0], sub-ULP value).
    Construction notes — three cheaper dependences all get optimized
    away (verified in compiled HLO):
    * additive/multiplicative scalar perturbation: conv/fc are linear,
      so XLA rewrites ``fn(x0 + s) = fn(x0) + s·fn(1)`` and hoists the
      loop-invariant part (measured: >5 PFLOP/s readings);
    * select-on-predicate rebinding: the select sinks / the op hoists;
    * optimization_barrier: the barrier's unused output is DCE'd and
      the op with it (0 dot ops left in the compiled module).
    DUS on the CARRY is in-place (no per-iteration copy — DUS on the
    invariant x0 forces a full-tensor copy each iteration) and nothing
    distributes through a point update, so the op stays in the loop
    body.  Timing two chain lengths and dividing the extra ops by the
    time DIFFERENCE cancels the per-dispatch transport floor exactly —
    the round-4 sweep's unresolved rows (every dtype ≈ the 0.5 ms/iter
    scan floor) resolve under this method.
    """
    def make(L):
        @jax.jit
        def run(x0, *ops):
            def body(_i, xc):
                out = fn(xc, *ops)
                lead = out[0] if isinstance(out, tuple) else out
                probe = (lead.reshape(-1)[0].astype(jnp.float32)
                         * 1e-20).astype(x0.dtype)
                return jax.lax.dynamic_update_slice(
                    xc, probe.reshape((1,) * x0.ndim),
                    (0,) * x0.ndim)
            xf = jax.lax.fori_loop(0, L, body, x0)
            return xf.reshape(-1)[0].astype(jnp.float32)
        return run

    def best(L):
        prog = make(L)
        float(prog(x0, *ops))          # compile + warm
        b = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(prog(x0, *ops))      # host fetch = true sync
            b = min(b, time.perf_counter() - t0)
        return b

    # adaptive: reference shapes run in tens of µs, so the K-vs-4K time
    # difference must be grown until it clears dispatch jitter (same
    # discipline as benchmark_score.score_steady)
    while True:
        t1, t2 = best(L1), best(L2)
        if t2 - t1 > 0.33 * t1 or L2 >= 32768:
            break
        L1 *= 4
        L2 *= 4
    return max(t2 - t1, 1e-9) / (L2 - L1) * 1e3


def bench_conv_serial(data_shape, kernel, num_filter, pad, stride,
                      L1=128, L2=512):
    """int8-vs-bf16 ratio at one reference conv shape (serial-chain)."""
    rs = np.random.RandomState(0)
    conv = get_op("Convolution").fcompute
    qconv = get_op("_contrib_quantized_conv").fcompute
    w_shape = (num_filter, data_shape[1]) + kernel
    x32 = jnp.asarray(rs.normal(0, 0.2, data_shape), jnp.float32)
    w32 = jnp.asarray(rs.normal(0, 1, w_shape), jnp.float32)

    results = {}
    for name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        results[name] = bench_serial_shape(
            lambda a, b: conv(a, b, None, kernel=kernel, stride=stride,
                              pad=pad, num_filter=num_filter, no_bias=True),
            x32.astype(dt), (w32.astype(dt),), L1, L2)

    x8 = jnp.clip(jnp.rint(x32 / jnp.abs(x32).max() * 127), -127,
                  127).astype(jnp.int8)
    w8 = jnp.clip(jnp.rint(w32 / jnp.abs(w32).max() * 127), -127,
                  127).astype(jnp.int8)
    mn, mx_ = jnp.float32(-1), jnp.float32(1)
    results["int8"] = bench_serial_shape(
        lambda a, b: qconv(a, b, mn, mx_, mn, mx_, kernel=kernel,
                           stride=stride, pad=pad, num_filter=num_filter,
                           no_bias=True)[0].astype(jnp.int8),
        x8, (w8,), L1, L2)
    return results


def bench_fc_serial(batch, in_features, num_hidden, L1=128, L2=512):
    """int8-vs-bf16 ratio at one reference FC shape (serial-chain)."""
    rs = np.random.RandomState(0)
    fc = get_op("FullyConnected").fcompute
    qfc = get_op("_contrib_quantized_fully_connected").fcompute
    x32 = jnp.asarray(rs.normal(0, 0.2, (batch, in_features)), jnp.float32)
    w32 = jnp.asarray(rs.normal(0, 1, (num_hidden, in_features)),
                      jnp.float32)

    results = {}
    for name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        results[name] = bench_serial_shape(
            lambda a, b: fc(a, b, num_hidden=num_hidden, no_bias=True),
            x32.astype(dt), (w32.astype(dt),), L1, L2)

    x8 = jnp.clip(jnp.rint(x32 * 127), -127, 127).astype(jnp.int8)
    w8 = jnp.clip(jnp.rint(w32 / jnp.abs(w32).max() * 127), -127,
                  127).astype(jnp.int8)
    mn, mx_ = jnp.float32(-1), jnp.float32(1)
    results["int8"] = bench_serial_shape(
        lambda a, b: qfc(a, b, mn, mx_, mn, mx_, num_hidden=num_hidden,
                         no_bias=True)[0].astype(jnp.int8),
        x8, (w8,), L1, L2)
    return results


def bench_serial_matmul(n=8192, repeats=30):
    """The conclusive int8-vs-bf16 probe: each iteration's matmul consumes
    the previous OUTPUT (renormalized), a dependency XLA cannot hoist or
    algebraically distribute away — unlike scalar-perturbation chains,
    which XLA rewrites as ``a@b + eps@b`` and hoists (measured 2x-fake
    throughput).  Same methodology for both dtypes, so the RATIO is
    solid even where absolute numbers carry the renorm pass."""
    key = jax.random.PRNGKey(0)
    results = {}
    for name, dt in (("bf16", jnp.bfloat16), ("int8", jnp.int8)):
        if dt == jnp.int8:
            a = (jax.random.normal(key, (n, n)) * 10).astype(jnp.int8)
            b = (jax.random.normal(key, (n, n)) * 10).astype(jnp.int8)

            def mm(x, y):
                return jax.lax.dot_general(
                    x, y, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)

            def norm(o):
                return (o >> 8).astype(jnp.int8)
        else:
            a = jax.random.normal(key, (n, n), dt)
            b = jax.random.normal(key, (n, n), dt)

            def mm(x, y):
                return x @ y

            def norm(o):
                return o * jnp.float32(1e-4).astype(o.dtype)

        @jax.jit
        def many(a, b):
            def body(carry, _):
                out = mm(carry, b)
                return norm(out), out.reshape(-1)[0].astype(jnp.float32)
            _, probes = jax.lax.scan(body, a, None, length=repeats)
            return probes.sum()

        float(many(a, b))
        t0 = time.perf_counter()
        float(many(a, b))
        dt_s = time.perf_counter() - t0
        results[name] = {
            "ms": dt_s / repeats * 1e3,
            "tops": 2 * n ** 3 * repeats / dt_s / 1e12,
        }
    return results


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--fc", action="store_true", help="include FC sweep")
    p.add_argument("--conv", action="store_true", help="include conv sweep")
    p.add_argument("--serial-probe", action="store_true",
                   help="serial-chain 8192^3 matmul: the conclusive "
                        "int8-vs-bf16 ratio")
    p.add_argument("--serial-sweep", action="store_true",
                   help="floor-cancelling serial chain at EVERY reference "
                        "conv/fc shape (VERDICT r4 task 7)")
    p.add_argument("--chain", type=int, default=128,
                   help="serial-sweep L1 (L2 = 4*L1)")
    args = p.parse_args()
    if args.serial_sweep:
        for cfg in CONV_CONFIGS:
            r = bench_conv_serial(*cfg, L1=args.chain, L2=4 * args.chain)
            print(json.dumps({
                "op": "conv_serial", "data_shape": cfg[0], "kernel": cfg[1],
                "num_filter": cfg[2], "stride": cfg[4],
                "f32_ms": round(r["f32"], 4), "bf16_ms": round(r["bf16"], 4),
                "int8_ms": round(r["int8"], 4),
                "int8_vs_f32": round(r["f32"] / r["int8"], 2),
                "int8_vs_bf16": round(r["bf16"] / r["int8"], 2),
            }), flush=True)
        for cfg in FC_CONFIGS[:-1]:     # 8192^3 has the dedicated probe
            r = bench_fc_serial(*cfg, L1=args.chain, L2=4 * args.chain)
            print(json.dumps({
                "op": "fc_serial", "batch": cfg[0], "in_features": cfg[1],
                "num_hidden": cfg[2],
                "f32_ms": round(r["f32"], 4), "bf16_ms": round(r["bf16"], 4),
                "int8_ms": round(r["int8"], 4),
                "int8_vs_f32": round(r["f32"] / r["int8"], 2),
                "int8_vs_bf16": round(r["bf16"] / r["int8"], 2),
            }), flush=True)
        return
    if args.serial_probe:
        r = bench_serial_matmul()
        print(json.dumps({
            "op": "serial_matmul_8192", "bf16_ms": round(r["bf16"]["ms"], 2),
            "int8_ms": round(r["int8"]["ms"], 2),
            "bf16_tflops": round(r["bf16"]["tops"], 1),
            "int8_tops": round(r["int8"]["tops"], 1),
            "int8_vs_bf16": round(r["bf16"]["ms"] / r["int8"]["ms"], 2),
        }), flush=True)
        return      # standalone measurement: no implicit sweeps after it
    do_conv = args.conv or not args.fc
    if do_conv:
        for cfg in CONV_CONFIGS:
            r = bench_conv(*cfg)
            print(json.dumps({
                "op": "conv", "data_shape": cfg[0], "kernel": cfg[1],
                "num_filter": cfg[2], "stride": cfg[4],
                "f32_ms": round(r["f32"], 3), "bf16_ms": round(r["bf16"], 3),
                "int8_ms": round(r["int8"], 3),
                "int8_vs_f32": round(r["f32"] / r["int8"], 2),
                "int8_vs_bf16": round(r["bf16"] / r["int8"], 2),
            }), flush=True)
    if args.fc:
        for cfg in FC_CONFIGS:
            r = bench_fc(*cfg)
            print(json.dumps({
                "op": "fc", "batch": cfg[0], "in": cfg[1], "hidden": cfg[2],
                "f32_ms": round(r["f32"], 3), "bf16_ms": round(r["bf16"], 3),
                "int8_ms": round(r["int8"], 3),
                "int8_vs_f32": round(r["f32"] / r["int8"], 2),
                "int8_vs_bf16": round(r["bf16"] / r["int8"], 2),
            }), flush=True)


if __name__ == "__main__":
    main()
