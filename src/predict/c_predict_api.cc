// C predict API shim.
//
// TPU-native rebirth of the reference's deployment surface
// (include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc): a plain
// C ABI that C/C++ applications link to run inference on a checkpoint
// (symbol JSON + .params) without writing Python.
//
// Where the reference backs this with its C++ graph executor, the
// compute engine here IS XLA driven through the Python package, so the
// shim embeds a CPython interpreter and drives
// incubator_mxnet_tpu through it — the same layering as every other
// binding in the reference (all of Scala/R/Perl go through one C ABI,
// SURVEY §1 layer 8/10).

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Predictor {
  PyObject* obj = nullptr;                 // python-side predictor
  std::vector<float> out_buf;
  std::string err;
};

std::string g_last_error;

void ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // release the GIL the init call leaves held: every entry point takes
    // it via PyGILState_Ensure, and keeping it here would deadlock any
    // OTHER thread's first call into this ABI
    PyEval_SaveThread();
  }
}

void set_err(const std::string& msg) { g_last_error = msg; }

std::string fetch_py_error() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      // PyUnicode_AsUTF8 returns nullptr for non-UTF8-encodable text;
      // keep the fallback message rather than constructing from nullptr
      const char* c = PyUnicode_AsUTF8(s);
      if (c) {
        msg = c;
      } else {
        // non-UTF8-encodable text: AsUTF8 left a UnicodeEncodeError
        // pending, which would poison the next C-API call
        PyErr_Clear();
      }
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

}  // namespace

extern "C" {

const char* MXGetLastError() { return g_last_error.c_str(); }

// Create a predictor from symbol JSON + .params bytes.
// input_keys/input_shape_*: one entry per input, shapes flattened with
// csr-style indptr, exactly like the reference MXPredCreate signature.
int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 int param_size, int /*dev_type*/, int /*dev_id*/,
                 uint32_t num_input_nodes, const char** input_keys,
                 const uint32_t* input_shape_indptr,
                 const uint32_t* input_shape_data, void** out) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = PyImport_ImportModule("incubator_mxnet_tpu.predict");
  if (!mod) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  PyObject* fn = PyObject_GetAttrString(mod, "create_predictor");
  PyObject* shapes = PyDict_New();
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyObject* shp = PyTuple_New(input_shape_indptr[i + 1] -
                                input_shape_indptr[i]);
    for (uint32_t j = input_shape_indptr[i]; j < input_shape_indptr[i + 1];
         ++j) {
      PyTuple_SetItem(shp, j - input_shape_indptr[i],
                      PyLong_FromUnsignedLong(input_shape_data[j]));
    }
    PyDict_SetItemString(shapes, input_keys[i], shp);
    Py_DECREF(shp);
  }
  PyObject* params = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* res = PyObject_CallFunction(fn, "sOO", symbol_json, params,
                                        shapes);
  Py_DECREF(params);
  Py_DECREF(shapes);
  Py_DECREF(fn);
  Py_DECREF(mod);
  if (res) {
    auto* p = new Predictor();
    p->obj = res;
    *out = p;
    rc = 0;
  } else {
    set_err(fetch_py_error());
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredSetInput(void* handle, const char* key, const float* data,
                   uint32_t size) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(float));
  PyObject* res = PyObject_CallMethod(p->obj, "set_input", "sO", key, bytes);
  Py_DECREF(bytes);
  int rc = res ? 0 : -1;
  if (!res) set_err(fetch_py_error());
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return rc;
}

int MXPredForward(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(p->obj, "forward", nullptr);
  int rc = res ? 0 : -1;
  if (!res) set_err(fetch_py_error());
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutputShape(void* handle, uint32_t index, uint32_t** shape_data,
                         uint32_t* shape_ndim) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(p->obj, "output_shape", "I", index);
  if (!res) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(res);
  static thread_local std::vector<uint32_t> shape_buf;
  shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape_buf[i] = static_cast<uint32_t>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(res, i)));
  }
  Py_DECREF(res);
  *shape_data = shape_buf.data();
  *shape_ndim = static_cast<uint32_t>(n);
  PyGILState_Release(gil);
  return 0;
}

int MXPredGetOutput(void* handle, uint32_t index, float* data,
                    uint32_t size) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(p->obj, "output_bytes", "I", index);
  if (!res) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(res, &buf, &len);
  size_t want = static_cast<size_t>(size) * sizeof(float);
  std::memcpy(data, buf, len < static_cast<Py_ssize_t>(want)
                             ? static_cast<size_t>(len) : want);
  Py_DECREF(res);
  PyGILState_Release(gil);
  return 0;
}

int MXPredFree(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
  return 0;
}

}  // extern "C"
