// C train API shim.
//
// The training counterpart of c_predict_api.cc — the ABI the reference's
// non-Python bindings (cpp-package/include/mxnet-cpp/, scala-package/,
// R-package/) all sit on (SURVEY §1 layer 10).  A C/C++ application can
// build a trainer from symbol JSON, feed batches, run fused
// forward+backward+update steps, and read back updated .params bytes —
// no Python source required at the call site.  The compute engine IS XLA
// driven through the Python package, so the shim embeds CPython and
// drives incubator_mxnet_tpu.train_api, the same layering the predict
// shim uses.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Trainer {
  PyObject* obj = nullptr;
};

std::string g_last_error;

void ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // release the GIL the init call leaves held: every entry point takes
    // it via PyGILState_Ensure, and keeping it here would deadlock any
    // OTHER thread's first call into this ABI
    PyEval_SaveThread();
  }
}

void set_err(const std::string& msg) { g_last_error = msg; }

std::string fetch_py_error() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      // PyUnicode_AsUTF8 returns nullptr for non-UTF8-encodable text;
      // keep the fallback message rather than constructing from nullptr
      const char* c = PyUnicode_AsUTF8(s);
      if (c) {
        msg = c;
      } else {
        // non-UTF8-encodable text: AsUTF8 left a UnicodeEncodeError
        // pending, which would poison the next C-API call
        PyErr_Clear();
      }
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

}  // namespace

extern "C" {

const char* MXTrainGetLastError() { return g_last_error.c_str(); }

// Create a trainer from symbol JSON.  input_keys/input_shape_* describe
// every input INCLUDING labels (names ending in "label" bind as label
// slots, the Module convention).  optimizer_params_json e.g.
// "{\"learning_rate\": 0.05}".  param_bytes may be null for fresh
// Xavier-initialized parameters.
int MXTrainerCreate(const char* symbol_json, const char* optimizer,
                    const char* optimizer_params_json,
                    const void* param_bytes, int param_size,
                    uint32_t num_input_nodes, const char** input_keys,
                    const uint32_t* input_shape_indptr,
                    const uint32_t* input_shape_data, void** out) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = PyImport_ImportModule("incubator_mxnet_tpu.train_api");
  if (!mod) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  PyObject* fn = PyObject_GetAttrString(mod, "create_trainer");
  PyObject* shapes = PyDict_New();
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyObject* shp = PyTuple_New(input_shape_indptr[i + 1] -
                                input_shape_indptr[i]);
    for (uint32_t j = input_shape_indptr[i]; j < input_shape_indptr[i + 1];
         ++j) {
      PyTuple_SetItem(shp, j - input_shape_indptr[i],
                      PyLong_FromUnsignedLong(input_shape_data[j]));
    }
    PyDict_SetItemString(shapes, input_keys[i], shp);
    Py_DECREF(shp);
  }
  PyObject* params =
      param_bytes && param_size > 0
          ? PyBytes_FromStringAndSize(static_cast<const char*>(param_bytes),
                                      param_size)
          : (Py_INCREF(Py_None), Py_None);
  PyObject* res = PyObject_CallFunction(
      fn, "sOssO", symbol_json, shapes, optimizer,
      optimizer_params_json ? optimizer_params_json : "", params);
  Py_DECREF(params);
  Py_DECREF(shapes);
  Py_DECREF(fn);
  Py_DECREF(mod);
  if (res) {
    auto* t = new Trainer();
    t->obj = res;
    *out = t;
    rc = 0;
  } else {
    set_err(fetch_py_error());
  }
  PyGILState_Release(gil);
  return rc;
}

int MXTrainerSetInput(void* handle, const char* key, const float* data,
                      uint32_t size) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(float));
  PyObject* res = PyObject_CallMethod(t->obj, "set_input", "sO", key, bytes);
  int rc = res ? 0 : -1;
  if (!res) set_err(fetch_py_error());
  Py_XDECREF(res);
  Py_DECREF(bytes);
  PyGILState_Release(gil);
  return rc;
}

// One fused training step on the staged inputs: forward + backward +
// optimizer update.  *loss receives the batch loss.
int MXTrainerStep(void* handle, float* loss) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "step", nullptr);
  int rc = -1;
  if (res) {
    *loss = static_cast<float>(PyFloat_AsDouble(res));
    Py_DECREF(res);
    rc = 0;
  } else {
    set_err(fetch_py_error());
  }
  PyGILState_Release(gil);
  return rc;
}

int MXTrainerForward(void* handle) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "forward", nullptr);
  int rc = res ? 0 : -1;
  if (!res) set_err(fetch_py_error());
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return rc;
}

int MXTrainerGetOutputShape(void* handle, uint32_t index,
                            uint32_t** shape_data, uint32_t* shape_ndim) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "output_shape", "I", index);
  if (!res) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(res);
  static thread_local std::vector<uint32_t> shape_buf;
  shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape_buf[i] = static_cast<uint32_t>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(res, i)));
  }
  Py_DECREF(res);
  *shape_data = shape_buf.data();
  *shape_ndim = static_cast<uint32_t>(n);
  PyGILState_Release(gil);
  return 0;
}

int MXTrainerGetOutput(void* handle, uint32_t index, float* data,
                       uint32_t size) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "output_bytes", "I", index);
  if (!res) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(res, &buf, &len);
  size_t want = static_cast<size_t>(size) * sizeof(float);
  std::memcpy(data, buf,
              len < static_cast<Py_ssize_t>(want) ? static_cast<size_t>(len)
                                                  : want);
  Py_DECREF(res);
  PyGILState_Release(gil);
  return 0;
}

// Serialized .params (MXNet binary) of the CURRENT parameters.  The
// returned pointer stays valid until the next call on any trainer.
int MXTrainerSaveParams(void* handle, const char** out_bytes,
                        uint64_t* out_size) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "save_params", nullptr);
  if (!res) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(res, &buf, &len);
  static thread_local std::string params_buf;
  params_buf.assign(buf, static_cast<size_t>(len));
  Py_DECREF(res);
  *out_bytes = params_buf.data();
  *out_size = static_cast<uint64_t>(params_buf.size());
  PyGILState_Release(gil);
  return 0;
}

int MXTrainerFree(void* handle) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(t->obj);
  PyGILState_Release(gil);
  delete t;
  return 0;
}

// ---------------------------------------------------------------------------
// Data iterators (the reference's MXDataIterCreateIter/Next/GetData/GetLabel
// C API family, src/c_api/c_api.cc — over the Python io registry).
// ---------------------------------------------------------------------------

int MXDataIterCreate(const char* name, const char* params_json, void** out) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = PyImport_ImportModule("incubator_mxnet_tpu.train_api");
  if (mod) {
    PyObject* res = PyObject_CallMethod(mod, "create_data_iter", "ss", name,
                                        params_json ? params_json : "{}");
    if (res) {
      auto* t = new Trainer();
      t->obj = res;
      *out = t;
      rc = 0;
    } else {
      set_err(fetch_py_error());
    }
    Py_DECREF(mod);
  } else {
    set_err(fetch_py_error());
  }
  PyGILState_Release(gil);
  return rc;
}

// *out_has_next = 1 and the batch is staged, or 0 at epoch end.
int MXDataIterNext(void* handle, int* out_has_next) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "next", nullptr);
  int rc = -1;
  if (res) {
    *out_has_next = static_cast<int>(PyLong_AsLong(res));
    Py_DECREF(res);
    rc = 0;
  } else {
    set_err(fetch_py_error());
  }
  PyGILState_Release(gil);
  return rc;
}

int MXDataIterReset(void* handle) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "reset", nullptr);
  int rc = res ? 0 : -1;
  if (!res) set_err(fetch_py_error());
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return rc;
}

namespace {

// fetch "<which>_bytes" into the shared blob + "<which>_shape" into the
// shared shape buffer; pointers stay valid until the next fetch on any
// iterator (single-reader convention, same as MXTrainerSaveParams)
int fetch_batch_part(Trainer* t, const char* which, const float** out_data,
                     const uint32_t** out_shape, uint32_t* out_ndim) {
  PyGILState_STATE gil = PyGILState_Ensure();
  std::string meth = std::string(which) + "_bytes";
  PyObject* res = PyObject_CallMethod(t->obj, meth.c_str(), nullptr);
  if (!res) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(res, &buf, &len);
  static thread_local std::string data_buf;
  data_buf.assign(buf, static_cast<size_t>(len));
  Py_DECREF(res);

  std::string smeth = std::string(which) + "_shape";
  PyObject* shp = PyObject_CallMethod(t->obj, smeth.c_str(), nullptr);
  if (!shp) {
    set_err(fetch_py_error());
    PyGILState_Release(gil);
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(shp);
  static thread_local std::vector<uint32_t> shape_buf;
  shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    shape_buf[i] = static_cast<uint32_t>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shp, i)));
  }
  Py_DECREF(shp);
  *out_data = reinterpret_cast<const float*>(data_buf.data());
  *out_shape = shape_buf.data();
  *out_ndim = static_cast<uint32_t>(n);
  PyGILState_Release(gil);
  return 0;
}

}  // namespace

int MXDataIterGetData(void* handle, const float** out_data,
                      const uint32_t** out_shape, uint32_t* out_ndim) {
  return fetch_batch_part(static_cast<Trainer*>(handle), "data", out_data,
                          out_shape, out_ndim);
}

int MXDataIterGetLabel(void* handle, const float** out_data,
                       const uint32_t** out_shape, uint32_t* out_ndim) {
  return fetch_batch_part(static_cast<Trainer*>(handle), "label", out_data,
                          out_shape, out_ndim);
}

int MXDataIterFree(void* handle) { return MXTrainerFree(handle); }

// ---------------------------------------------------------------------------
// Eval metrics (the registry the Python fit loop uses, by name).
// ---------------------------------------------------------------------------

int MXMetricCreate(const char* name, void** out) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = PyImport_ImportModule("incubator_mxnet_tpu.train_api");
  if (mod) {
    PyObject* res = PyObject_CallMethod(mod, "create_metric", "s", name);
    if (res) {
      auto* t = new Trainer();
      t->obj = res;
      *out = t;
      rc = 0;
    } else {
      set_err(fetch_py_error());
    }
    Py_DECREF(mod);
  } else {
    set_err(fetch_py_error());
  }
  PyGILState_Release(gil);
  return rc;
}

int MXMetricUpdate(void* handle, const float* label, const uint32_t* lshape,
                   uint32_t lndim, const float* pred, const uint32_t* pshape,
                   uint32_t pndim) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  size_t ln = 1, pn = 1;
  PyObject* lsh = PyTuple_New(lndim);
  for (uint32_t i = 0; i < lndim; ++i) {
    ln *= lshape[i];
    PyTuple_SetItem(lsh, i, PyLong_FromUnsignedLong(lshape[i]));
  }
  PyObject* psh = PyTuple_New(pndim);
  for (uint32_t i = 0; i < pndim; ++i) {
    pn *= pshape[i];
    PyTuple_SetItem(psh, i, PyLong_FromUnsignedLong(pshape[i]));
  }
  PyObject* lb = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(label), ln * sizeof(float));
  PyObject* pb = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(pred), pn * sizeof(float));
  PyObject* res = PyObject_CallMethod(t->obj, "update", "OOOO", lb, lsh, pb,
                                      psh);
  int rc = res ? 0 : -1;
  if (!res) set_err(fetch_py_error());
  Py_XDECREF(res);
  Py_DECREF(lb);
  Py_DECREF(pb);
  Py_DECREF(lsh);
  Py_DECREF(psh);
  PyGILState_Release(gil);
  return rc;
}

int MXMetricGet(void* handle, float* out_value) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "get", nullptr);
  int rc = -1;
  if (res) {
    *out_value = static_cast<float>(PyFloat_AsDouble(res));
    Py_DECREF(res);
    rc = 0;
  } else {
    set_err(fetch_py_error());
  }
  PyGILState_Release(gil);
  return rc;
}

int MXMetricReset(void* handle) {
  auto* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallMethod(t->obj, "reset", nullptr);
  int rc = res ? 0 : -1;
  if (!res) set_err(fetch_py_error());
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return rc;
}

int MXMetricFree(void* handle) { return MXTrainerFree(handle); }

}  // extern "C"
