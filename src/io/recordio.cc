// Native RecordIO data plane.
//
// TPU-native rebirth of the reference's C++ IO layer (dmlc-core
// recordio.h + src/io/iter_image_recordio_2.cc's threaded record
// reader): the same magic-framed wire format
//   [kMagic:4B][cflag:3b|len:29b:4B][payload][pad to 4B]
// read and written natively, plus a background-thread prefetching
// reader (bounded ring of parsed records) so record parsing and file IO
// overlap Python-side decode — the role ThreadedIter played for
// ImageRecordIter2 (SURVEY §2.1 Data IO).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this
// toolchain); incubator_mxnet_tpu/recordio.py picks it up when built.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Record {
  std::vector<char> data;
};

// ---------------------------------------------------------------------------
// plain sequential reader/writer
// ---------------------------------------------------------------------------

struct Reader {
  FILE* fp = nullptr;
  std::vector<char> buf;   // last record, handed to the caller
};

struct Writer {
  FILE* fp = nullptr;
};

bool read_one(FILE* fp, std::vector<char>* out) {
  out->clear();
  uint32_t head[2];
  for (;;) {
    if (std::fread(head, sizeof(uint32_t), 2, fp) != 2) return false;
    if (head[0] != kMagic) return false;
    uint32_t cflag = head[1] >> 29;
    uint32_t len = head[1] & ((1u << 29) - 1);
    size_t off = out->size();
    out->resize(off + len);
    if (len && std::fread(out->data() + off, 1, len, fp) != len) return false;
    uint32_t pad = (4 - len % 4) % 4;
    if (pad) std::fseek(fp, pad, SEEK_CUR);
    // cflag: 0 whole, 1 begin, 2 middle, 3 end of a split record
    if (cflag == 0 || cflag == 3) return true;
  }
}

}  // namespace

extern "C" {

void* MXTPURecordIOReaderCreate(const char* path) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return nullptr;
  auto* r = new Reader();
  r->fp = fp;
  return r;
}

// 1 = record available (out/size valid until the next call), 0 = EOF/error
int MXTPURecordIOReaderNext(void* handle, const char** out, uint64_t* size) {
  auto* r = static_cast<Reader*>(handle);
  if (!read_one(r->fp, &r->buf)) return 0;
  *out = r->buf.data();
  *size = r->buf.size();
  return 1;
}

void MXTPURecordIOReaderSeek(void* handle, uint64_t pos) {
  auto* r = static_cast<Reader*>(handle);
  std::fseek(r->fp, static_cast<long>(pos), SEEK_SET);
}

uint64_t MXTPURecordIOReaderTell(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  return static_cast<uint64_t>(std::ftell(r->fp));
}

void MXTPURecordIOReaderFree(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (r->fp) std::fclose(r->fp);
  delete r;
}

void* MXTPURecordIOWriterCreate(const char* path) {
  FILE* fp = std::fopen(path, "wb");
  if (!fp) return nullptr;
  auto* w = new Writer();
  w->fp = fp;
  return w;
}

uint64_t MXTPURecordIOWriterTell(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  return static_cast<uint64_t>(std::ftell(w->fp));
}

namespace {

int write_chunk(FILE* fp, uint32_t cflag, const char* data, uint64_t size) {
  uint32_t head[2] = {kMagic,
                      (cflag << 29) | static_cast<uint32_t>(size)};
  if (std::fwrite(head, sizeof(uint32_t), 2, fp) != 2) return -1;
  if (size && std::fwrite(data, 1, size, fp) != size) return -1;
  uint32_t pad = (4 - size % 4) % 4;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && std::fwrite(zeros, 1, pad, fp) != pad) return -1;
  return 0;
}

}  // namespace

int MXTPURecordIOWriterWrite(void* handle, const char* data, uint64_t size) {
  auto* w = static_cast<Writer*>(handle);
  // payloads that overflow the 29-bit length field split into
  // begin(1)/middle(2)/end(3) parts — the dmlc-core convention the
  // reader's accumulate-until-cflag-0-or-3 loop already understands;
  // a single-chunk write would silently corrupt the length into cflag
  constexpr uint64_t kMaxLen = (1u << 29) - 1;
  if (size <= kMaxLen) {
    return write_chunk(w->fp, 0, data, size);
  }
  uint64_t off = 0;
  while (off < size) {
    uint64_t n = size - off < kMaxLen ? size - off : kMaxLen;
    uint32_t cflag = off == 0 ? 1u : (off + n >= size ? 3u : 2u);
    if (write_chunk(w->fp, cflag, data + off, n) != 0) return -1;
    off += n;
  }
  return 0;
}

void MXTPURecordIOWriterFree(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (w->fp) std::fclose(w->fp);
  delete w;
}

// ---------------------------------------------------------------------------
// threaded prefetching reader (ThreadedIter reborn)
// ---------------------------------------------------------------------------

struct PrefetchReader {
  FILE* fp = nullptr;
  std::thread worker;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::deque<Record> queue;
  size_t capacity = 16;
  bool done = false;        // producer finished (EOF)
  bool stop = false;        // consumer requested shutdown
  Record current;           // record handed to the caller

  void run() {
    std::vector<char> buf;
    for (;;) {
      if (!read_one(fp, &buf)) break;
      Record rec;
      rec.data.swap(buf);
      std::unique_lock<std::mutex> lk(mu);
      not_full.wait(lk, [&] { return queue.size() < capacity || stop; });
      if (stop) return;
      queue.emplace_back(std::move(rec));
      not_empty.notify_one();
    }
    std::unique_lock<std::mutex> lk(mu);
    done = true;
    not_empty.notify_all();
  }
};

void* MXTPUPrefetchReaderCreate(const char* path, uint64_t capacity) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return nullptr;
  auto* p = new PrefetchReader();
  p->fp = fp;
  if (capacity) p->capacity = capacity;
  p->worker = std::thread([p] { p->run(); });
  return p;
}

int MXTPUPrefetchReaderNext(void* handle, const char** out, uint64_t* size) {
  auto* p = static_cast<PrefetchReader*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->not_empty.wait(lk, [&] { return !p->queue.empty() || p->done; });
  if (p->queue.empty()) return 0;
  p->current = std::move(p->queue.front());
  p->queue.pop_front();
  p->not_full.notify_one();
  *out = p->current.data.data();
  *size = p->current.data.size();
  return 1;
}

void MXTPUPrefetchReaderFree(void* handle) {
  auto* p = static_cast<PrefetchReader*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
    p->not_full.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  if (p->fp) std::fclose(p->fp);
  delete p;
}

}  // extern "C"
