"""Benchmark: decoder-transformer LM training — tokens/sec and MFU on one chip.

The compute-bound counterpart to bench.py's (HBM-bound, see
docs/perf_analysis_r03.md) ResNet-50: a GPT-style decoder LM at
d_model 2048 where >90% of the FLOPs are large bf16 matmuls, so the
measured model-FLOPs utilisation (MFU) is a direct statement about how
well the framework's fused train step feeds the MXU.

Model: learned token+position embeddings -> N pre-norm decoder blocks
(causal MultiHeadAttention flash kernel + 4x FFN) -> vocab projection.
Whole train step (fwd + CE loss + bwd + SGD-momentum update, bf16 compute
with f32 master weights) is ONE jitted XLA program via
DataParallelTrainer.

MFU convention (PaLM appendix B): model FLOPs = 6 * N * tokens with N =
NON-embedding parameters (the input token/position tables are gathers —
0 matmul FLOPs — so counting them would inflate MFU ~7% at the default
config; the vocab-projection head IS a matmul and stays in N), plus the
causal attention term 6 * S * tokens * d_model (QK^T and PV, halved for
causality, x3 for fwd+bwd) — flash recompute in the backward is NOT
counted (it is overhead, not model work).  The JSON reports both
conventions: "mfu" (non-embedding, headline) and "mfu_all_params" (the
pre-round-5 number, for comparability).

Prints ONE JSON line:
  {"metric": "transformer_lm_train_tokens_per_sec", "value": N,
   "unit": "tokens/s", "mfu": ..., "tflops_per_sec": ..., ...}
"""
import json
import os
import time

import numpy as np

# peak dense bf16 TFLOP/s by device_kind (public spec sheets)
_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def model_flops_per_step(n_params, tokens, seq_len, d_model, n_layers=1):
    """PaLM-style model FLOPs for one train step (fwd+bwd)."""
    dense = 6.0 * n_params * tokens
    # per-LAYER causal attention matmuls: 0.5 * 12 * S * T * d
    attn = 6.0 * seq_len * tokens * d_model * n_layers
    return dense + attn


def main():
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.parallel import make_mesh, DataParallelTrainer

    # Default config: measured 61.7% MFU on v5e (docs/perf_analysis_r04.md
    # — d_model 4096 puts every matmul on a shape the MXU sustains; d 2048
    # shapes cap at ~100-112 TFLOP/s and ~50% MFU end-to-end).
    vocab = int(os.environ.get("BENCH_VOCAB", "16384"))
    d_model = int(os.environ.get("BENCH_DMODEL", "4096"))
    n_heads = int(os.environ.get("BENCH_HEADS", "32"))
    d_ffn = int(os.environ.get("BENCH_FFN", str(4 * d_model)))
    n_layers = int(os.environ.get("BENCH_LAYERS", "4"))
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    n_steps = int(os.environ.get("BENCH_STEPS", "15"))

    mx.random.seed(0)

    class DecoderBlock(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.ln1 = nn.LayerNorm()
                # fused_qkv measured slightly SLOWER end-to-end here
                # (405.8 vs 383.1 ms/step at d=4096): XLA already
                # schedules the three projections well at this shape
                self.attn = nn.MultiHeadAttention(d_model, n_heads,
                                                  causal=True, use_bias=False)
                self.ln2 = nn.LayerNorm()
                self.fc1 = nn.Dense(d_ffn, flatten=False, in_units=d_model,
                                    use_bias=False)
                self.fc2 = nn.Dense(d_model, flatten=False, in_units=d_ffn,
                                    use_bias=False)

        def hybrid_forward(self, F, x):
            x = x + self.attn(self.ln1(x))
            h = F.Activation(self.fc1(self.ln2(x)), act_type="relu")
            return x + self.fc2(h)

    class TransformerLM(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.embed = nn.Embedding(vocab, d_model)
                self.pos_embed = self.params.get(
                    "pos_embed", shape=(seq_len, d_model),
                    init=mx.init.Normal(0.02))
                self.blocks = nn.HybridSequential(prefix="blocks_")
                with self.blocks.name_scope():
                    for _ in range(n_layers):
                        self.blocks.add(DecoderBlock())
                self.ln_f = nn.LayerNorm()
                self.head = nn.Dense(vocab, flatten=False, in_units=d_model,
                                     use_bias=False)

        def hybrid_forward(self, F, tokens, pos_embed):
            h = self.embed(tokens) + F.expand_dims(pos_embed, axis=0)
            h = self.blocks(h)
            return self.head(self.ln_f(h))

    import jax.numpy as jnp

    @mx.init.register
    class HostXavier(mx.init.Xavier):
        """Xavier generated on the HOST, one upload per parameter.

        Over the axon tunnel every device dispatch costs ~1 s once any jit
        has run; device-RNG init of a ~1B-param model takes minutes, while
        host numpy + a pre-jit device_put moves the same bytes in seconds
        (docs/perf_analysis_r04.md).  Math matches Xavier gaussian/avg.
        """

        def __init__(self, **kwargs):
            kwargs.setdefault("rnd_type", "gaussian")
            super().__init__(**kwargs)
            self._rs = np.random.RandomState(0)

        def _init_weight(self, name, arr):
            shape = arr.shape
            hw = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
            factor = (shape[1] * hw + shape[0] * hw) / 2.0
            scale = np.sqrt(self.magnitude / factor)
            arr._write(jnp.asarray(
                self._rs.standard_normal(shape).astype(np.float32) * scale))

        def _init_default(self, name, arr):
            arr._write(jnp.asarray(
                self._rs.standard_normal(arr.shape).astype(np.float32)
                * 0.02))

    net = TransformerLM()
    net.pos_embed.init = None          # route through HostXavier._init_default
    net.initialize(HostXavier())

    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer="sgd", optimizer_params={"learning_rate": 0.05,
                                           "momentum": 0.9},
        mesh=mesh, dtype="bfloat16")

    rs = np.random.RandomState(0)
    # int32 token ids: the trainer keeps wide-integer inputs exact (no
    # bf16 rounding of indices); labels stay f32 for the pick-based loss
    x = mx.nd.array(rs.randint(0, vocab, (batch, seq_len)), dtype=np.int32)
    y = mx.nd.array(rs.randint(0, vocab, (batch, seq_len)).astype(np.float32))

    for _ in range(3):
        loss = trainer.step(x, y)
    float(np.asarray(loss))

    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = trainer.step(x, y)
    final = float(np.asarray(loss))  # host fetch = true sync point
    dt = time.perf_counter() - t0
    assert np.isfinite(final), "transformer bench loss went non-finite"

    n_params = int(sum(int(np.prod(p.shape))
                       for p in net.collect_params().values()))
    # input embedding + position table are gathers, not matmuls: exclude
    # from the FLOP model (PaLM appendix B non-embedding convention)
    n_embed = vocab * d_model + seq_len * d_model
    n_matmul = n_params - n_embed
    tokens = batch * seq_len
    tok_s = n_steps * tokens / dt
    flops = model_flops_per_step(n_matmul, tokens, seq_len, d_model,
                                 n_layers)
    flops_all = model_flops_per_step(n_params, tokens, seq_len, d_model,
                                     n_layers)
    achieved_tflops = flops * n_steps / dt / 1e12
    kind = jax.devices()[0].device_kind
    peak = float(os.environ.get("BENCH_PEAK_TFLOPS",
                                _PEAK_TFLOPS.get(kind, 0.0)))
    mfu = achieved_tflops / peak if peak else None
    mfu_all = (flops_all * n_steps / dt / 1e12) / peak if peak else None

    print(json.dumps({
        "metric": "transformer_lm_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_all_params": round(mfu_all, 4) if mfu_all is not None else None,
        "tflops_per_sec": round(achieved_tflops, 2),
        "peak_tflops": peak, "device_kind": kind,
        "n_params": n_params, "n_params_non_embedding": n_matmul,
        "d_model": d_model, "n_layers": n_layers, "n_heads": n_heads,
        "d_ffn": d_ffn, "seq_len": seq_len, "batch": batch,
        "step_ms": round(dt / n_steps * 1e3, 2),
    }))


if __name__ == "__main__":
    main()
