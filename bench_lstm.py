"""Benchmark: Gluon LSTM language model — tokens/sec on one chip.

The second metric named by BASELINE.json ("Gluon LSTM tokens/sec", config
"Gluon LSTM language model (example/gluon, hybridize())").  Workload: the
classic word-LM shape — embedding → multi-layer LSTM (the lax.scan fused
kernel standing in for cudnnRNNForwardTraining) → vocab projection,
trained end-to-end (forward + CE loss + backward + SGD update) as ONE
jitted XLA program via DataParallelTrainer, bf16 compute with f32 master
weights.

Prints ONE JSON line:
  {"metric": "gluon_lstm_train_tokens_per_sec", "value": N,
   "unit": "tokens/s", ...}
"""
import json
import os
import time

import numpy as np


def main():
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn, rnn
    from incubator_mxnet_tpu.parallel import make_mesh, DataParallelTrainer

    vocab = int(os.environ.get("BENCH_VOCAB", "10000"))
    embed = int(os.environ.get("BENCH_EMBED", "512"))
    hidden = int(os.environ.get("BENCH_HIDDEN", "512"))
    layers = 2
    seq_len = int(os.environ.get("BENCH_SEQ", "128"))
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    n_steps = int(os.environ.get("BENCH_STEPS", "20"))

    mx.random.seed(0)

    class WordLM(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.embed = nn.Embedding(vocab, embed)
                self.lstm = rnn.LSTM(hidden, num_layers=layers,
                                     layout="NTC", input_size=embed)
                self.proj = nn.Dense(vocab, flatten=False,
                                     in_units=hidden)

        def hybrid_forward(self, F, x):
            h = self.embed(x)
            h = self.lstm(h)
            return self.proj(h)

    net = WordLM()
    net.initialize(mx.init.Xavier())

    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer="sgd", optimizer_params={"learning_rate": 0.5},
        mesh=mesh, dtype="bfloat16")

    rs = np.random.RandomState(0)
    # int32 token ids stay exact through the trainer's mixed-precision
    # input cast (bf16 would round large vocab ids); labels f32 for pick
    x = mx.nd.array(rs.randint(0, vocab, (batch, seq_len)), dtype=np.int32)
    y = mx.nd.array(rs.randint(0, vocab, (batch, seq_len)).astype(np.float32))

    for _ in range(3):
        loss = trainer.step(x, y)
    float(np.asarray(loss))

    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = trainer.step(x, y)
    final = float(np.asarray(loss))
    dt = time.perf_counter() - t0
    assert np.isfinite(final), "lstm bench loss went non-finite"

    tok_s = n_steps * batch * seq_len / dt
    print(json.dumps({
        "metric": "gluon_lstm_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "batch": batch, "seq_len": seq_len,
        "hidden": hidden, "layers": layers, "vocab": vocab,
    }))


if __name__ == "__main__":
    main()
