"""graftserve load generator — p50/p99 latency vs offered QPS, and
batched-vs-serial throughput (ISSUE 11, the ROADMAP serving scenario).

The model under load is a small MLP served two ways:

* **serial** — the pre-graftserve path: one ``Module.predict`` call per
  request (per-op executor replay at batch 1), the baseline every
  framework ships first;
* **batched** — the graftserve runtime: requests enqueue into the
  dynamic batcher, assemble under GRAFT_SERVE_MAX_BATCH /
  GRAFT_SERVE_MAX_WAIT_MS, and dispatch as ONE compiled call per padded
  shape bucket (default ``exact`` batch mode: every row IS the
  unbatched graph, so responses are asserted BIT-EQUAL to the serial
  ``Module.predict`` outputs before any throughput number is reported
  — the PR 4 oracle discipline).

Sections (all land in ONE BENCH JSON line):

* ``serve_serial_qps`` / ``serve_batched_qps`` /
  ``serve_batched_speedup`` — closed-loop: K client threads submitting
  back-to-back; the speedup bar is ≥ 3x (asserted);
* ``serve_qps_points`` — open-loop: a paced arrival stream at ≥ 3
  offered rates (fractions of the measured capacity), reporting
  p50/p99 end-to-end latency and the achieved rate at each point;
* mean SLO component split (queue_wait/batch_assembly/device_compute/
  host_io) over the run, the ``graft_serve_*`` metrics snapshot and the
  flight-recorder status.

``--smoke`` runs the same sections at small counts for the lint tier.
"""
import json
import sys
import threading
import time

import numpy as np

DIN, DHID, DOUT = 16, 32, 8


def _build_module(batch=1):
    """The bench model as a bound inference Module (symbol path — the
    serial baseline AND the serving source, so both serve the exact
    same weights)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import symbol as sym
    from incubator_mxnet_tpu.module import Module

    net = sym.FullyConnected(sym.var("data"), num_hidden=DHID, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=DOUT, name="fc2")
    net = sym.tanh(net, name="out")
    mod = Module(symbol=net, data_names=("data",), label_names=None,
                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (batch, DIN))], label_shapes=None,
             for_training=False)
    mod.init_params(initializer=mx.initializer.Uniform(0.07))
    return mod


def _serial_qps(mod, xs, iters):
    """The per-request Module.predict loop (one forward per request)."""
    import incubator_mxnet_tpu as mx
    outs = []
    mod.predict(mx.nd.array(xs[0][None]))           # warm the executor
    t0 = time.perf_counter()
    for i in range(iters):
        outs.append(mod.predict(
            mx.nd.array(xs[i % len(xs)][None])).asnumpy()[0])
    dt = time.perf_counter() - t0
    return iters / dt, outs


def _closed_loop(srv, name, xs, n_clients, per_client):
    """K threads each submitting back-to-back; returns (qps, outputs in
    submit order per client)."""
    outs = [[None] * per_client for _ in range(n_clients)]

    def client(k):
        futs = []
        for i in range(per_client):
            futs.append(srv.submit(name, xs[(k * per_client + i) % len(xs)]))
        for i, f in enumerate(futs):
            outs[k][i] = f.get(timeout=120.0)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    return n_clients * per_client / dt, outs


def _open_loop(srv, name, xs, rate, n):
    """Paced arrivals at ``rate`` req/s; returns the latency/achieved
    stats for one offered-QPS point."""
    futs = []
    t0 = time.perf_counter()
    for i in range(n):
        target = t0 + i / rate
        while True:
            now = time.perf_counter()
            if now >= target:
                break
            time.sleep(min(target - now, 1e-3))
        futs.append(srv.submit(name, xs[i % len(xs)]))
    for f in futs:
        f.get(timeout=120.0)
    dt = time.perf_counter() - t0
    walls = sorted(f.record["wall_s"] for f in futs)
    return {
        "offered_qps": round(rate, 1),
        "achieved_qps": round(n / dt, 1),
        "p50_ms": round(walls[len(walls) // 2] * 1e3, 3),
        "p99_ms": round(walls[min(int(len(walls) * 0.99),
                                  len(walls) - 1)] * 1e3, 3),
    }


def run(smoke=False):
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import serving
    from incubator_mxnet_tpu.serving import slo

    serial_iters = 40 if smoke else 200
    n_clients = 2 if smoke else 4
    per_client = 48 if smoke else 400
    open_n = 40 if smoke else 200

    rs = np.random.RandomState(0)
    xs = [rs.randn(DIN).astype(np.float32) for _ in range(64)]
    mod = _build_module()

    # -- serial baseline: the per-request Module.predict loop ------------
    serial_qps, serial_outs = _serial_qps(mod, xs, serial_iters)

    slo.reset()
    with serving.Server(max_batch=32, max_wait_ms=2) as srv:
        srv.load("bench", module=mod)
        srv.warmup("bench", xs[0])

        # -- parity gate: batched == the serial unbatched forward --------
        futs = [srv.submit("bench", x) for x in xs]
        served = [f.get(timeout=120.0) for f in futs]
        for i, (x, y) in enumerate(zip(xs, served)):
            ref = mod.predict(mx.nd.array(x[None])).asnumpy()[0]
            assert y.tobytes() == ref.tobytes(), \
                "serving output %d diverged from the unbatched " \
                "Module.predict forward" % i
        parity = True

        # -- closed-loop throughput --------------------------------------
        batched_qps, outs = _closed_loop(srv, "bench", xs, n_clients,
                                         per_client)
        # spot-check closed-loop rows against the serial oracle
        for j in range(min(len(xs), 16)):
            ref = mod.predict(mx.nd.array(xs[j][None])).asnumpy()[0]
            assert outs[0][j].tobytes() == ref.tobytes(), \
                "closed-loop output %d diverged from Module.predict" % j
        speedup = batched_qps / serial_qps

        # -- open-loop latency vs offered QPS ----------------------------
        cap = batched_qps
        rates = [max(cap * f, 20.0) for f in (0.2, 0.5, 0.9)]
        points = [_open_loop(srv, "bench", xs, rate, open_n)
                  for rate in rates]

        summary = slo.summary()
        stats = srv.stats()

    result = {
        "metric": "serving",
        "backend": jax.default_backend(),
        "model": "mlp_%d_%d_%d" % (DIN, DHID, DOUT),
        "serve_parity": parity,
        "serve_batch_mode": serving.serve_batch_mode(),
        "serve_serial_qps": round(serial_qps, 1),
        "serve_batched_qps": round(batched_qps, 1),
        "serve_batched_speedup": round(speedup, 2),
        "serve_qps_points": points,
        "serve_mean_batch_size": summary.get("mean_batch_size"),
        "serve_components_ms": summary.get("components_ms"),
        "serve_p50_ms": summary.get("p50_ms"),
        "serve_p99_ms": summary.get("p99_ms"),
        "serve_registry": stats["registry"],
        "metrics": {k: v for k, v in
                    mx.telemetry.compact_snapshot().items()
                    if k.startswith("graft_serve")},
        "blackbox": mx.telemetry.blackbox.stats(),
    }
    assert speedup >= 3.0, \
        "batched dispatch only %.2fx the serial Module.predict loop " \
        "(bar: 3x)" % speedup
    print(json.dumps(result))


if __name__ == "__main__":
    run(smoke="--smoke" in sys.argv[1:])
