"""Gluon tests (parity model: tests/python/unittest/test_gluon.py)."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, autograd
from incubator_mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier", ctx=mx.cpu(0))
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert p.list_data()[0].shape == (10, 10)


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu(0))
    params.save("/tmp/test_paramdict.params")
    params.load("/tmp/test_paramdict.params", mx.cpu(0))


def test_parameter_sharing():
    class Net(gluon.Block):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.dense0 = nn.Dense(5, in_units=5)
                self.dense1 = nn.Dense(5, in_units=5)

        def forward(self, x):
            return self.dense1(self.dense0(x))

    net1 = Net(prefix="net1_")
    net2 = Net(prefix="net2_", params=net1.collect_params())
    net1.collect_params().initialize()
    net2(mx.nd.zeros((3, 5)))
    net1.save_params("/tmp/net1.params")
    net3 = Net(prefix="net3_")
    net3.load_params("/tmp/net1.params", mx.cpu())


def test_basic_dense_shapes():
    model = nn.Sequential()
    model.add(nn.Dense(128, activation="tanh", in_units=10),
              nn.Dense(64, activation="tanh", in_units=128),
              nn.Dense(32, in_units=64))
    model.initialize()
    x = mx.nd.array(np.random.randn(2, 10).astype(np.float32))
    assert model(x).shape == (2, 32)


def test_dense_flatten_false():
    model = nn.Dense(10, flatten=False, in_units=5)
    model.initialize()
    x = mx.nd.array(np.random.randn(2, 3, 5).astype(np.float32))
    assert model(x).shape == (2, 3, 10)


def test_deferred_init_and_hybridize():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.randn(3, 7).astype(np.float32))
    y0 = net(x)
    net.hybridize()
    y1 = net(x)
    np.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(), rtol=1e-5, atol=1e-5)


def test_hybrid_training_matches_eager():
    def build():
        mx.random.seed(42)
        net = nn.HybridSequential(prefix="m_")
        with net.name_scope():
            net.add(nn.Dense(8, activation="relu", in_units=6))
            net.add(nn.Dense(3, in_units=8))
        net.initialize(mx.init.Xavier())
        return net

    x = mx.nd.array(np.random.randn(4, 6).astype(np.float32))
    label = mx.nd.array(np.array([0, 1, 2, 1], np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    losses = []
    for hybrid in (False, True):
        net = build()
        if hybrid:
            net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        cur = []
        for _ in range(3):
            with autograd.record():
                L = loss_fn(net(x), label)
            L.backward()
            trainer.step(4)
            cur.append(float(L.mean().asscalar()))
        losses.append(cur)
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)


def test_conv_pool_stack():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"))
        net.add(nn.MaxPool2D(2))
        net.add(nn.Conv2D(16, kernel_size=3, padding=1))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.GlobalAvgPool2D())
        net.add(nn.Flatten())
        net.add(nn.Dense(10))
    net.initialize()
    x = mx.nd.array(np.random.randn(2, 3, 16, 16).astype(np.float32))
    assert net(x).shape == (2, 10)
    net.hybridize()
    assert net(x).shape == (2, 10)


def test_batchnorm_moving_stats():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = mx.nd.array((np.random.randn(8, 4, 3, 3) * 3 + 1).astype(np.float32))
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    rv = bn.running_var.data().asnumpy()
    assert not np.allclose(rm, 0)
    assert not np.allclose(rv, 1)
    # inference mode must not move stats
    before = rm.copy()
    bn(x)
    np.testing.assert_allclose(bn.running_mean.data().asnumpy(), before)


def test_conv_transpose():
    net = nn.Conv2DTranspose(4, kernel_size=4, strides=2, padding=1,
                             in_channels=3)
    net.initialize()
    x = mx.nd.array(np.random.randn(1, 3, 8, 8).astype(np.float32))
    assert net(x).shape == (1, 4, 16, 16)


def test_embedding_block():
    emb = nn.Embedding(10, 5)
    emb.initialize()
    idx = mx.nd.array(np.array([1, 2, 3], np.float32))
    assert emb(idx).shape == (3, 5)


def test_losses_basic():
    pred = mx.nd.array(np.random.randn(4, 5).astype(np.float32))
    label_sparse = mx.nd.array(np.array([0, 1, 2, 3], np.float32))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label_sparse)
    assert l.shape == (4,)
    # L2
    a = mx.nd.array(np.ones((3, 2), np.float32))
    b = mx.nd.array(np.zeros((3, 2), np.float32))
    l2 = gluon.loss.L2Loss()(a, b)
    np.testing.assert_allclose(l2.asnumpy(), np.full(3, 0.5), rtol=1e-6)
    l1 = gluon.loss.L1Loss()(a, b)
    np.testing.assert_allclose(l1.asnumpy(), np.ones(3), rtol=1e-6)
    # BCE matches manual
    p = mx.nd.array(np.array([[0.5, -0.5]], np.float32))
    t = mx.nd.array(np.array([[1.0, 0.0]], np.float32))
    got = gluon.loss.SigmoidBinaryCrossEntropyLoss()(p, t).asnumpy()
    x = np.array([[0.5, -0.5]])
    ref = (np.maximum(x, 0) - x * np.array([[1.0, 0.0]])
           + np.log1p(np.exp(-np.abs(x)))).mean(axis=1)
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_huber_hinge_triplet():
    pred = mx.nd.array(np.array([[2.0], [0.3]], np.float32))
    label = mx.nd.array(np.array([[0.0], [0.0]], np.float32))
    h = gluon.loss.HuberLoss(rho=1)(pred, label).asnumpy()
    np.testing.assert_allclose(h, [1.5, 0.5 * 0.09], rtol=1e-5)
    hi = gluon.loss.HingeLoss()(pred, mx.nd.array(np.array([[1.0], [-1.0]],
                                                           np.float32))).asnumpy()
    np.testing.assert_allclose(hi, [0.0, 1.3], rtol=1e-5)


def test_ctc_loss_matches_simple_case():
    # T=2, C=3 (blank=0), label "1": paths: (b,1),(1,b),(1,1)
    logits = np.zeros((2, 1, 3), np.float32)  # uniform → each path (1/3)^2
    loss = gluon.loss.CTCLoss(layout="TNC")(
        mx.nd.array(logits), mx.nd.array(np.array([[1]], np.float32)))
    expected = -np.log(3 * (1 / 9))
    np.testing.assert_allclose(loss.asnumpy(), [expected], rtol=1e-4)


def test_trainer_step_and_state_io():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = mx.nd.array(np.ones((4, 3), np.float32))
    with autograd.record():
        L = net(x).sum()
    L.backward()
    w_before = net.weight.data().asnumpy().copy()
    trainer.step(4)
    assert not np.allclose(net.weight.data().asnumpy(), w_before)
    trainer.save_states("/tmp/trainer.states")
    trainer.load_states("/tmp/trainer.states")


def test_clip_global_norm():
    arrays = [mx.nd.array(np.ones((2, 2), np.float32) * 3),
              mx.nd.array(np.ones((2,), np.float32) * 4)]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(total - 1.0) < 1e-4
    assert abs(norm - np.sqrt(9 * 4 + 16 * 2)) < 1e-3


def test_split_and_load():
    data = mx.nd.array(np.arange(12).reshape(6, 2).astype(np.float32))
    slices = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(0)])
    assert len(slices) == 2
    np.testing.assert_allclose(slices[0].asnumpy(), data.asnumpy()[:3])


def test_block_save_load_params():
    net = nn.HybridSequential(prefix="ckpt_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    net.initialize()
    y0 = net(mx.nd.ones((1, 3)))
    net.save_params("/tmp/blk.params")
    net2 = nn.HybridSequential(prefix="ckpt_")
    with net2.name_scope():
        net2.add(nn.Dense(4, in_units=3))
    net2.load_params("/tmp/blk.params")
    y1 = net2(mx.nd.ones((1, 3)))
    np.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(), rtol=1e-6)


def test_sequential_getitem_len():
    net = nn.Sequential()
    for _ in range(3):
        net.add(nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)


def test_lambda_blocks():
    net = nn.Sequential()
    net.add(nn.HybridLambda(lambda F, x: F.Activation(x, act_type="relu")))
    net.add(nn.Lambda(lambda x: x * 2))
    x = mx.nd.array(np.array([[-1.0, 2.0]], np.float32))
    np.testing.assert_allclose(net(x).asnumpy(), [[0.0, 4.0]])


def test_dataset_dataloader():
    X = np.random.randn(10, 3).astype(np.float32)
    Y = np.arange(10).astype(np.float32)
    dataset = gluon.data.ArrayDataset(X, Y)
    loader = gluon.data.DataLoader(dataset, batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 3
    xb, yb = batches[0]
    assert xb.shape == (4, 3)
    np.testing.assert_allclose(yb.asnumpy(), [0, 1, 2, 3])
    # threaded path
    loader2 = gluon.data.DataLoader(dataset, batch_size=4, num_workers=2)
    assert len(list(loader2)) == 3


def test_dataset_transform():
    X = np.ones((4, 2), np.float32)
    ds = gluon.data.ArrayDataset(X, np.zeros(4, np.float32))
    ds2 = ds.transform_first(lambda x: x * 3)
    x, y = ds2[0]
    np.testing.assert_allclose(np.asarray(x), [3, 3])


def test_rnn_cells_and_layers():
    cell = gluon.rnn.GRUCell(6, input_size=4)
    cell.initialize()
    x = mx.nd.array(np.random.randn(2, 5, 4).astype(np.float32))
    outs, state = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 6)

    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(6, input_size=4))
    stack.add(gluon.rnn.LSTMCell(6, input_size=6))
    stack.initialize()
    outs, states = stack.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 6)
    assert len(states) == 4

    layer = gluon.rnn.GRU(6, num_layers=1, layout="NTC", input_size=4)
    layer.initialize()
    out = layer(x)
    assert out.shape == (2, 5, 6)


def test_rnn_layer_vs_cell_consistency():
    """Fused RNN op must match the unrolled cell math (reference guarantees
    the same; SURVEY §2.2 RNN row)."""
    T, N, C, H = 4, 2, 3, 5
    x = mx.nd.array(np.random.randn(T, N, C).astype(np.float32))

    layer = gluon.rnn.LSTM(H, num_layers=1, layout="TNC", input_size=C)
    layer.initialize()
    out_layer = layer(x)

    cell = gluon.rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    # copy layer weights into cell
    cp = {p.name.split("_", 1)[1]: p for p in layer.collect_params().values()}
    cell.i2h_weight.set_data(cp["l0_i2h_weight"].data())
    cell.h2h_weight.set_data(cp["l0_h2h_weight"].data())
    cell.i2h_bias.set_data(cp["l0_i2h_bias"].data())
    cell.h2h_bias.set_data(cp["l0_h2h_bias"].data())
    out_cell, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(out_layer.asnumpy(), out_cell.asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_bidirectional_cell():
    l_cell = gluon.rnn.LSTMCell(4, input_size=3)
    r_cell = gluon.rnn.LSTMCell(4, input_size=3)
    bi = gluon.rnn.BidirectionalCell(l_cell, r_cell)
    bi.initialize()
    x = mx.nd.array(np.random.randn(2, 5, 3).astype(np.float32))
    outs, states = bi.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 8)


def test_model_zoo_smoke():
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    x = mx.nd.array(np.random.randn(1, 3, 32, 32).astype(np.float32))
    net = vision.get_model("resnet18_v1", classes=10, thumbnail=True)
    net.initialize(mx.init.Xavier())
    assert net(x).shape == (1, 10)
    net = vision.get_model("resnet18_v2", classes=10, thumbnail=True)
    net.initialize(mx.init.Xavier())
    assert net(x).shape == (1, 10)
    net = vision.get_model("mobilenet0.25", classes=10)
    net.initialize(mx.init.Xavier())
    x224 = mx.nd.array(np.random.randn(1, 3, 224, 224).astype(np.float32))
    assert net(x224).shape == (1, 10)


def test_constant_param():
    const = gluon.Constant("const", np.array([[1.0, 2.0]], np.float32))
    const.initialize()
    np.testing.assert_allclose(const.data().asnumpy(), [[1.0, 2.0]])
    assert const.grad_req == "null"


def test_cast():
    net = nn.Dense(3, in_units=2)
    net.initialize()
    net.cast("bfloat16")
    assert net.weight.data().dtype == np.dtype("bfloat16")
    x = mx.nd.array(np.ones((1, 2), np.float32)).astype("bfloat16")
    assert net(x).dtype == np.dtype("bfloat16")


def test_functionalize_threads_rng():
    """functionalize's rng keyword must control stochastic ops: same key
    -> same dropout mask, fresh keys -> different masks (review finding:
    the first cut baked one host key into the trace)."""
    import jax
    from incubator_mxnet_tpu.gluon.block import functionalize

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32), nn.Dropout(0.5))
    net.initialize(mx.init.Xavier())
    x = mx.nd.random.uniform(shape=(4, 8))
    fn, params = functionalize(net, x, train=True)
    jfn = jax.jit(fn)
    xv = x._read()
    a = np.asarray(jfn(params, xv, rng=jax.random.PRNGKey(1)))
    b = np.asarray(jfn(params, xv, rng=jax.random.PRNGKey(1)))
    c = np.asarray(jfn(params, xv, rng=jax.random.PRNGKey(2)))
    np.testing.assert_array_equal(a, b)
    assert (a != c).any(), "different keys must give different masks"
    assert ((a == 0).mean() > 0.2), "dropout inactive in train trace"
