"""Capacity-based Switch all-to-all MoE dispatch (SURVEY §2.4 EP row).

The dense masked path computes every expert for every token (compute
∝ num_experts); dispatch='capacity' routes each token's activations to
its expert's device via lax.all_to_all and back — the classic Switch
formulation, same module interface.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, parallel
from incubator_mxnet_tpu.parallel import (ExpertParallelMoE,
                                          DataParallelTrainer, make_mesh)

import jax


def _copy_params(src, dst):
    for (n, a), (m, b) in zip(sorted(src.collect_params().items()),
                              sorted(dst.collect_params().items())):
        b.set_data(a.data())


def test_capacity_matches_dense_when_no_overflow():
    """With top-1 routing and ample capacity, all-to-all dispatch must
    reproduce the dense masked path exactly."""
    E, d, h, N = 4, 6, 10, 16
    mesh = make_mesh({"ep": 4}, jax.devices("cpu")[:4])
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(N, d).astype(np.float32))

    mx.random.seed(1)
    dense = ExpertParallelMoE(hidden_size=h, num_experts=E, top_k=1)
    dense.initialize(mx.init.Xavier())
    dense(x)  # resolve deferred shapes
    out_dense = dense(x).asnumpy()

    mx.random.seed(2)
    cap = ExpertParallelMoE(hidden_size=h, num_experts=E, top_k=1,
                            dispatch="capacity", capacity_factor=64.0)
    cap.initialize(mx.init.Xavier())
    with parallel.use_mesh(mesh):
        cap(x)  # deferred shapes
        _copy_params(dense, cap)
        out_cap = cap(x).asnumpy()
    np.testing.assert_allclose(out_cap, out_dense, rtol=2e-5, atol=2e-6)
    assert cap.last_drop_fraction == 0.0


def test_capacity_overflow_drops_and_reports():
    """A tiny capacity factor must drop overflow tokens (their FFN output
    is zero) and report the drop fraction."""
    E, d, h, N = 2, 4, 6, 16
    mesh = make_mesh({"ep": 2}, jax.devices("cpu")[:2])
    mx.random.seed(3)
    blk = ExpertParallelMoE(hidden_size=h, num_experts=E, top_k=1,
                            dispatch="capacity", capacity_factor=0.25)
    blk.initialize(mx.init.Xavier())
    rs = np.random.RandomState(3)
    x = mx.nd.array(rs.randn(N, d).astype(np.float32))
    with parallel.use_mesh(mesh):
        out = blk(x).asnumpy()
    # cap = ceil(0.25 * 8 / 2) = 1 slot per expert per device:
    # at most 2 experts × 1 slot × 2 devices = 4 tokens survive of 16
    assert blk.last_drop_fraction >= 0.5, blk.last_drop_fraction
    dropped_rows = np.sum(np.all(out == 0.0, axis=-1))
    assert dropped_rows >= N // 2, dropped_rows


def test_capacity_dispatch_trains_in_fused_trainer():
    """dispatch='capacity' inside the DataParallelTrainer jit over a
    dp x ep mesh: all-to-all runs in-graph and the model trains."""
    E, d, h = 4, 6, 8
    mesh = make_mesh({"dp": 2, "ep": 4}, jax.devices("cpu")[:8])
    mx.random.seed(4)
    net = gluon.nn.HybridSequential()
    net.add(ExpertParallelMoE(hidden_size=h, num_experts=E, top_k=1,
                              dispatch="capacity", capacity_factor=2.0))
    net.add(gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    rs = np.random.RandomState(5)
    N = 16
    x = rs.randn(N, d).astype(np.float32)
    y = (rs.rand(N) > 0.5).astype(np.float32)
    with parallel.use_mesh(mesh):
        net(mx.nd.array(x))  # deferred shapes
        tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 optimizer="sgd",
                                 optimizer_params={"learning_rate": 0.2},
                                 mesh=mesh)
        l0 = float(np.asarray(tr.step(mx.nd.array(x), mx.nd.array(y))))
        for _ in range(25):
            l = float(np.asarray(tr.step(mx.nd.array(x), mx.nd.array(y))))
    assert np.isfinite(l) and l < l0, (l0, l)


def test_capacity_rejects_topk():
    with pytest.raises(ValueError, match="top-1"):
        ExpertParallelMoE(hidden_size=4, num_experts=4, top_k=2,
                          dispatch="capacity")


def test_capacity_trainer_without_ambient_scope():
    """The trainer must scope its OWN mesh for the trace — no ambient
    use_mesh required (review regression)."""
    E, d, h = 4, 6, 8
    mesh = make_mesh({"dp": 2, "ep": 4}, jax.devices("cpu")[:8])
    mx.random.seed(6)
    net = gluon.nn.HybridSequential()
    net.add(ExpertParallelMoE(hidden_size=h, num_experts=E, top_k=1,
                              dispatch="capacity", capacity_factor=2.0))
    net.add(gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    rs = np.random.RandomState(6)
    x = rs.randn(16, d).astype(np.float32)
    y = (rs.rand(16) > 0.5).astype(np.float32)
    with parallel.use_mesh(mesh):
        net(mx.nd.array(x))  # eager deferred-shape pass needs the scope
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1},
                             mesh=mesh)
    l = float(np.asarray(tr.step(mx.nd.array(x), mx.nd.array(y))))
    assert np.isfinite(l)


def test_router_receives_gradient_dense():
    """Top-1 dense combine must scale by the router probability so the
    gating logits train (advisor regression: a renormalised top-1 combine
    collapses to 1.0 and gives the gate zero gradient)."""
    E, d, h, N = 4, 6, 8, 16
    rs = np.random.RandomState(7)
    x = mx.nd.array(rs.randn(N, d).astype(np.float32))
    mx.random.seed(8)
    blk = ExpertParallelMoE(hidden_size=h, num_experts=E, top_k=1)
    blk.initialize(mx.init.Xavier())
    blk(x)  # deferred shapes
    blk.hybridize()  # tape records through the CachedOp vjp
    with mx.autograd.record():
        out = blk(x)
        loss = (out * out).sum()
    loss.backward()
    g = blk.gate_weight.grad().asnumpy()
    assert np.abs(g).sum() > 0, g


def test_router_receives_gradient_capacity():
    """In capacity dispatch the gate participates only through routing, so
    a zero router gradient would leave gate_weight frozen under training
    (advisor regression: bare one-hot combine)."""
    E, d, h, N = 4, 6, 8, 16
    mesh = make_mesh({"dp": 2, "ep": 4}, jax.devices("cpu")[:8])
    mx.random.seed(8)
    net = gluon.nn.HybridSequential()
    net.add(ExpertParallelMoE(hidden_size=h, num_experts=E, top_k=1,
                              dispatch="capacity", capacity_factor=4.0))
    net.add(gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    rs = np.random.RandomState(8)
    x = rs.randn(N, d).astype(np.float32)
    y = (rs.rand(N) > 0.5).astype(np.float32)
    moe = net._children[0]
    with parallel.use_mesh(mesh):
        net(mx.nd.array(x))  # deferred shapes
        gate0 = moe.gate_weight.data().asnumpy().copy()
        tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 optimizer="sgd",
                                 optimizer_params={"learning_rate": 0.5},
                                 mesh=mesh)
        for _ in range(3):
            tr.step(mx.nd.array(x), mx.nd.array(y))
        tr.sync_params()
    gate1 = moe.gate_weight.data().asnumpy()
    assert not np.allclose(gate0, gate1), "router weights did not move"


def test_capacity_reports_aux_loss():
    E, d, h, N = 2, 4, 6, 16
    mesh = make_mesh({"ep": 2}, jax.devices("cpu")[:2])
    mx.random.seed(9)
    blk = ExpertParallelMoE(hidden_size=h, num_experts=E, top_k=1,
                            dispatch="capacity", capacity_factor=8.0)
    blk.initialize(mx.init.Xavier())
    rs = np.random.RandomState(9)
    x = mx.nd.array(rs.randn(N, d).astype(np.float32))
    with parallel.use_mesh(mesh):
        blk(x)
    # aux >= 1 always (Cauchy-Schwarz; == 1 at perfectly uniform routing)
    assert blk.last_aux_loss is not None and blk.last_aux_loss >= 1.0 - 1e-5

