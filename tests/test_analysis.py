"""graftlint (analysis/) — op-contract linter + strict-mode engine verifier.

Pass 1 fixtures: one deliberately-broken registration per diagnostic
rule, asserting the specific code (docs/static_analysis.md).  Fixture
Operators are constructed directly (no registry pollution); only the
collision test touches the registry and cleans up after itself.

Pass 2: GRAFT_ENGINE_CHECK strict mode must (a) catch forced
stale-extract / double-rebind / integrity / fusion hazards through the
PR-1 view path, and (b) stay silent on correct programs (the whole
tier-1 suite runs under GRAFT_ENGINE_CHECK=1).
"""
import contextlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, engine
from incubator_mxnet_tpu.analysis import contracts
from incubator_mxnet_tpu.analysis.engine_check import EngineHazardError
from incubator_mxnet_tpu.ndarray.ndarray import invoke
from incubator_mxnet_tpu.ops.registry import (Operator, get_op, register,
                                              registration_log, _REGISTRY,
                                              _REGISTRATION_LOG)


def _codes(diags):
    return {d.code for d in diags if not d.suppressed}


def _drop_fixture_registrations(prefix="_glt_"):
    for name in [n for n in _REGISTRY if n.startswith(prefix)]:
        _REGISTRY.pop(name, None)
    _REGISTRATION_LOG[:] = [e for e in _REGISTRATION_LOG
                            if not e["name"].startswith(prefix)]


@pytest.fixture
def fixture_registry():
    """Yields register(); removes every _glt_* registration afterwards so
    later full-registry lint runs (and other tests) stay clean."""
    try:
        yield register
    finally:
        _drop_fixture_registrations()


@contextlib.contextmanager
def strict_engine():
    engine.set_engine_check(True)
    try:
        yield
    finally:
        engine.set_engine_check(None)


# ---------------------------------------------------------------------------
# pass 1 — one broken fixture per rule
# ---------------------------------------------------------------------------

def test_gl101_fixed_arity_mismatch():
    def fc(data):
        return data
    assert "GL101" in _codes(contracts.lint_operator(
        Operator("_glt_bad_arity", fc, num_inputs=2)))


def test_gl101_fake_variadic():
    def fc(a, b):
        return a + b
    assert "GL101" in _codes(contracts.lint_operator(
        Operator("_glt_fake_variadic", fc, num_inputs=None)))


def test_gl101_clean_on_true_variadic():
    def fc(*args, axis=0):
        return args[0]
    assert "GL101" not in _codes(contracts.lint_operator(
        Operator("_glt_varargs", fc, num_inputs=None)))


def test_gl102_nograd_out_of_range():
    def fc(data, indices):
        return data
    assert "GL102" in _codes(contracts.lint_operator(
        Operator("_glt_bad_nograd", fc, num_inputs=2, nograd_inputs=(2,))))


def test_gl103_mutate_out_of_range():
    def fc(weight, grad):
        return weight
    assert "GL103" in _codes(contracts.lint_operator(
        Operator("_glt_bad_mutate", fc, num_inputs=2, mutate_inputs=(7,),
                 differentiable=False)))


def test_gl104_rng_missing():
    def fc(shape=()):
        return jnp.zeros(shape)
    assert "GL104" in _codes(contracts.lint_operator(
        Operator("_glt_no_rng", fc, num_inputs=0, needs_rng=True,
                 differentiable=False)))


def test_gl104_rng_undeclared():
    def fc(data, rng=None):
        return data
    assert "GL104" in _codes(contracts.lint_operator(
        Operator("_glt_undeclared_rng", fc, num_inputs=1)))


def test_gl105_is_train_missing():
    def fc(data):
        return data
    assert "GL105" in _codes(contracts.lint_operator(
        Operator("_glt_no_train", fc, num_inputs=1, takes_is_train=True)))


def test_gl106_input_names_wrong_length():
    def fc(data, weight):
        return data
    assert "GL106" in _codes(contracts.lint_operator(
        Operator("_glt_names_len", fc, num_inputs=2,
                 input_names=("data", "weight", "bias"))))


def test_gl106_input_names_order_mismatch():
    def fc(a, b):
        return a + b
    assert "GL106" in _codes(contracts.lint_operator(
        Operator("_glt_names_order", fc, num_inputs=2,
                 input_names=("x", "y"))))


def test_gl106_no_bias_path_unresolvable():
    def fc(data, weight, bias=None):
        return data
    assert "GL106" in _codes(contracts.lint_operator(
        Operator("_glt_no_bias", fc, num_inputs=None,
                 input_names=("data", "weight", "bias"))))


def test_gl107_registration_collision(fixture_registry):
    @fixture_registry("_glt_dup", num_inputs=1)
    def fc1(data):
        return data

    @fixture_registry("_glt_dup", num_inputs=1)
    def fc2(data):
        return data * 2

    diags = contracts.lint_all(names={"_glt_dup"})
    hits = [d for d in diags if d.code == "GL107"]
    assert hits and not hits[0].suppressed
    assert any(e["name"] == "_glt_dup" and e["collided_with"] is not None
               for e in registration_log())


def test_gl108_host_rng():
    def fc(data):
        return data * np.random.rand()
    assert "GL108" in _codes(contracts.lint_operator(
        Operator("_glt_impure_rng", fc, num_inputs=1)))


def test_gl108_numpy_on_array_input():
    def fc(data):
        return jnp.asarray(np.asarray(data).sum())
    assert "GL108" in _codes(contracts.lint_operator(
        Operator("_glt_np_input", fc, num_inputs=1)))


def test_gl108_static_shape_math_not_flagged():
    def fc(data, kernel=()):
        size = float(np.prod(kernel))
        return data * size
    assert "GL108" not in _codes(contracts.lint_operator(
        Operator("_glt_shape_math", fc, num_inputs=1)))


def test_gl109_divergent_returns():
    def fc(data, both=False):
        if both:
            return data, data * 2
        return data
    assert "GL109" in _codes(contracts.lint_operator(
        Operator("_glt_divergent", fc, num_inputs=1)))


def test_gl109_silent_with_fnum_outputs():
    def fc(data, both=False):
        if both:
            return data, data * 2
        return data
    assert "GL109" not in _codes(contracts.lint_operator(
        Operator("_glt_divergent_ok", fc, num_inputs=1,
                 fnum_outputs=lambda p: 2 if p.get("both") else 1)))


def test_gl110_aux_not_subset():
    def fc(data, gamma):
        return data
    assert "GL110" in _codes(contracts.lint_operator(
        Operator("_glt_bad_aux", fc, num_inputs=2,
                 input_names=("data", "gamma"),
                 aux_input_names=("moving_mean",))))


def test_suppression_comment_honored():
    # graftlint: disable=GL101 -- fixture: wrong arity on purpose
    def fc(data):
        return data
    diags = [d for d in contracts.lint_operator(
        Operator("_glt_suppressed", fc, num_inputs=3)) if d.code == "GL101"]
    assert diags, "GL101 should still be reported"
    assert all(d.suppressed for d in diags)
    assert "fixture" in diags[0].justification


def test_repo_registry_lints_clean():
    """The live registry must stay clean — every future op PR inherits
    this check for free (fixture ops excluded defensively)."""
    diags = [d for d in contracts.lint_all()
             if not d.suppressed and not d.op_name.startswith("_glt_")]
    assert not diags, "\n".join(repr(d) for d in diags)


def test_graftlint_cli_json(capsys):
    import json
    from incubator_mxnet_tpu.analysis.graftlint import main
    assert main(["--ops", "take,topk,Convolution", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["version"] == 1 and report["total"] == 0
    assert isinstance(report["counts"], dict)


# ---------------------------------------------------------------------------
# registry contract metadata
# ---------------------------------------------------------------------------

def test_operator_contract_metadata():
    c = get_op("take").contract()
    assert c["num_inputs"] == 2 and c["nograd_inputs"] == [1]
    assert c["source_file"].endswith("tensor.py") and c["source_line"] > 0
    assert c["param_defaults"]["mode"] == "clip"
    c = get_op("BatchNorm").contract()
    assert c["takes_is_train"] and c["aux_input_names"] == [
        "moving_mean", "moving_var"]


def test_operator_defaults_populated_eagerly():
    """_defaults is built in __init__ — introspection (the linter, symbol
    executors) must never mutate Operator instances mid-flight."""
    def fc(data, no_bias=True, eps=1e-5):
        return data
    op = Operator("_glt_defaults", fc, num_inputs=1)
    assert "_defaults" in op.__dict__
    assert op._defaults == {"no_bias": True, "eps": 1e-5}
    before = dict(op.__dict__)
    assert op._param_default("no_bias") is True
    assert op._param_default("missing") is None
    assert dict(op.__dict__) == before


# ---------------------------------------------------------------------------
# view-group bookkeeping (ndarray)
# ---------------------------------------------------------------------------

def test_view_group_tracks_live_views():
    a = nd.array(np.arange(12.0).reshape(3, 4))
    v1 = a.reshape((4, 3))
    v2 = a[1:3]
    root, views = v1._view_group()
    assert root is a
    assert set(id(v) for v in views) >= {id(v1), id(v2)}
    del views, v2
    import gc
    gc.collect()
    assert id(v1) in {id(v) for v in a._live_views()}
    assert len(a._live_views()) == 1


# ---------------------------------------------------------------------------
# pass 2 — strict-mode engine hazards (GRAFT_ENGINE_CHECK)
# ---------------------------------------------------------------------------

def test_engine_check_env_toggle(monkeypatch):
    engine.set_engine_check(None)
    monkeypatch.delenv("GRAFT_ENGINE_CHECK", raising=False)
    assert not engine.engine_check_enabled()
    monkeypatch.setenv("GRAFT_ENGINE_CHECK", "1")
    assert engine.engine_check_enabled()
    with engine.bulk(4):
        assert engine._current().check
    monkeypatch.delenv("GRAFT_ENGINE_CHECK")
    assert not engine.engine_check_enabled()


def test_strict_mode_stale_extract_hazard():
    """EH101 — write-after-read through the PR-1 view path: an extract
    pending recorded at base version V fed back after the base rebound.
    (Production paths re-extract via the _cache_version guard; the
    strict check proves the guard's invariant is actually enforceable.)"""
    with strict_engine():
        with engine.bulk(32):
            r = nd.array(np.arange(12.0).reshape(3, 4))
            r2 = r + 1
            v = r2.reshape((4, 3))
            p = v._read_deferred()        # records _bulk_view_extract
            assert type(p) is engine._Pending
            r2 += 1                       # rebinds the base: version moves
            # feed the stale extract back THROUGH the view (production's
            # _read_deferred re-extracts instead — this simulates that
            # guard being bypassed, the invariant EH101 verifies)
            with pytest.raises(EngineHazardError) as ei:
                engine.maybe_defer(get_op("abs"), {}, [p], False, {},
                                   nd_inputs=[v])
            assert ei.value.code == "EH101"
            assert ei.value.detail["current_version"] > \
                ei.value.detail["recorded_version"]


def test_strict_mode_snapshot_copy_is_not_a_hazard():
    """A stale extract reached through a DIFFERENT owner is a legal
    snapshot: `w[:] = v` copies the pre-write view value, and a later
    base rebind must not trip EH101 (the recorded program replays the
    same pre-write snapshot eager copy semantics produced)."""
    with strict_engine():
        with engine.bulk(32):
            r2 = nd.array(np.arange(12.0).reshape(3, 4)) + 1
            v = r2.reshape((12,))
            w = nd.array(np.zeros(12, np.float32))
            w[:] = v                      # snapshot of the pre-write view
            r2 += 1                       # base rebinds afterwards
            got = (w + 1).asnumpy()
    np.testing.assert_allclose(
        got, np.arange(12.0) + 2)         # (x+1) snapshot, +1


def test_strict_mode_double_rebind_hazard():
    """EH102 — lost update: a _bulk_view_write whose base operand is no
    longer the base's current binding would discard the write between."""
    with strict_engine():
        with engine.bulk(32):
            r = nd.array(np.arange(12.0).reshape(3, 4))
            r2 = r * 1
            v = r2.reshape((12,))
            stale = r2._data              # binding BEFORE the first write
            v[:] = 5.0                    # first rebind (recorded write)
            with pytest.raises(EngineHazardError) as ei:
                engine.maybe_defer(get_op("_bulk_view_write"),
                                   {"offset": 0},
                                   [stale, jnp.zeros((12,), jnp.float32)],
                                   False, {}, nd_inputs=[r2, None])
            assert ei.value.code == "EH102"


def test_strict_mode_segment_integrity_hazard():
    """EH103 — an ext operand no instruction references (orphans corrupt
    the replay-cache key; see maybe_defer's staging invariant)."""
    with strict_engine():
        with engine.bulk(8):
            a = nd.array(np.ones((2, 2), np.float32))
            a + 1
            engine._current().ext.append(jnp.zeros((2,)))
            with pytest.raises(EngineHazardError) as ei:
                engine.flush()
            assert ei.value.code == "EH103"
        # scope-close flush after the hazard must be a clean no-op
    with strict_engine():
        with engine.bulk(8):
            b = nd.array(np.ones((2,), np.float32))
            assert (b + 1).asnumpy() is not None


def test_strict_mode_fusion_oracle_catches_divergence(fixture_registry):
    """EH104 — an op whose traced and eager semantics differ is exactly
    what the fused/unfused bit-comparison oracle must catch."""
    @fixture_registry("_glt_jekyll", num_inputs=1, differentiable=False)
    def _glt_jekyll(x):
        if isinstance(x, jax.core.Tracer):
            return x + 1.0
        return x + 2.0

    with strict_engine():
        with pytest.raises(EngineHazardError) as ei:
            with engine.bulk(8):
                a = nd.array(np.ones((2, 2), np.float32))
                invoke(get_op("_glt_jekyll"), [a], {}).asnumpy()
        assert ei.value.code == "EH104"
        assert "_glt_jekyll" in ei.value.detail["ops"]

    # same program, checks FORCED off: the divergence goes unnoticed
    # (this is precisely the blind spot strict mode exists to close)
    engine.set_engine_check(False)
    try:
        with engine.bulk(8):
            a = nd.array(np.ones((2, 2), np.float32))
            out = invoke(get_op("_glt_jekyll"), [a], {}).asnumpy()
    finally:
        engine.set_engine_check(None)
    np.testing.assert_allclose(out, 2.0)  # fused value ships silently


def test_strict_mode_clean_on_correct_programs():
    """No false positives: a realistic bulked program (views, in-place
    writes, autograd) under strict mode matches eager exactly."""
    rs = np.random.RandomState(7)
    aw = rs.rand(6, 4).astype(np.float32)

    def run(bulked):
        a = nd.array(aw)
        a.attach_grad()
        scope = engine.bulk(64) if bulked else contextlib.nullcontext()
        with scope:
            with autograd.record():
                h = (a * 2).reshape((4, 6))
                y = (h[1:3] + 1).sum()
            y.backward()
            c = a * 3
            c += 1
            v = c.reshape((24,))
            v += 1                     # write-through via a deferred view
            return c.asnumpy(), a.grad.asnumpy()

    with strict_engine():
        got_c, got_g = run(True)
    want_c, want_g = run(False)
    np.testing.assert_allclose(got_c, want_c, rtol=1e-6)
    np.testing.assert_allclose(got_g, want_g, rtol=1e-6)
