"""Operator correctness tests (parity: tests/python/unittest/test_operator.py
subset — vs numpy references + numeric gradients; SURVEY §4.1)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.test_utils import (assert_almost_equal,
                                            check_numeric_gradient)


def _nd(x):
    return mx.nd.array(np.asarray(x, np.float32))


def test_unary_ops_vs_numpy():
    x_np = np.random.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    x = _nd(x_np)
    cases = {
        "sqrt": np.sqrt, "exp": np.exp, "log": np.log, "square": np.square,
        "sin": np.sin, "cos": np.cos, "tanh": np.tanh, "abs": np.abs,
        "sigmoid": lambda a: 1 / (1 + np.exp(-a)), "rsqrt": lambda a: 1 / np.sqrt(a),
        "log1p": np.log1p, "expm1": np.expm1, "floor": np.floor, "ceil": np.ceil,
        "sign": np.sign, "reciprocal": lambda a: 1 / a,
    }
    for name, ref in cases.items():
        out = getattr(mx.nd, name)(x)
        assert_almost_equal(out, ref(x_np), rtol=1e-4, atol=1e-5, names=(name, "np"))


def test_activation_ops():
    x_np = np.random.randn(4, 5).astype(np.float32)
    x = _nd(x_np)
    assert_almost_equal(mx.nd.Activation(x, act_type="relu"), np.maximum(x_np, 0))
    assert_almost_equal(mx.nd.Activation(x, act_type="softrelu"),
                        np.log1p(np.exp(x_np)), rtol=1e-4, atol=1e-5)
    assert_almost_equal(mx.nd.LeakyReLU(x, act_type="leaky", slope=0.1),
                        np.where(x_np > 0, x_np, 0.1 * x_np))
    e = np.where(x_np > 0, x_np, 0.25 * (np.exp(x_np) - 1))
    assert_almost_equal(mx.nd.LeakyReLU(x, act_type="elu", slope=0.25), e,
                        rtol=1e-4, atol=1e-5)


def test_softmax_ops():
    x_np = np.random.randn(3, 6).astype(np.float32)
    x = _nd(x_np)
    e = np.exp(x_np - x_np.max(axis=-1, keepdims=True))
    p = e / e.sum(axis=-1, keepdims=True)
    assert_almost_equal(mx.nd.softmax(x), p, rtol=1e-4, atol=1e-5)
    assert_almost_equal(mx.nd.log_softmax(x), np.log(p), rtol=1e-4, atol=1e-4)
    # temperature
    assert_almost_equal(mx.nd.softmax(x, temperature=2.0),
                        np.exp(x_np / 2 - (x_np / 2).max(-1, keepdims=True)) /
                        np.exp(x_np / 2 - (x_np / 2).max(-1, keepdims=True)).sum(-1, keepdims=True),
                        rtol=1e-4, atol=1e-5)


def test_fully_connected():
    x = np.random.randn(4, 7).astype(np.float32)
    w = np.random.randn(3, 7).astype(np.float32)
    b = np.random.randn(3).astype(np.float32)
    out = mx.nd.FullyConnected(_nd(x), _nd(w), _nd(b), num_hidden=3)
    assert_almost_equal(out, x @ w.T + b, rtol=1e-4, atol=1e-4)
    out2 = mx.nd.FullyConnected(_nd(x), _nd(w), num_hidden=3, no_bias=True)
    assert_almost_equal(out2, x @ w.T, rtol=1e-4, atol=1e-4)
    # flatten semantics: (N, ...) collapses
    x4 = np.random.randn(2, 3, 2, 2).astype(np.float32)
    w4 = np.random.randn(5, 12).astype(np.float32)
    out3 = mx.nd.FullyConnected(_nd(x4), _nd(w4), num_hidden=5, no_bias=True)
    assert_almost_equal(out3, x4.reshape(2, -1) @ w4.T, rtol=1e-4, atol=1e-4)


def _np_conv2d(x, w, stride, pad):
    n, c, h, wd = x.shape
    oc, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    out = np.zeros((n, oc, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh, j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out


def test_convolution_vs_numpy():
    x = np.random.randn(2, 3, 7, 7).astype(np.float32)
    w = np.random.randn(4, 3, 3, 3).astype(np.float32)
    b = np.random.randn(4).astype(np.float32)
    out = mx.nd.Convolution(_nd(x), _nd(w), _nd(b), kernel=(3, 3), num_filter=4,
                            stride=(2, 2), pad=(1, 1))
    ref = _np_conv2d(x, w, 2, 1) + b.reshape(1, -1, 1, 1)
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-3)


def test_convolution_grouped_and_1x1():
    x = np.random.randn(1, 4, 5, 5).astype(np.float32)
    w = np.random.randn(4, 2, 1, 1).astype(np.float32)
    out = mx.nd.Convolution(_nd(x), _nd(w), kernel=(1, 1), num_filter=4,
                            num_group=2, no_bias=True)
    assert out.shape == (1, 4, 5, 5)


def test_pooling():
    x = np.random.randn(2, 3, 6, 6).astype(np.float32)
    mp = mx.nd.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2), pool_type="max")
    ref = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    assert_almost_equal(mp, ref)
    ap = mx.nd.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2), pool_type="avg")
    refa = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
    assert_almost_equal(ap, refa, rtol=1e-4, atol=1e-5)
    gp = mx.nd.Pooling(_nd(x), global_pool=True, pool_type="max", kernel=(1, 1))
    assert gp.shape == (2, 3, 1, 1)
    assert_almost_equal(gp, x.max(axis=(2, 3), keepdims=True))


def test_batchnorm_train_and_inference():
    x = np.random.randn(8, 4, 3, 3).astype(np.float32)
    gamma = np.random.rand(4).astype(np.float32) + 0.5
    beta = np.random.randn(4).astype(np.float32)
    mean = np.zeros(4, np.float32)
    var = np.ones(4, np.float32)
    # inference mode: uses moving stats
    out = mx.nd.BatchNorm(_nd(x), _nd(gamma), _nd(beta), _nd(mean), _nd(var),
                          fix_gamma=False, eps=1e-5)
    ref = (x - mean.reshape(1, -1, 1, 1)) / np.sqrt(var.reshape(1, -1, 1, 1) + 1e-5)
    ref = ref * gamma.reshape(1, -1, 1, 1) + beta.reshape(1, -1, 1, 1)
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)
    # train mode: uses batch stats
    with mx.autograd.record():
        out_t = mx.nd.BatchNorm(_nd(x), _nd(gamma), _nd(beta), _nd(mean), _nd(var),
                                fix_gamma=False, eps=1e-5)
    bm = x.mean(axis=(0, 2, 3))
    bv = x.var(axis=(0, 2, 3))
    ref_t = (x - bm.reshape(1, -1, 1, 1)) / np.sqrt(bv.reshape(1, -1, 1, 1) + 1e-5)
    ref_t = ref_t * gamma.reshape(1, -1, 1, 1) + beta.reshape(1, -1, 1, 1)
    assert_almost_equal(out_t, ref_t, rtol=1e-3, atol=1e-3)


def test_layernorm():
    x = np.random.randn(4, 10).astype(np.float32)
    g = np.random.rand(10).astype(np.float32)
    b = np.random.randn(10).astype(np.float32)
    out = mx.nd.LayerNorm(_nd(x), _nd(g), _nd(b), eps=1e-5)
    mu = x.mean(-1, keepdims=True)
    sig = x.var(-1, keepdims=True)
    assert_almost_equal(out, (x - mu) / np.sqrt(sig + 1e-5) * g + b,
                        rtol=1e-4, atol=1e-4)


def test_transpose_slice_ops():
    x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    assert_almost_equal(mx.nd.transpose(_nd(x)), x.T)
    assert_almost_equal(mx.nd.transpose(_nd(x), axes=(1, 0, 2)), x.transpose(1, 0, 2))
    assert_almost_equal(mx.nd.slice_axis(_nd(x), axis=1, begin=1, end=3), x[:, 1:3])
    assert_almost_equal(mx.nd.slice(_nd(x), begin=(0, 1), end=(2, 3)), x[0:2, 1:3])
    assert_almost_equal(mx.nd.flip(_nd(x), axis=2), x[:, :, ::-1])
    assert_almost_equal(mx.nd.expand_dims(_nd(x), axis=1), x[:, None])
    assert_almost_equal(mx.nd.tile(_nd(x[0]), reps=(2, 1)), np.tile(x[0], (2, 1)))
    assert_almost_equal(mx.nd.repeat(_nd(x), repeats=2, axis=0), np.repeat(x, 2, 0))


def test_pad_op():
    x = np.random.randn(1, 1, 3, 3).astype(np.float32)
    out = mx.nd.pad(_nd(x), mode="constant", pad_width=(0, 0, 0, 0, 1, 1, 2, 2),
                    constant_value=5.0)
    ref = np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)), constant_values=5.0)
    assert_almost_equal(out, ref)


def test_ordering_ops():
    x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], np.float32)
    assert_almost_equal(mx.nd.topk(_nd(x), k=2), [[0, 2], [1, 2]])
    assert_almost_equal(mx.nd.topk(_nd(x), k=2, ret_typ="value"), [[3, 2], [5, 4]])
    assert_almost_equal(mx.nd.sort(_nd(x)), np.sort(x))
    assert_almost_equal(mx.nd.sort(_nd(x), is_ascend=False), -np.sort(-x))
    assert_almost_equal(mx.nd.argsort(_nd(x)), np.argsort(x))
    assert_almost_equal(mx.nd.argmax(_nd(x), axis=1), [0, 1])
    assert_almost_equal(mx.nd.argmin(_nd(x), axis=0), [1, 0, 0])


def test_where_clip():
    cond = np.array([[1, 0], [0, 1]], np.float32)
    x = np.ones((2, 2), np.float32)
    y = np.zeros((2, 2), np.float32)
    assert_almost_equal(mx.nd.where(_nd(cond), _nd(x), _nd(y)), cond)
    a = np.array([-2.0, 0.5, 3.0], np.float32)
    assert_almost_equal(mx.nd.clip(_nd(a), a_min=-1.0, a_max=1.0), np.clip(a, -1, 1))


def test_sequence_ops():
    # (T, N, D) = (4, 2, 3)
    x = np.random.randn(4, 2, 3).astype(np.float32)
    slen = np.array([2.0, 4.0], np.float32)
    last = mx.nd.SequenceLast(_nd(x), _nd(slen), use_sequence_length=True)
    assert_almost_equal(last, np.stack([x[1, 0], x[3, 1]]))
    masked = mx.nd.SequenceMask(_nd(x), _nd(slen), use_sequence_length=True, value=-1.0)
    ref = x.copy()
    ref[2:, 0] = -1.0
    assert_almost_equal(masked, ref)
    rev = mx.nd.SequenceReverse(_nd(x), _nd(slen), use_sequence_length=True)
    ref2 = x.copy()
    ref2[:2, 0] = x[:2, 0][::-1]
    ref2[:, 1] = x[:, 1][::-1]
    assert_almost_equal(rev, ref2)


def test_gather_scatter():
    data = np.arange(9).reshape(3, 3).astype(np.float32)
    idx = np.array([[0, 2], [1, 0]], np.float32)  # (M=2, N=2)
    out = mx.nd.gather_nd(_nd(data), _nd(idx))
    assert_almost_equal(out, [data[0, 1], data[2, 0]])
    s = mx.nd.scatter_nd(_nd(np.array([5.0, 6.0])), _nd(idx), shape=(3, 3))
    ref = np.zeros((3, 3), np.float32)
    ref[0, 1] = 5
    ref[2, 0] = 6
    assert_almost_equal(s, ref)


def test_pick():
    x = np.random.randn(3, 4).astype(np.float32)
    idx = np.array([0.0, 2.0, 3.0], np.float32)
    out = mx.nd.pick(_nd(x), _nd(idx))
    assert_almost_equal(out, x[np.arange(3), [0, 2, 3]])


def test_numeric_gradients_core_ops():
    x = mx.nd.array(np.random.rand(3, 4).astype(np.float32) + 0.5)
    w = mx.nd.array(np.random.rand(4, 2).astype(np.float32))
    check_numeric_gradient(lambda a: mx.nd.tanh(a), [x])
    check_numeric_gradient(lambda a, b: mx.nd.dot(a, b), [x, w])
    check_numeric_gradient(lambda a: mx.nd.softmax(a), [x])
    check_numeric_gradient(lambda a: mx.nd.Pooling(
        a.reshape((1, 1, 3, 4)), kernel=(2, 2), stride=(1, 1), pool_type="avg"), [x])


def test_lrn():
    x = np.random.randn(2, 5, 3, 3).astype(np.float32)
    out = mx.nd.LRN(_nd(x), nsize=3, alpha=1e-4, beta=0.75, knorm=2.0)
    # numpy reference
    sq = x ** 2
    ref = np.zeros_like(x)
    for c in range(5):
        lo, hi = max(0, c - 1), min(5, c + 2)
        s = sq[:, lo:hi].sum(axis=1)
        ref[:, c] = x[:, c] * (2.0 + 1e-4 / 3 * s) ** -0.75
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)


def test_deconvolution_shape_inverse():
    # deconv inverts conv spatial shape math
    x = np.random.randn(1, 2, 5, 5).astype(np.float32)
    w = np.random.randn(2, 3, 3, 3).astype(np.float32)  # (in, out, kh, kw)
    out = mx.nd.Deconvolution(_nd(x), _nd(w), kernel=(3, 3), num_filter=3,
                              stride=(2, 2), pad=(1, 1), adj=(1, 1))
    assert out.shape == (1, 3, 10, 10)


def test_regression_outputs():
    d = np.random.randn(4, 3).astype(np.float32)
    l = np.random.randn(4, 3).astype(np.float32)
    data = _nd(d)
    data.attach_grad()
    with mx.autograd.record():
        out = mx.nd.LinearRegressionOutput(data, _nd(l))
    assert_almost_equal(out, d)
    out.backward()
    assert_almost_equal(data.grad, d - l, rtol=1e-4, atol=1e-5)


def test_l2_normalization():
    x = np.random.randn(2, 3, 4).astype(np.float32)
    out = mx.nd.L2Normalization(_nd(x), mode="instance")
    ref = x / np.sqrt((x ** 2).sum(axis=(1, 2), keepdims=True) + 1e-10)
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)


def test_batchnorm_large_mean_variance():
    """Shifted one-pass variance must survive |mean| >> std channels
    (round-2 review: naive E[x^2]-E[x]^2 cancels catastrophically)."""
    rs = np.random.RandomState(0)
    x = (rs.randn(64, 4, 3, 3) * 0.03 + 1000.0).astype(np.float32)
    gamma = mx.nd.array(np.ones(4, np.float32))
    beta = mx.nd.array(np.zeros(4, np.float32))
    mmean = mx.nd.array(np.zeros(4, np.float32))  # stale running mean
    mvar = mx.nd.array(np.ones(4, np.float32))
    with mx.autograd.record():
        out = mx.nd.BatchNorm(mx.nd.array(x), gamma, beta, mmean, mvar,
                              fix_gamma=False)
    got = out.asnumpy()
    want = (x - x.mean(axis=(0, 2, 3), keepdims=True)) / \
        np.sqrt(x.var(axis=(0, 2, 3), keepdims=True) + 1e-3)
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)
