"""graftlens tests: per-step attribution conservation, overlap-aware
comm accounting, step-id threading, the cross-rank aggregator +
straggler table, metadata/flow trace validation, the rank-suffixed dump
path, and the 2-proc dist harness with a deliberately delayed rank.

Covers the ISSUE-8 acceptance surface: the six lens components must sum
to the measured step wall time (including an overlapped PR-7 step where
``exposed_comm`` < total collective time and a serial step where they
are equal), and ``--analyze`` over two ranks' artifacts must produce a
schema-valid merged chrome trace with per-rank tracks, cross-rank flow
links per reduced bucket, and a straggler table naming the delayed
rank.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.telemetry import aggregate, blackbox, lens
from incubator_mxnet_tpu.telemetry import tracing as ttracing
from incubator_mxnet_tpu.telemetry.__main__ import main as telemetry_main


@pytest.fixture
def fresh_lens():
    """A clean, force-enabled lens for one test."""
    lens.set_enabled(True)
    lens.reset()
    yield lens
    lens.reset()
    lens.set_enabled(None)


def _build_params(n, shape=(8, 8), prefix="lp", seed=0):
    rs = np.random.RandomState(seed)
    ps = []
    for k in range(n):
        p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
        p.initialize(ctx=mx.cpu())
        p.data()._write(rs.randn(*shape).astype(np.float32))
        ps.append(p)
    return ps


def _train_steps(ps, trainer, n):
    for _ in range(n):
        with autograd.record():
            loss = None
            for p in ps:
                y = (p.data() * p.data()).sum()
                loss = y if loss is None else loss + y
        loss.backward()
        trainer.step(1)
    ps[-1].data().asnumpy()


def _assert_conserved(rec):
    total = sum(rec["components"].values())
    assert total == pytest.approx(rec["wall_s"], abs=1e-6), \
        (rec["components"], rec["wall_s"])
    for v in rec["components"].values():
        assert v >= 0.0


# ---------------------------------------------------------------------------
# attribution conservation
# ---------------------------------------------------------------------------

def test_components_sum_to_step_wall_time(fresh_lens):
    """The conservation contract over a full training loop with every
    source lit: io iterator, record scope, backward, a local kvstore,
    the fused update."""
    from incubator_mxnet_tpu import io
    net = gluon.nn.Dense(4)
    net.initialize()
    rs = np.random.RandomState(0)
    x = rs.rand(24, 8).astype(np.float32)
    y = np.zeros((24, 4), np.float32)
    net(mx.nd.array(x[:4])).asnumpy()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1},
                            kvstore=mx.kv.create("local"))
    it = io.NDArrayIter(data=x, label=y, batch_size=4)
    for batch in it:
        with autograd.record():
            out = net(batch.data[0])
            loss = (out * out).mean()
        loss.backward()
        trainer.step(4)
        loss.asnumpy()
    recs = lens.steps()
    assert len(recs) == 6
    for rec in recs:
        _assert_conserved(rec)
    # steady-state steps exercise every component source
    steady = recs[-1]
    assert steady["components"]["forward"] > 0
    assert steady["components"]["backward_compute"] > 0
    assert steady["components"]["optimizer_update"] > 0
    assert steady["components"]["exposed_comm"] > 0   # kv push/pull
    assert any(r["components"]["data_wait"] > 0 for r in recs)
    assert steady["io_waits"] >= 1 and steady["collectives"] >= 1


def test_overlapped_step_hides_comm_serial_step_does_not(fresh_lens):
    """ISSUE-8 conservation satellite: on the overlapped (PR 7) path
    ``exposed_comm`` (blocked) < total collective in-flight time; with
    GRAFT_OVERLAP off the two book EQUAL by construction.  Conservation
    holds on both."""
    def run(overlap, prefix):
        lens.reset()
        ps = _build_params(8, prefix=prefix)
        t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                          kvstore=mx.kv.create("dist_sync"))
        t._bucket_bytes_override = 1024
        t._overlap_override = overlap
        _train_steps(ps, t, 4)
        return lens.steps()

    serial = run(False, "ls")
    for rec in serial:
        _assert_conserved(rec)
        # sync brackets book blocked == in-flight identically
        assert rec["comm_blocked_s"] == rec["comm_inflight_s"]

    overlapped = run(True, "lo")
    for rec in overlapped:
        _assert_conserved(rec)
    last = overlapped[-1]
    assert last.get("overlapped") is True
    # the reduce was issued mid-backward: its in-flight span covers the
    # rest of the walk, while step() only paid the wait
    assert last["comm_blocked_s"] < last["comm_inflight_s"]


def test_lens_survives_disabled_blackbox(fresh_lens):
    """Step windows must close (via _LensOnlyStep) AND collective
    brackets must keep feeding comm accounting (light-mode bracket)
    when the flight recorder is off."""
    prev = blackbox._enabled_override
    blackbox.set_enabled(False)
    before = len(blackbox.events())
    try:
        ps = _build_params(2, prefix="lb")
        t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                          kvstore=mx.kv.create("local"))
        _train_steps(ps, t, 3)
        assert len(blackbox.events()) == before    # recorder really off
    finally:
        blackbox.set_enabled(prev)
    recs = lens.steps()
    assert len(recs) == 3
    for rec in recs:
        _assert_conserved(rec)
    # the kvstore reduce still booked as exposed communication
    assert recs[-1]["collectives"] >= 1
    assert recs[-1]["comm_blocked_s"] > 0
    assert recs[-1]["components"]["exposed_comm"] > 0


def test_disabled_lens_is_a_noop():
    lens.set_enabled(False)
    try:
        lens.reset()
        lens.interval("forward", 0.0, 1.0)
        lens.io_wait(0.0, 1.0)
        lens.comm(0.0, 1.0)
        assert lens.step_end("t") is None
        assert lens.steps() == []
        assert lens.current_step() is None
    finally:
        lens.set_enabled(None)
        lens.reset()


def test_open_window_is_bounded_without_step_boundaries(fresh_lens):
    """A serving/eval loop (hooks fire, step_end never does) must not
    grow the open window without bound."""
    for i in range(3 * lens._MAX_OPEN_INTERVALS):
        lens.io_wait(float(i), float(i) + 0.5)
    st = lens._state()
    assert len(st.intervals) <= lens._MAX_OPEN_INTERVALS
    rec = lens.step_end("eval")        # a late step still conserves
    _assert_conserved(rec)


def test_toggle_does_not_book_ghost_step(fresh_lens):
    """A window left open across a disabled period must be dropped on
    re-enable, not billed as one giant host_gap step."""
    ps = _build_params(2, prefix="lg")
    t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01}, kvstore=None)
    _train_steps(ps, t, 1)
    lens.set_enabled(False)
    time.sleep(0.2)                        # "trains" with the lens off
    lens.set_enabled(True)
    _train_steps(ps, t, 1)
    recs = lens.steps()
    assert len(recs) == 2
    _assert_conserved(recs[-1])
    # the disabled 0.2s must NOT appear in the re-enabled step's window
    assert recs[-1]["wall_s"] < 0.15, recs[-1]


def test_priority_sweep_never_double_counts(fresh_lens):
    """Overlapping intervals of different categories attribute each
    elementary slice exactly once, highest priority first."""
    # forward covers [0, 10]; bwd [4, 8] nested; comm [6, 12] overlaps
    intervals = [("forward", 0.0, 10.0),
                 ("backward_compute", 4.0, 8.0),
                 ("exposed_comm", 6.0, 12.0)]
    comp, attributed = lens._attribute(intervals, 0.0, 20.0)
    assert comp["forward"] == pytest.approx(4.0)           # [0,4]
    assert comp["backward_compute"] == pytest.approx(2.0)  # [4,6]
    assert comp["exposed_comm"] == pytest.approx(6.0)      # [6,12]
    assert attributed == pytest.approx(12.0)
    # clipping to the window
    comp, attributed = lens._attribute(intervals, 5.0, 11.0)
    assert comp["forward"] == pytest.approx(0.0)
    assert comp["backward_compute"] == pytest.approx(1.0)  # [5,6]
    assert comp["exposed_comm"] == pytest.approx(5.0)      # [6,11]
    assert attributed == pytest.approx(6.0)


def test_ring_bound_and_report(fresh_lens, capfd, monkeypatch):
    monkeypatch.setenv("GRAFT_LENS_RING", "4")
    monkeypatch.setenv("GRAFT_STEP_REPORT", "2")
    lens.configure()
    try:
        ps = _build_params(2, prefix="lr")
        t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01}, kvstore=None)
        _train_steps(ps, t, 6)
        recs = lens.steps()
        assert len(recs) == 4                  # ring bound
        assert recs[-1]["step"] == 6
        err = capfd.readouterr().err
        assert "graftlens step 2" in err and "graftlens step 6" in err
        assert "graftlens step 3" not in err   # off-cadence steps silent
    finally:
        monkeypatch.delenv("GRAFT_LENS_RING")
        lens.configure()


# ---------------------------------------------------------------------------
# step-id threading (flushes + collectives + journals share the key)
# ---------------------------------------------------------------------------

def test_step_id_threaded_through_ring_events(fresh_lens):
    blackbox.set_enabled(True)
    blackbox._ring.clear()
    try:
        ps = _build_params(4, prefix="lt")
        t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                          kvstore=mx.kv.create("local"))
        _train_steps(ps, t, 3)
        evs = blackbox.events()
        steps = [e["data"] for e in evs if e["kind"] == "step"]
        assert [s["step"] for s in steps] == [1, 2, 3]
        assert all("lens" in s for s in steps)
        # collectives carry the step they ran under plus a lockstep seq
        colls = [e["data"] for e in evs if e["kind"] == "collective"]
        assert colls
        assert all("seq" in c for c in colls)
        seqs = [c["seq"] for c in colls]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        coll_steps = {c["step"] for c in colls if "step" in c}
        assert coll_steps and coll_steps <= {1, 2, 3}
        # the journal's lens fold conserves too (ms view)
        fold = steps[-1]["lens"]
        parts = sum(fold[c + "_ms"] for c in lens.COMPONENTS)
        assert parts == pytest.approx(fold["wall_ms"], abs=0.01)
    finally:
        blackbox.set_enabled(None)


# ---------------------------------------------------------------------------
# chrome-trace metadata + flow-step validation (satellite)
# ---------------------------------------------------------------------------

def test_process_metadata_events_label_tracks():
    evs = ttracing.process_metadata_events(rank=3, role="blackbox", pid=3)
    names = {e["name"]: e for e in evs}
    assert names["process_name"]["args"]["name"] == "rank 3 (blackbox)"
    assert names["process_sort_index"]["args"]["sort_index"] == 3
    assert names["thread_name"]["pid"] == 3


def test_validator_accepts_metadata_and_multi_hop_flows():
    trace = {"traceEvents": (
        ttracing.process_metadata_events(rank=0)
        + [{"name": "c", "cat": "x", "ph": "X", "ts": 1.0, "dur": 2.0,
            "pid": 0, "tid": 0},
           {"name": "l", "cat": "f", "ph": "s", "id": "a", "ts": 1.0,
            "pid": 0, "tid": 0},
           {"name": "l", "cat": "f", "ph": "t", "id": "a", "ts": 2.0,
            "pid": 1, "tid": 0},
           {"name": "l", "cat": "f", "ph": "f", "bp": "e", "id": "a",
            "ts": 3.0, "pid": 2, "tid": 0}])}
    assert ttracing.validate_chrome_trace(trace) == []
    # a hop without a start is still a problem
    bad = {"traceEvents": [
        {"name": "l", "cat": "f", "ph": "t", "id": "zz", "ts": 1.0,
         "pid": 0, "tid": 0}]}
    assert any("without a start" in p
               for p in ttracing.validate_chrome_trace(bad))
    # M events must carry args
    assert any("(M)" in p for p in ttracing.validate_chrome_trace(
        {"traceEvents": [{"name": "process_name", "ph": "M", "pid": 0}]}))


def test_profiler_dump_carries_metadata_and_wall_anchor(tmp_path):
    from incubator_mxnet_tpu import profiler
    path = str(tmp_path / "trace.json")
    profiler.set_config(filename=path, profile_all=True)
    profiler.set_state("run")
    (mx.nd.ones((4, 4)) + 1).asnumpy()
    profiler.set_state("stop")
    profiler.dump()
    with open(path) as f:
        doc = json.load(f)
    assert ttracing.validate_chrome_trace(doc) == []
    assert any(e.get("ph") == "M" and e.get("name") == "process_name"
               for e in doc["traceEvents"])
    anchor = doc["otherData"]["wall_anchor"]
    assert abs(anchor["wall_s"] - time.time()) < 60.0
    assert doc["otherData"]["rank"] == blackbox._rank[0]


# ---------------------------------------------------------------------------
# multi-rank dump path (satellite)
# ---------------------------------------------------------------------------

def test_blackbox_dump_path_rank_suffix(tmp_path, monkeypatch):
    monkeypatch.setenv("GRAFT_BLACKBOX_PATH", str(tmp_path / "bb.json"))
    try:
        blackbox.set_rank(0)
        assert blackbox.default_path() == str(tmp_path / "bb.json")
        blackbox.set_rank(2)
        assert blackbox.default_path() == str(tmp_path / "bb.rank2.json")
        # a path already naming this rank (old per-worker guidance) is
        # kept verbatim; a {rank} placeholder substitutes exactly
        monkeypatch.setenv("GRAFT_BLACKBOX_PATH",
                           str(tmp_path / "bb_rank2.json"))
        assert blackbox.default_path() == str(tmp_path / "bb_rank2.json")
        monkeypatch.setenv("GRAFT_BLACKBOX_PATH",
                           str(tmp_path / "bb.{rank}.json"))
        assert blackbox.default_path() == str(tmp_path / "bb.2.json")
        blackbox.set_clock_offset(0.125)
        doc = blackbox.snapshot()
        assert doc["rank"] == 2 and doc["clock_offset_s"] == 0.125
    finally:
        blackbox.set_rank(0)
        blackbox._clock_offset[0] = None


# ---------------------------------------------------------------------------
# cross-rank aggregation + straggler table
# ---------------------------------------------------------------------------

def test_aggregate_selftest_passes():
    assert aggregate.selftest() == []


def test_aggregate_blames_delayed_rank(tmp_path):
    delay = 0.2
    paths = []
    for rank in (0, 1):
        p = tmp_path / ("rank%d.json" % rank)
        p.write_text(json.dumps(aggregate._synthetic_dump(rank, delay)))
        paths.append(str(p))
    merged_path = str(tmp_path / "merged.json")
    report, trace = aggregate.analyze(paths, merged_out=merged_path)
    assert report["problems"] == []
    assert ttracing.validate_chrome_trace(trace) == []
    s = report["straggler_summary"]
    assert s["worst_rank"] == 1
    assert s["max_enter_spread_s"] == pytest.approx(delay, abs=0.02)
    assert s["blame"]["1"] == s["collectives_matched"] > 0
    # per-rank process tracks + >=1 flow link per reduced bucket
    pids = {e["pid"] for e in trace["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"}
    assert pids == {0, 1}
    labels = {r["label"] for r in report["stragglers"]}
    assert len(labels) == 2
    assert report["cross_rank_flow_links"] >= len(labels)
    flow_ids = {e["id"] for e in trace["traceEvents"]
                if e.get("ph") in ("s", "t", "f")}
    assert any(str(f).startswith("xr/") for f in flow_ids)
    with open(merged_path) as f:
        assert ttracing.validate_chrome_trace(json.load(f)) == []


def test_async_collectives_never_corrupt_clock_or_exit_blame(tmp_path):
    """Overlapped (reduce_many_async) events are stamped at host-local
    wait-return time: they must not serve as clock anchors (a healthy
    40ms host lag before wait() would fabricate a 40ms offset) nor as
    exit-spread evidence."""
    base = 1700000000.0
    lag = 0.04                    # rank 0 reaches wait() 40ms late
    docs = {}
    for rank in (0, 1):
        events = []
        for step in range(1, 4):
            t = base + step * 0.5
            # async reduce: both ranks ISSUE together (enter == t), but
            # rank 0's host returns from wait() `lag` later
            exit_ = t + 0.1 + (lag if rank == 0 else 0.0)
            events.append({"ts": exit_, "kind": "collective", "data": {
                "path": "reduce_many_async", "seq": step, "step": step,
                "bucket": "bucket[float32:8p:2048B]",
                "latency_ms": (exit_ - t) * 1e3}})
            events.append({"ts": t + 0.3, "kind": "dist_heartbeat",
                           "data": {"workers": 2, "step": step}})
        docs[rank] = dict(aggregate._synthetic_dump(rank, 0.0),
                          events=events, events_total=len(events))
        (tmp_path / ("a%d.json" % rank)).write_text(json.dumps(docs[rank]))
    report, _trace = aggregate.analyze([str(tmp_path / "a0.json"),
                                        str(tmp_path / "a1.json")])
    assert report["problems"] == []
    # clocks really are synced: the async wait lag must not leak in
    assert abs(report["clock_offsets_s"]["1"]) < 1e-6, report
    rows = report["stragglers"]
    assert rows
    for r in rows:
        assert r["last_to_exit"] is None and r["exit_spread_s"] is None
        assert r["enter_spread_s"] == pytest.approx(0.0, abs=1e-6)


def test_aggregate_mixed_trace_and_dump(tmp_path, fresh_lens):
    """A real profiler trace of this process merges with a synthetic
    peer dump: collective chrome spans carry seq/step so the join works
    across artifact kinds."""
    from incubator_mxnet_tpu import profiler
    blackbox.set_enabled(True)
    blackbox._ring.clear()
    tracefile = str(tmp_path / "r0_trace.json")
    try:
        seq0 = next(blackbox._collective_seq)
        profiler.set_config(filename=tracefile, profile_all=True)
        profiler.set_state("run")
        kv = mx.kv.create("local")
        kv.init("w", mx.nd.ones((8,)))
        kv.push("w", mx.nd.ones((8,)))
        out = mx.nd.zeros((8,))
        kv.pull("w", out=out)
        out.asnumpy()
        profiler.set_state("stop")
        profiler.dump()
    finally:
        blackbox.set_enabled(None)
    with open(tracefile) as f:
        doc = json.load(f)
    colls = [e for e in doc["traceEvents"]
             if e.get("cat") == "collective" and e.get("ph") == "X"]
    assert colls and all("seq" in e["args"] for e in colls)
    # a synthetic rank-1 dump whose collectives reuse the same seqs
    wall = aggregate._wall_fn(doc["otherData"]["wall_anchor"])
    events = []
    for e in colls:
        events.append({"ts": wall(e["ts"] + e.get("dur", 0.0)) + 0.05,
                       "kind": "collective",
                       "data": {"path": e["args"]["path"],
                                "seq": e["args"]["seq"], "rank": 1,
                                "nbytes": e["args"].get("nbytes"),
                                "latency_ms": 1.0}})
    peer = dict(aggregate._synthetic_dump(1, 0.0), events=events,
                events_total=len(events))
    p1 = tmp_path / "rank1.json"
    p1.write_text(json.dumps(peer))
    report, trace = aggregate.analyze([tracefile, str(p1)])
    assert report["problems"] == []
    assert report["cross_rank_flow_links"] >= 1
    assert seq0 >= 0
    # a rank's trace AND dump together are legitimate ('mixed freely'):
    # they merge onto ONE track — no phantom rank, no self-match
    own = dict(aggregate._synthetic_dump(0, 0.0), events=[
        {"ts": wall(e["ts"] + e.get("dur", 0.0)), "kind": "collective",
         "data": {"path": e["args"]["path"], "seq": e["args"]["seq"],
                  "rank": 0, "latency_ms": e.get("dur", 0.0) / 1e3}}
        for e in colls])
    p0 = tmp_path / "rank0_dump.json"
    p0.write_text(json.dumps(own))
    report, trace = aggregate.analyze([tracefile, str(p0), str(p1)])
    assert report["problems"] == []
    assert sorted(report["ranks"]) == ["0", "1"]
    assert len(report["ranks"]["0"]["sources"]) == 2
    pids = {e["pid"] for e in trace["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"}
    assert pids == {0, 1}
    for row in report["stragglers"]:
        assert sorted(row["ranks"]) == [0, 1]   # never rank 0 vs itself


def test_cli_analyze_and_steps(tmp_path, capsys):
    for rank in (0, 1):
        (tmp_path / ("r%d.json" % rank)).write_text(
            json.dumps(aggregate._synthetic_dump(rank, 0.1)))
    merged = str(tmp_path / "merged.json")
    rc = telemetry_main(["--analyze", str(tmp_path / "r0.json"),
                         str(tmp_path / "r1.json"), "--merged", merged,
                         "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    report = json.loads(out)
    assert report["straggler_summary"]["worst_rank"] == 1
    assert os.path.exists(merged)
    rc = telemetry_main(["--analyze", str(tmp_path / "r0.json"),
                         str(tmp_path / "r1.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "straggler table" in out and "worst rank: 1" in out


def test_cli_steps_renders_live_ring(capsys):
    rc = telemetry_main(["--steps", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["summary"]["steps"] == len(doc["steps"]) > 0
    for rec in doc["steps"]:
        total = sum(rec["components"].values())
        assert total == pytest.approx(rec["wall_s"], abs=1e-6)


# ---------------------------------------------------------------------------
# the 2-proc dist harness: a deliberately delayed rank must be named
# ---------------------------------------------------------------------------

_PRELUDE = textwrap.dedent("""
    import os, sys, traceback
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
""")


def _skipwrap(body):
    return _PRELUDE + "try:\n" \
        + textwrap.indent(textwrap.dedent(body), "    ") \
        + textwrap.dedent("""
            except Exception:
                if "Multiprocess computations aren't implemented" \\
                        in traceback.format_exc():
                    print("SKIP-MULTIPROC", flush=True)
                    os._exit(0)
                raise
        """)


_LENS_WORKER = """
    import time
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.telemetry import blackbox, lens

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, nw
    rs = np.random.RandomState(0)
    ps = []
    for k in range(8):
        p = gluon.Parameter("p%%d" %% k, shape=(8, 8))
        p.initialize(ctx=mx.cpu())
        p.data()._write(rs.randn(8, 8).astype(np.float32))
        ps.append(p)
    t = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01}, kvstore=kv)
    t._bucket_bytes_override = 1024
    t._overlap_override = False      # serial reduces: enter times carry
    #                                  the full straggler signal
    for step in range(4):
        if rank == 1:
            time.sleep(0.2)          # rank 1 is the deliberate straggler
        with autograd.record():
            loss = None
            for p in ps:
                y = (p.data() * p.data()).sum()
                loss = y if loss is None else loss + y
        loss.backward()
        t.step(1)
    ps[-1].data().asnumpy()

    # in-worker conservation check over the whole dist loop
    recs = lens.steps()
    assert len(recs) >= 4, recs
    for r in recs:
        total = sum(r["components"].values())
        assert abs(total - r["wall_s"]) < 1e-6, (r["components"],
                                                 r["wall_s"])
    out = blackbox.dump(path=r"%(dir)s/lens_bb.rank%%d.json" %% rank,
                        reason="manual")
    assert out, "dump failed"
    print("WORKER %%d LENS OK" %% rank, flush=True)
"""


def _launch_two(tmp_path, source, timeout=300, port_base=9900):
    worker = tmp_path / "worker.py"
    worker.write_text(source)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.abspath(repo) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    port = port_base + os.getpid() % 500
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "-p", str(port), sys.executable, str(worker)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        proc.wait()
        pytest.fail("2-process lens run deadlocked (%ds timeout)"
                    % timeout)
    out = stdout + stderr
    if "SKIP-MULTIPROC" in out:
        pytest.skip("backend lacks multiprocess CPU collectives")
    assert proc.returncode == 0, out[-3000:]
    return out


def test_two_process_straggler_analysis(tmp_path):
    """ISSUE-8 acceptance: train on the real 2-proc dist_sync wire with
    rank 1 deliberately delayed, dump both flight recorders, and the
    aggregator must name rank 1 in a schema-valid merged trace with
    cross-rank flow links per reduced bucket."""
    src = _skipwrap(_LENS_WORKER % {"dir": str(tmp_path)})
    out = _launch_two(tmp_path, src, timeout=300)
    assert "WORKER 0 LENS OK" in out and "WORKER 1 LENS OK" in out, \
        out[-3000:]
    p0 = tmp_path / "lens_bb.rank0.json"
    p1 = tmp_path / "lens_bb.rank1.json"
    assert p0.exists() and p1.exists()
    merged = str(tmp_path / "merged.json")
    report, trace = aggregate.analyze([str(p0), str(p1)],
                                      merged_out=merged)
    assert report["problems"] == []
    assert ttracing.validate_chrome_trace(trace) == []
    s = report["straggler_summary"]
    assert s["worst_rank"] == 1, report["straggler_summary"]
    assert s["max_enter_spread_s"] > 0.05
    # every reduced bucket got a matched row + flow link
    bucket_rows = [r for r in report["stragglers"]
                   if str(r["label"]).startswith("bucket[")]
    assert bucket_rows, report["stragglers"]
    assert all(r["last_to_enter"] == 1 for r in bucket_rows)
    assert report["cross_rank_flow_links"] >= len(bucket_rows)
    pids = {e["pid"] for e in trace["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"}
    assert pids == {0, 1}
