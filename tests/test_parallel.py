"""Parallelism tests on the 8-device virtual CPU mesh: data parallel
(fused step), ring attention (sp), pipeline (pp), flash attention kernel,
tensor-parallel sharding. The driver's dryrun_multichip covers the same
surface; these pin numerics."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import make_mesh, DataParallelTrainer
from incubator_mxnet_tpu.parallel.ring_attention import ring_attention
from incubator_mxnet_tpu.parallel.pipeline import pipeline_apply
from incubator_mxnet_tpu.ops.attention import (flash_attention,
                                               _attention_reference)


def test_data_parallel_trainer_matches_single_device():
    def build():
        mx.random.seed(7)
        net = nn.HybridSequential(prefix="dp_")
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu", in_units=8))
            net.add(nn.Dense(3, in_units=16))
        net.initialize(mx.init.Xavier())
        return net

    rs = np.random.RandomState(0)
    x = rs.randn(16, 8).astype(np.float32)
    y = (rs.rand(16) * 3).astype(np.float32)

    losses = {}
    for ndev in (1, 8):
        net = build()
        mesh = make_mesh({"dp": ndev})
        tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 optimizer="sgd",
                                 optimizer_params={"learning_rate": 0.1},
                                 mesh=mesh)
        cur = [float(tr.step(mx.nd.array(x), mx.nd.array(y)))
               for _ in range(4)]
        losses[ndev] = cur
    np.testing.assert_allclose(losses[1], losses[8], rtol=1e-4)


def test_ring_attention_matches_reference():
    mesh = make_mesh({"sp": 8})
    rs = np.random.RandomState(0)
    B, H, S, D = 2, 4, 64, 16
    q = rs.randn(B, H, S, D).astype(np.float32)
    k = rs.randn(B, H, S, D).astype(np.float32)
    v = rs.randn(B, H, S, D).astype(np.float32)
    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, axis="sp")
    ref = _attention_reference(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_causal():
    mesh = make_mesh({"sp": 4})
    rs = np.random.RandomState(1)
    B, H, S, D = 1, 2, 32, 8
    q = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    out = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    ref = _attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_fallback_and_grad():
    rs = np.random.RandomState(2)
    B, H, S, D = 1, 2, 16, 8
    q = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    out = flash_attention(q, k, v)
    ref = _attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)
    g = jax.grad(lambda a: flash_attention(a, k, v).sum())(q)
    g_ref = jax.grad(lambda a: _attention_reference(a, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4,
                               atol=1e-5)


def test_flash_attention_op_surface():
    rs = np.random.RandomState(3)
    q = mx.nd.array(rs.randn(1, 2, 8, 4).astype(np.float32))
    k = mx.nd.array(rs.randn(1, 2, 8, 4).astype(np.float32))
    v = mx.nd.array(rs.randn(1, 2, 8, 4).astype(np.float32))
    out = mx.nd._contrib_FlashAttention(q, k, v, causal=True)
    assert out.shape == (1, 2, 8, 4)


def test_pipeline_matches_sequential():
    mesh = make_mesh({"pp": 4})
    rs = np.random.RandomState(0)
    D = 16
    # 4 stages of y = relu(x @ W + b), identical shapes
    Ws = rs.randn(4, D, D).astype(np.float32) * 0.3
    bs = rs.randn(4, D).astype(np.float32) * 0.1
    params = {"W": jnp.asarray(Ws), "b": jnp.asarray(bs)}

    def stage(p, x):
        return jax.nn.relu(x @ p["W"] + p["b"])

    x = jnp.asarray(rs.randn(8, D).astype(np.float32))
    out = pipeline_apply(stage, params, x, mesh, axis="pp",
                         num_microbatches=4)
    ref = x
    for i in range(4):
        ref = jax.nn.relu(ref @ params["W"][i] + params["b"][i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


def test_tensor_parallel_matmul_sharding():
    """GSPMD tensor parallelism: column-parallel matmul over 'tp' — the
    strictly-more-general replacement for ctx_group placement (SURVEY §2.4
    model-parallelism row)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh({"tp": 8})
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 32).astype(np.float32))
    w = jnp.asarray(rs.randn(32, 64).astype(np.float32))
    w_sh = jax.device_put(w, NamedSharding(mesh, P(None, "tp")))
    x_rep = jax.device_put(x, NamedSharding(mesh, P()))

    @jax.jit
    def f(a, b):
        return a @ b

    out = f(x_rep, w_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), rtol=1e-4)
    # output is column-sharded over tp
    assert out.sharding.spec == P(None, "tp")


def test_dp_sp_2d_mesh_attention():
    """2-D mesh: batch over dp, sequence over sp — the composition the
    multi-chip dry run exercises."""
    mesh = make_mesh({"dp": 2, "sp": 4})
    rs = np.random.RandomState(4)
    B, H, S, D = 4, 2, 32, 8
    q = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    from jax.sharding import NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu._jax_compat import shard_map
    import functools
    from incubator_mxnet_tpu.parallel.ring_attention import _ring_body
    spec = P("dp", None, "sp", None)
    stat = P("dp", None, "sp")
    fn = shard_map(functools.partial(_ring_body, axis_name="sp",
                                     causal=False, scale=D ** -0.5),
                   mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=(spec, stat, stat),
                   check_vma=False)
    out, _, _ = fn(q, k, v)
    ref = _attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


@pytest.mark.parametrize("sq,sk,causal", [(16, 16, False), (16, 16, True),
                                          (8, 24, True), (24, 8, True),
                                          (128, 256, True)])
def test_flash_attention_grads_match_reference(sq, sk, causal):
    """Chunked flash backward vs autodiff of the dense reference, covering
    KV-cache decode shapes (Sq < Sk) and rows with no visible keys
    (Sq > Sk) — round-1 advisor findings on the causal mask + O(S²) bwd."""
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.randn(1, 2, sq, 16).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 2, sk, 16).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 2, sk, 16).astype(np.float32))
    g = jnp.asarray(rs.randn(1, 2, sq, 16).astype(np.float32))

    out, vjp = jax.vjp(lambda a, b, c: flash_attention(a, b, c, causal),
                       q, k, v)
    ref_out, ref_vjp = jax.vjp(
        lambda a, b, c: _attention_reference(a, b, c, causal), q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-4, atol=1e-5)
    for got, want in zip(vjp(g), ref_vjp(g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-4)


def test_mixed_precision_matches_f32():
    """bf16 compute + f32 masters tracks the f32 loss curve (reference
    mp_sgd semantics, src/operator/optimizer_op.cc mp_* ops)."""
    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Conv2D(8, kernel_size=3, padding=1))
            net.add(nn.BatchNorm())
            net.add(nn.Activation("relu"))
            net.add(nn.GlobalAvgPool2D())
            net.add(nn.Flatten())
            net.add(nn.Dense(4))
        return net

    rs = np.random.RandomState(0)
    x = rs.randn(16, 3, 8, 8).astype(np.float32)
    y = (rs.rand(16) * 4).astype(np.float32)
    losses = {}
    for dt in (None, "bfloat16"):
        mx.random.seed(0)
        net = build()
        net.initialize(mx.init.Xavier())
        tr = DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            mesh=make_mesh({"dp": 8}), dtype=dt)
        losses[dt] = [float(tr.step(mx.nd.array(x), mx.nd.array(y)))
                      for _ in range(8)]
    assert losses["bfloat16"][-1] < losses["bfloat16"][0]  # it learns
    np.testing.assert_allclose(losses[None], losses["bfloat16"], atol=0.05)


def test_sync_params_then_eager_eval():
    """sync_params must leave Block params usable by eager single-device
    forward (mesh-sharded buffers pulled to host first)."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1},
                             mesh=make_mesh({"dp": 8}))
    x = mx.nd.array(np.ones((8, 6), np.float32))
    y = mx.nd.array(np.zeros(8, np.float32))
    tr.step(x, y)
    tr.sync_params()
    out = net(mx.nd.array(np.ones((2, 6), np.float32)))
    assert out.shape == (2, 4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads_match_reference(causal):
    """Ring attention's custom vjp (dK/dV touring the ring) must equal the
    single-device reference autodiff (VERDICT r1: was inference-only)."""
    mesh = make_mesh({"sp": 4})
    rs = np.random.RandomState(11)
    B, H, S, D = 1, 2, 32, 8
    q = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    g = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))

    out, vjp = jax.vjp(lambda a, b, c: ring_attention(a, b, c, mesh, "sp",
                                                      causal), q, k, v)
    ref_out, ref_vjp = jax.vjp(
        lambda a, b, c: _attention_reference(a, b, c, causal), q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=2e-4, atol=2e-5)
    for got, want in zip(vjp(g), ref_vjp(g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-4)


def test_ring_attention_trains_in_jit():
    """grad-of-ring-attention inside jit over a dp×sp mesh (the long-context
    training configuration)."""
    mesh = make_mesh({"dp": 2, "sp": 4})
    rs = np.random.RandomState(5)
    q = jnp.asarray(rs.randn(2, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rs.randn(2, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(rs.randn(2, 2, 16, 8).astype(np.float32))

    @jax.jit
    def f(q, k, v):
        return jax.grad(
            lambda a: ring_attention(a, k, v, mesh, "sp", True).sum())(q)

    gq = f(q, k, v)
    g_ref = jax.grad(
        lambda a: _attention_reference(a, k, v, True).sum())(q)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-4)


def test_pipeline_train_step_matches_sequential():
    """GPipe backward: grads per stage equal the unpipelined chain's grads;
    a few SGD steps reduce the loss (VERDICT r1: was forward-only)."""
    from incubator_mxnet_tpu.parallel.pipeline import (pipeline_train_step,
                                                       make_pipeline_trainer)
    mesh = make_mesh({"pp": 4})
    rs = np.random.RandomState(0)
    D = 8
    Ws = (rs.randn(4, D, D) * 0.4).astype(np.float32)
    bs = (rs.randn(4, D) * 0.1).astype(np.float32)
    params = {"W": jnp.asarray(Ws), "b": jnp.asarray(bs)}
    x = jnp.asarray(rs.randn(8, D).astype(np.float32))
    y = jnp.asarray(rs.randn(8, D).astype(np.float32))

    def stage(p, a):
        return jnp.tanh(a @ p["W"] + p["b"])

    def loss_fn(out, y):
        return jnp.sum((out - y) ** 2, axis=-1)

    loss, grads = pipeline_train_step(stage, params, x, y, loss_fn, mesh,
                                      num_microbatches=4)

    def seq_objective(params):
        a = x
        for i in range(4):
            a = jnp.tanh(a @ params["W"][i] + params["b"][i])
        return jnp.mean(jnp.sum((a - y) ** 2, axis=-1))

    ref_loss, ref_grads = jax.value_and_grad(seq_objective)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for name in ("W", "b"):
        np.testing.assert_allclose(np.asarray(grads[name]),
                                   np.asarray(ref_grads[name]),
                                   rtol=1e-4, atol=1e-5)

    train = make_pipeline_trainer(stage, loss_fn, mesh, num_microbatches=4,
                                  learning_rate=0.05)
    p, losses = params, []
    for _ in range(10):
        p, l = train(p, x, y)
        losses.append(float(l))
    assert losses[-1] < 0.5 * losses[0], losses
