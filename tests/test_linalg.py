"""Tests for the la_op family + FFT/count_sketch.

Model: reference tests/python/unittest/test_operator.py test_laop* and
check_numeric_gradient (python/mxnet/test_utils.py:792).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def _rand_spd(b, n):
    rng = np.random.RandomState(7)
    a = rng.randn(b, n, n).astype("float32")
    return np.matmul(a, np.swapaxes(a, -1, -2)) + n * np.eye(n, dtype="float32")


def test_gemm_gemm2():
    rng = np.random.RandomState(0)
    A = rng.randn(2, 3, 4).astype("float32")
    B = rng.randn(2, 4, 5).astype("float32")
    C = rng.randn(2, 3, 5).astype("float32")
    out = nd.linalg.gemm(nd.array(A), nd.array(B), nd.array(C),
                         alpha=2.0, beta=0.5)
    assert_almost_equal(out.asnumpy(), 2.0 * np.matmul(A, B) + 0.5 * C, rtol=1e-4)
    out2 = nd.linalg.gemm2(nd.array(A), nd.array(B.swapaxes(-1, -2)),
                           transpose_b=True, alpha=3.0)
    assert_almost_equal(out2.asnumpy(), 3.0 * np.matmul(A, B), rtol=1e-4)


def test_potrf_potri_sumlogdiag():
    A = _rand_spd(3, 4)
    L = nd.linalg.potrf(nd.array(A))
    assert_almost_equal(np.matmul(L.asnumpy(), L.asnumpy().swapaxes(-1, -2)),
                        A, rtol=1e-3, atol=1e-3)
    Ainv = nd.linalg.potri(L)
    assert_almost_equal(np.matmul(Ainv.asnumpy(), A),
                        np.broadcast_to(np.eye(4, dtype="float32"), A.shape),
                        rtol=1e-2, atol=1e-2)
    sld = nd.linalg.sumlogdiag(L)
    assert_almost_equal(sld.asnumpy(),
                        np.sum(np.log(np.diagonal(L.asnumpy(), axis1=-2, axis2=-1)), -1),
                        rtol=1e-4)


def test_trmm_trsm_roundtrip():
    rng = np.random.RandomState(1)
    Lnp = np.tril(rng.rand(2, 4, 4).astype("float32") + 1.0)
    B = rng.randn(2, 4, 3).astype("float32")
    prod = nd.linalg.trmm(nd.array(Lnp), nd.array(B), alpha=2.0)
    back = nd.linalg.trsm(nd.array(Lnp), prod, alpha=0.5)
    assert_almost_equal(back.asnumpy(), B, rtol=1e-3, atol=1e-3)
    # rightside: X @ L^T
    Br = rng.randn(2, 3, 4).astype("float32")
    prod_r = nd.linalg.trmm(nd.array(Lnp), nd.array(Br), rightside=True,
                            transpose=True)
    assert_almost_equal(prod_r.asnumpy(),
                        np.matmul(Br, Lnp.swapaxes(-1, -2)), rtol=1e-3, atol=1e-3)


def test_syrk():
    rng = np.random.RandomState(2)
    A = rng.randn(2, 3, 5).astype("float32")
    out = nd.linalg.syrk(nd.array(A), alpha=1.5)
    assert_almost_equal(out.asnumpy(), 1.5 * np.matmul(A, A.swapaxes(-1, -2)),
                        rtol=1e-4, atol=1e-4)
    out_t = nd.linalg.syrk(nd.array(A), transpose=True)
    assert_almost_equal(out_t.asnumpy(), np.matmul(A.swapaxes(-1, -2), A),
                        rtol=1e-4, atol=1e-4)


def test_gelqf():
    rng = np.random.RandomState(3)
    A = rng.randn(2, 3, 5).astype("float32")
    Q, L = nd.linalg.gelqf(nd.array(A))
    Qn, Ln = Q.asnumpy(), L.asnumpy()
    assert_almost_equal(np.matmul(Ln, Qn), A, rtol=1e-3, atol=1e-3)
    assert_almost_equal(np.matmul(Qn, Qn.swapaxes(-1, -2)),
                        np.broadcast_to(np.eye(3, dtype="float32"), (2, 3, 3)),
                        rtol=1e-3, atol=1e-3)
    # L lower triangular with non-negative diagonal
    assert np.allclose(Ln, np.tril(Ln), atol=1e-5)
    assert (np.diagonal(Ln, axis1=-2, axis2=-1) >= -1e-5).all()


def test_syevd():
    A = _rand_spd(2, 5)
    U, w = nd.linalg.syevd(nd.array(A))
    Un, wn = U.asnumpy(), w.asnumpy()
    # A = U^T diag(w) U, rows of U are eigenvectors
    recon = np.matmul(Un.swapaxes(-1, -2) * wn[..., None, :], Un)
    assert_almost_equal(recon, A, rtol=1e-2, atol=1e-2)
    assert (np.diff(wn, axis=-1) >= -1e-4).all()  # ascending


def test_linalg_grad():
    """Numeric gradient through potrf+sumlogdiag (logdet) — the canonical
    composite the la_op family exists for."""
    from incubator_mxnet_tpu import autograd
    A = _rand_spd(1, 3)
    x = nd.array(A)
    x.attach_grad()
    with autograd.record():
        y = nd.linalg.sumlogdiag(nd.linalg.potrf(x))
        y.backward()
    # d logdet(A) / dA = A^{-1} (symmetrized halves for the factored path);
    # check against finite differences instead of the closed form to stay
    # convention-agnostic.
    g = x.grad.asnumpy()
    eps = 1e-2

    def f(a):
        import jax.numpy as jnp
        import jax
        L = jax.lax.linalg.cholesky(jnp.asarray(a))
        return float(jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1))))

    for i in range(3):
        d = np.zeros_like(A)
        d[0, i, i] = eps
        fd = (f(A + d) - f(A - d)) / (2 * eps)
        assert abs(fd - g[0, i, i]) < 1e-2, (i, fd, g[0, i, i])


def test_fft_ifft_roundtrip():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 8).astype("float32")
    y = nd.contrib.fft(nd.array(x))
    assert y.shape == (3, 16)
    c = np.fft.fft(x, axis=-1)
    inter = np.stack([c.real, c.imag], -1).reshape(3, 16).astype("float32")
    assert_almost_equal(y.asnumpy(), inter, rtol=1e-3, atol=1e-3)
    # unnormalized inverse: ifft(fft(x)) == N * x
    back = nd.contrib.ifft(y)
    assert_almost_equal(back.asnumpy(), 8.0 * x, rtol=1e-3, atol=1e-3)


def test_count_sketch():
    rng = np.random.RandomState(5)
    n, d, k = 4, 6, 3
    x = rng.randn(n, d).astype("float32")
    h = rng.randint(0, k, size=(1, d)).astype("float32")
    s = (rng.randint(0, 2, size=(1, d)) * 2 - 1).astype("float32")
    out = nd.contrib.count_sketch(nd.array(x), nd.array(h), nd.array(s),
                                  out_dim=k)
    expect = np.zeros((n, k), dtype="float32")
    for i in range(d):
        expect[:, int(h[0, i])] += s[0, i] * x[:, i]
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-4, atol=1e-4)


def test_linalg_symbolic():
    """la_op family reachable from the Symbol surface with correct shapes."""
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.linalg.gemm2(a, b)
    arg_shapes, out_shapes, _ = out.infer_shape(a=(2, 3, 4), b=(2, 4, 5))
    assert out_shapes[0] == (2, 3, 5)
    ex = out.bind(mx.cpu(), {"a": nd.ones((2, 3, 4)), "b": nd.ones((2, 4, 5))})
    y = ex.forward()[0]
    assert_almost_equal(y.asnumpy(), 4.0 * np.ones((2, 3, 5), "float32"))
