"""Tests for visualization, env config layer, and the im2rec tool.

Parity models: python/mxnet/visualization.py, docs/faq/env_var.md,
tools/im2rec.py.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import config, visualization


def _mlp():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.softmax(fc2, name="sm")


def test_print_summary(capsys):
    out = visualization.print_summary(_mlp(), shape={"data": (2, 8)})
    # params: fc1 = 8*16+16 = 144, fc2 = 16*4+4 = 68 → 212
    assert "Total params: 212" in out
    assert "fc1(FullyConnected)" in out
    assert "relu1(Activation)" in out


def test_plot_network_dot():
    res = visualization.plot_network(_mlp(), title="net")
    src = res if isinstance(res, str) else res.source
    assert "digraph" in src
    assert '"fc1" -> "relu1"' in src and '"relu1" -> "fc2"' in src
    assert '"data"' in src          # data var shown
    assert '"fc1_weight"' not in src  # weights hidden by default


def test_config_env_layer(monkeypatch):
    assert config.get("ENGINE_TYPE") == "AsyncEngine"
    monkeypatch.setenv("MXTPU_ENGINE_TYPE", "NaiveEngine")
    assert config.naive_engine()
    monkeypatch.delenv("MXTPU_ENGINE_TYPE")
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")  # fallback prefix
    assert config.naive_engine()
    monkeypatch.setenv("MXTPU_SEED", "123")
    assert config.get_int("SEED") == 123
    monkeypatch.setenv("MXTPU_PROFILER_AUTOSTART", "true")
    assert config.get_bool("PROFILER_AUTOSTART")
    doc = config.document()
    assert "MXTPU_ENGINE_TYPE" in doc and "NaiveEngine" in doc
    # generated doc is committed
    here = os.path.join(os.path.dirname(__file__), "..", "docs", "env_var.md")
    assert os.path.exists(here)


def test_im2rec_list_and_pack(tmp_path):
    cv2 = pytest.importorskip("cv2")
    root = tmp_path / "images"
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            img = (np.random.RandomState(i).rand(8, 8, 3) * 255).astype("uint8")
            cv2.imwrite(str(d / ("%s_%d.jpg" % (cls, i))), img)
    prefix = str(tmp_path / "set")
    tool = os.path.join(os.path.dirname(__file__), "..", "tools", "im2rec.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, tool, prefix, str(root),
                        "--list", "--recursive"], env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    lines = open(prefix + ".lst").read().strip().splitlines()
    assert len(lines) == 6
    labels = {float(ln.split("\t")[1]) for ln in lines}
    assert labels == {0.0, 1.0}

    r = subprocess.run([sys.executable, tool, prefix, str(root),
                        "--resize", "8"], env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec") and os.path.exists(prefix + ".idx")

    from incubator_mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    keys = sorted(rec.keys)
    assert len(keys) == 6
    hdr, img = recordio.unpack_img(rec.read_idx(keys[0]))
    assert img.shape[2] == 3 and hdr.label in (0.0, 1.0)


def test_rec2idx_roundtrip(tmp_path):
    """tools/rec2idx.py: an index built from a bare .rec enables read_idx
    random access identical to the write-time index."""
    import importlib.util
    import os
    import numpy as np
    from incubator_mxnet_tpu import recordio

    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXRecordIO(rec, "w")
    payloads = [b"rec-%d-" % i + bytes(np.arange(i % 7, dtype=np.uint8))
                for i in range(9)]
    for pl in payloads:
        w.write(pl)
    w.close()

    spec = importlib.util.spec_from_file_location(
        "rec2idx", os.path.join(os.path.dirname(__file__), "..",
                                "tools", "rec2idx.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.create_index(rec, idx) == len(payloads)

    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    for i in (0, 4, 8, 2):
        assert r.read_idx(i) == payloads[i]
    r.close()


def test_parse_log_markdown(tmp_path):
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "parse_log", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "parse_log.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    lines = [
        "INFO:root:Epoch[0] Train-accuracy=0.5",
        "INFO:root:Epoch[0] Time cost=12.5",
        "INFO:root:Epoch[0] Validation-accuracy=0.55",
        "INFO:root:Epoch[1] Train-accuracy=0.75",
        "INFO:root:Epoch[1] Time cost=11.0",
    ]
    data, cols = mod.parse(lines)
    assert data[0]["train-accuracy"] == 0.5
    assert data[0]["val-accuracy"] == 0.55
    assert data[1]["time"] == 11.0
    md = mod.to_markdown(data, cols)
    assert md.startswith("| epoch |") and "| 1 | 0.75" in md
    # scientific notation + regex-special metric names (round-4 advisor)
    data2, _ = mod.parse(["INFO:root:Epoch[2] Train-loss=1e-05"], ("loss",))
    assert data2[2]["train-loss"] == 1e-05
    data3, _ = mod.parse(
        ["INFO:root:Epoch[0] Train-top_k_accuracy_5=0.9"],
        ("top_k_accuracy_5",))
    assert data3[0]["train-top_k_accuracy_5"] == 0.9
