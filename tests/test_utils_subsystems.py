"""Tests for visualization, env config layer, and the im2rec tool.

Parity models: python/mxnet/visualization.py, docs/faq/env_var.md,
tools/im2rec.py.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import config, visualization


def _mlp():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.softmax(fc2, name="sm")


def test_print_summary(capsys):
    out = visualization.print_summary(_mlp(), shape={"data": (2, 8)})
    # params: fc1 = 8*16+16 = 144, fc2 = 16*4+4 = 68 → 212
    assert "Total params: 212" in out
    assert "fc1(FullyConnected)" in out
    assert "relu1(Activation)" in out


def test_plot_network_dot():
    res = visualization.plot_network(_mlp(), title="net")
    src = res if isinstance(res, str) else res.source
    assert "digraph" in src
    assert '"fc1" -> "relu1"' in src and '"relu1" -> "fc2"' in src
    assert '"data"' in src          # data var shown
    assert '"fc1_weight"' not in src  # weights hidden by default


def test_config_env_layer(monkeypatch):
    assert config.get("ENGINE_TYPE") == "AsyncEngine"
    monkeypatch.setenv("MXTPU_ENGINE_TYPE", "NaiveEngine")
    assert config.naive_engine()
    monkeypatch.delenv("MXTPU_ENGINE_TYPE")
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")  # fallback prefix
    assert config.naive_engine()
    monkeypatch.setenv("MXTPU_SEED", "123")
    assert config.get_int("SEED") == 123
    monkeypatch.setenv("MXTPU_PROFILER_AUTOSTART", "true")
    assert config.get_bool("PROFILER_AUTOSTART")
    doc = config.document()
    assert "MXTPU_ENGINE_TYPE" in doc and "NaiveEngine" in doc
    # generated doc is committed
    here = os.path.join(os.path.dirname(__file__), "..", "docs", "env_var.md")
    assert os.path.exists(here)


def test_im2rec_list_and_pack(tmp_path):
    cv2 = pytest.importorskip("cv2")
    root = tmp_path / "images"
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            img = (np.random.RandomState(i).rand(8, 8, 3) * 255).astype("uint8")
            cv2.imwrite(str(d / ("%s_%d.jpg" % (cls, i))), img)
    prefix = str(tmp_path / "set")
    tool = os.path.join(os.path.dirname(__file__), "..", "tools", "im2rec.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, tool, prefix, str(root),
                        "--list", "--recursive"], env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    lines = open(prefix + ".lst").read().strip().splitlines()
    assert len(lines) == 6
    labels = {float(ln.split("\t")[1]) for ln in lines}
    assert labels == {0.0, 1.0}

    r = subprocess.run([sys.executable, tool, prefix, str(root),
                        "--resize", "8"], env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec") and os.path.exists(prefix + ".idx")

    from incubator_mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    keys = sorted(rec.keys)
    assert len(keys) == 6
    hdr, img = recordio.unpack_img(rec.read_idx(keys[0]))
    assert img.shape[2] == 3 and hdr.label in (0.0, 1.0)
