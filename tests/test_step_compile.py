"""graftstep: whole-step compiled training — fwd+bwd+fused update as ONE
donated XLA program (two at a kvstore boundary).

The contract under test (gluon/step_compile.py):

* **Parity** — compiled params AND optimizer states track the
  bucketed-eager ``record → backward → Trainer.step`` triple over ≥5
  steps for sgd / momentum / adam / mp-bf16, within the documented ULP
  tolerance (lr/wd/rescale ride as traced OPERANDS in the compiled
  program where graftfuse bakes constants; operands can shift
  fma-contraction by ~1 ULP per step — the EH104 convention, asserted
  via ``max_ulp_diff``'s monotone int-key oracle rather than allclose).
* **Guards** — shape change, dtype change, and param freeze/thaw each
  cost exactly ONE eager fallback step + ONE lazy retrace;
  ``set_learning_rate`` and a batch-size change cost ZERO retraces (the
  whole point of the operand layout); a static-shape loop shows zero
  retraces after step 2.
* **Boundary** — behind a store the cross-worker reduce stays at the
  program boundary via the existing ``reduce_many`` wire (labeled
  ``compiled_step`` in the flight recorder).
* **Telemetry** — a compiled step books a conservation-exact lens
  window carrying ``compiled: True``.
* **Satellites** — first-touch pull ordering
  (``Trainer.note_first_touch_order`` / ``GRAFT_BUCKET_ORDER=touch``),
  the ``GRAFT_PREFETCH_DEPTH`` DataLoader knob, and the autotuner's
  worker→prefetch escalation.
"""
import os
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu import optimizer as opt
from incubator_mxnet_tpu.gluon.step_compile import (
    CompiledStep, max_ulp_diff, step_compile_enabled)
from incubator_mxnet_tpu.telemetry import autotune, blackbox, lens

import jax.numpy as jnp


ULP_TOL = 8          # documented operand-vs-constant fma drift budget
N_PARAMS = 4
SHAPE = (1, 5)


def make_net(prefix, n_params=N_PARAMS, shape=SHAPE, dtype="float32"):
    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                for k in range(n_params):
                    setattr(self, "w%d" % k,
                            self.params.get("w%d" % k, shape=shape,
                                            dtype=dtype))

        def hybrid_forward(self, F, x, **ps):
            acc = None
            for k in range(n_params):
                y = (ps["w%d" % k] * ps["w%d" % k] * x).sum()
                acc = y if acc is None else acc + y
            return acc

    return Net(prefix=prefix)


def seed_net(net, seed=7):
    rng = np.random.RandomState(seed)
    net.initialize(ctx=mx.cpu())
    for name in sorted(net.collect_params()):
        p = net.collect_params()[name]
        p.data()._write(jnp.asarray(
            rng.uniform(-1.0, 1.0, p.shape).astype(np.float32)
        ).astype(p.data().dtype))


def make_pair(optimizer="sgd", opt_kw=None, n_params=N_PARAMS,
              shape=SHAPE, dtype="float32", kvstore=None, loss=None):
    """Identical (eager-twin, compiled) nets + trainers + the CompiledStep."""
    opt_kw = dict(opt_kw or {"learning_rate": 0.05})
    out = []
    for tag in ("e", "c"):
        net = make_net("sc%s_" % tag, n_params, shape, dtype)
        seed_net(net)
        kv = mx.kv.create(kvstore) if kvstore else None
        tr = gluon.Trainer(net.collect_params(), optimizer, dict(opt_kw),
                           kvstore=kv)
        out.extend([net, tr])
    net_e, tr_e, net_c, tr_c = out
    cstep = tr_c.compile_step(net_c, loss=loss, enabled=True)
    return net_e, tr_e, net_c, tr_c, cstep


def eager_step(net, tr, *args, loss=None, batch_size=1):
    with autograd.record():
        if loss is not None:
            out = loss(net(*args[:-1]), args[-1])
        else:
            out = net(*args)
    out.backward()
    tr.step(batch_size)
    return out


def _leaves(state):
    if state is None:
        return []
    if isinstance(state, (tuple, list)):
        out = []
        for s in state:
            out.extend(_leaves(s))
        return out
    return [state]


def assert_parity(net_e, tr_e, net_c, tr_c, tol=ULP_TOL):
    for ne, nc in zip(sorted(net_e.collect_params()),
                      sorted(net_c.collect_params())):
        ulp = max_ulp_diff(net_e.collect_params()[ne].data()._read(),
                           net_c.collect_params()[nc].data()._read())
        assert ulp <= tol, "weight %s diverged by %s ULP" % (ne, ulp)
    se, sc = tr_e._updaters[0].states, tr_c._updaters[0].states
    assert set(se) == set(sc)
    for i in se:
        for a, b in zip(_leaves(se[i]), _leaves(sc[i])):
            ulp = max_ulp_diff(a._read(), b._read())
            assert ulp <= tol, "state %d diverged by %s ULP" % (i, ulp)


def xbatch(rng, shape=(6, 5)):
    return mx.nd.array(rng.uniform(0.5, 1.5, shape).astype(np.float32))


# ---------------------------------------------------------------------------
# parity: ≥5 steps per optimizer family, zero retraces after step 2
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("optimizer,opt_kw,dtype", [
    ("sgd", {"learning_rate": 0.05}, "float32"),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4},
     "float32"),
    ("adam", {"learning_rate": 0.01}, "float32"),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9,
             "multi_precision": True}, "bfloat16"),
], ids=["sgd", "momentum", "adam", "mp-bf16"])
def test_compiled_matches_eager_over_five_steps(optimizer, opt_kw, dtype):
    net_e, tr_e, net_c, tr_c, cstep = make_pair(optimizer, opt_kw,
                                                dtype=dtype)
    rng = np.random.RandomState(3)
    for _ in range(6):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert_parity(net_e, tr_e, net_c, tr_c)
    # step 1 fell back eager and traced lazily; steps 2..6 compiled with
    # ZERO further retraces — the acceptance criterion
    assert cstep.retraces == 1
    assert cstep.fallback_steps == 1
    assert cstep.compiled_steps == 5


def make_rowwise_net(prefix, n_params=N_PARAMS, shape=SHAPE):
    """Like make_net but per-ROW outputs (shape (N,)) so a batch-axis
    loss such as L2Loss has an axis to reduce over."""
    class Net(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                for k in range(n_params):
                    setattr(self, "w%d" % k,
                            self.params.get("w%d" % k, shape=shape))

        def hybrid_forward(self, F, x, **ps):
            acc = None
            for k in range(n_params):
                y = (ps["w%d" % k] * ps["w%d" % k] * x).sum(axis=1)
                acc = y if acc is None else acc + y
            return acc

    return Net(prefix=prefix)


def test_compiled_with_loss_fn_and_batch_size_change():
    """loss-callable call convention (last arg is the label) AND a
    batch-size change mid-loop: rescale rides as an operand, so no
    retrace — parity holds through both."""
    loss = gluon.loss.L2Loss()
    pair = []
    for tag in ("e", "c"):
        net = make_rowwise_net("scl%s_" % tag)
        seed_net(net)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9})
        pair.extend([net, tr])
    net_e, tr_e, net_c, tr_c = pair
    cstep = tr_c.compile_step(net_c, loss=loss, enabled=True)
    rng = np.random.RandomState(5)
    for step in range(6):
        x = xbatch(rng)
        y = mx.nd.array(rng.uniform(-1, 1, (6,)).astype(np.float32))
        bs = 1 if step < 3 else 4
        eager_step(net_e, tr_e, x, y, loss=loss, batch_size=bs)
        cstep(x, y, batch_size=bs)
    assert cstep.retraces == 1, \
        "batch-size change retraced (rescale must be an operand)"
    assert_parity(net_e, tr_e, net_c, tr_c)


def test_kvstore_boundary_reduce_stays_on_the_wire():
    """Behind a store the compiled step splits at the program boundary:
    program A's bucket flats go through KVStore.reduce_many (the
    existing collective bracket, labeled), then the donated update
    program applies the reduced flats.  Parity vs the eager twin on the
    same store type, and the labeled collective lands in the flight
    recorder."""
    marker = time.time()
    net_e, tr_e, net_c, tr_c, cstep = make_pair(
        "sgd", {"learning_rate": 0.05, "momentum": 0.9},
        kvstore="dist_sync")
    rng = np.random.RandomState(11)
    for _ in range(6):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 1
    assert cstep.compiled_steps == 5
    assert_parity(net_e, tr_e, net_c, tr_c)
    evs = [e for e in blackbox.events()
           if e.get("kind") == "collective" and e.get("ts", 0) >= marker
           and e.get("data", {}).get("label") == "compiled_step"]
    assert len(evs) >= 5, \
        "compiled steps must ride the labeled reduce_many wire"


# ---------------------------------------------------------------------------
# guards: what retraces, what must not
# ---------------------------------------------------------------------------

def test_set_learning_rate_does_not_retrace():
    net_e, tr_e, net_c, tr_c, cstep = make_pair(
        "sgd", {"learning_rate": 0.05, "momentum": 0.9})
    rng = np.random.RandomState(7)
    for _ in range(3):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 1
    tr_e.set_learning_rate(0.005)
    tr_c.set_learning_rate(0.005)
    for _ in range(3):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 1, \
        "set_learning_rate retraced the compiled step (lr is an operand)"
    assert cstep.compiled_steps == 5
    assert_parity(net_e, tr_e, net_c, tr_c)


def test_shape_change_guard_one_retrace_each_then_cached():
    net_e, tr_e, net_c, tr_c, cstep = make_pair("sgd")
    rng = np.random.RandomState(9)
    for _ in range(2):
        x = xbatch(rng, (6, 5))
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 1
    for _ in range(2):                      # new input shape: ONE retrace
        x = xbatch(rng, (3, 5))
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 2
    assert cstep.fallback_steps == 2
    # back to the first shape: the entry is still cached — no retrace
    x = xbatch(rng, (6, 5))
    eager_step(net_e, tr_e, x)
    cstep(x)
    assert cstep.retraces == 2
    assert_parity(net_e, tr_e, net_c, tr_c)


def test_dtype_change_guard_misses():
    net_e, tr_e, net_c, tr_c, cstep = make_pair("sgd")
    rng = np.random.RandomState(13)
    for _ in range(2):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 1
    x16 = xbatch(rng).astype("float16")
    x16e = x16.copy()
    eager_step(net_e, tr_e, x16e)
    cstep(x16)
    assert cstep.retraces == 2, "input dtype change must re-trace"
    assert_parity(net_e, tr_e, net_c, tr_c)


def test_param_freeze_thaw_guard():
    """Freezing a param (grad_req write → null) moves it out of the
    trainable set → guard miss, one retrace; thawing it back re-hits the
    ORIGINAL cached entry — no third trace.  The eager twin freezes
    identically, so parity holds throughout."""
    net_e, tr_e, net_c, tr_c, cstep = make_pair(
        "sgd", {"learning_rate": 0.05})
    rng = np.random.RandomState(17)
    for _ in range(2):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 1

    def freeze(net, req):
        name = sorted(net.collect_params())[0]
        net.collect_params()[name].grad_req = req

    freeze(net_e, "null")
    freeze(net_c, "null")
    for _ in range(2):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 2, "freeze must re-trace (fewer diff inputs)"
    assert_parity(net_e, tr_e, net_c, tr_c)
    freeze(net_e, "write")
    freeze(net_c, "write")
    for _ in range(2):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 2, "thaw back must re-hit the cached entry"
    assert_parity(net_e, tr_e, net_c, tr_c)


def test_kill_switch_and_recording_guard(monkeypatch):
    """GRAFT_STEP_COMPILE=0 runs every call on the bit-identical eager
    triple (zero compiled dispatches); calling a CompiledStep inside
    record() raises — the compiled step IS the whole triple."""
    monkeypatch.setenv("GRAFT_STEP_COMPILE", "0")
    assert not step_compile_enabled()
    assert step_compile_enabled(True)       # explicit override wins
    net_e, tr_e, net_c, tr_c, _ = make_pair("sgd")
    cstep = tr_c.compile_step(net_c)        # enabled=None → env decides
    rng = np.random.RandomState(19)
    for _ in range(3):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.compiled_steps == 0
    assert cstep.retraces == 0
    assert cstep.fallback_steps == 3
    # kill-switched steps ARE the eager triple: bit-identical, not ULP
    assert_parity(net_e, tr_e, net_c, tr_c, tol=0)
    with autograd.record():
        with pytest.raises(RuntimeError):
            cstep(xbatch(rng))


# ---------------------------------------------------------------------------
# telemetry: lens conservation + compiled flag
# ---------------------------------------------------------------------------

def test_compiled_step_books_conserved_lens_window():
    lens.set_enabled(True)
    lens.reset()
    try:
        _net_e, _tr_e, net_c, tr_c, cstep = make_pair(
            "sgd", {"learning_rate": 0.05, "momentum": 0.9})
        rng = np.random.RandomState(23)
        for _ in range(4):
            cstep(xbatch(rng))
        net_c.collect_params()[
            sorted(net_c.collect_params())[-1]].data().asnumpy()
        lens.pulse_drain(5.0)
        recs = lens.steps()
        assert len(recs) == 4
        for rec in recs:
            total = sum(rec["components"].values())
            assert total == pytest.approx(rec["wall_s"], abs=1e-6), \
                (rec["components"], rec["wall_s"])
            for v in rec["components"].values():
                assert v >= 0.0
        steady = recs[-1]
        assert steady.get("compiled") is True
        assert recs[0].get("compiled") is None      # the eager fallback
        # the programs were booked through the pulse ledger: some device
        # time must have landed inside the window
        assert steady["components"]["optimizer_update"] > 0 \
            or steady["device_busy_s"] >= 0.0
        # the compiled flag survives into the compact stream
        assert lens.compact(steady).get("compiled") is True
    finally:
        lens.pulse_drain(5.0)
        lens.reset()
        lens.set_enabled(None)


# ---------------------------------------------------------------------------
# satellite: first-touch pull ordering
# ---------------------------------------------------------------------------

def test_first_touch_order_recorded_and_fed_to_trainer():
    _net_e, _tr_e, net_c, tr_c, cstep = make_pair("sgd")
    rng = np.random.RandomState(29)
    cstep(xbatch(rng))                      # fallback + lazy trace
    assert cstep.forward_order is not None
    assert tr_c._first_touch_order == cstep.forward_order
    # the toy net touches w0..w3 in definition order
    names = [tr_c._params[i].name for i in cstep.forward_order]
    suffixes = [n.rsplit("w", 1)[-1] for n in names]
    assert suffixes == sorted(suffixes, key=int)
    assert len(cstep.forward_order) == N_PARAMS


def test_touch_perm_orders_pull_keys():
    params = [gluon.Parameter("tp%d" % k, shape=(2,)) for k in range(4)]
    for p in params:
        p.initialize(ctx=mx.cpu())
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore=None)
    assert tr._first_touch_order is None
    tr.note_first_touch_order((2, 0))
    assert tr._first_touch_order == (2, 0)
    # touched params first (in touch order), untouched after in index order
    assert tr._touch_perm([0, 1, 2, 3]) == [2, 0, 1, 3]
    # dedup + bounds filtering
    tr.note_first_touch_order((1, 1, 3, 99))
    assert tr._first_touch_order == (1, 3)


def test_bucket_order_touch_mode(monkeypatch):
    from incubator_mxnet_tpu import overlap
    monkeypatch.setenv("GRAFT_BUCKET_ORDER", "touch")
    assert overlap.bucket_order() == "touch"
    params = [gluon.Parameter("bo%d" % k, shape=(2,)) for k in range(3)]
    for p in params:
        p.initialize(ctx=mx.cpu())
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore=None)
    mode, sig_perm, build_perm = tr._plan_order()
    assert mode == "touch"
    assert build_perm == (0, 1, 2)          # nothing recorded yet
    tr.note_first_touch_order((2, 1))
    mode, sig_perm, build_perm = tr._plan_order()
    assert build_perm == (2, 1, 0)
    assert sig_perm == build_perm           # recording re-keys the plan


# ---------------------------------------------------------------------------
# satellite: GRAFT_PREFETCH_DEPTH + autotuner escalation
# ---------------------------------------------------------------------------

def test_prefetch_depth_knob(monkeypatch):
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataloader import (
        prefetch_depth_default)
    from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset
    assert prefetch_depth_default() == 2    # the double-buffer default
    monkeypatch.setenv("GRAFT_PREFETCH_DEPTH", "5")
    assert prefetch_depth_default() == 5
    monkeypatch.setenv("GRAFT_PREFETCH_DEPTH", "0")
    assert prefetch_depth_default() == 1    # floor: one in flight
    monkeypatch.setenv("GRAFT_PREFETCH_DEPTH", "junk")
    assert prefetch_depth_default() == 2
    ds = ArrayDataset(mx.nd.array(np.arange(32, dtype=np.float32)))
    loader = DataLoader(ds, batch_size=4, prefetch_device=False)
    try:
        assert loader.prefetch_depth() == 2
        loader.set_prefetch_depth(6)        # live override beats the env
        assert loader.prefetch_depth() == 6
        loader.set_prefetch_depth(0)
        assert loader.prefetch_depth() == 1
        out = [b for b in loader]
        assert len(out) == 8                # depth never changes content
    finally:
        loader.close()


def _fake_rec(step, wall=0.1, data_wait=0.06):
    comp = {c: 0.0 for c in lens.COMPONENTS}
    comp["data_wait"] = data_wait
    comp["host_gap"] = wall - data_wait
    return {"step": step, "origin": "trainer", "wall_s": wall,
            "components": comp, "comm_blocked_s": 0.0,
            "comm_inflight_s": 0.0, "collectives": 0, "io_waits": 0}


def test_autotune_escalates_to_prefetch_when_workers_capped():
    """Workers grow first; once the starved loader is at the worker cap,
    the SAME data_wait signal doubles its prefetch depth instead —
    journaled, cooldown'd, capped at max_prefetch."""
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset
    ds = ArrayDataset(mx.nd.array(np.arange(16, dtype=np.float32)))
    loader = DataLoader(ds, batch_size=2, num_workers=2,
                        prefetch_device=False)
    loader._blocked_wait_s = 1.0            # looks starved to the ranker
    autotune.set_enabled(True)
    ctrl = autotune.Autotuner(interval=1, cooldown=0, data_wait_bound=0.2,
                              max_workers=2, max_prefetch=8)
    try:
        ctrl.attach_loader(loader)
        marker = time.time()
        ctrl.on_step(_fake_rec(0))
        # workers were already at the cap → the prefetch knob moved
        assert loader._num_workers == 2
        assert loader.prefetch_depth() == 4
        ctrl.on_step(_fake_rec(1))
        assert loader.prefetch_depth() == 8
        ctrl.on_step(_fake_rec(2))
        assert loader.prefetch_depth() == 8  # max_prefetch cap holds
        grows = [d for d in ctrl.decisions()
                 if d["target"] == "prefetch_depth"]
        assert [(d["old"], d["new"]) for d in grows] == [(2, 4), (4, 8)]
        evs = [e for e in blackbox.events()
               if e.get("kind") == "autotune_decision"
               and e.get("ts", 0) >= marker
               and e.get("data", {}).get("target") == "prefetch_depth"]
        assert len(evs) == 2
    finally:
        autotune.set_enabled(None)
        loader.close()


def test_autotune_worker_growth_still_first():
    """A loader below the worker cap grows workers, NOT prefetch —
    escalation only fires when worker growth is exhausted."""
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset
    ds = ArrayDataset(mx.nd.array(np.arange(16, dtype=np.float32)))
    loader = DataLoader(ds, batch_size=2, num_workers=1,
                        prefetch_device=False)
    loader._blocked_wait_s = 1.0
    autotune.set_enabled(True)
    ctrl = autotune.Autotuner(interval=1, cooldown=0, data_wait_bound=0.2,
                              max_workers=4, max_prefetch=8)
    try:
        ctrl.attach_loader(loader)
        ctrl.on_step(_fake_rec(0))
        assert loader._num_workers == 2
        assert loader.prefetch_depth() == 2  # untouched
    finally:
        autotune.set_enabled(None)
        loader.close()


# ---------------------------------------------------------------------------
# selftest tier (the run_lint hook) stays green
# ---------------------------------------------------------------------------

def test_module_selftest():
    from incubator_mxnet_tpu.gluon import step_compile
    assert step_compile.selftest() == []
