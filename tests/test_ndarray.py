"""NDArray tests (parity: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.test_utils import assert_almost_equal, default_context


def test_creation():
    x = mx.nd.zeros((3, 4))
    assert x.shape == (3, 4)
    assert x.dtype == np.float32
    assert x.size == 12
    assert_almost_equal(x, np.zeros((3, 4)))

    y = mx.nd.ones((2, 2), dtype="int32")
    assert y.dtype == np.int32
    assert_almost_equal(y, np.ones((2, 2)))

    z = mx.nd.full((2, 3), 7.5)
    assert_almost_equal(z, np.full((2, 3), 7.5))

    a = mx.nd.array([[1, 2], [3, 4]])
    assert a.dtype == np.float32  # list default float32 like reference
    assert_almost_equal(a, [[1, 2], [3, 4]])

    r = mx.nd.arange(0, 10, 2)
    assert_almost_equal(r, np.arange(0, 10, 2, dtype=np.float32))


def test_elementwise_arith():
    a_np = np.random.rand(3, 4).astype(np.float32)
    b_np = np.random.rand(3, 4).astype(np.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    assert_almost_equal(a + b, a_np + b_np)
    assert_almost_equal(a - b, a_np - b_np)
    assert_almost_equal(a * b, a_np * b_np)
    assert_almost_equal(a / b, a_np / b_np)
    assert_almost_equal(a ** 2, a_np ** 2)
    assert_almost_equal(a + 1, a_np + 1)
    assert_almost_equal(2 - a, 2 - a_np)
    assert_almost_equal(2 / a, 2 / a_np)
    assert_almost_equal(-a, -a_np)
    assert_almost_equal(abs(-a), np.abs(a_np))


def test_inplace_arith():
    a_np = np.random.rand(3, 4).astype(np.float32)
    a = mx.nd.array(a_np)
    a += 1
    assert_almost_equal(a, a_np + 1)
    a *= 2
    assert_almost_equal(a, (a_np + 1) * 2)


def test_comparison():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([2.0, 2.0, 2.0])
    assert_almost_equal(a > b, [0, 0, 1])
    assert_almost_equal(a >= b, [0, 1, 1])
    assert_almost_equal(a == b, [0, 1, 0])
    assert_almost_equal(a == 2, [0, 1, 0])
    # dtype preserved (mxnet returns same-dtype 0/1)
    assert (a > b).dtype == np.float32


def test_broadcast():
    a = mx.nd.ones((3, 1))
    b = mx.nd.ones((1, 4)) * 2
    c = a + b
    assert c.shape == (3, 4)
    assert_almost_equal(c, np.full((3, 4), 3.0))
    d = mx.nd.broadcast_to(mx.nd.array([[1.0], [2.0]]), shape=(2, 3))
    assert_almost_equal(d, [[1, 1, 1], [2, 2, 2]])


def test_indexing_and_views():
    x = mx.nd.array(np.arange(12).reshape(3, 4))
    # int index → view (NDArray::At)
    row = x[1]
    assert row.shape == (4,)
    assert_almost_equal(row, [4, 5, 6, 7])
    # slice → view sharing storage (NDArray::Slice)
    v = x[1:3]
    v[:] = 0
    assert_almost_equal(x, [[0, 1, 2, 3], [0, 0, 0, 0], [0, 0, 0, 0]])
    # write through int index
    x[0] = 9
    assert_almost_equal(x[0], [9, 9, 9, 9])
    # setitem with array value
    x[2] = mx.nd.array([1, 2, 3, 4])
    assert_almost_equal(x[2], [1, 2, 3, 4])


def test_reshape_view_semantics():
    x = mx.nd.array(np.arange(6).reshape(2, 3))
    r = x.reshape((3, 2))
    r[0] = -1
    # write through the reshape view must hit the base (reference: views
    # share the Chunk, ndarray.h:523)
    assert_almost_equal(x, [[-1, -1, 2], [3, 4, 5]])
    # mxnet reshape special codes
    y = mx.nd.zeros((2, 3, 4))
    assert y.reshape((-1,)).shape == (24,)
    assert y.reshape((0, -1)).shape == (2, 12)
    assert y.reshape((-2,)).shape == (2, 3, 4)
    assert y.reshape((-3, 0)).shape == (6, 4)
    assert y.reshape((0, -4, 1, 3, 0)).shape == (2, 1, 3, 4)


def test_dtype_cast():
    x = mx.nd.array([1.5, 2.5])
    y = x.astype("int32")
    assert y.dtype == np.int32
    z = x.astype(np.float16)
    assert z.dtype == np.float16


def test_copy_and_context():
    x = mx.nd.array([1.0, 2.0])
    y = x.copy()
    y += 1
    assert_almost_equal(x, [1, 2])
    assert_almost_equal(y, [2, 3])
    z = mx.nd.zeros((2,))
    x.copyto(z)
    assert_almost_equal(z, [1, 2])
    w = x.as_in_context(mx.cpu(0))
    assert w.context.device_type == "cpu"


def test_scalar_conversion():
    x = mx.nd.array([3.5])
    assert x.asscalar() == 3.5
    assert float(x) == 3.5
    with pytest.raises(ValueError):
        mx.nd.array([1.0, 2.0]).asscalar()


def test_reductions():
    a_np = np.random.rand(2, 3, 4).astype(np.float32)
    a = mx.nd.array(a_np)
    assert_almost_equal(a.sum(), a_np.sum())
    assert_almost_equal(a.sum(axis=1), a_np.sum(axis=1))
    assert_almost_equal(mx.nd.sum(a, axis=(0, 2)), a_np.sum(axis=(0, 2)))
    assert_almost_equal(mx.nd.mean(a), a_np.mean())
    assert_almost_equal(mx.nd.max(a, axis=2), a_np.max(axis=2))
    assert_almost_equal(mx.nd.min(a), a_np.min())
    assert_almost_equal(mx.nd.norm(a), np.sqrt((a_np ** 2).sum()))
    # exclude semantics (reference broadcast_reduce_op)
    assert_almost_equal(mx.nd.sum(a, axis=1, exclude=True), a_np.sum(axis=(0, 2)))


def test_dot():
    a_np = np.random.rand(3, 4).astype(np.float32)
    b_np = np.random.rand(4, 5).astype(np.float32)
    assert_almost_equal(mx.nd.dot(mx.nd.array(a_np), mx.nd.array(b_np)),
                        a_np @ b_np, rtol=1e-4, atol=1e-4)
    # transpose flags
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a_np), mx.nd.array(b_np.T), transpose_b=True),
        a_np @ b_np, rtol=1e-4, atol=1e-4)
    # batch_dot
    x = np.random.rand(2, 3, 4).astype(np.float32)
    y = np.random.rand(2, 4, 5).astype(np.float32)
    assert_almost_equal(mx.nd.batch_dot(mx.nd.array(x), mx.nd.array(y)),
                        np.matmul(x, y), rtol=1e-4, atol=1e-4)


def test_concat_split_stack():
    a = mx.nd.ones((2, 3))
    b = mx.nd.zeros((2, 3))
    c = mx.nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = mx.nd.split(mx.nd.array(np.arange(12).reshape(2, 6)), num_outputs=3, axis=1)
    assert len(s) == 3 and s[0].shape == (2, 2)
    assert_almost_equal(s[1], [[2, 3], [8, 9]])
    st = mx.nd.stack(a, b, axis=1)
    assert st.shape == (2, 2, 3)


def test_take_onehot():
    w = mx.nd.array(np.arange(12).reshape(4, 3))
    idx = mx.nd.array([0, 2])
    out = mx.nd.take(w, idx)
    assert_almost_equal(out, [[0, 1, 2], [6, 7, 8]])
    oh = mx.nd.one_hot(mx.nd.array([1, 0]), depth=3)
    assert_almost_equal(oh, [[0, 1, 0], [1, 0, 0]])
    e = mx.nd.Embedding(idx, w, input_dim=4, output_dim=3)
    assert_almost_equal(e, [[0, 1, 2], [6, 7, 8]])


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs")
    a = mx.nd.array(np.random.rand(3, 4))
    b = mx.nd.array(np.random.rand(5))
    mx.nd.save(fname, [a, b])
    loaded = mx.nd.load(fname)
    assert_almost_equal(loaded[0], a)
    assert_almost_equal(loaded[1], b)
    mx.nd.save(fname, {"w": a, "b": b})
    d = mx.nd.load(fname)
    assert set(d) == {"w", "b"}
    assert_almost_equal(d["w"], a)


def test_wait_and_iter():
    x = mx.nd.ones((4, 2))
    x.wait_to_read()
    mx.nd.waitall()
    rows = list(x)
    assert len(rows) == 4 and rows[0].shape == (2,)
    assert len(x) == 4


def test_random_moments():
    mx.random.seed(7)
    u = mx.nd.random.uniform(0, 1, shape=(50000,))
    assert abs(float(u.mean().asscalar()) - 0.5) < 0.02
    n = mx.nd.random.normal(2.0, 3.0, shape=(50000,))
    assert abs(float(n.mean().asscalar()) - 2.0) < 0.1
    # determinism under seed
    mx.random.seed(42)
    a = mx.nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    b = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert np.array_equal(a, b)


def test_empty_allocates_on_target_device():
    """nd.empty(ctx=cpu) must not bounce through the default device (a
    per-parameter accelerator->host download during init at scale)."""
    import incubator_mxnet_tpu as mx
    a = mx.nd.empty((4, 5), ctx=mx.cpu(0))
    dev = a._read().sharding.device_set
    assert all(d.platform == "cpu" for d in dev)
    assert a.shape == (4, 5)
    assert float(a.asnumpy().sum()) == 0.0
