"""graftelastic: live membership change — epoch-fenced re-partition,
checkpoint-streamed rejoin, quiesce, chaos sites (PR 20).

Single-process coverage via the simulated-N-rank harness
(``elastic.harness``) plus direct unit tests of the membership algebra,
the lockstep epoch re-base, the stream protocol, ``quiesce()``, and the
armor restore-across-world-sizes contract.
"""
import os
import pickle
import tempfile
import zlib
from concurrent.futures import Future

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import elastic
from incubator_mxnet_tpu.analysis import lockstep
from incubator_mxnet_tpu.armor import checkpoint as ckpt
from incubator_mxnet_tpu.armor import faults
from incubator_mxnet_tpu.armor.errors import (CheckpointCorruptError,
                                              CollectiveTimeoutError,
                                              FaultInjectedError,
                                              MembershipChangedError,
                                              QuiesceTimeoutError,
                                              ShardOwnershipError)
from incubator_mxnet_tpu.elastic import (InProcessByteStore, Membership,
                                         MembershipView, key_owner,
                                         merge_shard_states,
                                         repartition_plan,
                                         repartition_shard_states)
from incubator_mxnet_tpu.elastic import rejoin as erj
from incubator_mxnet_tpu.elastic.harness import (SimulatedCluster,
                                                 shard_owner)

_ENV = ("GRAFT_ELASTIC", "GRAFT_FAULTS", "GRAFT_REJOIN_TIMEOUT",
        "GRAFT_QUIESCE_TIMEOUT", "GRAFT_BUCKET_BYTES",
        "GRAFT_SHARD_OPTIMIZER")


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {k: os.environ.get(k) for k in _ENV}
    yield
    faults.reset()
    elastic.set_enabled(None)
    lockstep.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# -- membership algebra ------------------------------------------------------

def test_view_advance_pure_and_monotonic():
    v0 = MembershipView(0, range(4))
    assert v0.world_size == 4 and v0.ranks == (0, 1, 2, 3)
    v1 = v0.advance(departed=[2])
    assert v1.epoch == 1 and v1.ranks == (0, 1, 3)
    assert v1.departed == (2,) and v1.joined == ()
    assert v0.advance(departed=[2]) == v1           # pure
    v2 = v1.advance(joined=[5])
    assert v2.epoch == 2 and v2.ranks == (0, 1, 3, 5)
    with pytest.raises(ValueError):
        MembershipView(0, [0]).advance(departed=[0])


def test_key_owner_matches_ps_wire_hash():
    from incubator_mxnet_tpu.parallel import ps
    for k in ("w0", "dense0_weight", 17, "__quant_ef__/f32:0"):
        for n in (1, 2, 3, 5):
            assert key_owner(k, n) == zlib.crc32(str(k).encode()) % n
    # mirrors GroupClient placement exactly
    gc = object.__new__(ps.GroupClient)
    gc._n = 3
    assert all(key_owner(k, 3) == gc._shard_of(str(k))
               for k in ("a", "b", "c", "w17"))


def test_repartition_plan_minimal_and_order_free():
    keys = ["w%d" % i for i in range(64)]
    plan, moved = repartition_plan(keys, 4, 3)
    assert repartition_plan(list(reversed(keys)), 4, 3) == (plan, moved)
    assert all(plan[k][0] != plan[k][1] for k in moved)
    unmoved = [k for k in keys if k not in moved]
    assert all(plan[k][0] == plan[k][1] for k in unmoved)
    assert repartition_plan(keys, 4, 4)[1] == []


@pytest.mark.parametrize("old_n,new_n", [(2, 4), (4, 2)])
def test_shard_state_repartition_both_directions(old_n, new_n):
    blobs = [pickle.dumps(({i: "state%d" % i,
                            "__quant_ef__/float32:%d" % i: "ef%d" % i},
                           "OPT" if i == 0 else None))
             for i in range(old_n)]
    merged, opt = merge_shard_states(blobs)
    assert opt == "OPT"
    assert set(merged) == (set(range(old_n))
                           | {"__quant_ef__/float32:%d" % i
                              for i in range(old_n)})
    out = repartition_shard_states(blobs, new_n)
    assert len(out) == new_n and len(set(out)) == 1
    assert out == repartition_shard_states(blobs, new_n)   # deterministic
    re_merged, re_opt = merge_shard_states(out[:1])
    assert re_merged == merged and re_opt == "OPT"


# -- lockstep epoch re-base --------------------------------------------------

def test_lockstep_epoch_base_and_fold_value():
    assert lockstep.epoch_base(0) == 0
    b1, b2 = lockstep.epoch_base(1), lockstep.epoch_base(2)
    assert b1 != b2 and b1 == lockstep.epoch_base(1)
    r = lockstep.fold_value(b1, 1, "reduce_many", 4, 1024)
    assert r == lockstep.fold_value(b1, 1, "reduce_many", 4, 1024)
    assert r != lockstep.fold_value(b2, 1, "reduce_many", 4, 1024)


def test_lockstep_rebase_reseeds_and_keeps_divergence():
    lockstep.reset()
    lockstep.rebase(3)
    snap = lockstep.snapshot()
    assert snap["epoch"] == 3
    assert snap["rolling_hash"] == lockstep.epoch_base(3)
    assert snap["folds"] == 0
    lockstep.reset()
    assert lockstep.snapshot()["epoch"] == 0


# -- the per-rank state machine + step fence ---------------------------------

def test_membership_queue_and_fence():
    m = Membership(0, world_size=3)
    assert m.epoch == 0 and not m.pending()
    m.request_change(departed=[1])
    m.request_change(joined=[1])
    assert m.pending()
    final = m.apply_pending()
    assert final.epoch == 2 and final.ranks == (0, 1, 2)
    assert not m.pending() and m.apply_pending() is None


def test_repartition_drop_keeps_old_view_deterministically():
    faults.configure("membership.repartition:drop:times=1")
    launch = MembershipView(0, range(3))
    lag, ok = Membership(0, view=launch), Membership(2, view=launch)
    for m in (lag, ok):
        m.request_change(departed=[1])
    lag.apply_pending()
    ok.apply_pending()
    assert (lag.epoch, ok.epoch) == (0, 1)
    faults.reset()
    # the dropped change is consumed, not replayed
    lag.apply_pending()
    assert lag.epoch == 0 and not lag.pending()


def test_join_chaos_seeded_replay_is_deterministic():
    def verdicts(n):
        faults.configure("membership.join:error:p=0.5:seed=13:times=100")
        out = []
        for _ in range(n):
            try:
                faults.fault_point("membership.join", tag="t")
                out.append(False)
            except FaultInjectedError:
                out.append(True)
        return out
    a, b = verdicts(24), verdicts(24)
    assert a == b and any(a) and not all(a)


def test_trainer_step_fence_gated_on_elastic(simple_trainer=None):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, random_state
    random_state.seed(7)
    net = gluon.nn.Dense(3, prefix="fence_test_")
    net.initialize(ctx=mx.cpu())
    x = mx.nd.array(np.random.RandomState(0).randn(2, 5).astype(np.float32))
    net(x)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    m = Membership(0, world_size=4)
    trainer.attach_membership(m)
    seen = []
    trainer.on_membership_change(lambda view: seen.append(view.epoch))
    m.request_change(departed=[3])

    def step():
        with autograd.record():
            loss = (net(x) * net(x)).sum()
        loss.backward()
        trainer.step(2)

    elastic.set_enabled(False)
    step()
    assert m.epoch == 0 and m.pending() and seen == []
    elastic.set_enabled(True)
    step()
    assert m.epoch == 1 and not m.pending() and seen == [1]


def test_enabled_memoizes_env():
    os.environ.pop("GRAFT_ELASTIC", None)
    elastic.set_enabled(None)
    assert elastic.enabled() is False
    os.environ["GRAFT_ELASTIC"] = "1"
    assert elastic.enabled() is True
    os.environ["GRAFT_ELASTIC"] = "off"
    assert elastic.enabled() is False
    elastic.set_enabled(True)
    assert elastic.enabled() is True


# -- quiesce -----------------------------------------------------------------

def test_base_kvstore_quiesce_is_noop():
    kv = mx.kv.create("local")
    assert kv.quiesce() == 0
    assert kv.quiesce(timeout=0.01) == 0


def _bare_dist_kv():
    from incubator_mxnet_tpu.parallel.dist import DistKVStore
    kv = object.__new__(DistKVStore)
    kv._push_futs = []
    kv._pull_pool = None
    return kv


def test_quiesce_timeout_is_typed_and_keeps_ownership():
    kv = _bare_dist_kv()
    stuck = Future()
    kv._push_futs = [stuck]
    with pytest.raises(QuiesceTimeoutError) as ei:
        kv.quiesce(timeout=0.05)
    exc = ei.value
    assert isinstance(exc, CollectiveTimeoutError)
    assert exc.site == "kvstore.quiesce" and exc.pending == 1
    assert kv._push_futs == [stuck]       # still owned for barrier/close
    stuck.set_result(None)
    assert kv.quiesce(timeout=1.0) == 1
    assert kv._push_futs == []


def test_quiesce_surfaces_failure_after_drain():
    kv = _bare_dist_kv()
    good, bad = Future(), Future()
    good.set_result(None)
    bad.set_exception(RuntimeError("wire died"))
    kv._push_futs = [good, bad]
    with pytest.raises(RuntimeError, match="wire died"):
        kv.quiesce(timeout=1.0)
    assert kv._push_futs == []            # drained despite the failure


def test_quiesce_timeout_env_default():
    from incubator_mxnet_tpu.parallel.dist import DistKVStore
    os.environ["GRAFT_QUIESCE_TIMEOUT"] = "7.5"
    assert DistKVStore._quiesce_timeout() == 7.5
    os.environ["GRAFT_QUIESCE_TIMEOUT"] = "junk"
    assert DistKVStore._quiesce_timeout() == 30.0
    os.environ.pop("GRAFT_QUIESCE_TIMEOUT", None)
    assert DistKVStore._quiesce_timeout() == 30.0


# -- the rejoin stream -------------------------------------------------------

def test_stream_roundtrip_and_chunking():
    os.environ["GRAFT_BUCKET_BYTES"] = str(64 << 10)   # floor: forces chunks
    store = InProcessByteStore()
    payload = os.urandom(200 << 10)
    fd, tmp = tempfile.mkstemp()
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        man = erj.stream_snapshot(store, tmp, "t1")
        assert man["nchunks"] == 4 and man["nbytes"] == len(payload)
        assert erj.fetch_snapshot(store, "t1", timeout=2.0) == payload
    finally:
        os.unlink(tmp)


def test_fetch_absent_stream_times_out_typed():
    faults.reset()
    with pytest.raises(CollectiveTimeoutError) as ei:
        erj.fetch_snapshot(InProcessByteStore(), "missing", timeout=0.2)
    assert ei.value.site == "membership.join"


def test_fetch_torn_stream_raises_corrupt():
    import hashlib
    import json
    store = InProcessByteStore()
    raw = b"x" * 1000
    mkey, ckeys = erj._keys("torn", 1)
    store.init({ckeys[0]: np.frombuffer(raw[:-1], np.uint8)})
    store.init({mkey: np.frombuffer(json.dumps(
        {"nchunks": 1, "nbytes": len(raw),
         "sha256": hashlib.sha256(raw).hexdigest(), "tag": "torn"},
        sort_keys=True).encode(), np.uint8)})
    with pytest.raises(CheckpointCorruptError):
        erj.fetch_snapshot(store, "torn", timeout=2.0)


def test_join_drop_consumes_budget_not_stream():
    store = InProcessByteStore()
    fd, tmp = tempfile.mkstemp()
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(b"payload-bytes")
        erj.stream_snapshot(store, tmp, "t2")
        faults.configure("membership.join:drop:n=1")
        # first poll is dropped, second finds the manifest
        assert erj.fetch_snapshot(store, "t2",
                                  timeout=5.0) == b"payload-bytes"
        faults.configure("membership.join:drop")       # every poll dropped
        with pytest.raises(CollectiveTimeoutError):
            erj.fetch_snapshot(store, "t2", timeout=0.2)
    finally:
        os.unlink(tmp)


# -- the simulated cluster: kill + rejoin byte parity ------------------------

def test_kill_rejoin_byte_parity_across_epochs():
    base = SimulatedCluster(3).run(6)
    assert base.digests_agree()
    c = SimulatedCluster(3)
    c.run(2)
    c.kill(1)
    c.run(2)
    c.rejoin(1)
    c.run(2)
    assert sorted(c.epochs_seen) == [0, 1, 2]
    assert c.digests_agree()
    assert c.loss_trajectory == base.loss_trajectory
    assert c.params_bytes() == base.params_bytes()
    assert c.params_bytes(1) == c.params_bytes(0)


def test_shard_owner_is_pure_in_view():
    v = MembershipView(4, [0, 2, 3])
    owners = [shard_owner(s, v) for s in range(6)]
    assert owners == [0, 2, 3, 0, 2, 3]
    assert owners == [shard_owner(s, MembershipView(4, [3, 0, 2]))
                      for s in range(6)]    # rank order never matters


# -- armor restore across a changed world size (satellite 6) -----------------

def _tiny_trainer(seed=3):
    from incubator_mxnet_tpu import autograd, gluon, random_state
    random_state.seed(seed)
    net = gluon.nn.Dense(4, prefix="elastic_ckpt_")
    net.initialize(ctx=mx.cpu())
    rs = np.random.RandomState(seed)
    x = mx.nd.array(rs.randn(2, 6).astype(np.float32))
    net(x)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    with autograd.record():
        loss = (net(x) * net(x)).sum()
    loss.backward()
    trainer.step(2)                 # momentum state materializes
    return net, trainer


@pytest.mark.parametrize("old_n,new_n", [(2, 4), (4, 2)])
def test_restore_across_world_size(old_n, new_n):
    _, t1 = _tiny_trainer()
    t1._zero_spec = lambda: {"axis": "ctx", "n": old_n, "rank": 0}
    state = ckpt.snapshot_trainer(t1, 9)
    assert state["shard"]["n"] == old_n
    assert "membership_epoch" in state

    net2, t2 = _tiny_trainer()
    t2._zero_spec = lambda: {"axis": "ctx", "n": new_n, "rank": 1}
    elastic.set_enabled(False)
    with pytest.raises(ShardOwnershipError) as ei:
        ckpt.restore_trainer(t2, state)
    assert ei.value.epoch is not None
    assert "GRAFT_ELASTIC" in str(ei.value)

    elastic.set_enabled(True)
    assert ckpt.restore_trainer(t2, state) == 9
    got = {n: np.asarray(p.data()._read()).tobytes()
           for n, p in net2.collect_params().items()}
    net3, t3 = _tiny_trainer()
    t3._zero_spec = lambda: {"axis": "ctx", "n": new_n, "rank": 1}
    assert ckpt.restore_trainer(t3, state) == 9
    assert {n: np.asarray(p.data()._read()).tobytes()
            for n, p in net3.collect_params().items()} == got


def test_restore_axis_change_refuses_even_with_elastic():
    _, t1 = _tiny_trainer()
    t1._zero_spec = lambda: {"axis": "ctx", "n": 2, "rank": 0}
    state = ckpt.snapshot_trainer(t1, 1)
    _, t2 = _tiny_trainer()
    t2._zero_spec = lambda: {"axis": "worker", "n": 2, "rank": 0}
    elastic.set_enabled(True)
    with pytest.raises(ShardOwnershipError):
        ckpt.restore_trainer(t2, state)


def test_membership_changed_error_fields():
    exc = MembershipChangedError(2, 4, departed=[1], joined=[5],
                                 detail="peer ahead")
    assert exc.old_epoch == 2 and exc.new_epoch == 4
    assert exc.departed == (1,) and exc.joined == (5,)
    assert "epoch 2 -> 4" in str(exc) and "peer ahead" in str(exc)
