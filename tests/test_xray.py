"""graftxray tests (ISSUE 18): scope-map parsing from optimized HLO,
conservation-exact phase attribution over synthetic profiler traces,
the ONE shared parser core behind both the online capture path and the
offline ``--ingest-xla`` CLI, trigger plumbing (slow-step lens observer,
watchdog trip, explicit request), off-by-default inertness, the
at-trace-time cost ledger + retrace cost diffing (the EH301 feed), the
full compiled-window selftest, and the ``--xray`` renderer."""
import json
import os
import time
import types
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx  # noqa: F401
from incubator_mxnet_tpu.telemetry import aggregate, blackbox, lens, xray


@pytest.fixture
def fresh_xray(monkeypatch):
    """Armed, clean harness for one test."""
    monkeypatch.setenv("GRAFT_XRAY", "1")
    xray.reset()
    yield xray
    xray.reset()


@pytest.fixture
def dark_xray(monkeypatch):
    """Explicitly DISarmed harness."""
    monkeypatch.delenv("GRAFT_XRAY", raising=False)
    xray.reset()
    yield xray
    xray.reset()


# ---------------------------------------------------------------------------
# scope maps from optimized HLO
# ---------------------------------------------------------------------------

_HLO = """\
HloModule jit_gstep_one, entry_computation_layout={(f32[1,5]{1,0})->f32[1,5]{1,0}}

%fused_computation (p0: f32[1,5]) -> f32[1,5] {
  %p0 = f32[1,5]{1,0} parameter(0)
  ROOT %m = f32[1,5]{1,0} multiply(%p0, %p0)
}

ENTRY %main.42 (param_0: f32[1,5]) -> f32[1,5] {
  %param_0 = f32[1,5]{1,0} parameter(0)
  %fusion.1 = f32[1,5]{1,0} fusion(%param_0), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(gstep_one)/jit(main)/xray:forward/mul" source_file="net.py" source_line=7}
  %loop_add = f32[1,5]{1,0} add(%fusion.1, %fusion.1), metadata={op_name="jit(gstep_one)/jit(main)/xray:update[0]/xray:inner/add"}
  %copy.9 = f32[1,5]{1,0} copy(%loop_add), metadata={op_name="jit(gstep_one)/jit(main)/convert"}
  ROOT %sub.3 = f32[1,5]{1,0} subtract(%copy.9, %fusion.1), metadata={op_name="jit(gstep_one)/jit(main)/xray:backward/sub"}
}
"""


def test_scope_map_from_hlo_parses_fusions_root_and_skips_scopeless():
    m = xray.scope_map_from_hlo(_HLO)
    assert m == {
        "fusion.1": "forward",
        # nested scopes resolve to the OUTERMOST xray token
        "loop_add": "update[0]",
        "sub.3": "backward",
    }
    # scope-less ops (copy.9, param_0, the fused-computation body) are
    # left out — they pool into "unattributed" at attribution time
    assert "copy.9" not in m and "param_0" not in m and "m" not in m


def test_phase_of_first_token_wins_and_hyphen_spelling_is_excluded():
    assert xray.phase_of(
        "jit(f)/xray:update[3]/xray:inner/add") == "update[3]"
    # the optimizer's fused-formula scope is DELIBERATELY spelled with
    # a hyphen ("xray-apply-sgd") so bucket-grained update[k] phases
    # stay the unit of attribution — it must NOT parse as a phase
    assert xray.phase_of("jit(f)/xray-apply-sgd/mul") is None
    assert xray.phase_of("") is None
    assert xray.phase_of(None) is None


def test_norm_module_strips_jit_prefix_and_uniquifier():
    assert xray._norm_module("jit_gstep_one.5") == "gstep_one"
    assert xray._norm_module("jit_gstep_update") == "gstep_update"
    assert xray._norm_module("gstep_one") == "gstep_one"
    assert xray._norm_module(None) == ""


# ---------------------------------------------------------------------------
# attribution: the conservation-exact partition
# ---------------------------------------------------------------------------

def _dev_ev(name, ts_us, dur_us, op=None, module="jit_gstep_one.3",
            step=None, pid=7):
    args = {}
    if op is not None:
        args["hlo_op"] = op
    if module is not None:
        args["hlo_module"] = module
    if step is not None:
        args["step"] = step
    return {"ph": "X", "name": name, "pid": pid, "tid": 1,
            "ts": ts_us, "dur": dur_us, "args": args}


def _meta(pid=7, name="/device:TPU:0 Compute"):
    return {"ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": name}}


def test_attribute_exact_conservation_with_fractional_us():
    """Fractional-µs durations (the TPU trace reality) must still sum
    EXACTLY: durations accumulate as integer nanoseconds, so the phase
    partition + unattributed == program span is integer equality, not
    a float tolerance."""
    scope_maps = {"gstep_one": {"fusion.1": "forward",
                                "loop_add": "update[0]",
                                "sub.3": "backward"}}
    events = [
        _meta(),
        _dev_ev("fusion.1", 100.0, 10.3, op="fusion.1", step=0),
        _dev_ev("sub.3", 111.0, 20.7, op="sub.3", step=0),
        _dev_ev("loop_add", 132.5, 5.1, op="loop_add", step=1),
        # scope-less op of a REGISTERED module -> unattributed
        _dev_ev("copy.9", 138.0, 0.7, op="copy.9", step=1),
        # op of an UNREGISTERED module -> unattributed
        _dev_ev("other", 139.0, 3.3, op="whatever",
                module="jit_warmup.1", step=1),
        # host event on a non-device pid: excluded entirely
        {"ph": "X", "name": "python", "pid": 1, "tid": 2,
         "ts": 100.0, "dur": 500.0, "args": {}},
    ]
    rep = xray.attribute(events, scope_maps=scope_maps)
    assert rep["device_events"] == 5
    assert set(rep["phases"]) == {"forward", "backward", "update[0]"}
    assert rep["phases"]["forward"]["device_s"] == pytest.approx(10.3e-6)
    assert rep["phases"]["backward"]["device_s"] == pytest.approx(20.7e-6)
    assert rep["phases"]["update[0]"]["device_s"] == pytest.approx(5.1e-6)
    assert rep["unattributed_s"] == pytest.approx((0.7 + 3.3) * 1e-6)
    # the conservation contract is EXACT (integer ns), not approx
    assert rep["conservation_ok"]
    assert rep["program_device_s"] == (10300 + 20700 + 5100 + 700
                                       + 3300) * 1e-9
    # shares partition to 1 over phases + unattributed
    total_share = sum(p["share"] for p in rep["phases"].values())
    assert total_share == pytest.approx(1.0 - (4000 / 40100))
    # true device-side window in the trace timebase
    assert rep["span"]["t0"] == pytest.approx(100.0e-6)
    assert rep["span"]["t1"] == pytest.approx((139.0 + 3.3) * 1e-6)
    # modules roll up by normalized name
    assert set(rep["modules"]) == {"gstep_one", "warmup"}
    # the shared ledger produced one row per step stamp
    steps = [r["step"] for r in rep["ledger"]["steps"]]
    assert steps == [0, 1]
    for row in rep["ledger"]["steps"]:
        assert row["busy_s"] + row["idle_s"] == pytest.approx(
            row["wall_s"])
    # top op is the backward sub
    assert rep["top_ops"][0]["op"] == "sub.3"
    assert rep["top_ops"][0]["phase"] == "backward"


def test_attribute_empty_and_scopeless_traces():
    rep = xray.attribute([], scope_maps={})
    assert rep["device_events"] == 0
    assert rep["phases"] == {}
    assert rep["conservation_ok"]    # 0 + 0 == 0
    assert rep["span"] is None
    # a trace with device ops but NO registered scope maps: everything
    # pools into unattributed, conservation still exact
    events = [_meta(), _dev_ev("x", 10.0, 2.5, op="x", step=0)]
    rep = xray.attribute(events, scope_maps={})
    assert rep["phases"] == {}
    assert rep["unattributed_s"] == pytest.approx(2.5e-6)
    assert rep["conservation_ok"]


def test_parse_trace_offline_twin(tmp_path):
    doc = {"traceEvents": [_meta(),
                           _dev_ev("f", 5.0, 4.0, op="fusion.1", step=0)]}
    p = tmp_path / "t.trace.json"
    p.write_text(json.dumps(doc))
    rep = xray.parse_trace(str(p),
                           scope_maps={"gstep_one": {"fusion.1": "fwd"}})
    assert rep["phases"]["fwd"]["device_s"] == pytest.approx(4.0e-6)
    assert rep["conservation_ok"]


# ---------------------------------------------------------------------------
# parser unification: ONE shared core behind aggregate.ingest_xla and
# the online capture sessions
# ---------------------------------------------------------------------------

def test_parser_core_is_shared_not_cloned():
    """The offline CLI's parser internals must BE the xray core (same
    function objects), not a drifting copy — the dedup the refactor
    promised."""
    assert aggregate._merge_intervals is xray.merge_intervals
    assert aggregate._DEVICE_PID_HINTS is xray.DEVICE_PID_HINTS


def test_ingest_xla_and_attribute_agree_on_step_rows(tmp_path):
    """Both paths run the same events through step_spans/step_rows: the
    per-step device ledger rows must be identical."""
    events = [_meta(),
              _dev_ev("a", 10.0, 3.0, op="a", step=0),
              _dev_ev("b", 14.0, 2.0, op="b", step=0),
              _dev_ev("c", 17.0, 4.5, op="c", step=1),
              _dev_ev("d", 30.0, 1.5, op="d")]      # unstamped pool
    p = tmp_path / "steps.trace.json"
    p.write_text(json.dumps({"traceEvents": events}))
    offline = aggregate.ingest_xla(str(p))
    online = xray.attribute(events, scope_maps={})
    assert offline["steps"] == online["ledger"]["steps"]
    assert offline["total"] == online["ledger"]["total"]


# ---------------------------------------------------------------------------
# triggers + capture lifecycle
# ---------------------------------------------------------------------------

def test_unarmed_harness_is_inert(dark_xray):
    assert not xray.armed()
    assert xray.request_capture("manual") is False
    xray.dispatch_begin()
    xray.dispatch_end(sync=None)
    assert xray._dispatch_count[0] == 0      # begin returned pre-count
    assert xray._pending == []
    assert not xray.capture_active()
    assert xray.sessions() == []
    # the triggered paths stay inert too
    xray._lens_trigger({"compiled": True, "wall_s": 9.9})
    assert xray._pending == []


def test_request_capture_dedups_and_caps(fresh_xray):
    assert xray.request_capture("manual") is True
    assert xray.request_capture("manual") is True    # accepted, deduped
    assert xray._pending == ["manual"]
    for i in range(10):
        xray.request_capture("r%d" % i)
    assert len(xray._pending) == 4                   # FIFO cap


def test_slow_step_lens_trigger(fresh_xray):
    """≥8 compiled walls build the baseline; one outlier past
    GRAFT_XRAY_SLOW_X × median requests a one-shot capture."""
    for _ in range(10):
        xray._lens_trigger({"compiled": True, "wall_s": 0.01})
    assert xray._pending == []                       # steady state
    xray._lens_trigger({"compiled": True, "wall_s": 1.0})
    assert "slow-step" in xray._pending
    # eager (non-compiled) outliers never trigger — the capture harness
    # profiles the compiled step only
    xray.reset()
    for _ in range(10):
        xray._lens_trigger({"compiled": True, "wall_s": 0.01})
    xray._lens_trigger({"compiled": False, "wall_s": 5.0})
    assert xray._pending == []


def test_slow_step_trigger_needs_baseline(fresh_xray):
    """The first few walls must not trigger — no median yet."""
    for w in (0.01, 0.02, 5.0):
        xray._lens_trigger({"compiled": True, "wall_s": w})
    assert xray._pending == []


def test_watchdog_trip_on_compiled_bracket_requests_capture(
        fresh_xray, monkeypatch, tmp_path):
    from incubator_mxnet_tpu.telemetry import watchdog as wdmod
    monkeypatch.setattr(wdmod._blackbox, "dump",
                        lambda **kw: str(tmp_path / "dump.json"))
    wd = wdmod.Watchdog(timeout=1.0, abort=False)
    entry = {"site": "compiled_step", "since": time.time() - 5.0,
             "detail": {"compiled": True, "programs": 2},
             "thread": "MainThread"}
    wd.trip(entry, 5.0)
    assert "watchdog:compiled_step" in xray._pending
    # a NON-compiled hang (an eager collective, a loader stall) must
    # not burn the one-shot on a trace that can't explain it
    xray.reset()
    entry = {"site": "ps_push", "since": time.time() - 5.0,
             "detail": {"keys": 3}, "thread": "MainThread"}
    wd.trip(entry, 5.0)
    assert xray._pending == []


# ---------------------------------------------------------------------------
# cost ledger + retrace diffing (the EH301 feed)
# ---------------------------------------------------------------------------

class _FakeCompiled(object):
    """Weakref-able stand-in for jax.stages.Compiled."""

    def __init__(self, flops, hlo=""):
        self._flops = float(flops)
        self._hlo = hlo

    def cost_analysis(self):
        return {"flops": self._flops, "bytes accessed": 4096.0}

    def memory_analysis(self):
        return types.SimpleNamespace(temp_size_in_bytes=128,
                                     argument_size_in_bytes=256,
                                     output_size_in_bytes=64,
                                     generated_code_size_in_bytes=32)

    def as_text(self):
        return self._hlo


def test_note_program_journals_costs_and_retrace_diffs(fresh_xray):
    marker = time.time()
    c1 = _FakeCompiled(1000.0)
    c2 = _FakeCompiled(2500.0)
    costs = xray.note_program("gstep_one", c1, label="one/4p/2b")
    assert costs["flops"] == 1000.0
    assert costs["bytes_accessed"] == 4096.0
    assert costs["temp_bytes"] == 128.0
    assert xray.cost_regressions() == ""          # first build: no diff
    xray.note_program("gstep_one", c2, label="one/4p/2b")
    hist = xray.cost_history("gstep_one")
    assert [h["flops"] for h in hist] == [1000.0, 2500.0]
    line = xray.cost_regressions()
    assert "gstep_one" in line and "flops" in line
    assert "1e+03" in line and "2.5e+03" in line
    evs = [e for e in blackbox.events() if e.get("ts", 0) >= marker]
    kinds = [e["kind"] for e in evs]
    assert kinds.count("xray_cost") == 2
    diffs = [e for e in evs if e["kind"] == "xray_cost_diff"]
    assert len(diffs) == 1
    assert diffs[0]["data"]["program"] == "gstep_one"
    assert diffs[0]["data"]["flops"] == {"old": 1000.0, "new": 2500.0}
    del c1, c2


def test_cost_regressions_ignores_shrinkage(fresh_xray):
    """The storm report names what got MORE expensive; a program that
    got cheaper is not a regression."""
    xray.note_program("p", _FakeCompiled(2000.0))
    xray.note_program("p", _FakeCompiled(500.0))
    assert xray.cost_regressions() == ""


def test_diff_costs_threshold():
    old = {"flops": 1000.0, "temp_bytes": 64.0}
    assert xray.diff_costs(old, {"flops": 1001.0, "temp_bytes": 64.0}) \
        == {}                                # < 0.5%: noise, not a diff
    d = xray.diff_costs(old, {"flops": 1200.0})
    assert d["flops"] == (1000.0, 1200.0)
    assert d["temp_bytes"] == (64.0, None)   # disappeared fields surface


def test_scope_maps_resolve_lazily_from_live_executables(fresh_xray):
    c = _FakeCompiled(1.0, hlo=_HLO)
    xray.note_program("gstep_one", c)
    maps = xray._scope_maps()
    assert maps["gstep_one"]["fusion.1"] == "forward"
    # a collected executable drops out instead of erroring
    xray.note_program("gone", _FakeCompiled(1.0))
    import gc
    gc.collect()
    assert "gone" not in xray._scope_maps() or \
        xray._scope_maps().get("gone") is not None
    del c


def test_eh301_storm_report_names_cost_growth(fresh_xray):
    """The retrace-storm warning must carry the cost-ledger diff: not
    just WHICH guard churned but what got more expensive."""
    from incubator_mxnet_tpu.analysis.compile_safety import StepAuditor
    xray.note_program("gstep_one", _FakeCompiled(1000.0))
    xray.note_program("gstep_one", _FakeCompiled(3000.0))
    aud = StepAuditor(label="t")
    with warnings.catch_warnings(record=True) as got:
        warnings.simplefilter("always")
        for _ in range(StepAuditor.STORM_MISSES):
            aud.note_call()
            aud.note_miss("bspecs", "bucket count 2 -> 3")
    storm = [w for w in got if "EH301" in str(w.message)]
    assert storm, "no EH301 storm warning raised"
    msg = str(storm[-1].message)
    assert "cost growth since previous trace" in msg
    assert "gstep_one" in msg and "flops" in msg


# ---------------------------------------------------------------------------
# the full compiled window (the selftest is the acceptance contract)
# ---------------------------------------------------------------------------

def test_xray_selftest_compiled_window_conserves():
    """End-to-end: a real compiled 3-step capture on this backend —
    phase rows present, conservation EXACT, armed-idle dispatches
    inert.  (The same scenario lint tier 12 runs.)"""
    problems = xray.selftest()
    assert problems == [], problems


def test_capture_session_publishes_to_lens_and_blackbox(monkeypatch):
    """Run the selftest scenario manually and check the publication
    fan-out: blackbox xray_capture event, lens window annotation."""
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.gluon import step_compile as sc
    monkeypatch.setenv("GRAFT_XRAY", "1")
    monkeypatch.setenv("GRAFT_XRAY_STEPS", "2")
    monkeypatch.delenv("GRAFT_XRAY_EVERY", raising=False)
    xray.reset()
    marker = time.time()
    try:
        net = sc._make_net("graftxraytest_", n_params=3, shape=(1, 4))
        sc._seed_params(net)
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.05}, kvstore=None)
        cstep = sc.CompiledStep(tr, net, enabled=True)
        rng = np.random.RandomState(3)

        def batch():
            return mx.nd.array(
                rng.uniform(0.5, 1.5, (4, 4)).astype(np.float32))

        for _ in range(2):
            cstep(batch())
        assert cstep.compiled_steps >= 1
        assert xray.request_capture("test-hook")
        for _ in range(3):
            cstep(batch())
        sess = xray.sessions()
        assert sess and sess[-1]["ok"], sess
        s = sess[-1]
        assert s["reason"] == "test-hook"
        assert s["steps"] == 2
        rep = s["report"]
        assert rep["conservation_ok"]
        assert rep["phases"]
        evs = [e for e in blackbox.events()
               if e["kind"] == "xray_capture" and e["ts"] >= marker]
        assert evs and evs[-1]["data"]["reason"] == "test-hook"
        assert evs[-1]["data"]["conservation_ok"] is True
        if lens.enabled():
            annotated = [r for r in lens.steps() if "xray" in r]
            assert annotated
            x = annotated[-1]["xray"]
            assert x["reason"] == "test-hook"
            assert x["program_device_s"] > 0.0
    finally:
        xray.reset()


# ---------------------------------------------------------------------------
# the --xray renderer
# ---------------------------------------------------------------------------

def _fake_session(reason="manual", ok=True):
    return {"reason": reason, "steps": 3, "wall_s": 0.5,
            "at": time.time(), "ok": ok,
            "report": {"phases": {"forward": {"device_s": 1.5e-3,
                                              "share": 0.6},
                                  "backward": {"device_s": 0.5e-3,
                                               "share": 0.2}},
                       "unattributed_s": 0.5e-3,
                       "program_device_s": 2.5e-3,
                       "conservation_ok": True,
                       "top_ops": [{"op": "fusion.1", "phase": "forward",
                                    "device_s": 1.0e-3, "count": 3}]}}


def test_cli_xray_renders_live_sessions(capsys):
    from incubator_mxnet_tpu.telemetry.__main__ import main as tmain
    xray.reset()
    try:
        with xray._session_lock:
            xray._sessions.append(_fake_session("slow-step"))
        assert tmain(["--xray"]) == 0
        out = capsys.readouterr().out
        assert "slow-step" in out
        assert "forward" in out and "backward" in out
        assert "conservation EXACT" in out
        assert "fusion.1" in out
    finally:
        xray.reset()


def test_cli_xray_renders_blackbox_dump(tmp_path, capsys):
    """Dump events nest fields under "data" — the renderer must read
    them there (not flat) and fall back to the flattened phase dict the
    blackbox publication writes."""
    from incubator_mxnet_tpu.telemetry.__main__ import main as tmain
    doc = {"events": [
        {"ts": 1.0, "kind": "xray_capture",
         "data": {"reason": "watchdog:compiled_step", "steps": 2,
                  "ok": True, "phases": {"forward": 0.002},
                  "unattributed_s": 0.001, "program_device_s": 0.003,
                  "conservation_ok": True,
                  "top_ops": [{"op": "sub.3", "phase": "backward",
                               "device_us": 11.5, "count": 2}]}},
        {"ts": 2.0, "kind": "other", "data": {}},
    ]}
    p = tmp_path / "dump.json"
    p.write_text(json.dumps(doc))
    assert tmain(["--xray", str(p)]) == 0
    out = capsys.readouterr().out
    assert "watchdog:compiled_step" in out
    assert "forward" in out
    assert "conservation EXACT" in out
    assert tmain(["--xray", str(p), "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed[0]["reason"] == "watchdog:compiled_step"


def test_cli_xray_empty_state_hints_at_arming(capsys):
    from incubator_mxnet_tpu.telemetry.__main__ import main as tmain
    xray.reset()
    assert tmain(["--xray"]) == 0
    assert "GRAFT_XRAY=1" in capsys.readouterr().out
