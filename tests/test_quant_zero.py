"""graftzero: block-scaled quantized bucket allreduce (error feedback)
+ ZeRO-1 sharded optimizer update.

The wire contract (docs/observability.md "quantization contract"): a
quantized reduce keeps the collective stream's SHAPE — one reduce per
bucket, same issue order — and bounds the per-element error by
``max|block|/254`` (int8) / ``max|block|/2`` (2bit) of the
error-compensated payload, with the dropped residual carried in the
Updater store (``__quant_ef__/...`` string keys) so it is re-injected
next round instead of accumulating.  ``GRAFT_QUANT_REDUCE=0`` is the
bit-identical escape hatch, even over a legacy
``set_gradient_compression("2bit")`` routing.

The ZeRO-1 contract: ``GRAFT_SHARD_OPTIMIZER=1`` makes each context (or
dist rank) run the fused update — and lazily create optimizer state —
only for its contiguous shard of the bucket plan, then broadcast; the
parity target is BYTE equality with the unsharded step's context-0
replica, and per-shard state bytes land on the
``graft_trainer_state_shard_bytes`` gauge (~1/N).
"""
import os
import pickle

import numpy as np
import pytest

import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, gluon
from incubator_mxnet_tpu.analysis import lockstep, tsan
from incubator_mxnet_tpu.parallel import quant
from incubator_mxnet_tpu.telemetry import metrics as tmetrics


SPECS = [(7,), (3, 5), (11,), (2, 2, 2), (13,), (4,)]

_ENV = ("GRAFT_QUANT_REDUCE", "GRAFT_QUANT_BLOCK", "GRAFT_SHARD_OPTIMIZER")


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {k: os.environ.pop(k, None) for k in _ENV}
    try:
        yield
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v


def _make_params(prefix, specs=SPECS, ctx=None):
    params = []
    for k, shape in enumerate(specs):
        p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
        p.initialize(ctx=ctx if ctx is not None else mx.cpu())
        params.append(p)
    return params


def _seed(params, weights):
    for p, w in zip(params, weights):
        for d in p.list_data():
            d._write(engine.colocate(jnp.asarray(w).astype(d.dtype),
                                     d._read()))


def _backward_loss(params, consts):
    with autograd.record():
        loss = None
        for p, c in zip(params, consts):
            y = (p.data() * p.data() * c).sum()
            loss = y if loss is None else loss + y
    loss.backward()


def _build_trainer(params, optimizer="sgd", opt_kw=None, overlap=False,
                   bucket_bytes=48):
    t = gluon.Trainer(params, optimizer,
                      dict(opt_kw or {"learning_rate": 0.05}),
                      kvstore=mx.kv.create("dist_sync"))
    t._bucket_bytes_override = bucket_bytes
    t._overlap_override = overlap
    return t


def _fixtures(seed=7, specs=SPECS):
    rs = np.random.RandomState(seed)
    weights = [rs.randn(*s).astype(np.float32) for s in specs]
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in specs]
    return weights, consts


def _residual_keys(trainer):
    return sorted(k for k in trainer._updaters[0].states
                  if quant.is_residual_key(k))


def _assert_bit_identical(pa, pb, ta, tb):
    for a, b in zip(pa, pb):
        assert a.data().asnumpy().tobytes() == b.data().asnumpy().tobytes(), \
            "weight %s diverged" % a.name
    sa, sb = ta._updaters[0].states, tb._updaters[0].states
    assert set(sa) == set(sb)
    for k in sa:
        for x, y in zip(_leaves(sa[k]), _leaves(sb[k])):
            assert np.asarray(_np(x)).tobytes() == \
                np.asarray(_np(y)).tobytes(), "state %r diverged" % (k,)


def _leaves(state):
    if isinstance(state, (tuple, list)):
        out = []
        for s in state:
            out.extend(_leaves(s))
        return out
    return [] if state is None else [state]


def _np(leaf):
    return leaf.asnumpy() if hasattr(leaf, "asnumpy") else np.asarray(leaf)


# ---------------------------------------------------------------------------
# kernels: round-trip bounds, wire bytes, shard maps
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [1, 15, 255, 256, 257, 1000])
@pytest.mark.parametrize("block", [64, 256])
def test_int8_roundtrip_bound(n, block):
    rs = np.random.RandomState(n + block)
    x = jnp.asarray((rs.randn(n) * 10).astype(np.float32))
    codes, scales = quant.encode(x, "int8", block)
    y = np.asarray(quant.decode(codes, scales, n, "int8", block))
    err = np.abs(y - np.asarray(x))
    for b in range(quant.n_blocks(n, block)):
        blk = np.asarray(x)[b * block:(b + 1) * block]
        bound = np.abs(blk).max() / 254.0 + 1e-7
        assert err[b * block:(b + 1) * block].max() <= bound, \
            "int8 block %d error above max|block|/254" % b


@pytest.mark.parametrize("n", [16, 255, 512, 1000])
def test_2bit_roundtrip_bound(n):
    block = 256
    rs = np.random.RandomState(n)
    x = jnp.asarray((rs.randn(n) * 3).astype(np.float32))
    codes, scales = quant.encode(x, "2bit", block)
    y = np.asarray(quant.decode(codes, scales, n, "2bit", block))
    err = np.abs(y - np.asarray(x))
    for b in range(quant.n_blocks(n, block)):
        blk = np.asarray(x)[b * block:(b + 1) * block]
        bound = np.abs(blk).max() / 2.0 + 1e-6
        assert err[b * block:(b + 1) * block].max() <= bound, \
            "2bit block %d error above max|block|/2" % b


def test_wire_nbytes_ratios():
    n = 1 << 16
    f32 = 4 * n
    assert f32 / quant.wire_nbytes(n, "int8", 256) >= 3.5
    assert f32 / quant.wire_nbytes(n, "2bit", 256) >= 12.0
    # ragged tail still bills whole blocks (codes are padded on the wire)
    assert quant.wire_nbytes(257, "int8", 256) == 2 * 256 + 8


def test_resolve_mode_and_block():
    assert quant.resolve_mode() is None
    os.environ["GRAFT_QUANT_REDUCE"] = "int8"
    assert quant.resolve_mode() == "int8"
    os.environ["GRAFT_QUANT_REDUCE"] = "0"
    # the escape hatch beats the legacy compression override
    assert quant.resolve_mode(override="2bit") is None
    del os.environ["GRAFT_QUANT_REDUCE"]
    assert quant.resolve_mode(override="2bit") == "2bit"
    os.environ["GRAFT_QUANT_BLOCK"] = "100"
    assert quant.resolve_block() == 112          # rounded up to 16 lanes
    os.environ["GRAFT_QUANT_BLOCK"] = "4"
    assert quant.resolve_block() == 16           # floor


def test_shard_owners_contiguous_and_complete():
    owners = quant.shard_owners(10, 4)
    assert len(owners) == 10
    assert list(owners) == sorted(owners), "shards must be contiguous runs"
    assert set(owners) <= set(range(4))
    # fewer buckets than shards: one bucket each for the first few
    assert quant.shard_owners(2, 8) == (0, 4)
    assert quant.shard_owners(0, 8) == ()
    # every rank derives the identical map (it is pure arithmetic)
    assert quant.shard_owners(10, 4) == owners


def test_residual_key_namespace():
    key = quant.residual_key((3, 1, 2), "float32")
    assert key == "__quant_ef__/float32:3,1,2"
    assert quant.is_residual_key(key)
    assert not quant.is_residual_key(7)
    assert not quant.is_residual_key("momentum")


def test_error_feedback_telescopes_exactly():
    """EF convergence in EXACT arithmetic: with dyadic-rational payloads
    every quantity (scale, code*scale, residual subtraction) is exactly
    representable in f32, so the telescoping identity

        sum_k decode(encode(g_k + r_{k-1})) == sum_k g_k - r_K

    holds to the BIT — the quantizer drops no mass, it only delays it.
    2bit mode: scale = max|block| (a power of two here), decoded values
    in {0, +/-scale}."""
    block = 16
    g = jnp.asarray(np.array([1.0, -0.5, 0.25, 2.0] * 4, np.float32))
    res = jnp.zeros_like(g)
    sum_dec = np.zeros(g.shape, np.float64)
    sum_g = np.zeros(g.shape, np.float64)
    for _ in range(8):
        acc = g + res
        codes, scales = quant.encode(acc, "2bit", block)
        dec = quant.decode(codes, scales, g.shape[0], "2bit", block)
        res = acc - dec
        sum_dec += np.asarray(dec, np.float64)
        sum_g += np.asarray(g, np.float64)
    np.testing.assert_array_equal(sum_dec + np.asarray(res, np.float64),
                                  sum_g)


def test_error_feedback_mean_converges():
    """The practical corollary: the running mean of the decoded payloads
    converges to the true (constant) gradient at 1/K — the residual is
    bounded, so its amortized share vanishes."""
    block = 64
    rs = np.random.RandomState(5)
    g = jnp.asarray((rs.randn(200) * 7).astype(np.float32))

    def mean_err(k_rounds):
        res = jnp.zeros_like(g)
        total = np.zeros(g.shape, np.float64)
        for _ in range(k_rounds):
            acc = g + res
            codes, scales = quant.encode(acc, "int8", block)
            dec = quant.decode(codes, scales, g.shape[0], "int8", block)
            res = acc - dec
            total += np.asarray(dec, np.float64)
        return np.abs(total / k_rounds - np.asarray(g, np.float64)).max()

    assert mean_err(32) < mean_err(2) / 8.0


# ---------------------------------------------------------------------------
# the trainer wire: serial + overlapped, escape hatch, legacy routing
# ---------------------------------------------------------------------------

def _quant_parity_run(mode, steps=4, lr=0.05, overlap=False):
    weights, consts = _fixtures()
    pa, pb = _make_params("f"), _make_params("q")
    _seed(pa, weights)
    _seed(pb, weights)
    ta = _build_trainer(pa, opt_kw={"learning_rate": lr})
    tb = _build_trainer(pb, opt_kw={"learning_rate": lr}, overlap=overlap)
    for _ in range(steps):
        _backward_loss(pa, consts)
        ta.step(2)
        os.environ["GRAFT_QUANT_REDUCE"] = mode
        _backward_loss(pb, consts)
        tb.step(2)
        del os.environ["GRAFT_QUANT_REDUCE"]
    maxdiff = max(
        float(np.abs(a.data().asnumpy().astype(np.float64)
                     - b.data().asnumpy().astype(np.float64)).max())
        for a, b in zip(pa, pb))
    return pa, pb, ta, tb, maxdiff


def test_int8_serial_parity_within_tolerance():
    pa, pb, ta, tb, maxdiff = _quant_parity_run("int8")
    # loose end-to-end ceiling over the documented per-step per-element
    # bound (lr/batch * max|block|/254, amplified by the grad dynamics)
    assert 0 < maxdiff < 1e-2, maxdiff
    keys = _residual_keys(tb)
    assert keys and all(quant.is_residual_key(k) for k in keys)
    assert _residual_keys(ta) == []


def test_2bit_serial_parity_within_tolerance():
    _, _, _, tb, maxdiff = _quant_parity_run("2bit", lr=0.01)
    assert 0 < maxdiff < 0.5, maxdiff
    assert _residual_keys(tb)


def test_overlapped_quant_bit_identical_to_serial_quant():
    """Overlap moves the ISSUE time of the quantized reduce, never its
    content: serial-quant and overlapped-quant are byte-equal, residuals
    included."""
    weights, consts = _fixtures()
    pa, pb = _make_params("qs"), _make_params("qo")
    _seed(pa, weights)
    _seed(pb, weights)
    ta = _build_trainer(pa)
    tb = _build_trainer(pb, overlap=True)
    os.environ["GRAFT_QUANT_REDUCE"] = "int8"
    for _ in range(5):
        _backward_loss(pa, consts)
        ta.step(2)
        _backward_loss(pb, consts)
        tb.step(2)
    assert tb._scheduler.issued_total > 0, "overlap never engaged"
    _assert_bit_identical(pa, pb, ta, tb)


def test_quant_off_env_is_bit_identical():
    weights, consts = _fixtures()
    pa, pb = _make_params("n"), _make_params("z")
    _seed(pa, weights)
    _seed(pb, weights)
    ta = _build_trainer(pa)
    tb = _build_trainer(pb)
    for _ in range(4):
        _backward_loss(pa, consts)
        ta.step(2)
        os.environ["GRAFT_QUANT_REDUCE"] = "0"
        _backward_loss(pb, consts)
        tb.step(2)
        del os.environ["GRAFT_QUANT_REDUCE"]
    _assert_bit_identical(pa, pb, ta, tb)


def test_legacy_2bit_compression_deprecates_and_routes():
    """set_gradient_compression("2bit") must warn, route the store onto
    the graftzero wire (no serial per-key fallback), and stay overridden
    by the GRAFT_QUANT_REDUCE=0 escape hatch."""
    kv = mx.kv.create("dist_sync")
    with pytest.warns(DeprecationWarning):
        kv.set_gradient_compression({"type": "2bit"})
    assert kv._quant_override == "2bit"
    assert quant.resolve_mode(kv._quant_override) == "2bit"

    weights, consts = _fixtures()
    pa, pb = _make_params("lc"), _make_params("ln")
    _seed(pa, weights)
    _seed(pb, weights)
    ta = gluon.Trainer(pa, "sgd", {"learning_rate": 0.05}, kvstore=kv)
    ta._bucket_bytes_override = 48
    ta._overlap_override = False
    tb = _build_trainer(pb)
    # compression no longer excludes the fused plan
    for _ in range(3):
        _backward_loss(pa, consts)
        ta.step(2)
        _backward_loss(pb, consts)
        tb.step(2)
    assert ta._fused_plan() is not None and ta._fused_plan()[0], \
        "legacy compression store fell off the bucketed path"
    assert _residual_keys(ta), "legacy 2bit routing never quantized"
    # escape hatch beats the legacy routing, bit for bit
    pc = _make_params("le")
    _seed(pc, weights)
    kv2 = mx.kv.create("dist_sync")
    with pytest.warns(DeprecationWarning):
        kv2.set_gradient_compression({"type": "2bit"})
    tc = gluon.Trainer(pc, "sgd", {"learning_rate": 0.05}, kvstore=kv2)
    tc._bucket_bytes_override = 48
    tc._overlap_override = False
    os.environ["GRAFT_QUANT_REDUCE"] = "0"
    for _ in range(3):
        _backward_loss(pc, consts)
        tc.step(2)
    for b, c in zip(pb, pc):
        assert b.data().asnumpy().tobytes() == c.data().asnumpy().tobytes()


# ---------------------------------------------------------------------------
# wire-bytes telemetry + lockstep signature
# ---------------------------------------------------------------------------

def test_reduce_quantized_counts_codes_plus_scales():
    kv = mx.kv.create("dist_sync")
    n = 1000
    x = jnp.asarray(np.random.RandomState(0).randn(n).astype(np.float32))
    codes, scales = quant.encode(x, "int8", 256)
    from incubator_mxnet_tpu.ndarray import NDArray
    pair = (NDArray(codes), NDArray(scales))
    snap0 = tmetrics.compact_snapshot()
    kv.reduce_quantized([pair], [n], "int8", 256, label="t")
    snap1 = tmetrics.compact_snapshot()
    d_raw = snap1.get("graft_kvstore_push_bytes_total", 0) \
        - snap0.get("graft_kvstore_push_bytes_total", 0)
    d_wire = snap1.get("graft_kvstore_wire_bytes_total", 0) \
        - snap0.get("graft_kvstore_wire_bytes_total", 0)
    assert d_raw == 4 * n
    assert d_wire == quant.wire_nbytes(n, "int8", 256)
    assert d_raw / d_wire >= 3.5


def test_quant_signature_folds_into_lockstep():
    kv = mx.kv.create("dist_sync")
    wire, sig = kv._quant_signature([1000], "int8", 256)
    assert sig == "q:int8:b256:nb4"
    assert wire == quant.wire_nbytes(1000, "int8", 256)
    lockstep.reset()
    try:
        lockstep.fold(1, "reduce_quant", n_keys=1, nbytes=wire, keys=[sig])
        _, h_a = lockstep.state()
        lockstep.reset()
        _, sig_b = kv._quant_signature([1000], "int8", 128)
        wire_b = quant.wire_nbytes(1000, "int8", 128)
        lockstep.fold(1, "reduce_quant", n_keys=1, nbytes=wire_b,
                      keys=[sig_b])
        _, h_b = lockstep.state()
        assert h_a != h_b, \
            "a mismatched GRAFT_QUANT_BLOCK must diverge the digest"
        lockstep.reset()
        lockstep.fold(1, "reduce_quant", n_keys=1, nbytes=wire, keys=[sig])
        _, h_c = lockstep.state()
        assert h_c == h_a, "identical quant config must agree"
    finally:
        lockstep.reset()


def test_tsan_clean_overlapped_quant_loop():
    """The overlapped quantized loop — grad-ready hooks issuing
    reduce_quantized_async mid-backward, EF residual read/write in the
    Updater store — must be EH2xx-silent."""
    tsan.set_enabled(True)
    tsan.clear()
    try:
        weights, consts = _fixtures()
        ps = _make_params("ts")
        _seed(ps, weights)
        t = _build_trainer(ps, overlap=True)
        os.environ["GRAFT_QUANT_REDUCE"] = "int8"
        for _ in range(4):
            with engine.bulk(32):
                _backward_loss(ps, consts)
            t.step(2)
        assert t._scheduler.issued_total > 0, "overlap never engaged"
        assert tsan.reports() == [], tsan.reports()
    finally:
        tsan.set_enabled(None)
        tsan.clear()


# ---------------------------------------------------------------------------
# ZeRO-1 sharded update (8-ctx mesh from conftest)
# ---------------------------------------------------------------------------

def _mesh_fixtures(seed=6, specs=SPECS):
    ctxs = [mx.cpu(i) for i in range(8)]
    rs = np.random.RandomState(seed)
    weights = [rs.randn(*s).astype(np.float32) for s in specs]
    base = [rs.randn(*s).astype(np.float32) for s in specs]
    consts = [[mx.nd.array(c * (j + 1), ctx=ctx)
               for j, ctx in enumerate(ctxs)] for c in base]
    return ctxs, weights, consts


def _mesh_step(ctxs, ps, t, consts):
    with autograd.record():
        losses = []
        for j, ctx in enumerate(ctxs):
            loss = None
            for p, cs in zip(ps, consts):
                d = p.data(ctx)
                y = (d * d * cs[j]).sum()
                loss = y if loss is None else loss + y
            losses.append(loss)
    autograd.backward(losses)
    t.step(len(ctxs))


def _mesh_build(prefix, ctxs, weights, optimizer="sgd", opt_kw=None):
    ps = _make_params(prefix, ctx=ctxs)
    _seed(ps, weights)
    t = gluon.Trainer(ps, optimizer,
                      dict(opt_kw or {"learning_rate": 0.05,
                                      "momentum": 0.9}),
                      kvstore=mx.kv.create("dist_sync"))
    t._bucket_bytes_override = 48
    return ps, t


def test_zero_sgd_momentum_byte_parity_and_gauge():
    ctxs, weights, consts = _mesh_fixtures()
    pa, ta = _mesh_build("u", ctxs, weights)
    for _ in range(4):
        _mesh_step(ctxs, pa, ta, consts)
    unsharded_bytes = ta._updaters[0].states_nbytes()
    pb, tb = _mesh_build("z", ctxs, weights)
    os.environ["GRAFT_SHARD_OPTIMIZER"] = "1"
    for _ in range(4):
        _mesh_step(ctxs, pb, tb, consts)
    del os.environ["GRAFT_SHARD_OPTIMIZER"]
    for a, b in zip(pa, pb):
        ra = a.list_data()[0].asnumpy()
        rb = b.list_data()[0].asnumpy()
        assert ra.tobytes() == rb.tobytes(), \
            "sharded %s diverged from the unsharded ctx-0 replica " \
            "(max |d|=%g)" % (a.name, np.abs(ra - rb).max())
    shard_bytes = max(u.states_nbytes() for u in tb._updaters)
    assert 0 < shard_bytes < unsharded_bytes / 2, \
        "per-shard state %d not ~1/N of %d" % (shard_bytes, unsharded_bytes)
    gauge = float(tmetrics.compact_snapshot().get(
        "graft_trainer_state_shard_bytes", 0.0))
    assert gauge == float(shard_bytes)
    assert float(tmetrics.compact_snapshot().get(
        "graft_trainer_state_shards", 0.0)) == 8.0


def test_zero_adam_single_step_byte_parity():
    """Adam is byte-exact for ONE step (after that the unsharded
    multi-ctx baseline's own replicas diverge — the shared per-index
    update count gives each context its own bias correction; ctx-0 is
    the defined parity target)."""
    ctxs, weights, consts = _mesh_fixtures()
    pa, ta = _mesh_build("ua", ctxs, weights, "adam",
                         {"learning_rate": 0.01})
    _mesh_step(ctxs, pa, ta, consts)
    pb, tb = _mesh_build("za", ctxs, weights, "adam",
                         {"learning_rate": 0.01})
    os.environ["GRAFT_SHARD_OPTIMIZER"] = "1"
    _mesh_step(ctxs, pb, tb, consts)
    del os.environ["GRAFT_SHARD_OPTIMIZER"]
    for a, b in zip(pa, pb):
        assert a.list_data()[0].asnumpy().tobytes() == \
            b.list_data()[0].asnumpy().tobytes()


def test_zero_quant_compose_broadcast_consistent():
    """ZeRO + int8: the quantized reduce-scatter feeds the sharded
    update; every context replica must hold the SAME bytes after the
    broadcast, within quant tolerance of the unsharded trajectory."""
    ctxs, weights, consts = _mesh_fixtures()
    pa, ta = _mesh_build("uq", ctxs, weights)
    for _ in range(3):
        _mesh_step(ctxs, pa, ta, consts)
    pb, tb = _mesh_build("zq", ctxs, weights)
    os.environ["GRAFT_SHARD_OPTIMIZER"] = "1"
    os.environ["GRAFT_QUANT_REDUCE"] = "int8"
    for _ in range(3):
        _mesh_step(ctxs, pb, tb, consts)
    del os.environ["GRAFT_SHARD_OPTIMIZER"]
    del os.environ["GRAFT_QUANT_REDUCE"]
    for p in pb:
        ref = p.list_data()[0].asnumpy()
        for d in p.list_data()[1:]:
            assert d.asnumpy().tobytes() == ref.tobytes(), \
                "broadcast left %s replicas inconsistent" % p.name
    maxdiff = max(
        float(np.abs(a.list_data()[0].asnumpy().astype(np.float64)
                     - b.list_data()[0].asnumpy().astype(np.float64)).max())
        for a, b in zip(pa, pb))
    assert maxdiff < 1.0, maxdiff


def test_save_load_states_refuse_sharded():
    ctxs, weights, consts = _mesh_fixtures()
    ps, t = _mesh_build("sv", ctxs, weights)
    os.environ["GRAFT_SHARD_OPTIMIZER"] = "1"
    _mesh_step(ctxs, ps, t, consts)
    with pytest.raises(ValueError, match="checkpointer"):
        t.save_states("/tmp/never_written.states")
    with pytest.raises(ValueError, match="checkpointer"):
        t.load_states(b"anything")
    del os.environ["GRAFT_SHARD_OPTIMIZER"]


# ---------------------------------------------------------------------------
# armor: sharded checkpoint round trip + typed ownership error
# ---------------------------------------------------------------------------

def test_armor_sharded_snapshot_roundtrip_with_residuals():
    from incubator_mxnet_tpu.armor.checkpoint import (restore_trainer,
                                                      snapshot_trainer)
    ctxs, weights, consts = _mesh_fixtures()
    pa, ta = _mesh_build("ck", ctxs, weights)
    os.environ["GRAFT_SHARD_OPTIMIZER"] = "1"
    os.environ["GRAFT_QUANT_REDUCE"] = "int8"
    for _ in range(2):
        _mesh_step(ctxs, pa, ta, consts)
    snap = snapshot_trainer(ta, step=2)
    assert snap["shard"] == {"axis": "ctx", "n": 8, "rank": 0}
    assert snap["optimizer"] is None
    assert len(snap["optimizer_shards"]) == 8
    res_seen = 0
    for blob in snap["optimizer_shards"]:
        states, _opt = pickle.loads(blob)
        for k, v in states.items():
            if quant.is_residual_key(k):
                res_seen += 1
                assert isinstance(v, np.ndarray), \
                    "EF residual persisted as %r, not numpy" % type(v)
    assert res_seen, "no EF residuals captured in the shard blobs"

    pb, tb = _mesh_build("ck", ctxs, weights)
    _mesh_step(ctxs, pb, tb, consts)        # materialize store + plan
    restore_trainer(tb, snap)
    for a, b in zip(pa, pb):
        for da, db in zip(a.list_data(), b.list_data()):
            assert da.asnumpy().tobytes() == db.asnumpy().tobytes()
    # the restored run must continue in LOCKSTEP with the original
    _mesh_step(ctxs, pa, ta, consts)
    _mesh_step(ctxs, pb, tb, consts)
    for a, b in zip(pa, pb):
        assert a.list_data()[0].asnumpy().tobytes() == \
            b.list_data()[0].asnumpy().tobytes()
    del os.environ["GRAFT_SHARD_OPTIMIZER"]
    del os.environ["GRAFT_QUANT_REDUCE"]


def test_armor_shard_ownership_error_both_directions():
    from incubator_mxnet_tpu.armor import ShardOwnershipError
    from incubator_mxnet_tpu.armor.checkpoint import (restore_trainer,
                                                      snapshot_trainer)
    ctxs, weights, consts = _mesh_fixtures()
    # sharded snapshot -> unsharded trainer
    pa, ta = _mesh_build("so", ctxs, weights)
    os.environ["GRAFT_SHARD_OPTIMIZER"] = "1"
    _mesh_step(ctxs, pa, ta, consts)
    sharded_snap = snapshot_trainer(ta, step=1)
    del os.environ["GRAFT_SHARD_OPTIMIZER"]
    pb, tb = _mesh_build("so", ctxs, weights)
    _mesh_step(ctxs, pb, tb, consts)
    with pytest.raises(ShardOwnershipError) as exc:
        restore_trainer(tb, sharded_snap)
    assert exc.value.saved == {"axis": "ctx", "n": 8, "rank": 0}
    assert exc.value.current is None
    # unsharded snapshot -> sharded trainer
    unsharded_snap = snapshot_trainer(tb, step=1)
    os.environ["GRAFT_SHARD_OPTIMIZER"] = "1"
    with pytest.raises(ShardOwnershipError) as exc:
        restore_trainer(ta, unsharded_snap)
    assert exc.value.saved is None
    assert exc.value.current == {"axis": "ctx", "n": 8, "rank": 0}
    del os.environ["GRAFT_SHARD_OPTIMIZER"]


# ---------------------------------------------------------------------------
# compiled step: in-program quantize/dequantize + guard retrace-once
# ---------------------------------------------------------------------------

def _compiled_pair():
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from test_step_compile import make_pair, eager_step, xbatch
    return make_pair, eager_step, xbatch


def test_compiled_step_quantizes_in_program():
    make_pair, eager_step, xbatch = _compiled_pair()
    os.environ["GRAFT_QUANT_REDUCE"] = "int8"
    net_e, tr_e, net_c, tr_c, cstep = make_pair(
        "sgd", {"learning_rate": 0.05, "momentum": 0.9},
        kvstore="dist_sync")
    rng = np.random.RandomState(11)
    for _ in range(5):
        x = xbatch(rng)
        eager_step(net_e, tr_e, x)
        cstep(x)
    assert cstep.retraces == 1, "static quant loop retraced"
    assert cstep.compiled_steps >= 4
    # parity vs the EAGER-quant twin: same quantized math, operand-vs-
    # constant fma drift only (the EH104 ULP convention, not bitwise)
    for name in sorted(net_e.collect_params()):
        a = net_e.collect_params()[name].data().asnumpy()
        b = net_c.collect_params()[
            name.replace("sce_", "scc_")].data().asnumpy()
        assert np.abs(a - b).max() < 1e-5, name
    # both twins carry the SAME EF residual namespace in their stores
    assert _residual_keys(tr_e) == _residual_keys(tr_c) != []


def test_compiled_step_quant_toggle_retraces_exactly_once():
    make_pair, eager_step, xbatch = _compiled_pair()
    os.environ["GRAFT_QUANT_REDUCE"] = "int8"
    _net_e, _tr_e, _net_c, _tr_c, cstep = make_pair(
        "sgd", {"learning_rate": 0.05}, kvstore="dist_sync")
    rng = np.random.RandomState(3)
    for _ in range(3):
        cstep(xbatch(rng))
    assert cstep.retraces == 1
    # OFF: one guard miss (the quant-cfg component), then steady state
    os.environ["GRAFT_QUANT_REDUCE"] = "0"
    cstep(xbatch(rng))
    cstep(xbatch(rng))
    assert cstep.retraces == 2, \
        "quant toggle must retrace exactly once, got %d" % cstep.retraces
    # back ON: the int8 entry is still cached under its guard key — the
    # toggle back costs ZERO new traces
    os.environ["GRAFT_QUANT_REDUCE"] = "int8"
    cstep(xbatch(rng))
    cstep(xbatch(rng))
    assert cstep.retraces == 2
    # the guard-key differ names the quant component (regression: a
    # None-vs-tuple quant slot must not crash the retrace-reason diff)
    from incubator_mxnet_tpu.analysis import compile_safety as cs
    assert "quant-cfg" in cs.GUARD_COMPONENTS
    old = cstep._guard_key((None,))
    os.environ["GRAFT_QUANT_REDUCE"] = "0"
    new = cstep._guard_key((None,))
    comp, _detail = cs.diff_guard_key(old, new)
    assert comp == "quant-cfg"
