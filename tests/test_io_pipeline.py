"""Data-plane pipeline tests: threaded decode, deep prefetch, raw records.

Covers the fused fast path of the reference's ImageRecordIter
(src/io/iter_image_recordio_2.cc:663-762): multi-threaded decode+augment
(`preprocess_threads`), N-deep background prefetch (`prefetch_buffer` /
iter_prefetcher.h), and the raw-tensor record path that feeds an
accelerator faster than a host JPEG decoder can.
"""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io as mio
from incubator_mxnet_tpu import recordio


def _write_rec(tmp_path, n=12, h=8, w=8, raw=False, indexed=True):
    import cv2
    prefix = str(tmp_path / ("raw" if raw else "jpg"))
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rs = np.random.RandomState(7)
    imgs = []
    for i in range(n):
        img = (rs.rand(h, w, 3) * 255).astype(np.uint8)
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        if raw:
            s = recordio.pack(header, img.tobytes())
        else:
            ok, buf = cv2.imencode(".png", cv2.cvtColor(img,
                                                        cv2.COLOR_RGB2BGR))
            assert ok
            s = recordio.pack(header, buf.tobytes())
        rec.write_idx(i, s)
        imgs.append(img)
    rec.close()
    return prefix, np.stack(imgs)


def test_image_record_iter_honors_knobs(tmp_path):
    """preprocess_threads must actually change the decode path (pool) and
    prefetch_buffer must wrap in PrefetchingIter — and the data must come
    out identical to the single-threaded, unbuffered path."""
    prefix, imgs = _write_rec(tmp_path, n=12)
    kw = dict(path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
              data_shape=(3, 8, 8), batch_size=4, label_width=1)
    it_plain = mio.ImageRecordIter(preprocess_threads=1, prefetch_buffer=0,
                                   **kw)
    it_fast = mio.ImageRecordIter(preprocess_threads=3, prefetch_buffer=3,
                                  **kw)
    assert isinstance(it_fast, mio.PrefetchingIter)
    assert not isinstance(it_plain, mio.PrefetchingIter)
    for _ in range(2):  # two epochs: reset() must survive the buffering
        got_plain = [b.data[0].asnumpy() for b in it_plain]
        got_fast = [b.data[0].asnumpy() for b in it_fast]
        assert len(got_plain) == len(got_fast) == 3
        for a, b in zip(got_plain, got_fast):
            np.testing.assert_array_equal(a, b)
        it_plain.reset()
        it_fast.reset()


def test_raw_record_decode(tmp_path):
    """decode='raw'/auto must reproduce the packed tensors exactly."""
    prefix, imgs = _write_rec(tmp_path, n=8, raw=True)
    it = mio.ImageRecordIter(path_imgrec=prefix + ".rec",
                             path_imgidx=prefix + ".idx",
                             data_shape=(3, 8, 8), batch_size=8,
                             preprocess_threads=2, prefetch_buffer=2)
    batch = it.next()
    got = batch.data[0].asnumpy()  # NCHW float32
    want = imgs.transpose(0, 3, 1, 2).astype(np.float32)
    np.testing.assert_array_equal(got, want)
    labels = batch.label[0].asnumpy()
    np.testing.assert_array_equal(labels, np.arange(8) % 3)


def test_prefetching_iter_depth_and_reset():
    """A prefetch_buffer-deep PrefetchingIter must deliver every batch of
    every epoch in order, same as the base iterator."""
    data = np.arange(40, dtype=np.float32).reshape(20, 2)
    label = np.arange(20, dtype=np.float32)
    base = mio.NDArrayIter(data.copy(), label.copy(), batch_size=5)
    pf = mio.PrefetchingIter(
        mio.NDArrayIter(data.copy(), label.copy(), batch_size=5),
        prefetch_buffer=3)
    for _ in range(3):
        want = [b.data[0].asnumpy() for b in base]
        got = [b.data[0].asnumpy() for b in pf]
        assert len(want) == len(got) == 4
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)
        base.reset()
        pf.reset()


def test_sequential_rec_native_or_python(tmp_path):
    """Sequential (non-indexed) .rec reading must work through whichever
    reader backend is active (native C++ prefetch reader when built)."""
    prefix, imgs = _write_rec(tmp_path, n=6, raw=True)
    from incubator_mxnet_tpu.image import image as img_mod
    it = img_mod.ImageIter(batch_size=3, data_shape=(3, 8, 8),
                           path_imgrec=prefix + ".rec")
    seen = [b.data[0].asnumpy() for b in it]
    assert len(seen) == 2
    np.testing.assert_array_equal(
        np.concatenate(seen),
        imgs.transpose(0, 3, 1, 2).astype(np.float32))
    it.reset()  # native reader must reopen cleanly
    again = [b.data[0].asnumpy() for b in it]
    np.testing.assert_array_equal(np.concatenate(again),
                                  np.concatenate(seen))


def test_prefetching_iter_repolls_after_exhaustion():
    """iter_next() past end-of-epoch must keep answering False, not hang
    (regression: the queue-based rewrite initially deadlocked here)."""
    data = np.arange(8, dtype=np.float32).reshape(4, 2)
    pf = mio.PrefetchingIter(mio.NDArrayIter(data, np.zeros(4), batch_size=2),
                             prefetch_buffer=2)
    assert pf.iter_next() and pf.iter_next()
    for _ in range(3):
        assert not pf.iter_next()
    pf.reset()
    assert pf.iter_next()


def test_uint8_pipeline_keeps_float_labels(tmp_path):
    """dtype='uint8' types only the image blob — labels >= 256 must
    survive (regression: labels were cast to uint8 and wrapped mod 256)."""
    prefix = str(tmp_path / "biglabel")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rs = np.random.RandomState(0)
    for i in range(4):
        img = (rs.rand(8, 8, 3) * 255).astype(np.uint8)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(700 + i), i, 0), img.tobytes()))
    rec.close()
    it = mio.ImageRecordIter(path_imgrec=prefix + ".rec",
                             path_imgidx=prefix + ".idx",
                             data_shape=(3, 8, 8), batch_size=4,
                             dtype="uint8", aug_list=[],
                             preprocess_threads=1, prefetch_buffer=0)
    b = it.next()
    assert b.data[0].dtype == np.uint8
    np.testing.assert_array_equal(b.label[0].asnumpy(),
                                  [700.0, 701.0, 702.0, 703.0])


def test_prefetch_propagates_producer_errors():
    """A corrupt record must fail the consumer loudly, not hang it."""
    class Boom(mio.DataIter):
        def __init__(self):
            super().__init__(2)
            self.n = 0
        @property
        def provide_data(self):
            return [mio.DataDesc("data", (2, 2), "float32")]
        @property
        def provide_label(self):
            return [mio.DataDesc("l", (2,), "float32")]
        def reset(self):
            self.n = 0
        def next(self):
            self.n += 1
            if self.n == 2:
                raise ValueError("corrupt record")
            return mio.DataBatch([mx.nd.zeros((2, 2))], [mx.nd.zeros(2)], 0)
    pf = mio.PrefetchingIter(Boom(), prefetch_buffer=2)
    assert pf.iter_next()
    with pytest.raises(ValueError, match="corrupt record"):
        pf.iter_next()
    pf.close()


def test_uint8_with_augmenters_rejected(tmp_path):
    prefix, _ = _write_rec(tmp_path, n=4, raw=True)
    with pytest.raises(ValueError, match="uint8"):
        mio.ImageRecordIter(path_imgrec=prefix + ".rec",
                            path_imgidx=prefix + ".idx",
                            data_shape=(3, 8, 8), batch_size=4,
                            dtype="uint8", mean_r=123.0,
                            preprocess_threads=1, prefetch_buffer=0)


def test_multipart_record_roundtrip(tmp_path, monkeypatch):
    """Payloads over the 29-bit length limit split into begin/middle/end
    parts (dmlc convention) instead of silently corrupting the header —
    readable by BOTH the python and native readers (ADVICE r02)."""
    monkeypatch.setattr(recordio, "_MAX_REC_LEN", 100)  # force splitting
    monkeypatch.setenv("MXTPU_NATIVE_IO", "0")  # python framing path
    path = str(tmp_path / "multi.rec")
    w = recordio.MXRecordIO(path, "w")
    assert not w._native_handle
    payloads = [b"x" * 10, b"y" * 321, b"z" * 100, b"w" * 205]
    for pl in payloads:
        w.write(pl)
    w.close()

    r = recordio.MXRecordIO(path, "r")
    assert not r._native_handle
    got = []
    while True:
        s = r.read()
        if s is None:
            break
        got.append(bytes(s))
    assert got == payloads

    from incubator_mxnet_tpu import _native
    if _native.available():
        nr = _native.NativeRecordReader(path)
        ngot = []
        while True:
            s = nr.read()
            if s is None:
                break
            ngot.append(bytes(s))
        assert ngot == payloads
