"""Native C++ layer: RecordIO data plane + C predict API.

Parity models: dmlc-core recordio wire format (byte interchange between
the C++ and Python paths), src/c_api/c_predict_api.cc driven through its
C ABI (in-process: the embedded-interpreter path sees an already-live
interpreter and just takes the GIL).
"""
import ctypes
import os
import struct

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym, recordio, _native

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason="native libs not built")


def test_native_write_python_read(tmp_path, monkeypatch):
    f = str(tmp_path / "a.rec")
    w = _native.NativeRecordWriter(f)
    recs = [b"hello", b"x" * 7, b"", b"world!!!"]
    for r in recs:
        w.write(r)
    w.close()
    monkeypatch.setenv("MXTPU_NATIVE_IO", "0")   # force python reader
    rd = recordio.MXRecordIO(f, "r")
    got = []
    while True:
        r = rd.read()
        if r is None:
            break
        got.append(r)
    assert got == recs


def test_python_write_native_read(tmp_path, monkeypatch):
    f = str(tmp_path / "b.rec")
    monkeypatch.setenv("MXTPU_NATIVE_IO", "0")   # force python writer
    w = recordio.MXRecordIO(f, "w")
    recs = [b"abc", b"d" * 13, b"efgh"]
    for r in recs:
        w.write(r)
    w.close()
    rd = _native.NativeRecordReader(f)
    got = []
    while True:
        r = rd.read()
        if r is None:
            break
        got.append(r)
    assert got == recs


def test_native_indexed_roundtrip(tmp_path):
    prefix = str(tmp_path / "c")
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(20):
        w.write_idx(i, ("rec%03d" % i).encode() * (i + 1))
    w.close()
    r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    # uses the native reader (NATIVE_IO default on) incl. seek
    assert r._native_handle
    for i in (5, 0, 19, 7):
        assert r.read_idx(i) == ("rec%03d" % i).encode() * (i + 1)


def test_native_prefetch_reader(tmp_path):
    f = str(tmp_path / "d.rec")
    w = recordio.MXRecordIO(f, "w")
    recs = [os.urandom(64 * (i % 5 + 1)) for i in range(100)]
    for r in recs:
        w.write(r)
    w.close()
    pr = _native.NativePrefetchReader(f, capacity=8)
    got = []
    while True:
        r = pr.read()
        if r is None:
            break
        got.append(r)
    pr.close()
    assert got == recs


def test_c_predict_api_in_process(tmp_path):
    """Drive the MXPred* C ABI via ctypes (embedded-interpreter shim)."""
    lib_path = os.path.join(os.path.dirname(__file__), "..", "src",
                            "build", "libmxtpu_predict.so")
    if not os.path.exists(lib_path):
        pytest.skip("predict lib not built")
    lib = ctypes.CDLL(lib_path)
    lib.MXPredCreate.restype = ctypes.c_int
    lib.MXGetLastError.restype = ctypes.c_char_p

    # build + save a tiny model
    rng = np.random.RandomState(0)
    net = sym.softmax(sym.FullyConnected(sym.var("data"), num_hidden=3,
                                         name="fcp"))
    w = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    params_path = str(tmp_path / "m.params")
    nd.save(params_path, {"arg:fcp_weight": nd.array(w),
                          "arg:fcp_bias": nd.array(b)})
    param_bytes = open(params_path, "rb").read()
    sym_json = net.tojson().encode()

    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    shape = (ctypes.c_uint32 * 2)(2, 4)
    handle = ctypes.c_void_p()
    rc = lib.MXPredCreate(sym_json, param_bytes, len(param_bytes), 1, 0,
                          1, keys, indptr, shape, ctypes.byref(handle))
    assert rc == 0, lib.MXGetLastError()

    x = rng.randn(2, 4).astype(np.float32)
    rc = lib.MXPredSetInput(handle, b"data",
                            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                            x.size)
    assert rc == 0, lib.MXGetLastError()
    assert lib.MXPredForward(handle) == 0, lib.MXGetLastError()

    shape_data = ctypes.POINTER(ctypes.c_uint32)()
    ndim = ctypes.c_uint32()
    assert lib.MXPredGetOutputShape(handle, 0, ctypes.byref(shape_data),
                                    ctypes.byref(ndim)) == 0
    out_shape = tuple(shape_data[i] for i in range(ndim.value))
    assert out_shape == (2, 3)
    out = np.zeros(6, np.float32)
    assert lib.MXPredGetOutput(
        handle, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size) == 0
    lib.MXPredFree(handle)

    logits = x @ w.T + b
    e = np.exp(logits - logits.max(1, keepdims=True))
    ref = e / e.sum(1, keepdims=True)
    np.testing.assert_allclose(out.reshape(2, 3), ref, rtol=1e-5)


def test_c_train_api_in_process(tmp_path):
    """Drive the MXTrainer* C ABI via ctypes: create from symbol JSON,
    feed batches, fused step() until the loss drops, round-trip the
    updated .params back into a Python Module (the cpp-package layer's
    foundation, SURVEY layer 10)."""
    lib_path = os.path.join(os.path.dirname(__file__), "..", "src",
                            "build", "libmxtpu_train.so")
    if not os.path.exists(lib_path):
        pytest.skip("train lib not built")
    lib = ctypes.CDLL(lib_path)
    lib.MXTrainerCreate.restype = ctypes.c_int
    lib.MXTrainGetLastError.restype = ctypes.c_char_p

    rng = np.random.RandomState(0)
    X = rng.randn(64, 6).astype(np.float32)
    w_true = rng.randn(6).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)

    net = sym.FullyConnected(sym.var("data"), num_hidden=16, name="fct1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=2, name="fct2")
    net = sym.SoftmaxOutput(net, name="softmax",
                            normalization="batch")
    sym_json = net.tojson().encode()

    keys = (ctypes.c_char_p * 2)(b"data", b"softmax_label")
    indptr = (ctypes.c_uint32 * 3)(0, 2, 3)
    shape = (ctypes.c_uint32 * 3)(64, 6, 64)
    handle = ctypes.c_void_p()
    rc = lib.MXTrainerCreate(
        sym_json, b"sgd", b'{"learning_rate": 1.0}', None, 0,
        2, keys, indptr, shape, ctypes.byref(handle))
    assert rc == 0, lib.MXTrainGetLastError()

    def put(key, arr):
        rc = lib.MXTrainerSetInput(
            handle, key, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            arr.size)
        assert rc == 0, lib.MXTrainGetLastError()

    put(b"data", X)
    put(b"softmax_label", y)
    loss = ctypes.c_float()
    losses = []
    for _ in range(400):
        assert lib.MXTrainerStep(handle, ctypes.byref(loss)) == 0, \
            lib.MXTrainGetLastError()
        losses.append(loss.value)
    # normalization='batch' mean-reduces grads: convergence is steady but
    # unhurried at full-batch SGD (verify-skill note)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    # updated parameters round-trip into a Python Module
    out_bytes = ctypes.c_char_p()
    out_size = ctypes.c_uint64()
    assert lib.MXTrainerSaveParams(handle, ctypes.byref(out_bytes),
                                   ctypes.byref(out_size)) == 0
    blob = ctypes.string_at(out_bytes, out_size.value)
    lib.MXTrainerFree(handle)

    params_path = str(tmp_path / "trained.params")
    with open(params_path, "wb") as f:
        f.write(blob)
    loaded = nd.load(params_path)
    arg_params = {k.split(":", 1)[-1]: v for k, v in loaded.items()
                  if not k.startswith("aux:")}
    import incubator_mxnet_tpu as mx
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (64, 6))],
             label_shapes=[("softmax_label", (64,))], for_training=False)
    mod.init_params(arg_params=arg_params, aux_params={},
                    allow_missing=False)
    mod.forward(mx.io.DataBatch(data=[nd.array(X)], label=None),
                is_train=False)
    pred = mod.get_outputs()[0].asnumpy().argmax(1)
    assert (pred == y).mean() > 0.9, (pred == y).mean()


def test_c_data_iter_and_metric_abi(tmp_path):
    """MXDataIter* + MXMetric* ABIs via ctypes: write a raw .rec from
    Python, iterate it through the C handle, and score a perfect
    prediction set with the registry accuracy metric."""
    lib_path = os.path.join(os.path.dirname(__file__), "..", "src",
                            "build", "libmxtpu_train.so")
    if not os.path.exists(lib_path):
        pytest.skip("train lib not built")
    lib = ctypes.CDLL(lib_path)
    lib.MXTrainGetLastError.restype = ctypes.c_char_p

    # 8 records of 1x4x4 raw uint8, labels alternate 0/1
    rec = str(tmp_path / "it.rec")
    w = recordio.MXRecordIO(rec, "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = rng.randint(0, 4, (4, 4, 1), dtype=np.uint8)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 2), i, 0),
                              img.tobytes()))
    w.close()

    params = ('{"path_imgrec": "%s", "data_shape": [1, 4, 4], '
              '"batch_size": 4, "label_width": 1, "decode": "raw", '
              '"prefetch_buffer": 0}' % rec)
    h = ctypes.c_void_p()
    rc = lib.MXDataIterCreate(b"ImageRecordIter", params.encode(),
                              ctypes.byref(h))
    assert rc == 0, lib.MXTrainGetLastError()

    fptr = ctypes.POINTER(ctypes.c_float)
    uptr = ctypes.POINTER(ctypes.c_uint32)
    data_p, shape_p = fptr(), uptr()
    ndim = ctypes.c_uint32()
    has = ctypes.c_int()
    seen_labels = []
    batches = 0
    while True:
        assert lib.MXDataIterNext(h, ctypes.byref(has)) == 0
        if not has.value:
            break
        batches += 1
        assert lib.MXDataIterGetData(h, ctypes.byref(data_p),
                                     ctypes.byref(shape_p),
                                     ctypes.byref(ndim)) == 0
        shape = tuple(shape_p[i] for i in range(ndim.value))
        assert shape == (4, 1, 4, 4)
        assert lib.MXDataIterGetLabel(h, ctypes.byref(data_p),
                                      ctypes.byref(shape_p),
                                      ctypes.byref(ndim)) == 0
        n = 1
        for i in range(ndim.value):
            n *= shape_p[i]
        seen_labels.extend(data_p[i] for i in range(n))
    assert batches == 2
    assert sorted(set(seen_labels)) == [0.0, 1.0]
    # reset replays the epoch
    assert lib.MXDataIterReset(h) == 0
    assert lib.MXDataIterNext(h, ctypes.byref(has)) == 0 and has.value
    lib.MXDataIterFree(h)

    # metric: 3/4 correct predictions -> 0.75
    m = ctypes.c_void_p()
    assert lib.MXMetricCreate(b"accuracy", ctypes.byref(m)) == 0, \
        lib.MXTrainGetLastError()
    labels = np.array([0, 1, 0, 1], np.float32)
    preds = np.array([[.9, .1], [.2, .8], [.3, .7], [.1, .9]], np.float32)
    lshape = (ctypes.c_uint32 * 1)(4)
    pshape = (ctypes.c_uint32 * 2)(4, 2)
    assert lib.MXMetricUpdate(
        m, labels.ctypes.data_as(fptr), lshape, 1,
        preds.ctypes.data_as(fptr), pshape, 2) == 0
    val = ctypes.c_float()
    assert lib.MXMetricGet(m, ctypes.byref(val)) == 0
    assert abs(val.value - 0.75) < 1e-6
    assert lib.MXMetricReset(m) == 0
    lib.MXMetricFree(m)


def test_cpp_example_full_loop(tmp_path):
    """Compile and run cpp-package/example/train_mlp.cc: the C++ side
    writes a .rec, trains through ImageRecordIter batches and prints a
    registry-metric accuracy — exit 0 means >0.9 (VERDICT r4 task 8)."""
    import subprocess
    root = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    if not os.path.exists(os.path.join(root, "src", "build",
                                       "libmxtpu_train.so")):
        pytest.skip("train lib not built")
    exe = str(tmp_path / "train_mlp")
    rc = subprocess.run(
        ["g++", "-std=c++17", "-Icpp-package/include",
         "cpp-package/example/train_mlp.cc", "-Lsrc/build",
         "-lmxtpu_train", "-lmxtpu_io", "-o", exe],
        cwd=root, capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr
    env = dict(os.environ, PYTHONPATH=root, JAX_PLATFORMS="cpu",
               LD_LIBRARY_PATH=os.path.join(root, "src", "build"))
    env.pop("XLA_FLAGS", None)
    run = subprocess.run([exe], cwd=root, env=env, capture_output=True,
                         text=True, timeout=900)
    assert run.returncode == 0, (run.stdout, run.stderr)
    assert "eval accuracy" in run.stdout
