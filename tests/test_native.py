"""Native C++ layer: RecordIO data plane + C predict API.

Parity models: dmlc-core recordio wire format (byte interchange between
the C++ and Python paths), src/c_api/c_predict_api.cc driven through its
C ABI (in-process: the embedded-interpreter path sees an already-live
interpreter and just takes the GIL).
"""
import ctypes
import os
import struct

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym, recordio, _native

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason="native libs not built")


def test_native_write_python_read(tmp_path, monkeypatch):
    f = str(tmp_path / "a.rec")
    w = _native.NativeRecordWriter(f)
    recs = [b"hello", b"x" * 7, b"", b"world!!!"]
    for r in recs:
        w.write(r)
    w.close()
    monkeypatch.setenv("MXTPU_NATIVE_IO", "0")   # force python reader
    rd = recordio.MXRecordIO(f, "r")
    got = []
    while True:
        r = rd.read()
        if r is None:
            break
        got.append(r)
    assert got == recs


def test_python_write_native_read(tmp_path, monkeypatch):
    f = str(tmp_path / "b.rec")
    monkeypatch.setenv("MXTPU_NATIVE_IO", "0")   # force python writer
    w = recordio.MXRecordIO(f, "w")
    recs = [b"abc", b"d" * 13, b"efgh"]
    for r in recs:
        w.write(r)
    w.close()
    rd = _native.NativeRecordReader(f)
    got = []
    while True:
        r = rd.read()
        if r is None:
            break
        got.append(r)
    assert got == recs


def test_native_indexed_roundtrip(tmp_path):
    prefix = str(tmp_path / "c")
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(20):
        w.write_idx(i, ("rec%03d" % i).encode() * (i + 1))
    w.close()
    r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    # uses the native reader (NATIVE_IO default on) incl. seek
    assert r._native_handle
    for i in (5, 0, 19, 7):
        assert r.read_idx(i) == ("rec%03d" % i).encode() * (i + 1)


def test_native_prefetch_reader(tmp_path):
    f = str(tmp_path / "d.rec")
    w = recordio.MXRecordIO(f, "w")
    recs = [os.urandom(64 * (i % 5 + 1)) for i in range(100)]
    for r in recs:
        w.write(r)
    w.close()
    pr = _native.NativePrefetchReader(f, capacity=8)
    got = []
    while True:
        r = pr.read()
        if r is None:
            break
        got.append(r)
    pr.close()
    assert got == recs


def test_c_predict_api_in_process(tmp_path):
    """Drive the MXPred* C ABI via ctypes (embedded-interpreter shim)."""
    lib_path = os.path.join(os.path.dirname(__file__), "..", "src",
                            "build", "libmxtpu_predict.so")
    if not os.path.exists(lib_path):
        pytest.skip("predict lib not built")
    lib = ctypes.CDLL(lib_path)
    lib.MXPredCreate.restype = ctypes.c_int
    lib.MXGetLastError.restype = ctypes.c_char_p

    # build + save a tiny model
    rng = np.random.RandomState(0)
    net = sym.softmax(sym.FullyConnected(sym.var("data"), num_hidden=3,
                                         name="fcp"))
    w = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    params_path = str(tmp_path / "m.params")
    nd.save(params_path, {"arg:fcp_weight": nd.array(w),
                          "arg:fcp_bias": nd.array(b)})
    param_bytes = open(params_path, "rb").read()
    sym_json = net.tojson().encode()

    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    shape = (ctypes.c_uint32 * 2)(2, 4)
    handle = ctypes.c_void_p()
    rc = lib.MXPredCreate(sym_json, param_bytes, len(param_bytes), 1, 0,
                          1, keys, indptr, shape, ctypes.byref(handle))
    assert rc == 0, lib.MXGetLastError()

    x = rng.randn(2, 4).astype(np.float32)
    rc = lib.MXPredSetInput(handle, b"data",
                            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                            x.size)
    assert rc == 0, lib.MXGetLastError()
    assert lib.MXPredForward(handle) == 0, lib.MXGetLastError()

    shape_data = ctypes.POINTER(ctypes.c_uint32)()
    ndim = ctypes.c_uint32()
    assert lib.MXPredGetOutputShape(handle, 0, ctypes.byref(shape_data),
                                    ctypes.byref(ndim)) == 0
    out_shape = tuple(shape_data[i] for i in range(ndim.value))
    assert out_shape == (2, 3)
    out = np.zeros(6, np.float32)
    assert lib.MXPredGetOutput(
        handle, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size) == 0
    lib.MXPredFree(handle)

    logits = x @ w.T + b
    e = np.exp(logits - logits.max(1, keepdims=True))
    ref = e / e.sum(1, keepdims=True)
    np.testing.assert_allclose(out.reshape(2, 3), ref, rtol=1e-5)


def test_c_train_api_in_process(tmp_path):
    """Drive the MXTrainer* C ABI via ctypes: create from symbol JSON,
    feed batches, fused step() until the loss drops, round-trip the
    updated .params back into a Python Module (the cpp-package layer's
    foundation, SURVEY layer 10)."""
    lib_path = os.path.join(os.path.dirname(__file__), "..", "src",
                            "build", "libmxtpu_train.so")
    if not os.path.exists(lib_path):
        pytest.skip("train lib not built")
    lib = ctypes.CDLL(lib_path)
    lib.MXTrainerCreate.restype = ctypes.c_int
    lib.MXTrainGetLastError.restype = ctypes.c_char_p

    rng = np.random.RandomState(0)
    X = rng.randn(64, 6).astype(np.float32)
    w_true = rng.randn(6).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)

    net = sym.FullyConnected(sym.var("data"), num_hidden=16, name="fct1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=2, name="fct2")
    net = sym.SoftmaxOutput(net, name="softmax",
                            normalization="batch")
    sym_json = net.tojson().encode()

    keys = (ctypes.c_char_p * 2)(b"data", b"softmax_label")
    indptr = (ctypes.c_uint32 * 3)(0, 2, 3)
    shape = (ctypes.c_uint32 * 3)(64, 6, 64)
    handle = ctypes.c_void_p()
    rc = lib.MXTrainerCreate(
        sym_json, b"sgd", b'{"learning_rate": 1.0}', None, 0,
        2, keys, indptr, shape, ctypes.byref(handle))
    assert rc == 0, lib.MXTrainGetLastError()

    def put(key, arr):
        rc = lib.MXTrainerSetInput(
            handle, key, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            arr.size)
        assert rc == 0, lib.MXTrainGetLastError()

    put(b"data", X)
    put(b"softmax_label", y)
    loss = ctypes.c_float()
    losses = []
    for _ in range(400):
        assert lib.MXTrainerStep(handle, ctypes.byref(loss)) == 0, \
            lib.MXTrainGetLastError()
        losses.append(loss.value)
    # normalization='batch' mean-reduces grads: convergence is steady but
    # unhurried at full-batch SGD (verify-skill note)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    # updated parameters round-trip into a Python Module
    out_bytes = ctypes.c_char_p()
    out_size = ctypes.c_uint64()
    assert lib.MXTrainerSaveParams(handle, ctypes.byref(out_bytes),
                                   ctypes.byref(out_size)) == 0
    blob = ctypes.string_at(out_bytes, out_size.value)
    lib.MXTrainerFree(handle)

    params_path = str(tmp_path / "trained.params")
    with open(params_path, "wb") as f:
        f.write(blob)
    loaded = nd.load(params_path)
    arg_params = {k.split(":", 1)[-1]: v for k, v in loaded.items()
                  if not k.startswith("aux:")}
    import incubator_mxnet_tpu as mx
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (64, 6))],
             label_shapes=[("softmax_label", (64,))], for_training=False)
    mod.init_params(arg_params=arg_params, aux_params={},
                    allow_missing=False)
    mod.forward(mx.io.DataBatch(data=[nd.array(X)], label=None),
                is_train=False)
    pred = mod.get_outputs()[0].asnumpy().argmax(1)
    assert (pred == y).mean() > 0.9, (pred == y).mean()
