"""ONNX importer, contrib.text, SequentialModule/PythonModule/FeedForward.

Parity models: tests/python/unittest/onnx backend tests (translator
behavior), test_contrib_text.py, test_module.py SequentialModule cases.
"""
import collections
import types

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym, io
from incubator_mxnet_tpu.test_utils import assert_almost_equal


# ---------------------------------------------------------------------------
# ONNX importer (graph translation without the onnx package: duck-typed
# protos, the layer the reference tests against its backend suite)
# ---------------------------------------------------------------------------

def _node(op_type, inputs, outputs, **attrs):
    return types.SimpleNamespace(op_type=op_type, input=list(inputs),
                                 output=list(outputs), attribute=attrs)


def _init(name, array):
    return types.SimpleNamespace(name=name,
                                 array=np.asarray(array, np.float32))


def _graph(nodes, inputs, outputs, initializers):
    return types.SimpleNamespace(node=nodes, input=inputs, output=outputs,
                                 initializer=initializers)


def test_onnx_import_mlp():
    from incubator_mxnet_tpu.contrib.onnx import GraphProto
    rng = np.random.RandomState(0)
    w1 = rng.randn(4, 3).astype(np.float32)
    b1 = rng.randn(4).astype(np.float32)
    graph = _graph(
        nodes=[_node("Gemm", ["x", "w1", "b1"], ["h"], transB=1),
               _node("Relu", ["h"], ["a"]),
               _node("Softmax", ["a"], ["y"])],
        inputs=["x", "w1", "b1"],
        outputs=["y"],
        initializers=[_init("w1", w1), _init("b1", b1)])
    s, arg_params, aux_params = GraphProto().from_onnx(graph)
    assert set(arg_params) == {"w1", "b1"}
    x = rng.randn(2, 3).astype(np.float32)
    args = dict(arg_params)
    args["x"] = nd.array(x)
    out = s.bind(mx.cpu(), args, grad_req="null") \
           .forward(is_train=False)[0].asnumpy()
    h = np.maximum(x @ w1.T + b1, 0)
    e = np.exp(h - h.max(1, keepdims=True))
    ref = e / e.sum(1, keepdims=True)
    assert_almost_equal(out, ref, rtol=1e-5)


def test_onnx_import_conv_pool_bn():
    from incubator_mxnet_tpu.contrib.onnx import GraphProto
    rng = np.random.RandomState(1)
    w = rng.randn(4, 2, 3, 3).astype(np.float32)
    gamma = rng.rand(4).astype(np.float32) + 0.5
    beta = rng.randn(4).astype(np.float32)
    mean = rng.randn(4).astype(np.float32)
    var = rng.rand(4).astype(np.float32) + 0.5
    graph = _graph(
        nodes=[_node("Conv", ["x", "w"], ["c"], kernel_shape=(3, 3),
                     pads=(1, 1, 1, 1)),
               _node("BatchNormalization",
                     ["c", "gamma", "beta", "mean", "var"], ["bn"],
                     epsilon=1e-5),
               _node("MaxPool", ["bn"], ["p"], kernel_shape=(2, 2),
                     strides=(2, 2)),
               _node("Flatten", ["p"], ["f"]),
               _node("GlobalAveragePool", ["c"], ["g"])],
        inputs=["x", "w", "gamma", "beta", "mean", "var"],
        outputs=["f"],
        initializers=[_init("w", w), _init("gamma", gamma),
                      _init("beta", beta), _init("mean", mean),
                      _init("var", var)])
    s, arg_params, aux_params = GraphProto().from_onnx(graph)
    x = rng.randn(1, 2, 8, 8).astype(np.float32)
    args = dict(arg_params)
    args["x"] = nd.array(x)
    exe = s.bind(mx.cpu(), args, grad_req="null", aux_states=aux_params)
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (1, 4 * 4 * 4)
    # reference: conv -> BN(global stats) -> maxpool -> flatten
    ref_c = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                           num_filter=4, pad=(1, 1), no_bias=True).asnumpy()
    ref_bn = (ref_c - mean.reshape(1, -1, 1, 1)) / \
        np.sqrt(var.reshape(1, -1, 1, 1) + 1e-5) * \
        gamma.reshape(1, -1, 1, 1) + beta.reshape(1, -1, 1, 1)
    ref_p = ref_bn.reshape(1, 4, 4, 2, 4, 2).max(axis=(3, 5))
    assert_almost_equal(out, ref_p.reshape(1, -1), rtol=1e-4, atol=1e-4)


def test_onnx_unsupported_op_errors():
    from incubator_mxnet_tpu.contrib.onnx import GraphProto
    graph = _graph(nodes=[_node("NotAnOp", ["x"], ["y"])],
                   inputs=["x"], outputs=["y"], initializers=[])
    with pytest.raises(mx.MXNetError):
        GraphProto().from_onnx(graph)


def test_onnx_import_model_needs_onnx_package():
    from incubator_mxnet_tpu.contrib.onnx import import_model
    with pytest.raises(ImportError):
        import_model("/nonexistent/model.onnx")


# ---------------------------------------------------------------------------
# contrib.text
# ---------------------------------------------------------------------------

def test_text_vocabulary():
    from incubator_mxnet_tpu.contrib import text
    counter = text.utils.count_tokens_from_str("a b b c c c\nd d d d")
    assert counter["c"] == 3 and counter["d"] == 4
    vocab = text.Vocabulary(counter, most_freq_count=None, min_freq=2,
                            reserved_tokens=["<pad>"])
    # <unk>, <pad>, then d, c, b by frequency ("a" dropped: freq 1)
    assert vocab.idx_to_token[:5] == ["<unk>", "<pad>", "d", "c", "b"]
    assert vocab.to_indices(["d", "zzz"]) == [2, 0]
    assert vocab.to_tokens([3, 4]) == ["c", "b"]
    assert len(vocab) == 5


def test_text_custom_embedding(tmp_path):
    from incubator_mxnet_tpu.contrib import text
    p = tmp_path / "emb.txt"
    p.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    emb = text.embedding.CustomEmbedding(str(p))
    assert emb.vec_len == 3
    v = emb.get_vecs_by_tokens(["hello", "unknowntok"]).asnumpy()
    assert_almost_equal(v[0], [1.0, 2.0, 3.0], rtol=1e-6)
    assert (v[1] == 0).all()
    emb.update_token_vectors("world", nd.array(np.array([9., 9., 9.],
                                                        np.float32)))
    assert_almost_equal(emb.get_vecs_by_tokens("world").asnumpy(),
                        [9, 9, 9], rtol=1e-6)
    emb2 = text.embedding.create("customembedding",
                                 pretrained_file_path=str(p))
    assert emb2.vec_len == 3


# ---------------------------------------------------------------------------
# SequentialModule / PythonLossModule / FeedForward
# ---------------------------------------------------------------------------

def _toy():
    rng = np.random.RandomState(0)
    x = rng.randn(120, 10).astype(np.float32)
    w = rng.randn(10, 3).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.float32)
    return x, y


def test_sequential_module_trains():
    x, y = _toy()
    net1 = sym.Activation(sym.FullyConnected(sym.var("data"), num_hidden=16,
                                             name="fc1"), act_type="relu")
    net2 = sym.SoftmaxOutput(sym.FullyConnected(sym.var("data"),
                                                num_hidden=3, name="fc2"),
                             name="softmax")
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(net1, label_names=None)) \
       .add(mx.mod.Module(net2), take_labels=True, auto_wiring=True)
    it = io.NDArrayIter(x, y, batch_size=20, shuffle=True)
    seq.fit(it, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier())
    acc = seq.score(io.NDArrayIter(x, y, batch_size=20), "acc")[0][1]
    assert acc > 0.9


def test_python_loss_module_trains():
    x, y = _toy()
    feat = sym.FullyConnected(sym.var("data"), num_hidden=3, name="fcp")
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(feat, label_names=None)) \
       .add(mx.mod.PythonLossModule(), take_labels=True)
    it = io.NDArrayIter(x, y, batch_size=20, shuffle=True)
    seq.fit(it, num_epoch=15, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2},
            initializer=mx.init.Xavier())
    seq.forward(io.DataBatch(data=[nd.array(x)], label=[nd.array(y)]),
                is_train=False)
    out = seq.get_outputs()[0].asnumpy()
    assert (out.argmax(1) == y).mean() > 0.9


def test_feedforward_create_and_score():
    import warnings
    x, y = _toy()
    net = sym.SoftmaxOutput(sym.FullyConnected(sym.var("data"),
                                               num_hidden=3, name="fcf"),
                            name="softmax")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        model = mx.model.FeedForward.create(
            net, io.NDArrayIter(x, y, batch_size=20), num_epoch=12,
            optimizer="sgd", initializer=mx.init.Xavier(),
            learning_rate=0.5)
        acc = model.score(io.NDArrayIter(x, y, batch_size=20))
    assert acc > 0.9
    pred = model.predict(x[:20])
    assert pred.shape == (20, 3)


def test_executor_manager_shim():
    from incubator_mxnet_tpu.executor_manager import (_split_input_slice,
                                                      _check_arguments)
    slices = _split_input_slice(10, [1, 1])
    assert slices == [slice(0, 5), slice(5, 10)]
    slices = _split_input_slice(9, [2, 1])
    assert slices[0] == slice(0, 6) and slices[1] == slice(6, 9)
    net = sym.FullyConnected(sym.var("data"), num_hidden=2, name="fcx")
    _check_arguments(net)   # no duplicates → passes
