"""Smoke tests for the benchmark drivers (tiny shapes, CPU).

The drivers print JSON lines; these tests shrink their configs and check
the JSON contract so the real TPU runs can't bit-rot.
"""
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_sparse_dot_bench(capsys):
    mod = _load("benchmark/python/sparse/dot.py", "bench_sparse_dot")
    mod.CONFIGS = [(32, 64, 8, 0.1)]
    sys.argv, old = ["dot.py", "--repeat", "2"], sys.argv
    try:
        mod.main()
    finally:
        sys.argv = old
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["op"] == "csr_dot_dense" and rec["sparse_ms"] > 0


def test_sparse_cast_bench(capsys):
    mod = _load("benchmark/python/sparse/cast_storage.py", "bench_cast")
    mod.CONFIGS = [(16, 32, 0.1)]
    sys.argv, old = ["cast_storage.py", "--repeat", "2"], sys.argv
    try:
        mod.main()
    finally:
        sys.argv = old
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["dense_to_csr_ms"] > 0 and rec["csr_to_dense_ms"] > 0


def test_sparse_updater_bench(capsys):
    mod = _load("benchmark/python/sparse/updater.py", "bench_updater")
    mod.CONFIGS = [(256, 8, 0.1)]
    sys.argv, old = ["updater.py", "--repeat", "2"], sys.argv
    try:
        mod.main()
    finally:
        sys.argv = old
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["lazy_rsp_ms"] > 0 and rec["dense_ms"] > 0


def test_sparse_end2end_bench(capsys):
    mod = _load("benchmark/python/sparse/sparse_end2end.py", "bench_e2e")
    sys.argv, old = ["sparse_end2end.py", "--batch-size", "16", "--dim",
                     "128", "--nnz", "4", "--steps", "3"], sys.argv
    try:
        mod.main()
    finally:
        sys.argv = old
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] > 0


def test_quantization_op_bench(capsys):
    # FC only on CPU: XLA's CPU backend cannot lower the s8xs8->s32 conv
    # (LLVM verifier failure); the conv sweep runs on the real chip.
    mod = _load("benchmark/python/quantization/benchmark_op.py", "bench_q")
    mod.FC_CONFIGS = [(4, 16, 8)]
    mod.REPEATS = 2
    sys.argv, old = ["benchmark_op.py", "--fc"], sys.argv
    try:
        mod.main()
    finally:
        sys.argv = old
    lines = capsys.readouterr().out.strip().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert {r["op"] for r in recs} == {"fc"}
    assert all(r["int8_ms"] > 0 for r in recs)


def test_inference_score_bench(capsys):
    mod = _load("example/image-classification/benchmark_score.py",
                "bench_score")
    img_s = mod.score_eager("squeezenet-1.0", batch_size=1, num_batches=2,
                            dtype="float32")
    assert img_s > 0


def test_inference_score_steady_state():
    """The chip-true mode: a 3-long scan chain through the functionalized
    forward must run and yield a positive rate (mechanics only on CPU;
    the real numbers come from the TPU sweep)."""
    mod = _load("example/image-classification/benchmark_score.py",
                "bench_score2")
    img_s = mod.score_steady("squeezenet-1.0", batch_size=1, chain=3,
                             repeats=1, dtype="float32")
    assert img_s > 0


def test_transformer_bench_flops_model():
    mod = _load("bench_transformer.py", "bench_tf")
    # 6*N*T + L * 6*S*T*d (attention term is per layer)
    got = mod.model_flops_per_step(100, 10, 4, 8, n_layers=3)
    assert got == 6 * 100 * 10 + 3 * 6 * 4 * 10 * 8


def test_quantized_inference_bench_mechanics(monkeypatch, capsys):
    """The INT8 serving bench (fold -> calibrate -> quantize -> chained
    steady timing) runs end-to-end on a thumbnail resnet-18 and reports
    a positive speedup field (mechanics only on CPU; the committed ratio
    comes from the TPU run)."""
    import json as _json
    import sys
    monkeypatch.setattr(sys, "argv", [
        "x", "--num-layers", "18", "--image-size", "32", "--batch-size",
        "2", "--chain", "2", "--num-calib-batches", "1",
        "--calib-batch-size", "4"])
    mod = _load("example/quantization/imagenet_inference.py", "bench_qinf")
    mod.main()
    line = [l for l in capsys.readouterr().out.splitlines()
            if l.startswith("{")][-1]
    row = _json.loads(line)
    assert row["int8_speedup_vs_bf16"] > 0
    assert row["bf16_imgs_per_sec"] > 0 and row["int8_imgs_per_sec"] > 0
    assert 0.0 <= row["top1_agreement_int8_vs_f32"] <= 1.0


def test_symbolic_resnet_shapes():
    """The spec-driven symbolic ResNet family infers the canonical
    feature shapes at every depth (ref example/image-classification/
    symbols/resnet.py depth table)."""
    mod = _load("example/image-classification/symbols/resnet.py",
                "sym_resnet")
    for depth in (18, 34, 50, 101, 152):
        sym = mod.get_symbol(num_classes=10, num_layers=depth)
        pred = sym.get_internals()["fc1_output"]
        shapes, _, _ = pred.infer_shape(data=(1, 3, 224, 224))
        out = dict(zip(pred.list_arguments(), shapes))
        assert out["fc1_weight"][1] == (2048 if depth >= 50 else 512)
