"""dist_async parameter-server semantics (parallel/ps.py).

The reference applies each worker's push the moment it arrives on the
server with the server-side optimizer (kvstore_dist_server.h:306-314);
our host ParameterServer reproduces that outside XLA's sync model.
Single-process tests here; the 2-process run lives in
tests/test_dist_multiprocess.py.
"""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_async_push_applies_immediately():
    kv = mx.kv.create("dist_async")
    kv.init("w", nd.ones((4,)) * 10.0)
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 10.0)
    # no optimizer: pushes accumulate into the weights
    kv.push("w", nd.ones((4,)) * 2.0)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 12.0)


def test_async_server_side_optimizer():
    kv = mx.kv.create("dist_async")
    kv.init(3, nd.ones((2, 3)))
    opt = mx.optimizer.create("sgd", learning_rate=0.5)
    kv.set_optimizer(opt)
    # each push applies w -= lr * grad IMMEDIATELY (async, no merge)
    kv.push(3, nd.ones((2, 3)))
    kv.push(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.5 - 0.5, atol=1e-6)


def test_async_trains_a_model():
    """A Gluon Trainer over dist_async converges (single worker: the
    degenerate-but-complete PS loop: push grad -> server update ->
    pull)."""
    from incubator_mxnet_tpu import gluon, autograd
    rs = np.random.RandomState(0)
    X = rs.randn(64, 6).astype(np.float32)
    W = rs.randn(6, 1).astype(np.float32)
    y = (X @ W > 0).astype(np.float32).ravel()
    mx.random.seed(2)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier(magnitude=2.0))
    kv = mx.kv.create("dist_async")
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.3}, kvstore=kv)
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for ep in range(25):
        tot = 0.0
        for i in range(0, 64, 16):
            xb, yb = nd.array(X[i:i+16]), nd.array(y[i:i+16])
            with autograd.record():
                l = lf(net(xb), yb)
            l.backward()
            tr.step(16)
            tot += float(l.asnumpy().mean())
        losses.append(tot)
    assert losses[-1] < 0.5 * losses[0], losses


def test_async_module_fit():
    """Module.fit over dist_async: update_on_kvstore routes updates to
    the parameter server (the reference's PS training flow)."""
    mx.random.seed(4)
    rs = np.random.RandomState(0)
    X = rs.randn(128, 10).astype(np.float32)
    W = rs.randn(10, 1).astype(np.float32)
    y = (X @ W > 0).astype(np.float32).ravel()
    data = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    kv = mx.kv.create("dist_async")
    mod.fit(data, num_epoch=12, kvstore=kv,
            optimizer="sgd", optimizer_params={"learning_rate": 0.2},
            initializer=mx.init.Xavier(magnitude=2.0))
    score = dict(mod.score(data, "acc"))
    assert score["accuracy"] > 0.9, score


def test_server_group_shards_keys_and_big_arrays():
    """N-server group: small keys hash-shard, big arrays row-slice across
    ALL servers (kvstore_dist.h MXNET_KVSTORE_BIGARRAY_BOUND), and the
    client reassembles exactly."""
    import os
    from incubator_mxnet_tpu.parallel import ps

    os.environ["MXTPU_KVSTORE_BIGARRAY_BOUND"] = "1000"
    try:
        grp = ps.ServerGroup(3)
        cli = ps.GroupClient(grp.address, rank=0)
        rs = np.random.RandomState(0)
        small = {"a": rs.randn(10).astype(np.float32),
                 "b": rs.randn(7, 3).astype(np.float32)}
        big = rs.randn(600, 4).astype(np.float32)   # 2400 > bound
        cli.init({**small, "big": big})

        # big array must be row-sliced across every member server
        sub_counts = [sum(1 for k in s._store if k.startswith("big@"))
                      for s in grp.servers]
        assert sub_counts == [1, 1, 1], sub_counts

        got = cli.pull(["a", "b", "big"])
        np.testing.assert_array_equal(got["a"], small["a"])
        np.testing.assert_array_equal(got["b"], small["b"])
        np.testing.assert_array_equal(got["big"], big)

        # push accumulates through the shards
        cli.push({"big": np.ones_like(big)})
        np.testing.assert_allclose(cli.pull(["big"])["big"], big + 1.0)

        # pull_rows ships only requested rows, across block boundaries
        ids = np.array([0, 199, 200, 599], np.int64)
        rows = cli.pull_rows({"big": ids})["big"]
        np.testing.assert_allclose(rows, (big + 1.0)[ids])

        # heartbeat -> dead_nodes: rank 0 beat recently (alive); a rank
        # that beat once and went silent is dead past the window
        cli2 = ps.GroupClient(grp.address, rank=7)
        import time as _t
        _t.sleep(1.5)                     # let both heartbeat loops beat
        assert cli2.dead_nodes(window=60.0) == []
        cli2._hb_stop.set()               # rank 7 "dies"
        _t.sleep(0.5)
        assert 7 in cli.dead_nodes(window=0.4)
        cli.close()
        cli2.close()
        grp.shutdown()
    finally:
        del os.environ["MXTPU_KVSTORE_BIGARRAY_BOUND"]


def test_async_row_sparse_pull_row_ids():
    """row_sparse_pull with row_ids on the async path fetches ONLY the
    requested rows from the service (kvstore_dist_server.h:223)."""
    kv = mx.kv.create("dist_async")
    w = np.arange(24, dtype=np.float32).reshape(6, 4)
    kv.init("rs", nd.array(w))
    kv.push("rs", nd.array(np.ones_like(w)))
    out = nd.zeros((6, 4)).tostype("row_sparse")
    ids = nd.array(np.array([1, 4], np.float32))
    kv.row_sparse_pull("rs", out=out, row_ids=ids)
    dense = out.todense().asnumpy()
    np.testing.assert_allclose(dense[1], w[1] + 1)
    np.testing.assert_allclose(dense[4], w[4] + 1)
    assert kv.num_dead_nodes() == 0


def test_group_client_discovers_placement_late():
    """A client that never init/pushed a sharded key must still pull it
    (review regression: placement lived only in the initializing client;
    a restarted worker got a server KeyError)."""
    import os
    from incubator_mxnet_tpu.parallel import ps

    os.environ["MXTPU_KVSTORE_BIGARRAY_BOUND"] = "100"
    try:
        grp = ps.ServerGroup(3)
        writer = ps.GroupClient(grp.address)
        rs = np.random.RandomState(1)
        big = rs.randn(90, 4).astype(np.float32)     # 360 > 100
        small = rs.randn(5).astype(np.float32)
        writer.init({"big": big, "small": small})

        fresh = ps.GroupClient(grp.address)          # knows nothing
        got = fresh.pull(["big", "small"])
        np.testing.assert_array_equal(got["big"], big)
        np.testing.assert_array_equal(got["small"], small)
        rows = fresh.pull_rows({"big": np.array([0, 45, 89], np.int64)})
        np.testing.assert_array_equal(rows["big"], big[[0, 45, 89]])
        empty = fresh.pull_rows({"big": np.array([], np.int64)})
        assert empty["big"].shape == (0, 4)
        writer.close()
        fresh.close()
        grp.shutdown()
    finally:
        del os.environ["MXTPU_KVSTORE_BIGARRAY_BOUND"]
