"""dist_async parameter-server semantics (parallel/ps.py).

The reference applies each worker's push the moment it arrives on the
server with the server-side optimizer (kvstore_dist_server.h:306-314);
our host ParameterServer reproduces that outside XLA's sync model.
Single-process tests here; the 2-process run lives in
tests/test_dist_multiprocess.py.
"""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_async_push_applies_immediately():
    kv = mx.kv.create("dist_async")
    kv.init("w", nd.ones((4,)) * 10.0)
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 10.0)
    # no optimizer: pushes accumulate into the weights
    kv.push("w", nd.ones((4,)) * 2.0)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 12.0)


def test_async_server_side_optimizer():
    kv = mx.kv.create("dist_async")
    kv.init(3, nd.ones((2, 3)))
    opt = mx.optimizer.create("sgd", learning_rate=0.5)
    kv.set_optimizer(opt)
    # each push applies w -= lr * grad IMMEDIATELY (async, no merge)
    kv.push(3, nd.ones((2, 3)))
    kv.push(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.5 - 0.5, atol=1e-6)


def test_async_trains_a_model():
    """A Gluon Trainer over dist_async converges (single worker: the
    degenerate-but-complete PS loop: push grad -> server update ->
    pull)."""
    from incubator_mxnet_tpu import gluon, autograd
    rs = np.random.RandomState(0)
    X = rs.randn(64, 6).astype(np.float32)
    W = rs.randn(6, 1).astype(np.float32)
    y = (X @ W > 0).astype(np.float32).ravel()
    mx.random.seed(2)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier(magnitude=2.0))
    kv = mx.kv.create("dist_async")
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.3}, kvstore=kv)
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for ep in range(25):
        tot = 0.0
        for i in range(0, 64, 16):
            xb, yb = nd.array(X[i:i+16]), nd.array(y[i:i+16])
            with autograd.record():
                l = lf(net(xb), yb)
            l.backward()
            tr.step(16)
            tot += float(l.asnumpy().mean())
        losses.append(tot)
    assert losses[-1] < 0.5 * losses[0], losses


def test_async_module_fit():
    """Module.fit over dist_async: update_on_kvstore routes updates to
    the parameter server (the reference's PS training flow)."""
    mx.random.seed(4)
    rs = np.random.RandomState(0)
    X = rs.randn(128, 10).astype(np.float32)
    W = rs.randn(10, 1).astype(np.float32)
    y = (X @ W > 0).astype(np.float32).ravel()
    data = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    kv = mx.kv.create("dist_async")
    mod.fit(data, num_epoch=12, kvstore=kv,
            optimizer="sgd", optimizer_params={"learning_rate": 0.2},
            initializer=mx.init.Xavier(magnitude=2.0))
    score = dict(mod.score(data, "acc"))
    assert score["accuracy"] > 0.9, score
