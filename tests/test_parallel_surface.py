"""Gluon-surface parallelism: PipelineTrainer and MultiHeadAttention(sp).

Round-2 review item: pipeline parallelism and ring attention existed only
as raw jax functions; these tests drive them through the framework's
user-facing API on the 8-device virtual CPU mesh (SURVEY §2.4 TP/SP rows,
§7 phase 11).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, autograd
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.parallel import make_mesh, PipelineTrainer

import jax


def _stage_block(width, seed):
    blk = nn.Dense(width, activation="tanh", flatten=False, in_units=width)
    blk.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0))
    return blk


def test_pipeline_trainer_gluon_surface():
    """An HybridSequential of identical stage blocks trains over pp=4:
    loss decreases and the final params match a plain (non-pipelined)
    sequential training run step for step."""
    n_stages, width, batch = 4, 6, 8
    mesh = make_mesh({"pp": n_stages}, jax.devices("cpu")[:n_stages])
    rs = np.random.RandomState(0)
    X = rs.randn(batch, width).astype(np.float32)
    Y = rs.randn(batch, width).astype(np.float32)

    mx.random.seed(7)
    body = nn.HybridSequential()
    for i in range(n_stages):
        body.add(_stage_block(width, i))
    loss = gluon.loss.L2Loss()
    tr = PipelineTrainer(body, loss, mesh, num_microbatches=4,
                         learning_rate=0.05)
    # reference: identical net trained eagerly without the pipeline
    mx.random.seed(7)
    ref = nn.HybridSequential()
    for i in range(n_stages):
        ref.add(_stage_block(width, i))
    ref_tr = gluon.Trainer(ref.collect_params(), "sgd",
                           {"learning_rate": 0.05})

    losses = []
    for step in range(10):
        losses.append(float(np.asarray(tr.step(X, Y))))
        with autograd.record():
            ref_l = loss(ref(mx.nd.array(X)), mx.nd.array(Y))
        ref_l.backward()
        # PipelineTrainer's update is mean-loss SGD; Trainer.step(batch)
        # divides summed grads by batch -> same scale with L2Loss mean
        ref_tr.step(batch)
    assert losses[-1] < losses[0], losses

    tr.sync_params()
    for (pa, pb) in zip(body.collect_params().values(),
                        ref.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                                   rtol=2e-4, atol=2e-5)


def test_pipeline_trainer_pre_post():
    """Structurally different embed/head blocks ride outside the ring."""
    n_stages, width = 2, 6
    mesh = make_mesh({"pp": n_stages}, jax.devices("cpu")[:n_stages])
    rs = np.random.RandomState(1)
    X = rs.randn(8, 3).astype(np.float32)
    Yl = (rs.rand(8) * 4).astype(np.float32)

    mx.random.seed(3)
    pre = nn.Dense(width, flatten=False, in_units=3)
    pre.initialize(mx.init.Xavier())
    body = nn.HybridSequential()
    for i in range(n_stages):
        body.add(_stage_block(width, i))
    post = nn.Dense(4, flatten=False, in_units=width)
    post.initialize(mx.init.Xavier())
    tr = PipelineTrainer(body, gluon.loss.SoftmaxCrossEntropyLoss(), mesh,
                         num_microbatches=2, learning_rate=0.1,
                         pre=pre, post=post)
    losses = [float(np.asarray(tr.step(X, Yl))) for _ in range(15)]
    assert losses[-1] < losses[0], losses
    tr.sync_params()  # must not raise; pre/post values written back
    assert np.isfinite(pre.weight.data().asnumpy()).all()


def test_pipeline_trainer_stage_count_mismatch():
    mesh = make_mesh({"pp": 2}, jax.devices("cpu")[:2])
    body = nn.HybridSequential()
    body.add(_stage_block(4, 0))
    with pytest.raises(ValueError, match="stage blocks"):
        PipelineTrainer(body, gluon.loss.L2Loss(), mesh)


def test_multihead_attention_ring_matches_local():
    """The SAME Gluon layer must produce identical output with
    seq_axis='sp' (ring attention over the mesh) and seq_axis=None
    (local flash attention)."""
    B, S, E, H = 2, 16, 8, 2
    mesh = make_mesh({"sp": 4}, jax.devices("cpu")[:4])
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(B, S, E).astype(np.float32))

    for causal in (False, True):
        mx.random.seed(11)
        local = nn.MultiHeadAttention(E, H, causal=causal)
        local.initialize(mx.init.Xavier())
        out_local = local(x).asnumpy()

        mx.random.seed(11)
        ring = nn.MultiHeadAttention(E, H, causal=causal, seq_axis="sp")
        ring.initialize(mx.init.Xavier())
        with parallel.use_mesh(mesh):
            out_ring = ring(x).asnumpy()
        np.testing.assert_allclose(out_ring, out_local, rtol=2e-4, atol=2e-5)


def test_multihead_attention_trains_with_sp():
    """MultiHeadAttention(seq_axis='sp') differentiates end-to-end through
    the tape (ring attention custom VJP) and the grads match the local
    layer's."""
    B, S, E, H = 2, 8, 8, 2
    mesh = make_mesh({"sp": 2}, jax.devices("cpu")[:2])
    rs = np.random.RandomState(2)
    x = mx.nd.array(rs.randn(B, S, E).astype(np.float32))
    y = mx.nd.array(rs.randn(B, S, E).astype(np.float32))
    loss_fn = gluon.loss.L2Loss()

    grads = {}
    for tag, seq_axis in (("local", None), ("ring", "sp")):
        mx.random.seed(5)
        blk = nn.MultiHeadAttention(E, H, causal=True, seq_axis=seq_axis)
        blk.initialize(mx.init.Xavier())
        for p in blk.collect_params().values():
            p.grad_req = "write"
        with parallel.use_mesh(mesh):
            with autograd.record():
                l = loss_fn(blk(x), y)
            l.backward()
        grads[tag] = {n: p.grad().asnumpy()
                      for n, p in blk.collect_params().items()}
    for (na, ga), (nb, gb) in zip(sorted(grads["local"].items()),
                                  sorted(grads["ring"].items())):
        np.testing.assert_allclose(ga, gb, rtol=2e-3, atol=1e-5)


def test_ring_attention_requires_mesh():
    blk = nn.MultiHeadAttention(8, 2, seq_axis="sp")
    blk.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.rand(1, 4, 8).astype(np.float32))
    with pytest.raises(RuntimeError, match="no device mesh"):
        blk(x)


def test_multihead_attention_sp_in_fused_trainer():
    """The production path: MultiHeadAttention(seq_axis='sp') traced
    INSIDE the DataParallelTrainer's jitted step over a dp x sp mesh —
    attention stays sequence-sharded in-graph and the model trains."""
    from incubator_mxnet_tpu.parallel import DataParallelTrainer
    B, S, E, H = 4, 8, 8, 2
    mesh = make_mesh({"dp": 2, "sp": 4}, jax.devices("cpu")[:8])
    mx.random.seed(9)
    net = nn.HybridSequential()
    net.add(nn.MultiHeadAttention(E, H, causal=True, seq_axis="sp"))
    net.add(nn.Dense(4, flatten=False))
    net.initialize(mx.init.Xavier())
    rs = np.random.RandomState(4)
    x = rs.randn(B, S, E).astype(np.float32)
    y = (rs.rand(B, S) * 4).astype(np.float32)
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1},
                             mesh=mesh)
    with parallel.use_mesh(mesh):
        l0 = float(np.asarray(tr.step(mx.nd.array(x), mx.nd.array(y))))
        for _ in range(15):
            l = float(np.asarray(tr.step(mx.nd.array(x), mx.nd.array(y))))
    assert np.isfinite(l) and l < l0, (l0, l)


def test_pipeline_trainer_rejects_divergent_stage_compute():
    """Same param shapes but different compute (tanh vs relu) must be
    rejected, not silently run through stage 0's function."""
    mesh = make_mesh({"pp": 2}, jax.devices("cpu")[:2])
    body = nn.HybridSequential()
    a = nn.Dense(4, activation="tanh", flatten=False, in_units=4)
    b = nn.Dense(4, activation="relu", flatten=False, in_units=4)
    a.initialize(mx.init.Xavier()); b.initialize(mx.init.Xavier())
    body.add(a); body.add(b)
    tr = PipelineTrainer(body, gluon.loss.L2Loss(), mesh,
                         num_microbatches=2)
    X = np.random.RandomState(0).randn(4, 4).astype(np.float32)
    with pytest.raises(ValueError, match="computes differently"):
        tr.step(X, X)


def test_pipeline_trainer_batchnorm_stages():
    """Training-mode-sensitive layers (BatchNorm) in identical stages must
    pass the stage-equivalence probe (review regression: the probe once
    compared train-mode vs inference-mode outputs)."""
    class Stage(nn.HybridSequential):
        def __init__(self):
            super().__init__()
            self.add(nn.Dense(6, flatten=False, in_units=6))
            self.add(nn.BatchNorm(axis=-1, in_channels=6))
            self.add(nn.Activation("tanh"))

    mesh = make_mesh({"pp": 2}, jax.devices("cpu")[:2])
    mx.random.seed(13)
    body = nn.HybridSequential()
    for _ in range(2):
        s = Stage()
        s.initialize(mx.init.Xavier())
        body.add(s)
    tr = PipelineTrainer(body, gluon.loss.L2Loss(), mesh,
                         num_microbatches=2, learning_rate=0.05)
    X = np.random.RandomState(1).randn(4, 6).astype(np.float32)
    losses = [float(np.asarray(tr.step(X, X))) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses), losses
