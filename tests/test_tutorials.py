"""Every ```python block in docs/tutorials/ must EXECUTE — the tutorial
tree is part of the tested surface (ref: docs/tutorials/, whose snippets
the reference CI also executes via its doc build).  Blocks within one
page share a namespace, so pages read top-to-bottom like a session."""
import os
import re

import pytest

TUTORIAL_DIR = os.path.join(os.path.dirname(__file__), "..", "docs",
                            "tutorials")
PAGES = sorted(f for f in os.listdir(TUTORIAL_DIR) if f.endswith(".md"))

_BLOCK_RE = re.compile(r"```python\n(.*?)```", re.S)


def _blocks(page):
    with open(os.path.join(TUTORIAL_DIR, page)) as f:
        return _BLOCK_RE.findall(f.read())


def test_tutorial_tree_exists():
    assert len(PAGES) >= 5, PAGES
    assert all(_blocks(p) or "bash" in open(
        os.path.join(TUTORIAL_DIR, p)).read() for p in PAGES)


@pytest.mark.parametrize("page", PAGES)
def test_tutorial_page_runs(page):
    blocks = _blocks(page)
    if not blocks:
        pytest.skip("no python blocks")
    ns = {}
    for i, src in enumerate(blocks):
        try:
            exec(compile(src, "%s[block %d]" % (page, i), "exec"), ns)
        except Exception as e:
            raise AssertionError(
                "%s block %d failed: %r\n---\n%s" % (page, i, e, src))
