"""graftwatch tests: flight-recorder ring, crash dumps, watchdog trips,
straggler detection, and the post-mortem CLI.

Covers the ISSUE-6 acceptance surface: ring-buffer wraparound, a
subprocess that raises mid-``Trainer.step`` leaving a schema-valid dump
naming the failing phase with the last >= 8 engine flushes, a
monkeypatched stalled flush tripping the watchdog within the configured
timeout (the dump names the stuck segment), the worker-skew histogram in
the 2-proc dist harness, and the ``--blackbox --selftest`` schema check.
"""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import engine, gluon
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.telemetry import blackbox, watchdog
from incubator_mxnet_tpu.telemetry import tracing as ttracing


@pytest.fixture
def recorder():
    """A clean, force-enabled recorder for one test."""
    blackbox.set_enabled(True)
    blackbox._ring.clear()
    blackbox._failures.clear()
    yield blackbox
    blackbox.set_enabled(None)


def _kinds(evs):
    return [e["kind"] for e in evs]


# ---------------------------------------------------------------------------
# ring buffer
# ---------------------------------------------------------------------------

def test_ring_wraparound(recorder):
    try:
        blackbox.configure(size=8)
        for i in range(20):
            blackbox.record("tick", i=i)
        evs = blackbox.events()
        assert len(evs) == 8                      # bounded
        assert [e["data"]["i"] for e in evs] == list(range(12, 20))
        st = blackbox.stats()
        assert st["events_total"] >= 20           # total keeps counting
        assert st["events_held"] == 8
        assert st["counts"] == {"tick": 8}
    finally:
        os.environ.pop("GRAFT_BLACKBOX_SIZE", None)
        blackbox.configure()                      # back to the default


def test_disabled_recorder_is_a_noop(recorder):
    blackbox.set_enabled(False)
    before = len(blackbox.events())
    blackbox.record("tick")
    with blackbox.in_flight("x"):
        pass
    with blackbox.collective("push", n_keys=1):
        pass
    assert len(blackbox.events()) == before


def test_ring_size_floor_and_env(recorder):
    try:
        os.environ["GRAFT_BLACKBOX_SIZE"] = "2"   # below the floor of 8
        blackbox.configure()
        for i in range(10):
            blackbox.record("tick", i=i)
        assert len(blackbox.events()) == 8
    finally:
        os.environ.pop("GRAFT_BLACKBOX_SIZE", None)
        blackbox.configure()


# ---------------------------------------------------------------------------
# subsystem events
# ---------------------------------------------------------------------------

def test_engine_flush_events(recorder):
    a = mx.nd.ones((6, 6))
    for _ in range(3):
        with engine.bulk(8):
            ((a * a) + a).asnumpy()
    flushes = [e for e in blackbox.events() if e["kind"] == "engine_flush"]
    assert len(flushes) >= 3
    d = flushes[-1]["data"]
    assert d["cause"] in ("read", "scope-close")
    assert d["nodes"] == 2
    assert d["cache"] in ("hit", "miss")
    assert d["latency_ms"] >= 0
    assert "error" not in d


def test_kvstore_collective_events(recorder):
    kv = mx.kv.create("local")
    kv.init("k", mx.nd.ones((8,)))
    kv.push("k", mx.nd.ones((8,)))
    out = mx.nd.zeros((8,))
    kv.pull("k", out=out)
    kv.reduce_many([mx.nd.ones((4,))])
    colls = [e["data"] for e in blackbox.events()
             if e["kind"] == "collective"]
    paths = [c["path"] for c in colls]
    assert "push" in paths and "pull" in paths and "reduce_many" in paths
    push = next(c for c in colls if c["path"] == "push")
    assert push["nbytes"] == 32 and push["n_keys"] == 1
    assert push["rank"] == 0 and push["latency_ms"] >= 0
    pull = next(c for c in colls if c["path"] == "pull")
    assert pull["nbytes"] == 32


def test_slow_collective_detection(recorder):
    for _ in range(4):                  # prime the EWMA above the floor
        with blackbox.collective("probe"):
            time.sleep(0.004)
    with blackbox.collective("probe"):  # ~10x the EWMA
        time.sleep(0.04)
    slow = [e["data"] for e in blackbox.events()
            if e["kind"] == "slow_collective"]
    assert slow and slow[-1]["path"] == "probe"
    assert slow[-1]["latency_ms"] > slow[-1]["ewma_ms"]
    snap = telemetry.compact_snapshot()
    assert snap.get('graft_dist_slow_collectives_total{path="probe"}',
                    0) >= 1


def test_step_journal_records_phases_and_memory(recorder):
    with blackbox.step_journal("trainer", batch_size=4):
        with ttracing.phase_span("kvstore"):
            pass
        with ttracing.phase_span("update"):
            time.sleep(0.002)
    steps = [e["data"] for e in blackbox.events() if e["kind"] == "step"]
    assert steps
    s = steps[-1]
    assert s["origin"] == "trainer" and s["batch_size"] == 4
    assert set(s["phases"]) == {"kvstore", "update"}
    assert s["phases"]["update"] >= 0.002
    assert s["latency_ms"] >= 2.0


def test_step_journal_names_failing_phase(recorder):
    with pytest.raises(RuntimeError):
        with blackbox.step_journal("trainer", batch_size=1):
            with ttracing.phase_span("update"):
                raise RuntimeError("boom")
    steps = [e["data"] for e in blackbox.events() if e["kind"] == "step"]
    assert steps[-1]["error_phase"] == "update"
    assert "error" in steps[-1]
    fails = blackbox.snapshot()["failures"]
    assert any(f["site"] == "phase" and f["detail"]["phase"] == "update"
               for f in fails)


def test_trainer_step_emits_journal(recorder):
    p = gluon.Parameter("w", shape=(4, 4))
    p.initialize(ctx=mx.cpu())
    p.data()._write(np.ones((4, 4), np.float32))
    p.grad()._write(np.ones((4, 4), np.float32))
    tr = gluon.Trainer([p], "sgd", {"learning_rate": 0.1}, kvstore=None)
    tr.step(1)
    steps = [e["data"] for e in blackbox.events() if e["kind"] == "step"]
    assert steps and steps[-1]["origin"] == "trainer"
    assert "update" in steps[-1]["phases"]


# ---------------------------------------------------------------------------
# dump + schema
# ---------------------------------------------------------------------------

def test_dump_validates_and_summarizes(recorder, tmp_path):
    a = mx.nd.ones((3, 3))
    with engine.bulk(8):
        (a + a).asnumpy()
    with blackbox.in_flight("probe", {"why": "held"}):
        path = blackbox.dump(path=str(tmp_path / "bb.json"),
                             reason="manual")
    with open(path) as f:
        doc = json.load(f)
    assert blackbox.validate_dump(doc) == []
    assert doc["reason"] == "manual" and doc["pid"] == os.getpid()
    assert any(e["site"] == "probe" for e in doc["in_flight"])
    assert any(t for t in doc["threads"])         # formatted stacks
    report = blackbox.summarize_dump(doc)
    assert report["last_flushes"]
    assert report["counts"]["engine_flush"] >= 1


def test_validate_dump_rejects_malformed(recorder):
    assert blackbox.validate_dump([]) == ["dump is not a JSON object"]
    doc = blackbox.snapshot()
    bad = dict(doc, schema="nope")
    assert any("schema" in p for p in blackbox.validate_dump(bad))
    bad = dict(doc, events=[{"kind": "x", "data": {}}])   # no ts
    assert any("ts" in p for p in blackbox.validate_dump(bad))
    bad = dict(doc)
    bad.pop("in_flight")
    assert any("in_flight" in p for p in blackbox.validate_dump(bad))


def test_cli_blackbox_selftest():
    repo = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ, PYTHONPATH=os.path.abspath(repo) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run(
        [sys.executable, "-m", "incubator_mxnet_tpu.telemetry",
         "--blackbox", "--selftest"],
        capture_output=True, text=True, env=env, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "graftwatch selftest OK" in r.stdout


# ---------------------------------------------------------------------------
# crash post-mortem: a subprocess raising mid-Trainer.step
# ---------------------------------------------------------------------------

_CRASH_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["GRAFT_BLACKBOX_PATH"] = sys.argv[1]
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import engine, gluon

    a = mx.nd.array(np.ones((4, 4), np.float32))
    for _ in range(10):                    # >= 8 engine_flush ring events
        with engine.bulk(8):
            ((a * a) + a).asnumpy()

    p = gluon.Parameter("w", shape=(4, 4))
    p.initialize(ctx=mx.cpu())
    p.data()._write(np.ones((4, 4), np.float32))
    p.grad()._write(np.ones((4, 4), np.float32))
    tr = gluon.Trainer([p], "sgd", {"learning_rate": 0.1}, kvstore=None)
    tr.step(1)                             # one healthy step first

    def boom(*a, **k):
        raise RuntimeError("synthetic mid-step crash")
    tr._bucketed_update = boom
    tr._update = boom
    tr.step(1)                             # dies inside the update phase
""")


def test_crash_mid_step_leaves_valid_dump(tmp_path):
    dump_path = str(tmp_path / "crash.json")
    script = tmp_path / "crash.py"
    script.write_text(_CRASH_SCRIPT)
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ, PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, str(script), dump_path],
                       capture_output=True, text=True, env=env,
                       timeout=180)
    assert r.returncode != 0
    assert "synthetic mid-step crash" in r.stderr
    with open(dump_path) as f:
        doc = json.load(f)
    # the dump passes the schema the CLI selftest enforces
    assert blackbox.validate_dump(doc) == []
    assert doc["reason"] == "exception"
    assert doc["exception"]["type"] == "RuntimeError"
    flushes = [e for e in doc["events"] if e["kind"] == "engine_flush"]
    assert len(flushes) >= 8
    # the in-flight phase at crash time is named: the phase bracket
    # closed WITH the error, landing in failures + the step event
    assert any(f["site"] == "phase" and f["detail"]["phase"] == "update"
               for f in doc["failures"])
    steps = [e["data"] for e in doc["events"] if e["kind"] == "step"]
    assert steps[-1].get("error_phase") == "update"
    # and the renderer reconstructs the timeline from it
    rr = subprocess.run(
        [sys.executable, "-m", "incubator_mxnet_tpu.telemetry",
         "--blackbox", dump_path, "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert rr.returncode == 0, rr.stdout + rr.stderr
    report = json.loads(rr.stdout)
    assert report["problems"] == []
    assert report["exception"]["type"] == "RuntimeError"
    assert len(report["last_flushes"]) >= 8


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_trips_on_stalled_flush(recorder, monkeypatch, tmp_path):
    dump_path = str(tmp_path / "wd.json")
    orig_build = engine._build_replay

    def slow_build(instrs, live):
        replay = orig_build(instrs, live)

        def slow(ext):
            time.sleep(1.2)               # the synthetic stalled flush
            return replay(ext)
        return slow

    monkeypatch.setattr(engine, "_build_replay", slow_build)
    wd = watchdog.start(timeout=0.3, interval=0.05, abort=False,
                        path=dump_path)
    assert wd is not None
    try:
        a = mx.nd.array(np.ones((5, 9), np.float32))  # unique: cache miss
        t0 = time.perf_counter()
        with engine.bulk(8):
            ((a * a) + a).asnumpy()
        stall = time.perf_counter() - t0
        deadline = time.time() + 2
        while wd.trips == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert wd.trips == 1
    finally:
        watchdog.stop()
    with open(dump_path) as f:
        doc = json.load(f)
    assert blackbox.validate_dump(doc) == []
    assert doc["reason"] == "watchdog"
    # the dump names the stuck segment, and the trip landed within the
    # configured timeout (+ polling slack), well before the stall ended
    wdinfo = doc["watchdog"]
    assert wdinfo["tripped_site"] == "engine_flush"
    assert wdinfo["tripped_detail"]["cause"] == "read"
    assert wdinfo["tripped_detail"]["nodes"] == 2
    assert "segment" in wdinfo["tripped_detail"]
    assert 0.3 <= wdinfo["age_s"] < stall
    inflight = [e for e in doc["in_flight"] if e["site"] == "engine_flush"]
    assert inflight and inflight[0]["detail"]["cause"] == "read"
    trips = [e for e in doc["events"] if e["kind"] == "watchdog_trip"]
    assert trips and trips[-1]["data"]["site"] == "engine_flush"
    snap = telemetry.compact_snapshot()
    assert snap.get(
        'graft_watchdog_trips_total{site="engine_flush"}', 0) >= 1


def test_watchdog_idle_process_never_trips(recorder):
    wd = watchdog.start(timeout=0.05, interval=0.02, abort=False)
    try:
        time.sleep(0.2)                   # idle: nothing in flight
        assert wd.trips == 0
    finally:
        watchdog.stop()


def test_watchdog_env_configuration(monkeypatch):
    monkeypatch.delenv("GRAFT_WATCHDOG_TIMEOUT", raising=False)
    assert watchdog.configured_timeout() is None
    assert watchdog.start() is None       # no timeout -> no thread
    monkeypatch.setenv("GRAFT_WATCHDOG_TIMEOUT", "2.5")
    assert watchdog.configured_timeout() == 2.5
    monkeypatch.setenv("GRAFT_WATCHDOG_TIMEOUT", "0")
    assert watchdog.configured_timeout() is None
    monkeypatch.setenv("GRAFT_WATCHDOG_TIMEOUT", "nope")
    assert watchdog.configured_timeout() is None


def test_watchdog_gauges_update_on_poll(recorder):
    wd = watchdog.Watchdog(timeout=60)    # never started: poll directly
    with blackbox.in_flight("probe", {"n": 1}):
        time.sleep(0.01)
        wd.poll()
        snap = telemetry.compact_snapshot()
        assert snap.get("graft_watchdog_inflight") == 1
        assert snap.get("graft_watchdog_oldest_inflight_seconds") > 0
    wd.poll()
    assert telemetry.compact_snapshot().get("graft_watchdog_inflight") == 0
    assert wd.trips == 0


# ---------------------------------------------------------------------------
# dist straggler detection (2-proc harness; skips where the backend
# cannot run multiprocess collectives, like the pre-existing dist tests
# fail on such machines)
# ---------------------------------------------------------------------------

def _skew_worker():
    from test_dist_multiprocess import _PRELUDE
    return _PRELUDE + textwrap.dedent("""
        try:
            kv = mx.kv.create("dist_sync")
            rank, nw = kv.rank, kv.num_workers
            assert nw == 2, nw
            kv.init("w", nd.zeros((16,)))
            for step in range(3):
                kv.push("w", nd.ones((16,)) * (rank + 1))
            out = nd.zeros((16,))
            kv.pull("w", out=out)
            # no updater: the store holds the LAST reduced push (1+2)
            assert np.allclose(out.asnumpy(), 3.0), out.asnumpy()

            from incubator_mxnet_tpu import telemetry
            from incubator_mxnet_tpu.telemetry import blackbox
            snap = telemetry.compact_snapshot()
            # one skew observation per reduce batch (init bcast is not one)
            assert snap.get("graft_dist_worker_skew_seconds_count",
                            0) >= 3, snap
            beats = [e for e in blackbox.events()
                     if e["kind"] == "dist_heartbeat"]
            assert len(beats) >= 3, beats
            assert beats[-1]["data"]["workers"] == 2
            doc = blackbox.snapshot()
            assert set(doc["workers"]) == {"0", "1"}, doc["workers"]
            assert doc["workers"]["0"]["step"] >= 3
            assert doc["workers"]["1"]["step"] >= 3
            assert doc["rank"] == rank
            print("WORKER %d SKEW OK" % rank, flush=True)
        except Exception:
            import traceback
            tb = traceback.format_exc()
            if "Multiprocess computations aren't implemented" in tb:
                print("SKIP-MULTIPROC", flush=True)
                os._exit(0)
            raise
    """)


def test_two_process_worker_skew_histogram(tmp_path):
    """Straggler detection: the per-step worker-skew histogram and the
    flight recorder's per-worker last-seen table must populate from the
    heartbeat piggybacked on the dist_sync reduce path."""
    from test_dist_multiprocess import _launch_two
    out = _launch_two(tmp_path, _skew_worker(), timeout=240,
                      port_base=9900, require_rc0=False)
    if "SKIP-MULTIPROC" in out:
        pytest.skip("backend lacks multiprocess CPU collectives")
    assert "WORKER 0 SKEW OK" in out and "WORKER 1 SKEW OK" in out, \
        out[-3000:]


# ---------------------------------------------------------------------------
# review regressions: innermost-trip, SIG_IGN chaining, renderer edge
# ---------------------------------------------------------------------------

def test_watchdog_trips_innermost_expired_bracket(recorder):
    """A stalled collective inside a step opens step -> collective; the
    trip must name the INNERMOST stuck bracket, and the whole nest is
    one incident (no second trip for the enclosing step)."""
    wd = watchdog.Watchdog(timeout=0.05)
    trips = []
    wd.trip = lambda entry, age: trips.append((entry["site"], age))
    with blackbox.in_flight("step", {"origin": "trainer"}):
        time.sleep(0.02)
        with blackbox.in_flight("collective", {"path": "reduce_many"}):
            time.sleep(0.1)               # both brackets now expired
            wd.poll()
            assert [s for s, _ in trips] == ["collective"]
            assert trips[0][1] > 0.05
            wd.poll()                     # same incident: no re-trip
            assert len(trips) == 1


def test_signal_hooks_respect_sig_ign(tmp_path):
    """A process that parked SIGTERM on SIG_IGN before import must keep
    ignoring it — the chain may not turn an ignored signal fatal."""
    script = tmp_path / "ign.py"
    script.write_text(textwrap.dedent("""
        import os, signal
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import incubator_mxnet_tpu as mx
        os.kill(os.getpid(), signal.SIGTERM)   # must stay ignored
        print("SURVIVED", flush=True)
    """))
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ, PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, str(script)], capture_output=True,
                       text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SURVIVED" in r.stdout


def test_renderer_handles_error_phase_without_error(recorder):
    """A step event whose phase failed but whose exception was caught
    inside the journal has error_phase and no error key — the text
    renderer must render it, not KeyError on the dump it explains."""
    blackbox.record("step", origin="t", index=1, latency_ms=1.0,
                    phases={"update": 0.001}, error_phase="update")
    from incubator_mxnet_tpu.telemetry.__main__ import _render_blackbox_text
    text = _render_blackbox_text(
        blackbox.summarize_dump(blackbox.snapshot()))
    assert "ERROR update" in text
