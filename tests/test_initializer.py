"""Initializer tests (parity model: tests/python/unittest/test_init.py)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.initializer import InitDesc


def _arr(shape):
    return mx.nd.empty(shape)


def test_constant_and_zero_one():
    a = _arr((3, 4))
    mx.init.Constant(2.5)(InitDesc("x_weight"), a)
    np.testing.assert_allclose(a.asnumpy(), np.full((3, 4), 2.5))
    mx.init.Zero()(InitDesc("x_weight"), a)
    assert a.asnumpy().sum() == 0
    mx.init.One()(InitDesc("x_weight"), a)
    assert a.asnumpy().sum() == 12


def test_suffix_dispatch():
    init = mx.init.Uniform(0.1)
    b = _arr((5,))
    init(InitDesc("fc_bias"), b)
    assert b.asnumpy().sum() == 0
    g = _arr((5,))
    init(InitDesc("bn_gamma"), g)
    np.testing.assert_allclose(g.asnumpy(), np.ones(5))
    mv = _arr((5,))
    init(InitDesc("bn_moving_var"), mv)
    np.testing.assert_allclose(mv.asnumpy(), np.ones(5))
    mm = _arr((5,))
    init(InitDesc("bn_moving_mean"), mm)
    assert mm.asnumpy().sum() == 0


def test_xavier_scale():
    a = _arr((128, 256))
    mx.init.Xavier(rnd_type="uniform", factor_type="avg", magnitude=3)(
        InitDesc("w_weight"), a)
    v = a.asnumpy()
    bound = np.sqrt(3.0 / ((128 + 256) / 2))
    assert np.abs(v).max() <= bound + 1e-6
    assert v.std() > 0.01


def test_uniform_normal_ranges():
    a = _arr((1000,))
    mx.init.Uniform(0.5)(InitDesc("u_weight"), a)
    assert np.abs(a.asnumpy()).max() <= 0.5
    mx.init.Normal(2.0)(InitDesc("n_weight"), a)
    assert 1.5 < a.asnumpy().std() < 2.5


def test_orthogonal():
    a = _arr((16, 16))
    mx.init.Orthogonal(scale=1.0)(InitDesc("o_weight"), a)
    q = a.asnumpy()
    np.testing.assert_allclose(q @ q.T, np.eye(16), atol=1e-4)


def test_attr_override_via_init_desc():
    a = _arr((4, 4))
    desc = InitDesc("custom_weight", attrs={"__init__": '["constant", {"value": 7.0}]'})
    mx.init.Uniform()(desc, a)
    np.testing.assert_allclose(a.asnumpy(), np.full((4, 4), 7.0))


def test_mixed_and_load():
    a = _arr((2, 2))
    mixed = mx.init.Mixed([".*bias", ".*"],
                          [mx.init.Zero(), mx.init.Constant(3.0)])
    mixed("conv_bias", a)
    assert a.asnumpy().sum() == 0
    mixed("conv_weight", a)
    np.testing.assert_allclose(a.asnumpy(), np.full((2, 2), 3.0))

    saved = {"p_weight": mx.nd.array(np.arange(4.0).reshape(2, 2))}
    load = mx.init.Load(saved, default_init=mx.init.Zero())
    b = _arr((2, 2))
    load("p_weight", b)
    np.testing.assert_allclose(b.asnumpy(), np.arange(4.0).reshape(2, 2))
    c = _arr((2, 2))
    load("q_weight", c)
    assert c.asnumpy().sum() == 0


def test_lstm_bias():
    a = _arr((8,))
    mx.init.LSTMBias(forget_bias=1.0)(InitDesc("l0_bias"), a)
    v = a.asnumpy()
    np.testing.assert_allclose(v[2:4], np.ones(2))
    assert v[:2].sum() == 0 and v[4:].sum() == 0


def test_dumps_create_roundtrip():
    import json
    blob = mx.init.Xavier(magnitude=2.0).dumps()
    name, kwargs = json.loads(blob)
    init2 = mx.init.create(name, **kwargs)
    assert isinstance(init2, mx.init.Xavier)
    assert init2.magnitude == 2.0
