"""CustomOp registry, mx.rnn legacy cells, BucketSentenceIter, gap ops.

Parity models: tests/python/unittest/test_operator.py test_custom_op,
test_rnn.py (cell unroll shapes), rnn/io.py BucketSentenceIter usage.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd
from incubator_mxnet_tpu.test_utils import assert_almost_equal


# ---------------------------------------------------------------------------
# mx.operator CustomOp
# ---------------------------------------------------------------------------

class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], nd.array(1 / (1 + np.exp(-x))))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        g = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], nd.array(g * y * (1 - y)))


@mx.operator.register("testsigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _Sigmoid()


def test_custom_op_nd_forward_backward():
    x = nd.array(np.array([[-1.0, 0.0, 2.0]], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="testsigmoid")
        loss = nd.sum(y)
    loss.backward()
    ref = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(y.asnumpy(), ref, rtol=1e-5)
    assert_almost_equal(x.grad.asnumpy(), ref * (1 - ref), rtol=1e-5)


def test_custom_op_symbol_graph():
    data = mx.sym.var("data")
    s = mx.sym.Custom(data, op_type="testsigmoid", name="cust")
    exe = s.simple_bind(ctx=mx.cpu(), data=(2, 3))
    x = nd.array(np.random.randn(2, 3).astype(np.float32))
    out = exe.forward(is_train=True, data=x)[0]
    ref = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-5)
    exe.backward(nd.ones((2, 3)))
    assert_almost_equal(exe.grad_dict["data"].asnumpy(), ref * (1 - ref),
                        rtol=1e-5)


def test_custom_op_registry_listing():
    assert "testsigmoid" in mx.operator.get_all_registered_operators()
    with pytest.raises(mx.MXNetError):
        nd.Custom(nd.ones((1,)), op_type="not_registered")


# ---------------------------------------------------------------------------
# gap ops: hard_sigmoid / square_sum / cast_storage / sparse_retain
# ---------------------------------------------------------------------------

def test_hard_sigmoid():
    x = nd.array(np.array([-10.0, -1.0, 0.0, 1.0, 10.0], np.float32))
    out = nd.hard_sigmoid(x, alpha=0.2, beta=0.5)
    assert_almost_equal(out.asnumpy(),
                        np.clip(0.2 * x.asnumpy() + 0.5, 0, 1), rtol=1e-6)


def test_square_sum_op():
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    out = nd.square_sum(nd.array(x), axis=1)
    assert_almost_equal(out.asnumpy(), (x * x).sum(axis=1), rtol=1e-5)
    # reachable from symbol graphs too
    s = mx.sym.square_sum(mx.sym.var("data"), axis=0)
    got = s.eval_dict({"data": nd.array(x)})
    assert_almost_equal(got.asnumpy(), (x * x).sum(axis=0), rtol=1e-5)


def test_sparse_retain_op():
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    idx = nd.array(np.array([0, 2], np.float32))
    out = nd.sparse_retain(nd.array(x), idx)
    expect = np.zeros_like(x)
    expect[[0, 2]] = x[[0, 2]]
    assert_almost_equal(out.asnumpy(), expect, rtol=1e-6)


def test_cast_storage_op_symbol():
    x = np.random.RandomState(1).randn(2, 3).astype(np.float32)
    s = mx.sym.cast_storage(mx.sym.var("data"), stype="default")
    got = s.eval_dict({"data": nd.array(x)})
    assert_almost_equal(got.asnumpy(), x, rtol=1e-6)


# ---------------------------------------------------------------------------
# mx.rnn cells
# ---------------------------------------------------------------------------

def test_rnn_cell_unroll_shapes():
    for cell, width in [(mx.rnn.RNNCell(8, prefix="r_"), 8),
                        (mx.rnn.LSTMCell(8, prefix="l_"), 8),
                        (mx.rnn.GRUCell(8, prefix="g_"), 8)]:
        out, states = cell.unroll(4, mx.sym.var("data"), merge_outputs=True)
        exe = out.simple_bind(ctx=mx.cpu(), data=(2, 4, 5))
        r = exe.forward(is_train=False,
                        data=nd.array(np.random.randn(2, 4, 5)
                                      .astype(np.float32)))[0]
        assert r.shape == (2, 4, width), type(cell).__name__


def test_rnn_stack_residual_dropout():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.GRUCell(8, prefix="g1_"))
    stack.add(mx.rnn.DropoutCell(0.2))
    stack.add(mx.rnn.ResidualCell(mx.rnn.GRUCell(8, prefix="g2_")))
    out, states = stack.unroll(3, mx.sym.var("data"), merge_outputs=True)
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 3, 8))
    r = exe.forward(is_train=False, data=nd.ones((2, 3, 8)))[0]
    assert r.shape == (2, 3, 8)
    assert len(states) == 2


def test_rnn_bidirectional():
    bic = mx.rnn.BidirectionalCell(mx.rnn.RNNCell(4, prefix="l_"),
                                   mx.rnn.RNNCell(4, prefix="r_"))
    out, _ = bic.unroll(3, mx.sym.var("data"), merge_outputs=True)
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 3, 6))
    r = exe.forward(is_train=False, data=nd.ones((2, 3, 6)))[0]
    assert r.shape == (2, 3, 8)


def test_fused_rnn_cell_and_unfuse():
    fused = mx.rnn.FusedRNNCell(8, num_layers=2, mode="lstm",
                                bidirectional=True, prefix="f_")
    out, _ = fused.unroll(5, mx.sym.var("data"), layout="NTC")
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 5, 6))
    r = exe.forward(is_train=False,
                    data=nd.array(np.random.randn(2, 5, 6)
                                  .astype(np.float32)))[0]
    assert r.shape == (2, 5, 16)
    stack = fused.unfuse()
    out2, _ = stack.unroll(5, mx.sym.var("data"), merge_outputs=True)
    exe2 = out2.simple_bind(ctx=mx.cpu(), data=(2, 5, 6))
    r2 = exe2.forward(is_train=False, data=nd.ones((2, 5, 6)))[0]
    assert r2.shape == (2, 5, 16)


def test_fused_matches_unfused_lstm():
    """Same weights → identical outputs for fused vs step-unrolled LSTM."""
    rng = np.random.RandomState(7)
    H, C, T, N = 4, 3, 3, 2
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="f_")
    of, _ = fused.unroll(T, mx.sym.var("data"), layout="NTC")
    ef = of.simple_bind(ctx=mx.cpu(), data=(N, T, C))
    w_i2h = rng.randn(4 * H, C).astype(np.float32)
    w_h2h = rng.randn(4 * H, H).astype(np.float32)
    b_i2h = rng.randn(4 * H).astype(np.float32)
    b_h2h = rng.randn(4 * H).astype(np.float32)
    ef.copy_params_from({"f_l0_i2h_weight": nd.array(w_i2h),
                         "f_l0_h2h_weight": nd.array(w_h2h),
                         "f_l0_i2h_bias": nd.array(b_i2h),
                         "f_l0_h2h_bias": nd.array(b_h2h)},
                        allow_extra_params=True)
    x = rng.randn(N, T, C).astype(np.float32)
    rf = ef.forward(is_train=False, data=nd.array(x))[0].asnumpy()

    cell = mx.rnn.LSTMCell(H, prefix="u_")
    ou, _ = cell.unroll(T, mx.sym.var("data"), merge_outputs=True)
    eu = ou.simple_bind(ctx=mx.cpu(), data=(N, T, C))
    eu.copy_params_from({"u_i2h_weight": nd.array(w_i2h),
                         "u_h2h_weight": nd.array(w_h2h),
                         "u_i2h_bias": nd.array(b_i2h),
                         "u_h2h_bias": nd.array(b_h2h)},
                        allow_extra_params=True)
    ru = eu.forward(is_train=False, data=nd.array(x))[0].asnumpy()
    assert_almost_equal(rf, ru, rtol=1e-4, atol=1e-5)


def test_bucket_sentence_iter():
    sents = [[1, 2, 3], [2, 3], [1, 2, 3, 4, 5], [3, 4], [1, 2], [2, 2, 2]]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=2, buckets=[3, 5],
                                   invalid_label=0)
    keys = set()
    count = 0
    for batch in it:
        assert batch.data[0].shape[0] == 2
        assert batch.data[0].shape[1] == batch.bucket_key
        # label is next-token shift of data
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        assert (l[:, :-1] == d[:, 1:]).all()
        keys.add(batch.bucket_key)
        count += 1
    assert count >= 2 and keys <= {3, 5}


def test_encode_sentences():
    sents = [["a", "b"], ["b", "c"]]
    coded, vocab = mx.rnn.encode_sentences(sents, start_label=1)
    assert len(vocab) >= 3
    assert coded[0][1] == coded[1][0]   # same token "b" → same id
