"""Numeric-gradient sweep over the operator registry.

Parity model: the reference's check_numeric_gradient harness driven
across test_operator.py (python/mxnet/test_utils.py:792; 5,439-LoC op
suite).  One parameterized test per op entry: analytic tape gradients
vs central finite differences on smooth-input samples.
"""
import zlib

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.test_utils import check_numeric_gradient


def _rng(seed):
    return np.random.RandomState(seed)


def _arr(shape, seed=0, lo=-1.0, hi=1.0):
    return nd.array(_rng(seed).uniform(lo, hi, shape).astype(np.float32))


def _pos(shape, seed=0, lo=0.3, hi=2.0):
    return _arr(shape, seed, lo, hi)


def _away_from_zero(shape, seed=0, margin=0.25):
    x = _rng(seed).uniform(-1, 1, shape).astype(np.float32)
    x = np.where(np.abs(x) < margin, margin * np.sign(x) + (x == 0) * margin,
                 x)
    return nd.array(x)


# (test id, f(*inputs) -> NDArray, [inputs])
CASES = []


def case(name, f, inputs):
    CASES.append(pytest.param(f, inputs, id=name))


S = (2, 3)

# -- smooth unary math ------------------------------------------------------
for opname in ["sigmoid", "tanh", "exp", "square", "negative", "erf",
               "softsign", "sin", "cos", "arctan", "sinh", "cosh",
               "arcsinh", "expm1"]:
    case(opname, (lambda op: lambda x: getattr(nd, op)(x))(opname),
         [_arr(S, seed=zlib.crc32(opname.encode()) % 100)])

for opname in ["log", "sqrt", "rsqrt", "cbrt", "reciprocal", "log1p",
               "log2", "log10", "gammaln"]:
    case(opname, (lambda op: lambda x: getattr(nd, op)(x))(opname),
         [_pos(S, seed=zlib.crc32(opname.encode()) % 100)])

case("abs", lambda x: nd.abs(x), [_away_from_zero(S, 3)])
case("relu", lambda x: nd.relu(x), [_away_from_zero(S, 4)])
case("arcsin", lambda x: nd.arcsin(x), [_arr(S, 5, -0.8, 0.8)])
case("arccos", lambda x: nd.arccos(x), [_arr(S, 6, -0.8, 0.8)])
case("arctanh", lambda x: nd.arctanh(x), [_arr(S, 7, -0.8, 0.8)])
case("arccosh", lambda x: nd.arccosh(x), [_pos(S, 8, 1.5, 3.0)])
case("tan", lambda x: nd.tan(x), [_arr(S, 9, -0.5, 0.5)])
case("hard_sigmoid", lambda x: nd.hard_sigmoid(x),
     [_arr(S, 10, -1.5, 1.5)])

# -- scalar ops -------------------------------------------------------------
case("plus_scalar", lambda x: x + 1.5, [_arr(S, 11)])
case("minus_scalar", lambda x: x - 0.5, [_arr(S, 12)])
case("rminus_scalar", lambda x: 2.0 - x, [_arr(S, 13)])
case("mul_scalar", lambda x: x * 3.0, [_arr(S, 14)])
case("div_scalar", lambda x: x / 2.0, [_arr(S, 15)])
case("rdiv_scalar", lambda x: 2.0 / x, [_pos(S, 16)])
case("pow_scalar", lambda x: x ** 3.0, [_pos(S, 17)])

# -- binary / broadcast -----------------------------------------------------
case("elemwise_add", lambda a, b: a + b, [_arr(S, 20), _arr(S, 21)])
case("elemwise_sub", lambda a, b: a - b, [_arr(S, 22), _arr(S, 23)])
case("elemwise_mul", lambda a, b: a * b, [_arr(S, 24), _arr(S, 25)])
case("elemwise_div", lambda a, b: a / b, [_arr(S, 26), _pos(S, 27)])
case("broadcast_add", lambda a, b: nd.broadcast_add(a, b),
     [_arr((2, 3), 28), _arr((1, 3), 29)])
case("broadcast_mul", lambda a, b: nd.broadcast_mul(a, b),
     [_arr((2, 3), 30), _arr((2, 1), 31)])
case("broadcast_div", lambda a, b: nd.broadcast_div(a, b),
     [_arr((2, 3), 32), _pos((1, 3), 33)])
case("broadcast_power", lambda a, b: nd.broadcast_power(a, b),
     [_pos((2, 3), 34), _arr((1, 3), 35)])
case("maximum", lambda a, b: nd.broadcast_maximum(a, b),
     [_arr(S, 36, -1, 0), _arr(S, 37, 0.1, 1)])
case("minimum", lambda a, b: nd.broadcast_minimum(a, b),
     [_arr(S, 38, -1, 0), _arr(S, 39, 0.1, 1)])
case("hypot", lambda a, b: nd.broadcast_hypot(a, b), [_pos(S, 40), _pos(S, 41)])

# -- reductions -------------------------------------------------------------
case("sum", lambda x: nd.sum(x), [_arr(S, 50)])
case("sum_axis", lambda x: nd.sum(x, axis=1), [_arr(S, 51)])
case("mean", lambda x: nd.mean(x, axis=0), [_arr(S, 52)])
case("prod", lambda x: nd.prod(x, axis=1), [_pos(S, 53)])
case("nansum", lambda x: nd.nansum(x, axis=0), [_arr(S, 54)])
case("norm", lambda x: nd.norm(x), [_pos(S, 55)])
case("max_reduce", lambda x: nd.max(x, axis=1),
     [nd.array(np.array([[1., 5., 2.], [7., 3., 4.]], np.float32))])
case("min_reduce", lambda x: nd.min(x, axis=1),
     [nd.array(np.array([[1., 5., 2.], [7., 3., 4.]], np.float32))])
case("square_sum", lambda x: nd.square_sum(x, axis=1), [_arr(S, 56)])
case("sum_keepdims", lambda x: nd.sum(x, axis=1, keepdims=True),
     [_arr(S, 57)])

# -- shape / indexing -------------------------------------------------------
case("reshape", lambda x: nd.reshape(x, shape=(3, 2)), [_arr(S, 60)])
case("transpose", lambda x: nd.transpose(x, axes=(1, 0)), [_arr(S, 61)])
case("swapaxes", lambda x: nd.swapaxes(x, dim1=0, dim2=1), [_arr(S, 62)])
case("expand_dims", lambda x: nd.expand_dims(x, axis=1), [_arr(S, 63)])
case("flatten", lambda x: nd.Flatten(x), [_arr((2, 3, 2), 64)])
case("flip", lambda x: nd.flip(x, axis=1), [_arr(S, 65)])
case("tile", lambda x: nd.tile(x, reps=(2, 1)), [_arr(S, 66)])
case("repeat", lambda x: nd.repeat(x, repeats=2, axis=0), [_arr(S, 67)])
case("clip", lambda x: nd.clip(x, a_min=-0.6, a_max=0.6), [_arr(S, 68, -0.5, 0.5)])
case("slice", lambda x: nd.slice(x, begin=(0, 1), end=(2, 3)),
     [_arr(S, 69)])
case("slice_axis", lambda x: nd.slice_axis(x, axis=1, begin=0, end=2),
     [_arr(S, 70)])
case("concat", lambda a, b: nd.concat(a, b, dim=1),
     [_arr(S, 71), _arr(S, 72)])
case("stack", lambda a, b: nd.stack(a, b, axis=0),
     [_arr(S, 73), _arr(S, 74)])
case("split_sum", lambda x: nd.split(x, num_outputs=3, axis=1)[1],
     [_arr(S, 75)])
case("take", lambda x: nd.take(x, nd.array(np.array([0, 1, 0],
                                                    np.float32))),
     [_arr(S, 76)])
case("where", lambda a, b: nd.where(
    nd.array(np.array([[1, 0, 1], [0, 1, 0]], np.float32)), a, b),
    [_arr(S, 77), _arr(S, 78)])
case("pad", lambda x: nd.Pad(x, mode="constant",
                             pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
     [_arr((1, 1, 2, 3), 79)])
case("reverse", lambda x: nd.reverse(x, axis=1), [_arr(S, 80)])
case("cast64", lambda x: nd.cast(x, dtype="float64"), [_arr(S, 81)])

# -- linear algebra ---------------------------------------------------------
case("dot", lambda a, b: nd.dot(a, b), [_arr((2, 3), 90), _arr((3, 2), 91)])
case("dot_ta", lambda a, b: nd.dot(a, b, transpose_a=True),
     [_arr((3, 2), 92), _arr((3, 2), 93)])
case("batch_dot", lambda a, b: nd.batch_dot(a, b),
     [_arr((2, 2, 3), 94), _arr((2, 3, 2), 95)])
case("linalg_gemm2", lambda a, b: nd.linalg.gemm2(a, b),
     [_arr((2, 3), 96), _arr((3, 2), 97)])
case("linalg_syrk", lambda a: nd.linalg.syrk(a), [_arr((3, 3), 98)])

# -- neural network ---------------------------------------------------------
case("FullyConnected",
     lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=4),
     [_arr((2, 3), 100), _arr((4, 3), 101), _arr((4,), 102)])
case("Convolution",
     lambda x, w: nd.Convolution(x, w, kernel=(2, 2), num_filter=2,
                                 no_bias=True),
     [_arr((1, 2, 4, 4), 103), _arr((2, 2, 2, 2), 104)])
case("Deconvolution",
     lambda x, w: nd.Deconvolution(x, w, kernel=(2, 2), num_filter=2,
                                   no_bias=True),
     [_arr((1, 2, 3, 3), 105), _arr((2, 2, 2, 2), 106)])
case("Pooling_avg",
     lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="avg", stride=(2, 2)),
     [_arr((1, 1, 4, 4), 107)])
case("Pooling_max",
     lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="max", stride=(2, 2)),
     [nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))])
case("Activation_tanh",
     lambda x: nd.Activation(x, act_type="tanh"), [_arr(S, 108)])
case("Activation_softrelu",
     lambda x: nd.Activation(x, act_type="softrelu"), [_arr(S, 109)])
case("LeakyReLU",
     lambda x: nd.LeakyReLU(x, act_type="leaky", slope=0.1),
     [_away_from_zero(S, 110)])
case("softmax", lambda x: nd.softmax(x), [_arr(S, 111)])
case("log_softmax", lambda x: nd.log_softmax(x), [_arr(S, 112)])
case("LayerNorm",
     lambda x, g, b: nd.LayerNorm(x, g, b),
     [_arr(S, 113), _pos((3,), 114), _arr((3,), 115)])
case("LRN", lambda x: nd.LRN(x, nsize=3), [_arr((1, 4, 2, 2), 116)])
case("BilinearSampler",
     lambda x, g: nd.BilinearSampler(x, g),
     [_arr((1, 1, 4, 4), 117), _arr((1, 2, 3, 3), 118, -0.7, 0.7)])
case("Embedding_data_grad",
     lambda w: nd.Embedding(nd.array(np.array([1, 0, 2], np.float32)), w,
                            input_dim=4, output_dim=3),
     [_arr((4, 3), 119)])
case("SequenceMask",
     lambda x: nd.SequenceMask(x, nd.array(np.array([1, 2], np.float32)),
                               use_sequence_length=True),
     [_arr((3, 2, 2), 120)])
case("UpSampling",
     lambda x: nd.UpSampling(x, scale=2, sample_type="nearest"),
     [_arr((1, 1, 2, 2), 121)])
case("flash_attention",
     lambda q, k, v: nd.flash_attention(q, k, v),
     [_arr((1, 1, 4, 4), 122), _arr((1, 1, 4, 4), 123),
      _arr((1, 1, 4, 4), 124)])
case("ROIPooling",
     lambda x: nd.ROIPooling(
         x, nd.array(np.array([[0, 0, 0, 3, 3]], np.float32)),
         pooled_size=(2, 2), spatial_scale=1.0),
     [_arr((1, 1, 4, 4), 125, 0.5, 2.0)])
case("ctc_loss",
     lambda x: nd.contrib.CTCLoss(
         x, nd.array(np.array([[1, 2], [1, 1]], np.float32))),
     [_arr((4, 2, 4), 126)])


@pytest.mark.parametrize("f,inputs", CASES)
def test_numeric_gradient(f, inputs):
    check_numeric_gradient(f, inputs)


def test_sweep_covers_many_ops():
    assert len(CASES) >= 95
