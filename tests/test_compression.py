"""2-bit gradient wire-packing unit tests.

The wire contract of src/kvstore/gradient_compression.h:37-132: 16
two-bit codes per 32-bit word (code 1 = +threshold, 2 = -threshold,
0 = zero), so the transported buffer is 1/16 the bytes of the f32
values; dequantization reproduces the quantized values exactly.
"""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.parallel import compression as C


def _quantize(x, t):
    return np.where(x >= t, t, np.where(x <= -t, -t, 0.0)).astype(np.float32)


def test_packed_size_is_one_sixteenth():
    for n in (1, 15, 16, 17, 1000, 4096):
        rs = np.random.RandomState(n)
        q = _quantize(rs.randn(n).astype(np.float32), 0.5)
        words = C.encode_2bit(mx.nd.array(q)._read(), 0.5)
        assert words.dtype == np.uint32
        assert words.nbytes == C.packed_nbytes(n)
        # the 1/16 contract vs the f32 buffer (up to one word of padding)
        assert words.nbytes <= 4 * n / 16 + 4


def test_roundtrip_exact():
    rs = np.random.RandomState(0)
    for t in (0.5, 0.25, 2.0):
        x = rs.randn(1037).astype(np.float32) * 2
        q = _quantize(x, t)
        words = C.encode_2bit(mx.nd.array(x)._read() * 0 + q, t)
        back = np.asarray(C.decode_2bit(words, t, 1037))
        np.testing.assert_array_equal(back, q)


def test_decode_sum_matches_dense_sum():
    rs = np.random.RandomState(3)
    t = 0.5
    n = 515
    qs = [_quantize(rs.randn(n).astype(np.float32), t) for _ in range(4)]
    words = np.stack([np.asarray(C.encode_2bit(mx.nd.array(q)._read(), t))
                      for q in qs])
    import jax.numpy as jnp
    summed = np.asarray(C.decode_2bit_sum(jnp.asarray(words), t, n))
    np.testing.assert_allclose(summed, np.sum(qs, axis=0), atol=1e-6)


def test_kvstore_compression_algebra_single_process():
    """Residual accumulation semantics through the public kvstore API
    (unchanged by the wire packing — single process takes the local
    path)."""
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", mx.nd.zeros((4,)))
    # push 0.3: below threshold -> quantized 0, residual 0.3
    kv.push("w", mx.nd.ones((4,)) * 0.3)
    out = mx.nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.0)
    # push 0.3 again: residual 0.6 >= t -> quantized 0.5, residual 0.1
    kv.push("w", mx.nd.ones((4,)) * 0.3)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)


def test_allreduce_packed_sum_virtual_mesh():
    """The scale-correct wire (all-to-all of packed shards + int8 sum
    gather) must reproduce the exact multi-worker sum on an 8-device
    virtual worker mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu.parallel import compression as C

    W, n, t = 8, 100, 0.5
    mesh = Mesh(np.array(jax.devices("cpu")[:W]), ("worker",))
    rs = np.random.RandomState(0)
    # per-worker quantized vectors in {-t, 0, +t}
    qs = (rs.randint(-1, 2, size=(W, n)) * t).astype(np.float32)
    words = np.stack([np.asarray(C.encode_2bit(jnp.asarray(q), t))
                      for q in qs])
    nw = words.shape[1]
    k = -(-nw // W)
    wordsp = np.pad(words, ((0, 0), (0, k * W - nw)))
    garr = jax.device_put(jnp.asarray(wordsp),
                          NamedSharding(mesh, P("worker")))
    fn = C._rs_jitted(mesh, W, k, C._sum_code_dtype(W))
    codes = np.asarray(fn(garr))
    got = codes[:n].astype(np.float32) * t
    np.testing.assert_allclose(got, qs.sum(axis=0), rtol=0, atol=1e-6)


def test_wire_bytes_beat_dense_for_all_worker_counts():
    """Bytes-on-wire per worker must stay below a dense f32 all-reduce for
    every W (the round-3 allgather wire inverted past W~33)."""
    from incubator_mxnet_tpu.parallel.compression import wire_bytes_per_worker
    n = 1 << 20
    for W in (2, 4, 8, 16, 32, 64, 128, 512, 1024):
        compressed, dense = wire_bytes_per_worker(n, W)
        assert compressed < dense, (W, compressed, dense)


def test_compressed_wire_hlo_contains_intended_collectives():
    """Pin the LOWERING the scale-correctness claim rides on (round-4
    verdict #5): the compiled HLO of the jitted wire must contain (a) an
    all-to-all on u32 — the packed 2-bit reduce-scatter — and (b) an
    all-gather on s8 — the exact integer shard sums; and NO collective
    may move f32 (a silent GSPMD re-lowering to a dense f32 all-reduce
    would keep the numbers right while shipping 8x the bytes)."""
    import re
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu.parallel import compression as C

    W, n = 8, 100
    mesh = Mesh(np.array(jax.devices("cpu")[:W]), ("worker",))
    nw = C.packed_words(n)
    k = -(-nw // W)
    garr = jax.device_put(jnp.zeros((W, W * k), jnp.uint32),
                          NamedSharding(mesh, P("worker")))
    fn = C._rs_jitted(mesh, W, k, C._sum_code_dtype(W))
    hlo = fn.lower(garr).compile().as_text()

    a2a = re.findall(r"\bu32\[[\d,]*\][^\n]*\ball-to-all", hlo)
    assert a2a, "no u32 all-to-all in compiled HLO:\n" + hlo[:2000]
    ag = re.findall(r"\bs8\[[\d,]*\][^\n]*\ball-gather", hlo)
    assert ag, "no s8 all-gather in compiled HLO:\n" + hlo[:2000]
    f32_coll = re.findall(
        r"\bf32\[[\d,]*\][^\n]*\b(all-reduce|all-gather|all-to-all)", hlo)
    assert not f32_coll, "f32 collective leaked into the wire: %s" % f32_coll
