"""2-bit gradient wire-packing unit tests.

The wire contract of src/kvstore/gradient_compression.h:37-132: 16
two-bit codes per 32-bit word (code 1 = +threshold, 2 = -threshold,
0 = zero), so the transported buffer is 1/16 the bytes of the f32
values; dequantization reproduces the quantized values exactly.
"""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.parallel import compression as C


def _quantize(x, t):
    return np.where(x >= t, t, np.where(x <= -t, -t, 0.0)).astype(np.float32)


def test_packed_size_is_one_sixteenth():
    for n in (1, 15, 16, 17, 1000, 4096):
        rs = np.random.RandomState(n)
        q = _quantize(rs.randn(n).astype(np.float32), 0.5)
        words = C.encode_2bit(mx.nd.array(q)._read(), 0.5)
        assert words.dtype == np.uint32
        assert words.nbytes == C.packed_nbytes(n)
        # the 1/16 contract vs the f32 buffer (up to one word of padding)
        assert words.nbytes <= 4 * n / 16 + 4


def test_roundtrip_exact():
    rs = np.random.RandomState(0)
    for t in (0.5, 0.25, 2.0):
        x = rs.randn(1037).astype(np.float32) * 2
        q = _quantize(x, t)
        words = C.encode_2bit(mx.nd.array(x)._read() * 0 + q, t)
        back = np.asarray(C.decode_2bit(words, t, 1037))
        np.testing.assert_array_equal(back, q)


def test_decode_sum_matches_dense_sum():
    rs = np.random.RandomState(3)
    t = 0.5
    n = 515
    qs = [_quantize(rs.randn(n).astype(np.float32), t) for _ in range(4)]
    words = np.stack([np.asarray(C.encode_2bit(mx.nd.array(q)._read(), t))
                      for q in qs])
    import jax.numpy as jnp
    summed = np.asarray(C.decode_2bit_sum(jnp.asarray(words), t, n))
    np.testing.assert_allclose(summed, np.sum(qs, axis=0), atol=1e-6)


def test_kvstore_compression_algebra_single_process():
    """Residual accumulation semantics through the public kvstore API
    (unchanged by the wire packing — single process takes the local
    path)."""
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", mx.nd.zeros((4,)))
    # push 0.3: below threshold -> quantized 0, residual 0.3
    kv.push("w", mx.nd.ones((4,)) * 0.3)
    out = mx.nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.0)
    # push 0.3 again: residual 0.6 >= t -> quantized 0.5, residual 0.1
    kv.push("w", mx.nd.ones((4,)) * 0.3)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)
