"""Vision/detection op tests (parity model: tests/python/unittest/
test_operator.py ROI/multibox/sampler sections)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def test_roi_pooling():
    data = mx.nd.array(np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8))
    rois = mx.nd.array(np.array([[0, 0, 0, 7, 7]], np.float32))
    out = mx.nd.ROIPooling(data, rois, pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    # max of each quadrant
    np.testing.assert_allclose(out.asnumpy()[0, 0],
                               [[27, 31], [59, 63]])


def test_roi_align():
    data = mx.nd.array(np.ones((1, 2, 8, 8), np.float32))
    rois = mx.nd.array(np.array([[0, 1, 1, 5, 5]], np.float32))
    out = mx.nd.ROIAlign(data, rois, pooled_size=(3, 3), spatial_scale=1.0)
    assert out.shape == (1, 2, 3, 3)
    np.testing.assert_allclose(out.asnumpy(), 1.0, rtol=1e-5)


def test_multibox_prior():
    data = mx.nd.zeros((1, 8, 4, 4))
    anchors = mx.nd.MultiBoxPrior(data, sizes=(0.5, 0.25), ratios=(1, 2))
    # num anchors per pixel = sizes + ratios - 1 = 3
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()[0]
    # first anchor centered at (0.125, 0.125) with size 0.5
    np.testing.assert_allclose(a[0], [0.125 - 0.25, 0.125 - 0.25,
                                      0.125 + 0.25, 0.125 + 0.25], atol=1e-6)


def test_multibox_target():
    anchors = mx.nd.array(np.array([[[0.0, 0.0, 0.5, 0.5],
                                     [0.5, 0.5, 1.0, 1.0]]], np.float32))
    # one gt box matching the second anchor
    label = mx.nd.array(np.array([[[1, 0.5, 0.5, 1.0, 1.0],
                                   [-1, 0, 0, 0, 0]]], np.float32))
    cls_pred = mx.nd.zeros((1, 3, 2))
    loc_t, loc_m, cls_t = mx.nd.MultiBoxTarget(anchors, label, cls_pred)
    ct = cls_t.asnumpy()[0]
    assert ct[1] == 2.0  # class 1 → target 2 (0 is background)
    assert ct[0] == 0.0
    lm = loc_m.asnumpy()[0].reshape(2, 4)
    assert lm[1].sum() == 4 and lm[0].sum() == 0


def test_multibox_detection():
    anchors = mx.nd.array(np.array([[[0.1, 0.1, 0.4, 0.4],
                                     [0.6, 0.6, 0.9, 0.9]]], np.float32))
    cls_prob = mx.nd.array(np.array([[[0.1, 0.8],     # background
                                      [0.9, 0.1],     # class 0
                                      [0.0, 0.1]]], np.float32))
    loc_pred = mx.nd.zeros((1, 8))
    out = mx.nd.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                  nms_threshold=0.5)
    det = out.asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert len(kept) >= 1
    assert kept[0][0] == 0.0  # class 0 detection
    np.testing.assert_allclose(kept[0][2:], [0.1, 0.1, 0.4, 0.4], atol=1e-5)


def test_box_nms():
    boxes = np.array([[[0.9, 0.0, 0.0, 1.0, 1.0],
                       [0.8, 0.05, 0.05, 1.0, 1.0],   # overlaps first
                       [0.7, 2.0, 2.0, 3.0, 3.0]]], np.float32)
    data = mx.nd.array(boxes)
    out = mx.nd.box_nms(data, overlap_thresh=0.5, coord_start=1,
                        score_index=0)
    v = out.asnumpy()[0]
    assert v[0][0] == pytest.approx(0.9)
    assert v[1][0] == pytest.approx(0.7)  # second suppressed, third kept
    assert v[2][0] == -1.0


def test_proposal():
    B, A, H, W = 1, 2, 4, 4  # A must equal len(scales) * len(ratios)
    rs = np.random.RandomState(0)
    cls_prob = mx.nd.array(rs.rand(B, 2 * A, H, W).astype(np.float32))
    bbox_pred = mx.nd.array((rs.randn(B, 4 * A, H, W) * 0.1).astype(np.float32))
    im_info = mx.nd.array(np.array([[64, 64, 1.0]], np.float32))
    rois = mx.nd.Proposal(cls_prob, bbox_pred, im_info,
                          rpn_pre_nms_top_n=12, rpn_post_nms_top_n=5,
                          feature_stride=16, scales=(2, 4), ratios=(1.0,))
    assert rois.shape == (5, 5)
    v = rois.asnumpy()
    assert (v[:, 0] == 0).all()
    assert (v[:, 1:] >= 0).all() and (v[:, 1:] <= 64).all()


def test_bilinear_sampler_identity():
    data = mx.nd.array(np.random.RandomState(0).randn(1, 2, 5, 5)
                       .astype(np.float32))
    xs = np.linspace(-1, 1, 5, dtype=np.float32)
    gx, gy = np.meshgrid(xs, xs)
    grid = mx.nd.array(np.stack([gx, gy])[None])
    out = mx.nd.BilinearSampler(data, grid)
    np.testing.assert_allclose(out.asnumpy(), data.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_spatial_transformer_identity():
    data = mx.nd.array(np.random.RandomState(1).randn(2, 3, 6, 6)
                       .astype(np.float32))
    theta = mx.nd.array(np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32),
                                (2, 1)))
    out = mx.nd.SpatialTransformer(data, theta, target_shape=(6, 6),
                                   transform_type="affine",
                                   sampler_type="bilinear")
    np.testing.assert_allclose(out.asnumpy(), data.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_grid_generator_warp():
    flow = mx.nd.zeros((1, 2, 4, 4))
    grid = mx.nd.GridGenerator(flow, transform_type="warp")
    g = grid.asnumpy()[0]
    np.testing.assert_allclose(g[0, 0], np.linspace(-1, 1, 4), atol=1e-6)


def test_correlation_self():
    data = mx.nd.array(np.ones((1, 4, 6, 6), np.float32))
    out = mx.nd.Correlation(data, data, max_displacement=1, pad_size=1)
    assert out.shape[1] == 9  # (2d+1)^2 displacement channels


def test_pad():
    x = mx.nd.array(np.ones((1, 1, 2, 2), np.float32))
    out = mx.nd.Pad(x, mode="constant", pad_width=(0, 0, 0, 0, 1, 1, 1, 1),
                    constant_value=5.0)
    assert out.shape == (1, 1, 4, 4)
    v = out.asnumpy()[0, 0]
    assert v[0, 0] == 5 and v[1, 1] == 1


def test_crop():
    x = mx.nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    like = mx.nd.zeros((1, 1, 2, 2))
    out = mx.nd.Crop(x, like, num_args=2, center_crop=True)
    np.testing.assert_allclose(out.asnumpy()[0, 0], [[5, 6], [9, 10]])


def test_bilinear_sampler_grad():
    from incubator_mxnet_tpu import autograd
    data = mx.nd.array(np.random.RandomState(0).randn(1, 1, 4, 4)
                       .astype(np.float32))
    data.attach_grad()
    xs = np.linspace(-0.9, 0.9, 4, dtype=np.float32)
    gx, gy = np.meshgrid(xs, xs)
    grid = mx.nd.array(np.stack([gx, gy])[None])
    with autograd.record():
        out = mx.nd.BilinearSampler(data, grid)
    out.backward()
    assert np.abs(data.grad.asnumpy()).sum() > 0


def test_multibox_target_negative_mining():
    """negative_mining_ratio keeps only ratio x num_pos hard negatives as
    background; the rest become ignore_label (multibox_target.cc)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    rs = np.random.RandomState(0)
    # a 4x4 grid of unit anchors; one gt box matching the first anchor
    xs, ys_ = np.meshgrid(np.arange(4) / 4.0, np.arange(4) / 4.0)
    anchors = np.stack([xs.ravel(), ys_.ravel(),
                        xs.ravel() + 0.25, ys_.ravel() + 0.25], 1)
    anchors = anchors[None].astype(np.float32)           # (1, 16, 4)
    label = np.array([[[0, 0.0, 0.0, 0.25, 0.25]]], np.float32)
    cls_pred = rs.rand(1, 2, 16).astype(np.float32)      # confident junk
    lt, lm, ct = nd.MultiBoxTarget(nd.array(anchors), nd.array(label),
                                   nd.array(cls_pred),
                                   negative_mining_ratio=3.0,
                                   negative_mining_thresh=0.5)
    c = ct.asnumpy()[0]
    assert (c == 1).sum() == 1                  # one positive (cls 0 -> 1)
    assert (c == 0).sum() == 3                  # 3x1 hard negatives kept
    assert (c == -1).sum() == 12                # the rest ignored
    # hardness order: the kept negatives are the lowest-background-prob
    # (most confidently wrong) candidates, per multibox_target.cc
    e = np.exp(cls_pred[0] - cls_pred[0].max(0, keepdims=True))
    bg = (e / e.sum(0))[0]
    kept = set(np.where(c == 0)[0])
    hardest = set(np.argsort(np.where(np.arange(16) == 0, np.inf, bg))[:3])
    assert kept == hardest, (kept, hardest)
    # without mining every negative stays background
    _, _, ct2 = nd.MultiBoxTarget(nd.array(anchors), nd.array(label),
                                  nd.array(cls_pred))
    c2 = ct2.asnumpy()[0]
    assert (c2 == -1).sum() == 0 and (c2 == 0).sum() == 15
