"""Engine op-bulking: `with mx.engine.bulk()` defers pure eager ops and
replays the segment as one jitted program (the TPU-native BulkAppend,
threaded_engine.h:472-509; see engine.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, engine


def _chain(a, b, c, n=16):
    for _ in range(n // 4):
        a = a * b
        a = a + c
        a = a.abs()
        a = a - c
    return a


def test_bulk_matches_eager():
    rs = np.random.RandomState(0)
    a = nd.array(rs.rand(8, 8))
    b = nd.array(rs.rand(8, 8) + 0.5)
    c = nd.array(rs.rand(8, 8))
    want = _chain(a, b, c).asnumpy()
    with engine.bulk(64):
        got = _chain(a, b, c)
        # still deferred here; asnumpy must flush transparently
        got_np = got.asnumpy()
    np.testing.assert_allclose(got_np, want, rtol=1e-4, atol=1e-6)


def test_bulk_segment_overflow_flushes():
    """More ops than the segment size: auto-flush mid-scope, results still
    exact across the segment boundary."""
    rs = np.random.RandomState(1)
    a = nd.array(rs.rand(4, 4))
    b = nd.array(rs.rand(4, 4) + 0.5)
    c = nd.array(rs.rand(4, 4))
    want = _chain(a, b, c, n=32).asnumpy()
    with engine.bulk(5):   # forces several flushes
        got = _chain(a, b, c, n=32).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_bulk_replay_cache_hits():
    """Steady-state loops must reuse the compiled replay program."""
    rs = np.random.RandomState(2)
    a = nd.array(rs.rand(4, 4))
    b = nd.array(rs.rand(4, 4) + 0.5)
    c = nd.array(rs.rand(4, 4))
    before = len(engine._replay_cache)
    for _ in range(4):
        with engine.bulk(64):
            _chain(a, b, c).asnumpy()
    grew = len(engine._replay_cache) - before
    assert grew == 1, grew


def test_bulk_random_ops_consume_keys():
    """RNG ops defer too (key captured at record time): two bulk scopes
    draw different samples, matching eager key-consumption semantics."""
    mx.random.seed(0)
    with engine.bulk(16):
        x1 = nd.random.uniform(shape=(16,)).asnumpy()
    with engine.bulk(16):
        x2 = nd.random.uniform(shape=(16,)).asnumpy()
    assert not np.allclose(x1, x2)
    mx.random.seed(0)
    e1 = nd.random.uniform(shape=(16,)).asnumpy()
    np.testing.assert_allclose(x1, e1)


def test_bulk_autograd_runs_eagerly():
    """Recording ops bypass deferral (the tape takes vjp at invoke) and
    training still works inside a bulk scope."""
    rs = np.random.RandomState(3)
    a = nd.array(rs.rand(4, 4))
    a.attach_grad()
    with engine.bulk(64):
        with autograd.record():
            y = (a * a).sum()
        y.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), 2 * a.asnumpy(),
                               rtol=1e-6)


def test_bulk_inplace_write_resolves():
    """In-place stores on deferred values flush first (version semantics
    preserved)."""
    a = nd.array(np.ones((4, 4), np.float32))
    with engine.bulk(64):
        y = a * 2.0
        y[:] = 7.0
        out = (y + 1).asnumpy()
    np.testing.assert_allclose(out, 8.0)


def test_bulk_mixed_with_views():
    a = nd.array(np.arange(16, dtype=np.float32).reshape(4, 4))
    with engine.bulk(64):
        y = a * 2
        v = y[1]           # view of a deferred value: materializes base
        got = v.asnumpy()
    np.testing.assert_allclose(got, np.arange(4, 8, dtype=np.float32) * 2)


def test_bulk_waitall_covers_replay():
    """nd.waitall() must drain bulk-replayed dispatches too (WaitForAll
    contract, review regression)."""
    from incubator_mxnet_tpu.ndarray import ndarray as nd_mod
    nd_mod._DISPATCH_DEVICES.clear()
    a = nd.array(np.ones((8, 8), np.float32))
    with engine.bulk(16):
        out = a * 3 + 1
    assert len(nd_mod._DISPATCH_DEVICES) > 0
    nd.waitall()
    np.testing.assert_allclose(out.asnumpy(), 4.0)


def test_bulk_ext_dedup():
    """Repeated operands enter the replay program once (identity dedup)."""
    a = nd.array(np.ones((4, 4), np.float32))
    b = nd.array(np.ones((4, 4), np.float32) * 2)
    with engine.bulk(32) as scope:
        y = a * b
        z = y + b      # b reused
        w = z * b      # and again
        st = engine._current()
        assert len(st.ext) == 2, st.ext   # a and b only
        got = w.asnumpy()
    np.testing.assert_allclose(got, (1 * 2 + 2) * 2)
