"""Engine op-bulking: `with mx.engine.bulk()` defers pure eager ops and
replays the segment as one jitted program (the TPU-native BulkAppend,
threaded_engine.h:472-509; see engine.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, engine


def _chain(a, b, c, n=16):
    for _ in range(n // 4):
        a = a * b
        a = a + c
        a = a.abs()
        a = a - c
    return a


def test_bulk_matches_eager():
    rs = np.random.RandomState(0)
    a = nd.array(rs.rand(8, 8))
    b = nd.array(rs.rand(8, 8) + 0.5)
    c = nd.array(rs.rand(8, 8))
    want = _chain(a, b, c).asnumpy()
    with engine.bulk(64):
        got = _chain(a, b, c)
        # still deferred here; asnumpy must flush transparently
        got_np = got.asnumpy()
    np.testing.assert_allclose(got_np, want, rtol=1e-4, atol=1e-6)


def test_bulk_segment_overflow_flushes():
    """More ops than the segment size: auto-flush mid-scope, results still
    exact across the segment boundary."""
    rs = np.random.RandomState(1)
    a = nd.array(rs.rand(4, 4))
    b = nd.array(rs.rand(4, 4) + 0.5)
    c = nd.array(rs.rand(4, 4))
    want = _chain(a, b, c, n=32).asnumpy()
    with engine.bulk(5):   # forces several flushes
        got = _chain(a, b, c, n=32).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_bulk_replay_cache_hits():
    """Steady-state loops must reuse the compiled replay program."""
    rs = np.random.RandomState(2)
    a = nd.array(rs.rand(4, 4))
    b = nd.array(rs.rand(4, 4) + 0.5)
    c = nd.array(rs.rand(4, 4))
    before = len(engine._replay_cache)
    for _ in range(4):
        with engine.bulk(64):
            _chain(a, b, c).asnumpy()
    grew = len(engine._replay_cache) - before
    assert grew == 1, grew


def test_bulk_random_ops_consume_keys():
    """RNG ops defer too (key captured at record time): two bulk scopes
    draw different samples, matching eager key-consumption semantics."""
    mx.random.seed(0)
    with engine.bulk(16):
        x1 = nd.random.uniform(shape=(16,)).asnumpy()
    with engine.bulk(16):
        x2 = nd.random.uniform(shape=(16,)).asnumpy()
    assert not np.allclose(x1, x2)
    mx.random.seed(0)
    e1 = nd.random.uniform(shape=(16,)).asnumpy()
    np.testing.assert_allclose(x1, e1)


def test_bulk_autograd_runs_eagerly():
    """Recording ops bypass deferral (the tape takes vjp at invoke) and
    training still works inside a bulk scope."""
    rs = np.random.RandomState(3)
    a = nd.array(rs.rand(4, 4))
    a.attach_grad()
    with engine.bulk(64):
        with autograd.record():
            y = (a * a).sum()
        y.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), 2 * a.asnumpy(),
                               rtol=1e-6)


def test_bulk_inplace_write_resolves():
    """In-place stores on deferred values flush first (version semantics
    preserved)."""
    a = nd.array(np.ones((4, 4), np.float32))
    with engine.bulk(64):
        y = a * 2.0
        y[:] = 7.0
        out = (y + 1).asnumpy()
    np.testing.assert_allclose(out, 8.0)


def test_bulk_mixed_with_views():
    a = nd.array(np.arange(16, dtype=np.float32).reshape(4, 4))
    with engine.bulk(64):
        y = a * 2
        v = y[1]           # view of a deferred value: defers (round 6)
        got = v.asnumpy()  # host read is the only materialization point
    np.testing.assert_allclose(got, np.arange(4, 8, dtype=np.float32) * 2)


def _view_chain(a, b, n=32):
    """n compute ops with two interleaved views per round (reshape in,
    reshape back) — the attention/im2col glue shape from the issue.  Op
    pairs are chosen so XLA cannot FMA-contract across them (mul never
    feeds add directly): bit-for-bit parity must hold between the fused
    replay and per-op eager dispatch."""
    x = a
    for _ in range(n // 4):
        x = x * b
        x = x.reshape((4, 16))      # view 1
        x = x.abs()
        x = x.reshape((8, 8))       # view 2
        x = x - 0.25
        x = x / b
    return x


def test_bulk_view_chain_flushes_once():
    """Tier-1 fragmentation guard: a 32-op chain with two interleaved
    views per round under engine.bulk() must execute as ONE replay
    program (flush-cause counters), bit-for-bit equal to unbulked eager
    execution — view creation may never break the segment again."""
    rs = np.random.RandomState(7)
    a = nd.array(rs.rand(8, 8).astype(np.float32))
    b = nd.array(rs.rand(8, 8).astype(np.float32) + 0.5)
    want = _view_chain(a, b).asnumpy()
    engine.reset_flush_stats()
    with engine.bulk(128):
        got = _view_chain(a, b)
    g = got.asnumpy()
    stats = engine.flush_stats()
    assert stats["causes"]["scope-close"] == 1, stats
    assert sum(stats["causes"].values()) == 1, \
        "view chain fragmented: %r" % (stats,)
    assert list(stats["segment_lengths"].values()) == [1], stats
    np.testing.assert_array_equal(g, want)


def test_bulk_slice_transpose_mid_chain_parity():
    """reshape/slice/transpose mid-chain: bit-for-bit eager-vs-bulk
    forward parity, one program."""
    rs = np.random.RandomState(11)
    av = rs.rand(6, 8).astype(np.float32)

    def run(bulked):
        import contextlib
        a = nd.array(av)
        scope = engine.bulk(64) if bulked else contextlib.nullcontext()
        with scope:
            x = a * 2.0
            x = x.transpose((1, 0))     # (8,6) — registered op
            x = x[2:6]                  # (4,6) — basic slice view
            x = x.reshape((2, 12))      # view
            x = x + 0.5
            x = x.reshape((24,))        # view
            out = (x * x).asnumpy()
        return out

    want = run(False)
    engine.reset_flush_stats()
    got = run(True)
    stats = engine.flush_stats()
    np.testing.assert_array_equal(got, want)
    assert sum(stats["causes"].values()) == 1, stats


def test_bulk_write_through_deferred_view():
    """Write-through to a deferred view rebinds the base inside the same
    program (lax.dynamic_update_slice node): full-slice store and +=
    both stay deferred, and the base observes the write exactly as in
    eager execution."""
    def run(bulked):
        import contextlib
        y0 = nd.array(np.arange(16, dtype=np.float32).reshape(4, 4))
        scope = engine.bulk(64) if bulked else contextlib.nullcontext()
        with scope:
            y = y0 * 2.0
            v = y[1:3]          # deferred view
            v[:] = 7.0          # write-through: scatter node, no flush
            w = y.reshape((2, 8))
            w += 1.0            # read-modify-write through a view
            z = y + 0.0
        return y.asnumpy(), z.asnumpy()

    ye, ze = run(False)
    engine.reset_flush_stats()
    yb, zb = run(True)
    stats = engine.flush_stats()
    np.testing.assert_array_equal(ye, yb)
    np.testing.assert_array_equal(ze, zb)
    assert stats["causes"]["scope-close"] == 1, stats
    assert sum(stats["causes"].values()) == 1, stats


def test_bulk_recorded_view_segment_backward_parity():
    """A recorded (autograd) segment carrying reshape/transpose/slice
    keeps the one-tape-node contract: ONE flush (cause 'autograd'), and
    the segment vjp flows through the view nodes with gradients
    bit-identical to unbulked eager execution."""
    import contextlib
    rs = np.random.RandomState(0)
    xv = rs.randn(4, 6).astype(np.float32)
    wv = rs.randn(6, 8).astype(np.float32)

    def step(bulked):
        x = nd.array(xv)
        w = nd.array(wv)
        x.attach_grad()
        w.attach_grad()
        scope = engine.bulk(64) if bulked else contextlib.nullcontext()
        with scope:
            with autograd.record():
                h = mx.nd.dot(x, w)          # (4,8)
                h = h.reshape((8, 4))
                h = h.transpose((1, 0))      # (4,8)
                h = h[1:3]                   # (2,8)
                loss = (h * h).sum()
            loss.backward()
        return (float(loss.asnumpy()), x.grad.asnumpy().copy(),
                w.grad.asnumpy().copy())

    l0, gx0, gw0 = step(False)
    engine.reset_flush_stats()
    l1, gx1, gw1 = step(True)
    stats = engine.flush_stats()
    assert l0 == l1
    np.testing.assert_array_equal(gx0, gx1)
    np.testing.assert_array_equal(gw0, gw1)
    assert stats["causes"]["autograd"] == 1, stats
    assert sum(stats["causes"].values()) == 1, stats


def test_bulk_view_of_cross_scope_value_materializes():
    """A view whose base pending belongs to a CLOSED segment cannot
    defer: it materializes under the 'view' flush cause — the documented
    fallback, not an error."""
    a = nd.array(np.arange(8, dtype=np.float32))
    with engine.bulk(4):
        y = a * 2.0
        with engine.bulk(4):       # inner scope: y is cross-scope
            v = y.reshape((2, 4))
            z = v + 1.0            # view read falls back, flushes outer
            got = z.asnumpy()
    np.testing.assert_allclose(got, np.arange(8).reshape(2, 4) * 2.0 + 1)


def test_bulk_waitall_covers_replay():
    """nd.waitall() must drain bulk-replayed dispatches too (WaitForAll
    contract, review regression)."""
    from incubator_mxnet_tpu.ndarray import ndarray as nd_mod
    nd_mod._DISPATCH_DEVICES.clear()
    a = nd.array(np.ones((8, 8), np.float32))
    with engine.bulk(16):
        out = a * 3 + 1
    assert len(nd_mod._DISPATCH_DEVICES) > 0
    nd.waitall()
    np.testing.assert_allclose(out.asnumpy(), 4.0)


def test_bulk_ext_dedup():
    """Repeated operands enter the replay program once (identity dedup)."""
    a = nd.array(np.ones((4, 4), np.float32))
    b = nd.array(np.ones((4, 4), np.float32) * 2)
    with engine.bulk(32) as scope:
        y = a * b
        z = y + b      # b reused
        w = z * b      # and again
        st = engine._current()
        assert len(st.ext) == 2, st.ext   # a and b only
        got = w.asnumpy()
    np.testing.assert_allclose(got, (1 * 2 + 2) * 2)


def test_bulk_defers_recorded_ops_gradients_identical():
    """Round-4: autograd-recording ops defer into the segment; the whole
    recorded chain backs up through ONE segment tape node with gradients
    bit-identical to unbulked eager execution
    (threaded_engine.h MXNET_EXEC_BULK_EXEC_TRAIN)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd

    rs = np.random.RandomState(0)
    xv = rs.randn(4, 6).astype(np.float32)
    wv = rs.randn(6, 3).astype(np.float32)

    def train_step(bulked):
        x = mx.nd.array(xv)
        w = mx.nd.array(wv)
        x.attach_grad()
        w.attach_grad()
        import contextlib
        scope = mx.engine.bulk(64) if bulked else contextlib.nullcontext()
        with scope:
            with autograd.record():
                h = mx.nd.dot(x, w)
                h = mx.nd.relu(h)
                h = h * 2.0 + 1.0
                loss = mx.nd.sum(h * h)
            loss.backward()
        return (float(loss.asnumpy()), x.grad.asnumpy().copy(),
                w.grad.asnumpy().copy())

    l0, gx0, gw0 = train_step(False)
    l1, gx1, gw1 = train_step(True)
    assert l0 == l1
    np.testing.assert_array_equal(gx0, gx1)
    np.testing.assert_array_equal(gw0, gw1)


def test_bulk_pause_inside_record_stops_gradient():
    """Ops under autograd.pause() inside a bulked record scope must stay
    constants on the tape, exactly as in eager execution."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd

    x = mx.nd.array(np.ones((3,), np.float32) * 2.0)
    x.attach_grad()
    with mx.engine.bulk(64):
        with autograd.record():
            y = x * 3.0
            with autograd.pause():
                c = y * 10.0          # constant branch: no grad through it
            z = mx.nd.sum(y + c)
        z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [3.0, 3.0, 3.0])


def test_bulk_training_loop_multiple_steps():
    """Steady-state bulked training: several record+backward+update steps
    hit the replay/vjp caches and keep training."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd

    rs = np.random.RandomState(1)
    X = rs.randn(32, 4).astype(np.float32)
    yv = (X @ rs.randn(4).astype(np.float32) > 0).astype(np.float32)
    w = mx.nd.array(rs.randn(4, 1).astype(np.float32) * 0.1)
    w.attach_grad()
    losses = []
    for _ in range(6):
        with mx.engine.bulk(64):
            with autograd.record():
                logits = mx.nd.dot(mx.nd.array(X), w).reshape((-1,))
                p = mx.nd.sigmoid(logits)
                eps = 1e-6
                loss = -mx.nd.mean(mx.nd.array(yv) * mx.nd.log(p + eps)
                                   + (1 - mx.nd.array(yv))
                                   * mx.nd.log(1 - p + eps))
            loss.backward()
        losses.append(float(loss.asnumpy()))
        w -= 0.5 * w.grad
        w.grad[:] = 0
    assert losses[-1] < losses[0], losses


def test_bulk_detach_alias_keeps_separate_grad_slots():
    """x and x.detach() share a buffer but must NOT share a gradient slot
    in a bulked recorded segment (review regression: buffer-id dedup
    differentiated through the detached alias)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd

    def run(bulked):
        import contextlib
        x = mx.nd.array(np.array([2.0, 3.0], np.float32))
        x.attach_grad()
        scope = mx.engine.bulk(16) if bulked else contextlib.nullcontext()
        with scope:
            with autograd.record():
                xd = x.detach()
                loss = (x * xd).sum()
            loss.backward()
        return x.grad.asnumpy().copy()

    np.testing.assert_array_equal(run(False), run(True))


def test_bulk_pause_only_input_grad_untouched():
    """An input that only fed pause-scope ops inside the segment must not
    land on the tape node (review regression: its .grad was overwritten
    with zeros)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd

    x = mx.nd.array(np.ones((3,), np.float32))
    k = mx.nd.array(np.ones((3,), np.float32))
    x.attach_grad()
    k.attach_grad()
    k.grad[:] = 42.0
    with mx.engine.bulk(16):
        with autograd.record():
            y = x * 3.0
            with autograd.pause():
                c = k * 2.0
            z = (y + c).sum()
        z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 3.0)
    np.testing.assert_allclose(k.grad.asnumpy(), 42.0)  # untouched


def test_bulk_inplace_write_mid_segment_uses_fresh_buffer():
    """An in-place write between two deferred ops must rebind the ext
    slot (review regression: owner-keyed dedup returned the stale
    pre-write buffer)."""
    import numpy as np
    import incubator_mxnet_tpu as mx

    w = mx.nd.array(np.array([1.0, 2.0], np.float32))
    with mx.engine.bulk(16):
        y = w * 2.0            # deferred against w's buffer v1
        w += 1.0               # eager mutating op: w rebinds to v2
        z = w * 3.0            # must see v2, not the stale slot
        got_y = y.asnumpy().copy()
        got_z = z.asnumpy().copy()
    np.testing.assert_allclose(got_y, [2.0, 4.0])
    np.testing.assert_allclose(got_z, [6.0, 9.0])


def test_bulk_defers_optimizer_updates():
    """out= stores and mutating optimizer ops defer into the segment
    (round 5 — reference bulks train-segment updates,
    threaded_engine.h:472-509): a chained update + consumer inside one
    bulk scope must match eager bit-for-bit, including momentum state
    written back through mutate_inputs."""
    import numpy as np
    import incubator_mxnet_tpu as mx

    rs = np.random.RandomState(3)
    w0 = rs.randn(8, 4).astype(np.float32)
    g0 = rs.randn(8, 4).astype(np.float32)
    m0 = rs.randn(8, 4).astype(np.float32)

    def run(bulked):
        w, g, m = (mx.nd.array(a) for a in (w0, g0, m0))
        if bulked:
            with mx.engine.bulk(64):
                for _ in range(3):
                    mx.nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9,
                                         wd=0.01, out=w)
                s = (w * 2.0).sum()
                got = float(s.asnumpy())
        else:
            for _ in range(3):
                mx.nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9,
                                     wd=0.01, out=w)
            got = float(((w * 2.0).sum()).asnumpy())
        return w.asnumpy(), m.asnumpy(), got

    we, me, se = run(False)
    wb, mb, sb = run(True)
    np.testing.assert_array_equal(we, wb)
    np.testing.assert_array_equal(me, mb)
    assert abs(se - sb) < 1e-4


def test_bulk_out_store_dtype_mismatch_falls_back():
    """A deferred out= store rebinds the buffer with no astype fixup, so
    a dtype-mismatched target must dispatch eagerly (and still cast)."""
    import numpy as np
    import incubator_mxnet_tpu as mx

    a = mx.nd.array(np.ones((4,), np.float32))
    o = mx.nd.zeros((4,), dtype=np.float16)
    with mx.engine.bulk(16):
        mx.nd.elemwise_add(a, a, out=o)
        got = o.asnumpy()
    assert got.dtype == np.float16
    np.testing.assert_allclose(got, 2.0)


def test_bulk_lazy_sparse_sgd_defers():
    """The row-sparse lazy SGD update is a registered op and defers under
    bulk: one flush covers update + consumer, result equals eager."""
    import numpy as np
    import incubator_mxnet_tpu as mx

    rs = np.random.RandomState(5)
    w0 = rs.randn(16, 4).astype(np.float32)
    dense_g = np.zeros((16, 4), np.float32)
    dense_g[[2, 9]] = rs.randn(2, 4)

    def run(bulked):
        opt = mx.optimizer.SGD(learning_rate=0.1, lazy_update=True)
        w = mx.nd.array(w0)
        grad = mx.nd.array(dense_g).tostype("row_sparse")
        if bulked:
            with mx.engine.bulk(16):
                opt.update(0, w, grad, None)
                out = (w * 1.0).sum().asnumpy()
        else:
            opt.update(0, w, grad, None)
            out = (w * 1.0).sum().asnumpy()
        return w.asnumpy(), float(out)

    we, se = run(False)
    wb, sb = run(True)
    np.testing.assert_array_equal(we, wb)
    assert abs(se - sb) < 1e-4
    # untouched rows really untouched
    np.testing.assert_array_equal(we[0], w0[0])
    assert not np.allclose(we[2], w0[2])


def test_bulk_chained_store_dead_intermediates_eliminated():
    """A chain of out= stores rebinds the target N times; only the LAST
    pending is exposed, so the compiled replay must return exactly one
    value (review finding: superseded intermediates escaped as dead
    outputs, shipping N-1 weight-sized buffers per flush)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import engine

    w = mx.nd.array(np.ones((8,), np.float32))
    g = mx.nd.array(np.full((8,), 0.5, np.float32))
    before = set(engine._replay_cache)
    with mx.engine.bulk(16):
        for _ in range(4):
            mx.nd.sgd_update(w, g, lr=0.1, wd=0.0, out=w)
    new_keys = [k for k in engine._replay_cache if k not in before]
    assert len(new_keys) == 1
    live = new_keys[0][-1]
    assert len(live) == 1, "dead intermediate outputs shipped: %r" % (live,)
    np.testing.assert_allclose(w.asnumpy(), 1.0 - 4 * 0.05, rtol=1e-6)
