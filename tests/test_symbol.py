"""Symbol tests (parity model: tests/python/unittest/test_symbol.py +
test_infer_shape.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _mlp():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"), name="sm")


def test_list_arguments_auto_vars():
    out = _mlp()
    assert out.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias", "softmax_label"]
    assert out.list_outputs() == ["sm_output"]


def test_infer_shape_fills_params():
    out = _mlp()
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(
        data=(8, 10), softmax_label=(8,))
    args = dict(zip(out.list_arguments(), arg_shapes))
    assert args["fc1_weight"] == (16, 10)
    assert args["fc2_weight"] == (4, 16)
    assert out_shapes == [(8, 4)]


def test_infer_shape_conv():
    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                              name="c0")
    arg_shapes, out_shapes, _ = conv.infer_shape(data=(2, 3, 16, 16))
    args = dict(zip(conv.list_arguments(), arg_shapes))
    assert args["c0_weight"] == (8, 3, 3, 3)
    assert args["c0_bias"] == (8,)
    assert out_shapes == [(2, 8, 16, 16)]


def test_batchnorm_aux_states():
    bn = mx.sym.BatchNorm(mx.sym.var("x"), name="bn")
    assert bn.list_arguments() == ["x", "bn_gamma", "bn_beta"]
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    arg_shapes, _, aux_shapes = bn.infer_shape(x=(2, 5, 4, 4))
    assert aux_shapes == [(5,), (5,)]


def test_compose():
    net1 = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=10, name="fc1")
    net2 = mx.sym.FullyConnected(mx.sym.var("other"), num_hidden=4, name="fc2")
    composed = net2(other=net1, name="composed")
    args = composed.list_arguments()
    assert "data" in args and "fc1_weight" in args and "fc2_weight" in args
    assert "other" not in args


def test_symbol_arithmetic_eval():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = (a + b * 2.0) / 2.0
    r = c.eval_dict({"a": mx.nd.ones((2, 2)), "b": mx.nd.ones((2, 2))})
    np.testing.assert_allclose(r.asnumpy(), np.full((2, 2), 1.5))


def test_json_roundtrip():
    out = _mlp()
    js = out.tojson()
    out2 = mx.sym.load_json(js)
    assert out2.list_arguments() == out.list_arguments()
    assert out2.list_outputs() == out.list_outputs()
    _, shapes1, _ = out.infer_shape(data=(4, 6), softmax_label=(4,))
    _, shapes2, _ = out2.infer_shape(data=(4, 6), softmax_label=(4,))
    assert shapes1 == shapes2


def test_save_load_file(tmp_path):
    out = _mlp()
    fname = str(tmp_path / "sym.json")
    out.save(fname)
    out2 = mx.sym.load(fname)
    assert out2.list_arguments() == out.list_arguments()


def test_group_and_getitem():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    fc2 = mx.sym.FullyConnected(data, num_hidden=4, name="fc2")
    grp = mx.sym.Group([fc1, fc2])
    assert grp.list_outputs() == ["fc1_output", "fc2_output"]
    assert grp[0].name == "fc1"
    _, out_shapes, _ = grp.infer_shape(data=(2, 8))
    assert out_shapes == [(2, 16), (2, 4)]


def test_get_internals():
    out = _mlp()
    internals = out.get_internals()
    names = internals.list_outputs()
    assert any("fc1" in n for n in names)


def test_executor_forward_backward():
    out = _mlp()
    exe = out.simple_bind(ctx=mx.cpu(), data=(8, 10), softmax_label=(8,))
    rs = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = rs.randn(*arr.shape).astype(np.float32) * 0.1
    outs = exe.forward(is_train=True,
                       data=rs.randn(8, 10).astype(np.float32),
                       softmax_label=rs.randint(0, 4, (8,)).astype(np.float32))
    assert outs[0].shape == (8, 4)
    np.testing.assert_allclose(outs[0].asnumpy().sum(axis=1),
                               np.ones(8), rtol=1e-5)  # softmax rows
    exe.backward()
    assert np.abs(exe.grad_dict["fc1_weight"].asnumpy()).sum() > 0


def test_executor_grad_add_req():
    x = mx.sym.var("x")
    y = mx.sym.sum(x * x)
    exe = y.bind(mx.cpu(), {"x": mx.nd.array(np.ones(3, np.float32))},
                 args_grad={"x": mx.nd.zeros(3)}, grad_req="add")
    exe.forward(is_train=True)
    exe.backward()
    exe.forward(is_train=True)
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["x"].asnumpy(), np.full(3, 4.0))


def test_executor_reshape():
    out = _mlp()
    exe = out.simple_bind(ctx=mx.cpu(), data=(8, 10), softmax_label=(8,))
    exe2 = exe.reshape(data=(4, 10), softmax_label=(4,))
    assert exe2.arg_dict["data"].shape == (4, 10)
    # params shared
    assert exe2.arg_dict["fc1_weight"] is exe.arg_dict["fc1_weight"]


def test_monitor_callback():
    out = _mlp()
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 10), softmax_label=(2,))
    taps = []
    exe.set_monitor_callback(lambda name, arr: taps.append(name))
    exe.forward(is_train=False, data=np.zeros((2, 10), np.float32),
                softmax_label=np.zeros((2,), np.float32))
    assert any("fc1" in t for t in taps)


def test_attr_scope():
    with mx.AttrScope(ctx_group="dev1"):
        a = mx.sym.var("a")
        b = mx.sym.FullyConnected(a, num_hidden=3)
    assert a.attr("ctx_group") == "dev1"
    assert b.attr("ctx_group") == "dev1"


def test_var_shape_attr():
    v = mx.sym.var("w", shape=(3, 4))
    fc = mx.sym.FullyConnected(mx.sym.var("data"), weight=v, num_hidden=3,
                               no_bias=True)
    arg_shapes, out_shapes, _ = fc.infer_shape(data=(2, 4))
    assert out_shapes == [(2, 3)]


def test_autograd_get_symbol():
    from incubator_mxnet_tpu import autograd
    x = mx.nd.array(np.ones((2, 2), np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x * 2).sum()
    s = autograd.get_symbol(y)
    assert isinstance(s, mx.sym.Symbol)
