"""Sparse NDArray tests (parity model: tests/python/unittest/
test_sparse_ndarray.py + test_sparse_operator.py + the sparse end-to-end
benchmark benchmark/python/sparse/sparse_end2end.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.ndarray import sparse as sp


def test_rsp_roundtrip():
    a = np.array([[0, 0], [1, 2], [0, 0], [3, 4]], np.float32)
    rsp = sp.row_sparse_array(a)
    assert rsp.stype == "row_sparse"
    np.testing.assert_allclose(rsp.asnumpy(), a)
    np.testing.assert_allclose(rsp.indices.asnumpy(), [1, 3])
    dense = rsp.tostype("default")
    np.testing.assert_allclose(dense.asnumpy(), a)


def test_csr_roundtrip():
    a = np.array([[0, 2, 0], [1, 0, 3]], np.float32)
    csr = sp.csr_matrix(a)
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.asnumpy(), a)
    np.testing.assert_allclose(csr.indptr.asnumpy(), [0, 1, 3])
    back = csr.tostype("default")
    np.testing.assert_allclose(back.asnumpy(), a)


def test_cast_storage_via_ndarray():
    a = mx.nd.array(np.array([[1, 0], [0, 0]], np.float32))
    rsp = a.tostype("row_sparse")
    assert rsp.stype == "row_sparse"
    assert rsp.indices.shape == (1,)


def test_retain():
    a = np.diag(np.arange(1.0, 5.0)).astype(np.float32)
    rsp = sp.row_sparse_array(a)
    kept = sp.retain(rsp, mx.nd.array(np.array([0, 2], np.float32)))
    np.testing.assert_allclose(kept.indices.asnumpy(), [0, 2])
    d = kept.asnumpy()
    assert d[0, 0] == 1 and d[2, 2] == 3 and d[1, 1] == 0


def test_csr_dot():
    rs = np.random.RandomState(0)
    A = rs.rand(5, 7).astype(np.float32) * (rs.rand(5, 7) > 0.6)
    B = rs.randn(7, 3).astype(np.float32)
    csr = sp.csr_matrix(A)
    out = sp.dot(csr, mx.nd.array(B))
    np.testing.assert_allclose(out.asnumpy(), A @ B, rtol=1e-5, atol=1e-6)
    # transpose_a
    C = rs.randn(5, 3).astype(np.float32)
    outT = sp.dot(csr, mx.nd.array(C), transpose_a=True)
    np.testing.assert_allclose(outT.asnumpy(), A.T @ C, rtol=1e-5, atol=1e-6)


def test_rsp_add():
    r1 = sp.row_sparse_array(np.array([[1, 1], [0, 0], [2, 2]], np.float32))
    r2 = sp.row_sparse_array(np.array([[0, 0], [3, 3], [4, 4]], np.float32))
    s = sp.elemwise_add(r1, r2)
    assert s.stype == "row_sparse"
    np.testing.assert_allclose(s.asnumpy(), [[1, 1], [3, 3], [6, 6]])


def test_sparse_sgd_lazy_update():
    w = mx.nd.array(np.ones((4, 2), np.float32))
    grad = sp.row_sparse_array((np.array([[1.0, 1.0]], np.float32),
                                np.array([2])), shape=(4, 2))
    opt = mx.optimizer.SGD(learning_rate=0.5)
    updater = mx.optimizer.get_updater(opt)
    updater(0, grad, w)
    out = w.asnumpy()
    np.testing.assert_allclose(out[2], [0.5, 0.5])
    np.testing.assert_allclose(out[[0, 1, 3]], 1.0)  # untouched rows


def test_kvstore_sparse_push_pull():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((4, 2)))
    g1 = sp.row_sparse_array((np.array([[1.0, 1.0]], np.float32),
                              np.array([1])), shape=(4, 2))
    g2 = sp.row_sparse_array((np.array([[2.0, 2.0]], np.float32),
                              np.array([3])), shape=(4, 2))
    kv.push("w", [g1, g2])
    out = mx.nd.zeros((4, 2))
    kv.pull("w", out=out)
    v = out.asnumpy()
    np.testing.assert_allclose(v[1], [1, 1])
    np.testing.assert_allclose(v[3], [2, 2])
    # row_sparse_pull of selected rows
    rs_out = mx.nd.zeros((4, 2))
    kv.row_sparse_pull("w", out=rs_out,
                       row_ids=mx.nd.array(np.array([3], np.float32)))
    v2 = rs_out.asnumpy()
    np.testing.assert_allclose(v2[3], [2, 2])
    assert v2[1].sum() == 0


def test_sparse_linear_classification_end_to_end():
    """BASELINE config 5: linear classifier on sparse features (ref:
    benchmark/python/sparse/sparse_end2end.py semantics)."""
    rs = np.random.RandomState(0)
    n, d, k = 200, 50, 3
    X = (rs.rand(n, d) * (rs.rand(n, d) > 0.8)).astype(np.float32)
    w_true = rs.randn(d, k).astype(np.float32)
    y = (X @ w_true).argmax(axis=1)
    csr = sp.csr_matrix(X)

    W = mx.nd.array(np.zeros((d, k), np.float32))
    opt = mx.optimizer.SGD(learning_rate=2.0)
    updater = mx.optimizer.get_updater(opt)
    for _ in range(150):
        logits = sp.dot(csr, W).asnumpy()
        p = np.exp(logits - logits.max(1, keepdims=True))
        p /= p.sum(1, keepdims=True)
        p[np.arange(n), y] -= 1
        gW = sp.dot(csr, mx.nd.array(p / n), transpose_a=True)
        updater(0, gW, W)
    acc = (sp.dot(csr, W).asnumpy().argmax(1) == y).mean()
    assert acc > 0.85, acc


def test_libsvm_iter(tmp_path):
    fn = str(tmp_path / "data.libsvm")
    with open(fn, "w") as f:
        f.write("1 0:1.5 3:2.0\n0 1:1.0\n1 2:3.0 3:1.0\n0 0:0.5\n")
    it = mx.io.LibSVMIter(data_libsvm=fn, data_shape=(4,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (2, 4)
    np.testing.assert_allclose(batches[0].data[0].asnumpy()[0],
                               [1.5, 0, 0, 2.0])


def test_sparse_zeros():
    z = sp.zeros("row_sparse", (3, 2))
    assert z.asnumpy().sum() == 0
    zc = sp.zeros("csr", (3, 2))
    assert zc.asnumpy().sum() == 0


def test_dense_sparse_dot_transpose_a():
    """dense(op) @ csr honoring transpose_a (round-1 advisor finding)."""
    rs = np.random.RandomState(3)
    A = rs.randn(4, 5).astype(np.float32)
    B = rs.rand(4, 6).astype(np.float32) * (rs.rand(4, 6) > 0.5)
    csr = sp.csr_matrix(B)
    out = sp.dot(mx.nd.array(A), csr, transpose_a=True)
    np.testing.assert_allclose(out.asnumpy(), A.T @ B, rtol=1e-5, atol=1e-6)
    A2 = rs.randn(3, 4).astype(np.float32)
    out2 = sp.dot(mx.nd.array(A2), csr)
    np.testing.assert_allclose(out2.asnumpy(), A2 @ B, rtol=1e-5, atol=1e-6)
    # transpose_b as well: dense @ csrᵀ
    A3 = rs.randn(3, 6).astype(np.float32)
    out3 = sp.dot(mx.nd.array(A3), csr, transpose_b=True)
    np.testing.assert_allclose(out3.asnumpy(), A3 @ B.T, rtol=1e-5, atol=1e-6)


def test_csr_row_slicing():
    """CSRNDArray row slices stay CSR and match the dense slice (needed
    by executor-group batch splitting over LibSVMIter batches)."""
    rs = np.random.RandomState(0)
    dense = (rs.rand(7, 5) < 0.4) * rs.randn(7, 5).astype(np.float32)
    csr = mx.nd.array(dense).tostype("csr")
    for key in (slice(2, 6), slice(0, 7), 3):
        sl = csr[key]
        want = dense[key if isinstance(key, slice) else slice(key, key + 1)]
        assert sl.stype == "csr"
        np.testing.assert_allclose(sl.todense().asnumpy(), want)


def test_csr_reduce_densify_guard(monkeypatch):
    """The cross-worker CSR reduce must not materialize an unbounded
    dense matrix: above MXTPU_CSR_DENSIFY_BOUND it warns and switches to
    the chunked row-band path, whose result must equal the direct path
    (single-process: the reduce is identity, so chunking correctness is
    exactly what's exercised)."""
    from incubator_mxnet_tpu import kvstore as kvs
    kv = kvs.create("dist_sync")
    rs = np.random.RandomState(7)
    dense = ((rs.rand(64, 48) < 0.15) * rs.randn(64, 48)).astype(np.float32)
    # direct path (bound far above the matrix size)
    monkeypatch.setenv("MXTPU_CSR_DENSIFY_BOUND", str(1 << 30))
    ref = kv._cross_worker_reduce_sparse(mx.nd.array(dense).tostype("csr"))
    np.testing.assert_allclose(ref.todense().asnumpy(), dense, rtol=1e-6)
    # guard path: bound below one full densify -> warning + row bands
    monkeypatch.setenv("MXTPU_CSR_DENSIFY_BOUND",
                       str(10 * 48 * 4))   # ~10 rows per band
    with pytest.warns(UserWarning, match="MXTPU_CSR_DENSIFY_BOUND"):
        out = kv._cross_worker_reduce_sparse(
            mx.nd.array(dense).tostype("csr"))
    assert out.stype == "csr"
    np.testing.assert_allclose(out.todense().asnumpy(), dense, rtol=1e-6)
    np.testing.assert_array_equal(out.indptr.asnumpy(),
                                  ref.indptr.asnumpy())
    np.testing.assert_array_equal(out.indices.asnumpy(),
                                  ref.indices.asnumpy())
