"""INT8 quantization subsystem tests.

Model: reference tests/python/quantization/test_quantization.py
(quantize/dequantize/requantize op checks, quantized conv/FC vs FP32,
quantize_model with calibration — `<=1%` accuracy drop bar from VERDICT).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, sym, io
from incubator_mxnet_tpu.contrib import quantization as qz
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_quantize_int8_roundtrip():
    rng = np.random.RandomState(1)
    x = rng.randn(5, 7).astype(np.float32)
    a = nd.array(x)
    q, qmin, qmax = nd.contrib.quantize(a, nd.min(a), nd.max(a),
                                        out_type="int8")
    assert q.dtype == np.int8
    r = max(abs(x.min()), abs(x.max()))
    assert abs(qmin.asscalar() + r) < 1e-5 and abs(qmax.asscalar() - r) < 1e-5
    deq = nd.contrib.dequantize(q, qmin, qmax)
    # one int8 step = r/127 → round-trip error bounded by half a step
    assert np.abs(deq.asnumpy() - x).max() <= r / 127.0


def test_quantize_uint8():
    x = np.linspace(0.0, 4.0, 32, dtype=np.float32).reshape(4, 8)
    a = nd.array(x)
    q, qmin, qmax = nd.contrib.quantize(a, nd.min(a), nd.max(a),
                                        out_type="uint8")
    assert q.dtype == np.uint8
    deq = nd.contrib.dequantize(q, qmin, qmax)
    assert np.abs(deq.asnumpy() - x).max() <= 4.0 / 255.0


def test_requantize_calibrated():
    rng = np.random.RandomState(2)
    acc = rng.randint(-(2 ** 20), 2 ** 20, size=(3, 4)).astype(np.int32)
    r = 100.0   # int32 grid spans [-r, r]
    real = acc.astype(np.float64) * (r / np.iinfo(np.int32).max)
    out, omin, omax = nd.contrib.requantize(
        nd.array(acc), nd.array(-r, dtype=np.float32),
        nd.array(r, dtype=np.float32),
        min_calib_range=-0.001, max_calib_range=0.001)
    assert out.dtype == np.int8
    assert abs(omax.asscalar() - 0.001) < 1e-9
    deq = out.asnumpy().astype(np.float64) * (0.001 / 127)
    clipped = np.clip(real, -0.001, 0.001)
    assert np.abs(deq - clipped).max() <= 0.001 / 127


def test_quantized_fully_connected_matches_float():
    rng = np.random.RandomState(3)
    d = rng.randn(4, 32).astype(np.float32)
    w = rng.randn(8, 32).astype(np.float32)
    b = rng.randn(8).astype(np.float32)

    def q(x):
        a = nd.array(x)
        return nd.contrib.quantize(a, nd.min(a), nd.max(a), out_type="int8")

    qd, dmin, dmax = q(d)
    qw, wmin, wmax = q(w)
    qb, bmin, bmax = q(b)
    out, omin, omax = nd.contrib.quantized_fully_connected(
        qd, qw, qb, dmin, dmax, wmin, wmax, bmin, bmax,
        num_hidden=8, no_bias=False)
    assert out.dtype == np.int32
    got = nd.contrib.dequantize(out, omin, omax).asnumpy()
    ref = d @ w.T + b
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.03


def test_quantized_conv_matches_float():
    rng = np.random.RandomState(4)
    d = rng.randn(2, 4, 8, 8).astype(np.float32)
    w = rng.randn(6, 4, 3, 3).astype(np.float32)

    def q(x):
        a = nd.array(x)
        return nd.contrib.quantize(a, nd.min(a), nd.max(a), out_type="int8")

    qd, dmin, dmax = q(d)
    qw, wmin, wmax = q(w)
    out, omin, omax = nd.contrib.quantized_conv(
        qd, qw, dmin, dmax, wmin, wmax,
        kernel=(3, 3), num_filter=6, pad=(1, 1), no_bias=True)
    assert out.dtype == np.int32
    got = nd.contrib.dequantize(out, omin, omax).asnumpy()
    ref = nd.Convolution(nd.array(d), nd.array(w), kernel=(3, 3),
                         num_filter=6, pad=(1, 1), no_bias=True).asnumpy()
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.03


def test_quantized_pooling():
    rng = np.random.RandomState(5)
    x = rng.randint(-127, 128, size=(1, 2, 4, 4)).astype(np.int8)
    out, omin, omax = nd.contrib.quantized_pooling(
        nd.array(x), nd.array(-1.0, dtype=np.float32),
        nd.array(1.0, dtype=np.float32),
        kernel=(2, 2), stride=(2, 2), pool_type="max")
    ref = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
    assert (out.asnumpy() == ref).all()
    assert omin.asscalar() == -1.0 and omax.asscalar() == 1.0


def _small_cnn():
    data = sym.var("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                         name="conv0")
    a1 = sym.Activation(c1, act_type="relu", name="relu0")
    p1 = sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                     name="pool0")
    f = sym.Flatten(p1, name="flatten0")
    fc = sym.FullyConnected(f, num_hidden=10, name="fc0")
    return sym.softmax(fc, name="sm0")


def _init_args(net, data_shape, seed=0, scale=0.1):
    rng = np.random.RandomState(seed)
    arg_shapes, _, _ = net.infer_shape(data=data_shape)
    return {n: nd.array(rng.randn(*s).astype(np.float32) * scale)
            for n, s in zip(net.list_arguments(), arg_shapes)}


def test_quantize_symbol_structure():
    net = _small_cnn()
    params = [n for n in net.list_arguments() if n != "data"]
    qsym = qz.quantize_symbol(net, offline_params=params)
    ops = {n._op.name for n in qsym._topo() if not n.is_variable()}
    assert "_contrib_quantized_conv" in ops
    assert "_contrib_quantized_fully_connected" in ops
    assert "_contrib_quantized_pooling" in ops
    assert "_contrib_requantize" in ops
    assert "_contrib_dequantize" in ops
    # offline params became *_quantize(+_min/_max) variables
    qargs = qsym.list_arguments()
    assert "conv0_weight_quantize" in qargs
    assert "conv0_weight_quantize_min" in qargs
    assert "conv0_weight_quantize_max" in qargs
    # excluded node stays float
    qsym2 = qz.quantize_symbol(net, excluded_sym_names=["conv0"],
                               offline_params=params)
    ops2 = {n._op.name for n in qsym2._topo() if not n.is_variable()}
    assert "Convolution" in ops2
    assert "_contrib_quantized_fully_connected" in ops2


def _synthetic_task(rng, n, nclass=4, shape=(3, 16, 16)):
    """Separable images: class c gets a bright patch in quadrant c."""
    x = rng.randn(n, *shape).astype(np.float32) * 0.3
    y = rng.randint(0, nclass, size=n)
    h2, w2 = shape[1] // 2, shape[2] // 2
    for i, c in enumerate(y):
        r, col = divmod(int(c), 2)
        x[i, :, r * h2:(r + 1) * h2, col * w2:(col + 1) * w2] += 1.5
    return x, y.astype(np.float32)


@pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
def test_quantize_model_accuracy(calib_mode):
    """Train a small CNN, quantize it, assert ≤1% accuracy drop
    (the reference quantization suite's bar; VERDICT #3 Done criterion)."""
    from incubator_mxnet_tpu.module import Module
    rng = np.random.RandomState(7)
    xtr, ytr = _synthetic_task(rng, 128)
    xte, yte = _synthetic_task(rng, 64)

    data = sym.var("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                         name="conv0")
    a1 = sym.Activation(c1, act_type="relu", name="relu0")
    p1 = sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                     name="pool0")
    f = sym.Flatten(p1, name="flatten0")
    fc = sym.FullyConnected(f, num_hidden=4, name="fc0")
    train_net = sym.SoftmaxOutput(fc, name="softmax")

    mod = Module(symbol=train_net, context=mx.cpu())
    train_iter = io.NDArrayIter(data=xtr, label=ytr, batch_size=16,
                                shuffle=True)
    mod.fit(train_iter, num_epoch=4,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    arg_params, aux_params = mod.get_params()

    pred_net = sym.softmax(fc, name="sm0")
    ref_args = dict(arg_params)
    ref_args["data"] = nd.array(xte)
    ref = pred_net.bind(mx.cpu(), ref_args, grad_req="null") \
                  .forward(is_train=False)[0].asnumpy()
    fp32_acc = (ref.argmax(1) == yte).mean()
    assert fp32_acc > 0.9, "fp32 model failed to train (acc=%f)" % fp32_acc

    calib = io.NDArrayIter(data=xtr, batch_size=16)
    qsym, qparams, _ = qz.quantize_model(
        pred_net, arg_params, aux_params, calib_mode=calib_mode,
        calib_data=calib, num_calib_examples=64)
    if calib_mode != "none":
        reqs = [n for n in qsym._topo()
                if not n.is_variable() and n._op.name == "_contrib_requantize"]
        assert reqs and all("min_calib_range" in n._params for n in reqs)

    qargs = dict(qparams)
    qargs["data"] = nd.array(xte)
    got = qsym.bind(mx.cpu(), qargs, grad_req="null") \
              .forward(is_train=False)[0].asnumpy()
    int8_acc = (got.argmax(1) == yte).mean()
    assert fp32_acc - int8_acc <= 0.01 + 1e-9, \
        "accuracy drop %.3f > 1%%" % (fp32_acc - int8_acc)


def test_optimal_threshold():
    rng = np.random.RandomState(6)
    # heavy-tailed data: KL threshold should clip the tails
    x = np.concatenate([rng.randn(100000) * 0.1, np.array([20.0, -20.0])])
    _, _, _, th = qz.get_optimal_threshold(x.astype(np.float32))
    assert 0.1 < th < 20.0
    th_dict = qz.get_optimal_thresholds(
        {"layer_output": [x.astype(np.float32)]})
    lo, hi = th_dict["layer_output"]
    assert lo == -hi and 0.1 < hi < 20.0


def test_quantized_model_via_module():
    """Quantized symbol runs through the Module API (simple_bind path with
    dtype-aware allocation)."""
    from incubator_mxnet_tpu.module import Module
    net = _small_cnn()
    data_shape = (4, 3, 16, 16)
    args = _init_args(net, data_shape, seed=9)
    params = {k: v for k, v in args.items() if k != "data"}
    qsym = qz.quantize_symbol(net, offline_params=list(params))
    qparams = qz.quantize_params(qsym, params)

    mod = Module(symbol=qsym, data_names=("data",), label_names=None,
                 context=mx.cpu())
    mod.bind(data_shapes=[("data", data_shape)], for_training=False)
    mod.set_params(qparams, {}, allow_missing=False)
    batch = io.DataBatch(data=[args["data"]], label=None)
    mod.forward(batch, is_train=False)
    got = mod.get_outputs()[0].asnumpy()
    ref = net.bind(mx.cpu(), args, grad_req="null") \
             .forward(is_train=False)[0].asnumpy()
    assert (got.argmax(1) == ref.argmax(1)).all()


def test_fold_batchnorm_preserves_inference():
    """conv+BN folds to one conv with scaled weights/shifted bias; the
    folded graph must reproduce the unfolded inference output and drop
    the BN params, leaving a quantization-friendly conv chain."""
    rng = np.random.RandomState(0)
    data = sym.var("data")
    c = sym.Convolution(data, kernel=(3, 3), num_filter=6, pad=(1, 1),
                        no_bias=True, name="convA")
    b = sym.BatchNorm(c, fix_gamma=False, eps=1e-3, name="bnA")
    r = sym.Activation(b, act_type="relu", name="reluA")
    c2 = sym.Convolution(r, kernel=(1, 1), num_filter=4, name="convB")
    b2 = sym.BatchNorm(c2, fix_gamma=True, eps=1e-3, name="bnB")
    net = sym.Flatten(b2, name="flat")

    args = {
        "convA_weight": nd.array(rng.randn(6, 3, 3, 3).astype(np.float32)),
        "bnA_gamma": nd.array(rng.rand(6).astype(np.float32) + 0.5),
        "bnA_beta": nd.array(rng.randn(6).astype(np.float32)),
        "convB_weight": nd.array(rng.randn(4, 6, 1, 1).astype(np.float32)),
        "convB_bias": nd.array(rng.randn(4).astype(np.float32)),
        "bnB_gamma": nd.array(rng.rand(4).astype(np.float32) + 0.5),
        "bnB_beta": nd.array(rng.randn(4).astype(np.float32)),
    }
    aux = {
        "bnA_moving_mean": nd.array(rng.randn(6).astype(np.float32)),
        "bnA_moving_var": nd.array(rng.rand(6).astype(np.float32) + 0.5),
        "bnB_moving_mean": nd.array(rng.randn(4).astype(np.float32)),
        "bnB_moving_var": nd.array(rng.rand(4).astype(np.float32) + 0.5),
    }
    x = nd.array(rng.randn(2, 3, 8, 8).astype(np.float32))

    ref = net.bind(mx.cpu(), {**args, "data": x}, aux_states=aux,
                   grad_req="null").forward(is_train=False)[0].asnumpy()

    fsym, fargs, faux = qz.fold_batchnorm(net, args, aux)
    assert not faux, "all BN stats should fold away"
    assert not any("gamma" in k or "beta" in k for k in fargs)
    op_names = [n._op.name for n in fsym._topo() if not n.is_variable()]
    assert "BatchNorm" not in op_names
    got = fsym.bind(mx.cpu(), {**fargs, "data": x},
                    grad_req="null").forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    # folded graph quantizes end-to-end and stays close
    qsym, qargs, _ = qz.quantize_model(
        fsym, fargs, {}, calib_mode="naive",
        calib_data=io.NDArrayIter(data=x.asnumpy(), batch_size=2),
        num_calib_examples=2)
    qout = qsym.bind(mx.cpu(), {**qargs, "data": x},
                     grad_req="null").forward(is_train=False)[0].asnumpy()
    # int8 tolerance: relative to the output scale
    assert np.abs(qout - ref).max() / (np.abs(ref).max() + 1e-9) < 0.1


def test_fold_batchnorm_refuses_unsafe_patterns():
    """Shared parameter variables and non-channel axis must NOT fold
    (review findings: a shared weight would be double-rescaled; axis!=1
    scales the wrong weight dimension)."""
    rng = np.random.RandomState(1)
    # shared weight feeding two conv+BN pairs
    data = sym.var("data")
    w = sym.var("shared_weight")
    c1 = sym.Convolution(data, w, kernel=(1, 1), num_filter=4, no_bias=True,
                         name="convS1")
    b1 = sym.BatchNorm(c1, fix_gamma=True, name="bnS1")
    c2 = sym.Convolution(data, w, kernel=(1, 1), num_filter=4, no_bias=True,
                         name="convS2")
    b2 = sym.BatchNorm(c2, fix_gamma=True, name="bnS2")
    net = b1 + b2
    args = {"shared_weight": nd.array(rng.randn(4, 3, 1, 1)
                                      .astype(np.float32)),
            "bnS1_gamma": nd.ones((4,)), "bnS1_beta": nd.zeros((4,)),
            "bnS2_gamma": nd.ones((4,)), "bnS2_beta": nd.zeros((4,))}
    aux = {"bnS1_moving_mean": nd.array(rng.randn(4).astype(np.float32)),
           "bnS1_moving_var": nd.array(rng.rand(4).astype(np.float32) + .5),
           "bnS2_moving_mean": nd.array(rng.randn(4).astype(np.float32)),
           "bnS2_moving_var": nd.array(rng.rand(4).astype(np.float32) + .5)}
    x = nd.array(rng.randn(2, 3, 5, 5).astype(np.float32))
    ref = net.bind(mx.cpu(), {**args, "data": x}, aux_states=aux,
                   grad_req="null").forward(is_train=False)[0].asnumpy()
    fsym, fargs, faux = qz.fold_batchnorm(net, args, aux)
    ops = [n._op.name for n in fsym._topo() if not n.is_variable()]
    assert ops.count("BatchNorm") == 2, "shared weight must refuse to fold"
    got = fsym.bind(mx.cpu(), {**fargs, "data": x}, aux_states=faux,
                    grad_req="null").forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    # axis != 1: refuse
    c3 = sym.Convolution(data, kernel=(1, 1), num_filter=5, no_bias=True,
                         name="convAx")
    b3 = sym.BatchNorm(c3, axis=3, fix_gamma=True, name="bnAx")
    args3 = {"convAx_weight": nd.array(rng.randn(5, 3, 1, 1)
                                       .astype(np.float32)),
             "bnAx_gamma": nd.ones((5,)), "bnAx_beta": nd.zeros((5,))}
    aux3 = {"bnAx_moving_mean": nd.array(rng.randn(5).astype(np.float32)),
            "bnAx_moving_var": nd.array(rng.rand(5).astype(np.float32) + .5)}
    fsym3, _, faux3 = qz.fold_batchnorm(b3, args3, aux3)
    ops3 = [n._op.name for n in fsym3._topo() if not n.is_variable()]
    assert "BatchNorm" in ops3 and faux3, "axis!=1 must refuse to fold"


def test_quantized_resnet_is_single_int8_chain():
    """With quantized relu + residual-add twins (round 5), a folded
    ResNet quantizes into ONE int8 chain: exactly one _contrib_quantize
    (the input) and one _contrib_dequantize (the output) — no per-layer
    float round-trips (the round-4 graph had 17 of them on resnet-18,
    which is why int8 lost end-to-end)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "sym_resnet", os.path.join(
            os.path.dirname(__file__), "..", "example",
            "image-classification", "symbols", "resnet.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    rng = np.random.RandomState(0)
    net = m.get_symbol(num_classes=10, num_layers=18, thumbnail=True)
    pred = net.get_internals()["fc1_output"]
    shapes, _, aux_shapes = pred.infer_shape(data=(2, 3, 32, 32))
    args = {n: nd.array(rng.randn(*s).astype(np.float32) * 0.1)
            for n, s in zip(pred.list_arguments(), shapes) if n != "data"}
    aux = {n: nd.array(np.ones(s, np.float32) if "var" in n
                       else np.zeros(s, np.float32))
           for n, s in zip(pred.list_auxiliary_states(), aux_shapes)}
    fsym, fargs, _ = qz.fold_batchnorm(pred, args, aux)
    calib = rng.uniform(-1, 1, (4, 3, 32, 32)).astype(np.float32)
    qsym, qargs, _ = qz.quantize_model(
        fsym, fargs, {}, calib_mode="naive",
        calib_data=io.NDArrayIter(data=calib, batch_size=4),
        num_calib_examples=4)
    counts = {}
    for n in qsym._topo():
        if not n.is_variable():
            counts[n._op.name] = counts.get(n._op.name, 0) + 1
    assert counts.get("_contrib_quantize", 0) == 1, counts
    assert counts.get("_contrib_dequantize", 0) == 1, counts
    assert counts.get("_contrib_quantized_act", 0) > 0
    assert counts.get("_contrib_quantized_elemwise_add", 0) > 0
    # numerics hold through the full chain
    x = nd.array(rng.uniform(-1, 1, (4, 3, 32, 32)).astype(np.float32))
    ref = fsym.bind(mx.cpu(), {**fargs, "data": x},
                    grad_req="null").forward(is_train=False)[0].asnumpy()
    got = qsym.bind(mx.cpu(), {**qargs, "data": x},
                    grad_req="null").forward(is_train=False)[0].asnumpy()
    assert np.abs(got - ref).mean() / (ref.std() + 1e-9) < 0.05
