"""graftguard: compile-safety lint (GL3xx) + runtime retrace/donation
auditor (EH3xx) for the whole-step compiled path.

The contract under test (analysis/compile_safety.py):

* **Static pass** — every GL301-GL308 fixture fires exactly its code and
  every clean twin stays silent; taint survives factory indirection
  (``jax.jit(make_step())``) but NOT host-static predicates (``x is
  None``, ``name in params``, dict-KEY iteration); a local ``step =
  self._make_step()`` shadows the method of the same name (the
  data_parallel false-positive regression); suppression works at line
  level and at def (scope) level, keeps its justification, and never
  hides a different code.
* **Coverage** — the package walk reaches serving/, armor/ and
  gluon/step_compile.py (planted-finding regression), and the repo
  itself holds ZERO active findings on both the package and registry
  passes.
* **Runtime auditor** (``GRAFT_COMPILE_CHECK=1``) — EH301 retrace
  storms name the exact churned guard-key component (and land in the
  retrace metric + flight recorder), EH302 turns a donated-buffer read
  before write-back into a typed two-stack error, EH303 catches a
  fused-config scalar drifting under an unchanged guard key, EH304
  replays the un-jitted twin on sentinel steps and raises on ULP
  divergence — and the whole auditor is INERT when the flag is off.
* **Baseline** — ``graftlint --baseline`` masks snapshot findings by
  per-key count budget and fails only on NEW ones.
"""
import os
import warnings

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.analysis import compile_safety as cs
from incubator_mxnet_tpu.analysis import contracts, graftlint
from incubator_mxnet_tpu.analysis.compile_safety import (
    GUARD_COMPONENTS, CompileSafetyError, StepAuditor, diff_guard_key)
from incubator_mxnet_tpu.gluon import step_compile as sc
from incubator_mxnet_tpu.telemetry import blackbox, metrics


def active_codes(src, **kw):
    return sorted({d.code for d in cs.lint_source(src, **kw)
                   if not d.suppressed})


# ---------------------------------------------------------------------------
# GL301-GL308: each fixture fires its code, each clean twin is silent
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("code", sorted(cs._GL_FIXTURES))
def test_gl_fixture_fires(code):
    bad, _clean = cs._GL_FIXTURES[code]
    assert active_codes(bad, filename="fixture_%s.py" % code) == [code]


@pytest.mark.parametrize("code", sorted(cs._GL_FIXTURES))
def test_gl_clean_twin_silent(code):
    _bad, clean = cs._GL_FIXTURES[code]
    assert active_codes(clean, filename="fixture_%s_ok.py" % code) == []


def test_rule_tables_cover_all_codes():
    assert sorted(cs.RULES) == ["GL30%d" % i for i in range(1, 9)]
    assert sorted(cs.EH_RULES) == ["EH30%d" % i for i in range(1, 5)]
    assert set(cs._GL_FIXTURES) == set(cs.RULES)


# ---------------------------------------------------------------------------
# taint refinements (each one a shipped false-positive regression)
# ---------------------------------------------------------------------------

def test_gl302_host_static_predicates_exempt():
    # `is None`, membership with an untainted probe, and their boolean
    # combinations branch on Python structure, not traced values
    src = (
        "import jax\n"
        "def mk(f, use_b):\n"
        "    def loss(x, b=None):\n"
        "        if use_b and b is not None:\n"
        "            x = x + b\n"
        "        names = {'w0': x}\n"
        "        if 'w0' in names:\n"
        "            x = x * 2\n"
        "        return x.sum()\n"
        "    return jax.jit(loss)\n")
    assert active_codes(src) == []


def test_gl302_dict_key_iteration_not_tainted():
    # for n, v in tainted.items(): the KEY is a host string; the VALUE
    # still carries taint (second variant must fire)
    clean = (
        "import jax\n"
        "def mk(tvals):\n"
        "    def loss(aux, x):\n"
        "        for n, v in aux.items():\n"
        "            if n not in tvals:\n"
        "                x = x + v\n"
        "        return x.sum()\n"
        "    return jax.jit(loss)\n")
    assert active_codes(clean) == []
    bad = clean.replace("if n not in tvals:", "if v > 0:")
    assert active_codes(bad) == ["GL302"]


def test_traced_set_follows_factory_return():
    src = (
        "import jax\n"
        "def make_step():\n"
        "    def step(x):\n"
        "        if x > 0:\n"
        "            return x\n"
        "        return -x\n"
        "    return step\n"
        "def compile():\n"
        "    step = make_step()\n"
        "    return jax.jit(step)\n")
    assert active_codes(src) == ["GL302"]


def test_local_factory_shadows_method():
    # the data_parallel regression: `step = self._make_step()` then
    # `jax.jit(step)` must resolve to the factory's closure, NOT the
    # host-side method of the same name
    src = (
        "import jax\n"
        "class T:\n"
        "    def _make_step(self):\n"
        "        def step(x):\n"
        "            return x * 2\n"
        "        return step\n"
        "    def step(self, data):\n"
        "        if float(data.sum()) > 0:\n"
        "            return self._place(data)\n"
        "        return data\n"
        "    def compile(self):\n"
        "        step = self._make_step()\n"
        "        return jax.jit(step)\n")
    assert active_codes(src) == []


def test_literal_call_arg_does_not_taint():
    # helper(x, False): the literal must not taint `flat` — branching
    # on a host bool inside the traced helper is fine
    src = (
        "import jax\n"
        "def helper(x, flat):\n"
        "    if flat:\n"
        "        return x.reshape((-1,))\n"
        "    return x\n"
        "def mk():\n"
        "    def loss(x):\n"
        "        return helper(x, False).sum()\n"
        "    return jax.jit(loss)\n")
    assert active_codes(src) == []


def test_static_attrs_break_taint():
    src = (
        "import jax\n"
        "def mk():\n"
        "    def loss(x):\n"
        "        if x.ndim > 2 or x.shape[0] == 1:\n"
        "            return x.sum()\n"
        "        return x.mean()\n"
        "    return jax.jit(loss)\n")
    assert active_codes(src) == []


# ---------------------------------------------------------------------------
# suppression: line level, scope level, justification, no cross-code hiding
# ---------------------------------------------------------------------------

def test_line_suppression_keeps_justification():
    bad, _ = cs._GL_FIXTURES["GL304"]
    sup = bad.replace(
        "seen.append(1)",
        "seen.append(1)  # graftlint: disable=GL304 -- trace-time memo")
    diags = [d for d in cs.lint_source(sup) if d.code == "GL304"]
    assert diags and all(d.suppressed for d in diags)
    assert any(d.justification == "trace-time memo" for d in diags)


def test_scope_suppression_covers_whole_def():
    # one directive above the def silences every occurrence inside it
    # (the optimizer.py fused-apply convention: 9 deliberate bakes)
    src = (
        "import jax\n"
        "def mk(lr, wd):\n"
        "    # graftlint: disable=GL305 -- baked by design\n"
        "    def step(x):\n"
        "        return x * lr + x * wd\n"
        "    return jax.jit(step)\n")
    diags = [d for d in cs.lint_source(src) if d.code == "GL305"]
    assert diags and all(d.suppressed for d in diags)


def test_suppression_does_not_hide_other_codes():
    bad, _ = cs._GL_FIXTURES["GL302"]
    sup = "\n".join(
        line + "  # graftlint: disable=GL304 -- wrong code"
        if "if " in line else line for line in bad.splitlines())
    assert "GL302" in active_codes(sup)


# ---------------------------------------------------------------------------
# coverage: the walk reaches serving/armor/step_compile; the repo is clean
# ---------------------------------------------------------------------------

def test_package_walk_reaches_subsystem_dirs(tmp_path):
    bad, _ = cs._GL_FIXTURES["GL301"]
    pkg = tmp_path / "fakepkg"
    for sub in ("serving", "armor", "gluon"):
        (pkg / sub).mkdir(parents=True)
        (pkg / sub / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "serving" / "batcher.py").write_text(bad)
    (pkg / "armor" / "faults.py").write_text(bad)
    (pkg / "gluon" / "step_compile.py").write_text(bad)
    diags = [d for d in cs.lint_package(root=str(pkg)) if not d.suppressed]
    hit = {os.path.basename(d.file) for d in diags}
    assert hit == {"batcher.py", "faults.py", "step_compile.py"}


def test_repo_package_pass_clean():
    diags = cs.lint_package()
    active = [d for d in diags if not d.suppressed]
    assert active == [], "\n".join(repr(d) for d in active)
    # the intentional bakes stay visible as suppressed findings WITH
    # their reasons (audit trail, not silence)
    sup = [d for d in diags if d.suppressed]
    assert any("optimizer.py" in (d.file or "") for d in sup)
    assert any("step_compile.py" in (d.file or "") for d in sup)
    assert all(d.justification for d in sup)


def test_repo_registry_pass_clean():
    import incubator_mxnet_tpu.ops  # noqa: F401  registration effects
    diags = cs.lint_registry()
    active = [d for d in diags if not d.suppressed]
    assert active == [], "\n".join(repr(d) for d in active)


def test_registry_seeds_only_array_params():
    # num_inputs=None + input_names: host kwargs (no_bias/flatten) must
    # not be seeded — FullyConnected's `if not no_bias and bias is not
    # None` stays clean while a traced-value branch still fires
    import incubator_mxnet_tpu.ops  # noqa: F401
    diags = cs.lint_registry(names={"FullyConnected", "Convolution",
                                    "SequenceMask"})
    assert [d for d in diags if not d.suppressed] == []


# ---------------------------------------------------------------------------
# guard-key diffing (the EH301 component namer / retrace metric label)
# ---------------------------------------------------------------------------

def _synthetic_key(**over):
    base = {
        "input-sig": ((("f32", (4, 5)),),),
        "input-fmt": ("leaf",),
        "param-set": ("w0", "w1"),
        "param-meta": ((("w0", (1, 5), "f32", "write"),),),
        "optimizer-sig": ("sgd", False, 0.9, None, 0.0, 0.0, 1e-8),
        "n-ctx": 1,
        "kvstore-sig": None,
        "bucket-bytes": 4 << 20,
        "quant-cfg": None,
    }
    base.update(over)
    return tuple(base[c] for c in GUARD_COMPONENTS)


def test_diff_guard_key_cold_and_identical():
    k = _synthetic_key()
    comp, detail = diff_guard_key(None, k)
    assert comp == "cold"
    comp, detail = diff_guard_key(k, k)
    assert comp == "identical" and detail is None


@pytest.mark.parametrize("component,change", [
    ("input-sig", ((("f32", (6, 5)),),)),
    ("param-set", ("w0", "w1", "w2")),
    ("optimizer-sig", ("sgd", False, 0.95, None, 0.0, 0.0, 1e-8)),
    ("kvstore-sig", "dist_sync"),
])
def test_diff_guard_key_names_first_changed_component(component, change):
    old = _synthetic_key()
    new = _synthetic_key(**{component: change})
    comp, detail = diff_guard_key(old, new)
    assert comp == component
    assert detail


# ---------------------------------------------------------------------------
# baseline: mask by per-key count budget, fail only on NEW findings
# ---------------------------------------------------------------------------

def _diag(code, op, file, line):
    return contracts.Diagnostic(code, op, "synthetic", file=file,
                                line=line)


def test_baseline_masks_by_count_and_fails_new(tmp_path):
    path = str(tmp_path / "base.json")
    old = [_diag("GL302", "mod.fn", "/a/x.py", 10),
           _diag("GL302", "mod.fn", "/a/x.py", 20),
           _diag("GL305", "mod.g", "/a/y.py", 5)]
    graftlint.write_baseline(path, old)

    # same findings at DIFFERENT lines: still masked (lines are not
    # part of the key), plus one genuinely new finding that must fail
    now = [_diag("GL302", "mod.fn", "/b/x.py", 11),
           _diag("GL302", "mod.fn", "/b/x.py", 99),
           _diag("GL302", "mod.fn", "/b/x.py", 100),   # over budget
           _diag("GL301", "mod.h", "/b/z.py", 1)]      # new code
    new, masked = graftlint.apply_baseline(path, now)
    assert len(masked) == 2
    assert sorted(d.code for d in new) == ["GL301", "GL302"]


def test_baseline_suppressed_findings_stay_out(tmp_path):
    path = str(tmp_path / "base.json")
    d = _diag("GL302", "mod.fn", "/a/x.py", 10)
    d.suppressed = True
    graftlint.write_baseline(path, [d])
    new, masked = graftlint.apply_baseline(
        path, [_diag("GL302", "mod.fn", "/a/x.py", 10)])
    assert len(new) == 1 and not masked


# ---------------------------------------------------------------------------
# runtime auditor harness (EH301-EH304) — one compiled step per module
# ---------------------------------------------------------------------------

def make_cstep(prefix, n_params=4):
    net = sc._make_net(prefix, n_params=n_params)
    sc._seed_params(net)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    return sc.CompiledStep(tr, net, enabled=True), tr, net


@pytest.fixture
def guarded():
    prev_every = os.environ.pop("GRAFT_COMPILE_CHECK_EVERY", None)
    cs.set_enabled(True)
    try:
        yield
    finally:
        cs.set_enabled(None)
        if prev_every is not None:
            os.environ["GRAFT_COMPILE_CHECK_EVERY"] = prev_every


@pytest.fixture(scope="module")
def steady():
    """A warmed compiled step shared by the EH302/303/304 tests (one
    trace, reused; each test arms/disarms the auditor itself)."""
    cstep, tr, net = make_cstep("tguard_steady_")
    x = mx.nd.array(
        np.random.RandomState(5).rand(4, 5).astype(np.float32))
    cs.set_enabled(True)
    try:
        for _ in range(3):
            cstep(x)
    finally:
        cs.set_enabled(None)
    assert cstep.compiled_steps >= 1
    return cstep, tr, x


def test_eh301_storm_names_churned_component(guarded):
    cstep, _tr, _net = make_cstep("tguard_eh301_")
    rng = np.random.RandomState(2)
    before = metrics.registry().snapshot().get(
        "graft_step_retrace_storms_total", {"samples": []})
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for i in range(5):       # every step a NEW shape: pure churn
            x = mx.nd.array(
                rng.rand(2 + i, 5).astype(np.float32))
            cstep(x)
    storm = [str(w.message) for w in caught if "EH301" in str(w.message)]
    assert storm, "shape-flip loop raised no storm warning"
    # the report must name the exact churned guard-key component
    assert "input-sig" in storm[-1]
    assert cstep._auditor is not None and cstep._auditor.storms >= 1
    # journaled to the flight recorder ...
    evs = [e for e in blackbox.events()
           if e.get("kind") == "compile_check"
           and e["data"].get("code") == "EH301"]
    assert evs and evs[-1]["data"].get("component") == "input-sig"
    # ... and counted: retraces labeled by component, storms totaled
    snap = metrics.registry().snapshot()
    labels = {s["labels"].get("reason")
              for s in snap["graft_step_retraces_total"]["samples"]}
    assert "input-sig" in labels
    after = snap.get("graft_step_retrace_storms_total", {"samples": []})
    total = lambda m: sum(s["value"] for s in m["samples"])  # noqa: E731
    assert total(after) > total(before)


def test_eh301_static_loop_no_storm(guarded):
    cstep, _tr, _net = make_cstep("tguard_eh301_quiet_")
    x = mx.nd.array(
        np.random.RandomState(3).rand(4, 5).astype(np.float32))
    for _ in range(6):
        cstep(x)
    aud = cstep._auditor
    assert aud is not None and aud.storms == 0


def test_eh302_donated_read_raises_with_both_stacks(guarded, steady):
    cstep, tr, x = steady
    real_wb = cstep._write_back

    def bad_write_back(entry, new_w, new_s, state_nds, frozen_nds, aux):
        nd = tr._params[entry["trainable"][0]].list_data()[0]
        nd._read()               # donated, replacement not landed yet
        return real_wb(entry, new_w, new_s, state_nds, frozen_nds, aux)

    cstep._write_back = bad_write_back
    # force the sampled EH302 window onto this exact call
    cstep._auditor._since_deep = cstep._auditor.DEEP_EVERY
    try:
        with pytest.raises(CompileSafetyError) as ei:
            cstep(x)
    finally:
        cstep._write_back = real_wb
    assert ei.value.code == "EH302"
    msg = str(ei.value)
    assert "dispatch" in msg and "read stack" in msg
    cstep(x)                     # clean step passes again


def test_eh302_normal_write_back_unpoisons(guarded, steady):
    cstep, tr, x = steady
    # force an armed window: the clean write-back must close it
    cstep._auditor._since_deep = cstep._auditor.DEEP_EVERY
    cstep(x)
    assert not cs._POISON        # sweep closed the dispatch window
    # params are freely readable between steps
    for p in list(tr._params)[:2]:
        p.list_data()[0]._read()


def test_eh302_window_is_sampled(guarded):
    """The EH302/EH303 deep checks run every DEEP_EVERY-th armed call,
    not every call — the per-array dict store / write-back pop is the
    one auditor cost that scales with param count."""
    cstep, _tr, _net = make_cstep("tguard_sample_")
    x = mx.nd.array(
        np.random.RandomState(11).rand(4, 5).astype(np.float32))
    cstep(x)                     # build
    aud = cstep._auditor
    aud._since_deep = 0
    armed = []
    real_poison = cs.StepAuditor.poison

    def counting_poison(self, nds, tag):
        armed.append(tag)
        return real_poison(self, nds, tag)

    cs.StepAuditor.poison = counting_poison
    try:
        for _ in range(2 * aud.DEEP_EVERY):
            cstep(x)
    finally:
        cs.StepAuditor.poison = real_poison
    assert len(armed) == 2


def test_eh303_bake_drift_names_field(guarded, steady):
    cstep, _tr, x = steady
    from incubator_mxnet_tpu import optimizer as opt_mod
    real_cfg = opt_mod._fused_config

    def drifted(optimizer, kind):
        cfg = real_cfg(optimizer, kind)
        return (cfg[0] + 0.05,) + tuple(cfg[1:])

    opt_mod._fused_config = drifted
    sc.opt._fused_config = drifted
    # force the sampled deep-check window onto this exact call
    cstep._auditor._since_deep = cstep._auditor.DEEP_EVERY
    try:
        with pytest.raises(CompileSafetyError) as ei:
            cstep(x)
    finally:
        opt_mod._fused_config = real_cfg
        sc.opt._fused_config = real_cfg
    assert ei.value.code == "EH303"
    assert "momentum" in str(ei.value)
    cstep(x)


def test_eh304_sentinel_parity_and_divergence(guarded, steady):
    cstep, _tr, x = steady
    os.environ["GRAFT_COMPILE_CHECK_EVERY"] = "1"
    try:
        before = cstep._auditor.sentinel_checks if cstep._auditor else 0
        cstep(x)                 # clean sentinel: twin agrees
        aud = cstep._auditor
        assert aud is not None and aud.sentinel_checks > before
        key = next(k for k in cstep._entries
                   if isinstance(cstep._entries.get(k), dict))
        entry = cstep._entries[key]
        real_raw = entry["one_raw"]
        entry["one_raw"] = lambda *a: cs._perturb(real_raw(*a))
        try:
            with pytest.raises(CompileSafetyError) as ei:
                cstep(x)
        finally:
            entry["one_raw"] = real_raw
        assert ei.value.code == "EH304"
        assert "ULP" in str(ei.value)
        cstep(x)
    finally:
        os.environ.pop("GRAFT_COMPILE_CHECK_EVERY", None)


def test_auditor_off_is_inert(steady):
    cstep, tr, x = steady
    cs.set_enabled(False)
    try:
        assert cs.refresh() is False
        assert not cs._ACTIVE[0] and not cs._POISON
        calls_before = cstep._auditor.calls if cstep._auditor else 0
        cstep(x)
        cstep(x)
        calls_after = cstep._auditor.calls if cstep._auditor else 0
        assert calls_after == calls_before
    finally:
        cs.set_enabled(None)


def test_guard_entries_gauge_tracks_cache(guarded, steady):
    cstep, _tr, x = steady
    cstep(x)
    snap = metrics.registry().snapshot()
    vals = [s["value"]
            for s in snap["graft_step_guard_entries"]["samples"]]
    assert vals and vals[-1] >= 1


def test_blackbox_compiled_section(steady):
    cstep, _tr, x = steady
    cs.set_enabled(True)
    try:
        cstep(x)
    finally:
        cs.set_enabled(None)
    report = blackbox.summarize_dump(blackbox.snapshot())
    comp = report.get("compiled")
    assert comp is not None
    assert comp["steps_compiled"] >= 1
    assert isinstance(comp["last_transitions"], list)
    assert isinstance(comp["auditor_reports"], list)


def test_selftest_is_green():
    problems = cs.selftest()
    assert problems == [], "\n".join(problems)
