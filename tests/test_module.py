"""Module API tests (parity model: tests/python/unittest/test_module.py)."""
import logging

import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _mlp_sym(num_hidden=32, classes=3):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                                name="softmax")


def _toy_data(n=120, d=10, k=3, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, d).astype(np.float32)
    w = rs.randn(d, k).astype(np.float32)
    y = (X @ w).argmax(axis=1).astype(np.float32)
    return X, y


def test_module_fit_and_score():
    X, y = _toy_data()
    it = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier())
    acc = mod.score(mx.io.NDArrayIter(X, y, batch_size=20), "acc")[0][1]
    assert acc > 0.9


def test_module_forward_backward_update_loop():
    X, y = _toy_data()
    it = mx.io.NDArrayIter(X, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    metric = mx.metric.create("acc")
    for _ in range(10):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.9


def test_module_multi_device():
    X, y = _toy_data()
    it = mx.io.NDArrayIter(X, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=[mx.cpu(0), mx.cpu(1)])
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore="device", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    metric = mx.metric.create("acc")
    for _ in range(10):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.9


def test_module_checkpoint_roundtrip(tmp_path):
    X, y = _toy_data()
    it = mx.io.NDArrayIter(X, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    prefix = str(tmp_path / "ck")
    mod.save_checkpoint(prefix, 3)
    mod2 = mx.mod.Module.load(prefix, 3, context=mx.cpu())
    mod2.bind([("data", (20, 10))], [("softmax_label", (20,))],
              for_training=False)
    p1 = mod.predict(mx.io.NDArrayIter(X, batch_size=20))
    p2 = mod2.predict(mx.io.NDArrayIter(X, batch_size=20))
    np.testing.assert_allclose(p1.asnumpy(), p2.asnumpy(), rtol=1e-5)


def test_module_predict_shapes():
    X, y = _toy_data()
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=30)
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params()
    out = mod.predict(mx.io.NDArrayIter(X, batch_size=30))
    assert out.shape == (120, 3)


def test_module_input_grads():
    X, y = _toy_data()
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (20, 10))], [("softmax_label", (20,))],
             for_training=True, inputs_need_grad=True)
    mod.init_params()
    batch = mx.io.DataBatch([mx.nd.array(X[:20])], [mx.nd.array(y[:20])])
    mod.forward(batch, is_train=True)
    mod.backward()
    grads = mod.get_input_grads()
    assert grads[0].shape == (20, 10)
    assert np.abs(grads[0].asnumpy()).sum() > 0


def test_module_reshape():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (20, 10))], [("softmax_label", (20,))])
    mod.init_params()
    mod.reshape([("data", (10, 10))], [("softmax_label", (10,))])
    batch = mx.io.DataBatch([mx.nd.zeros((10, 10))], [mx.nd.zeros((10,))])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (10, 3)


def test_module_optimizer_states_io(tmp_path):
    X, y = _toy_data()
    it = mx.io.NDArrayIter(X, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    batch = next(iter(it))
    mod.forward_backward(batch)
    mod.update()
    fname = str(tmp_path / "opt.states")
    mod.save_optimizer_states(fname)
    mod.load_optimizer_states(fname)


def test_bucketing_module():
    """Variable-length MLP buckets sharing params (parity:
    test_module.py test_bucket_module semantics)."""
    def sym_gen(bucket_key):
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc_shared")
        out = mx.sym.SoftmaxOutput(fc, mx.sym.var("softmax_label"),
                                   name="softmax")
        return out, ["data"], ["softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    from incubator_mxnet_tpu.io import DataBatch, DataDesc
    mod.bind([DataDesc("data", (4, 10))], [DataDesc("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for key in (10, 10, 10):
        batch = DataBatch([mx.nd.zeros((4, key))], [mx.nd.zeros((4,))],
                          bucket_key=key,
                          provide_data=[DataDesc("data", (4, key))],
                          provide_label=[DataDesc("softmax_label", (4,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    assert mod.get_outputs()[0].shape == (4, 8)


def test_feedforward_shim():
    X, y = _toy_data()
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ff = mx.model.FeedForward(_mlp_sym(), ctx=mx.cpu(), num_epoch=15,
                                  learning_rate=0.5, numpy_batch_size=20)
        ff.fit(X, y)
        pred = ff.predict(X)
    assert pred.shape == (120, 3)
    assert (pred.argmax(axis=1) == y).mean() > 0.8


def test_save_load_checkpoint_functions(tmp_path):
    sym = _mlp_sym()
    arg = {"fc1_weight": mx.nd.ones((32, 10))}
    aux = {}
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 7, sym, arg, aux)
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 7)
    assert sym2.list_outputs() == sym.list_outputs()
    np.testing.assert_allclose(arg2["fc1_weight"].asnumpy(), np.ones((32, 10)))
