"""Higher-order autograd, waitall, row_sparse_pull, memory accounting.

Parity models: tests/python/unittest/test_autograd.py (grad with
create_graph), test_kvstore.py row-sparse pulls, reference
Engine::WaitForAll contract.
"""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_second_order_grad():
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        g1 = autograd.grad([nd.sum(y)], [x], create_graph=True,
                           retain_graph=True)[0]
        z = nd.sum(g1)
    z.backward()
    assert_almost_equal(x.grad.asnumpy(), 6 * x.asnumpy(), rtol=1e-5)


def test_third_order_grad():
    x = nd.array(np.array([1.0, 2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x * x
        g1 = autograd.grad([nd.sum(y)], [x], create_graph=True,
                           retain_graph=True)[0]
        g2 = autograd.grad([nd.sum(g1)], [x], create_graph=True,
                           retain_graph=True)[0]
        w = nd.sum(g2)
    w.backward()
    assert_almost_equal(x.grad.asnumpy(), 24 * x.asnumpy(), rtol=1e-5)


def test_second_order_through_mixed_graph():
    x = nd.array(np.array([0.5, 1.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x) * x
        g = autograd.grad([nd.sum(y)], [x], create_graph=True,
                          retain_graph=True)[0]
        s = nd.sum(g * g)
    s.backward()
    ex = np.exp(x.asnumpy())
    xv = x.asnumpy()
    expect = 2 * ex * (1 + xv) * ex * (2 + xv)
    assert_almost_equal(x.grad.asnumpy(), expect, rtol=1e-4)


def test_first_order_unaffected():
    x = nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), [4.0], rtol=1e-6)


def test_waitall_blocks_outstanding_work():
    a = nd.array(np.random.randn(64, 64).astype(np.float32))
    outs = [nd.dot(a, a) for _ in range(4)]
    nd.waitall()     # must not raise; after it, results are materialized
    for o in outs:
        assert np.isfinite(o.asnumpy()).all()


def test_row_sparse_pull_sparse_out():
    kv = mx.kv.create("local")
    w = np.arange(12, dtype=np.float32).reshape(4, 3)
    kv.init("w", nd.array(w))
    out = nd.sparse.row_sparse_array(
        (np.zeros((1, 3), np.float32), np.array([0], np.int64)),
        shape=(4, 3))
    kv.row_sparse_pull("w", out=out,
                       row_ids=nd.array(np.array([2, 0, 2], np.float32)))
    assert out.stype == "row_sparse"
    assert (out.indices.asnumpy() == [0, 2]).all()   # deduped + sorted
    assert_almost_equal(out.data.asnumpy(), w[[0, 2]], rtol=1e-7)
    # only the requested rows are materialized
    assert out.data.shape == (2, 3)


def test_row_sparse_pull_dense_out():
    kv = mx.kv.create("local")
    w = np.arange(12, dtype=np.float32).reshape(4, 3)
    kv.init("w2", nd.array(w))
    dout = nd.zeros((4, 3))
    kv.row_sparse_pull("w2", out=dout,
                       row_ids=nd.array(np.array([1], np.float32)))
    got = dout.asnumpy()
    assert_almost_equal(got[1], w[1], rtol=1e-7)
    assert got[0].sum() == 0 and got[2].sum() == 0


def test_memory_stats_api():
    stats = mx.context.memory_stats(mx.cpu())
    assert isinstance(stats, dict)   # CPU backend may report no counters


def test_csr_negative_and_oob_int_indexing():
    """csr[-1] must address the last row; out-of-range ints raise
    (advisor regression: slice(-1, 0) built a corrupt negative-row-count
    CSRNDArray)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    dense = np.arange(12, dtype=np.float32).reshape(4, 3)
    dense[1] = 0
    csr = mx.nd.sparse.csr_matrix(dense)
    np.testing.assert_allclose(csr[-1].asnumpy(), dense[3:4])
    np.testing.assert_allclose(csr[-4].asnumpy(), dense[0:1])
    for bad in (4, -5):
        try:
            csr[bad]
        except IndexError:
            pass
        else:
            raise AssertionError("expected IndexError for %d" % bad)


def test_batchnorm_stat_outputs_carry_gradient():
    """Differentiating through the batch mean/var outputs must reach the
    data (advisor regression: their cotangents were silently dropped)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd

    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(4, 3, 2, 2).astype(np.float32))
    gamma = mx.nd.ones((3,))
    beta = mx.nd.zeros((3,))
    mmean = mx.nd.zeros((3,))
    mvar = mx.nd.ones((3,))
    x.attach_grad()
    with autograd.record():
        out, mean, var = mx.nd.BatchNorm(
            x, gamma, beta, mmean, mvar, fix_gamma=False,
            output_mean_var=True)
        loss = (mean * mean).sum() + var.sum()
    loss.backward()
    g = x.grad.asnumpy()
    xn = x.asnumpy()
    m = xn.shape[0] * xn.shape[2] * xn.shape[3]
    bmean = xn.mean(axis=(0, 2, 3), keepdims=True)
    expect = 2.0 * bmean / m + 2.0 * (xn - bmean) / m
    np.testing.assert_allclose(g, expect, rtol=1e-4, atol=1e-6)
