"""Profiler tests (parity model: tests/python/unittest/test_profiler.py)."""
import json
import os

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, profiler


def test_profile_operators(tmp_path):
    fname = str(tmp_path / "profile_op.json")
    profiler.set_config(filename=fname, profile_imperative=True)
    profiler.set_state("run")
    a = nd.array(np.random.randn(32, 32).astype(np.float32))
    b = nd.array(np.random.randn(32, 32).astype(np.float32))
    for _ in range(3):
        c = nd.dot(a, b)
    c.asnumpy()
    profiler.set_state("stop")
    path = profiler.dump()
    assert path == fname and os.path.exists(fname)
    with open(fname) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    dots = [e for e in events if e["name"] == "dot" and e["ph"] == "X"]
    assert len(dots) >= 3
    assert all(e["dur"] >= 0 and "ts" in e for e in dots)


def test_profile_pause_and_aggregate():
    profiler.set_config(filename="unused.json")
    profiler.set_state("run")
    x = nd.ones((8, 8))
    y = x + x
    profiler.pause()
    _ = x * x          # not recorded
    profiler.resume()
    z = y * y
    z.asnumpy()
    profiler.set_state("stop")
    table = profiler.dumps(reset=True)
    assert "Calls" in table and "Avg(us)" in table
    lines = [ln for ln in table.splitlines() if ln.strip()]
    assert len(lines) >= 2   # header + at least one op row


def test_profile_executor_symbolic(tmp_path):
    fname = str(tmp_path / "profile_sym.json")
    profiler.set_config(filename=fname, profile_symbolic=True)
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    exe = fc.simple_bind(ctx=mx.cpu(), data=(2, 8), grad_req="null")
    profiler.set_state("run")
    exe.forward(is_train=False, data=nd.ones((2, 8)))
    exe.outputs[0].asnumpy()
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    assert any(e["name"].startswith("Executor.forward") for e in events)


def test_profile_custom_objects(tmp_path):
    fname = str(tmp_path / "profile_custom.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    dom = profiler.Domain("app")
    with dom.new_task("step"):
        pass
    with profiler.Event("tick"):
        pass
    cnt = dom.new_counter("samples", 0)
    cnt += 5
    cnt -= 2
    dom.new_marker("here").mark("global")
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events}
    assert {"step", "tick", "samples", "here"} <= names
    counter_vals = [e["args"]["samples"] for e in events
                    if e["name"] == "samples"]
    assert counter_vals == [0, 5, 3]
    marker = [e for e in events if e["name"] == "here"][0]
    assert marker["ph"] == "i" and marker["s"] == "g"


def test_profiler_sync_mode(tmp_path):
    fname = str(tmp_path / "profile_sync.json")
    profiler.set_config(filename=fname, sync=True)
    profiler.set_state("run")
    a = nd.ones((64, 64))
    nd.dot(a, a)
    profiler.set_state("stop")
    profiler.set_config(sync=False)
    profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    assert any(e["name"] == "dot" for e in events)


def test_device_memory_accounting():
    """Per-device live/peak bytes in the aggregate table (SURVEY §2.1
    storage accounting; ref: storage_profiler.h via storage.cc:77-79)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import profiler
    mems = profiler.device_memory()
    assert len(mems) >= 1
    for m in mems:
        for k in ("device", "bytes_in_use", "peak_bytes_in_use",
                  "bytes_limit", "num_allocs", "source"):
            assert k in m
    # the accounting must SEE allocations (allocator counters on real
    # TPU runtimes; live_arrays fallback elsewhere)
    base = mems[0]["bytes_in_use"]
    keep = mx.nd.zeros((1024, 1024))  # 4 MB on device 0
    keep.asnumpy()
    now = profiler.device_memory()[0]
    assert now["bytes_in_use"] - base >= 4 * 1024 * 1024
    assert now["peak_bytes_in_use"] >= now["bytes_in_use"]
    del keep
    profiler.set_config(profile_all=True, aggregate_stats=True)
    profiler.set_state("run")
    x = mx.nd.ones((64, 64))
    y = (x * 2).asnumpy()
    profiler.record_memory_snapshot()
    table = profiler.dumps()
    profiler.set_state("stop")
    assert "Device memory" in table
    assert "InUse(bytes)" in table


def test_op_span_marks_deferred_records_under_bulking(tmp_path):
    """Since graftscope, a deferred op's record event must not present
    dispatch time as op duration: it is marked deferred with its owning
    segment, and the cost lands on the bulk_segment_flush span."""
    from incubator_mxnet_tpu import engine
    fname = str(tmp_path / "profile_bulk.json")
    profiler.dumps(reset=True)          # drop events leaked by prior tests
    profiler.set_config(filename=fname, profile_imperative=True)
    profiler.set_state("run")
    a = nd.ones((16, 16))
    with engine.bulk(16):
        b = a * a
        c = b + a
        c.asnumpy()
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    op_events = [e for e in events
                 if e.get("cat") == "operator" and e["ph"] == "X"
                 and e["name"] != "_ones"]
    assert len(op_events) == 2
    for e in op_events:
        assert e["args"]["deferred"] is True
        assert isinstance(e["args"]["segment"], int)
    flushes = [e for e in events if e["name"] == "bulk_segment_flush"]
    assert len(flushes) == 1
    assert flushes[0]["args"]["segment"] == op_events[0]["args"]["segment"]
    assert flushes[0]["args"]["nodes"] == 2
    # eager path events carry the device_time attribution flag instead
    eager = [e for e in events if e["name"] == "_ones"]
    assert eager and eager[0]["args"]["device_time"] is False


def test_executor_forward_span_device_time_attribution(tmp_path):
    """Executor.forward gets the same treatment: its span says whether
    the duration is async dispatch or true device latency (sync)."""
    fname = str(tmp_path / "profile_exec_attr.json")
    profiler.set_config(filename=fname, profile_symbolic=True)
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc_attr")
    exe = fc.simple_bind(ctx=mx.cpu(), data=(2, 8), grad_req="null")
    profiler.set_state("run")
    exe.forward(is_train=False, data=nd.ones((2, 8)))
    exe.outputs[0].asnumpy()
    profiler.set_config(sync=True)
    exe.forward(is_train=False, data=nd.ones((2, 8)))
    profiler.set_config(sync=False)
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    spans = [e for e in events
             if e["name"].startswith("Executor.forward")]
    assert len(spans) == 2
    assert spans[0]["args"]["device_time"] is False
    assert spans[1]["args"]["device_time"] is True


def test_dumps_survives_marker_events():
    """Instant ('i') marker events have no duration — the aggregate table
    must skip them, not crash (review regression)."""
    from incubator_mxnet_tpu import profiler
    profiler.set_config(aggregate_stats=True)
    profiler.set_state("run")
    d = profiler.Domain("test")
    d.new_marker("hello").mark()
    table = profiler.dumps()
    profiler.set_state("stop")
    assert "Name" in table
