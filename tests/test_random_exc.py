"""Random sampler statistics + exception propagation.

Parity models: tests/python/unittest/test_random.py (statistical
moments), test_exc_handling.py (async errors surface at sync points).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_uniform_moments():
    mx.random.seed(0)
    x = nd.random.uniform(low=-2.0, high=4.0, shape=(200000,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.02
    assert abs(x.var() - 36.0 / 12) < 0.05
    assert x.min() >= -2.0 and x.max() < 4.0


def test_normal_moments():
    mx.random.seed(1)
    x = nd.random.normal(loc=2.0, scale=3.0, shape=(200000,)).asnumpy()
    assert abs(x.mean() - 2.0) < 0.03
    assert abs(x.std() - 3.0) < 0.03


def test_gamma_poisson_moments():
    mx.random.seed(2)
    g = nd.random.gamma(alpha=4.0, beta=2.0, shape=(100000,)).asnumpy()
    assert abs(g.mean() - 8.0) < 0.1          # mean = alpha * beta
    p = nd.random.poisson(lam=3.5, shape=(100000,)).asnumpy()
    assert abs(p.mean() - 3.5) < 0.05
    assert abs(p.var() - 3.5) < 0.15


def test_seed_reproducibility():
    mx.random.seed(42)
    a = nd.random.normal(shape=(16,)).asnumpy()
    mx.random.seed(42)
    b = nd.random.normal(shape=(16,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = nd.random.normal(shape=(16,)).asnumpy()
    assert not np.array_equal(b, c)


def test_multinomial_distribution():
    mx.random.seed(3)
    probs = nd.array(np.array([[0.1, 0.2, 0.7]], np.float32))
    draws = nd.sample_multinomial(probs, shape=(20000,)).asnumpy().ravel()
    freq = np.bincount(draws.astype(int), minlength=3) / draws.size
    np.testing.assert_allclose(freq, [0.1, 0.2, 0.7], atol=0.02)


# ---------------------------------------------------------------------------
# exception propagation (async dispatch must still surface errors)
# ---------------------------------------------------------------------------

def test_shape_error_raises():
    with pytest.raises(Exception):
        nd.dot(nd.ones((2, 3)), nd.ones((4, 5))).asnumpy()


def test_invalid_reshape_raises():
    with pytest.raises(ValueError):
        nd.ones((2, 3)).reshape((7,))


def test_unknown_op_param_raises():
    with pytest.raises(Exception):
        nd.Activation(nd.ones((2, 2)), act_type="not_an_act").asnumpy()


def test_error_after_async_chain():
    """Errors raised mid-chain surface when the result is consumed, and
    the runtime stays usable afterwards (threaded_engine.h exception
    rethrow contract)."""
    a = nd.ones((4, 4))
    b = nd.dot(a, a)                 # fine
    with pytest.raises(Exception):
        nd.dot(b, nd.ones((5, 5))).asnumpy()
    # runtime still healthy
    assert float(nd.sum(b).asscalar()) == 64.0
