"""graftscope tests: segment-aware tracing + unified metrics registry.

Covers the PR-3 acceptance surface: flow events link each deferred op to
exactly one segment flush, sync-mode vs deferred-mode traces agree on op
counts, the metrics snapshot round-trips through the Prometheus text
format, and every instrumented subsystem (engine, kvstore, io, autograd,
monitor, training loop) reports through the registry.
"""
import json

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, gluon, io, profiler
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.telemetry import metrics as tmetrics
from incubator_mxnet_tpu.telemetry import tracing as ttracing


def _traced(fn, tmp_path, name="trace.json"):
    """Run fn under the profiler, return the dumped trace events."""
    fname = str(tmp_path / name)
    profiler.set_config(filename=fname, profile_all=True)
    profiler.set_state("run")
    try:
        fn()
    finally:
        profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        return json.load(f)["traceEvents"]


def _chain(a):
    b = a * a
    c = b + a
    d = c - a
    return d


# ---------------------------------------------------------------------------
# tracing: flow links, schema, attribution
# ---------------------------------------------------------------------------

def test_flow_links_each_deferred_op_to_one_flush(tmp_path):
    a = mx.nd.ones((8, 8))

    def run():
        with engine.bulk(16):
            _chain(a).asnumpy()
        with engine.bulk(16):
            _chain(a).asnumpy()

    events = _traced(run, tmp_path)
    starts = [e for e in events if e.get("ph") == "s"]
    finishes = [e for e in events if e.get("ph") == "f"]
    deferred = [e for e in events
                if e.get("args", {}).get("deferred") is True]
    assert len(deferred) == 6          # 3 ops per scope, two scopes
    assert len(starts) == 6 and len(finishes) == 6
    # exactly one finish per start, ids match 1:1
    assert sorted(e["id"] for e in starts) \
        == sorted(e["id"] for e in finishes)
    assert len({e["id"] for e in starts}) == 6
    # each finish names the segment of exactly one flush span
    seg_spans = {e["args"]["segment"]: e for e in events
                 if e.get("name") == ttracing.SEGMENT_SPAN}
    assert len(seg_spans) == 2
    for f in finishes:
        assert f["args"]["segment"] in seg_spans
    # each deferred record points at its owning segment
    for e in deferred:
        assert e["args"]["segment"] in seg_spans
    # schema-level validation agrees
    assert ttracing.validate_chrome_trace({"traceEvents": events}) == []


def test_segment_span_carries_attribution(tmp_path):
    a = mx.nd.ones((4, 4))

    def run():
        with engine.bulk(16):
            _chain(a).asnumpy()

    events = _traced(run, tmp_path)
    spans = [e for e in events if e.get("name") == ttracing.SEGMENT_SPAN]
    assert len(spans) == 1
    args = spans[0]["args"]
    assert args["cause"] == "read"
    assert args["nodes"] == 3
    assert args["cache"] in ("hit", "miss")
    assert args["recorded"] is False
    assert spans[0]["dur"] >= 0
    # deferred records must NOT present dispatch time as op duration:
    # their events are explicitly marked
    for e in events:
        if e.get("cat") == "operator" and e.get("ph") == "X":
            assert e["args"]["deferred"] is True


def test_sync_and_deferred_traces_agree_on_op_counts(tmp_path):
    a = mx.nd.ones((8, 8))
    _chain(a).asnumpy()     # warm caches outside any trace

    def eager():
        _chain(a).asnumpy()

    def bulked():
        with engine.bulk(16):
            _chain(a).asnumpy()

    eager_events = _traced(eager, tmp_path, "eager.json")
    bulk_events = _traced(bulked, tmp_path, "bulk.json")
    eager_ops = sorted(e["name"] for e in eager_events
                       if e.get("cat") == "operator" and e["ph"] == "X")
    bulk_ops = sorted(e["name"] for e in bulk_events
                      if e.get("cat") == "operator" and e["ph"] == "X")
    assert eager_ops == bulk_ops
    # and the eager ones are NOT marked deferred
    for e in eager_events:
        if e.get("cat") == "operator":
            args = e.get("args") or {}
            assert args.get("deferred") is not True
            assert "segment" not in args


def test_profiler_stopped_mid_segment_leaves_no_dangling_flow(tmp_path):
    """Flow starts emitted at record time must be closed at flush even
    if the profiler was deactivated in between (review fix)."""
    a = mx.nd.ones((4, 4))
    fname = str(tmp_path / "midstop.json")
    profiler.dumps(reset=True)
    profiler.set_config(filename=fname, profile_all=True)
    profiler.set_state("run")
    with engine.bulk(16):
        b = a * a
        profiler.set_state("stop")      # mid-segment
        c = b + a                       # recorded, but not traced
        c.asnumpy()                     # flush with profiler off
    profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    assert ttracing.validate_chrome_trace(trace) == []
    starts = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
    finishes = [e for e in trace["traceEvents"] if e.get("ph") == "f"]
    assert len(starts) == 1
    assert sorted(e["id"] for e in starts) \
        == sorted(e["id"] for e in finishes)


def test_monitor_computes_concrete_stats_eagerly():
    """Outside a bulk scope nothing is deferred: stat_helper must reduce
    immediately instead of pinning the tensor until toc() (review fix)."""
    from incubator_mxnet_tpu.monitor import Monitor
    mon = Monitor(interval=1)
    mon.tic()
    arr = mx.nd.ones((4, 4))
    arr.asnumpy()                       # concrete
    mon.stat_helper("x_output0", arr)
    (_step, _name, payload, lazy), = mon.queue
    assert lazy is False
    assert not hasattr(payload, "asnumpy") or payload.size == 1
    entries = mon.toc()
    assert len(entries) == 1 and float(entries[0][2]) == 1.0


def test_prometheus_label_backslash_n_roundtrip():
    reg = telemetry.MetricsRegistry()
    tricky = "a\\nb"          # literal backslash + 'n', NOT a newline
    reg.counter("esc_total", "t", ("p",)).inc(3, p=tricky)
    parsed = telemetry.parse_prometheus_text(reg.prometheus_text())
    assert parsed["esc_total"][frozenset({"p": tricky}.items())] == 3


def test_sync_mode_flush_span_reports_device_time(tmp_path):
    a = mx.nd.ones((8, 8))
    fname = str(tmp_path / "sync.json")
    profiler.set_config(filename=fname, profile_all=True, sync=True)
    profiler.set_state("run")
    try:
        with engine.bulk(16):
            _chain(a).asnumpy()
    finally:
        profiler.set_state("stop")
        profiler.set_config(sync=False)
    profiler.dump()
    with open(fname) as f:
        events = json.load(f)["traceEvents"]
    spans = [e for e in events if e.get("name") == ttracing.SEGMENT_SPAN]
    assert spans and all(e["args"]["device_time"] is True for e in spans)


# ---------------------------------------------------------------------------
# metrics registry: semantics + expositions
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_basics():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("test_total", "a counter", ("kind",))
    c.inc(kind="x")
    c.inc(2, kind="x")
    c.inc(kind="y")
    assert c.value(kind="x") == 3 and c.value(kind="y") == 1
    with pytest.raises(ValueError):
        c.inc(-1, kind="x")
    g = reg.gauge("test_gauge", "a gauge")
    g.set(5)
    g.dec(2)
    assert g.value() == 3
    h = reg.histogram("test_seconds", "a histogram", buckets=(1, 10))
    h.observe(0.5)
    h.observe(5)
    h.observe(50)
    (_, payload), = h.samples()
    assert payload["count"] == 3 and payload["sum"] == 55.5
    assert payload["buckets"] == {"1": 1, "10": 2}
    # same name, different kind → rejected
    with pytest.raises(ValueError):
        reg.gauge("test_total")


def test_metrics_snapshot_roundtrips_prometheus_text():
    reg = telemetry.MetricsRegistry()
    reg.counter("rt_total", "ops", ("op", "ctx")).inc(
        7, op='dot "big"', ctx="cpu(0)")
    reg.gauge("rt_bytes", "bytes").set(12.5)
    h = reg.histogram("rt_lat", "latency", ("phase",), buckets=(0.1, 1))
    h.observe(0.05, phase="fwd")
    h.observe(2.0, phase="fwd")
    text = reg.prometheus_text()
    parsed = telemetry.parse_prometheus_text(text)
    assert parsed["rt_total"][
        frozenset({"op": 'dot "big"', "ctx": "cpu(0)"}.items())] == 7
    assert parsed["rt_bytes"][frozenset()] == 12.5
    b = parsed["rt_lat_bucket"]
    assert b[frozenset({"phase": "fwd", "le": "0.1"}.items())] == 1
    assert b[frozenset({"phase": "fwd", "le": "1"}.items())] == 1
    assert b[frozenset({"phase": "fwd", "le": "+Inf"}.items())] == 2
    assert parsed["rt_lat_sum"][frozenset({"phase": "fwd"}.items())] \
        == pytest.approx(2.05)
    assert parsed["rt_lat_count"][frozenset({"phase": "fwd"}.items())] == 2
    # the snapshot agrees with the wire values
    snap = reg.snapshot()
    assert snap["rt_total"]["samples"][0]["value"] == 7
    assert snap["rt_lat"]["samples"][0]["value"]["count"] == 2


def test_registry_absorbs_engine_flush_stats():
    engine.reset_flush_stats()
    a = mx.nd.ones((4, 4))
    with engine.bulk(16):
        (a + a).asnumpy()
    with engine.bulk(2):
        b = a + a
        c = b + a
        d = c + a          # size-cap flush
        d.asnumpy()
    stats = engine.flush_stats()
    snap = telemetry.registry().snapshot()
    mirrored = {s["labels"]["cause"]: s["value"]
                for s in snap["graft_engine_flushes_total"]["samples"]}
    for cause, n in stats["causes"].items():
        assert mirrored[cause] == n
    assert mirrored["read"] >= 1 and mirrored["size-cap"] >= 1
    # reset keeps both views in step
    engine.reset_flush_stats()
    snap = telemetry.registry().snapshot()
    assert all(s["value"] == 0
               for s in snap["graft_engine_flushes_total"]["samples"])


def test_telemetry_disable_switch():
    reg = telemetry.registry()
    c = reg.counter("switch_total", "t")
    before = c.value()
    telemetry.set_enabled(False)
    try:
        c.inc(5)
        assert c.value() == before
    finally:
        telemetry.set_enabled(None)
    c.inc(5)
    assert c.value() == before + 5


# ---------------------------------------------------------------------------
# subsystem instrumentation
# ---------------------------------------------------------------------------

def test_kvstore_push_pull_bytes_and_compression():
    reg = telemetry.registry()
    kv = mx.kv.create("local")
    shape = (64, 64)
    kv.init("w", mx.nd.ones(shape))
    push0 = reg.counter("graft_kvstore_push_bytes_total").value()
    wire0 = reg.counter("graft_kvstore_wire_bytes_total").value()
    pull0 = reg.counter("graft_kvstore_pull_bytes_total").value()
    nb = 64 * 64 * 4
    kv.push("w", mx.nd.ones(shape))
    out = mx.nd.zeros(shape)
    kv.pull("w", out=out)
    assert reg.counter("graft_kvstore_push_bytes_total").value() \
        - push0 == nb
    assert reg.counter("graft_kvstore_wire_bytes_total").value() \
        - wire0 == nb
    assert reg.counter("graft_kvstore_pull_bytes_total").value() \
        - pull0 == nb
    # 2-bit compression: 16 elements per float32 word on the wire
    kv2 = mx.kv.create("local")
    kv2.init("g", mx.nd.zeros(shape))
    kv2.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    p0 = reg.counter("graft_kvstore_push_bytes_total").value()
    w0 = reg.counter("graft_kvstore_wire_bytes_total").value()
    kv2.push("g", mx.nd.ones(shape))
    assert reg.counter("graft_kvstore_push_bytes_total").value() - p0 == nb
    assert reg.counter("graft_kvstore_wire_bytes_total").value() - w0 \
        == nb // 16
    # the gauge is CUMULATIVE raw/wire over the process (earlier tests
    # may have paid graftzero's whole-block scale overhead on tiny
    # buckets, which legitimately bills wire > raw) — assert its
    # contract, not a history-dependent threshold
    ratio = reg.gauge("graft_kvstore_compression_ratio").value()
    pushed = reg.counter("graft_kvstore_push_bytes_total").value()
    wire = reg.counter("graft_kvstore_wire_bytes_total").value()
    assert ratio == pytest.approx(pushed / wire)
    assert pushed - p0 > (wire - w0) * 10  # this push itself compressed


def test_io_batches_metrics():
    reg = telemetry.registry()
    data = np.random.rand(12, 3).astype(np.float32)
    it = io.NDArrayIter(data=data, batch_size=4)
    c = reg.counter("graft_io_batches_total", labelnames=("iter",))
    before = c.value(iter="NDArrayIter")
    n = sum(1 for _ in it)
    assert n == 3
    assert c.value(iter="NDArrayIter") - before == 3


def test_autograd_tape_metrics():
    reg = telemetry.registry()
    h = reg.histogram("graft_autograd_tape_size")
    samples = h.samples()
    count0 = samples[0][1]["count"] if samples else 0
    x = mx.nd.ones((4,))
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    (_, payload), = h.samples()
    assert payload["count"] == count0 + 1
    assert payload["sum"] >= 2          # at least the two recorded ops


def test_monitor_batches_stats_behind_one_flush():
    """stat_helper must queue lazily; toc() materializes everything with
    ONE engine flush tagged cause="monitor" (not per-array user reads)."""
    from incubator_mxnet_tpu.monitor import Monitor
    engine.reset_flush_stats()
    mon = Monitor(interval=1)
    mon.tic()
    a = mx.nd.ones((4, 4))
    with engine.bulk(32):
        outs = []
        x = a
        for i in range(4):
            x = x + a
            outs.append(x)
            mon.stat_helper("layer%d_output0" % i, x)
        entries = mon.toc()
    assert len(entries) == 4
    for _step, _name, text in entries:
        assert float(text) > 0
    stats = engine.flush_stats()
    assert stats["causes"]["monitor"] == 1
    assert stats["causes"]["read"] == 0 and stats["causes"]["view"] == 0


def test_trainer_step_emits_phase_spans(tmp_path):
    net = gluon.nn.Dense(4)
    net.initialize()
    x = mx.nd.ones((2, 8))
    net(x).asnumpy()
    kv = mx.kv.create("local")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)

    def run():
        with autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(batch_size=2)

    events = _traced(run, tmp_path)
    phases = {e["name"] for e in events if e.get("cat") == "phase"}
    assert {"bwd", "kvstore", "update"} <= phases
    # and the histogram observed them
    h = telemetry.registry()._metrics["graft_phase_seconds"]
    observed = {labels["phase"] for labels, _ in h.samples()}
    assert {"bwd", "kvstore", "update"} <= observed


def test_module_forward_backward_phase_spans(tmp_path):
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    sym = mx.sym.SoftmaxOutput(fc, mx.sym.var("softmax_label"),
                               name="softmax")
    X = np.random.rand(8, 6).astype(np.float32)
    y = np.zeros((8,), np.float32)
    it = io.NDArrayIter(X, y, batch_size=8)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    batch = next(iter(it))

    def run():
        mod.forward_backward(batch)
        mod.update()

    events = _traced(run, tmp_path)
    phases = {e["name"] for e in events if e.get("cat") == "phase"}
    assert {"fwd", "bwd", "update"} <= phases


# ---------------------------------------------------------------------------
# snapshot completeness + CLI
# ---------------------------------------------------------------------------

def test_snapshot_includes_device_memory_gauges():
    keep = mx.nd.zeros((256, 256))
    keep.asnumpy()
    snap = telemetry.registry().snapshot()
    mems = snap["graft_device_memory_bytes"]["samples"]
    kinds = {s["labels"]["kind"] for s in mems}
    assert {"in_use", "peak", "limit"} <= kinds
    in_use = [s["value"] for s in mems
              if s["labels"]["kind"] == "in_use"]
    assert any(v > 0 for v in in_use)
    del keep


def test_cli_selftest_passes():
    from incubator_mxnet_tpu.telemetry.__main__ import selftest
    assert selftest() == []


def test_cli_summary_json(capsys):
    """The acceptance path: one bulked gluon-Trainer step traced +
    summarized with flush causes, kvstore bytes and device memory."""
    from incubator_mxnet_tpu.telemetry.__main__ import main
    assert main(["--summary", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["segments_total"] >= 1
    assert report["top_segments"]
    assert any(v > 0 for v in report["flush_causes"].values())
    assert report["kvstore_bytes"]["push_bytes"] > 0
    assert report["device_memory"]
    assert "graft_engine_flushes_total" in report["metrics"]


# ---------------------------------------------------------------------------
# graftwatch satellites: exception-safe spans + registry thread safety
# ---------------------------------------------------------------------------

def test_phase_span_closes_on_exception(tmp_path):
    """A body that raises mid-phase must still land a (marked) phase
    event and its latency observation — crash traces stay well-formed."""
    before = telemetry.compact_snapshot().get(
        'graft_phase_seconds_count{phase="fwd"}', 0)

    def run():
        with pytest.raises(ValueError):
            with ttracing.phase_span("fwd"):
                raise ValueError("mid-phase crash")

    events = _traced(run, tmp_path)
    spans = [e for e in events
             if e.get("cat") == "phase" and e["name"] == "fwd"]
    assert spans and spans[-1]["args"]["error"] is True
    assert ttracing.validate_chrome_trace({"traceEvents": events}) == []
    after = telemetry.compact_snapshot().get(
        'graft_phase_seconds_count{phase="fwd"}', 0)
    assert after == before + 1


def test_op_span_closes_on_exception(tmp_path, monkeypatch):
    """An eager op that raises at dispatch must still close its span
    (previously the manual __enter__/__exit__ pair leaked the event)."""
    from incubator_mxnet_tpu.ops.registry import get_op
    op = get_op("abs")

    def bad_bind(params, is_train):
        raise RuntimeError("bind exploded")

    monkeypatch.setattr(op, "bind", bad_bind)
    a = mx.nd.ones((4, 4))

    def run():
        with pytest.raises(RuntimeError):
            a.abs()

    events = _traced(run, tmp_path)
    spans = [e for e in events
             if e.get("name") == "abs" and e.get("ph") == "X"]
    assert spans and spans[-1]["args"]["error"] is True


def test_segment_flush_span_closes_flows_on_error(tmp_path, monkeypatch):
    """A replay that raises mid-flush must still emit the segment span
    and finish every flow link — no dangling arrows in a crash trace."""
    def bad_build(instrs, live):
        def boom(ext):
            raise ValueError("replay exploded")
        return boom

    monkeypatch.setattr(engine, "_build_replay", bad_build)
    a = mx.nd.array(np.ones((7, 5), np.float32))   # unique: cache miss

    def run():
        with pytest.raises(ValueError):
            with engine.bulk(8):
                ((a * a) + a).asnumpy()

    events = _traced(run, tmp_path)
    assert ttracing.validate_chrome_trace({"traceEvents": events}) == []
    spans = [e for e in events if e.get("name") == ttracing.SEGMENT_SPAN]
    assert spans and spans[-1]["args"]["error"] is True
    starts = [e for e in events if e.get("ph") == "s"]
    finishes = [e for e in events if e.get("ph") == "f"]
    assert len(starts) == 2 and len(finishes) == 2


def test_metrics_mutation_vs_snapshot_thread_safety():
    """The watchdog snapshots from a background thread while training
    threads mutate: exports must be internally consistent (a histogram's
    bucket counts, count and sum from ONE moment) and no increment may
    be lost."""
    import threading

    reg = tmetrics.MetricsRegistry()
    c = reg.counter("hammer_total", "x")
    h = reg.histogram("hammer_hist", "x", buckets=(0.5, 1.5))
    g = reg.gauge("hammer_gauge", "x", labelnames=("t",))
    n_threads, n_iters = 8, 3000
    errs = []

    def worker(tid):
        try:
            for i in range(n_iters):
                c.inc()
                h.observe(1.0)
                g.set(i, t=str(tid))
        except Exception as exc:       # pragma: no cover - the failure
            errs.append(exc)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    # hammer snapshots concurrently: every exported histogram payload
    # must satisfy the per-sample invariants (1.0 lands in the 1.5
    # bucket, sum == count exactly for unit observations)
    while any(t.is_alive() for t in threads):
        for _labels, payload in h.samples():
            assert payload["buckets"]["1.5"] == payload["count"]
            assert payload["sum"] == pytest.approx(payload["count"] * 1.0)
        reg.snapshot(collect=False)
        reg.prometheus_text(collect=False)
    for t in threads:
        t.join()
    assert not errs
    assert c.value() == n_threads * n_iters
    (_labels, payload), = h.samples()
    assert payload["count"] == n_threads * n_iters
    assert payload["sum"] == pytest.approx(n_threads * n_iters * 1.0)
