"""gluon.contrib layers and cells.

Parity model: tests/python/unittest/test_gluon_contrib.py (Concurrent,
HybridConcurrent, Identity, VariationalDropoutCell, LSTMPCell).
"""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd


def test_concurrent_and_identity():
    x = nd.array(np.random.RandomState(0).randn(2, 3).astype(np.float32))
    for cls in (gluon.contrib.nn.Concurrent,
                gluon.contrib.nn.HybridConcurrent):
        net = cls(axis=1)
        net.add(gluon.nn.Dense(4))
        net.add(gluon.contrib.nn.Identity())
        net.initialize(mx.init.Xavier())
        out = net(x)
        assert out.shape == (2, 7)
        # identity branch passes the input through untouched
        np.testing.assert_allclose(out.asnumpy()[:, 4:], x.asnumpy(),
                                   rtol=1e-6)


def test_lstmp_cell():
    cell = gluon.contrib.rnn.LSTMPCell(hidden_size=8, projection_size=3)
    cell.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(1).randn(2, 4, 5).astype(np.float32))
    outs, states = cell.unroll(4, x, merge_outputs=True)
    assert outs.shape == (2, 4, 3)            # projected outputs
    assert states[0].shape == (2, 3)          # projected h
    assert states[1].shape == (2, 8)          # full cell state


def test_variational_dropout_shares_mask_across_steps():
    base = gluon.rnn.RNNCell(6)
    vd = gluon.contrib.rnn.VariationalDropoutCell(base, drop_outputs=0.5)
    vd.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(2).randn(2, 3, 4).astype(np.float32))
    with autograd.record(train_mode=True):
        outs, _ = vd.unroll(3, x, merge_outputs=False)
    masks = [(o.asnumpy() == 0) for o in outs]
    assert masks[0].sum() > 0                 # dropout active
    assert all((m == masks[0]).all() for m in masks[1:])   # same mask
    # a fresh unroll resets the mask object (new mask drawn per sequence)
    first_mask_obj = vd.drop_outputs_mask
    with autograd.record(train_mode=True):
        vd.unroll(3, x, merge_outputs=False)
    assert vd.drop_outputs_mask is not first_mask_obj
    # inference mode: dropout inactive → no exact zeros from masking
    outs3, _ = vd.unroll(3, x, merge_outputs=False)
    assert (outs3[0].asnumpy() == 0).sum() == 0


def test_multihead_attention_fused_qkv_matches_unfused():
    """fused_qkv=True (one (E,3E) projection) must compute the same
    attention as three separate projections with the same weights."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon import nn

    E, H, B, S = 16, 4, 2, 8
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.randn(B, S, E).astype(np.float32))

    mx.random.seed(0)
    fused = nn.MultiHeadAttention(E, H, causal=True, use_bias=False,
                                  fused_qkv=True)
    fused.initialize(mx.init.Xavier())
    fused(x)  # shapes

    unfused = nn.MultiHeadAttention(E, H, causal=True, use_bias=False)
    unfused.initialize(mx.init.Xavier())
    unfused(x)

    w = fused.proj_qkv.weight.data().asnumpy()      # (3E, E)
    unfused.proj_q.weight.set_data(mx.nd.array(w[:E]))
    unfused.proj_k.weight.set_data(mx.nd.array(w[E:2 * E]))
    unfused.proj_v.weight.set_data(mx.nd.array(w[2 * E:]))
    unfused.proj_out.weight.set_data(fused.proj_out.weight.data())

    np.testing.assert_allclose(fused(x).asnumpy(), unfused(x).asnumpy(),
                               rtol=1e-5, atol=1e-6)

    import pytest
    with pytest.raises(ValueError, match="self-attention"):
        fused(x, x)
