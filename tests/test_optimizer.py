"""Optimizer correctness vs numpy reference updaters.

Parity model: tests/python/unittest/test_optimizer.py compares each fused
update op against a pure-python reference updater.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _setup(shape=(4, 7), seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(*shape).astype(np.float32)
    g = rs.randn(*shape).astype(np.float32)
    return w, g


def _run_updates(opt, w_np, g_np, n=3):
    w = mx.nd.array(w_np)
    updater = mx.optimizer.get_updater(opt)
    for _ in range(n):
        updater(0, mx.nd.array(g_np), w)
    return w.asnumpy()


def test_sgd_matches_numpy():
    w_np, g_np = _setup()
    lr, wd = 0.1, 0.01
    got = _run_updates(mx.optimizer.SGD(learning_rate=lr, wd=wd), w_np, g_np)
    ref = w_np.copy()
    for _ in range(3):
        ref = ref - lr * (g_np + wd * ref)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_sgd_momentum_matches_numpy():
    w_np, g_np = _setup()
    lr, mom, wd = 0.1, 0.9, 0.0
    got = _run_updates(mx.optimizer.SGD(learning_rate=lr, momentum=mom, wd=wd),
                       w_np, g_np)
    ref, m = w_np.copy(), np.zeros_like(w_np)
    for _ in range(3):
        m = mom * m - lr * g_np
        ref = ref + m
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_adam_matches_numpy():
    w_np, g_np = _setup()
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    got = _run_updates(mx.optimizer.Adam(learning_rate=lr, beta1=b1, beta2=b2,
                                         epsilon=eps), w_np, g_np)
    ref = w_np.copy()
    mean = np.zeros_like(w_np)
    var = np.zeros_like(w_np)
    for t in range(1, 4):
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        mean = b1 * mean + (1 - b1) * g_np
        var = b2 * var + (1 - b2) * g_np ** 2
        ref = ref - lr_t * mean / (np.sqrt(var) + eps)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_rmsprop_runs_and_descends():
    w_np = np.array([[2.0, -3.0]], dtype=np.float32)
    w = mx.nd.array(w_np)
    opt = mx.optimizer.RMSProp(learning_rate=0.1)
    updater = mx.optimizer.get_updater(opt)
    # gradient of 0.5*w^2 is w: repeated updates shrink |w|
    for _ in range(20):
        updater(0, w.copy(), w)
    assert np.abs(w.asnumpy()).sum() < np.abs(w_np).sum()


@pytest.mark.parametrize("name", ["sgd", "adam", "nag", "rmsprop", "adagrad",
                                  "adadelta", "ftrl", "ftml", "signum", "sgld",
                                  "dcasgd", "lbsgd", "test"])
def test_create_registry_and_update(name):
    opt = mx.optimizer.create(name)
    w = mx.nd.array(np.ones((3,), np.float32))
    g = mx.nd.array(np.full((3,), 0.5, np.float32))
    updater = mx.optimizer.get_updater(opt)
    updater(0, g, w)
    assert w.shape == (3,)
    assert np.all(np.isfinite(w.asnumpy()))


def test_multi_precision_sgd():
    w = mx.nd.array(np.ones((4,), np.float32)).astype("bfloat16")
    g = mx.nd.array(np.full((4,), 0.25, np.float32)).astype("bfloat16")
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    updater = mx.optimizer.get_updater(opt)
    for _ in range(2):
        updater(0, g, w)
    assert w.dtype == np.dtype("bfloat16")
    # m1 = -0.025; w1 = 0.975 ; m2 = 0.9*m1 - 0.025 = -0.0475; w2 = 0.9275
    np.testing.assert_allclose(w.astype("float32").asnumpy(),
                               np.full((4,), 0.9275), rtol=2e-2)


def test_updater_states_roundtrip():
    opt = mx.optimizer.Adam()
    updater = mx.optimizer.get_updater(opt)
    w = mx.nd.array(np.ones((3,), np.float32))
    updater(0, mx.nd.array(np.ones((3,), np.float32)), w)
    blob = updater.get_states(dump_optimizer=True)
    u2 = mx.optimizer.get_updater(mx.optimizer.Adam())
    u2.set_states(blob)
    assert 0 in u2.states


def test_lr_schedulers():
    s = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(1) == 1.0
    assert s(11) == 0.5
    m = mx.lr_scheduler.MultiFactorScheduler(step=[5, 10], factor=0.1)
    m.base_lr = 1.0
    assert m(1) == 1.0
    assert abs(m(6) - 0.1) < 1e-12
    assert abs(m(11) - 0.01) < 1e-12
    p = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0, pwr=2)
    assert p(0) == 1.0
    assert p(100) == 0.0


def test_lr_wd_mult():
    opt = mx.optimizer.SGD(learning_rate=1.0, param_idx2name={0: "fc_weight",
                                                              1: "fc_bias"})
    opt.set_lr_mult({"fc_weight": 0.5})
    opt.set_wd_mult({})
    assert opt._get_lr(0) == 0.5
    assert opt._get_lr(1) == 1.0
    # bias gets wd_mult 0 by the reference heuristic
    assert opt._get_wd(1) == 0.0


def test_updater_update_after_state_load():
    """States arrive as numpy after set_states — the next update must still
    run (round-1 advisor: set_states never rehydrated NDArrays)."""
    opt = mx.optimizer.Adam(learning_rate=0.1)
    updater = mx.optimizer.get_updater(opt)
    w = mx.nd.array(np.ones((3,), np.float32))
    g = mx.nd.array(np.full((3,), 0.5, np.float32))
    updater(0, g, w)
    blob = updater.get_states(dump_optimizer=True)

    w2 = mx.nd.array(w.asnumpy())
    u2 = mx.optimizer.get_updater(mx.optimizer.Adam())
    u2.set_states(blob)
    u2(0, g, w2)  # must not crash on numpy states
    updater(0, g, w)
    np.testing.assert_allclose(w2.asnumpy(), w.asnumpy(), rtol=1e-6)
