"""Model-parallel sharding annotations + expert parallelism + dist batch.

Parity models: tests/python/unittest/test_model_parallel.py (cross-device
graphs on CPU contexts), SURVEY §2.4 ctx_group → GSPMD mapping, plus the
new-capability EP row.
"""
import numpy as np

import jax

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.parallel import (DataParallelTrainer,
                                          ExpertParallelMoE, make_mesh)


def _toy():
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = (rng.rand(16) * 3).astype(np.float32)
    return x, y


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    return net


def test_tp_sharded_training_matches_replicated():
    """Weight sharded over 'tp' (the ctx_group→GSPMD surface) trains to
    the exact same losses as fully-replicated training."""
    x, y = _toy()
    results = {}
    for mode in ("replicated", "tp"):
        mx.random.seed(3)
        net = _mlp()
        mesh = make_mesh({"dp": 2, "tp": 4}, jax.devices()[:8])
        if mode == "tp":
            for name, p in net.collect_params().items():
                if p.shape and p.shape[0] % 4 == 0:
                    p.sharding = ("tp",) + (None,) * (len(p.shape) - 1)
        tr = DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, mesh=mesh)
        for _ in range(5):
            loss = tr.step(nd.array(x), nd.array(y))
        results[mode] = float(np.asarray(loss))
    assert abs(results["replicated"] - results["tp"]) < 1e-4


def test_tp_param_placement():
    mx.random.seed(0)
    net = _mlp()
    mesh = make_mesh({"dp": 2, "tp": 4}, jax.devices()[:8])
    for name, p in net.collect_params().items():
        if p.shape and len(p.shape) == 2 and p.shape[0] == 32:
            p.sharding = ("tp", None)
    x, y = _toy()
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mesh=mesh)
    tr.step(nd.array(x), nd.array(y))
    sharded = [n for n, v in tr._params.items()
               if not v.sharding.is_fully_replicated]
    assert sharded, "no parameter ended up sharded"


def test_moe_eager_and_topk():
    mx.random.seed(1)
    rng = np.random.RandomState(2)
    moe = ExpertParallelMoE(hidden_size=16, num_experts=8, top_k=2)
    moe.initialize(mx.init.Xavier())
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    out = moe(x)
    assert out.shape == (4, 8)
    assert moe.expert_w1.sharding == ("ep", None, None)
    assert moe.gate_weight.shape == (8, 8)
    # top_k == num_experts degenerates to dense soft mixture
    moe2 = ExpertParallelMoE(hidden_size=16, num_experts=4, top_k=4,
                             prefix="moe2_")
    moe2.initialize(mx.init.Xavier())
    assert moe2(x).shape == (4, 8)


def test_moe_expert_parallel_training():
    x, y = _toy()
    mx.random.seed(1)
    mesh = make_mesh({"dp": 2, "ep": 4}, jax.devices()[:8])
    net = gluon.nn.HybridSequential()
    net.add(ExpertParallelMoE(hidden_size=16, num_experts=4, top_k=1,
                              ep_axis="ep"))
    net.add(gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    _ = net(nd.array(x))    # resolve deferred shapes
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             optimizer="adam",
                             optimizer_params={"learning_rate": 0.01},
                             mesh=mesh)
    first = float(np.asarray(tr.step(nd.array(x), nd.array(y))))
    for _ in range(30):
        last = tr.step(nd.array(x), nd.array(y))
    assert float(np.asarray(last)) < first
    # expert weights actually sharded over ep
    w1 = tr._params[net[0].expert_w1.name]
    assert not w1.sharding.is_fully_replicated


def test_kvstore_batched_push_unchanged_semantics():
    kv = mx.kv.create("local")
    kv.init(["a", "b"], [nd.zeros((2, 2)), nd.zeros(3)])
    kv.push(["a", "b"], [nd.ones((2, 2)) * 2, nd.ones(3)])
    oa, ob = nd.zeros((2, 2)), nd.zeros(3)
    kv.pull(["a", "b"], out=[oa, ob])
    assert (oa.asnumpy() == 2).all() and (ob.asnumpy() == 1).all()


def test_legacy_json_upgrade():
    """Pre-1.0 graphs store op params under 'param'/'attr'."""
    import json
    net = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4,
                                name="fc")
    graph = json.loads(net.tojson())
    for node in graph["nodes"]:
        if "attrs" in node:
            node["param"] = node.pop("attrs")
    old = mx.sym.load_json(json.dumps(graph))
    out = old.eval_dict({"data": nd.ones((2, 3)),
                         "fc_weight": nd.ones((4, 3)),
                         "fc_bias": nd.zeros(4)})
    assert out.shape == (2, 4)
    assert (out.asnumpy() == 3).all()
