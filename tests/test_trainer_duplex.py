"""graftduplex: the full-duplex step must be BIT-IDENTICAL to the serial
bucketed path.

PR 7 hid the reduce (push) side of the wire under backward; this suite
covers the rest of the duplex contract (PR 9):

* the update_on_kvstore path — previously 100% serial — bucketed
  (``Trainer._duplex_plan`` + ``KVStore.apply_reduced``), its reduces
  overlapped mid-backward and its weight pulls issued per bucket as
  ``PullHandle``s waited at FIRST USE in the next forward
  (``overlap.PullScheduler`` first-touch hooks) — bytes-equality on
  weights AND store-side optimizer states across the optimizer matrix;
* the pull-side safety rails: stale (user-overwritten) weight →
  abandon-and-fallback, ``GRAFT_OVERLAP_PULL=0`` kill switch, the
  watchdog naming a stuck in-flight pull bucket;
* tape-order bucket packing (``GRAFT_BUCKET_ORDER=tape``, the default):
  buckets close EARLIER in backward than index packing on an
  interleaved-use model (issue fire-counts asserted), revertible via
  ``GRAFT_BUCKET_ORDER=index``;
* Module riding the same schedulers: bucketed+overlapped reduce on the
  local-update path (executor grad-ready hooks), first-touch pull
  overlap on update_on_kvstore — both bytes-equal to the per-key wire;
* an 8-virtual-device mesh backward through the overlap machinery
  (multi-ctx grad-ready hooks + committed-device-safe context sums);
* the prefetch-to-device DataLoader satellite (lens ``data_wait``
  shrinks) and the pull-overlap telemetry.
"""
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, module as mod
from incubator_mxnet_tpu.telemetry import blackbox, lens, watchdog
import jax.numpy as jnp


SPECS = [(7,), (3, 5), (11,), (2, 2, 2), (13,), (4,)]


def _make_params(prefix, specs=SPECS, dtype="float32", grad_reqs=None,
                 ctx=None):
    params = []
    for k, shape in enumerate(specs):
        req = grad_reqs[k] if grad_reqs else "write"
        p = gluon.Parameter("%s%d" % (prefix, k), shape=shape, dtype=dtype,
                            grad_req=req)
        p.initialize(ctx=ctx if ctx is not None else mx.cpu())
        params.append(p)
    return params


def _seed(params, weights):
    from incubator_mxnet_tpu import engine
    for p, w in zip(params, weights):
        for d in p.list_data():
            # colocate: jnp.asarray lands on the default device, but a
            # multi-ctx replica must stay committed to ITS device
            d._write(engine.colocate(jnp.asarray(w).astype(d.dtype),
                                     d._read()))


def _backward_loss(params, consts):
    with autograd.record():
        loss = None
        for p, c in zip(params, consts):
            if p.grad_req == "null":
                continue
            y = (p.data() * p.data() * c).sum()
            loss = y if loss is None else loss + y
    loss.backward()


def _build_duplex_trainer(params, optimizer, opt_kw, overlap, pull,
                          bucket_bytes=48):
    t = gluon.Trainer(params, optimizer, dict(opt_kw),
                      kvstore=mx.kv.create("dist_sync"),
                      update_on_kvstore=True)
    t._bucket_bytes_override = bucket_bytes
    t._overlap_override = overlap
    t._overlap_pull_override = pull
    return t


def _store_states(trainer):
    return trainer._kvstore_obj._updater.states


def _assert_store_parity(params_a, params_b, ta, tb):
    for a, b in zip(params_a, params_b):
        wa, wb = a.data().asnumpy(), b.data().asnumpy()
        assert wa.dtype == wb.dtype
        assert wa.tobytes() == wb.tobytes(), \
            "weight %s diverged (max |d|=%g)" % (
                a.name, float(np.max(np.abs(
                    wa.astype(np.float64) - wb.astype(np.float64)))))
    sa, sb = _store_states(ta), _store_states(tb)
    assert set(sa) == set(sb)

    def leaves(s):
        if s is None:
            return []
        if isinstance(s, (tuple, list)):
            out = []
            for x in s:
                out.extend(leaves(x))
            return out
        return [s]
    for i in sa:
        for x, y in zip(leaves(sa[i]), leaves(sb[i])):
            assert x.asnumpy().tobytes() == y.asnumpy().tobytes(), \
                "store state %s diverged" % (i,)


def _duplex_parity_run(optimizer, opt_kw, specs=SPECS, dtype="float32",
                       grad_reqs=None, bucket_bytes=48, steps=5,
                       batch_size=2):
    """serial (bucketed, overlap+pull off) vs full-duplex (both on) on
    the update_on_kvstore wire — plus a per-key reference (bucket plan
    disabled) so all three spellings of the step are bytes-equal."""
    rs = np.random.RandomState(7)
    weights = [rs.randn(*s).astype(np.float32) for s in specs]
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in specs]

    runs = {}
    for name, (bb, ov, pl) in {
            "perkey": (0, False, False),
            "serial": (bucket_bytes, False, False),
            "duplex": (bucket_bytes, True, True)}.items():
        ps = _make_params(name[0], specs, dtype, grad_reqs)
        _seed(ps, weights)
        t = _build_duplex_trainer(ps, optimizer, opt_kw, ov, pl, bb)
        for _ in range(steps):
            _backward_loss(ps, consts)
            t.step(batch_size)
        runs[name] = (ps, t)
    pd, td = runs["duplex"]
    assert td._duplex_plan() is not None, \
        "duplex trainer unexpectedly fell off the bucketed path"
    assert td._scheduler.issued_total > 0, "reduce overlap never engaged"
    assert td._scheduler.taken_total > 0
    assert td._pull_scheduler.issued_total > 0, "pull overlap never engaged"
    assert td._pull_scheduler.touched_total > 0, \
        "no pull was waited at first touch"
    for other in ("perkey", "serial"):
        po, to = runs[other]
        _assert_store_parity(po, pd, to, td)
    return runs


def test_duplex_sgd_parity_with_null_holes():
    _duplex_parity_run("sgd", {"learning_rate": 0.1, "wd": 0.01},
                       grad_reqs=["write", "null", "write", "write",
                                  "null", "write"])


def test_duplex_sgd_momentum_parity():
    _duplex_parity_run("sgd", {"learning_rate": 0.1, "momentum": 0.9,
                               "wd": 0.01})


def test_duplex_adam_parity():
    _duplex_parity_run("adam", {"learning_rate": 0.01}, steps=5)


def test_duplex_mp_bf16_parity():
    _duplex_parity_run("sgd", {"learning_rate": 0.05, "momentum": 0.9,
                               "wd": 0.001, "multi_precision": True},
                       dtype="bfloat16", bucket_bytes=24, steps=6)


def test_duplex_pulls_in_flight_until_first_touch():
    """The core pull-side semantic: after step() returns, the bucket
    pulls are OPEN flight-recorder brackets; the next forward's first
    weight read waits them (touched_total moves), and nothing stays in
    flight once every weight was touched."""
    rs = np.random.RandomState(3)
    params = _make_params("pif")
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = _build_duplex_trainer(params, "sgd", {"learning_rate": 0.1},
                              True, True)
    _backward_loss(params, consts)
    t.step(2)
    assert t._pull_scheduler.inflight_groups > 0, \
        "no pulls in flight after step"
    if blackbox.enabled():
        sites = [e for e in blackbox.inflight_entries()
                 if e["detail"].get("path") == "pull_many_async"]
        assert sites, "in-flight pull carries no recorder bracket"
        assert all("pull[" in str(e["detail"].get("bucket"))
                   for e in sites)
    touched_before = t._pull_scheduler.touched_total
    params[0].data().asnumpy()      # first touch: waits that bucket
    assert t._pull_scheduler.touched_total == touched_before + 1
    for p in params:                # touch the rest
        p.data().asnumpy()
    assert t._pull_scheduler.inflight_groups == 0
    assert not [e for e in blackbox.inflight_entries()
                if e["detail"].get("path") == "pull_many_async"]


def test_view_read_first_touches_base_pull():
    """A view read slices the BASE's buffer, so it must count as the
    base's first use: the pending pull lands before the slice (the
    dist_async path defers its weight writes to wait time — a view read
    that bypassed the hook would return pre-pull bytes)."""
    from incubator_mxnet_tpu.overlap import PullScheduler
    kv = mx.kv.create("local")
    kv.init([0], [mx.nd.array(np.arange(8, dtype=np.float32))])
    out = mx.nd.array(np.zeros(8, np.float32))
    view = out[2:5]
    view.asnumpy()              # materialize the view pre-pull
    sched = PullScheduler()
    sched.issue(kv, [0], [[out]], label="pull[view]")
    assert sched.inflight_groups == 1
    got = view.asnumpy()        # read through the VIEW only
    assert sched.touched_total == 1, "view read did not first-touch"
    assert sched.inflight_groups == 0
    assert np.array_equal(got, np.arange(2, 5, dtype=np.float32))


def test_graft_overlap_pull_env_kill_switch(monkeypatch):
    monkeypatch.setenv("GRAFT_OVERLAP_PULL", "0")
    rs = np.random.RandomState(2)
    params = _make_params("env")
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                      kvstore=mx.kv.create("dist_sync"),
                      update_on_kvstore=True)
    t._bucket_bytes_override = 48
    for _ in range(3):
        _backward_loss(params, consts)
        t.step(2)
    assert t._pull_scheduler.issued_total == 0
    # the reduce side keeps overlapping — the switches are independent
    assert t._scheduler.issued_total > 0


def test_stale_weight_mutation_abandons_and_falls_back():
    """Overwriting a weight while its pull is in flight must keep the
    USER's bytes (the serial pull-then-write ordering) and downgrade the
    next round to the serial pull — while a parallel serial trainer fed
    the same mutations stays bit-identical."""
    rs = np.random.RandomState(9)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    pa = _make_params("sta")
    pb = _make_params("stb")
    _seed(pa, weights)
    _seed(pb, weights)
    ta = _build_duplex_trainer(pa, "sgd", {"learning_rate": 0.1},
                               False, False)
    tb = _build_duplex_trainer(pb, "sgd", {"learning_rate": 0.1},
                               True, True)

    def mutated_step(params, trainer):
        _backward_loss(params, consts)
        trainer.step(2)
        # overwrite WITHOUT reading: serial semantics = pull landed
        # first, then this write wins
        params[0].data()._write(jnp.full(SPECS[0], 0.25, jnp.float32))

    for _ in range(3):
        mutated_step(pa, ta)
        mutated_step(pb, tb)
    # the final mutation happened with its pull still in flight: the
    # settle here must DETECT it (stale > 0), not silently apply
    stale_seen = tb._pull_scheduler.finish()
    assert stale_seen > 0, "stale overwrite was not detected"
    # the overwritten weight holds the user's bytes on both sides
    assert np.allclose(pb[0].data().asnumpy(), 0.25)
    _assert_store_parity(pa, pb, ta, tb)


def test_stale_round_runs_serial_next_pull():
    """After a stale detection the NEXT round's pulls are serial
    (abandon-and-fallback), then async resumes."""
    rs = np.random.RandomState(4)
    params = _make_params("fbk")
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = _build_duplex_trainer(params, "sgd", {"learning_rate": 0.1},
                              True, True)
    _backward_loss(params, consts)
    t.step(2)
    issued_before = t._pull_scheduler.issued_total
    assert issued_before > 0
    # overwrite while in flight -> stale
    params[0].data()._write(jnp.zeros(SPECS[0], jnp.float32))
    _backward_loss(params, consts)
    t.step(2)       # finish() sees the stale out; this round pulls serial
    assert t._pull_scheduler.issued_total == issued_before, \
        "stale round still issued async pulls"
    _backward_loss(params, consts)
    t.step(2)       # clean round: async resumes
    assert t._pull_scheduler.issued_total > issued_before


def test_first_touch_read_modify_write_sees_pulled_bytes():
    """`w *= 0.5` between steps READS first: the first-touch hook must
    deliver the pulled value before the mutation computes — byte-equal
    to the serial trainer doing the same mutation."""
    rs = np.random.RandomState(11)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    pa = _make_params("rma")
    pb = _make_params("rmb")
    _seed(pa, weights)
    _seed(pb, weights)
    ta = _build_duplex_trainer(pa, "sgd", {"learning_rate": 0.1},
                               False, False)
    tb = _build_duplex_trainer(pb, "sgd", {"learning_rate": 0.1},
                               True, True)
    for _ in range(3):
        for params, trainer in ((pa, ta), (pb, tb)):
            _backward_loss(params, consts)
            trainer.step(2)
            w = params[2].data()
            w._write(w._read() * 0.5)       # RMW: read fires the hook
    tb._pull_scheduler.finish()
    _assert_store_parity(pa, pb, ta, tb)


# ---------------------------------------------------------------------------
# watchdog: a stuck in-flight pull bucket is named
# ---------------------------------------------------------------------------

def test_watchdog_names_stalled_inflight_pull():
    prev = blackbox._enabled_override
    blackbox.set_enabled(True)
    try:
        kv = mx.kv.create("dist_sync")
        kv.init([0], [mx.nd.array(np.ones(16, np.float32))])
        outs = [[mx.nd.array(np.zeros(16, np.float32))]]
        h = kv.pull_many_async([0], outs, label="pull[float32:1p:64B]")
        wd = watchdog.Watchdog(timeout=0.05)
        trips = []
        wd.trip = lambda entry, age: trips.append(entry)
        time.sleep(0.12)
        # deliberately left in flight (the next forward has not touched
        # the weights yet) = healthy overlap: NO trip...
        wd.poll()
        assert not trips, "watchdog tripped on a healthy in-flight pull"
        # ...but the dump names it while in flight
        doc = blackbox.snapshot(reason="test")
        stuck = [e for e in doc["in_flight"]
                 if e["detail"].get("path") == "pull_many_async"
                 and e["detail"].get("bucket") == "pull[float32:1p:64B]"]
        assert stuck, doc["in_flight"]
        # once a consumer starts WAITING, a stall is a genuine hang
        h._begin_wait()
        time.sleep(0.12)
        wd.poll()
        assert trips, "watchdog did not trip on the stalled pull wait"
        assert trips[0]["site"] == "collective"
        assert trips[0]["detail"]["bucket"] == "pull[float32:1p:64B]"
        h.wait()
        assert not [e for e in blackbox.inflight_entries()
                    if e["detail"].get("bucket") == "pull[float32:1p:64B]"]
    finally:
        blackbox.set_enabled(prev)


def test_pull_handle_wait_idempotent_and_abandon():
    kv = mx.kv.create("local")
    kv.init([0], [mx.nd.array(np.arange(4, dtype=np.float32))])
    outs = [[mx.nd.array(np.zeros(4, np.float32))]]
    h = kv.pull_many_async([0], outs, label="pull[x]")
    assert h.wait() is h.values and h.done
    h.wait()                    # idempotent
    assert np.allclose(outs[0][0].asnumpy(), np.arange(4))
    h2 = kv.pull_many_async([0], outs, label="pull[y]")
    h2.abandon()
    assert h2.done
    assert not [e for e in blackbox.inflight_entries()
                if e["detail"].get("bucket") in ("pull[x]", "pull[y]")]


# ---------------------------------------------------------------------------
# tape-order bucket packing
# ---------------------------------------------------------------------------

TAPE_SPECS = [(4,)] * 6                 # equal sizes: 3 params per 48B bucket
TAPE_USE_ORDER = [0, 3, 1, 4, 2, 5]     # forward use order != index order


def _tape_order_run(overlap_trainer_order):
    """Train 2 steps with the given GRAFT_BUCKET_ORDER; return
    (plan bucket index tuples, issue_log of the last armed backward)."""
    rs = np.random.RandomState(5)
    params = _make_params("tp" + overlap_trainer_order, TAPE_SPECS)
    _seed(params, [rs.randn(*s).astype(np.float32) for s in TAPE_SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32))
              for s in TAPE_SPECS]
    t = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                      kvstore=mx.kv.create("dist_sync"))
    t._bucket_bytes_override = 48
    t._overlap_override = True

    def step():
        with autograd.record():
            loss = None
            for k in TAPE_USE_ORDER:
                y = (params[k].data() * params[k].data() * consts[k]).sum()
                loss = y if loss is None else loss + y
        loss.backward()
        t.step(2)

    step()          # arms (tape stamps exist from this first backward)
    step()          # overlapped: issue_log fills
    # read the log of the LAST pass before the next backward resets it
    plan = t._fused_plan()
    buckets = tuple(tuple(b.indices) for b in plan[0])
    log = list(t._scheduler.issue_log)
    assert log, "no buckets were issued mid-backward"
    return buckets, log


def test_tape_order_closes_first_bucket_earlier(monkeypatch):
    monkeypatch.setenv("GRAFT_BUCKET_ORDER", "tape")
    tape_buckets, tape_log = _tape_order_run("t")
    monkeypatch.setenv("GRAFT_BUCKET_ORDER", "index")
    index_buckets, index_log = _tape_order_run("i")
    # index mode is the PR 4 packing, revertible
    assert index_buckets == ((0, 1, 2), (3, 4, 5))
    # tape mode groups by reverse use order: first bucket = last-used
    assert tape_buckets == ((5, 2, 4), (1, 3, 0))
    # the tentpole claim, in fire-counts: the first bucket ISSUES after
    # fewer grad deliveries under tape packing than under index packing
    first_issue_tape = min(n for _idx, n in tape_log)
    first_issue_index = min(n for _idx, n in index_log)
    assert first_issue_tape == 3, tape_log
    assert first_issue_index == 5, index_log
    assert first_issue_tape < first_issue_index


def test_tape_order_parity_vs_serial():
    """Tape-packed overlapped steps stay bytes-equal to the serial
    trainer (whose plan is index-packed — partitioning must not matter)."""
    rs = np.random.RandomState(8)
    weights = [rs.randn(*s).astype(np.float32) for s in TAPE_SPECS]
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32))
              for s in TAPE_SPECS]
    pa = _make_params("tps", TAPE_SPECS)
    pb = _make_params("tpo", TAPE_SPECS)
    _seed(pa, weights)
    _seed(pb, weights)
    ta = gluon.Trainer(pa, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=mx.kv.create("dist_sync"))
    tb = gluon.Trainer(pb, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=mx.kv.create("dist_sync"))
    ta._bucket_bytes_override = tb._bucket_bytes_override = 48
    ta._overlap_override = False
    tb._overlap_override = True

    def step(params, trainer):
        with autograd.record():
            loss = None
            for k in TAPE_USE_ORDER:
                y = (params[k].data() * params[k].data() * consts[k]).sum()
                loss = y if loss is None else loss + y
        loss.backward()
        trainer.step(2)

    for _ in range(4):
        step(pa, ta)
        step(pb, tb)
    assert tb._scheduler.issued_total > 0
    for a, b in zip(pa, pb):
        assert a.data().asnumpy().tobytes() == b.data().asnumpy().tobytes()
    sa, sb = ta._updaters[0].states, tb._updaters[0].states
    for i in sa:
        assert sa[i].asnumpy().tobytes() == sb[i].asnumpy().tobytes()


# ---------------------------------------------------------------------------
# 8-virtual-device mesh: multi-ctx grad-ready hooks + device-safe sums
# ---------------------------------------------------------------------------

def test_multi_device_mesh_overlap_parity():
    import jax
    n_dev = min(8, len(jax.devices()))
    if n_dev < 2:
        pytest.skip("needs multiple host devices")
    ctxs = [mx.cpu(i) for i in range(n_dev)]
    specs = [(5,), (3, 4), (9,), (2, 3)]
    rs = np.random.RandomState(6)
    weights = [rs.randn(*s).astype(np.float32) for s in specs]
    base = [rs.randn(*s).astype(np.float32) for s in specs]

    def build(prefix, overlap):
        ps = _make_params(prefix, specs, ctx=ctxs)
        _seed(ps, weights)
        t = gluon.Trainer(ps, "sgd",
                          {"learning_rate": 0.05, "momentum": 0.9},
                          kvstore=mx.kv.create("dist_sync"))
        t._bucket_bytes_override = 48
        t._overlap_override = overlap
        consts = [[mx.nd.array(c * (j + 1), ctx=ctx)
                   for j, ctx in enumerate(ctxs)] for c in base]
        return ps, t, consts

    def step(ps, t, consts):
        # ONE recorded scope, one backward over all contexts' losses:
        # grads for every (param, ctx) finalize inside a single pass
        with autograd.record():
            losses = []
            for j, ctx in enumerate(ctxs):
                loss = None
                for p, cs in zip(ps, consts):
                    d = p.data(ctx)
                    y = (d * d * cs[j]).sum()
                    loss = y if loss is None else loss + y
                losses.append(loss)
        autograd.backward(losses)
        t.step(2)

    pa, ta, ca = build("mds", False)
    pb, tb, cb = build("mdo", True)
    for _ in range(4):
        step(pa, ta, ca)
        step(pb, tb, cb)
    assert tb._scheduler.issued_total > 0, \
        "multi-ctx hooks never issued a bucket"
    assert tb._scheduler.taken_total > 0
    for a, b in zip(pa, pb):
        for da, db in zip(a.list_data(), b.list_data()):
            assert da.asnumpy().tobytes() == db.asnumpy().tobytes(), \
                "replica of %s diverged" % a.name
    for ua, ub in zip(ta._updaters, tb._updaters):
        assert set(ua.states) == set(ub.states)
        for i in ua.states:
            assert ua.states[i].asnumpy().tobytes() \
                == ub.states[i].asnumpy().tobytes()


# ---------------------------------------------------------------------------
# Module: the executor grad arrays ride the same schedulers
# ---------------------------------------------------------------------------

def _build_sym():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


_MODULE_INIT = None


def _module_init():
    global _MODULE_INIT
    if _MODULE_INIT is None:
        rs = np.random.RandomState(1)
        _MODULE_INIT = {
            "fc1_weight": rs.randn(8, 10).astype(np.float32) * 0.1,
            "fc1_bias": np.zeros(8, np.float32),
            "fc2_weight": rs.randn(4, 8).astype(np.float32) * 0.1,
            "fc2_bias": np.zeros(4, np.float32)}
    return _MODULE_INIT


def _build_module(kvstore, bucket_bytes, overlap, pull):
    m = mod.Module(_build_sym(), context=mx.cpu())
    m.bind(data_shapes=[("data", (6, 10))],
           label_shapes=[("softmax_label", (6,))])
    m.init_params(arg_params={k: mx.nd.array(v)
                              for k, v in _module_init().items()},
                  aux_params={})
    m.init_optimizer(kvstore=kvstore, optimizer="sgd",
                     optimizer_params=(("learning_rate", 0.1),
                                       ("momentum", 0.9)))
    m._bucket_bytes_override = bucket_bytes
    m._overlap_override = overlap
    m._overlap_pull_override = pull
    return m


def _module_batch():
    rs = np.random.RandomState(0)
    x = rs.rand(6, 10).astype(np.float32)
    y = rs.randint(0, 4, (6,)).astype(np.float32)
    return mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])


def _train_module(m, batch, n=4):
    for _ in range(n):
        m.forward(batch, is_train=True)
        m.backward()
        m.update()


def _assert_module_parity(ma, mb):
    pa, aa = ma.get_params()
    pb, ab = mb.get_params()
    assert set(pa) == set(pb)
    for k in pa:
        assert pa[k].asnumpy().tobytes() == pb[k].asnumpy().tobytes(), \
            "param %s diverged" % k


def test_module_bucketed_overlap_parity(monkeypatch):
    """Local-update Module (MXNET_UPDATE_ON_KVSTORE=0): the executor's
    grad arrays fire grad-ready hooks, buckets reduce mid-backward, and
    the result is bytes-equal to the per-key push/pull wire."""
    monkeypatch.setenv("MXNET_UPDATE_ON_KVSTORE", "0")
    batch = _module_batch()
    ma = _build_module(mx.kv.create("dist_sync"), 0, False, False)
    mb = _build_module(mx.kv.create("dist_sync"), 64, True, False)
    assert not ma._update_on_kvstore and not mb._update_on_kvstore
    _train_module(ma, batch)
    _train_module(mb, batch)
    assert mb._scheduler.issued_total > 0, "module overlap never engaged"
    assert mb._scheduler.taken_total > 0
    _assert_module_parity(ma, mb)


def test_module_update_on_kvstore_pull_overlap_parity():
    """Store-update Module: weight pulls ride PullScheduler first-touch
    hooks; bytes-equal to the synchronous pull."""
    batch = _module_batch()
    ma = _build_module(mx.kv.create("dist_sync"), 0, False, False)
    mb = _build_module(mx.kv.create("dist_sync"), 64, False, True)
    assert ma._update_on_kvstore and mb._update_on_kvstore
    _train_module(ma, batch)
    _train_module(mb, batch)
    assert mb._pull_scheduler.issued_total > 0, "pull overlap never engaged"
    assert mb._pull_scheduler.touched_total > 0, \
        "module forward never first-touched a pulled weight"
    _assert_module_parity(ma, mb)


def test_module_grad_add_req_not_scheduled(monkeypatch):
    """grad_req='add' executors accumulate — their buckets must not arm
    (the executor also never fires hooks for add-req grads)."""
    monkeypatch.setenv("MXNET_UPDATE_ON_KVSTORE", "0")
    batch = _module_batch()
    m = mod.Module(_build_sym(), context=mx.cpu())
    m.bind(data_shapes=[("data", (6, 10))],
           label_shapes=[("softmax_label", (6,))], grad_req="add")
    m.init_params(arg_params={k: mx.nd.array(v)
                              for k, v in _module_init().items()},
                  aux_params={})
    m.init_optimizer(kvstore=mx.kv.create("dist_sync"), optimizer="sgd")
    m._bucket_bytes_override = 64
    m._overlap_override = True
    _train_module(m, batch, n=3)
    assert m._scheduler.issued_total == 0


# ---------------------------------------------------------------------------
# satellite: prefetch-to-device double buffering shrinks data_wait
# ---------------------------------------------------------------------------

class _SlowDataset(gluon.data.Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        time.sleep(0.004)
        return mx.nd.array(np.full((4,), i, np.float32))


def _loader_data_wait(dl):
    lens.reset()
    order = []
    for b in dl:
        order.append(float(b.asnumpy()[0, 0]))
        time.sleep(0.02)        # the consumer's "compute"
    st = lens._tls.lens
    waited = sum(t1 - t0 for c, t0, t1 in st.intervals if c == "data_wait")
    lens.reset()
    return waited, order


def test_prefetch_to_device_shrinks_data_wait():
    ds = _SlowDataset(24)
    sync = gluon.data.DataLoader(ds, batch_size=4, num_workers=0,
                                 prefetch_device=False)
    pre = gluon.data.DataLoader(ds, batch_size=4, num_workers=0,
                                prefetch_device=True)
    try:
        w_sync, order_sync = _loader_data_wait(sync)
        w_pre, order_pre = _loader_data_wait(pre)
    finally:
        pre.close()
    assert order_sync == order_pre, "prefetch reordered batches"
    assert w_pre < 0.5 * w_sync, \
        "prefetch did not shrink data_wait (%.3fs vs %.3fs)" % (
            w_pre, w_sync)


def test_prefetch_env_kill_switch(monkeypatch):
    monkeypatch.setenv("GRAFT_PREFETCH_DEVICE", "0")
    ds = _SlowDataset(8)
    dl = gluon.data.DataLoader(ds, batch_size=4, num_workers=0)
    batches = [b.asnumpy() for b in dl]
    assert len(batches) == 2
    assert dl._pool is None, \
        "kill switch still spun up the lookahead thread"


# ---------------------------------------------------------------------------
# telemetry: the pull-overlap gauge/counters populate
# ---------------------------------------------------------------------------

def test_pull_overlap_metrics_emitted():
    from incubator_mxnet_tpu import telemetry
    rs = np.random.RandomState(12)
    params = _make_params("met")
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = _build_duplex_trainer(params, "sgd", {"learning_rate": 0.1},
                              True, True)
    for _ in range(4):
        _backward_loss(params, consts)
        t.step(2)
    t._pull_scheduler.finish()
    _backward_loss(params, consts)
    t.step(2)       # publishes the settled round
    snap = telemetry.compact_snapshot()
    assert snap.get(
        'graft_trainer_pull_buckets_total{mode="overlapped"}', 0) > 0
    assert "graft_trainer_pull_overlap_ratio" in snap
    assert 0.0 <= snap["graft_trainer_pull_overlap_ratio"] <= 1.0
    assert snap.get("graft_trainer_pull_exposed_seconds_count", 0) >= 1


def test_lens_books_pull_wait_as_exposed_comm():
    """A blocked PullHandle.wait books exposed_comm with an in-flight
    span ≥ the blocked span (conservation: the interval lands inside the
    step window like any collective)."""
    prev = lens._enabled_override
    lens.set_enabled(True)
    lens.reset()
    try:
        kv = mx.kv.create("local")
        kv.init([0], [mx.nd.array(np.arange(8, dtype=np.float32))])
        outs = [[mx.nd.array(np.zeros(8, np.float32))]]
        h = kv.pull_many_async([0], outs, label="pull[z]")
        time.sleep(0.02)        # healthy in-flight gap
        h.wait()
        st = lens._tls.lens
        assert st.coll_n >= 1
        assert st.comm_inflight >= st.comm_blocked
        assert st.comm_inflight >= 0.02, \
            "in-flight span did not cover the issue→wait gap"
    finally:
        lens.set_enabled(prev)
        lens.reset()
