"""graftlap: the overlapped bucketed reduce must be BIT-IDENTICAL to the
PR 4 serial bucketed path.

The overlap path moves each bucket's allreduce ISSUE into the backward
pass (autograd grad-ready hooks -> Trainer._BucketScheduler ->
KVStore.reduce_many_async) while keeping the bucket contents, the
packing math (Trainer._bucket_flat, shared verbatim) and the per-bucket
reduction order exactly the serial path's — so the parity contract is
bytes-equality on weights AND optimizer states, not allclose.  Also
here: the hook fallbacks (retain_graph, stale grads, GRAFT_OVERLAP=0),
the engine offband guarantee (an async issue must not flush an open
bulk segment), the watchdog naming a stuck in-flight bucket, the
2-process dist_sync parity harness, and the DataLoader worker-pool
hoist satellite.
"""
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, gluon
from incubator_mxnet_tpu.telemetry import blackbox, watchdog
import jax.numpy as jnp


SPECS = [(7,), (3, 5), (11,), (2, 2, 2), (13,), (4,)]


def _make_params(prefix, specs=SPECS, dtype="float32", grad_reqs=None):
    params = []
    for k, shape in enumerate(specs):
        req = grad_reqs[k] if grad_reqs else "write"
        p = gluon.Parameter("%s%d" % (prefix, k), shape=shape, dtype=dtype,
                            grad_req=req)
        p.initialize(ctx=mx.cpu())
        params.append(p)
    return params


def _seed(params, weights):
    for p, w in zip(params, weights):
        p.data()._write(jnp.asarray(w).astype(p.data().dtype))


def _state_leaves(state):
    if state is None:
        return []
    if isinstance(state, (tuple, list)):
        out = []
        for s in state:
            out.extend(_state_leaves(s))
        return out
    return [state]


def _assert_bit_identical(params_a, params_b, trainer_a, trainer_b):
    for a, b in zip(params_a, params_b):
        wa, wb = a.data().asnumpy(), b.data().asnumpy()
        assert wa.dtype == wb.dtype
        assert wa.tobytes() == wb.tobytes(), \
            "weight %s diverged (max |d|=%g)" % (
                a.name, float(np.max(np.abs(
                    wa.astype(np.float64) - wb.astype(np.float64)))))
    sa, sb = trainer_a._updaters[0].states, trainer_b._updaters[0].states
    assert set(sa) == set(sb)
    for i in sa:
        for x, y in zip(_state_leaves(sa[i]), _state_leaves(sb[i])):
            assert x.asnumpy().tobytes() == y.asnumpy().tobytes(), \
                "state %d diverged" % i


def _backward_loss(params, consts):
    """One real recorded forward + backward over every trainable param
    (grads depend on the weights, so they evolve across steps) — this is
    what fires the grad-ready hooks."""
    with autograd.record():
        loss = None
        for p, c in zip(params, consts):
            if p.grad_req == "null":
                continue
            y = (p.data() * p.data() * c).sum()
            loss = y if loss is None else loss + y
    loss.backward()


def _build_trainer(params, optimizer, opt_kw, overlap, bucket_bytes=48):
    t = gluon.Trainer(params, optimizer, dict(opt_kw),
                      kvstore=mx.kv.create("dist_sync"))
    t._bucket_bytes_override = bucket_bytes
    t._overlap_override = overlap
    return t


def _parity_run(optimizer, opt_kw, specs=SPECS, dtype="float32",
                grad_reqs=None, bucket_bytes=48, steps=5, batch_size=2):
    rs = np.random.RandomState(7)
    weights = [rs.randn(*s).astype(np.float32) for s in specs]
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in specs]
    pa = _make_params("s", specs, dtype, grad_reqs)
    pb = _make_params("o", specs, dtype, grad_reqs)
    _seed(pa, weights)
    _seed(pb, weights)
    ta = _build_trainer(pa, optimizer, opt_kw, False, bucket_bytes)
    tb = _build_trainer(pb, optimizer, opt_kw, True, bucket_bytes)
    for _ in range(steps):
        _backward_loss(pa, consts)
        ta.step(batch_size)
        _backward_loss(pb, consts)
        tb.step(batch_size)
    assert tb._fused_plan() is not None, \
        "overlapped trainer unexpectedly fell off the bucketed path"
    # the first step arms the hooks, so steps 2..N must actually overlap
    assert ta._scheduler.issued_total == 0
    assert tb._scheduler.issued_total > 0, "overlap never engaged"
    assert tb._scheduler.taken_total > 0, \
        "issued reduces were never consumed by step()"
    _assert_bit_identical(pa, pb, ta, tb)
    return ta, tb


def test_sgd_parity_with_null_holes():
    _parity_run("sgd", {"learning_rate": 0.1, "wd": 0.01},
                grad_reqs=["write", "null", "write", "write", "null",
                           "write"])


def test_sgd_momentum_parity_small_buckets():
    _parity_run("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01},
                bucket_bytes=48)


def test_sgd_momentum_multi_precision_bf16_parity():
    _parity_run("sgd", {"learning_rate": 0.05, "momentum": 0.9,
                        "wd": 0.001, "multi_precision": True},
                dtype="bfloat16", bucket_bytes=24, steps=6)


def test_adam_parity():
    _parity_run("adam", {"learning_rate": 0.01},
                grad_reqs=["write", "null", "write", "write", "write",
                           "write"], steps=5)


def test_single_bucket_parity():
    _parity_run("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                bucket_bytes=1 << 20)


def test_reduce_issued_during_backward():
    """The core graftlap semantic: after backward returns (and BEFORE
    step), every bucket's reduce is already in flight as a ReduceHandle
    with an open flight-recorder bracket naming the bucket."""
    rs = np.random.RandomState(3)
    params = _make_params("inflight")
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = _build_trainer(params, "sgd", {"learning_rate": 0.1}, True)
    _backward_loss(params, consts)
    t.step(2)                       # serial; arms the hooks
    _backward_loss(params, consts)
    handles = [s["handle"] for s in t._scheduler._buckets.values()]
    assert handles and all(h is not None for h in handles), \
        "no reduces in flight after backward"
    sites = [e for e in blackbox.inflight_entries()
             if e["detail"].get("path") == "reduce_many_async"]
    if blackbox.enabled():
        assert sites, "in-flight reduce carries no recorder bracket"
        assert all("bucket[" in str(e["detail"].get("bucket"))
                   for e in sites)
    t.step(2)                       # consumes them
    assert not [e for e in blackbox.inflight_entries()
                if e["detail"].get("path") == "reduce_many_async"]
    assert t._scheduler.taken_total >= len(handles)


def test_hook_fallback_retain_graph():
    """retain_graph=True suppresses the grad-ready hooks (a later pass
    may re-write delivered grads), so the step must take the serial
    reduce — and still match a serial trainer bit-for-bit."""
    rs = np.random.RandomState(5)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    pa = _make_params("rga")
    pb = _make_params("rgb")
    _seed(pa, weights)
    _seed(pb, weights)
    ta = _build_trainer(pa, "sgd", {"learning_rate": 0.1}, False)
    tb = _build_trainer(pb, "sgd", {"learning_rate": 0.1}, True)

    def retain_step(params, trainer):
        with autograd.record():
            loss = None
            for p, c in zip(params, consts):
                y = (p.data() * p.data() * c).sum()
                loss = y if loss is None else loss + y
        loss.backward(retain_graph=True)
        trainer.step(2)

    retain_step(pa, ta)         # step 1 also arms tb's hooks
    retain_step(pb, tb)
    retain_step(pa, ta)
    retain_step(pb, tb)
    assert tb._scheduler.issued_total == 0, \
        "hooks fired under retain_graph"
    assert tb._scheduler.taken_total == 0
    _assert_bit_identical(pa, pb, ta, tb)


def test_stale_grads_fall_back_to_serial():
    """Mutating a gradient between backward and step (gradient clipping,
    manual edits) must invalidate the in-flight reduce — the step falls
    back to the serial path and consumes the CURRENT grads."""
    rs = np.random.RandomState(9)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    pa = _make_params("sta")
    pb = _make_params("stb")
    _seed(pa, weights)
    _seed(pb, weights)
    ta = _build_trainer(pa, "sgd", {"learning_rate": 0.1}, False)
    tb = _build_trainer(pb, "sgd", {"learning_rate": 0.1}, True)

    def clipped_step(params, trainer):
        _backward_loss(params, consts)
        for p in params:        # post-backward mutation: halve every grad
            g = p.grad()
            g._write(g._read() * 0.5)
        trainer.step(2)

    clipped_step(pa, ta)
    clipped_step(pb, tb)        # arms
    taken_before = tb._scheduler.taken_total
    clipped_step(pa, ta)
    clipped_step(pb, tb)        # issued mid-backward, then invalidated
    assert tb._scheduler.issued_total > 0, "hooks never issued"
    assert tb._scheduler.taken_total == taken_before, \
        "stale in-flight reduce was consumed"
    _assert_bit_identical(pa, pb, ta, tb)


def test_graft_overlap_env_disables(monkeypatch):
    monkeypatch.setenv("GRAFT_OVERLAP", "0")
    rs = np.random.RandomState(2)
    params = _make_params("env")
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                      kvstore=mx.kv.create("dist_sync"))
    t._bucket_bytes_override = 48
    for _ in range(3):
        _backward_loss(params, consts)
        t.step(2)
    assert not t._scheduler._armed
    assert t._scheduler.issued_total == 0


def test_dropped_trainer_scheduler_is_collectable():
    """A Trainer dropped without disarm must not be pinned by its hooks:
    the hook closure holds the scheduler weakly, so the scheduler dies
    with the Trainer, the autograd hook-source gate re-closes, and later
    backwards over the same params degrade the leftover hook attrs to
    no-ops."""
    import gc
    import weakref as _weakref
    from incubator_mxnet_tpu import autograd as _ag
    rs = np.random.RandomState(6)
    params = _make_params("gc")
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = _build_trainer(params, "sgd", {"learning_rate": 0.1}, True)
    for _ in range(2):
        _backward_loss(params, consts)
        t.step(2)
    assert t._scheduler._armed
    sched_ref = _weakref.ref(t._scheduler)
    assert any(s is t._scheduler for s in _ag._hook_sources)
    del t
    gc.collect()
    assert sched_ref() is None, "hooks kept the dropped scheduler alive"
    gc.collect()
    assert not list(_ag._hook_sources), "hook-source gate did not re-close"
    # leftover hook attrs are dead-ref no-ops: backward still works
    _backward_loss(params, consts)
    for p in params:
        assert p.grad().asnumpy() is not None


def test_grad_accumulation_add_req_not_scheduled():
    """grad_req='add' params accumulate across passes — their grads are
    never final per-backward, so their buckets must not arm."""
    rs = np.random.RandomState(4)
    params = _make_params("acc", grad_reqs=["add"] * len(SPECS))
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = _build_trainer(params, "sgd", {"learning_rate": 0.1}, True)
    for _ in range(3):
        for p in params:
            p.zero_grad()
        _backward_loss(params, consts)
        t.step(2)
    assert t._scheduler.issued_total == 0


# ---------------------------------------------------------------------------
# engine: async issue must not flush the surrounding bulk segment
# ---------------------------------------------------------------------------

def test_async_reduce_does_not_flush_open_bulk_segment():
    kv = mx.kv.create("dist_sync")
    vals = [mx.nd.array(np.arange(8, dtype=np.float32))]
    engine.reset_flush_stats()
    with engine.bulk(64):
        a = mx.nd.ones((4, 4))
        b = a + 1.0             # deferred
        c = b * 2.0             # deferred
        h = kv.reduce_many_async(vals, label="bucket[offband]")
        h.wait()
        stats = engine.flush_stats()
        assert sum(stats["causes"].values()) == 0, \
            "async reduce flushed the open segment: %s" % stats
        assert np.allclose(c.asnumpy(), 4.0)    # segment intact + correct
    assert np.allclose(vals[0].asnumpy(), np.arange(8))


def test_engine_offband_scope():
    engine.reset_flush_stats()
    with engine.bulk(64):
        a = mx.nd.ones((2, 2))
        b = a + 1.0             # deferred
        with engine.offband():
            # eager dispatch alongside: no join, no flush
            c = mx.nd.ones((2, 2)) * 3.0
            assert np.allclose(c.asnumpy(), 3.0)
        assert sum(engine.flush_stats()["causes"].values()) == 0
        assert np.allclose(b.asnumpy(), 2.0)


# ---------------------------------------------------------------------------
# watchdog: a stalled in-flight bucket is named
# ---------------------------------------------------------------------------

def test_watchdog_names_stalled_inflight_bucket():
    prev = blackbox._enabled_override
    blackbox.set_enabled(True)
    try:
        kv = mx.kv.create("dist_sync")
        vals = [mx.nd.array(np.ones(16, np.float32))]
        h = kv.reduce_many_async(vals, label="bucket[float32:4p:64B]")
        wd = watchdog.Watchdog(timeout=0.05)
        trips = []
        wd.trip = lambda entry, age: trips.append(entry)
        time.sleep(0.12)
        # a bucket deliberately left in flight (backward still running /
        # user code before step) is healthy overlap — NO trip, however
        # old the bracket is...
        wd.poll()
        assert not trips, "watchdog tripped on a healthy in-flight bucket"
        # ...but the dump names it while in flight
        doc = blackbox.snapshot(reason="test")
        stuck = [e for e in doc["in_flight"]
                 if e["detail"].get("path") == "reduce_many_async"]
        assert stuck and stuck[0]["detail"]["bucket"] \
            == "bucket[float32:4p:64B]", doc["in_flight"]
        # once the consumer starts WAITING, the clock re-stamps and a
        # stall is a genuine hang: the trip names the bucket
        h._begin_wait()
        time.sleep(0.12)
        wd.poll()
        assert trips, "watchdog did not trip on the stalled bucket wait"
        assert trips[0]["site"] == "collective"
        assert trips[0]["detail"]["bucket"] == "bucket[float32:4p:64B]"
        h.wait()
        assert not [e for e in blackbox.inflight_entries()
                    if e["detail"].get("path") == "reduce_many_async"]
    finally:
        blackbox.set_enabled(prev)


def test_reduce_handle_wait_idempotent_and_abandon():
    kv = mx.kv.create("local")
    vals = [mx.nd.array(np.arange(4, dtype=np.float32))]
    h = kv.reduce_many_async(vals, label="bucket[x]")
    assert h.wait() is h.values and h.done
    h.wait()                    # idempotent
    h2 = kv.reduce_many_async(vals, label="bucket[y]")
    h2.abandon()
    assert h2.done
    assert not [e for e in blackbox.inflight_entries()
                if e["detail"].get("path") == "reduce_many_async"]


# ---------------------------------------------------------------------------
# satellite: DataLoader worker pool is per-loader, not per-epoch
# ---------------------------------------------------------------------------

def test_dataloader_pool_reused_across_epochs_and_closed():
    data = gluon.data.ArrayDataset(
        mx.nd.array(np.arange(24, dtype=np.float32).reshape(12, 2)))
    dl = gluon.data.DataLoader(data, batch_size=4, num_workers=2)
    first = [b.asnumpy() for b in dl]
    pool = dl._pool
    assert pool is not None, "worker pool was not created"
    second = [b.asnumpy() for b in dl]
    assert dl._pool is pool, "pool was recreated between epochs"
    for a, b in zip(first, second):
        assert np.array_equal(a, b)
    dl.close()
    assert dl._pool is None
    assert pool._shutdown
    # a later epoch lazily recreates
    third = [b.asnumpy() for b in dl]
    assert len(third) == len(first) and dl._pool is not None
    dl.close()


# ---------------------------------------------------------------------------
# 2-process dist_sync: overlapped == serial across a REAL wire
# ---------------------------------------------------------------------------

def _overlap_worker():
    from test_dist_multiprocess import _skipwrap
    return _skipwrap("""
        from incubator_mxnet_tpu import autograd, gluon
        import jax.numpy as jnp

        SPECS = [(7,), (3, 5), (11,), (2, 2, 2)]
        rs = np.random.RandomState(7)
        weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
        base = [rs.randn(*s).astype(np.float32) for s in SPECS]

        kv_probe = mx.kv.create("dist_sync")
        rank, nw = kv_probe.rank, kv_probe.num_workers
        assert nw == 2, nw
        # rank-dependent data: the allreduce has real cross-worker work
        consts = [mx.nd.array(c * (rank + 1)) for c in base]

        def build(prefix, overlap):
            ps = []
            for k, s in enumerate(SPECS):
                p = gluon.Parameter("%s%d" % (prefix, k), shape=s)
                p.initialize(ctx=mx.cpu())
                p.data()._write(jnp.asarray(weights[k]))
                ps.append(p)
            t = gluon.Trainer(ps, "sgd",
                              {"learning_rate": 0.05, "momentum": 0.9},
                              kvstore=mx.kv.create("dist_sync"))
            t._bucket_bytes_override = 48
            t._overlap_override = overlap
            return ps, t

        def train(ps, t):
            for _ in range(4):
                with autograd.record():
                    loss = None
                    for p, c in zip(ps, consts):
                        y = (p.data() * p.data() * c).sum()
                        loss = y if loss is None else loss + y
                loss.backward()
                t.step(2)

        pa, ta = build("s", False)
        train(pa, ta)
        pb, tb = build("o", True)
        train(pb, tb)
        assert tb._scheduler.issued_total > 0, "overlap never engaged"
        assert tb._scheduler.taken_total > 0
        # fully-overlapped steps must still feed the dist heartbeat
        # (kv.heartbeat() from the wait side — worker-skew telemetry
        # would otherwise starve once reduces go async)
        from incubator_mxnet_tpu import telemetry
        snap = telemetry.compact_snapshot()
        assert snap.get("graft_dist_worker_skew_seconds_count", 0) \\
            >= 3, snap
        for a, b in zip(pa, pb):
            assert a.data().asnumpy().tobytes() \\
                == b.data().asnumpy().tobytes(), "diverged"
        sa = ta._updaters[0].states
        sb = tb._updaters[0].states
        for i in sa:
            assert sa[i].asnumpy().tobytes() \\
                == sb[i].asnumpy().tobytes(), "state %d diverged" % i
        # both ranks ended bit-identical to each other too
        from jax.experimental import multihost_utils
        both = multihost_utils.process_allgather(
            jnp.asarray(pb[0].data().asnumpy()))
        assert np.array_equal(np.asarray(both[0]), np.asarray(both[1]))
        print("WORKER %d OVERLAP PARITY OK" % rank, flush=True)
    """)


def test_two_process_overlap_parity(tmp_path):
    from test_dist_multiprocess import _launch_two
    out = _launch_two(tmp_path, _overlap_worker(), timeout=300,
                      port_base=9950, require_rc0=False)
    assert "WORKER 0 OVERLAP PARITY OK" in out \
        and "WORKER 1 OVERLAP PARITY OK" in out, out[-3000:]


# ---------------------------------------------------------------------------
# telemetry: the overlap gauge/histogram populate
# ---------------------------------------------------------------------------

def test_overlap_metrics_emitted():
    from incubator_mxnet_tpu import telemetry
    rs = np.random.RandomState(11)
    params = _make_params("met")
    _seed(params, [rs.randn(*s).astype(np.float32) for s in SPECS])
    consts = [mx.nd.array(rs.randn(*s).astype(np.float32)) for s in SPECS]
    t = _build_trainer(params, "sgd", {"learning_rate": 0.1}, True)
    for _ in range(3):
        _backward_loss(params, consts)
        t.step(2)
    snap = telemetry.compact_snapshot()
    assert snap.get(
        'graft_trainer_overlap_buckets_total{mode="overlapped"}', 0) > 0
    assert "graft_trainer_overlap_ratio" in snap
    assert 0.0 <= snap["graft_trainer_overlap_ratio"] <= 1.0
    assert snap.get("graft_trainer_overlap_exposed_seconds_count", 0) >= 1
