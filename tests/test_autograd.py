"""Autograd tests (parity: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd as ag
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_record_scope_flags():
    assert not ag.is_recording()
    assert not ag.is_training()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
    assert not ag.is_recording()
    with ag.record(train_mode=False):
        assert ag.is_recording()
        assert not ag.is_training()
    with ag.train_mode():
        assert ag.is_training()


def test_simple_backward():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, [2.0, 4.0, 6.0])


def test_chain_and_broadcast_backward():
    x = mx.nd.array(np.random.rand(3, 4).astype(np.float32))
    w = mx.nd.array(np.random.rand(4, 2).astype(np.float32))
    x.attach_grad()
    w.attach_grad()
    with ag.record():
        y = mx.nd.dot(x, w)
        z = (mx.nd.relu(y) * 2).sum()
    z.backward()
    y_np = x.asnumpy() @ w.asnumpy()
    gy = 2 * (y_np > 0)
    assert_almost_equal(x.grad, gy @ w.asnumpy().T, rtol=1e-4, atol=1e-4)
    assert_almost_equal(w.grad, x.asnumpy().T @ gy, rtol=1e-4, atol=1e-4)


def test_backward_head_grad():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3
    y.backward(mx.nd.array([10.0, 100.0]))
    assert_almost_equal(x.grad, [30.0, 300.0])


def test_grad_accumulation_add():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, [6.0, 12.0])  # 3 * 2x


def test_detach_blocks_grad():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, [4.0])  # only d(z)/dx through the last x


def test_stop_gradient_op():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = mx.nd.BlockGrad(x * x) + x
    y.backward()
    assert_almost_equal(x.grad, [1.0])


def test_autograd_grad_api():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x * x).sum()
    (gx,) = ag.grad([y], [x])
    assert_almost_equal(gx, 3 * np.array([1.0, 4.0, 9.0]))


def test_training_flag_affects_dropout():
    x = mx.nd.ones((100, 100))
    with ag.record(train_mode=True):
        y = mx.nd.Dropout(x, p=0.5)
    # under training, roughly half dropped and survivors scaled by 2
    frac = float((y == 0).mean().asscalar())
    assert 0.3 < frac < 0.7
    with ag.record(train_mode=False):
        z = mx.nd.Dropout(x, p=0.5)
    assert_almost_equal(z, np.ones((100, 100)))
    # predict-mode outside autograd
    w = mx.nd.Dropout(x, p=0.5)
    assert_almost_equal(w, np.ones((100, 100)))


def test_retain_graph():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert_almost_equal(x.grad, g1)


def test_softmax_output_integrated_grad():
    data = mx.nd.array(np.random.randn(4, 5).astype(np.float32))
    label = mx.nd.array([0.0, 1.0, 2.0, 3.0])
    data.attach_grad()
    with ag.record():
        out = mx.nd.SoftmaxOutput(data, label)
    out.backward()
    p = np.exp(data.asnumpy())
    p /= p.sum(axis=1, keepdims=True)
    oh = np.eye(5, dtype=np.float32)[[0, 1, 2, 3]]
    assert_almost_equal(data.grad, p - oh, rtol=1e-4, atol=1e-4)


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + mx.nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.nd.array(np.random.randn(5).astype(np.float32))
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-4, atol=1e-4)


def test_numeric_gradient_harness():
    from incubator_mxnet_tpu.test_utils import check_numeric_gradient
    x = mx.nd.array(np.random.rand(3, 3).astype(np.float32) + 0.5)
    check_numeric_gradient(lambda a: mx.nd.log(a * a + 1.0), [x])
