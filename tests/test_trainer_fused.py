"""graftfuse: bucketed Trainer.step must be BIT-IDENTICAL to the
per-param path.

The fused path groups dense float params into dtype-homogeneous flat
buckets, reduces each bucket's gradients with one concatenated collective
and applies one jitted multi-tensor optimizer program per bucket
(gluon/trainer.py, optimizer.fused_bucket_update).  Because the fused
programs run the exact registered op formulas element-for-element with
scalar operands that compile identically to the per-param constants, the
parity contract is bytes-equality on weights AND optimizer states — not
allclose.  Also here: the kvstore multi-key push/pull batching parity and
the GRAFT_REPLAY_CACHE_SIZE bound on the engine program caches.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import engine, gluon
import jax.numpy as jnp


SPECS = [(7,), (3, 5), (11,), (2, 2, 2), (13,), (4,)]


def _make_params(prefix, specs=SPECS, dtype="float32", grad_reqs=None):
    params = []
    for k, shape in enumerate(specs):
        req = grad_reqs[k] if grad_reqs else "write"
        p = gluon.Parameter("%s%d" % (prefix, k), shape=shape, dtype=dtype,
                            grad_req=req)
        p.initialize(ctx=mx.cpu())
        params.append(p)
    return params


def _seed(params, weights, grads):
    for p, w, g in zip(params, weights, grads):
        p.data()._write(jnp.asarray(w).astype(p.data().dtype))
        if p.grad_req != "null":
            p.grad()._write(jnp.asarray(g).astype(p.data().dtype))


def _state_leaves(state):
    if state is None:
        return []
    if isinstance(state, (tuple, list)):
        out = []
        for s in state:
            out.extend(_state_leaves(s))
        return out
    return [state]


def _assert_bit_identical(params_a, params_b, trainer_a, trainer_b):
    for a, b in zip(params_a, params_b):
        wa, wb = a.data().asnumpy(), b.data().asnumpy()
        assert wa.dtype == wb.dtype
        assert wa.tobytes() == wb.tobytes(), \
            "weight %s diverged (max |d|=%g)" % (
                a.name, float(np.max(np.abs(
                    wa.astype(np.float64) - wb.astype(np.float64)))))
    sa, sb = trainer_a._updaters[0].states, trainer_b._updaters[0].states
    assert set(sa) == set(sb)
    for i in sa:
        for x, y in zip(_state_leaves(sa[i]), _state_leaves(sb[i])):
            assert x.asnumpy().tobytes() == y.asnumpy().tobytes(), \
                "state %d diverged" % i


def _parity_run(optimizer, opt_kw, specs=SPECS, dtype="float32",
                grad_reqs=None, bucket_bytes=40, steps=4, kvstore=None,
                batch_size=2):
    rs = np.random.RandomState(7)
    weights = [rs.randn(*s).astype(np.float32) for s in specs]
    grads = [rs.randn(*s).astype(np.float32) for s in specs]
    pa = _make_params("a", specs, dtype, grad_reqs)
    pb = _make_params("b", specs, dtype, grad_reqs)
    _seed(pa, weights, grads)
    _seed(pb, weights, grads)
    make_kv = lambda: mx.kv.create(kvstore) if kvstore else None
    ta = gluon.Trainer(pa, optimizer, dict(opt_kw), kvstore=make_kv())
    tb = gluon.Trainer(pb, optimizer, dict(opt_kw), kvstore=make_kv())
    ta._bucket_bytes_override = 0           # force the per-param path
    tb._bucket_bytes_override = bucket_bytes
    for _ in range(steps):
        ta.step(batch_size)
        tb.step(batch_size)
    assert tb._fused_plan() is not None, \
        "bucketed trainer unexpectedly fell back to per-param"
    _assert_bit_identical(pa, pb, ta, tb)
    return ta, tb


def test_sgd_parity_with_frozen_and_null_holes():
    # grad_req="null" holes must be skipped by both paths identically
    _parity_run("sgd", {"learning_rate": 0.1, "wd": 0.01},
                grad_reqs=["write", "null", "write", "write", "null",
                           "write"])


def test_sgd_momentum_parity_small_buckets():
    # tiny bucket target -> several buckets with non-divisible tails
    _parity_run("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01},
                bucket_bytes=48)


def test_sgd_clip_gradient_parity():
    _parity_run("sgd", {"learning_rate": 0.1, "momentum": 0.9,
                        "clip_gradient": 0.5})


def test_sgd_momentum_multi_precision_bf16_parity():
    # f32 master weights + momentum; weight, master copy and momentum all
    # bit-identical (states compared by _assert_bit_identical)
    _parity_run("sgd", {"learning_rate": 0.05, "momentum": 0.9,
                        "wd": 0.001, "multi_precision": True},
                dtype="bfloat16", bucket_bytes=24, steps=6)


def test_adam_parity():
    _parity_run("adam", {"learning_rate": 0.01},
                grad_reqs=["write", "null", "write", "write", "write",
                           "write"], steps=5)


def test_adam_parity_through_dist_sync_kvstore():
    # single-worker dist_sync: update_on_kvstore=False, so the bucketed
    # path rides the flat-reduce wire (reduce_many) end to end
    _parity_run("adam", {"learning_rate": 0.01}, kvstore="dist_sync",
                steps=3)


def test_single_param_bucket_tail():
    # one lonely param smaller than any target: a single ragged bucket
    _parity_run("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                specs=[(5,)], bucket_bytes=1 << 20, steps=3)


def test_lr_and_batch_size_changes_stay_bit_identical():
    """Changing lr / batch_size mid-run keeps parity: the fused cache
    keys on the scalars exactly as the per-param Operator.bind cache
    does, so every combination compiles to matching constants."""
    rs = np.random.RandomState(3)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    grads = [rs.randn(*s).astype(np.float32) for s in SPECS]
    pa = _make_params("lra", SPECS)
    pb = _make_params("lrb", SPECS)
    _seed(pa, weights, grads)
    _seed(pb, weights, grads)
    ta = gluon.Trainer(pa, "sgd", {"learning_rate": 0.1}, kvstore=None)
    tb = gluon.Trainer(pb, "sgd", {"learning_rate": 0.1}, kvstore=None)
    ta._bucket_bytes_override = 0
    ta.step(2)
    tb.step(2)
    for lr, bs in [(0.05, 2), (0.01, 4), (0.2, 1), (0.05, 2)]:
        ta.set_learning_rate(lr)
        tb.set_learning_rate(lr)
        ta.step(bs)
        tb.step(bs)
    _assert_bit_identical(pa, pb, ta, tb)


def test_momentum_flip_mid_run_stays_bit_identical():
    """Flipping momentum after states exist, then unfreezing a param:
    the unfrozen param gets a momentum state while the others keep None.
    The per-param formulas key off the state object, so the plan must
    bucket by state arity (a mixed bucket would mix formulas) and stay
    bit-identical to the per-param path."""
    rs = np.random.RandomState(13)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    grads = [rs.randn(*s).astype(np.float32) for s in SPECS]
    reqs = ["write", "write", "null", "write", "write", "write"]
    pa = _make_params("mfa", SPECS, grad_reqs=list(reqs))
    pb = _make_params("mfb", SPECS, grad_reqs=list(reqs))
    _seed(pa, weights, grads)
    _seed(pb, weights, grads)
    ta = gluon.Trainer(pa, "sgd", {"learning_rate": 0.1}, kvstore=None)
    tb = gluon.Trainer(pb, "sgd", {"learning_rate": 0.1}, kvstore=None)
    ta._bucket_bytes_override = 0
    tb._bucket_bytes_override = 48
    for _ in range(2):
        ta.step(2)
        tb.step(2)
    # momentum flips on; pre-existing states stay momentum-free
    ta._optimizer.momentum = tb._optimizer.momentum = 0.9
    # the frozen param thaws: its state is created under momentum=0.9
    pa[2].grad_req = pb[2].grad_req = "write"
    pa[2].grad()._write(jnp.asarray(grads[2]))
    pb[2].grad()._write(jnp.asarray(grads[2]))
    for _ in range(3):
        ta.step(2)
        tb.step(2)
    plan = tb._fused_plan()
    assert plan is not None
    arities = {len(opt_leaves) for opt_leaves in (
        [_state_leaves(tb._updaters[0].states[i]) for b in plan[0]
         for i in b.indices])}
    assert arities == {0, 1}        # both variants exist, in separate buckets
    _assert_bit_identical(pa, pb, ta, tb)


def test_fused_fallbacks():
    """Configurations outside the fused contract must yield plan None."""
    rs = np.random.RandomState(5)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    grads = [rs.randn(*s).astype(np.float32) for s in SPECS]

    # unsupported optimizer class (RMSProp has no fused kernel)
    p = _make_params("fb1", SPECS)
    _seed(p, weights, grads)
    t = gluon.Trainer(p, "rmsprop", {"learning_rate": 0.01}, kvstore=None)
    t.step(2)
    assert t._fused_plan() is None

    # bucketing disabled by GRAFT_BUCKET_BYTES<=0
    p = _make_params("fb2", SPECS)
    _seed(p, weights, grads)
    t = gluon.Trainer(p, "sgd", {"learning_rate": 0.01}, kvstore=None)
    t._bucket_bytes_override = 0
    t.step(2)
    assert t._fused_plan() is None

    # update_on_kvstore (explicit local store instance) falls back
    p = _make_params("fb3", SPECS)
    _seed(p, weights, grads)
    t = gluon.Trainer(p, "sgd", {"learning_rate": 0.01},
                      kvstore=mx.kv.create("local"))
    t.step(2)
    assert t._update_on_kvstore and t._fused_plan() is None

    # gradient compression no longer forces the per-key serial path: it
    # routes the bucketed step onto the block-scaled quantized wire
    # (graftzero), with a DeprecationWarning at store configuration
    p = _make_params("fb4", SPECS)
    _seed(p, weights, grads)
    with pytest.warns(DeprecationWarning):
        t = gluon.Trainer(p, "sgd", {"learning_rate": 0.01},
                          kvstore=mx.kv.create("dist_sync"),
                          compression_params={"type": "2bit"})
        t.step(2)
    assert t._fused_plan() is not None and t._fused_plan()[0]
    from incubator_mxnet_tpu.parallel import quant
    assert any(quant.is_residual_key(k) for k in t._updaters[0].states)


def test_trainer_save_load_states_roundtrip_on_fused_path():
    """States created by the fused path serialize/load like per-param
    ones (they live in the same Updater store)."""
    rs = np.random.RandomState(11)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    grads = [rs.randn(*s).astype(np.float32) for s in SPECS]
    p = _make_params("sl", SPECS)
    _seed(p, weights, grads)
    t = gluon.Trainer(p, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore=None)
    t.step(2)
    t.save_states("/tmp/fused_trainer.states")
    before = {i: s.asnumpy().copy()
              for i, s in t._updaters[0].states.items()}
    t.load_states("/tmp/fused_trainer.states")
    t.step(2)                     # fused path must survive a state reload
    for i, s in t._updaters[0].states.items():
        assert not np.array_equal(s.asnumpy(), before[i]) or \
            np.all(before[i] == 0)


# ---------------------------------------------------------------------------
# kvstore multi-key batching parity
# ---------------------------------------------------------------------------

def test_kvstore_push_pull_many_matches_per_key():
    rs = np.random.RandomState(2)
    shapes = [(4, 3), (5,), (2, 2)]
    vals = [rs.randn(*s).astype(np.float32) for s in shapes]
    upd = [rs.randn(*s).astype(np.float32) for s in shapes]

    kv_a = mx.kv.create("local")
    kv_b = mx.kv.create("local")
    keys = list(range(len(shapes)))
    kv_a.init(keys, [mx.nd.array(v) for v in vals])
    kv_b.init(keys, [mx.nd.array(v) for v in vals])

    # per-key push/pull
    for k in keys:
        kv_a.push(k, mx.nd.array(upd[k]))
    outs_a = [mx.nd.array(np.zeros(s, np.float32)) for s in shapes]
    for k in keys:
        kv_a.pull(k, outs_a[k])

    # batched multi-key push/pull
    kv_b.push_many(keys, [mx.nd.array(u) for u in upd])
    outs_b = [mx.nd.array(np.zeros(s, np.float32)) for s in shapes]
    kv_b.pull_many(keys, outs_b)

    for a, b in zip(outs_a, outs_b):
        assert a.asnumpy().tobytes() == b.asnumpy().tobytes()


def test_kvstore_pull_mixed_dtype_out_still_casts():
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.array(np.ones((3,), np.float32) * 1.5))
    out16 = mx.nd.array(np.zeros((3,), np.float16), dtype=np.float16)
    kv.pull(0, out16)
    assert out16.asnumpy().dtype == np.float16
    np.testing.assert_allclose(out16.asnumpy().astype(np.float32),
                               [1.5, 1.5, 1.5])


def test_kvstore_reduce_many_single_worker_identity():
    vals = [mx.nd.array(np.arange(4, dtype=np.float32)),
            mx.nd.array(np.ones((2, 2), np.float32))]
    kv = mx.kv.create("local")
    before = [v.asnumpy().copy() for v in vals]
    kv.reduce_many(vals)
    for v, b in zip(vals, before):
        assert np.array_equal(v.asnumpy(), b)


# ---------------------------------------------------------------------------
# bounded engine caches (GRAFT_REPLAY_CACHE_SIZE)
# ---------------------------------------------------------------------------

def test_replay_cache_size_bounded(monkeypatch):
    monkeypatch.setenv("GRAFT_REPLAY_CACHE_SIZE", "3")
    engine._replay_cache.clear()
    rs = np.random.RandomState(0)
    a = mx.nd.array(rs.rand(3, 3))
    # 6 distinct segment shapes -> 6 distinct cache keys, bound is 3
    for n in range(1, 7):
        with engine.bulk(64):
            x = a
            for _ in range(n):
                x = x + 1.0
            x.asnumpy()
    assert len(engine._replay_cache) <= 3


def test_replay_cache_lru_keeps_hot_entry(monkeypatch):
    monkeypatch.setenv("GRAFT_REPLAY_CACHE_SIZE", "2")
    cache = engine.BoundedCache()
    cache["a"] = 1
    cache["b"] = 2
    assert cache.get("a") == 1          # refresh "a"
    cache["c"] = 3                      # evicts "b", not "a"
    assert "a" in cache and "c" in cache and "b" not in cache
    assert len(cache) == 2


def test_replay_cache_gauge_exposed():
    from incubator_mxnet_tpu import telemetry
    with engine.bulk(8):
        (mx.nd.ones((2, 2)) + 1.0).asnumpy()
    snap = telemetry.compact_snapshot()
    key = 'graft_engine_replay_cache_size{cache="replay"}'
    assert key in snap and snap[key] >= 1
    assert 'graft_engine_replay_cache_size{cache="fused_update"}' in snap


def test_trainer_bucket_metrics_emitted():
    from incubator_mxnet_tpu import telemetry
    rs = np.random.RandomState(9)
    weights = [rs.randn(*s).astype(np.float32) for s in SPECS]
    grads = [rs.randn(*s).astype(np.float32) for s in SPECS]
    p = _make_params("tm", SPECS)
    _seed(p, weights, grads)
    t = gluon.Trainer(p, "sgd", {"learning_rate": 0.1}, kvstore=None)
    t._bucket_bytes_override = 64
    t.step(2)
    snap = telemetry.compact_snapshot()
    assert snap.get("graft_trainer_bucket_count", 0) >= 1
    assert snap.get("graft_trainer_bucket_fused_updates_total", 0) >= 1
    assert snap.get("graft_trainer_bucket_bytes_count", 0) >= 1
