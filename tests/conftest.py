"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference strategy of running multi-device semantics on CPU
contexts (tests/python/unittest/test_model_parallel.py runs on CPU; SURVEY
§4.1) — multi-chip sharding is validated on
``--xla_force_host_platform_device_count=8`` host devices.

NOTE: the environment's axon sitecustomize force-selects the TPU platform
via jax.config at interpreter start, so we must override jax_platforms here
(env vars alone are not enough).
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rngs(request):
    """Per-test deterministic seeding with logged seed (parity:
    tests/python/unittest/common.py with_seed decorator)."""
    seed = abs(hash(request.node.nodeid)) % (2 ** 31)
    np.random.seed(seed)
    import incubator_mxnet_tpu as mx
    mx.random.seed(seed)
    yield
