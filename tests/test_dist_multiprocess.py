"""Multi-process distributed KVStore + end-to-end training: REAL 2-worker runs.

Parity model: tests/nightly/dist_sync_kvstore.py + tests/nightly/dist_lenet.py
— N worker processes on one machine launched via tools/launch.py, asserting
(a) exact algebraic invariants of sync push/pull (value == sum over workers,
row-sparse union semantics) and (b) that a MODEL trains across processes via
every user-facing surface: Module.fit(kvstore="dist_sync"), Gluon Trainer,
and the fused DataParallelTrainer whose gradient psum runs INSIDE the jitted
step over the process-spanning mesh.  Workers rendezvous through the jax
coordination service (the ps-lite tracker's successor); the kvstore wire is
the in-graph all-reduce of parallel/dist.py:_global_sum.
"""
import os
import signal
import subprocess
import sys
import textwrap

import pytest

_PRELUDE = textwrap.dedent("""
    import os, sys, traceback
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
""")


def _skipwrap(body):
    """Wrap a worker body so backends without multiprocess CPU collectives
    produce the ``SKIP-MULTIPROC`` sentinel (clean pytest.skip in
    ``_launch_two``) instead of a chronic red — same contract as the skew
    harness in test_blackbox.py."""
    return _PRELUDE + "try:\n" \
        + textwrap.indent(textwrap.dedent(body), "    ") \
        + textwrap.dedent("""
            except Exception:
                if "Multiprocess computations aren't implemented" \\
                        in traceback.format_exc():
                    print("SKIP-MULTIPROC", flush=True)
                    os._exit(0)
                raise
        """)


_KV_WORKER = _skipwrap("""
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, nw
    kv.init("w", nd.zeros((3, 2)))
    kv.push("w", nd.ones((3, 2)) * (rank + 1))     # 1 + 2 = 3
    out = nd.zeros((3, 2))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 3.0), out.asnumpy()

    kv.init(["a", "b"], [nd.zeros(2), nd.zeros(2)])
    kv.push(["a", "b"], [nd.ones(2) * (rank + 1),
                         nd.ones(2) * 10 * (rank + 1)])
    oa, ob = nd.zeros(2), nd.zeros(2)
    kv.pull(["a", "b"], out=[oa, ob])
    assert np.allclose(oa.asnumpy(), 3.0) and np.allclose(ob.asnumpy(), 30.0)

    # row-sparse push: workers hold DIFFERENT row sets; the reduce must
    # union row ids and sum overlaps (ref: comm.h ReduceRowSparse)
    kv.init("rs", nd.zeros((6, 3)))
    dense = np.zeros((6, 3), np.float32)
    for r in [rank, 2 + rank, 4]:
        dense[r] = rank + 1
    kv.push("rs", nd.array(dense).tostype("row_sparse"))
    ors = nd.zeros((6, 3))
    kv.pull("rs", out=ors)
    exp = np.zeros((6, 3), np.float32)
    exp[0], exp[1], exp[2], exp[3], exp[4] = 1, 2, 1, 2, 3
    assert np.allclose(ors.asnumpy(), exp), ors.asnumpy()

    # one distributed "train step": push local grads (summed across
    # workers), pull, apply — both workers land on identical params
    rng = np.random.RandomState(0)
    Xs = rng.randn(40, 6).astype(np.float32)[rank::2]
    grad = (Xs.T @ Xs / len(Xs)).astype(np.float32)[:3]   # (3, 6) shard grad
    kv.init("grad", nd.zeros((3, 6)))
    kv.push("grad", nd.array(grad))
    summed = nd.zeros((3, 6))
    kv.pull("grad", out=summed)
    w = 0.05 - 0.1 * summed.asnumpy() / nw
    from jax.experimental import multihost_utils
    both = multihost_utils.process_allgather(jax.numpy.asarray(w))
    assert np.allclose(both[0], both[1], atol=1e-6), "params diverged"

    # compressed push: the wire ships packed 2-bit words (1/16 bytes,
    # parallel/compression.py) and dequant+sum must match the residual
    # algebra exactly.  threshold 0.5; rank0 pushes 0.3 (below threshold,
    # q=0, residual 0.3), rank1 pushes 0.6 (q=0.5, residual 0.1) -> sum 0.5
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kvc.init("cw", nd.zeros((5,)))
    kvc.push("cw", nd.ones((5,)) * (0.3 * (rank + 1)))
    oc = nd.zeros((5,))
    kvc.pull("cw", out=oc)
    assert np.allclose(oc.asnumpy(), 0.5), oc.asnumpy()
    # second identical push: rank0 acc 0.6 -> 0.5 ; rank1 acc 0.7 -> 0.5
    kvc.push("cw", nd.ones((5,)) * (0.3 * (rank + 1)))
    kvc.pull("cw", out=oc)
    assert np.allclose(oc.asnumpy(), 1.0), oc.asnumpy()
    print("WORKER %d COMPRESS OK" % rank, flush=True)

    # dist_async: true parameter-server semantics on the host service —
    # each push applies IMMEDIATELY server-side (parallel/ps.py); order
    # across ranks is free but the commutative SGD algebra pins the sum
    kva = mx.kv.create("dist_async")
    kva.init("aw", nd.ones((4,)) * 10.0)
    import incubator_mxnet_tpu.optimizer as opt
    kva.set_optimizer(opt.create("sgd", learning_rate=1.0))
    kva.push("aw", nd.ones((4,)) * (rank + 1))   # -1 and -2, any order
    kva.barrier()
    oa2 = nd.zeros((4,))
    kva.pull("aw", out=oa2)
    assert np.allclose(oa2.asnumpy(), 7.0), oa2.asnumpy()
    print("WORKER %d ASYNC OK" % rank, flush=True)

    kv.barrier()
    print("WORKER %d OK" % rank)
""")

# End-to-end model training across processes — the path that deadlocked in
# round 2 (collective-order mismatch).  Covers the reference's
# tests/nightly/dist_lenet.py semantics on all three training surfaces.
_TRAIN_WORKER = _skipwrap("""
    from incubator_mxnet_tpu import gluon, autograd
    from incubator_mxnet_tpu.parallel import dist
    from incubator_mxnet_tpu.parallel.data_parallel import DataParallelTrainer
    from jax.experimental import multihost_utils

    def assert_synced(arr, tag):
        both = multihost_utils.process_allgather(jax.numpy.asarray(arr))
        assert np.allclose(both[0], both[1], atol=1e-5), tag + " diverged"

    rng = np.random.RandomState(42)
    X = rng.randn(64, 10).astype(np.float32)
    W = rng.randn(10, 1).astype(np.float32)
    y = (X @ W > 0).astype(np.float32).ravel()

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    Xs, ys = X[rank::nw], y[rank::nw]

    # --- surface 1: Module.fit(kvstore="dist_sync") ---------------------
    data = mx.io.NDArrayIter(Xs, ys, batch_size=8, shuffle=False,
                             label_name="softmax_label")
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(data, num_epoch=2, kvstore=kv,
            optimizer="sgd", optimizer_params={"learning_rate": 0.05},
            initializer=mx.init.Xavier(magnitude=2.0))
    assert_synced(mod.get_params()[0]["fc1_weight"].asnumpy(), "fit")
    print("WORKER %d FIT OK" % rank, flush=True)

    # --- surface 2: Gluon Trainer over the dist kvstore -----------------
    gnet = gluon.nn.Sequential()
    gnet.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(2))
    gnet.initialize(mx.init.Xavier(magnitude=2.0))
    kv2 = mx.kv.create("dist_sync")     # own store: int keys are per-store
    trainer = gluon.Trainer(gnet.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore=kv2)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for epoch in range(8):
        tot = 0.0
        for i in range(0, len(Xs), 8):
            xb, yb = nd.array(Xs[i:i+8]), nd.array(ys[i:i+8])
            with autograd.record():
                loss = loss_fn(gnet(xb), yb)
            loss.backward()
            trainer.step(8 * nw)
            tot += float(loss.asnumpy().mean())
        losses.append(tot)
    assert losses[-1] < losses[0], losses
    assert_synced(gnet[0].weight.data().asnumpy(), "trainer")
    print("WORKER %d TRAINER OK" % rank, flush=True)

    # --- surface 3: fused DataParallelTrainer, psum IN the jitted step --
    hnet = gluon.nn.HybridSequential()
    hnet.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(2))
    hnet.initialize(mx.init.Xavier(magnitude=2.0))
    tr = DataParallelTrainer(hnet, loss_fn, "sgd",
                             {"learning_rate": 0.05})
    yl = y.astype(np.int64)
    dlosses = []
    for ep in range(10):
        for i in range(0, 64, 16):
            lo = rank * 8
            loss = tr.step(X[i:i+16][lo:lo+8], yl[i:i+16][lo:lo+8])
            dlosses.append(float(jax.device_get(loss.addressable_data(0))))
    head, tail = np.mean(dlosses[:4]), np.mean(dlosses[-4:])
    assert tail < head, (head, tail, dlosses)
    tr.sync_params()
    assert_synced(hnet[0].weight.data().asnumpy(), "dpt")
    print("WORKER %d DPT OK" % rank, flush=True)
""")


def _launch_two(tmp_path, source, timeout=300, n=2, port_base=9300,
                require_rc0=True):
    worker = tmp_path / "worker.py"
    worker.write_text(source)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.abspath(repo) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    port = port_base + os.getpid() % 500  # avoid collisions between runs
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", str(n), "-p", str(port), sys.executable, str(worker)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        # a hang here IS the failure mode this test exists to catch;
        # kill the whole process group so the workers don't leak
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        proc.wait()
        pytest.fail("%d-process dist run deadlocked (%ds timeout)"
                    % (n, timeout))
    out = stdout + stderr
    if "SKIP-MULTIPROC" in out:
        pytest.skip("backend lacks multiprocess CPU collectives")
    if require_rc0:
        assert proc.returncode == 0, out[-3000:]
    return out


def test_two_process_dist_sync(tmp_path):
    out = _launch_two(tmp_path, _KV_WORKER, timeout=240)
    assert "WORKER 0 OK" in out and "WORKER 1 OK" in out, out[-2000:]
    assert "WORKER 0 COMPRESS OK" in out and "WORKER 1 COMPRESS OK" in out, \
        out[-2000:]
    assert "WORKER 0 ASYNC OK" in out and "WORKER 1 ASYNC OK" in out, \
        out[-2000:]


def test_two_process_end_to_end_training(tmp_path):
    """Round-2's known deadlock path: a model must actually TRAIN across
    processes on every surface (ref: tests/nightly/dist_lenet.py)."""
    out = _launch_two(tmp_path, _TRAIN_WORKER, timeout=420)
    for rank in (0, 1):
        for tag in ("FIT", "TRAINER", "DPT"):
            assert "WORKER %d %s OK" % (rank, tag) in out, out[-3000:]


_COMPRESS4_WORKER = _skipwrap("""
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 4, nw
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("cw", nd.zeros((37,)))     # odd length: exercises word padding
    # rank r pushes 0.3*(r+1): q = {0, 0.5, 0.5, 0.5}, residuals kept
    kv.push("cw", nd.ones((37,)) * (0.3 * (rank + 1)))
    out = nd.zeros((37,))
    kv.pull("cw", out=out)
    assert np.allclose(out.asnumpy(), 1.5), out.asnumpy()
    # second push: acc = residual + new = {0.6, 0.7, 1.3, 1.9}; the 2-bit
    # code takes ONE +-t step per push -> q = 0.5 everywhere -> sum 2.0
    kv.push("cw", nd.ones((37,)) * (0.3 * (rank + 1)))
    kv.pull("cw", out=out)
    assert np.allclose(out.asnumpy(), 2.0), out.asnumpy()
    print("WORKER %d COMPRESS4 OK" % rank, flush=True)
""")


def test_four_process_compressed_wire(tmp_path):
    """W=4 compressed reduce: the scale-correct wire (compressed
    reduce-scatter + int8 sum gather) must keep the exact residual
    algebra beyond the W=2 case the old allgather wire was tested at."""
    out = _launch_two(tmp_path, _COMPRESS4_WORKER, timeout=300, n=4,
                      port_base=9800)
    for rank in range(4):
        assert "WORKER %d COMPRESS4 OK" % rank in out, out[-3000:]


_DEAD_NODE_WORKER = _skipwrap("""
    import time
    kv = mx.kv.create("dist_async")
    rank, nw = kv.rank, kv.num_workers
    kv.init("w", nd.ones((4,)))
    kv.barrier()
    if rank == 1:
        # die without ceremony: heartbeats stop mid-job
        print("WORKER 1 DYING", flush=True)
        os._exit(0)
    # rank 0: watch the heartbeat table flip the dead worker
    deadline = time.time() + 30
    n = 0
    while time.time() < deadline:
        n = kv.num_dead_nodes(timeout_sec=2)
        if n == 1:
            break
        time.sleep(0.5)
    assert n == 1, n
    # the timeout path is SURFACED, not a silent return (graftwatch):
    # the gauge tracks the count and the flight recorder holds an event
    # naming the dead worker
    from incubator_mxnet_tpu import telemetry
    from incubator_mxnet_tpu.telemetry import blackbox
    assert telemetry.registry().gauge("graft_dist_dead_nodes").value() \
        == 1
    dead_evs = [e for e in blackbox.events() if e["kind"] == "dead_nodes"]
    assert dead_evs, blackbox.events()
    assert dead_evs[-1]["data"]["dead"] == [1], dead_evs[-1]
    print("WORKER 0 DEADNODE OK", flush=True)
    os._exit(0)   # skip jax.distributed teardown: rank 1 is gone
""")


def test_async_dead_node_detection(tmp_path):
    """Kill a worker mid-job: the parameter service's heartbeat table must
    surface num_dead_nodes == 1 (kvstore_dist.h:109-115) — through the
    graft_dist_dead_nodes gauge and a flight-recorder event, not just
    the return value (asserted inside the worker)."""
    # the launcher reports nonzero when a worker vanishes mid-job (the
    # coordination service flags the lost member) — that's the scenario
    # under test, so only the rank-0 marker matters
    out = _launch_two(tmp_path, _DEAD_NODE_WORKER, timeout=240,
                      port_base=9600, require_rc0=False)
    assert "WORKER 0 DEADNODE OK" in out, out[-3000:]
    assert "WORKER 1 DYING" in out, out[-3000:]


_CHAOS_WORKER_TMPL = _skipwrap("""
    import hashlib
    os.environ["GRAFT_RPC_BACKOFF_MS"] = "1"
    os.environ["GRAFT_FAULTS"] = "@FAULTS@"
    kva = mx.kv.create("dist_async")
    rank = kva.rank
    import incubator_mxnet_tpu.optimizer as opt
    kva.init("w", nd.ones((8,)) * 64.0)
    kva.set_optimizer(opt.create("sgd", learning_rate=1.0))
    # exact integer algebra: each push applies w -= grad server-side, so
    # ANY interleave/retry schedule that applies each push EXACTLY ONCE
    # lands on 64 - 5*(1+2) = 49 bit-for-bit.  A dropped-reply retry
    # that double-applied would land on != 49 and break the parity hash.
    for step in range(5):
        kva.push("w", nd.ones((8,)) * (rank + 1))
        kva.barrier()
    out = nd.zeros((8,))
    kva.pull("w", out=out)
    arr = np.asarray(out.asnumpy(), np.float32)
    assert np.allclose(arr, 49.0), arr
    from incubator_mxnet_tpu.telemetry import blackbox
    n_inj = len([e for e in blackbox.events()
                 if e["kind"] == "fault_injected"])
    print("CHAOS %d SHA %s INJ %d"
          % (rank, hashlib.sha256(arr.tobytes()).hexdigest(), n_inj),
          flush=True)
    kva.barrier()
""")


def _chaos_shas(out):
    shas, inj = {}, {}
    for line in out.splitlines():
        if line.startswith("CHAOS "):
            parts = line.split()
            shas[int(parts[1])] = parts[3]
            inj[int(parts[1])] = int(parts[5])
    return shas, inj


def test_two_process_chaos_parity(tmp_path):
    """graftarmor chaos gate: the same dist_async run under injected PS
    wire faults (dropped replies, mid-push disconnects on both ranks)
    must be BYTE-EQUAL to the un-faulted run — retries are idempotent
    (server-side dedup), reconnects are transparent."""
    clean = _launch_two(tmp_path,
                        _CHAOS_WORKER_TMPL.replace("@FAULTS@", ""),
                        timeout=240, port_base=10300)
    shas0, inj0 = _chaos_shas(clean)
    assert set(shas0) == {0, 1}, clean[-2000:]
    assert inj0 == {0: 0, 1: 0}, inj0

    spec = ("ps.recv:drop:n=2:cmd=push:rank=0;"
            "ps.send:disconnect:n=3:cmd=push:rank=1;"
            "ps.recv:drop:n=4:cmd=push:rank=1")
    chaos = _launch_two(tmp_path,
                        _CHAOS_WORKER_TMPL.replace("@FAULTS@", spec),
                        timeout=240, port_base=10300)
    shas1, inj1 = _chaos_shas(chaos)
    assert set(shas1) == {0, 1}, chaos[-2000:]
    assert inj1[0] >= 1 and inj1[1] >= 2, inj1   # the chaos really fired
    assert shas1 == shas0, (shas0, shas1)        # ...and changed nothing


_KILL_RESUME_WORKER = _skipwrap("""
    import time
    os.environ["GRAFT_RPC_BACKOFF_MS"] = "1"
    # rank 1 is killed mid-push (injected SIGKILL-style os._exit) — the
    # kill-rank-mid-step harness; rank 0 must see the dead rank AND its
    # own checkpoint/resume must replay the loss trajectory bit-exactly
    os.environ["GRAFT_FAULTS"] = "ps.send:kill:n=3:rank=1"
    kv = mx.kv.create("dist_async")
    rank = kv.rank
    kv.init("w", nd.ones((4,)))
    kv.barrier()
    if rank == 1:
        print("WORKER 1 PUSHING UNTIL KILLED", flush=True)
        for _ in range(10):
            kv.push("w", nd.ones((4,)))     # 3rd send never returns
        raise AssertionError("injected kill did not fire")

    from incubator_mxnet_tpu import gluon, autograd
    net = gluon.nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    rng = np.random.RandomState(7)
    batches = [rng.randn(2, 6).astype(np.float32) for _ in range(7)]
    net(nd.array(batches[0]))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})

    def step(i):
        x = nd.array(batches[i])
        with autograd.record():
            loss = (net(x) * net(x)).sum()
        loss.backward()
        trainer.step(2)
        return float(loss.asnumpy())

    ckdir = os.path.join(os.getcwd(), "graft-ckpt-%d" % os.getpid())
    cp = trainer.checkpointer(ckdir, keep=3, emergency=False)
    first = []
    for i in range(6):
        first.append(step(i))
        if i == 2:
            cp.save(step=2)
    restored = cp.resume()
    assert restored == 2, restored
    replay = [step(i) for i in range(3, 6)]
    assert replay == first[3:], (replay, first[3:])   # bit-exact losses
    print("WORKER 0 RESUME OK", flush=True)

    deadline = time.time() + 30
    n = 0
    while time.time() < deadline:
        n = kv.num_dead_nodes(timeout_sec=2)
        if n == 1:
            break
        time.sleep(0.5)
    assert n == 1, n
    print("WORKER 0 KILLRESUME OK", flush=True)
    os._exit(0)   # skip jax.distributed teardown: rank 1 is gone
""")


def test_kill_rank_checkpoint_resume(tmp_path):
    """graftarmor fail-recover gate: rank 1 dies mid-push via the
    injected kill harness; rank 0's heartbeat table flips the dead rank
    and its checkpoint resume() replays the loss trajectory bit-exactly
    (params + momentum + RNG restored)."""
    out = _launch_two(tmp_path, _KILL_RESUME_WORKER, timeout=240,
                      port_base=10600, require_rc0=False)
    assert "WORKER 1 PUSHING UNTIL KILLED" in out, out[-3000:]
    assert "graftarmor: injected kill" in out, out[-3000:]
    assert "WORKER 0 RESUME OK" in out, out[-3000:]
    assert "WORKER 0 KILLRESUME OK" in out, out[-3000:]


def test_num_dead_nodes_surfaces_gauge_single_process():
    """Single-process contract of the same surfacing: the sync wire
    always answers 0, and the answer lands on the gauge (runnable
    without multi-host collectives)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import telemetry
    kv = mx.kv.create("dist_sync")
    assert kv.num_dead_nodes() == 0
    assert telemetry.registry().gauge("graft_dist_dead_nodes").value() == 0
