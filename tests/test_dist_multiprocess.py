"""Multi-process distributed KVStore: REAL 2-worker dist_sync run.

Parity model: tests/nightly/dist_sync_kvstore.py — N worker processes on
one machine launched via tools/launch.py, asserting exact algebraic
invariants of sync push/pull (value == sum over workers).  Workers
rendezvous through the jax coordination service (the ps-lite tracker's
successor) and reduce over the fused allgather path.
"""
import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, nw
    kv.init("w", nd.zeros((3, 2)))
    kv.push("w", nd.ones((3, 2)) * (rank + 1))     # 1 + 2 = 3
    out = nd.zeros((3, 2))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 3.0), out.asnumpy()

    kv.init(["a", "b"], [nd.zeros(2), nd.zeros(2)])
    kv.push(["a", "b"], [nd.ones(2) * (rank + 1),
                         nd.ones(2) * 10 * (rank + 1)])
    oa, ob = nd.zeros(2), nd.zeros(2)
    kv.pull(["a", "b"], out=[oa, ob])
    assert np.allclose(oa.asnumpy(), 3.0) and np.allclose(ob.asnumpy(), 30.0)

    # one distributed "train step": push local grads (summed across
    # workers), pull, apply — both workers land on identical params
    rng = np.random.RandomState(0)
    Xs = rng.randn(40, 6).astype(np.float32)[rank::2]
    grad = (Xs.T @ Xs / len(Xs)).astype(np.float32)[:3]   # (3, 6) shard grad
    kv.init("grad", nd.zeros((3, 6)))
    kv.push("grad", nd.array(grad))
    summed = nd.zeros((3, 6))
    kv.pull("grad", out=summed)
    w = 0.05 - 0.1 * summed.asnumpy() / nw
    from jax.experimental import multihost_utils
    both = multihost_utils.process_allgather(jax.numpy.asarray(w))
    assert np.allclose(both[0], both[1], atol=1e-6), "params diverged"

    kv.barrier()
    print("WORKER %d OK" % rank)
""")


def test_two_process_dist_sync(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.abspath(repo) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    port = 9300 + os.getpid() % 500      # avoid collisions between runs
    import signal
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "tools", "launch.py"),
         "-n", "2", "-p", str(port), sys.executable, str(worker)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        # a hang here IS the failure mode this test exists to catch;
        # kill the whole process group so the workers don't leak
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        proc.wait()
        pytest.fail("2-process dist_sync deadlocked (240s timeout)")
    res = subprocess.CompletedProcess(proc.args, proc.returncode,
                                      stdout, stderr)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-2000:]
    assert "WORKER 0 OK" in out and "WORKER 1 OK" in out, out[-2000:]
