"""graftpulse tests (ISSUE 12): the ASYNC device-time ledger (exact-sum
conservation on plain deferred train loops, no-double-booking with sync
mode, watermark span-union), the per-site memory timeline, the
profiler-trace ingestion fallback, the lens-driven autotuner (worker
growth, bucket-bytes hill-climb, straggler feed, decision journaling,
off-by-default bit-identity), and the lockstep online bisection
satellite (a mid-stream skipped collective is pinned exactly)."""
import json
import os
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, gluon, profiler
from incubator_mxnet_tpu.telemetry import aggregate, autotune, blackbox, lens


@pytest.fixture
def fresh_lens():
    """A clean, force-enabled lens (+ pulse) for one test."""
    lens.set_enabled(True)
    lens.reset()
    lens.reset_pulse_stats()
    yield lens
    lens.pulse_drain(5.0)
    lens.reset()
    lens.set_pulse(None)
    lens.set_mem_sampler(None)
    lens.set_enabled(None)


def _build_params(n, shape=(8, 8), prefix="pp", seed=0):
    rs = np.random.RandomState(seed)
    ps = []
    for k in range(n):
        p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
        p.initialize(ctx=mx.cpu())
        p.data()._write(rs.randn(*shape).astype(np.float32))
        ps.append(p)
    return ps


def _train_step(ps, trainer, bulk=True):
    if bulk:
        with engine.bulk(64):
            with autograd.record():
                loss = None
                for p in ps:
                    y = (p.data() * p.data()).sum()
                    loss = y if loss is None else loss + y
            loss.backward()
    else:
        with autograd.record():
            loss = None
            for p in ps:
                y = (p.data() * p.data()).sum()
                loss = y if loss is None else loss + y
        loss.backward()
    trainer.step(1)


def _assert_device_conserved(rec):
    d = rec.get("device")
    assert d is not None, "device ledger empty on an async step: %r" % rec
    # the exact-sum contract: busy + idle == wall, bit-exact (idle is
    # wall - busy by construction, busy clamped at wall)
    assert d["busy_s"] + d["idle_s"] == rec["wall_s"]
    assert 0.0 < d["busy_s"] <= rec["wall_s"]
    assert d["idle_s"] >= 0.0
    assert d["spans"] >= 1


# ---------------------------------------------------------------------------
# the async device ledger (tentpole)
# ---------------------------------------------------------------------------

def test_async_ledger_conservation_on_deferred_loop(fresh_lens):
    """ISSUE 12 acceptance: on a PLAIN deferred (bulked, async — no
    sync mode, no profiler) train loop, every step window's device
    ledger satisfies busy + idle == wall exactly, fed only by the pulse
    reaper's done-callbacks."""
    assert not profiler.want_sync()
    ps = _build_params(4)
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=mx.kv.create("local"))
    for _ in range(4):
        _train_step(ps, trainer, bulk=True)
        # settle this window's callbacks before the NEXT step closes it
        # (a span completing after step_end books into the next window
        # by design; the drain pins the test deterministic)
        assert lens.pulse_drain(10.0)
    ps[-1].data().asnumpy()
    recs = lens.steps()
    assert len(recs) == 4
    # the first window opens at first activity; later windows are the
    # steady-state contract surface
    for rec in recs[1:]:
        _assert_device_conserved(rec)
    stats = lens.pulse_stats()
    assert stats["enqueued"] > 0
    assert stats["booked"] > 0
    assert stats["pending"] == 0


def test_async_ledger_fills_on_unbulked_eager_loop(fresh_lens):
    """Per-op done-callbacks: an eager (never-bulked) loop's dispatches
    feed the same ledger."""
    ps = _build_params(3)
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=mx.kv.create("local"))
    for _ in range(3):
        _train_step(ps, trainer, bulk=False)
        assert lens.pulse_drain(10.0)
    recs = lens.steps()
    for rec in recs[1:]:
        _assert_device_conserved(rec)


def test_no_double_booking_when_sync_and_callbacks_both_active(
        fresh_lens, tmp_path):
    """ISSUE 12 satellite: with profiler sync mode on AND the pulse
    ledger on, flushes/ops book directly (sync) and must NOT also
    enqueue to the reaper — the enqueue counter stays at zero, and the
    ledger still conserves."""
    lens.set_pulse(True)
    lens.reset_pulse_stats()
    ps = _build_params(3, prefix="sy")
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=mx.kv.create("local"))
    _train_step(ps, trainer, bulk=True)      # warm plans/compiles async
    lens.pulse_drain(10.0)
    lens.reset()
    lens.reset_pulse_stats()
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_all=True, sync=True)
    profiler.set_state("run")
    try:
        for _ in range(3):
            _train_step(ps, trainer, bulk=True)
    finally:
        profiler.set_state("stop")
    recs = lens.steps()
    assert any(r.get("device") for r in recs)
    for rec in recs:
        d = rec.get("device")
        if d is not None:
            assert d["busy_s"] + d["idle_s"] == rec["wall_s"]
    # every dispatch inside the loop ran under sync mode: direct
    # booking only, zero reaper enqueues (the no-double-booking gate)
    assert lens.pulse_stats()["enqueued"] == 0


def test_device_watermark_merges_overlapping_spans(fresh_lens):
    """The union watermark: re-booking the same span (or an overlapping
    one) adds only the uncovered part — the double-delivery rail."""
    t0 = time.perf_counter()
    st = lens._state()
    lens.device(t0, t0 + 1.0)
    lens.device(t0, t0 + 1.0)            # exact duplicate: no-op
    lens.device(t0 + 0.5, t0 + 1.5)      # overlap: only +0.5 books
    assert st.device_s == pytest.approx(1.5)
    lens._tls.lens = None


def test_pulse_kill_switch_restores_empty_ledger(fresh_lens):
    """GRAFT_PULSE=0 (via set_pulse): async loops book nothing — the
    pre-PR-12 behavior."""
    lens.set_pulse(False)
    lens.reset_pulse_stats()
    ps = _build_params(3, prefix="ko")
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=mx.kv.create("local"))
    for _ in range(3):
        _train_step(ps, trainer, bulk=True)
    recs = lens.steps()
    assert all("device" not in r for r in recs)
    assert lens.pulse_stats()["enqueued"] == 0


def test_reaper_releases_result_refs_after_drain(fresh_lens):
    """The reaper must not pin result buffers past the drain: locals
    surviving into its idle wait would hold dead arrays for the poll
    interval and make live-arrays memory accounting flicker
    (regression: profiler.device_memory interference)."""
    import gc
    import jax

    def live_big():
        gc.collect()
        return sum(x.nbytes for x in jax.live_arrays()
                   if x.nbytes >= 256 * 256 * 4)

    lens.pulse_drain(10.0)
    base = live_big()
    a = mx.nd.ones((256, 256))
    for _ in range(4):
        b = (a * 2.0) + 1.0
        b.asnumpy()
    assert lens.pulse_drain(10.0)
    del b
    grew = live_big() - base
    assert grew == 256 * 256 * 4, \
        "reaper pinned dead result buffers: %+d bytes vs `a` alone" % grew


class _Boom(object):                        # kills the live reaper: its
    def wait(self, t):                      # next idle wake raises and
        raise SystemExit                    # the thread exits silently

    def clear(self):
        pass

    def set(self):
        pass

    def is_set(self):
        return True                         # suppress device_async wakes


def _kill_reaper():
    """Settle, then make the live reaper thread exit — the 'fork's
    child' scenario (dead inherited thread) without a real fork."""
    assert lens.pulse_drain(10.0)           # settle to a known-idle state
    dead = lens._pulse_thread[0]
    real_wake = lens._pulse_wake
    lens._pulse_wake = _Boom()
    try:
        dead.join(5.0)
        assert not dead.is_alive(), "reaper refused to die — test broken"
    finally:
        lens._pulse_wake = real_wake
    return dead


def test_pulse_drain_revives_dead_reaper_with_latched_busy(fresh_lens):
    """A fork mid-batch leaves the child an empty queue, a DEAD reaper
    thread, and _pulse_busy latched True: pulse_drain must still start
    a fresh reaper (whose first empty pop clears the flag) instead of
    burning its whole timeout on a flag nobody will ever reset."""
    dead = _kill_reaper()
    lens._pulse_busy[0] = True              # "it died mid-batch"
    t0 = time.perf_counter()
    assert lens.pulse_drain(5.0), \
        "drain burned its timeout on the latched busy flag"
    assert time.perf_counter() - t0 < 2.0
    assert lens._pulse_busy[0] is False
    assert lens._pulse_thread[0] is not dead    # a fresh reaper took over


def test_ensure_reaper_spawns_exactly_one_under_concurrency(fresh_lens):
    """Two threads' FIRST concurrent enqueues both see no live reaper:
    the spawn must serialize to ONE thread — two loops fighting over
    _pulse_busy let pulse_drain return while spans are still unbooked."""
    import threading
    _kill_reaper()
    start = threading.Barrier(8)

    def hit():
        start.wait()
        lens._ensure_reaper()

    ts = [threading.Thread(target=hit) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(5.0)
    alive = [t for t in threading.enumerate()
             if t.name == "graft-pulse-reaper" and t.is_alive()]
    assert len(alive) == 1, "%d reaper loops running" % len(alive)


# ---------------------------------------------------------------------------
# the memory timeline (tentpole)
# ---------------------------------------------------------------------------

def test_memory_timeline_sites_and_step_field(fresh_lens):
    """Injected sampler (host CPU reports no allocator counters): flush
    boundaries and fused buckets sample per-site watermarks; the step
    record carries the window's peak + per-site peaks; the gauges
    publish."""
    counter = [0]

    def sampler():
        counter[0] += 1000
        return counter[0], counter[0] + 10

    lens.set_mem_sampler(sampler)
    ps = _build_params(4, prefix="mm")
    trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                            kvstore=mx.kv.create("local"))
    for _ in range(3):
        _train_step(ps, trainer, bulk=True)
    recs = lens.steps()
    steady = recs[-1]
    mem = steady.get("mem")
    assert mem is not None
    sites = mem["sites"]
    assert any(s.startswith("flush:") for s in sites)
    assert any(s.startswith("bucket[") for s in sites)
    assert mem["peak_bytes"] == max(sites.values())
    # the timeline ring + summary aggregate the same stream
    summ = lens.mem_summary()
    assert set(sites) <= set(summ)
    for s in summ.values():
        assert s["samples"] >= 1 and s["peak_bytes"] > 0
    # gauges: one series per site
    from incubator_mxnet_tpu import telemetry
    snap = telemetry.registry().snapshot()
    fam = snap.get("graft_mem_peak_bytes")
    assert fam is not None
    gauge_sites = {t["labels"]["site"] for t in fam["samples"]}
    assert set(sites) <= gauge_sites


def test_memory_sampler_auto_disables_without_allocator(fresh_lens):
    """On backends with no allocator counters (host CPU) the default
    sampler latches off after ONE probe — per-flush cost stays nil."""
    lens.reset_mem()
    assert lens.mem_sample("probe") is None
    assert lens._mem_auto_dead[0] is True
    # an explicit sampler re-arms
    lens.set_mem_sampler(lambda: (1, 2))
    assert lens.mem_sample("probe2") == (1, 2)


def test_mem_compact_embeds_peak(fresh_lens):
    lens.set_mem_sampler(lambda: (5, 7))
    lens.mem_sample("x")
    rec = lens.step_end("test")
    # peak_bytes is the LIVE-bytes watermark (site attribution basis);
    # the raw allocator peak rides along separately
    assert rec["mem"]["peak_bytes"] == 5
    assert rec["mem"]["alloc_peak_bytes"] == 7
    assert lens.compact(rec)["mem_peak_bytes"] == 5


def test_mem_sites_differentiate_under_lifetime_allocator_peak(fresh_lens):
    """Real allocators report a process-lifetime peak that never resets:
    once the global peak is hit, keying sites off it would tie every
    site to one constant.  Attribution must track LIVE bytes per site —
    the planner's 'which bucket drives the footprint' signal."""
    samples = [(8000, 8000),    # the early global peak, site a
               (1000, 8000),    # site b: low live bytes, stale peak
               (3000, 8000)]    # site c
    it = iter(samples)
    lens.set_mem_sampler(lambda: next(it))
    lens.mem_sample("a")
    lens.mem_sample("b")
    lens.mem_sample("c")
    rec = lens.step_end("test")
    assert rec["mem"]["sites"] == {"a": 8000, "b": 1000, "c": 3000}
    assert rec["mem"]["peak_bytes"] == 8000
    assert rec["mem"]["alloc_peak_bytes"] == 8000
    summ = lens.mem_summary()
    assert summ["b"]["peak_bytes"] == 1000
    assert summ["b"]["alloc_peak_bytes"] == 8000


# ---------------------------------------------------------------------------
# profiler-trace ingestion (the callback-less fallback)
# ---------------------------------------------------------------------------

def test_ingest_xla_unions_overlapping_device_spans(tmp_path):
    """Synthetic chrome trace: overlapping device spans must UNION per
    step (never sum), busy + idle == wall per row, unstamped device
    spans pool separately, host spans are ignored."""
    us = 1e6
    events = [
        {"ph": "M", "name": "process_name", "pid": "d0",
         "args": {"name": "TPU:0 device stream"}},
        # step 1: two overlapping spans 0-10ms and 5-15ms -> 15ms busy
        {"ph": "X", "name": "op", "pid": "d0", "tid": 1,
         "ts": 0.000 * us, "dur": 0.010 * us, "args": {"step": 1}},
        {"ph": "X", "name": "op", "pid": "d0", "tid": 1,
         "ts": 0.005 * us, "dur": 0.010 * us, "args": {"step": 1}},
        # step 2: one span 20-25ms; window = prev end (15ms) -> 25ms
        {"ph": "X", "name": "op", "pid": "d0", "tid": 1,
         "ts": 0.020 * us, "dur": 0.005 * us, "args": {"step": 2}},
        # our own sync-mode flush span (host pid, device_time arg)
        {"ph": "X", "name": "bulk_segment_flush", "pid": 77, "tid": 2,
         "ts": 0.030 * us, "dur": 0.002 * us,
         "args": {"device_time": True}},
        # host span: ignored
        {"ph": "X", "name": "host", "pid": 77, "tid": 2,
         "ts": 0.000 * us, "dur": 0.050 * us, "args": {}},
    ]
    path = tmp_path / "trace.json"
    path.write_text(json.dumps({"traceEvents": events}))
    report = aggregate.ingest_xla(str(path))
    assert report["problems"] == []
    assert report["device_events"] == 4
    rows = {r["step"]: r for r in report["steps"]}
    assert rows[1]["busy_s"] == pytest.approx(0.015)
    assert rows[1]["wall_s"] == pytest.approx(0.015)
    assert rows[2]["busy_s"] == pytest.approx(0.005)
    assert rows[2]["wall_s"] == pytest.approx(0.010)   # 15ms -> 25ms
    for r in report["steps"]:
        assert r["busy_s"] + r["idle_s"] == pytest.approx(r["wall_s"])
    assert rows[None]["spans"] == 1                    # the flush span


def test_ingest_xla_total_is_span_union_not_row_sum(tmp_path):
    """Unstamped spans pool into a None row whose window OVERLAPS the
    stamped rows' chained windows: the total must be the union over all
    device spans, not the sum of row walls (which would double the wall
    and halve the headline busy_fraction)."""
    us = 1e6
    path = tmp_path / "u.json"
    path.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 0.000 * us, "dur": 0.010 * us, "args": {"step": 1}},
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 0.020 * us, "dur": 0.005 * us, "args": {"step": 2}},
        # unstamped span covering the WHOLE capture
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 0.000 * us, "dur": 0.030 * us, "args": {}}]}))
    report = aggregate.ingest_xla(str(path))
    assert report["total"]["wall_s"] == pytest.approx(0.030)
    assert report["total"]["busy_s"] == pytest.approx(0.030)
    assert report["total"]["busy_fraction"] == pytest.approx(1.0)


def test_ingest_xla_flags_non_monotonic_step_ids(tmp_path):
    """A restarted step counter (or merged captures) puts a low step id
    LATE in time: id-order window chaining clamps its successors' wall
    to 0 — the report must say so in problems[], not zero silently."""
    us = 1e6
    path = tmp_path / "nm.json"
    path.write_text(json.dumps({"traceEvents": [
        # step 5 runs first in time, step 1 (restarted counter) after —
        # id order chains step 5's window start past its own spans
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 0.000 * us, "dur": 0.010 * us, "args": {"step": 5}},
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 0.100 * us, "dur": 0.010 * us, "args": {"step": 1}}]}))
    report = aggregate.ingest_xla(str(path))
    rows = {r["step"]: r for r in report["steps"]}
    assert rows[5]["wall_s"] == 0.0                 # the clamped row
    assert any("not time-monotonic" in p for p in report["problems"])


def test_ingest_xla_cli(tmp_path, capsys):
    from incubator_mxnet_tpu.telemetry.__main__ import main as tmain
    us = 1e6
    path = tmp_path / "t.json"
    path.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 0, "dur": 0.004 * us, "args": {"step": 1}}]}))
    rc = tmain(["--ingest-xla", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "device-ledger ingestion" in out
    assert "1" in out
    # external traces stamp steps as strings: "2" must pool with 2 and
    # a non-numeric stamp must sort, not TypeError against ints
    path3 = tmp_path / "m.json"
    path3.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 0, "dur": 1000, "args": {"step": 2}},
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 2000, "dur": 1000, "args": {"step": "2"}},
        {"ph": "X", "name": "op", "pid": "/device:TPU:0", "tid": 1,
         "ts": 4000, "dur": 1000, "args": {"step": "warmup"}}]}))
    report = aggregate.ingest_xla(str(path3))
    assert [r["step"] for r in report["steps"]] == [2, "warmup"]
    assert report["steps"][0]["spans"] == 2
    # empty trace: rc 1 + a problem line
    path2 = tmp_path / "e.json"
    path2.write_text(json.dumps({"traceEvents": []}))
    assert tmain(["--ingest-xla", str(path2)]) == 1


# ---------------------------------------------------------------------------
# the autotuner (tentpole)
# ---------------------------------------------------------------------------

def _fake_rec(step, wall=0.1, data_wait=0.0, blocked=0.0, inflight=0.0):
    comp = {c: 0.0 for c in lens.COMPONENTS}
    comp["data_wait"] = data_wait
    comp["host_gap"] = wall - data_wait
    return {"step": step, "origin": "trainer", "wall_s": wall,
            "components": comp, "comm_blocked_s": blocked,
            "comm_inflight_s": inflight, "collectives": 0, "io_waits": 0}


def test_autotune_off_by_default_is_inert():
    """GRAFT_AUTOTUNE unset: the observer returns immediately — no
    decisions, no knob movement (bit-identity with today)."""
    assert not autotune.enabled()
    ctrl = autotune.Autotuner(interval=2)
    before = os.environ.get("GRAFT_BUCKET_BYTES")
    for i in range(8):
        ctrl.on_step(_fake_rec(i, data_wait=0.09, blocked=0.05,
                               inflight=0.05))
    assert ctrl.decisions() == []
    assert os.environ.get("GRAFT_BUCKET_BYTES") == before


def test_autotune_grows_starved_loader_and_journals(fresh_lens):
    """The worker-growth loop on a real (tiny) starved DataLoader: a
    high data_wait window grows workers, the decision lands in the
    flight-recorder ring, and the cooldown holds the next move back."""
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import Dataset

    class Slow(Dataset):
        def __len__(self):
            return 64

        def __getitem__(self, idx):
            time.sleep(0.001)
            return np.zeros((2,), np.float32)

    loader = DataLoader(Slow(), batch_size=2, num_workers=1,
                        prefetch_device=False)
    autotune.set_enabled(True)
    ctrl = autotune.Autotuner(interval=2, cooldown=2, data_wait_bound=0.2,
                              max_workers=4)
    try:
        ctrl.attach_loader(loader)
        marker = time.time()
        for i in range(2):
            ctrl.on_step(_fake_rec(i, data_wait=0.06))
        assert loader._num_workers == 2
        grows = [d for d in ctrl.decisions()
                 if d["target"] == "dataloader_workers"]
        assert grows == [dict(signal="data_wait",
                              target="dataloader_workers", old=1, new=2,
                              cooldown_windows=2,
                              data_wait_fraction=0.6)]
        # journaled as a blackbox event
        evs = [e for e in blackbox.events()
               if e.get("kind") == "autotune_decision"
               and e.get("ts", 0) >= marker]
        assert evs
        assert evs[-1]["data"]["old"] == 1
        assert evs[-1]["data"]["new"] == 2
        assert evs[-1]["data"]["signal"] == "data_wait"
        # cooldown: the very next starved window must NOT move the knob
        for i in range(2, 4):
            ctrl.on_step(_fake_rec(i, data_wait=0.06))
        assert loader._num_workers == 2
        # ... but after the cooldown expires it does
        for i in range(4, 8):
            ctrl.on_step(_fake_rec(i, data_wait=0.06))
        assert loader._num_workers == 4
    finally:
        autotune.set_enabled(None)
        loader.close()


def test_autotune_bucket_bytes_hill_climb(monkeypatch):
    """A sagging comm_hidden_ratio shrinks GRAFT_BUCKET_BYTES (the
    earlier-issue direction first); a move that makes the ratio WORSE
    flips direction on the next decision."""
    monkeypatch.setenv("GRAFT_BUCKET_BYTES", str(4 << 20))
    autotune.set_enabled(True)
    try:
        ctrl = autotune.Autotuner(interval=1, cooldown=0,
                                  comm_hidden_bound=0.6,
                                  min_bucket_bytes=1 << 20,
                                  max_bucket_bytes=16 << 20)
        ps = _build_params(2, prefix="ab")
        trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                                kvstore=mx.kv.create("local"))
        ctrl.attach_trainer(trainer)
        # window 1: hidden = 1 - 0.08/0.10 = 0.2 < 0.6 -> shrink
        ctrl.on_step(_fake_rec(1, blocked=0.08, inflight=0.10))
        assert os.environ["GRAFT_BUCKET_BYTES"] == str(2 << 20)
        # window 2: ratio got WORSE (0.1) -> direction flips to grow
        ctrl.on_step(_fake_rec(2, blocked=0.09, inflight=0.10))
        assert os.environ["GRAFT_BUCKET_BYTES"] == str(4 << 20)
        moves = [d for d in ctrl.decisions()
                 if d["target"] == "bucket_bytes"]
        assert [(-(-d["old"] // d["new"]) if d["old"] > d["new"]
                 else d["new"] // d["old"]) for d in moves] == [2, 2]
    finally:
        autotune.set_enabled(None)


def test_pulse_env_memo_tracks_value_changes(fresh_lens, monkeypatch):
    """The hot-path env flags are memoized keyed on the RAW string:
    parsing must not run per eager dispatch, but setting the variable
    mid-process must still take effect immediately."""
    lens.set_pulse(None)
    monkeypatch.delenv("GRAFT_PULSE", raising=False)
    assert lens.pulse_enabled()
    monkeypatch.setenv("GRAFT_PULSE", "0")
    assert not lens.pulse_enabled()
    monkeypatch.setenv("GRAFT_PULSE", "1")
    assert lens.pulse_enabled()
    lens.set_enabled(None)      # overrides win: drop to the env path
    monkeypatch.setenv("GRAFT_LENS", "off")
    assert not lens.enabled()
    monkeypatch.delenv("GRAFT_LENS", raising=False)
    assert lens.enabled()
    lens.set_enabled(True)      # the fixture's state, for teardown


def test_autotune_ignores_non_train_windows():
    """A train+serve process streams serving windows (origin
    "serve_batch", data_wait 0, foreign wall) through the same observer:
    they must not enter decision windows — diluted, data_frac would
    never cross the bound while the DataLoader starves."""
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import Dataset

    class Tiny(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, idx):
            return np.zeros((2,), np.float32)

    loader = DataLoader(Tiny(), batch_size=2, num_workers=1,
                        prefetch_device=False)
    autotune.set_enabled(True)
    try:
        ctrl = autotune.Autotuner(interval=2, cooldown=0,
                                  data_wait_bound=0.2, max_workers=4)
        ctrl.attach_loader(loader)
        for i in range(6):      # serving windows: big wall, no data_wait
            ctrl.on_step(dict(_fake_rec(i, wall=1.0),
                              origin="serve_batch"))
        assert ctrl.decisions() == []       # never even formed a window
        for i in range(2):      # the starved TRAIN windows
            ctrl.on_step(_fake_rec(i, data_wait=0.06))
        assert loader._num_workers == 2
        assert len(ctrl.decisions()) == 1
    finally:
        autotune.set_enabled(None)
        loader.close()


def test_loader_grown_from_zero_workers_switches_mid_epoch(fresh_lens):
    """num_workers=0 picks the synchronous path at generator start: a
    live set_num_workers mid-epoch must switch the OPEN iterator to the
    pooled pipeline (not silently no-op until next epoch — the
    autotuner would walk the knob to its cap on zero feedback)."""
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import Dataset

    class Idx(Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, idx):
            return np.full((2,), float(idx), np.float32)

    loader = DataLoader(Idx(), batch_size=2, num_workers=0,
                        prefetch_device=False)
    try:
        got = []
        it = iter(loader)
        for _ in range(3):
            got.append(next(it))
        assert loader._pool is None             # still the sync path
        loader.set_num_workers(2)
        for b in it:
            got.append(b)
        assert loader._pool is not None, \
            "grow from 0 never engaged the pooled pipeline mid-epoch"
        # every batch delivered exactly once, in order
        flat = np.concatenate([np.asarray(b.asnumpy()).ravel()
                               for b in got])
        assert flat.tolist() == [float(v) for v in range(16)
                                 for _ in (0, 1)]
    finally:
        loader.close()


def test_autotune_grows_the_loader_the_consumer_blocked_on():
    """Two registered loaders, the fast one registered FIRST: the grow
    decision must rank by each loader's blocked-wait delta and grow the
    one the consumer actually stalled on, not walk the fast loader to
    the cap in registration order."""
    from incubator_mxnet_tpu.gluon.data import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import Dataset

    class Tiny(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, idx):
            return np.zeros((2,), np.float32)

    fast = DataLoader(Tiny(), batch_size=2, num_workers=1,
                      prefetch_device=False)
    slow = DataLoader(Tiny(), batch_size=2, num_workers=1,
                      prefetch_device=False)
    autotune.set_enabled(True)
    try:
        ctrl = autotune.Autotuner(interval=2, cooldown=0,
                                  data_wait_bound=0.2, max_workers=4)
        ctrl.attach_loader(fast)            # registration order: fast first
        ctrl.attach_loader(slow)
        fast._blocked_wait_s = 0.01
        slow._blocked_wait_s = 0.50         # the consumer stalled HERE
        for i in range(2):
            ctrl.on_step(_fake_rec(i, data_wait=0.06))
        assert slow._num_workers == 2
        assert fast._num_workers == 1
    finally:
        autotune.set_enabled(None)
        fast.close()
        slow.close()


def test_autotune_bucket_moves_rank0_proposes_multi_rank(monkeypatch):
    """Per-rank bucket moves diverge the collective stream (different
    plans -> mispaired wire -> lockstep fires on a healthy job): under
    multi-rank the knob is rank-0-decides.  Non-zero ranks observe
    only; rank 0 never flips the env directly either — it PARKS the
    move in the dist mailbox for the heartbeat broadcast, and every
    rank applies it via apply_bucket_bytes_broadcast when it lands."""
    import jax

    from incubator_mxnet_tpu.parallel import dist
    monkeypatch.setenv("GRAFT_BUCKET_BYTES", str(4 << 20))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    autotune.set_enabled(True)
    try:
        dist._take_bucket_proposal()        # drain any stale mailbox
        ps = _build_params(2, prefix="mr")
        trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                                kvstore=mx.kv.create("local"))
        # --- a NON-ZERO rank: fully inert ------------------------------
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        ctrl = autotune.Autotuner(interval=1, cooldown=0,
                                  comm_hidden_bound=0.6,
                                  min_bucket_bytes=1 << 20,
                                  max_bucket_bytes=16 << 20)
        ctrl.attach_trainer(trainer)
        ctrl.on_step(_fake_rec(1, blocked=0.08, inflight=0.10))
        assert os.environ["GRAFT_BUCKET_BYTES"] == str(4 << 20)
        assert ctrl.decisions() == []
        assert dist._take_bucket_proposal() == 0
        # --- rank 0: proposes via the mailbox, does NOT flip the env ---
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        ctrl = autotune.Autotuner(interval=1, cooldown=0,
                                  comm_hidden_bound=0.6,
                                  min_bucket_bytes=1 << 20,
                                  max_bucket_bytes=16 << 20)
        ctrl.attach_trainer(trainer)
        ctrl.on_step(_fake_rec(1, blocked=0.08, inflight=0.10))
        assert os.environ["GRAFT_BUCKET_BYTES"] == str(4 << 20)
        moves = [d for d in ctrl.decisions()
                 if d["target"] == "bucket_bytes"]
        assert len(moves) == 1
        assert moves[0]["old"] == 4 << 20 and moves[0]["new"] == 2 << 20
        assert moves[0]["broadcast"] == "proposed"
        assert dist._take_bucket_proposal() == 2 << 20
        # --- the landing: every rank flips on the SAME heartbeat -------
        assert autotune.apply_bucket_bytes_broadcast(2 << 20) is True
        assert os.environ["GRAFT_BUCKET_BYTES"] == str(2 << 20)
        # idempotent: the same value landing again is a no-op
        assert autotune.apply_bucket_bytes_broadcast(2 << 20) is False
        assert autotune.apply_bucket_bytes_broadcast(0) is False
    finally:
        autotune.set_enabled(None)


class _FakeBatcher(object):
    """The five knob methods serving.DynamicBatcher exposes."""

    def __init__(self, max_batch=8, wait_ms=4.0):
        self._mb = int(max_batch)
        self._wait = float(wait_ms)
        self._base = float(wait_ms)

    def max_batch(self):
        return self._mb

    def set_max_batch(self, n):
        self._mb = int(n)

    def max_wait_ms(self):
        return self._wait

    def configured_max_wait_ms(self):
        return self._base

    def set_max_wait_ms(self, ms):
        self._wait = float(ms)


def _seed_slo_ring(queue_wait_s, n=8):
    from incubator_mxnet_tpu.serving import slo
    slo.reset()
    for _ in range(n):
        slo.record_request("m", 1, queue_wait_s + 0.002,
                           {"queue_wait": queue_wait_s,
                            "batch_assembly": 0.0005,
                            "device_compute": 0.001,
                            "host_io": 0.0005}, 4, 8)


def test_autotune_serving_knob_grow_cap_squeeze_relax():
    """The serving knob end-to-end on the SLO ring's p99 queue_wait:
    a hot queue doubles max_batch; at the cap it halves max-wait; a
    cold queue relaxes max-wait back toward (never past) the
    configured value."""
    from incubator_mxnet_tpu.serving import slo
    autotune.set_enabled(True)
    try:
        ctrl = autotune.Autotuner(interval=1, cooldown=1,
                                  serve_qw_ms=5.0, max_serve_batch=16)
        b = _FakeBatcher(max_batch=8, wait_ms=4.0)
        ctrl.attach_batcher(b)
        serve = dict(_fake_rec(0, wall=0.02), origin="serve_batch")
        # hot queue (p99 20ms >> 5ms): grow max_batch 8 -> 16 (the cap)
        _seed_slo_ring(0.020)
        ctrl.on_step(serve)
        assert b.max_batch() == 16
        assert b.max_wait_ms() == 4.0
        # still hot, at the cap: halve max-wait instead, 4 -> 2 -> 1
        ctrl.on_step(serve)
        assert b.max_batch() == 16 and b.max_wait_ms() == 2.0
        ctrl.on_step(serve)
        assert b.max_wait_ms() == 1.0
        # cold queue (p99 0.5ms < bound/4): relax back toward the
        # configured 4ms, never past it
        _seed_slo_ring(0.0005)
        ctrl.on_step(serve)
        assert b.max_wait_ms() == 2.0
        ctrl.on_step(serve)
        assert b.max_wait_ms() == 4.0
        ctrl.on_step(serve)
        assert b.max_wait_ms() == 4.0       # at the configured value
        targets = [d["target"] for d in ctrl.decisions()]
        assert targets == ["serve_max_batch", "serve_max_wait_ms",
                           "serve_max_wait_ms", "serve_max_wait_ms",
                           "serve_max_wait_ms"]
        assert all(d["signal"] == "serve_queue_wait"
                   for d in ctrl.decisions())
    finally:
        autotune.set_enabled(None)
        slo.reset()


def test_autotune_serving_cooldown_ticks_on_serve_cadence():
    """A serve-only process has no train windows: the serving cooldown
    must still tick (and hold moves back) on the serve-window cadence
    itself."""
    from incubator_mxnet_tpu.serving import slo
    autotune.set_enabled(True)
    try:
        ctrl = autotune.Autotuner(interval=1, cooldown=3,
                                  serve_qw_ms=5.0, max_serve_batch=64)
        b = _FakeBatcher(max_batch=4, wait_ms=4.0)
        ctrl.attach_batcher(b)
        serve = dict(_fake_rec(0, wall=0.02), origin="serve_batch")
        _seed_slo_ring(0.020)
        ctrl.on_step(serve)                 # move: 4 -> 8, cooldown 3
        assert b.max_batch() == 8
        ctrl.on_step(serve)                 # cooldown holds (3 -> 2)
        ctrl.on_step(serve)                 # cooldown holds (2 -> 1)
        assert b.max_batch() == 8
        ctrl.on_step(serve)                 # expired: 8 -> 16
        assert b.max_batch() == 16
        assert len(ctrl.decisions()) == 2
    finally:
        autotune.set_enabled(None)
        slo.reset()


def test_autotune_validated_move_does_not_flip_on_later_sag(monkeypatch):
    """A bucket move that RECOVERS the ratio above the bound settles its
    hill-climb evaluation on that first post-move window — a stale
    pending flag must not judge an unrelated sag many windows later
    against the old ratio and walk the knob away from the validated
    setting."""
    monkeypatch.setenv("GRAFT_BUCKET_BYTES", str(4 << 20))
    autotune.set_enabled(True)
    try:
        ctrl = autotune.Autotuner(interval=1, cooldown=0,
                                  comm_hidden_bound=0.6,
                                  min_bucket_bytes=1 << 20,
                                  max_bucket_bytes=16 << 20)
        ps = _build_params(2, prefix="nf")
        trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                                kvstore=mx.kv.create("local"))
        ctrl.attach_trainer(trainer)
        # window 1: hidden = 0.4 < 0.6 -> shrink (move pending @ 0.4)
        ctrl.on_step(_fake_rec(1, blocked=0.06, inflight=0.10))
        assert os.environ["GRAFT_BUCKET_BYTES"] == str(2 << 20)
        # window 2: the shrink WORKED (0.8 >= bound): the pending move
        # must settle here, direction stays shrink, no new move
        ctrl.on_step(_fake_rec(2, blocked=0.02, inflight=0.10))
        assert os.environ["GRAFT_BUCKET_BYTES"] == str(2 << 20)
        # window 3: an unrelated later sag (0.35 — below the STALE 0.4)
        # must hill-climb in the established direction (shrink), not
        # flip to grow against the long-settled move
        ctrl.on_step(_fake_rec(3, blocked=0.065, inflight=0.10))
        assert os.environ["GRAFT_BUCKET_BYTES"] == str(1 << 20)
    finally:
        autotune.set_enabled(None)


def test_autotune_straggler_feed_repacks_bucket_order():
    """aggregate-style straggler rows feed the named bucket's lateness
    into the Trainer's packing tie-breaker and drop the plan cache so
    the next plan re-packs."""
    autotune.set_enabled(True)
    try:
        ctrl = autotune.Autotuner()
        ps = _build_params(4, prefix="st")
        trainer = gluon.Trainer(ps, "sgd", {"learning_rate": 0.01},
                                kvstore=mx.kv.create("local"))
        ctrl.attach_trainer(trainer)
        for _ in range(2):
            _train_step(ps, trainer, bulk=False)
        # a local kvstore takes the duplex (store-update) path, so the
        # plan lands in _duplex_plan_cache; the autotuner checks both
        cached = getattr(trainer, "_duplex_plan_cache", None) \
            or getattr(trainer, "_fused_plan_cache", None)
        assert cached is not None and cached[1] is not None
        plan = cached[1]
        label = trainer._sched_label(plan[0][0])
        matched = ctrl.feed_straggler_table(
            [{"label": label, "lateness_s": 0.25},
             {"label": "bucket[nonexistent]", "lateness_s": 1.0}])
        assert matched == 1
        assert trainer._duplex_plan_cache is None
        assert trainer._fused_plan_cache is None
        for i in plan[0][0].indices:
            assert trainer._bucket_lateness[i] > 0.0
        assert any(d["target"] == "bucket_order"
                   for d in ctrl.decisions())
        # the next step rebuilds the plan and still trains
        _train_step(ps, trainer, bulk=False)
    finally:
        autotune.set_enabled(None)


def test_autotune_selftest_converges():
    """The lint-tier scenario end-to-end: starved loader -> the
    controller grows workers until data_wait sinks below the bound."""
    problems = autotune.selftest(max_steps=60)
    assert problems == [], problems


# ---------------------------------------------------------------------------
# lockstep online bisection (satellite)
# ---------------------------------------------------------------------------

def test_lockstep_pins_skipped_collective_online():
    """A rank that SKIPS one mid-stream collective is not just named —
    the lagged-prefix points bracket the divergence to adjacent folds
    and the report pins the exact collective from the local table."""
    from incubator_mxnet_tpu.analysis import lockstep as ls
    ls.reset()
    ls.set_enabled(True)
    try:
        def digest(i):
            return ls._crc("reduce_many|1|%d|%d"
                           % (4096 + i, ls.keys_digest(["k%d" % i])))

        for i in range(1, 11):
            ls.fold(i, "reduce_many", n_keys=1, nbytes=4096 + i,
                    keys=["k%d" % i])
        # simulate the peer's stream: identical minus collective #5
        rolling, foldn, points = 0, 0, []
        for i in [1, 2, 3, 4, 6, 7, 8, 9, 10]:
            foldn += 1
            rolling = (rolling * 1000003 + digest(i) + foldn) & 0x7fffffff
            points.append((foldn, rolling))
        report = None
        for k, head in enumerate(points):
            lagp = points[k - 2] if k >= 2 else (0, 0)
            report = ls.observe({1: (head[0], head[1],
                                     lagp[0], lagp[1])}, my_rank=0)
            if report:
                break
        assert report is not None
        assert report["pinned"] is True
        assert report["first_divergent_fold"] == 5
        assert report["last_matching_fold"] == 4
        assert report["divergent_ranks"] == [1]
        c = report["divergent_collective"]
        assert c["path"] == "reduce_many" and c["nbytes"] == 4096 + 5
        # latched: later heartbeats do not re-report
        assert ls.observe({1: points[-1] + (0, 0)}, my_rank=0) is None
        assert ls.divergence()["pinned"] is True
    finally:
        ls.reset()
        ls.set_enabled(None)


def test_lockstep_state_lagged_pairs():
    from incubator_mxnet_tpu.analysis import lockstep as ls
    ls.reset()
    ls.set_enabled(True)
    try:
        # shorter than the lag: lag half ships (0, 0)
        ls.fold(1, "reduce_many", n_keys=1, nbytes=1, keys=["a"])
        f, h, lf, lh = ls.state_lagged()
        assert (f, lf, lh) == (1, 0, 0) and h != 0
        for i in range(2, 12):
            ls.fold(i, "reduce_many", n_keys=1, nbytes=i, keys=["a"])
        f, h, lf, lh = ls.state_lagged()
        assert f == 11 and lf == 11 - ls.lag()
        rows = {r["fold"]: r["rolling"] for r in ls.table()}
        assert lh == rows[lf]
        # a healthy laggard (peer = our own lagged prefix) never reports
        assert ls.observe({1: (lf, lh)}, my_rank=0) is None
    finally:
        ls.reset()
        ls.set_enabled(None)
