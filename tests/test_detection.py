"""Deformable conv / PSROI pooling / detection pipeline tests.

Parity models: reference tests for contrib ops
(tests/python/gpu/test_operator_gpu.py test_deformable_convolution,
test_psroipooling) and python/mxnet/image/detection.py usage in the SSD
example (SSD-shaped train step = VERDICT #7 Done criterion).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_deformable_conv_zero_offset_matches_conv():
    """With zero offsets, deformable conv == plain conv."""
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 8, 8).astype(np.float32)
    w = rng.randn(6, 4, 3, 3).astype(np.float32)
    b = rng.randn(6).astype(np.float32)
    off = np.zeros((2, 2 * 9, 8, 8), np.float32)
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), nd.array(b),
        kernel=(3, 3), num_filter=6, pad=(1, 1))
    ref = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), num_filter=6, pad=(1, 1))
    assert_almost_equal(out.asnumpy(), ref.asnumpy(), rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_offset_shifts():
    """Integer offset (0, 1) samples one pixel right — equals conv on the
    shifted image (interior pixels)."""
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    w = rng.randn(3, 2, 1, 1).astype(np.float32)
    off = np.zeros((1, 2, 6, 6), np.float32)
    off[:, 1] = 1.0   # x-offset +1 for the single 1x1 tap
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w),
        kernel=(1, 1), num_filter=3, no_bias=True)
    shifted = np.zeros_like(x)
    shifted[..., :-1] = x[..., 1:]
    ref = nd.Convolution(nd.array(shifted), nd.array(w), kernel=(1, 1),
                         num_filter=3, no_bias=True)
    assert_almost_equal(out.asnumpy()[..., :-1], ref.asnumpy()[..., :-1],
                        rtol=1e-4, atol=1e-4)


def test_deformable_conv_trainable():
    """Gradients flow to data, offset and weight."""
    rng = np.random.RandomState(2)
    x = nd.array(rng.randn(1, 2, 5, 5).astype(np.float32))
    # k=2, pad=1 → output 6x6; offset carries 2·kh·kw channels over it
    off = nd.array(rng.randn(1, 2 * 4, 6, 6).astype(np.float32) * 0.1)
    w = nd.array(rng.randn(4, 2, 2, 2).astype(np.float32))
    for a in (x, off, w):
        a.attach_grad()
    with autograd.record():
        y = nd.contrib.DeformableConvolution(
            x, off, w, kernel=(2, 2), num_filter=4, pad=(1, 1),
            no_bias=True)
        loss = nd.sum(y * y)
    loss.backward()
    for a in (x, off, w):
        assert float(nd.norm(a.grad).asscalar()) > 0


def test_psroi_pooling():
    """Constant-per-channel data: each output bin returns its
    position-sensitive channel's value."""
    od, k = 2, 3
    C = od * k * k
    data = np.zeros((1, C, 12, 12), np.float32)
    for c in range(C):
        data[0, c] = c
    rois = np.array([[0, 0, 0, 11, 11]], np.float32)
    out = nd.contrib.PSROIPooling(nd.array(data), nd.array(rois),
                                  spatial_scale=1.0, output_dim=od,
                                  pooled_size=k)
    assert out.shape == (1, od, k, k)
    got = out.asnumpy()[0]
    for ct in range(od):
        for ph in range(k):
            for pw in range(k):
                expect = (ct * k + ph) * k + pw
                assert got[ct, ph, pw] == expect, (ct, ph, pw)


def test_deformable_psroi_pooling():
    od, k = 2, 2
    C = od * k * k
    rng = np.random.RandomState(3)
    data = rng.randn(1, C, 10, 10).astype(np.float32)
    rois = np.array([[0, 1, 1, 8, 8]], np.float32)
    trans = np.zeros((1, 2, k, k), np.float32)
    out = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), nd.array(trans),
        spatial_scale=1.0, output_dim=od, pooled_size=k, group_size=k,
        part_size=k, sample_per_part=2, trans_std=0.1)
    assert out.shape == (1, od, k, k)
    # no_trans variant matches zero-trans
    out2 = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois),
        spatial_scale=1.0, output_dim=od, pooled_size=k, group_size=k,
        part_size=k, sample_per_part=2, trans_std=0.1, no_trans=True)
    assert_almost_equal(out.asnumpy(), out2.asnumpy(), rtol=1e-5)


def _make_det_samples(tmp_path, n=6, size=32):
    cv2 = pytest.importorskip("cv2")
    import incubator_mxnet_tpu.recordio as recordio
    prefix = str(tmp_path / "det")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        # label: header [hw=2, ow=5], one object per image
        cls = float(i % 3)
        box = np.array([cls, 0.1, 0.2, 0.6, 0.7], np.float32)
        label = np.concatenate([[2, 5], box]).astype(np.float32)
        header = recordio.IRHeader(0, label, i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()
    return prefix


def test_image_det_iter(tmp_path):
    prefix = _make_det_samples(tmp_path)
    it = mx.image.ImageDetIter(batch_size=3, data_shape=(3, 16, 16),
                               path_imgrec=prefix + ".rec",
                               path_imgidx=prefix + ".idx")
    assert it.provide_label[0][1] == (3, 1, 5)
    batch = next(iter([it.next()]))
    assert batch.data[0].shape == (3, 3, 16, 16)
    lab = batch.label[0].asnumpy()
    assert lab.shape == (3, 1, 5)
    assert (lab[:, 0, 0] >= 0).all()          # class ids present
    assert (lab[:, 0, 3] > lab[:, 0, 1]).all()  # valid boxes


def test_det_augmenters_preserve_box_validity(tmp_path):
    prefix = _make_det_samples(tmp_path)
    it = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 24, 24),
                               path_imgrec=prefix + ".rec",
                               path_imgidx=prefix + ".idx",
                               rand_crop=0.8, rand_pad=0.8,
                               rand_mirror=True,
                               min_object_covered=0.5)
    for _ in range(3):
        it.reset()
        batch = it.next()
        lab = batch.label[0].asnumpy()
        valid = lab[lab[:, :, 0] >= 0]
        assert valid.size > 0
        assert (valid[:, 1:5] >= -1e-6).all() and (valid[:, 1:5] <= 1 + 1e-6).all()
        assert (valid[:, 3] > valid[:, 1]).all()


def test_ssd_shaped_train_step():
    """SSD-shaped forward+backward: conv features → MultiBoxPrior/Target →
    losses → gradients (VERDICT #7 Done criterion)."""
    rng = np.random.RandomState(4)
    B, nA = 2, 4
    x = nd.array(rng.randn(B, 3, 32, 32).astype(np.float32))
    w = nd.array((rng.randn(8, 3, 3, 3) * 0.1).astype(np.float32))
    wc = nd.array((rng.randn(nA * 4, 8, 3, 3) * 0.1).astype(np.float32))
    wl = nd.array((rng.randn(nA * 4, 8, 3, 3) * 0.1).astype(np.float32))
    labels = np.full((B, 2, 5), -1, np.float32)
    labels[:, 0] = [0, 0.1, 0.1, 0.5, 0.5]
    labels_nd = nd.array(labels)
    for a in (w, wc, wl):
        a.attach_grad()
    with autograd.record():
        feat = nd.Convolution(x, w, kernel=(3, 3), num_filter=8,
                              pad=(1, 1), stride=(2, 2), no_bias=True)
        anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.3, 0.6),
                                           ratios=(1.0, 2.0, 0.5))
        cls_pred = nd.Convolution(feat, wc, kernel=(3, 3),
                                  num_filter=nA * 4, pad=(1, 1),
                                  no_bias=True)
        cls_pred = nd.reshape(nd.transpose(cls_pred, axes=(0, 2, 3, 1)),
                              shape=(B, -1, 4))
        cls_pred = nd.transpose(cls_pred, axes=(0, 2, 1))
        loc_pred = nd.Convolution(feat, wl, kernel=(3, 3),
                                  num_filter=nA * 4, pad=(1, 1),
                                  no_bias=True)
        loc_pred = nd.reshape(nd.transpose(loc_pred, axes=(0, 2, 3, 1)),
                              shape=(B, -1))
        loc_target, loc_mask, cls_target = nd.contrib.MultiBoxTarget(
            anchors, labels_nd, cls_pred)
        loc_loss = nd.sum(nd.abs(loc_pred * loc_mask - loc_target))
        flat_pred = nd.reshape(nd.transpose(cls_pred, axes=(0, 2, 1)),
                               shape=(-1, 4))
        flat_target = nd.reshape(cls_target, shape=(-1,))
        cls_prob = nd.SoftmaxOutput(flat_pred, flat_target,
                                    normalization="valid")
        cls_loss = nd.sum(cls_prob)
        loss = loc_loss + cls_loss
    loss.backward()
    assert float(nd.norm(wl.grad).asscalar()) > 0
