"""graftarmor: fault injection, self-healing PS wire, atomic
checkpoint/auto-resume, typed hang escalation (PR 15).

Single-process coverage of the robustness layer; the 2-process chaos
parity and kill-rank gates live in test_dist_multiprocess.py.
"""
import os
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.armor import (CheckpointCorruptError,
                                       CollectiveTimeoutError,
                                       FaultInjectedError,
                                       PSUnavailableError, faults)
from incubator_mxnet_tpu.armor import checkpoint as ckpt

_ENV = ("GRAFT_FAULTS", "GRAFT_RPC_TIMEOUT", "GRAFT_RPC_RETRIES",
        "GRAFT_RPC_BACKOFF_MS", "GRAFT_WATCHDOG_ESCALATE",
        "GRAFT_SERVE_DEADLINE_MS")


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {k: os.environ.get(k) for k in _ENV}
    yield
    faults.reset()
    faults.set_rank(None)
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _fires(spec, site, n, **ctx):
    faults.configure(spec)
    out = []
    for _ in range(n):
        try:
            faults.fault_point(site, **ctx)
            out.append(False)
        except FaultInjectedError:
            out.append(True)
    return out


# -- fault grammar -----------------------------------------------------------

def test_fault_grammar_selectors():
    assert _fires("a.b:error:n=3", "a.b", 5) \
        == [False, False, True, False, False]
    assert _fires("a.*:error:every=2:times=2", "a.x", 8) \
        == [False, True, False, True, False, False, False, False]
    assert _fires("a.b:error", "a.b", 3) == [True] * 3  # bare: every arrival
    assert _fires("a.b:error:cmd=push", "a.b", 2, cmd="pull") == [False] * 2
    assert _fires("a.b:error:cmd=push", "a.b", 2, cmd="push") == [True] * 2


def test_fault_grammar_seeded_probability_replays():
    one = _fires("p.q:error:p=0.4:seed=11:times=100", "p.q", 30)
    two = _fires("p.q:error:p=0.4:seed=11:times=100", "p.q", 30)
    assert one == two and any(one) and not all(one)


def test_fault_grammar_rank_filter():
    faults.set_rank(1)
    assert _fires("r.s:error:rank=0", "r.s", 2) == [False, False]
    faults.set_rank(0)
    assert _fires("r.s:error:rank=0:n=1", "r.s", 2) == [True, False]


def test_fault_grammar_rejects_bad_specs():
    for bad in ("siteonly", "a.b:melt", "a.b:error:n"):
        with pytest.raises(ValueError):
            faults.configure(bad)


def test_faults_off_by_default_inert():
    faults.reset()
    assert faults.fault_point("anything", cmd="push") is None
    assert faults.active_rules() == []


# -- self-healing PS wire ----------------------------------------------------

@pytest.fixture()
def ps_pair():
    from incubator_mxnet_tpu.parallel import ps
    os.environ["GRAFT_RPC_TIMEOUT"] = "10"
    os.environ["GRAFT_RPC_RETRIES"] = "2"
    os.environ["GRAFT_RPC_BACKOFF_MS"] = "1"
    srv = ps.ParameterServer(host="127.0.0.1")
    client = ps.PSClient(srv.address)
    yield srv, client
    faults.reset()
    client.close()
    srv.shutdown()


def test_ps_retry_after_dropped_reply_is_idempotent(ps_pair):
    _, client = ps_pair
    client.init({"w": np.zeros(4, np.float32)})
    # the reply to an APPLIED push is dropped: the retried request must
    # be deduplicated server-side (same monotonic id), not applied twice
    faults.configure("ps.recv:drop:n=1:cmd=push")
    client.push({"w": np.ones(4, np.float32)})
    assert float(client.pull(["w"])["w"][0]) == 1.0


def test_ps_reconnects_across_injected_disconnect(ps_pair):
    _, client = ps_pair
    client.init({"w": np.zeros(4, np.float32)})
    faults.configure("ps.send:disconnect:n=1:cmd=push")
    client.push({"w": np.ones(4, np.float32)})
    assert float(client.pull(["w"])["w"][0]) == 1.0


def test_ps_gives_up_with_typed_error(ps_pair):
    _, client = ps_pair
    client.init({"w": np.zeros(4, np.float32)})
    faults.configure("ps.send:error:every=1:cmd=push")
    with pytest.raises(PSUnavailableError) as ei:
        client.push({"w": np.ones(4, np.float32)})
    assert ei.value.cmd == "push"
    assert ei.value.attempts == 3          # 1 try + GRAFT_RPC_RETRIES=2
    faults.reset()
    # the wire heals once the chaos stops
    client.push({"w": np.ones(4, np.float32)})
    assert float(client.pull(["w"])["w"][0]) == 1.0


def test_ps_closed_client_fails_fast(ps_pair):
    _, client = ps_pair
    client.init({"w": np.zeros(4, np.float32)})
    client.close()
    with pytest.raises(PSUnavailableError):
        client.push({"w": np.ones(4, np.float32)})


# -- atomic checkpoint -------------------------------------------------------

def test_save_state_roundtrip_and_manifest(tmp_path):
    path = str(tmp_path / "snap.armor")
    state = {"step": 7, "params": {"w": np.arange(6, dtype=np.float32)}}
    ckpt.save_state(path, state)
    man = ckpt.manifest_of(path)
    assert man["format"] == ckpt.FORMAT and man["step"] == 7
    got = ckpt.load_state(path)
    assert got["step"] == 7
    assert np.array_equal(got["params"]["w"], state["params"]["w"])
    assert not [p for p in os.listdir(str(tmp_path)) if ".tmp" in p]


def test_load_state_rejects_every_corruption(tmp_path):
    path = str(tmp_path / "snap.armor")
    ckpt.save_state(path, {"step": 1})
    raw = open(path, "rb").read()
    cases = {
        "flipped payload byte": raw[:-2] + bytes([raw[-2] ^ 0xFF]) + raw[-1:],
        "truncated": raw[: len(raw) // 2],
        "bad magic": b"NOPE" + raw[4:],
        "empty": b"",
    }
    for name, blob in cases.items():
        bad = str(tmp_path / ("bad-" + name.split()[0]))
        with open(bad, "wb") as f:
            f.write(blob)
        with pytest.raises(CheckpointCorruptError):
            ckpt.load_state(bad)
    with pytest.raises(CheckpointCorruptError):
        ckpt.load_state(str(tmp_path / "does-not-exist.armor"))


def _tiny_trainer(seed=5):
    from incubator_mxnet_tpu import gluon
    net = gluon.nn.Dense(3)
    net.initialize(ctx=mx.cpu())
    rs = np.random.RandomState(seed)
    net(nd.array(rs.randn(2, 4).astype(np.float32)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    return net, trainer, rs


def _train_step(net, trainer, rs):
    from incubator_mxnet_tpu import autograd
    x = nd.array(rs.randn(2, 4).astype(np.float32))
    with autograd.record():
        loss = (net(x) * net(x)).sum()
    loss.backward()
    trainer.step(2)
    return float(loss.asnumpy())


def _param_bytes(net):
    return {name: p.data().asnumpy().tobytes()
            for name, p in net.collect_params().items()}


def test_checkpointer_resumes_last_valid_snapshot(tmp_path):
    net, trainer, rs = _tiny_trainer()
    _train_step(net, trainer, rs)
    cp = trainer.checkpointer(str(tmp_path), keep=4, emergency=False)
    try:
        cp.save(step=1)
        want = _param_bytes(net)
        _train_step(net, trainer, rs)
        cp.save(step=2)
        # corrupt the newest snapshot: resume must fall back to step 1
        p2 = cp._path(2)
        blob = bytearray(open(p2, "rb").read())
        blob[-1] ^= 0xFF
        with open(p2, "wb") as f:
            f.write(blob)
        assert cp.latest_valid()[0] == 1
        assert cp.resume() == 1
        assert _param_bytes(net) == want
        # momentum restored: a step off the restored state replays
        # bit-exactly
        rs2 = np.random.RandomState(99)
        first = _train_step(net, trainer, rs2)
        after = _param_bytes(net)
        cp.resume()
        rs2 = np.random.RandomState(99)
        assert _train_step(net, trainer, rs2) == first
        assert _param_bytes(net) == after
    finally:
        cp.close()


def test_checkpointer_periodic_and_prune(tmp_path):
    net, trainer, rs = _tiny_trainer()
    os.environ["GRAFT_CHECKPOINT_EVERY"] = "2"
    try:
        cp = trainer.checkpointer(str(tmp_path), keep=2, emergency=False)
        try:
            for step in range(1, 7):
                _train_step(net, trainer, rs)
                cp.step_end(step)
            snaps = sorted(f for f in os.listdir(str(tmp_path))
                           if f.endswith(".armor"))
            assert snaps == ["ckpt-00000004.armor", "ckpt-00000006.armor"]
        finally:
            cp.close()
    finally:
        os.environ.pop("GRAFT_CHECKPOINT_EVERY", None)


def test_trainer_save_load_checkpoint_roundtrip(tmp_path):
    net, trainer, rs = _tiny_trainer()
    _train_step(net, trainer, rs)
    path = str(tmp_path / "one.armor")
    trainer.save_checkpoint(path, step=5)
    want = _param_bytes(net)
    _train_step(net, trainer, rs)
    assert _param_bytes(net) != want
    assert trainer.load_checkpoint(path) == 5
    assert _param_bytes(net) == want


def test_fast_forward_data_iter():
    it = iter(range(10))
    ckpt.fast_forward(it, 4)
    assert next(it) == 4


# -- model.py checkpoint edges (satellite 4) ---------------------------------

def _write_model_ckpts(tmp_path, epochs):
    import incubator_mxnet_tpu.model as model
    prefix = str(tmp_path / "net")
    sym = mx.sym.Variable("data")
    for ep in epochs:
        model.save_checkpoint(prefix, ep, sym,
                              {"w": nd.ones((2, 2)) * ep}, {})
    return prefix


def test_resume_from_checkpoint_skips_corrupt_newest(tmp_path):
    import incubator_mxnet_tpu.model as model
    prefix = _write_model_ckpts(tmp_path, [1, 2, 3])
    with open("%s-0003.params" % prefix, "wb") as f:
        f.write(b"garbage that is not a params file")
    _sym, arg, _aux, epoch = model.resume_from_checkpoint(prefix)
    assert epoch == 2
    assert np.allclose(arg["w"].asnumpy(), 2.0)


def test_resume_from_checkpoint_skips_truncated(tmp_path):
    import incubator_mxnet_tpu.model as model
    prefix = _write_model_ckpts(tmp_path, [1, 2])
    p = "%s-0002.params" % prefix
    blob = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(blob[: max(len(blob) // 3, 1)])
    _sym, arg, _aux, epoch = model.resume_from_checkpoint(prefix)
    assert epoch == 1
    assert np.allclose(arg["w"].asnumpy(), 1.0)


def test_resume_from_checkpoint_tolerates_missing_epoch(tmp_path):
    import incubator_mxnet_tpu.model as model
    prefix = _write_model_ckpts(tmp_path, [1, 5])     # gap: 2-4 missing
    assert model.latest_checkpoint(prefix) == 5
    _sym, arg, _aux, epoch = model.resume_from_checkpoint(prefix)
    assert epoch == 5
    assert np.allclose(arg["w"].asnumpy(), 5.0)


def test_resume_from_checkpoint_none_valid(tmp_path):
    import incubator_mxnet_tpu.model as model
    prefix = str(tmp_path / "net")
    assert model.resume_from_checkpoint(prefix) == (None, None, None, 0)
    with open("%s-0001.params" % prefix, "wb") as f:
        f.write(b"junk")
    sym = mx.sym.Variable("data")
    sym.save("%s-symbol.json" % prefix)
    assert model.resume_from_checkpoint(prefix)[3] == 0


def test_nd_save_is_atomic(tmp_path):
    # tmp-then-rename: a leftover .tmp from a crashed writer is ignored
    # by the epoch scan, and a completed save leaves no tmp behind
    path = str(tmp_path / "x.params")
    nd.save(path, {"w": nd.ones((3,))})
    assert [f for f in os.listdir(str(tmp_path)) if ".tmp" in f] == []
    assert np.allclose(nd.load(path)["w"].asnumpy(), 1.0)

    import incubator_mxnet_tpu.model as model
    prefix = _write_model_ckpts(tmp_path, [1])
    with open("%s-0002.params.tmp.12345" % prefix, "wb") as f:
        f.write(b"half-written")
    assert model.latest_checkpoint(prefix) == 1


# -- serving deadline shed (satellite 3) -------------------------------------

def test_serving_sheds_expired_requests():
    from incubator_mxnet_tpu import serving
    b = serving.DynamicBatcher(serving.ModelRegistry(),
                               max_batch=64, max_wait_ms=10000)
    try:
        fut = b.submit("m", np.zeros(3, np.float32), deadline_ms=20)
        with pytest.raises(serving.DeadlineExceededError) as ei:
            fut.get(timeout=10.0)
        assert ei.value.model == "m"
        assert ei.value.waited_ms >= 20.0
    finally:
        b.close()


def test_serving_deadline_env_default():
    from incubator_mxnet_tpu import serving
    os.environ["GRAFT_SERVE_DEADLINE_MS"] = "15"
    try:
        assert serving.serve_deadline_ms() == 15.0
        b = serving.DynamicBatcher(serving.ModelRegistry(),
                                   max_batch=64, max_wait_ms=10000)
        try:
            fut = b.submit("m", np.zeros(3, np.float32))
            with pytest.raises(serving.DeadlineExceededError):
                fut.get(timeout=10.0)
        finally:
            b.close()
    finally:
        os.environ.pop("GRAFT_SERVE_DEADLINE_MS", None)
    assert serving.serve_deadline_ms() is None      # off by default


def test_serving_dispatch_fault_fails_batch_not_server():
    from incubator_mxnet_tpu import serving
    from incubator_mxnet_tpu import gluon

    net = gluon.nn.Dense(2)
    net.initialize(ctx=mx.cpu())
    net(nd.ones((1, 3)))
    with serving.Server(max_batch=4, max_wait_ms=1) as srv:
        srv.load("m", block=net, example=nd.ones((1, 3)))
        x = np.ones(3, np.float32)
        want = srv.submit("m", x).get(timeout=60.0)
        faults.configure("serve.dispatch:error:n=1")
        fut = srv.submit("m", x)
        with pytest.raises(FaultInjectedError):
            fut.get(timeout=60.0)
        faults.reset()
        # the dispatcher survives the injected dispatch failure
        again = srv.submit("m", x).get(timeout=60.0)
        assert np.allclose(np.asarray(again), np.asarray(want))


# -- typed hang escalation ---------------------------------------------------

def test_watchdog_escalation_delivers_typed_error(tmp_path):
    from incubator_mxnet_tpu.telemetry import blackbox, watchdog

    os.environ["GRAFT_WATCHDOG_ESCALATE"] = "1"
    prev = blackbox._enabled_override
    blackbox.set_enabled(True)
    watchdog.register_dead_nodes_provider(lambda: [2])
    caught = []
    ready = threading.Event()

    def victim():
        try:
            with blackbox.collective("ps_push", n_keys=1):
                ready.set()
                for _ in range(400):
                    time.sleep(0.01)
        except PSUnavailableError as exc:
            caught.append(exc)

    timeout = 0.4
    t = threading.Thread(target=victim, daemon=True)
    path = str(tmp_path / "trip.json")
    wd = watchdog.Watchdog(timeout=timeout, path=path)
    try:
        t.start()
        assert ready.wait(5.0)
        t0 = time.perf_counter()
        wd.start()
        t.join(10.0)
        elapsed = time.perf_counter() - t0
        assert caught, "typed error never reached the waiting thread"
        assert caught[0].dead_ranks == (2,)
        # the fail-fast budget: trip within ~1.25x timeout, delivery on
        # the victim's next bytecode hop (10ms sleep slices) + slack
        assert elapsed < 1.25 * timeout + 1.0, elapsed
        import json
        doc = json.load(open(path))
        assert blackbox.validate_dump(doc) == []
        assert doc["watchdog"]["dead_ranks"] == [2]
    finally:
        wd.stop()
        watchdog.register_dead_nodes_provider(None)
        blackbox.set_enabled(prev)


def test_escalation_off_by_default(tmp_path):
    from incubator_mxnet_tpu.telemetry import blackbox, watchdog

    os.environ.pop("GRAFT_WATCHDOG_ESCALATE", None)
    prev = blackbox._enabled_override
    blackbox.set_enabled(True)
    done = threading.Event()
    survived = []

    def victim():
        with blackbox.collective("ps_push", n_keys=1):
            done.wait(3.0)
        survived.append(True)

    t = threading.Thread(target=victim, daemon=True)
    wd = watchdog.Watchdog(timeout=0.2, path=str(tmp_path / "t.json"))
    try:
        t.start()
        time.sleep(0.5)
        wd.poll()               # trips, dumps — but must NOT escalate
        done.set()
        t.join(5.0)
        assert survived == [True]
    finally:
        blackbox.set_enabled(prev)


def test_typed_errors_carry_payload():
    e = CollectiveTimeoutError("collective", 1.5, 1.0, dead_ranks=(4,),
                               detail={"path": "reduce"})
    assert e.dead_ranks == (4,) and e.timeout_s == 1.0
    p = PSUnavailableError("push", 3, last_error="boom", dead_ranks=(1,))
    assert p.cmd == "push" and p.attempts == 3 and p.dead_ranks == (1,)
