"""rtc (runtime Pallas kernels) + checkpoint auto-resume helpers.

Parity models: python/mxnet/rtc.py CudaModule/CudaKernel API shape,
SURVEY §5.3 (checkpoint-based resume, absent in the reference).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def test_pallas_module_launch():
    def axpy_kernel(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0 + y_ref[...]

    mod = mx.rtc.PallasModule({"axpy": axpy_kernel})
    k = mod.get_kernel("axpy")
    x = nd.array(np.arange(8, dtype=np.float32))
    out = k.launch([x, nd.ones(8)])
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy() * 2 + 1)
    # compiled call is cached per signature
    out2 = k.launch([x, nd.ones(8)])
    np.testing.assert_allclose(out2.asnumpy(), out.asnumpy())
    with pytest.raises(mx.MXNetError):
        mod.get_kernel("nope")


def test_cuda_module_redirects():
    with pytest.raises(mx.MXNetError):
        mx.rtc.CudaModule("__global__ void f(){}")


def test_checkpoint_resume_cycle(tmp_path):
    prefix = str(tmp_path / "run")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3, name="fc"),
        name="softmax")
    arg = {"fc_weight": nd.ones((3, 4)), "fc_bias": nd.zeros(3)}
    assert mx.model.latest_checkpoint(prefix) is None
    s, a, x, ep = mx.model.resume_from_checkpoint(prefix)
    assert s is None and ep == 0
    mx.model.save_checkpoint(prefix, 2, net, arg, {})
    mx.model.save_checkpoint(prefix, 5, net, arg, {})
    assert mx.model.latest_checkpoint(prefix) == 5
    s, a, x, ep = mx.model.resume_from_checkpoint(prefix)
    assert ep == 5 and set(a) == {"fc_weight", "fc_bias"}

    # resume actually continues training
    rng = np.random.RandomState(0)
    data = rng.randn(60, 4).astype(np.float32)
    label = (rng.rand(60) * 3).astype(np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=20)
    mod = mx.mod.Module(s, context=mx.cpu())
    mod.fit(it, num_epoch=7, begin_epoch=ep, arg_params=a, aux_params=x,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    assert mod.get_params()[0]["fc_weight"].shape == (3, 4)
