"""grafttsan — happens-before race detector (analysis/tsan.py), the
lockstep divergence auditor (analysis/lockstep.py + the dist heartbeat
piggyback + telemetry/aggregate.py cross-check), and the GL2xx static
concurrency lint (analysis/concurrency.py).

Contract per the EH2xx half: one deliberately-injected race per rule
must yield EXACTLY that diagnostic with both racing stacks, the
sanctioned patterns (same-thread writes, wait-then-write, explicit sync
edges) must stay silent, and a real overlapped/duplex training loop
under GRAFT_TSAN=1 must produce zero reports (the clean-run parity the
tier-1 acceptance rides).
"""
import json
import textwrap
import threading

import numpy as np
import pytest

import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, gluon, nd, overlap
from incubator_mxnet_tpu.analysis import concurrency, lockstep, tsan
from incubator_mxnet_tpu.telemetry import aggregate, blackbox


@pytest.fixture
def tsan_on():
    tsan.set_enabled(True)
    tsan.clear()
    try:
        yield tsan
    finally:
        tsan.set_enabled(None)
        tsan.clear()


@pytest.fixture
def lockstep_clean():
    lockstep.reset()
    try:
        yield lockstep
    finally:
        lockstep.reset()


def _codes():
    return [r.code for r in tsan.reports()]


def _in_thread(fn, name="racer"):
    box = []

    def run():
        try:
            fn()
        except BaseException as exc:       # surfaced by the caller
            box.append(exc)
    t = threading.Thread(target=run, name=name)
    t.start()
    t.join()
    if box:
        raise box[0]


# ---------------------------------------------------------------------------
# EH201 — write to an in-flight handle value
# ---------------------------------------------------------------------------

def test_eh201_cross_thread_write_to_inflight_handle(tsan_on):
    kv = mx.kv.create("local")
    arr = nd.array(np.ones((4,), np.float32))
    handle = kv.reduce_many_async([arr], label="bucket[f32:1p]")
    try:
        _in_thread(lambda: arr._write(jnp.zeros((4,), jnp.float32)))
    finally:
        handle.abandon()
    assert _codes() == ["EH201"]
    rep = tsan.reports()[0]
    assert "bucket[f32:1p]" in rep.message
    assert rep.stack and rep.other_stack, "a racing stack went missing"
    assert rep.thread == "racer" and rep.other_thread == "MainThread"


def test_eh201_same_thread_and_post_wait_writes_are_clean(tsan_on):
    kv = mx.kv.create("local")
    arr = nd.array(np.ones((4,), np.float32))
    handle = kv.reduce_many_async([arr])
    arr._write(jnp.zeros((4,), jnp.float32))   # issuing thread: program
    handle.wait()                              # order, the version rails
    _in_thread(lambda: arr._write(jnp.ones((4,), jnp.float32)))
    assert _codes() == []                      # settled handle: free


def test_eh201_window_covers_the_blocking_wait(tsan_on):
    """wait() flips ``done`` before the blocking section, but the wire
    owns the bytes until the block returns — a third thread writing
    while another thread is still INSIDE wait() is a race; the waiting
    thread's own post-acquire writes are not."""
    from incubator_mxnet_tpu.kvstore import _AsyncHandle
    arr = nd.array(np.ones((4,), np.float32))
    entered, release = threading.Event(), threading.Event()

    class _Blocking(_AsyncHandle):
        __slots__ = ()

        def _materialize(self):
            entered.set()
            release.wait(5)

    handle = _Blocking([arr], label="blocking")
    waiter = threading.Thread(target=handle.wait, name="waiter")
    waiter.start()
    assert entered.wait(5)
    _in_thread(lambda: arr._write(jnp.zeros((4,), jnp.float32)))
    release.set()
    waiter.join()
    assert _codes() == ["EH201"]
    # after the wait completed the registry is settled: free to write
    _in_thread(lambda: arr._write(jnp.ones((4,), jnp.float32)))
    assert _codes() == ["EH201"]


def test_eh201_suppressed_by_explicit_sync_edge(tsan_on):
    """The vector-clock machinery, not a thread-id shortcut: a release/
    acquire pair between issuer and writer orders the accesses and the
    report must NOT fire."""
    kv = mx.kv.create("local")
    arr = nd.array(np.ones((4,), np.float32))
    handle = kv.reduce_many_async([arr])
    tsan.sync_release("chan")

    def writer():
        tsan.sync_acquire("chan")
        arr._write(jnp.zeros((4,), jnp.float32))
    _in_thread(writer)
    handle.abandon()
    assert _codes() == []


# ---------------------------------------------------------------------------
# EH202 — concurrent scheduler regions, through the real scheduler
# ---------------------------------------------------------------------------

class _BlockingHost(object):
    """BucketScheduler host whose _sched_eligible parks inside arm()
    until released — the window in which a second thread's entry is the
    injected race."""
    _sched_autograd_hooks = False

    def __init__(self):
        self.inside = threading.Event()
        self.release = threading.Event()

    def _sched_entries(self, b):
        return []

    def _sched_eligible(self, b):
        self.inside.set()
        self.release.wait(5)
        return False

    def _sched_kv(self):
        return None

    def _sched_flat(self, b):
        return None

    def _sched_pass_id(self):
        return 0

    def _sched_label(self, b):
        return "b"


def test_eh202_hook_races_consumer(tsan_on):
    host = _BlockingHost()
    sched = overlap.BucketScheduler(host)
    plan = ([overlap.Bucket((0,), None, np.dtype("f4"), 4)], [])

    t = threading.Thread(target=lambda: sched.arm(plan), name="armer")
    t.start()
    host.inside.wait(5)
    sched.disarm()              # concurrent entry while arm() is inside
    host.release.set()
    t.join()
    assert "EH202" in _codes()
    rep = next(r for r in tsan.reports() if r.code == "EH202")
    assert "disarm" in rep.message and "arm" in rep.message
    assert rep.stack and rep.other_stack


def test_eh202_single_threaded_reentry_is_clean(tsan_on):
    """arm() -> disarm() nests regions on ONE thread — the sanctioned
    shape must stay silent."""
    host = _BlockingHost()
    host.release.set()          # don't park
    sched = overlap.BucketScheduler(host)
    plan = ([overlap.Bucket((0,), None, np.dtype("f4"), 4)], [])
    sched.arm(plan)
    sched.take(plan)
    sched.disarm()
    assert _codes() == []


# ---------------------------------------------------------------------------
# EH203 — foreign-thread resolve of an open segment
# ---------------------------------------------------------------------------

def test_eh203_foreign_thread_resolves_open_segment(tsan_on):
    a = nd.array(np.ones((4, 4), np.float32))
    with engine.bulk(8):
        b = a * a
        _in_thread(b.asnumpy, name="reader")
    assert _codes() == ["EH203"]
    rep = tsan.reports()[0]
    assert "offband" in rep.message
    assert rep.stack and rep.other_stack
    # the remembered side is the segment-open site (this test function)
    assert any("bulk" in line or "test_eh203" in line
               for line in rep.other_stack)


def test_eh203_same_thread_and_offband_are_clean(tsan_on):
    a = nd.array(np.ones((4, 4), np.float32))
    with engine.bulk(8):
        b = a * a
        b.asnumpy()             # owner-thread read: ordinary flush
        with engine.offband():
            c = a + a           # off-band dispatch alongside the scope
            _in_thread(c.asnumpy, name="reader")   # concrete: no segment
    assert _codes() == []


# ---------------------------------------------------------------------------
# EH204 — tracked shared arrays
# ---------------------------------------------------------------------------

def test_eh204_unsynchronized_tracked_write(tsan_on):
    arr = tsan.track(nd.array(np.zeros((2,), np.float32)), label="cell")
    arr._write(jnp.ones((2,), jnp.float32))
    _in_thread(lambda: arr._write(jnp.zeros((2,), jnp.float32)))
    tsan.untrack(arr)
    assert _codes() == ["EH204"]
    rep = tsan.reports()[0]
    assert "cell" in rep.message
    assert rep.stack and rep.other_stack


def test_eh204_sync_edge_orders_the_accesses(tsan_on):
    arr = tsan.track(nd.array(np.zeros((2,), np.float32)))
    arr._write(jnp.ones((2,), jnp.float32))
    tsan.sync_release("handoff")

    def consumer():
        tsan.sync_acquire("handoff")
        arr._read()
        arr._write(jnp.zeros((2,), jnp.float32))
    _in_thread(consumer)
    tsan.untrack(arr)
    assert _codes() == []


def test_abort_raises_at_the_race(tsan_on, monkeypatch):
    monkeypatch.setenv("GRAFT_TSAN_ABORT", "1")
    arr = tsan.track(nd.array(np.zeros((2,), np.float32)))
    arr._write(jnp.ones((2,), jnp.float32))
    with pytest.raises(tsan.TsanError) as ei:
        _in_thread(lambda: arr._write(jnp.zeros((2,), jnp.float32)))
    assert ei.value.code == "EH204"
    tsan.untrack(arr)


def test_reports_land_in_blackbox_ring(tsan_on):
    prev = blackbox._enabled_override
    blackbox.set_enabled(True)
    try:
        arr = tsan.track(nd.array(np.zeros((2,), np.float32)))
        arr._write(jnp.ones((2,), jnp.float32))
        _in_thread(lambda: arr._write(jnp.zeros((2,), jnp.float32)))
        tsan.untrack(arr)
        evs = [e for e in blackbox.events() if e["kind"] == "tsan_report"]
        assert evs and evs[-1]["data"]["code"] == "EH204"
        assert evs[-1]["data"]["stack_tail"], "dump-side stack missing"
    finally:
        blackbox.set_enabled(prev)


def test_tsan_selftest_smoke():
    assert tsan.selftest() == []


# ---------------------------------------------------------------------------
# clean-run parity: the real overlapped/duplex machinery under GRAFT_TSAN
# ---------------------------------------------------------------------------

def _mini_params(prefix, specs, rs):
    params = []
    for k, shape in enumerate(specs):
        p = gluon.Parameter("%s%d" % (prefix, k), shape=shape)
        p.initialize(ctx=mx.cpu())
        p.data()._write(jnp.asarray(rs.randn(*shape).astype(np.float32)))
        params.append(p)
    return params


def test_clean_run_parity_overlapped_and_duplex(tsan_on):
    """tier-1's concurrency surface in miniature — bulked segments,
    grad-ready hooks issuing async reduces mid-backward, the duplex
    store-update path with first-touch pulls, and a worker-threaded
    DataLoader — must produce ZERO EH2xx reports."""
    rs = np.random.RandomState(3)
    specs = [(5,), (3, 4), (7,), (2, 3)]

    # overlapped local-update path (BucketScheduler + reduce_many_async)
    pa = _mini_params("cl", specs, rs)
    consts = [nd.array(rs.randn(*s).astype(np.float32)) for s in specs]
    ta = gluon.Trainer(pa, "sgd", {"learning_rate": 0.05},
                       kvstore=mx.kv.create("dist_sync"))
    ta._bucket_bytes_override = 48
    ta._overlap_override = True
    for _ in range(4):
        with engine.bulk(32):
            with autograd.record():
                loss = None
                for p, c in zip(pa, consts):
                    y = (p.data() * p.data() * c).sum()
                    loss = y if loss is None else loss + y
            loss.backward()
        ta.step(2)
    assert ta._scheduler.issued_total > 0, "overlap never engaged"

    # duplex store-update path (apply_reduced + PullScheduler)
    pb = _mini_params("cd", specs, rs)
    tb = gluon.Trainer(pb, "sgd", {"learning_rate": 0.05},
                       kvstore=mx.kv.create("local"),
                       update_on_kvstore=True)
    tb._bucket_bytes_override = 48
    for _ in range(3):
        with autograd.record():
            loss = None
            for p, c in zip(pb, consts):
                y = (p.data() * p.data() * c).sum()
                loss = y if loss is None else loss + y
        loss.backward()
        tb.step(2)
    tb._pull_scheduler.finish()

    # worker-threaded data pipeline
    ds = gluon.data.ArrayDataset(
        rs.rand(16, 4).astype(np.float32),
        rs.rand(16, 1).astype(np.float32))
    dl = gluon.data.DataLoader(ds, batch_size=4, num_workers=2)
    for _x, _y in dl:
        _x.asnumpy()
    dl.close()

    assert tsan.reports() == [], tsan.reports()


# ---------------------------------------------------------------------------
# lockstep auditor (unit)
# ---------------------------------------------------------------------------

def test_lockstep_fold_is_deterministic(lockstep_clean):
    stream = [(1, "reduce_many", 2, 4096, None),
              (2, "pull", 3, 1024, ["0", "1", "2"]),
              (3, "reduce_many_async", 1, 2048, ["bucket[f32]"])]
    for seq, path, nk, nb, keys in stream:
        lockstep.fold(seq, path, n_keys=nk, nbytes=nb, keys=keys)
    _seq_a, hash_a = lockstep.state()
    lockstep.reset()
    for seq, path, nk, nb, keys in stream:
        lockstep.fold(seq, path, n_keys=nk, nbytes=nb, keys=keys)
    _seq_b, hash_b = lockstep.state()
    assert hash_a == hash_b, "same stream, different hash"
    lockstep.reset()
    for seq, path, nk, nb, keys in [stream[0], stream[2], stream[1]]:
        lockstep.fold(seq, path, n_keys=nk, nbytes=nb, keys=keys)
    _seq_c, hash_c = lockstep.state()
    assert hash_c != hash_a, "order divergence must change the hash"


def test_lockstep_excludes_ps_paths(lockstep_clean):
    lockstep.fold(1, "ps_push", n_keys=4, nbytes=1024)
    assert lockstep.state() == (0, 0)


def test_lockstep_observe_names_rank_and_first_position(lockstep_clean):
    prev = blackbox._enabled_override
    blackbox.set_enabled(True)
    try:
        # fold 3 agrees; fold 5 diverges on rank 1
        assert lockstep.observe({0: (3, 111), 1: (3, 111)},
                                my_rank=0) is None
        rep = lockstep.observe({0: (5, 222), 1: (5, 999)}, my_rank=0)
        assert rep is not None
        assert rep["first_divergent_fold"] == 5
        assert rep["divergent_ranks"] == [1]
        assert lockstep.divergence() is rep
        evs = [e for e in blackbox.events()
               if e["kind"] == "lockstep_divergence"]
        assert evs and evs[-1]["data"]["first_divergent_fold"] == 5
        # latched: a later mismatch does not re-report
        assert lockstep.observe({0: (6, 1), 1: (6, 2)}, my_rank=0) is None
    finally:
        blackbox.set_enabled(prev)


def test_lockstep_observe_catches_skipped_collective(lockstep_clean):
    """A rank that SKIPS one collective misaligns its fold counts with
    everyone else's forever after — the exact-position match may never
    recur.  The self-table lookback still catches it: the peer's hash
    at fold F is checked against the LOCAL rolling at fold F."""
    for i in range(1, 6):
        lockstep.fold(i, "reduce_many", n_keys=1, nbytes=64 * i)
    rows = lockstep.table()
    my_roll_at_4 = rows[3]["rolling"]
    # a healthy laggard (same stream, one behind) must NOT report
    assert lockstep.observe({0: (5, rows[4]["rolling"]),
                             1: (4, my_roll_at_4)}, my_rank=0) is None
    # rank 1 skipped one bucket: at fold 4 its stream covered DIFFERENT
    # collectives, so its hash differs from our rolling at fold 4
    rep = lockstep.observe({0: (5, rows[4]["rolling"]),
                            1: (4, my_roll_at_4 ^ 0x5a5a)}, my_rank=0)
    assert rep is not None
    assert rep["divergent_ranks"] == [1]
    assert rep["first_divergent_fold"] == 4


def test_lockstep_order_guard(lockstep_clean):
    assert lockstep.note_order("ps_push_async", 0)
    assert lockstep.note_order("ps_push_async", 1)
    assert not lockstep.note_order("ps_push_async", 3)   # 2 skipped
    snap = lockstep.snapshot()
    assert snap["order_violations"] == [
        {"path": "ps_push_async", "expected": 2, "got": 3}]


def test_lockstep_table_rides_blackbox_dumps(lockstep_clean):
    prev = blackbox._enabled_override
    blackbox.set_enabled(True)
    try:
        lockstep.fold(7, "reduce_many", n_keys=1, nbytes=64)
        doc = blackbox.snapshot()
        assert doc["lockstep"]["folds"] == 1
        assert doc["lockstep"]["last_wire_seq"] == 7
        row = doc["lockstep"]["table"][-1]
        assert row["path"] == "reduce_many"
        assert row["fold"] == 1 and row["seq"] == 7
        assert blackbox.validate_dump(doc) == []
    finally:
        blackbox.set_enabled(prev)


def test_lockstep_fold_ignores_wire_seq_skew(lockstep_clean):
    """Two ranks with identical audited streams must hash identically
    even when rank-asymmetric ps_* brackets skewed their wire seq
    counters (the dist_async background client) — the hash mixes the
    fold index, never the wire seq."""
    for seq, path in [(1, "pull"), (5, "reduce_many")]:
        lockstep.fold(seq, path, n_keys=1, nbytes=64)
    reference = lockstep.state()
    lockstep.reset()
    for seq, path in [(3, "pull"), (9, "reduce_many")]:     # skewed
        lockstep.fold(seq, path, n_keys=1, nbytes=64)
    assert lockstep.state() == reference


def test_collective_brackets_feed_the_fold(lockstep_clean):
    kv = mx.kv.create("local")
    kv.init("lk", nd.ones((4,)))
    before = lockstep.state()
    kv.push("lk", nd.ones((4,)))
    out = nd.zeros((4,))
    kv.pull("lk", out=out)
    seq, rolling = lockstep.state()
    assert seq > before[0] and rolling != before[1]
    rows = lockstep.table()
    assert [r["path"] for r in rows[-2:]] == ["push", "pull"]


# ---------------------------------------------------------------------------
# offline cross-check (telemetry/aggregate.py)
# ---------------------------------------------------------------------------

def _divergent_dumps():
    """Two synthetic rank dumps: rank 1 swaps the two buckets of step 2
    (seqs 3/4 carry each other's label/nbytes) — the order-divergence
    injection."""
    d0 = aggregate._synthetic_dump(0, 0.0)
    d1 = aggregate._synthetic_dump(1, 0.0)
    swapped = 0
    for e in d1["events"]:
        if e["kind"] == "collective" and e["data"]["seq"] in (3, 4):
            e["data"]["bucket"] = (
                "bucket[float32:3p:3072B]" if e["data"]["seq"] == 3
                else "bucket[float32:4p:4096B]")
            e["data"]["nbytes"] = 3072 if e["data"]["seq"] == 3 else 4096
            swapped += 1
    assert swapped == 2
    return d0, d1


def test_aggregate_lockstep_check_names_divergent_collective():
    d0, d1 = _divergent_dumps()
    arts = [aggregate.parse_artifact(d0, source="r0"),
            aggregate.parse_artifact(d1, source="r1")]
    report = aggregate.lockstep_check(arts)
    assert report["first_divergent_seq"] == 3
    assert report["divergent_ranks"] == [1] or \
        report["divergent_ranks"] == [0, 1]
    assert report["mismatches"][0]["seq"] == 3
    # identical streams stay clean
    clean = [aggregate.parse_artifact(aggregate._synthetic_dump(r, 0.0),
                                      source="r%d" % r) for r in (0, 1)]
    rep2 = aggregate.lockstep_check(clean)
    assert rep2["first_divergent_seq"] is None
    assert rep2["seqs_checked"] > 0


def test_aggregate_lockstep_check_catches_holes():
    d0 = aggregate._synthetic_dump(0, 0.0)
    d1 = aggregate._synthetic_dump(1, 0.0)
    d1["events"] = [e for e in d1["events"]
                    if not (e["kind"] == "collective"
                            and e["data"]["seq"] == 3)]
    arts = [aggregate.parse_artifact(d0, source="r0"),
            aggregate.parse_artifact(d1, source="r1")]
    report = aggregate.lockstep_check(arts)
    assert {"seq": 3, "missing_rank": 1} in report["holes"]
    assert report["first_divergent_seq"] == 3
    assert 1 in report["divergent_ranks"]


def test_aggregate_lockstep_declines_async_wire_sets():
    """ps_* brackets skew the shared seq counter rank-dependently, so
    seq matching over a dist_async artifact set would blame healthy
    ranks — the offline check must decline with a note instead."""
    d0, d1 = _divergent_dumps()
    d0["events"].append({"ts": 1700000099.0, "kind": "collective",
                         "data": {"path": "ps_push_async", "seq": 99,
                                  "n_keys": 1, "nbytes": 64, "rank": 0,
                                  "latency_ms": 1.0}})
    arts = [aggregate.parse_artifact(d0, source="r0"),
            aggregate.parse_artifact(d1, source="r1")]
    report = aggregate.lockstep_check(arts)
    assert report["seqs_checked"] == 0
    assert report["first_divergent_seq"] is None
    assert "async wire" in report["note"]


def test_analyze_report_carries_lockstep_section(tmp_path):
    paths = []
    for r, doc in zip((0, 1), _divergent_dumps()):
        p = tmp_path / ("r%d.json" % r)
        p.write_text(json.dumps(doc))
        paths.append(str(p))
    report, _trace = aggregate.analyze(paths)
    assert report["lockstep"]["first_divergent_seq"] == 3
    assert report["problems"] == []     # divergence is a finding, not a
    #                                     malformed-artifact problem


# ---------------------------------------------------------------------------
# GL2xx static lint
# ---------------------------------------------------------------------------

_GL_FIXTURE = textwrap.dedent("""
    import threading
    _a_lock = threading.Lock()
    _b_lock = threading.Lock()
    _hits = 0

    def forward():
        with _a_lock:
            with _b_lock:
                pass

    def backward():
        with _b_lock:
            with _a_lock:
                pass

    def worker():
        global _hits
        _hits += 1

    threading.Thread(target=worker, daemon=True).start()

    class PartialHost:
        def _sched_entries(self, b):
            return []
        def _sched_kv(self):
            return None

    class LeakyOwner:
        def __init__(self):
            threading.Thread(target=worker, daemon=True).start()

    class CleanOwner:
        def __init__(self):
            threading.Thread(target=worker, daemon=True).start()
        def close(self):
            pass
""")


def _by_code(diags):
    out = {}
    for d in diags:
        out.setdefault(d.code, []).append(d)
    return out


def test_gl2xx_fixture_rules_fire():
    by = _by_code([d for d in concurrency.lint_source(
        _GL_FIXTURE, filename="fix.py") if not d.suppressed])
    assert set(by) == {"GL201", "GL202", "GL203", "GL204"}
    assert "PartialHost" in by["GL203"][0].message
    assert "_sched_eligible" in by["GL203"][0].message
    assert "LeakyOwner" in by["GL204"][0].message
    assert not any("CleanOwner" in d.message for d in by["GL204"])
    assert "_hits" in by["GL202"][0].message


def test_gl2xx_guarded_global_is_clean():
    src = _GL_FIXTURE.replace(
        "    global _hits\n    _hits += 1",
        "    global _hits\n    with _a_lock:\n        _hits += 1")
    assert "with _a_lock" in src
    by = _by_code(concurrency.lint_source(src, filename="fix.py"))
    assert "GL202" not in by


def test_gl2xx_suppression_syntax():
    src = _GL_FIXTURE.replace(
        "    _hits += 1",
        "    # graftlint: disable=GL202 advisory counter\n"
        "    _hits += 1")
    assert "disable=GL202" in src
    g202 = [d for d in concurrency.lint_source(src, filename="fix.py")
            if d.code == "GL202"]
    assert g202 and all(d.suppressed for d in g202)
    assert g202[0].justification == "advisory counter"


_GL_INTERPROC_FIXTURE = textwrap.dedent("""
    import threading
    _a_lock = threading.Lock()
    _b_lock = threading.Lock()

    def grab_b():
        with _b_lock:
            pass

    def forward():
        with _a_lock:
            grab_b()            # a -> b, one call level deep

    def backward():
        with _b_lock:
            with _a_lock:       # b -> a, lexical
                pass

    class Pipe:
        def __init__(self):
            self._x_lock = threading.Lock()
            self._y_lock = threading.Lock()

        def _grab_y(self):
            with self._y_lock:
                pass

        def fwd(self):
            with self._x_lock:
                self._grab_y()  # x -> y via a self-method call

        def bwd(self):
            with self._y_lock:
                with self._x_lock:
                    pass
""")


def test_gl201_interprocedural_one_level():
    """PR 12: a call made while holding lock A contributes A -> every
    lock the callee's own body acquires — both for bare same-module
    functions and self-method calls — so cross-function inversions form
    GL201 cycles."""
    by = _by_code([d for d in concurrency.lint_source(
        _GL_INTERPROC_FIXTURE, filename="ip.py") if not d.suppressed])
    assert "GL201" in by
    msgs = " | ".join(d.message for d in by["GL201"])
    assert "_a_lock" in msgs and "_b_lock" in msgs
    assert "_x_lock" in msgs and "_y_lock" in msgs
    # drop the lexical halves: the interprocedural edges alone are
    # acyclic, so no GL201 — one level propagates, nothing fabricates
    clean = _GL_INTERPROC_FIXTURE.replace(
        "def backward():\n"
        "    with _b_lock:\n"
        "        with _a_lock:       # b -> a, lexical\n"
        "            pass\n", "").replace(
        "    def bwd(self):\n"
        "        with self._y_lock:\n"
        "            with self._x_lock:\n"
        "                pass\n", "")
    assert "backward" not in clean and "bwd" not in clean
    by2 = _by_code(concurrency.lint_source(clean, filename="ip.py"))
    assert "GL201" not in by2


def test_gl201_nested_def_does_not_collide_with_top_level():
    """A local closure's lock summary must NOT merge with a same-named
    top-level function: the fabricated edge would report a deadlock
    cycle that does not exist in the call graph."""
    src = textwrap.dedent("""
        import threading
        _a_lock = threading.Lock()
        _b_lock = threading.Lock()

        def helper():
            pass                    # top-level helper: NO locks

        def runner():
            def helper():           # unrelated local closure
                with _a_lock:
                    pass
            helper()

        def forward():
            with _b_lock:
                helper()            # resolves to the TOP-LEVEL helper

        def backward():
            with _a_lock:
                with _b_lock:
                    pass
    """)
    by = _by_code(concurrency.lint_source(src, filename="nest.py"))
    assert "GL201" not in by


def test_gl201_interprocedural_stays_one_level():
    """Deeper call chains are documented out of scope: holding A and
    calling f, where only f's CALLEE takes B, must not edge A -> B."""
    src = textwrap.dedent("""
        import threading
        _a_lock = threading.Lock()
        _b_lock = threading.Lock()

        def leaf():
            with _b_lock:
                pass

        def middle():
            leaf()              # no locks of its own

        def forward():
            with _a_lock:
                middle()        # two levels to _b_lock: out of scope

        def backward():
            with _b_lock:
                with _a_lock:
                    pass
    """)
    by = _by_code(concurrency.lint_source(src, filename="deep.py"))
    assert "GL201" not in by


def test_gl2xx_repo_is_clean():
    active = [d for d in concurrency.lint_package() if not d.suppressed]
    assert active == [], "\n".join(repr(d) for d in active)


def test_sched_protocol_constant_matches_hosts():
    """The lint's protocol list must track the real hosts — a drift here
    means GL203 checks a stale surface."""
    from incubator_mxnet_tpu.gluon.trainer import Trainer
    from incubator_mxnet_tpu.module.module import Module
    for cls in (Trainer, Module):
        for name in concurrency.SCHED_PROTOCOL:
            assert hasattr(cls, name), (cls, name)


# ---------------------------------------------------------------------------
# graftduplex: the dist_async background push (ROADMAP satellite)
# ---------------------------------------------------------------------------

def test_dist_async_duplex_push_read_your_writes(lockstep_clean):
    kv = mx.kv.create("dist_async")
    try:
        assert kv._duplex_push_enabled()
        kv.init("dw", nd.ones((4,)) * 10.0)
        kv.push("dw", nd.ones((4,)) * 2.0)      # queued on the client
        out = nd.zeros((4,))
        kv.pull("dw", out=out)                  # sync pull drains first
        np.testing.assert_allclose(out.asnumpy(), 12.0)
        assert kv._push_futs == [], "drain left futures behind"
        assert lockstep.snapshot()["order_violations"] == []
    finally:
        kv.close()


def test_dist_async_duplex_push_groups_and_order(lockstep_clean,
                                                 monkeypatch):
    monkeypatch.setenv("GRAFT_BUCKET_BYTES", "64")  # tiny groups
    prev = blackbox._enabled_override
    blackbox.set_enabled(True)
    kv = mx.kv.create("dist_async")
    try:
        keys = list(range(6))
        vals = [nd.ones((8,)) * (i + 1) for i in keys]   # 32B each
        kv.init(keys, [nd.zeros((8,)) for _ in keys])
        kv.push_many(keys, vals)
        kv.barrier()                            # drains the queue
        outs = [nd.zeros((8,)) for _ in keys]
        kv.pull_many(keys, outs)
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o.asnumpy(), i + 1.0)
        asyncs = [e for e in blackbox.events()
                  if e["kind"] == "collective"
                  and e["data"]["path"] == "ps_push_async"]
        assert len(asyncs) >= 3, "push groups did not split (%d)" \
            % len(asyncs)
        assert lockstep.snapshot()["order_violations"] == []
    finally:
        blackbox.set_enabled(prev)
        kv.close()


def test_dist_async_duplex_push_kill_switch(monkeypatch):
    monkeypatch.setenv("GRAFT_DUPLEX_PUSH", "0")
    kv = mx.kv.create("dist_async")
    try:
        kv.init("kw", nd.zeros((4,)))
        kv.push("kw", nd.ones((4,)))
        assert kv._push_futs == []              # synchronous path
        out = nd.zeros((4,))
        kv.pull("kw", out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0)
    finally:
        kv.close()


def test_dist_async_push_failure_is_pruned():
    """A failed push RPC surfaces ONCE (at the next push) and is pruned
    — it must not re-raise its stale exception on every later call."""
    from concurrent.futures import Future
    kv = mx.kv.create("dist_async")
    try:
        kv.init("pf", nd.zeros((2,)))
        poisoned = Future()
        poisoned.set_exception(RuntimeError("server boom"))
        kv._push_futs.append(poisoned)
        with pytest.raises(RuntimeError, match="server boom"):
            kv.push("pf", nd.ones((2,)))    # reap surfaces the failure
        kv.push("pf", nd.ones((2,)))        # ...exactly once
        out = nd.zeros((2,))
        kv.pull("pf", out=out)
        # both real pushes landed (the raising call had already
        # submitted its RPC before the reap fired)
        np.testing.assert_allclose(out.asnumpy(), 2.0)
    finally:
        kv.close()


def test_dist_async_close_shuts_background_client():
    kv = mx.kv.create("dist_async")
    kv.init("cw", nd.zeros((2,)))
    kv.push("cw", nd.ones((2,)))
    pool = kv._pull_executor()
    kv.close()
    assert kv._pull_pool is None and kv._ps is None
    with pytest.raises(RuntimeError):
        pool.submit(lambda: None)               # executor really shut


# ---------------------------------------------------------------------------
# 2-proc forced-divergence harness (SKIP-MULTIPROC pattern)
# ---------------------------------------------------------------------------

_DIVERGENCE_WORKER = textwrap.dedent("""
    import os, sys, traceback
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["GRAFT_WATCHDOG_TIMEOUT"] = "120"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.analysis import lockstep
    try:
        kv = mx.kv.create("dist_sync")
        rank, nw = kv.rank, kv.num_workers
        assert nw == 2, nw
        # two same-shape "buckets": the wire pairs fine either way, but
        # rank 1 issues them in SWAPPED order — the injected lockstep
        # divergence (a rank-order bug; a skipped collective would hang
        # the XLA wire itself, which is exactly what this auditor exists
        # to catch BEFORE it happens)
        a = nd.ones((16,)) * (rank + 1)
        b = nd.ones((16,)) * (rank + 3)
        labels = ("bucket[A]", "bucket[B]")
        order = (0, 1) if rank == 0 else (1, 0)
        vals, labs = (a, b), labels
        for step in range(2):
            for j in order:
                kv.reduce_many_async([vals[j]], label=labs[j]).wait()
            kv.heartbeat()      # ships (seq, rolling hash); observe()
        div = lockstep.divergence()
        assert div is not None, "divergence not detected"
        assert div["first_divergent_fold"] <= 2, div
        peers = div["divergent_ranks"]
        assert (1 - rank) in peers or rank in peers, div
        from incubator_mxnet_tpu.telemetry import blackbox
        evs = [e for e in blackbox.events()
               if e["kind"] == "lockstep_divergence"]
        assert evs, "no flight-recorder divergence event"
        print("WORKER %d DIVERGENCE seq=%d peers=%s OK"
              % (rank, div["first_divergent_seq"], peers), flush=True)
    except Exception:
        tb = traceback.format_exc()
        if "Multiprocess computations aren't implemented" in tb:
            print("SKIP-MULTIPROC", flush=True)
            os._exit(0)
        raise
""")


def test_two_process_forced_divergence(tmp_path):
    """Rank 1 issues its buckets in swapped order; the heartbeat-borne
    rolling hash must name the divergence (first bad seq <= 2) on both
    ranks BEFORE any watchdog trip."""
    from test_dist_multiprocess import _launch_two
    out = _launch_two(tmp_path, _DIVERGENCE_WORKER, timeout=240,
                      port_base=9700, require_rc0=False)
    if "SKIP-MULTIPROC" in out:
        pytest.skip("backend lacks multiprocess CPU collectives")
    assert "WORKER 0 DIVERGENCE" in out and "WORKER 1 DIVERGENCE" in out, \
        out[-3000:]
    assert "WATCHDOG TRIP" not in out, out[-3000:]
