"""MXNet binary .params format + rewritten scheduler/callback behavior.

Parity model: src/ndarray/ndarray.cc NDArray::Save/Load byte layout,
tests/python/unittest/test_ndarray.py save/load round trips.
"""
import struct

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_params_roundtrip_named(tmp_path):
    f = str(tmp_path / "m.params")
    rng = np.random.RandomState(0)
    data = {"arg:w": nd.array(rng.randn(3, 4).astype(np.float32)),
            "arg:b": nd.array(rng.randn(4).astype(np.float64)),
            "aux:m": nd.array(rng.randint(0, 9, (2, 2)).astype(np.int32))}
    nd.save(f, data)
    back = nd.load(f)
    assert set(back) == set(data)
    for k in data:
        assert back[k].dtype == data[k].dtype
        assert_almost_equal(back[k].asnumpy(), data[k].asnumpy(), rtol=1e-7)


def test_params_roundtrip_list_and_sparse(tmp_path):
    f = str(tmp_path / "l.params")
    dense = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    rsp = nd.sparse.row_sparse_array(
        (np.array([[1, 2], [3, 4]], np.float32), np.array([0, 2], np.int64)),
        shape=(4, 2))
    csr = nd.sparse.csr_matrix(
        (np.array([5, 6], np.float32), np.array([1, 0], np.int64),
         np.array([0, 1, 2], np.int64)), shape=(2, 2))
    nd.save(f, [dense, rsp, csr])
    back = nd.load(f)
    assert isinstance(back, list) and len(back) == 3
    assert_almost_equal(back[0].asnumpy(), dense.asnumpy(), rtol=1e-7)
    assert back[1].stype == "row_sparse"
    assert_almost_equal(back[1].asnumpy(), rsp.asnumpy(), rtol=1e-7)
    assert back[2].stype == "csr"
    assert_almost_equal(back[2].asnumpy(), csr.asnumpy(), rtol=1e-7)


def test_params_binary_layout(tmp_path):
    """Byte-level check against the reference container constants
    (src/ndarray/ndarray.cc: list magic 0x112, V2 magic 0xF993fac9,
    uint32-ndim + int64-dims shapes, int32 dtype flags)."""
    f = str(tmp_path / "b.params")
    nd.save(f, {"x": nd.array(np.array([[1.5, -2.0]], np.float32))})
    raw = open(f, "rb").read()
    magic, reserved, count = struct.unpack_from("<QQQ", raw, 0)
    assert magic == 0x112 and reserved == 0 and count == 1
    off = 24
    v2, stype = struct.unpack_from("<Ii", raw, off)
    assert v2 == 0xF993FAC9 and stype == 0
    off += 8
    ndim = struct.unpack_from("<I", raw, off)[0]
    assert ndim == 2
    dims = struct.unpack_from("<2q", raw, off + 4)
    assert dims == (1, 2)
    off += 4 + 16
    dev_type, dev_id, flag = struct.unpack_from("<iii", raw, off)
    assert dev_type == 1 and flag == 0      # kCPU, kFloat32
    off += 12
    vals = struct.unpack_from("<2f", raw, off)
    assert vals == (1.5, -2.0)


def test_params_reads_reference_written_file(tmp_path):
    """A file assembled byte-by-byte the way stock MXNet writes it loads
    correctly (simulates checkpoint interop without the reference lib)."""
    f = str(tmp_path / "ref.params")
    payload = np.array([3.0, 4.0, 5.0], np.float32)
    blob = struct.pack("<QQQ", 0x112, 0, 1)
    blob += struct.pack("<Ii", 0xF993FAC9, 0)          # V2, dense
    blob += struct.pack("<Iq", 1, 3)                   # shape (3,)
    blob += struct.pack("<ii", 1, 0)                   # cpu ctx
    blob += struct.pack("<i", 0)                       # float32
    blob += payload.tobytes()
    name = b"arg:weight"
    blob += struct.pack("<Q", 1) + struct.pack("<Q", len(name)) + name
    open(f, "wb").write(blob)
    out = nd.load(f)
    assert list(out) == ["arg:weight"]
    assert_almost_equal(out["arg:weight"].asnumpy(), payload, rtol=1e-7)


def test_params_reads_v1_legacy_array(tmp_path):
    """V1 (pre-storage-type) dense arrays load (NDArray::LegacyLoad)."""
    f = str(tmp_path / "v1.params")
    payload = np.array([[7, 8]], np.int32)
    blob = struct.pack("<QQQ", 0x112, 0, 1)
    blob += struct.pack("<I", 0xF993FAC8)              # V1 magic
    blob += struct.pack("<I2q", 2, 1, 2)               # shape (1,2)
    blob += struct.pack("<ii", 1, 0)                   # cpu ctx
    blob += struct.pack("<i", 4)                       # int32
    blob += payload.tobytes()
    blob += struct.pack("<Q", 0)                       # unnamed
    open(f, "wb").write(blob)
    out = nd.load(f)
    assert out[0].dtype == np.int32
    assert (out[0].asnumpy() == payload).all()


def test_checkpoint_save_load_through_model(tmp_path):
    """model.save_checkpoint/load_checkpoint over the binary format."""
    prefix = str(tmp_path / "ck")
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    arg = {"fc_weight": nd.array(np.ones((3, 4), np.float32)),
           "fc_bias": nd.array(np.zeros(3, np.float32))}
    mx.model.save_checkpoint(prefix, 7, net, arg, {})
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 7)
    assert set(arg2) == set(arg)
    assert_almost_equal(arg2["fc_weight"].asnumpy(),
                        arg["fc_weight"].asnumpy(), rtol=1e-7)


def test_lr_schedulers_closed_form():
    s = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(1) == 1.0 and s(10) == 1.0
    assert s(11) == 0.5 and s(21) == 0.25
    # out-of-order probing gives the same answers (stateless)
    assert s(11) == 0.5 and s(1) == 1.0

    m = mx.lr_scheduler.MultiFactorScheduler(step=[5, 8], factor=0.1)
    m.base_lr = 1.0
    assert m(5) == 1.0 and abs(m(6) - 0.1) < 1e-12
    assert abs(m(9) - 0.01) < 1e-12

    p = mx.lr_scheduler.PolyScheduler(max_update=10, base_lr=1.0, pwr=2)
    assert p(0) == 1.0 and p(10) == 0.0
    assert abs(p(5) - 0.25) < 1e-12

    c = mx.lr_scheduler.CosineScheduler(max_update=10, base_lr=1.0,
                                        warmup_steps=2, warmup_begin_lr=0.0)
    assert c(0) == 0.0 and c(1) == 0.5
    assert abs(c(2) - 1.0) < 1e-12 and abs(c(10)) < 1e-9


def test_speedometer_logs(caplog):
    import logging
    sp = mx.callback.Speedometer(batch_size=4, frequent=2, auto_reset=False)

    class P:
        epoch = 0
        eval_metric = None

    with caplog.at_level(logging.INFO):
        for nbatch in range(5):
            p = P()
            p.nbatch = nbatch
            sp(p)
    msgs = [r.message for r in caplog.records if "samples/sec" in r.message]
    assert len(msgs) >= 2
